"""Goal-priority optimization loop.

Reference: ``analyzer/GoalOptimizer.java`` — the core loop :415-489 runs goals
by priority over one ClusterModel, collecting per-goal stats and the final
proposal diff; :289-337 serves cached proposals; precompute happens on a
background pool :137-188.  Here the loop body drives the TPU GoalSolver, and
"precompute" is a cache keyed by (model generation, goals, options) — one
batched solve is fast enough that a thread pool is unnecessary.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from cruise_control_tpu.analyzer.constraint import BalancingConstraint
from cruise_control_tpu.analyzer.context import build_context
from cruise_control_tpu.analyzer.goals.base import Goal
from cruise_control_tpu.analyzer.goals.registry import (
    DEFAULT_GOALS,
    get_goals_by_priority,
)
from cruise_control_tpu.analyzer.options import OptimizationOptions
from cruise_control_tpu.analyzer.proposals import diff_proposals
from cruise_control_tpu.analyzer import relax as _relax
from cruise_control_tpu.analyzer.solver import (
    GoalOptimizationInfo,
    GoalSolver,
    check_hard_goal,
    default_solver,
)
from cruise_control_tpu.common.actions import ExecutionProposal, ProposalSummary
from cruise_control_tpu.common.exceptions import OptimizationFailureError
from cruise_control_tpu.compilesvc.telemetry import telemetry as _compile_telemetry
from cruise_control_tpu.obsvc import convergence as _convergence
from cruise_control_tpu.obsvc.execution import execution as _execution
from cruise_control_tpu.obsvc.execution import path_histogram as _path_histogram
from cruise_control_tpu.obsvc.fidelity import fidelity as _fidelity
from cruise_control_tpu.obsvc.tracer import tracer as _obsvc_tracer
from cruise_control_tpu.model.state import ClusterMeta, ClusterState, Placement
from cruise_control_tpu.model.stats import ClusterModelStats, compute_stats

LOG = logging.getLogger(__name__)

# Balancedness weights (reference: KafkaCruiseControlUtils.java:734-762 —
# goal-violation weights used for the balancedness score gauge).
_BALANCEDNESS_WEIGHT_HARD = 3.0
_BALANCEDNESS_WEIGHT_SOFT = 1.0


def _host_local_placement(placement):
    """The given pytree (typically a Placement) with every leaf addressable
    on THIS process.

    Identity unless a leaf is actually a cross-process sharded global array
    (a GoalOptimizer built WITHOUT the global mesh keeps host-local arrays
    even inside a jax.distributed program — facade/detector optimizers must
    stay collective-free there, or non-lockstep calls would deadlock).  For
    global-mesh outputs (parallel/multihost.py) the host-side consumers
    (stats jit, proposal diff) need full arrays — gather them; every
    process reconstructs the same global value."""
    import jax

    non_addressable = any(
        isinstance(leaf, jax.Array) and not leaf.is_fully_addressable
        for leaf in jax.tree_util.tree_leaves(placement))
    if not non_addressable:
        return placement
    from jax.experimental import multihost_utils

    gathered = multihost_utils.process_allgather(placement, tiled=True)
    return jax.tree_util.tree_map(np.asarray, gathered)


def _changed_partitions(part_ids, a, b):
    """Partition ids whose placement (broker, leadership, or disk) differs
    between two host-local placements — the execution observatory's per-goal
    attribution diff.  Pure numpy over already-materialized outputs."""
    n = part_ids.shape[0]
    changed = ((np.asarray(a.broker)[:n] != np.asarray(b.broker)[:n])
               | (np.asarray(a.is_leader)[:n] != np.asarray(b.is_leader)[:n])
               | (np.asarray(a.disk)[:n] != np.asarray(b.disk)[:n]))
    return set(np.unique(part_ids[changed]).tolist())


@dataclass
class OptimizerResult:
    """Reference: ``analyzer/OptimizerResult.java``."""

    proposals: List[ExecutionProposal]
    goal_infos: List[GoalOptimizationInfo]
    stats_before: ClusterModelStats
    stats_after: ClusterModelStats
    violated_goals_before: List[str]
    violated_goals_after: List[str]
    balancedness_score: float
    elapsed_s: float
    final_placement: Optional[Placement] = None
    # Anytime result: the solve stopped at a budget boundary (deadline /
    # cancellation) before every goal converged.  The placement is still
    # feasible and hard-goal-safe for the goals that DID run — per-goal
    # status is in goal_infos[i].preempted.
    partial: bool = False
    preempt_reason: Optional[str] = None
    # Model-fidelity fingerprint of the snapshot this result was solved
    # from (fidelity observatory; None when the recorder is off).  Stamped
    # after the solve, never part of the proposal cache key.
    fingerprint: Optional[Dict] = None

    @property
    def summary(self) -> ProposalSummary:
        return ProposalSummary.of(self.proposals)

    def to_dict(self, explain: bool = False) -> Dict:
        s = self.summary
        d = {
            **({"partial": True, "preemptReason": self.preempt_reason}
               if self.partial else {}),
            "numInterBrokerReplicaMovements": s.num_inter_broker_replica_movements,
            "numIntraBrokerReplicaMovements": s.num_intra_broker_replica_movements,
            "numLeaderMovements": s.num_leadership_movements,
            "interBrokerDataToMoveMB": s.inter_broker_data_to_move_mb,
            "intraBrokerDataToMoveMB": s.intra_broker_data_to_move_mb,
            "violatedGoalsBefore": self.violated_goals_before,
            "violatedGoalsAfter": self.violated_goals_after,
            "balancednessScore": self.balancedness_score,
            "onDemandBalancednessScoreBefore": None,
            "statsBefore": self.stats_before.to_dict(),
            "statsAfter": self.stats_after.to_dict(),
            "goals": [
                {
                    "goal": g.goal_name,
                    "status": "preempted" if g.preempted else "completed",
                    "rounds": g.rounds,
                    "moves": g.moves_applied,
                    "violatedBrokersBefore": g.violated_brokers_before,
                    "violatedBrokersAfter": g.violated_brokers_after,
                    "metricBefore": g.metric_before,
                    "metricAfter": g.metric_after,
                }
                for g in self.goal_infos
            ],
        }
        if explain:
            # ?explain=true: per-proposal provenance (goal / path / solve
            # round / cost delta) plus the path histogram rollup and the
            # model-fidelity fingerprint the solve was decided on.
            d["proposals"] = [p.to_dict(explain=True) for p in self.proposals]
            d["provenancePaths"] = _path_histogram(self.proposals)
            d["modelFingerprint"] = self.fingerprint
        return d


def balancedness_score(goal_infos: Sequence[GoalOptimizationInfo],
                       goals: Sequence[Goal]) -> float:
    """[0, 100]: weighted fraction of satisfied goals (hard goals weigh 3×)."""
    by_name = {g.name: g for g in goals}
    total = 0.0
    got = 0.0
    for info in goal_infos:
        goal = by_name.get(info.goal_name)
        w = _BALANCEDNESS_WEIGHT_HARD if goal is not None and goal.is_hard \
            else _BALANCEDNESS_WEIGHT_SOFT
        total += w
        if info.violated_brokers_after == 0:
            got += w
    return 100.0 * got / total if total else 100.0


def _scenario_masks(gctx, state, meta, scenario_sets, revive: bool):
    """Per-lane (alive, excl_move, excl_lead) masks for what-if batches.

    ``revive=False`` decommissions each lane's brokers (dead + excluded as
    destinations, the RemoveBrokersRunnable semantics).  ``revive=True``
    brings each lane's provisioned-but-dead brokers up — liveness only:
    operator-stated exclusions (OptimizationOptions) are NOT cleared, a dead
    broker is blocked by ``state.alive`` in the structural checks, never by
    the exclusion masks."""
    s_n = len(scenario_sets)
    id_to_idx = {int(bid): i for i, bid in enumerate(meta.broker_ids)}
    unknown = sorted({int(b) for ids in scenario_sets for b in ids}
                     - id_to_idx.keys())
    if unknown:
        # Scenario sets originate from API requests (remove_broker / add_
        # broker params) — a typo'd id must surface as a clear client error,
        # not an opaque KeyError from deep inside the batch builder.
        raise ValueError(
            f"unknown broker id(s) {unknown} in what-if scenario: not in "
            f"this cluster model's broker set")
    alive_s = np.tile(np.asarray(state.alive), (s_n, 1))
    excl_move_s = np.tile(np.asarray(gctx.excluded_for_replica_move), (s_n, 1))
    excl_lead_s = np.tile(np.asarray(gctx.excluded_for_leadership), (s_n, 1))
    for s, ids in enumerate(scenario_sets):
        for bid in ids:
            i = id_to_idx[int(bid)]
            alive_s[s, i] = revive
            if not revive:
                excl_move_s[s, i] = True
                excl_lead_s[s, i] = True
    return alive_s, excl_move_s, excl_lead_s


@dataclass
class BatchScenarioResult:
    """Result of a vmapped what-if batch (one lane per scenario).

    Reference analog: ``servlet/handler/async/runnable/RemoveBrokersRunnable``
    run N times sequentially; here all N solves share one compiled program.
    """

    scenario_sets: List[List[int]]   # per-lane broker ids (removed or added)
    goal_names: List[str]
    violated_after: np.ndarray      # i32[S, G] violated brokers per scenario/goal
    moves: np.ndarray               # i32[S, G]
    rounds: np.ndarray              # i32[S, G]
    stranded_after: np.ndarray      # i32[S] offline replicas left (last goal)
    final_placements: Placement     # stacked [S, ...] pytree
    # Budget fired between goals: goal_names (and the [S, G] stats) cover
    # only the goal prefix that actually ran; every lane's placement is the
    # anytime result after that prefix.
    preempted: bool = False
    # Memory headroom guard refused the dispatch: no goal ran, every lane
    # returns its seed placement, stranded_after is -1 (unknown) so no
    # scenario reads as succeeded.  Degraded-style tagging, never a crash.
    memory_refused: bool = False

    @property
    def num_scenarios(self) -> int:
        return len(self.scenario_sets)

    @property
    def removal_sets(self) -> List[List[int]]:
        """Back-compat alias (the field predates add-scenario batches)."""
        return self.scenario_sets

    def succeeded(self, s: int) -> bool:
        """Scenario s evacuated everything and satisfies every goal."""
        return (int(self.stranded_after[s]) == 0
                and int(self.violated_after[s].sum()) == 0)

    def placement_for(self, s: int) -> Placement:
        import jax
        return jax.tree_util.tree_map(lambda x: x[s], self.final_placements)

    def balancedness(self, s: int) -> float:
        """Per-lane balancedness on the same hard=3.0/soft=1.0 weights as
        :func:`balancedness_score` (lane s's violated_after row stands in
        for the sequential run's goal_infos)."""
        from cruise_control_tpu.analyzer.goals.registry import goal_by_name
        total = 0.0
        got = 0.0
        for g, name in enumerate(self.goal_names):
            w = (_BALANCEDNESS_WEIGHT_HARD if goal_by_name(name).is_hard
                 else _BALANCEDNESS_WEIGHT_SOFT)
            total += w
            if int(self.violated_after[s, g]) == 0:
                got += w
        return 100.0 * got / total if total else 100.0

    def quality(self, s: int) -> Dict:
        """The per-row quality fields every bench row carries."""
        return {"violated_after": int(self.violated_after[s].sum()),
                "balancedness": round(self.balancedness(s), 3)}


class GoalOptimizer:
    """Runs a prioritized goal list over a frozen snapshot; caches the last
    result per model generation (GoalOptimizer.java:196-224 cache semantics)."""

    def __init__(
        self,
        constraint: Optional[BalancingConstraint] = None,
        goal_names: Optional[Sequence[str]] = None,
        solver: Optional[GoalSolver] = None,
        mesh=None,
        polish_passes: int = 1,
    ):
        self.constraint = constraint or BalancingConstraint()
        self.goal_names = list(goal_names or DEFAULT_GOALS)
        if solver is not None:
            self.solver = solver
        elif mesh is not None:
            self.solver = GoalSolver(
                max_candidates_per_round=self.constraint.max_candidates_per_round,
                max_rounds_per_goal=self.constraint.max_rounds_per_goal,
                mesh=mesh,
            )
        elif (self.constraint.max_candidates_per_round == 4096
              and self.constraint.max_rounds_per_goal == 96):
            self.solver = default_solver()
        else:
            self.solver = GoalSolver(
                max_candidates_per_round=self.constraint.max_candidates_per_round,
                max_rounds_per_goal=self.constraint.max_rounds_per_goal,
            )
        # Post-stack re-solve passes for re-violated soft goals (0 disables;
        # part of the proposal-cache key).
        self.polish_passes = polish_passes
        self._cache_lock = threading.Lock()
        self._cached: Dict[Tuple, OptimizerResult] = {}
        # Materialize the preemption sensor family at 0: dashboards (and the
        # docs/SENSORS.md drift guard) see it before the first partial solve.
        from cruise_control_tpu.common.metrics import registry
        for s in ("Solver.partial-solves", "Solver.preemptions",
                  "Solver.cancellations"):
            registry().counter(s)

    # ------------------------------------------------------------- the loop

    def optimizations(
        self,
        state: ClusterState,
        placement: Placement,
        meta: ClusterMeta,
        options: Optional[OptimizationOptions] = None,
        goals: Optional[Sequence[Goal]] = None,
        model_generation: Optional[int] = None,
        budget=None,
    ) -> OptimizerResult:
        """The core loop (GoalOptimizer.java:415-489): per-goal optimize with
        all previously-optimized goals enforcing acceptance, then diff.

        ``budget`` (a :class:`~cruise_control_tpu.analyzer.budget.SolveBudget`)
        makes the run anytime: the budget is checked at every goal boundary
        (and, when segmented, every segment boundary inside each goal); on
        expiry/cancel the result is returned as-is with ``partial=True``."""
        tr = _obsvc_tracer()
        if not tr.enabled:
            return self._optimizations_impl(state, placement, meta, options,
                                            goals, model_generation, budget)
        n = len(goals) if goals is not None else len(self.goal_names)
        with tr.span("optimize", num_goals=n, generation=model_generation):
            return self._optimizations_impl(state, placement, meta, options,
                                            goals, model_generation, budget)

    def _optimizations_impl(
        self,
        state: ClusterState,
        placement: Placement,
        meta: ClusterMeta,
        options: Optional[OptimizationOptions] = None,
        goals: Optional[Sequence[Goal]] = None,
        model_generation: Optional[int] = None,
        budget=None,
    ) -> OptimizerResult:
        tr = _obsvc_tracer()
        tel = _compile_telemetry()
        options = options or OptimizationOptions()
        cache_key = None
        if model_generation is not None:
            effective_names = (tuple(g.name for g in goals) if goals is not None
                               else tuple(self.goal_names))
            cache_key = (model_generation, effective_names, options,
                         self.polish_passes)
            if _relax.relaxation_enabled():
                # The relax knobs shape the result, so they join the key —
                # but ONLY when the fast path is on, keeping the off-path
                # cache key (and thus hit/miss behavior) byte-identical.
                cache_key = cache_key + (
                    ("relax",) + _relax.relaxation_params(),)
            with self._cache_lock:
                hit = self._cached.get(cache_key)
            if hit is not None:
                return hit

        goals = list(goals) if goals is not None else get_goals_by_priority(self.goal_names)
        t0 = time.monotonic()
        from cruise_control_tpu.common.metrics import registry
        proposal_timer = registry().timer("GoalOptimizer.proposal-computation-timer")
        gctx = build_context(state, placement, meta, self.constraint, options)
        gctx, placement = self.solver.shard_inputs(gctx, placement)

        agg0 = self.solver.aggregates(gctx, placement)
        vio0 = self.solver.violations(goals, gctx, placement, agg0)
        violated_before = [g.name for g, v in zip(goals, vio0) if v > 0]
        initial_local = _host_local_placement(placement)
        stats_before = compute_stats(state, initial_local,
                                     self.constraint.balance_threshold)

        # AbstractGoal.java:108-117: the stats-must-not-worsen contract is
        # waived only when the cluster has broken brokers or excluded-for-move
        # brokers still holding replicas (evacuation may legitimately worsen
        # a soft metric).
        has_broken = bool((~np.asarray(state.alive)
                           & np.asarray(state.broker_valid)).any())
        excl_move = np.asarray(gctx.excluded_for_replica_move)
        if excl_move.any():
            held = np.asarray(agg0.replica_counts)
            has_broken = has_broken or bool((excl_move & (held > 0)).any())

        # Provision gauges (AnomalyDetectorManager.java:173-192): a hard-goal
        # optimization failure marks the cluster under-provisioned.
        prov_under = registry().settable_gauge("AnomalyDetector.under-provisioned")
        prov_right = registry().settable_gauge("AnomalyDetector.right-sized")
        registry().settable_gauge("AnomalyDetector.over-provisioned")

        infos: List[GoalOptimizationInfo] = []
        priors: List[Goal] = []
        agg = agg0
        bucket = f"R{gctx.state.num_replicas_padded}"
        preempt_reason = None
        # Execution observatory: per-partition move provenance, built from
        # host-local snapshots bracketing each goal (and polish) pass.  All
        # numpy over already-materialized outputs — OFF-PATH for the solver:
        # no executable, jit cache key, or proposal cache key changes either
        # way (asserted by tests/test_execution_obs.py).
        exec_rec = _execution()
        prov_map: Optional[Dict[int, dict]] = None
        if exec_rec.enabled:
            exec_rec.clear_rounded()
            prov_map = {}
            part_ids = np.asarray(state.partition)[:meta.num_replicas]
            prev_local = initial_local
        for gi, goal in enumerate(goals):
            # Goal-boundary budget check: covers cancel-only budgets (fused
            # executables, byte-identical to budget-less) and deadlines that
            # fire between goals.  Goals never started are recorded as
            # preempted with zero rounds.
            if budget is not None:
                preempt_reason = budget.stop_reason()
                if preempt_reason is not None:
                    vio_rem = self.solver.violations(goals[gi:], gctx,
                                                     placement, agg)
                    for g, v in zip(goals[gi:], vio_rem):
                        infos.append(GoalOptimizationInfo(
                            goal_name=g.name,
                            violated_brokers_before=int(v),
                            violated_brokers_after=int(v),
                            preempted=True,
                            preempt_reason=preempt_reason))
                    break
            # One span per goal per optimization round: moves + rounds from
            # the solve, compile-vs-execute split from compilesvc telemetry
            # deltas (execute_ms materializes at render time as
            # wall_ms - compile_ms).
            # Convex-relaxation fast path: eligible distribution goals solve
            # fractionally + round, with the greedy solve demoted to a short
            # warm-started repair.  Deadline (segmented) solves stay on the
            # greedy path — its preemption seams (segment boundaries, anytime
            # results) have no relax equivalent.  Cancel-only budgets take
            # the fast path: their fused greedy solve is byte-identical to a
            # budget-less one and cancellation is honored at goal boundaries
            # either way (every servlet operation carries a cancel token, so
            # gating on budget-is-None would leave the fast path dead in the
            # service).
            use_relax = (_relax.relaxation_enabled()
                         and (budget is None or not budget.segmented)
                         and getattr(goal, "relax_eligible", False))
            with tr.span(f"goal.{goal.name}", bucket=bucket) as gsp:
                c0, s0 = tel.compile_count(), tel.compile_seconds_total()
                if use_relax:
                    placement, agg, info = _relax.optimize_goal_relaxed(
                        self.solver, goal, priors, gctx, placement, agg)
                else:
                    placement, agg, info = self.solver.optimize_goal(
                        goal, priors, gctx, placement, agg, budget=budget)
                gsp.set("rounds", info.rounds)
                gsp.set("moves", info.moves_applied)
                gsp.set("fresh_compiles", tel.compile_count() - c0)
                gsp.set("compile_ms", round(
                    (tel.compile_seconds_total() - s0) * 1000.0, 3))
                if info.relaxed:
                    gsp.set("relaxed", True)
                    gsp.set("relax_ms", round(info.relax_ms, 3))
                    if info.relax_fallback:
                        gsp.set("relax_fallback", True)
                if info.preempted:
                    gsp.set("preempted", info.preempt_reason)
            infos.append(info)
            if prov_map is not None:
                # Attribute this goal's placement changes.  Relaxed passes
                # three-way diff through the stashed post-rounding placement:
                # changed only before it = relax, only after = greedy repair,
                # both = rounding.  Everything else (pure greedy, fallback)
                # is one greedy diff.  Last writer wins across goals.
                cur_local = _host_local_placement(placement)
                base = {
                    "goal": info.goal_name,
                    "round": int(info.rounds),
                    "costDelta": round(
                        (info.metric_after - info.metric_before)
                        / max(info.moves_applied, 1), 6),
                }
                rounded = exec_rec.pop_rounded(goal.name)
                if rounded is not None and not info.relax_fallback:
                    r_local = _host_local_placement(rounded)
                    pre = _changed_partitions(part_ids, prev_local, r_local)
                    post = _changed_partitions(part_ids, r_local, cur_local)
                    for p in pre | post:
                        path = ("rounding" if p in pre and p in post
                                else "relax" if p in pre else "repair")
                        prov_map[p] = dict(base, path=path)
                else:
                    for p in _changed_partitions(part_ids, prev_local,
                                                 cur_local):
                        prov_map[p] = dict(base, path="greedy")
                prev_local = cur_local
            if info.preempted:
                # A mid-goal preemption: the placement is the best found so
                # far.  Skip the hard-goal/no-worsen verdicts — they judge
                # CONVERGED solves, and a partial result is allowed to carry
                # residual violations (the caller sees partial=True).
                preempt_reason = info.preempt_reason
                continue
            stranded = 0
            if goal.is_hard and goal.uses_replica_moves:
                # Goals that cannot relocate replicas across brokers (intra-disk,
                # leadership-only) are not responsible for dead-broker evacuation.
                stranded = info.stranded_after
            try:
                check_hard_goal(goal, info, stranded)
            except OptimizationFailureError:
                prov_under.set(1)
                prov_right.set(0)
                raise
            worsened = (info.rounds > 0 and info.metric_after
                        > info.metric_before * (1 + 1e-5) + 1e-9)
            if worsened and not has_broken:
                prov_under.set(1)
                prov_right.set(0)
                raise OptimizationFailureError(
                    f"[{goal.name}] optimized result is worse than before: "
                    f"{info.metric_before:.6g} -> {info.metric_after:.6g}")
            elif worsened:
                LOG.warning("goal %s metric worsened during evacuation: "
                            "%.6g -> %.6g", goal.name,
                            info.metric_before, info.metric_after)
            priors.append(goal)
        prov_under.set(0)
        prov_right.set(1)
        partial = any(i.preempted for i in infos)

        # Polish pass: a later goal's moves may RE-violate an earlier SOFT
        # goal's band (hard goals are protected by the acceptance chains).
        # Re-solve each re-violated soft goal with EVERY other goal as a
        # prior, so the fix cannot disturb anything else — the sequential
        # reference ends with whatever its single pass produced; this ends
        # strictly better.  Goals that never satisfied their band in their
        # OWN pass are excluded: re-solving them cannot improve anything and
        # would pay a fresh all-but-self compile for nothing.
        satisfied_own_pass = {i.goal_name for i in infos
                              if i.violated_brokers_after == 0}
        for _ in range(self.polish_passes if not partial else 0):
            vioP = self.solver.violations(goals, gctx, placement, agg)
            revio = [g for g, v in zip(goals, vioP)
                     if not g.is_hard and g.name in satisfied_own_pass
                     and v > 0]
            if not revio:
                break
            for goal in revio:
                with tr.span(f"polish.{goal.name}", bucket=bucket) as psp:
                    c0, s0 = tel.compile_count(), tel.compile_seconds_total()
                    placement, agg, pinfo = self.solver.optimize_goal(
                        goal, [p for p in goals if p is not goal], gctx,
                        placement, agg)
                    psp.set("rounds", pinfo.rounds)
                    psp.set("moves", pinfo.moves_applied)
                    psp.set("fresh_compiles", tel.compile_count() - c0)
                    psp.set("compile_ms", round(
                        (tel.compile_seconds_total() - s0) * 1000.0, 3))
                if prov_map is not None:
                    # Polish re-solves are pure greedy repairs of a soft
                    # goal's band; their moves overwrite earlier attribution.
                    cur_local = _host_local_placement(placement)
                    for p in _changed_partitions(part_ids, prev_local,
                                                 cur_local):
                        prov_map[p] = {
                            "goal": goal.name, "path": "greedy",
                            "round": int(pinfo.rounds),
                            "costDelta": round(
                                (pinfo.metric_after - pinfo.metric_before)
                                / max(pinfo.moves_applied, 1), 6)}
                    prev_local = cur_local
                for i, inf in enumerate(infos):
                    if inf.goal_name == goal.name:
                        inf.rounds += pinfo.rounds
                        inf.moves_applied += pinfo.moves_applied
                        inf.violated_brokers_after = pinfo.violated_brokers_after
                        inf.metric_after = pinfo.metric_after

        # Per-goal convergence sensors feed the history rings (and the
        # Solver.*.rounds SLO objective) even with round recording off —
        # final rounds/moves are free outputs of every solve.
        for inf in infos:
            registry().settable_gauge(
                f"Solver.{inf.goal_name}.rounds").set(inf.rounds)
            registry().settable_gauge(
                f"Solver.{inf.goal_name}.moves").set(inf.moves_applied)
        solve_id = _convergence().record_solve(
            [{"goal": inf.goal_name, "curve": inf.round_curve,
              "metric_before": inf.metric_before, "rounds": inf.rounds,
              "moves": inf.moves_applied,
              **({"relax_ms": round(inf.relax_ms, 3),
                  "repair_rounds": inf.repair_rounds,
                  "relax_fallback": inf.relax_fallback}
                 if inf.relaxed else {})} for inf in infos],
            kind="propose" if not partial else "propose-partial",
            attrs={"generation": model_generation,
                   **({"preempted": preempt_reason} if partial else {})})
        if partial:
            registry().counter("Solver.partial-solves").inc()
            for inf in infos:
                if inf.preempted:
                    registry().counter("Solver.preemptions").inc()
            if budget is not None and budget.cancelled():
                registry().counter("Solver.cancellations").inc()

        # `agg` is exact here: every solve returns a fresh full recompute and
        # the placement has not changed since the last one.
        vioN = self.solver.violations(goals, gctx, placement, agg)
        violated_after = [g.name for g, v in zip(goals, vioN) if v > 0]
        final_local = _host_local_placement(placement)
        stats_after = compute_stats(state, final_local,
                                    self.constraint.balance_threshold)
        if prov_map is not None:
            # The convergence recorder's solve id lands only now (it records
            # after the goal loop), so provenance records back-reference it
            # post-hoc; None when round recording is off.
            for rec in prov_map.values():
                rec["solveId"] = solve_id
        proposals = diff_proposals(state, initial_local, final_local, meta,
                                   provenance=prov_map)

        result = OptimizerResult(
            proposals=proposals,
            goal_infos=infos,
            stats_before=stats_before,
            stats_after=stats_after,
            violated_goals_before=violated_before,
            violated_goals_after=violated_after,
            balancedness_score=balancedness_score(infos, goals),
            elapsed_s=time.monotonic() - t0,
            final_placement=final_local,
            partial=partial,
            preempt_reason=preempt_reason if partial else None,
        )
        proposal_timer.update_ms(result.elapsed_s * 1000.0)
        # Fidelity observatory: stamp the solve-time model fingerprint onto
        # the result and every proposal (host dicts, compare=False fields —
        # never part of the proposal cache key or any executable input, so
        # the solve is byte-identical with the recorder off).  Stamped
        # before the cache write so a cached result keeps the fingerprint
        # of the model it was actually solved from.
        fid = _fidelity()
        if fid.enabled:
            fp = fid.current_fingerprint()
            if fp is not None:
                result.fingerprint = fp
                for p in proposals:
                    object.__setattr__(p, "fingerprint", fp)
        registry().settable_gauge("AnomalyDetector.balancedness-score").set(
            result.balancedness_score)
        # Partial results are never cached: a later request with more budget
        # (or none) must get the converged answer, not the preempted one.
        if cache_key is not None and not partial:
            with self._cache_lock:
                self._cached = {cache_key: result}   # keep only latest generation
        return result

    # ------------------------------------------------- vmapped what-if batch

    def batch_remove_scenarios(
        self,
        state: ClusterState,
        placement: Placement,
        meta: ClusterMeta,
        removal_sets: Sequence[Sequence[int]],
        options: Optional[OptimizationOptions] = None,
        goals: Optional[Sequence[Goal]] = None,
        num_candidates: int = 512,
        warm_start: Optional[Placement] = None,
        budget=None,
    ) -> BatchScenarioResult:
        """Solve S independent remove-broker what-ifs as ONE vmapped program
        per goal (BASELINE config #5; SURVEY §7 'jit once, vmap over
        scenarios').

        The reference runs ``RemoveBrokersRunnable`` once per request,
        serializing N decommission studies; here each scenario is a vmap lane
        whose liveness/exclusion masks differ, so the entire fleet of what-ifs
        costs one compiled solve per goal.  Scenario-dependent context (host
        capacity) is recomputed inside the trace.

        ``warm_start`` seeds every lane from an already-balanced placement
        (the facade's last full solve) instead of the raw snapshot: lanes
        only repair their own scenario's damage, and the while_loop's
        per-lane progress guard retires converged lanes after their first
        no-move round while unconverged lanes keep iterating.
        """
        return self._batch_scenarios(state, placement, meta, removal_sets,
                                     revive=False, options=options,
                                     goals=goals, num_candidates=num_candidates,
                                     warm_start=warm_start, budget=budget)

    def batch_add_scenarios(
        self,
        state: ClusterState,
        placement: Placement,
        meta: ClusterMeta,
        addition_sets: Sequence[Sequence[int]],
        options: Optional[OptimizationOptions] = None,
        goals: Optional[Sequence[Goal]] = None,
        num_candidates: int = 512,
        warm_start: Optional[Placement] = None,
        budget=None,
    ) -> BatchScenarioResult:
        """Add-broker what-ifs as vmapped lanes (the AddBrokersRunnable
        analog of :meth:`batch_remove_scenarios`).

        ``state`` carries every CANDIDATE broker already provisioned but
        dead (``alive=False``, no replicas); each lane revives its addition
        set, and the count/distribution goals pull load onto the empty
        arrivals.  One compiled solve per goal covers the whole fleet of
        expansion studies."""
        return self._batch_scenarios(state, placement, meta, addition_sets,
                                     revive=True, options=options,
                                     goals=goals, num_candidates=num_candidates,
                                     warm_start=warm_start, budget=budget)

    def _batch_scenarios(self, state, placement, meta, scenario_sets, revive,
                         options, goals, num_candidates,
                         warm_start=None, budget=None) -> BatchScenarioResult:
        tr = _obsvc_tracer()
        if not tr.enabled:
            return self._batch_scenarios_impl(
                state, placement, meta, scenario_sets, revive, options, goals,
                num_candidates, warm_start, budget)
        with tr.span("batch_optimize", lanes=len(scenario_sets),
                     warm_start=warm_start is not None):
            return self._batch_scenarios_impl(
                state, placement, meta, scenario_sets, revive, options, goals,
                num_candidates, warm_start, budget)

    def _batch_scenarios_impl(self, state, placement, meta, scenario_sets,
                              revive, options, goals, num_candidates,
                              warm_start=None, budget=None) -> BatchScenarioResult:
        options = options or OptimizationOptions()
        goals = (list(goals) if goals is not None
                 else get_goals_by_priority(self.goal_names))
        # Context is built from the BASE placement either way: it only feeds
        # placement-independent statics (capacity, racks, exclusions); every
        # lane recomputes its aggregates from its own (possibly warm-started)
        # placement inside the compiled solve.
        gctx = build_context(state, placement, meta, self.constraint, options)
        masks = _scenario_masks(gctx, state, meta, scenario_sets, revive=revive)
        return self._run_mask_scenarios(gctx, state, placement, goals,
                                        num_candidates, scenario_sets, *masks,
                                        warm_start=warm_start, budget=budget)

    def _run_mask_scenarios(self, gctx, state, placement, goals,
                            num_candidates, scenario_sets,
                            alive_s, excl_move_s, excl_lead_s,
                            warm_start=None, budget=None) -> BatchScenarioResult:
        """Shared lane runner, routed through the compile service's lane-chunk
        plan: an S-lane batch is split into blocks at already-compiled (or
        canonical-bucket) lane widths, so a 64-lane request rides the 16-lane
        executable 4× instead of compiling a fresh 64-wide program (BENCH_r05:
        383 s cold at 64 lanes vs ~5 s/16-lane block warm).  Padding lanes
        duplicate the last real lane's masks and are trimmed from the result.

        Mesh-sharded runs are never chunked: lane count there is part of the
        sharding layout, and splitting would fight ``scenario_shardings``.
        """
        from cruise_control_tpu.compilesvc.chunking import plan_is_identity
        from cruise_control_tpu.compilesvc.service import compile_service

        import jax

        from cruise_control_tpu.obsvc.memory import memory_ledger

        s_n = len(scenario_sets)
        svc = compile_service()
        lane_key = None
        plan = None
        if self.solver.mesh is None:
            lane_key = svc.lane_key([g.name for g in goals],
                                    state.num_replicas_padded,
                                    int(np.asarray(alive_s).shape[1]),
                                    num_candidates)
            plan = svc.plan_lanes(s_n, lane_key)
            # Headroom guard: the cost ledger projects peak bytes for the
            # plan's widest lane block; a projection over the headroom
            # fraction of the device budget re-chunks onto narrower widths,
            # and when nothing fits the dispatch is refused outright.
            c = min(num_candidates, state.num_replicas_padded)
            plan, refused = memory_ledger().guard_lane_plan(
                plan, s_n, f"R{state.num_replicas_padded}-C{c}",
                svc.policy.lane_ladder,
                compiled_widths=svc.compiled_lane_widths(lane_key))
            if refused:
                import jax
                seed = placement if warm_start is None else warm_start
                placement_s = jax.tree_util.tree_map(
                    lambda x: np.broadcast_to(np.asarray(x)[None],
                                              (s_n,) + x.shape), seed)
                return BatchScenarioResult(
                    scenario_sets=[list(map(int, ids))
                                   for ids in scenario_sets],
                    goal_names=[],
                    violated_after=np.zeros((s_n, 0), np.int32),
                    moves=np.zeros((s_n, 0), np.int32),
                    rounds=np.zeros((s_n, 0), np.int32),
                    stranded_after=np.full(s_n, -1, np.int32),
                    final_placements=placement_s,
                    preempted=True,
                    memory_refused=True,
                )

        if plan is None or plan_is_identity(plan, s_n):
            out = self._run_lane_block(gctx, state, placement, goals,
                                       num_candidates, alive_s, excl_move_s,
                                       excl_lead_s, warm_start=warm_start,
                                       budget=budget)
            if lane_key is not None:
                svc.note_lanes_compiled(lane_key, s_n)
            rounds, moves, violated, stranded, placement_s = out
            n_goals = rounds.shape[1]
        else:
            blocks = []
            # Once the budget preempts a block mid-stack, later blocks run
            # only the same solved-goal prefix so every block's [S, G] stats
            # stay column-aligned (their lanes still need placements).
            goal_limit = len(goals)
            for chunk in plan:
                # Padding lanes re-run the last real lane; harmless work that
                # keeps every block at a canonical compiled width.
                idx = np.minimum(chunk.start + np.arange(chunk.size), s_n - 1)
                out = self._run_lane_block(
                    gctx, state, placement, goals[:goal_limit], num_candidates,
                    alive_s[idx], excl_move_s[idx], excl_lead_s[idx],
                    warm_start=warm_start, budget=budget)
                goal_limit = min(goal_limit, out[0].shape[1])
                svc.note_lanes_compiled(lane_key, chunk.size)
                n = chunk.n_real
                blocks.append(tuple(
                    jax.tree_util.tree_map(lambda x: x[:n], part)
                    for part in out))
            rounds = np.concatenate([b[0][:, :goal_limit] for b in blocks], axis=0)
            moves = np.concatenate([b[1][:, :goal_limit] for b in blocks], axis=0)
            violated = np.concatenate([b[2][:, :goal_limit] for b in blocks], axis=0)
            stranded = np.concatenate([b[3] for b in blocks], axis=0)
            placement_s = jax.tree_util.tree_map(
                lambda *xs: np.concatenate([np.asarray(x) for x in xs], axis=0),
                *[b[4] for b in blocks])
            n_goals = goal_limit

        preempted = n_goals < len(goals)
        goals = goals[:n_goals]
        if preempted:
            from cruise_control_tpu.common.metrics import registry
            registry().counter("Solver.preemptions").inc()
            registry().counter("Solver.partial-solves").inc()
            if budget is not None and budget.cancelled():
                registry().counter("Solver.cancellations").inc()
        # Per-lane early-exit rounds: the batch executables never carry the
        # round-stats buffer (vmapped buffers would dwarf the solve state),
        # but the i32[S,G] rounds matrix they already return is exactly the
        # per-lane early-exit story the recorder wants.
        _convergence().record_batch([g.name for g in goals], rounds,
                                    warm_start=warm_start is not None)
        return BatchScenarioResult(
            scenario_sets=[list(map(int, ids)) for ids in scenario_sets],
            goal_names=[g.name for g in goals],
            violated_after=violated,
            moves=moves,
            rounds=rounds,
            stranded_after=stranded,
            final_placements=placement_s,
            preempted=preempted,
        )

    def _run_lane_block(self, gctx, state, placement, goals, num_candidates,
                        alive_s, excl_move_s, excl_lead_s, warm_start=None,
                        budget=None):
        """Ledgered wrapper over :meth:`_run_lane_block_impl`: the block's
        broadcast lane tensors (per-lane masks + seed placements) are the
        transient device-buffer bill of a what-if batch — posted to the
        ``lane-batch`` subsystem for the dispatch's lifetime."""
        from cruise_control_tpu.obsvc.memory import (SUBSYS_LANES,
                                                     measure_bytes,
                                                     memory_ledger)

        ledger = memory_ledger()
        lane_bytes = 0
        if ledger.enabled:
            s_n = int(np.asarray(alive_s).shape[0])
            seed = placement if warm_start is None else warm_start
            lane_bytes = (measure_bytes((alive_s, excl_move_s, excl_lead_s))
                          + s_n * measure_bytes(seed))
            ledger.post(SUBSYS_LANES, lane_bytes, kind="alloc")
        try:
            return self._run_lane_block_impl(
                gctx, state, placement, goals, num_candidates, alive_s,
                excl_move_s, excl_lead_s, warm_start=warm_start,
                budget=budget)
        finally:
            if ledger.enabled:
                ledger.post(SUBSYS_LANES, lane_bytes, kind="free")

    def _run_lane_block_impl(self, gctx, state, placement, goals,
                             num_candidates, alive_s, excl_move_s,
                             excl_lead_s, warm_start=None, budget=None):
        """One vmapped solve per goal over a block of lanes; returns host-local
        (rounds[S,G], moves[S,G], violated[S,G], stranded[S], placements).

        ``warm_start`` replaces the seed placement broadcast into the lanes.
        The executable is warm-start-agnostic — the placement is a traced
        input, so a warm block reuses the cold block's compilation.  Early
        exit is per-lane by construction: the vmapped while_loop's condition
        (work remaining ∧ progress ∧ round budget) masks each lane
        independently, so a lane seeded next to its fixed point stops
        spending candidate evaluations after its first no-move round."""
        import jax
        import jax.numpy as jnp

        s_n = int(np.asarray(alive_s).shape[0])
        alive_j = jnp.asarray(alive_s)
        excl_move_j = jnp.asarray(excl_move_s)
        excl_lead_j = jnp.asarray(excl_lead_s)

        seed = placement if warm_start is None else warm_start
        placement_s = jax.tree_util.tree_map(
            lambda x: jnp.broadcast_to(jnp.asarray(x)[None],
                                       (s_n,) + x.shape), seed)
        if self.solver.mesh is not None:
            from cruise_control_tpu.parallel import (
                replica_shardings,
                scenario_shardings,
            )
            r_pad = state.num_replicas_padded
            mesh = self.solver.mesh
            gctx = jax.device_put(gctx, replica_shardings(mesh, gctx, r_pad))
            lanes = (alive_j, excl_move_j, excl_lead_j, placement_s)
            alive_j, excl_move_j, excl_lead_j, placement_s = jax.device_put(
                lanes, scenario_shardings(mesh, lanes, r_pad, s_n))

        # Keep per-goal outputs on device inside the loop — converting eagerly
        # would synchronize each goal's execution with the next goal's trace/
        # compile instead of pipelining them.
        device_stats = []
        priors: List[Goal] = []
        stranded_d = None
        for goal in goals:
            # Goal-boundary budget check (a vmapped solve is not segmented —
            # lanes converge independently — so the boundary between goals is
            # the batch path's preemption seam).  At least one goal always
            # runs so every lane has a solved placement to return.
            if (budget is not None and priors and budget.should_stop()):
                break
            # Convex-relaxation fast path, vmapped across the lane block:
            # each lane's placement is replaced by its fractional-solve +
            # rounded warm start, and the existing vmapped greedy solve below
            # runs unchanged as the per-lane repair pass (few rounds to the
            # fixed point instead of a full ladder).  One extra dispatch per
            # eligible goal, shared by every lane in the block.  Same budget
            # gate as the sequential path: cancel-only budgets take the fast
            # path (the batch solve is never segmented), deadline budgets
            # stay greedy.
            if (_relax.relaxation_enabled()
                    and (budget is None or not budget.segmented)
                    and getattr(goal, "relax_eligible", False)):
                iters, k_cfg, waves, _tol = _relax.relaxation_params()
                k = min(k_cfg, num_candidates, state.num_replicas_padded)
                rfn = _relax._relax_batch_fn(
                    self.solver, goal, tuple(priors),
                    state.num_replicas_padded, k, waves)
                tr = _obsvc_tracer()
                if tr.enabled:
                    # Fence inside the span so relax_ms is device wall, not
                    # dispatch wall (same discipline as the solve spans).
                    with tr.span("solve.relax", goal=goal.name, lanes=s_n,
                                 candidates=k):
                        placement_s = rfn(gctx, alive_j, excl_move_j,
                                          excl_lead_j, placement_s,
                                          jnp.int32(iters))
                        jax.block_until_ready(placement_s)
                else:
                    placement_s = rfn(gctx, alive_j, excl_move_j,
                                      excl_lead_j, placement_s,
                                      jnp.int32(iters))
            batch = self.solver._batch_solve_fn(
                goal, tuple(priors), state.num_replicas_padded, num_candidates)
            (placement_s, rounds_d, moves_d, violated_d, stranded_d,
             *_rest) = batch(gctx, alive_j, excl_move_j, excl_lead_j, placement_s)
            device_stats.append((rounds_d, moves_d, violated_d))
            priors.append(goal)
        # Under a multi-process global mesh the per-lane stats and the
        # stacked placements span non-addressable devices; gather them so
        # every process reconstructs the same host-local values (identity
        # single-process).
        device_stats, stranded_d, placement_s = _host_local_placement(
            (device_stats, stranded_d, placement_s))
        rounds = np.stack([np.asarray(r) for r, _, _ in device_stats], axis=1)
        moves = np.stack([np.asarray(m) for _, m, _ in device_stats], axis=1)
        violated = np.stack([np.asarray(v) for _, _, v in device_stats], axis=1)
        stranded = np.asarray(stranded_d)
        return rounds, moves, violated, stranded, placement_s
