"""Solve budgets: deadlines + cancellation tokens for anytime solves.

A :class:`SolveBudget` rides a request from the servlet through the facade
into the optimizer and solver.  It carries two independent stop signals:

- an optional wall-clock **deadline** (monotonic, fixed at construction from
  ``deadline_ms``), and
- a **cancellation token** (a ``threading.Event``) any thread may set —
  ``POST /cancel_user_task``, the user-task wall-clock cap, the SLO
  solve-time escalation, and ``facade.shutdown``'s grace-drain all route
  through it.

The solver checks ``stop_reason()`` at every segment boundary (and the
optimizer between goals / batch lanes).  The greedy solve is *anytime* —
every round's placement is feasible and hard-goal-safe — so stopping simply
returns the best placement found so far, tagged ``partial``.

``segmented`` controls whether per-goal solves run through the segmented
executables: a deadline implies segmentation (the fused while_loop cannot
observe a clock), while a cancel-only budget defaults to the fused
executables — byte-identical to a budget-less solve — and is honored at
goal boundaries instead.  Callers wanting segment-granular cancellation
without a deadline pass ``segmented=True`` explicitly.
"""

from __future__ import annotations

import threading
import time
from typing import Optional


class SolveBudget:
    """Deadline + cancellation token threaded through one optimization."""

    def __init__(self, deadline_ms: Optional[float] = None,
                 cancel_event: Optional[threading.Event] = None,
                 segmented: Optional[bool] = None,
                 clock=time.monotonic):
        self._clock = clock
        deadline_ms = None if not deadline_ms or deadline_ms <= 0 \
            else float(deadline_ms)
        self.deadline_ms = deadline_ms
        self._deadline = (clock() + deadline_ms / 1000.0
                          if deadline_ms is not None else None)
        self.cancel_event = (cancel_event if cancel_event is not None
                             else threading.Event())
        self.segmented = (deadline_ms is not None if segmented is None
                          else bool(segmented))
        self._cancel_reason: Optional[str] = None
        self._lock = threading.Lock()

    def cancel(self, reason: str = "cancelled") -> None:
        """Set the token; first reason wins (later cancels are no-ops).
        The reason is ALSO pinned on the event itself, so the servlet's
        view of a task token and the facade's budget wrapping the same
        event agree on why the solve stopped."""
        with self._lock:
            if self._cancel_reason is None:
                self._cancel_reason = reason
        if getattr(self.cancel_event, "cancel_reason", None) is None:
            self.cancel_event.cancel_reason = reason
        self.cancel_event.set()

    def cancelled(self) -> bool:
        return self.cancel_event.is_set()

    @property
    def cancel_reason(self) -> Optional[str]:
        if not self.cancel_event.is_set():
            return None
        return (self._cancel_reason
                or getattr(self.cancel_event, "cancel_reason", None)
                or "cancelled")

    def expired(self) -> bool:
        return self._deadline is not None and self._clock() >= self._deadline

    def remaining_ms(self) -> Optional[float]:
        """Milliseconds to the deadline (clamped at 0), None without one."""
        if self._deadline is None:
            return None
        return max(0.0, (self._deadline - self._clock()) * 1000.0)

    def stop_reason(self) -> Optional[str]:
        """Why the solve should stop now, or None to keep going.
        Cancellation outranks the deadline (it carries operator intent)."""
        if self.cancel_event.is_set():
            return self.cancel_reason
        if self.expired():
            return "deadline"
        return None

    def should_stop(self) -> bool:
        return self.stop_reason() is not None
