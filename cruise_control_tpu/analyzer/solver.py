"""The batched greedy solver.

Replaces the reference's per-goal greedy search (``AbstractGoal.optimize``
:78-130 — ``while !finished: for broker: rebalanceForBroker`` with every
candidate action re-checked against all previously-optimized goals at
``AbstractGoal.maybeApplyBalancingAction`` :214-256).  The TPU formulation
makes every round one fused batch, with no sequential scan at all:

round (one jitted call per goal class)
 1. score all R replicas; ``lax.top_k`` picks ≤C candidates           (O(R))
 2. build the C×B feasibility mask: structural legitMove ∧ this goal's
    self-condition ∧ every prior goal's actionAcceptance               (O(C·B))
 3. per-candidate best destination by goal cost ``argmin``             (O(C·B))
 4. conflict-free selection: one move per partition always; per
    destination/host/source, EITHER at most one move (fallback) OR —
    when every in-play goal declares cumulative slacks — as many moves
    as the group's headroom fits, checked by within-group cumulative
    sums in priority order (multi-accept)                     (O(C log C))
 5. apply ALL kept moves with O(C) incremental scatter deltas
    (full aggregate recompute only at round start)                     (O(C))

Why step 4 makes batching safe: every predicate in step 2 was evaluated
against the round-start state; bounding each destination/host/source group's
CUMULATIVE consumption by the tightest in-play headroom means no subset of
kept moves can invalidate another kept move's capacity, count-band or
balance-band check, and partition uniqueness keeps rack/sibling predicates
exact.  Load conservation keeps balance-band thresholds fixed within a
round.  Anything skipped by conflict resolution is simply picked up next
round against fresh aggregates.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from cruise_control_tpu.analyzer.context import (
    Aggregates,
    GoalContext,
    apply_leadership_moves_batch,
    apply_replica_moves_batch,
    base_leadership_ok,
    base_replica_move_ok,
    compute_aggregates,
    current_leader_of,
    currently_offline,
    hash01,
    replica_role_load,
)
from cruise_control_tpu.analyzer.goals.base import Goal
from cruise_control_tpu.common.exceptions import OptimizationFailureError
from cruise_control_tpu.compilesvc.telemetry import telemetry as _compile_telemetry
from cruise_control_tpu.obsvc.tracer import tracer as _obsvc_tracer
from cruise_control_tpu.common.resources import Resource
from cruise_control_tpu.model.state import Placement

_SCORE_FLOOR = -1e29  # candidate scores below this are "not a candidate"
# Plain float (see leadership.py _BIG): no backend init at import.
_INF_COST = 3.4e38

# ---------------------------------------------------------------------------
# Round-level convergence recording (trace.solver.rounds).
#
# When enabled, every sequential solve threads a preallocated
# (max_rounds, ROUND_STATS_COLS) float32 buffer through the while_loop carry
# and scatters one row per round — no host callback, fusion preserved.  The
# flag joins the solver's jit-cache key and compilesvc bucket label, so the
# default-off executables (and their cache keys) are byte-identical to a
# build without the recorder.  The column layout is owned by
# obsvc/convergence.py (dependency-free, so it can be imported here without
# closing the solver↔obsvc cycle); _solve_body stacks its row in that order.

from cruise_control_tpu.obsvc.convergence import (  # noqa: E402
    ROUND_COL_APPLIED,
    ROUND_COL_METRIC,
    ROUND_COL_RESYNC,
    ROUND_COL_STALL,
    ROUND_COL_STRANDED,
    ROUND_COL_VIOLATED,
    ROUND_STATS_COLS,
)

_RECORD_ROUNDS = False


def set_round_recording(enabled: bool) -> None:
    """Process-wide trace.solver.rounds switch (wired by obsvc.configure)."""
    global _RECORD_ROUNDS
    _RECORD_ROUNDS = bool(enabled)


def round_recording_enabled() -> bool:
    return _RECORD_ROUNDS


_SEGMENT_ROUNDS_DEFAULT = 8


def set_default_segment_rounds(rounds: int) -> None:
    """Process-wide solver.segment.rounds (wired by main.build_app): every
    GoalSolver constructed without an explicit segment_rounds — including
    the shared default_solver() and per-request custom-goal solvers — picks
    it up.  Only budgeted (deadline-carrying) solves ever read it."""
    global _SEGMENT_ROUNDS_DEFAULT
    _SEGMENT_ROUNDS_DEFAULT = max(1, int(rounds))


def _top_candidates(score: jnp.ndarray, k: int, exact: bool = False,
                    force_exact=None):
    """(values, indices) of the ~k best-scoring rows, descending.

    ``lax.approx_max_k`` lowers to the TPU PartialReduce op — much faster
    than the full sort ``lax.top_k`` implies for large k over the replica
    axis.  Approximate selection is safe for SOFT goals: candidates are
    re-scored every round, so a recall miss is picked up a round later.
    HARD goals pass ``exact=True`` — approx misses are deterministic, so a
    shadowed-but-fixable candidate could repeat a zero-move round and turn
    the progress-based loop exit into a spurious OptimizationFailureError.

    ``force_exact`` (traced bool or None) covers the soft-goal edge of the
    same determinism trap: once the stall counter is running, approx recall
    misses repeat identically each round, so a shadowed-but-fixable
    candidate could ride the stall cutoff into a silently-accepted residual.
    Soft-goal solves pass ``stall > 0`` so every stalled round gets one
    exact pass before the cutoff can fire.
    """
    if exact or k >= score.shape[-1]:
        return jax.lax.top_k(score, k)
    if force_exact is None:
        return jax.lax.approx_max_k(score, k, recall_target=0.95)
    return jax.lax.cond(
        force_exact,
        lambda s: jax.lax.top_k(s, k),
        lambda s: jax.lax.approx_max_k(s, k, recall_target=0.95),
        score)


@dataclass
class GoalOptimizationInfo:
    """Host-side result of optimizing one goal."""

    goal_name: str
    rounds: int = 0
    moves_applied: int = 0
    leadership_moves: int = 0
    violated_brokers_before: int = 0
    violated_brokers_after: int = 0
    # Offline (dead-broker) replicas still stranded when the goal's loop
    # exited — consumed by the optimizer's hard-goal evacuation check.
    stranded_after: int = 0
    metric_before: float = 0.0
    metric_after: float = 0.0
    # Per-round convergence curve, shape (rounds, ROUND_STATS_COLS) —
    # present only when trace.solver.rounds recorded this solve.
    round_curve: Optional[np.ndarray] = None
    # The solve's budget expired / was cancelled before this goal converged
    # (anytime result: the placement is the best found so far, still
    # feasible and prior-goal-safe — see SolveBudget).
    preempted: bool = False
    # Why the solve stopped early ("deadline", "cancelled", operator reason).
    preempt_reason: Optional[str] = None
    # Convex-relaxation fast path (analyzer/relax.py).  When relaxed=True the
    # info covers the WHOLE relax+round+repair pass: metric/violated "before"
    # are re-anchored at the pre-relax placement, moves_applied includes the
    # rounding waves' moves, and rounds is the greedy repair's round count
    # (mirrored in repair_rounds for telemetry).  relax_fallback marks a pass
    # whose relaxed result regressed and was discarded for pure greedy.
    relaxed: bool = False
    relax_ms: float = 0.0
    repair_rounds: int = 0
    relax_fallback: bool = False

    @property
    def succeeded(self) -> bool:
        return self.violated_brokers_after == 0


def _chain_accept_replica(priors: Sequence[Goal]):
    def accept(gctx, placement, agg, r, dst):
        ok = base_replica_move_ok(gctx, placement, r, dst)
        for g in priors:
            ok = ok & g.accept_replica_move(gctx, placement, agg, r, dst)
        return ok
    return accept


def _chain_accept_leadership(priors: Sequence[Goal]):
    def accept(gctx, placement, agg, f):
        ok = base_leadership_ok(gctx, placement, f)
        for g in priors:
            ok = ok & g.accept_leadership_move(gctx, placement, agg, f)
        return ok
    return accept


def _chain_accept_swap(priors: Sequence[Goal]):
    """Both directional moves must be structurally legit, and every prior
    goal must accept the SWAP (AbstractGoal.java:271-322 applies the swap then
    re-checks optimized goals; goals may override accept_swap with an exact
    pairwise predicate)."""
    def accept(gctx, placement, agg, r_out, r_in, b_out, b_in):
        ok = (base_replica_move_ok(gctx, placement, r_out, b_in)
              & base_replica_move_ok(gctx, placement, r_in, b_out))
        for g in priors:
            ok = ok & g.accept_swap(gctx, placement, agg, r_out, r_in, b_out, b_in)
        return ok
    return accept


def _pick_dst_disk(gctx: GoalContext, agg: Aggregates, dst):
    """Emptiest alive logdir of dst (disk chosen at move-apply time)."""
    frac = agg.disk_load[dst] / jnp.maximum(gctx.state.disk_capacity[dst], 1e-9)
    frac = jnp.where(gctx.state.disk_alive[dst], frac, jnp.inf)
    return jnp.argmin(frac, axis=-1).astype(jnp.int32)


def _group_winners(order_key: jnp.ndarray, group: jnp.ndarray,
                   num_groups: int) -> jnp.ndarray:
    """bool[C]: is this candidate the best (smallest order_key) in its group.

    order_key carries C (out of range) for non-candidates so they never win.
    """
    best = jax.ops.segment_min(order_key, group, num_segments=num_groups)
    return best[group] == order_key


def _jittered(cost: jnp.ndarray, ok: jnp.ndarray, cand: jnp.ndarray,
              d2: jnp.ndarray, ridx, frac: float = 1.0) -> jnp.ndarray:
    """Add per-(candidate, dst) jitter scaled to each candidate's feasible
    cost range so the batch spreads over every acceptable destination instead
    of piling onto the single argmin (the feasibility mask already bounds
    quality: every candidate destination satisfies self_ok + acceptance).
    ``ridx`` (round index) reseeds the draw each round so an unlucky draw is
    never permanent across a zero-progress round."""
    lo = jnp.min(jnp.where(ok, cost, jnp.inf), axis=1, keepdims=True)
    hi = jnp.max(jnp.where(ok, cost, -jnp.inf), axis=1, keepdims=True)
    span = jnp.where(hi > lo, hi - lo, 0.0)
    scale = frac * span + 1e-6
    return cost + hash01(cand[:, None] + ridx * 7919, d2) * scale


def _src_sensitive(goal: Goal, priors: Sequence[Goal]) -> bool:
    """Does any acceptance predicate in play depend on the SOURCE broker's
    state?  If not, multiple moves may leave one source in a single batch
    (hard goals only shed load from sources, so their checks stay valid)."""
    return any(getattr(g, "src_sensitive_accept", False)
               for g in (goal, *priors))


def _cumulative_group_ok(order: jnp.ndarray, group: jnp.ndarray,
                         active: jnp.ndarray, constraints, c: int) -> jnp.ndarray:
    """bool[C]: does each active candidate fit its group's CUMULATIVE slacks.

    Candidates are processed in priority ``order`` within each ``group``
    (destination / source / host); a candidate passes iff, for every
    (weight[C], slack_of_row[C]) constraint, the running sum of weights of
    the ACTIVE candidates ahead of it in its group (including itself) stays
    within the group's slack.  One argsort + K cumsums — O(C log C).
    """
    key = group * (c + 1) + jnp.where(active, order, c)
    perm = jnp.argsort(key)
    g_s = group[perm]
    active_s = active[perm]
    is_start = jnp.concatenate([jnp.ones((1,), bool), g_s[1:] != g_s[:-1]])
    ok_s = jnp.ones(c, dtype=bool)
    for weight, slack_row in constraints:
        w_s = jnp.where(active_s, weight[perm], 0.0)
        cum = jnp.cumsum(w_s)
        excl = cum - w_s
        # Group base = exclusive cumsum at the group's first element;
        # weights are >= 0 so excl is non-decreasing and cummax broadcasts it.
        base = jax.lax.cummax(jnp.where(is_start, excl, -jnp.inf))
        within = cum - base
        # Zero-weight candidates never consume slack and must not be vetoed
        # by an already-negative group slack (mirrors the goals' per-candidate
        # "was over & consumes nothing" acceptance escapes).
        ok_s = ok_s & ((within <= slack_row[perm] + 1e-6) | (w_s <= 0.0))
    return jnp.zeros(c, dtype=bool).at[perm].set(ok_s) | ~active


def _multi_accept_constraints(goal: Goal, priors: Sequence[Goal], gctx,
                              placement, agg, cand, cand_load, is_lead_cand,
                              axis: str):
    """Gather (weight[C], slack[B]) cumulative constraints for one axis from
    the goal + priors (plus, for 'dst', the hard broker-capacity slacks the
    base feasibility always enforces)."""
    state = gctx.state
    out = []
    for g in (goal, *priors):
        fn = {"dst": g.dst_cumulative_slack,
              "src": g.src_cumulative_slack,
              "host": getattr(g, "host_cumulative_slack",
                              lambda *a: None)}[axis]
        got = fn(gctx, placement, agg, cand_load, is_lead_cand)
        if got is None:
            continue
        weight, slack = got
        if isinstance(weight, str):
            if weight == "potential_nw_out":
                weight = state.leader_load[cand, Resource.NW_OUT]
            elif weight == "leader_nw_in":
                weight = is_lead_cand * state.leader_load[cand, Resource.NW_IN]
            else:
                raise ValueError(f"unknown weight marker {weight!r}")
        out.append((weight, slack))
    return out


def _check_dst_slack_invariant(goal: Goal, priors: Sequence[Goal]) -> None:
    """Uncapped multi-accept arrivals are safe only if every in-play goal
    whose replica acceptance reads destination aggregate state bounds those
    arrivals — via a dst slack, the (topic, broker) group rule, or an
    explicit partition-/source-local exemption.  Trace-time, so a future
    goal cannot silently reintroduce the over-arrival hazard."""
    for g in (goal, *priors):
        overrides_accept = (type(g).accept_replica_move
                            is not Goal.accept_replica_move)
        declares_slack = (type(g).dst_cumulative_slack
                          is not Goal.dst_cumulative_slack)
        if (overrides_accept and not declares_slack
                and not getattr(g, "needs_topic_group", False)
                and not getattr(g, "dst_slack_exempt", False)):
            raise ValueError(
                f"{g.name}: multi_accept_safe goals overriding "
                "accept_replica_move must declare dst_cumulative_slack, set "
                "needs_topic_group, or mark dst_slack_exempt (acceptance "
                "reads no destination aggregates)")


def _stratified_top_dst(gctx: GoalContext, pscore: jnp.ndarray,
                        d: int) -> jnp.ndarray:
    """i32[d]: the d most attractive destination brokers, round-robin across
    racks by within-rack rank.

    Plain global top-d could prune an entire rack out of the tile (e.g. one
    hot rack), silently making rack-constrained moves infeasible this round.
    Taking every rack's best broker first, then every rack's second-best,
    etc., guarantees each rack keeps ~d/num_racks slots, so any
    rack-placement-feasible move keeps a destination in the tile; dead or
    invalid brokers ride along with -inf scores and are culled by the
    feasibility mask like any other infeasible pair."""
    order = jnp.argsort(-pscore).astype(jnp.int32)           # best first
    rack_sorted = gctx.state.rack[order]                     # i32[B]
    onehot = (rack_sorted[:, None]
              == jnp.arange(gctx.num_racks, dtype=jnp.int32)[None, :])
    cnt = jnp.cumsum(onehot.astype(jnp.int32), axis=0)
    rank = jnp.take_along_axis(cnt, rack_sorted[:, None], axis=1)[:, 0] - 1
    # Stable sort keeps global score order within equal ranks ("order" is
    # already score-sorted).  NOT a composite rank*B+idx key: that product
    # overflows int32 past ~46K padded brokers, and int64 silently downcasts
    # under JAX's default x64-disabled mode.
    stratified = order[jnp.argsort(rank, stable=True)]
    return stratified[:d]


def _replica_phase(goal: Goal, priors: Sequence[Goal], num_candidates: int,
                   score_fn: Callable, self_ok_fn: Callable,
                   dst_mask_fn: Optional[Callable] = None,
                   jitter_frac: float = 1.0,
                   prune_fn: Optional[Callable] = None,
                   max_dst: int = 0):
    """One conflict-free batched replica-move phase:
    (gctx, placement, agg) -> (placement, agg, applied).

    ``prune_fn`` (goal.dst_prune_score) + ``max_dst`` tile the DESTINATION
    axis: the C×B pair matrices dominate solve cost at north-star scale, and
    a goal that can rank brokers by attractiveness (band/count headroom)
    only ever sends load to the best few hundred of them in one round."""
    accept = _chain_accept_replica(priors)
    need_src_cap = _src_sensitive(goal, priors)
    multi_accept = all(getattr(g, "multi_accept_safe", False)
                       for g in (goal, *priors))
    if multi_accept:
        _check_dst_slack_invariant(goal, priors)
    needs_topic_group = any(getattr(g, "needs_topic_group", False)
                            for g in (goal, *priors))

    def phase(gctx: GoalContext, placement: Placement, agg: Aggregates,
              ridx, force_exact=None):
        c = num_candidates
        score = score_fn(gctx, placement, agg)
        top_score, cand = _top_candidates(score, c, exact=goal.is_hard,
                                          force_exact=force_exact)
        is_cand = top_score > _SCORE_FLOOR
        run = jnp.any(is_cand)
        dst_mask = None
        if dst_mask_fn is not None:
            # Pull phases: the destination mask (under-band brokers) is the
            # phase's whole purpose — when it is empty every pair would be
            # infeasible, so the O(B) mask check skips the C×B tile outright.
            # At north-star scale most tail rounds have over-band violators
            # only, making this the common case.
            dst_mask = dst_mask_fn(gctx, placement, agg)
            run = run & jnp.any(dst_mask)
        # Zero-candidate rounds skip the whole C×B tile.  Only in UNBATCHED
        # solves: under the what-if vmap the predicate is lane-dependent, so
        # XLA lowers the cond to a select and runs both branches — the skip
        # is inert there, not wrong.
        return jax.lax.cond(
            run,
            lambda pl, ag: _phase_body(gctx, pl, ag, ridx, top_score, cand,
                                       is_cand, dst_mask),
            lambda pl, ag: (pl, ag, jnp.int32(0)),
            placement, agg)

    def _phase_body(gctx: GoalContext, placement: Placement, agg: Aggregates,
                    ridx, top_score, cand, is_cand, dst_mask=None):
        state = gctx.state
        b = state.num_brokers_padded
        c = num_candidates
        r2 = cand[:, None]
        pscore = (prune_fn(gctx, placement, agg)
                  if prune_fn is not None and 0 < max_dst < b else None)
        if pscore is not None:
            dst_ids = _stratified_top_dst(gctx, pscore, max_dst)
            d2 = dst_ids[None, :]
            nd = max_dst
        else:
            dst_ids = None
            d2 = jnp.arange(b)[None, :]
            nd = b
        ok = accept(gctx, placement, agg, r2, d2)
        ok = ok & self_ok_fn(gctx, placement, agg, r2, d2)
        if dst_mask is not None:
            ok = ok & (dst_mask if dst_ids is None
                       else dst_mask[dst_ids])[None, :]
        cost_raw = goal.dst_cost(gctx, placement, agg, r2, d2)
        cost = jnp.where(ok, cost_raw, _INF_COST)
        # Rank matching: the i-th candidate (priority order) gets the i-th
        # cheapest destination — distinct destinations by construction, so a
        # batch fills as many brokers as it has candidates instead of every
        # argmin landing on the single emptiest broker.  Infeasible pairs
        # fall back to the candidate's own jittered argmin.  All indices here
        # live in the (possibly pruned) tile space; ``dst`` maps back to
        # broker ids right below.
        proxy = jnp.min(cost, axis=0)                        # f32[nd]
        ranked = jnp.argsort(proxy).astype(jnp.int32)        # cheap → expensive
        assign = ranked[jnp.arange(c, dtype=jnp.int32) % nd]
        ok_assign = jnp.take_along_axis(ok, assign[:, None], axis=1)[:, 0]
        jcost = jnp.where(ok, _jittered(cost_raw, ok, cand, d2, ridx,
                                        frac=jitter_frac), _INF_COST)
        fallback = jnp.argmin(jcost, axis=1).astype(jnp.int32)
        dst = jnp.where(ok_assign, assign, fallback)
        if dst_ids is not None:
            dst = dst_ids[dst]
        feasible = jnp.any(ok, axis=1) & is_cand

        # Conflict-free batch, candidate-priority order.
        order = jnp.where(feasible, jnp.arange(c, dtype=jnp.int32), c)
        part = state.partition[cand]
        host = state.host[dst]
        src = placement.broker[cand]
        keep = feasible & _group_winners(order, part, gctx.num_partitions)
        if multi_accept:
            # Multi-accept: a destination/host/source may take SEVERAL
            # candidates in one round as long as their cumulative consumption
            # fits every in-play goal's headroom (plus the hard capacity
            # slacks) — the convergence-rate fix over one-move-per-broker.
            cand_load = replica_role_load(gctx, placement, cand)    # [C,4]
            is_lead_c = placement.is_leader[cand]
            if needs_topic_group:
                topic = state.topic[cand]
                nseg = gctx.num_topics * b
                keep = (keep
                        & _group_winners(order, topic * b + dst, nseg)
                        & _group_winners(order, topic * b + src, nseg))
            dst_cons = _multi_accept_constraints(
                goal, priors, gctx, placement, agg, cand, cand_load,
                is_lead_c, "dst")
            if dst_cons:
                keep = keep & _cumulative_group_ok(
                    order, dst, keep,
                    [(w, s[dst]) for w, s in dst_cons], c)
            # else: arrivals are UNCAPPED.  Safe by invariant: every
            # multi_accept_safe goal whose acceptance reads destination
            # aggregate state declares a dst slack (capacity, counts, bands)
            # or is protected by the per-(topic, broker) group rule; the
            # remaining predicates (racks, siblings) are partition-local and
            # partition uniqueness keeps them exact.  This matters most for
            # pure-structure goals (RackAware, dead-broker evacuation) where
            # one-arrival-per-destination would cap a round at B moves.
            # Physical per-logdir fill guard (JBOD): every arrival a broker
            # takes this round gets the SAME pre-round argmin disk, so their
            # cumulative size must fit that logdir's remaining capacity.
            d_n = state.num_disks_per_broker
            if d_n > 1:
                dd = _pick_dst_disk(gctx, agg, dst)
                disk_limit = (gctx.capacity_threshold[Resource.DISK]
                              * state.disk_capacity)
                disk_slack = (disk_limit - agg.disk_load)[dst, dd]
                keep = keep & _cumulative_group_ok(
                    order, dst * d_n + dd, keep,
                    [(cand_load[:, Resource.DISK], disk_slack)], c)
            # Host-level constraints (same-host moves are host-neutral, so
            # their weight is zeroed).
            same_host = state.host[src] == host
            host_cons = [
                (jnp.where(same_host, 0.0, w), s[host])
                for w, s in _multi_accept_constraints(
                    goal, priors, gctx, placement, agg, cand, cand_load,
                    is_lead_c, "host")
            ]
            if host_cons:
                keep = keep & _cumulative_group_ok(order, host, keep,
                                                   host_cons, c)
            # (No host fallback needed: only host-scoped CapacityGoals read
            # host state in acceptance, and exactly those supply host_cons.)
            src_cons = _multi_accept_constraints(
                goal, priors, gctx, placement, agg, cand, cand_load,
                is_lead_c, "src")
            if src_cons:
                # Dead/offline sources are exempt: evacuation must proceed.
                src_dead = ~state.alive[src] | currently_offline(
                    gctx, placement, cand)
                src_rows = [(w, jnp.where(src_dead, jnp.inf, s[src]))
                            for w, s in src_cons]
                keep = keep & _cumulative_group_ok(order, src, keep,
                                                   src_rows, c)
        else:
            keep = (keep
                    & _group_winners(order, dst, b)
                    & _group_winners(order, host, gctx.num_hosts))
            if need_src_cap:
                keep = keep & _group_winners(order, src, b)

        dst_disk = _pick_dst_disk(gctx, agg, dst)
        # Incremental aggregate update (O(C) scatters, not an O(R) recompute):
        # non-kept rows target their own broker/disk, so their deltas cancel.
        dst_eff = jnp.where(keep, dst, placement.broker[cand])
        disk_eff = jnp.where(keep, dst_disk, placement.disk[cand])
        placement, agg = apply_replica_moves_batch(gctx, placement, agg,
                                                   cand, dst_eff, disk_eff)
        applied = jnp.sum(keep.astype(jnp.int32))
        return placement, agg, applied

    return phase


def _leadership_phase(goal: Goal, priors: Sequence[Goal], num_candidates: int):
    accept = _chain_accept_leadership(priors)
    multi = all(getattr(g, "multi_leadership_safe", False)
                for g in (goal, *priors))
    # Only goals with per-topic LEADER-count acceptance need the (topic,
    # broker) single-touch rule here; replica-count topic groups are
    # leadership-neutral and would needlessly re-cap the batch.
    topic_group = any(getattr(g, "leadership_topic_group", False)
                      for g in (goal, *priors))

    def phase(gctx: GoalContext, placement: Placement, agg: Aggregates,
              ridx, force_exact=None):
        del ridx    # promotions carry no tie-breaking jitter
        c = num_candidates
        score = goal.leadership_candidate_score(gctx, placement, agg)
        top_score, cand = _top_candidates(score, c, exact=goal.is_hard,
                                          force_exact=force_exact)
        is_cand = top_score > _SCORE_FLOOR
        return jax.lax.cond(
            jnp.any(is_cand),
            lambda pl, ag: _leadership_body(gctx, pl, ag, top_score, cand,
                                            is_cand),
            lambda pl, ag: (pl, ag, jnp.int32(0)),
            placement, agg)

    def _leadership_body(gctx: GoalContext, placement: Placement,
                         agg: Aggregates, top_score, cand, is_cand):
        state = gctx.state
        c = num_candidates
        ok = (is_cand & accept(gctx, placement, agg, cand)
              & goal.leadership_self_ok(gctx, placement, agg, cand))
        old = current_leader_of(gctx, placement, state.partition[cand])  # i32[C]
        ok = ok & (old >= 0)
        old_safe = jnp.maximum(old, 0)

        # One promotion per partition always; per gaining/losing broker,
        # EITHER at most one promotion (fallback) OR — when every in-play
        # goal composes — as many as the brokers' cumulative load/count
        # headroom fits (one check over both roles' streams, so a broker
        # that gains AND loses leadership shares a single budget).
        order = jnp.where(ok, jnp.arange(c, dtype=jnp.int32), c)
        gain_b = placement.broker[cand]
        lose_b = placement.broker[old_safe]
        b = state.num_brokers_padded
        keep = (ok
                & _group_winners(order, state.partition[cand], gctx.num_partitions))
        if multi:
            if topic_group:
                # Promoted follower and demoted leader share the partition
                # (hence the topic): one touch per (topic, broker) per round.
                t = state.topic[cand]
                nseg = gctx.num_topics * b
                key_g = t * b + gain_b
                key_l = t * b + lose_b
                keys2 = jnp.concatenate([key_g, key_l])
                order_t = jnp.concatenate([order, order])
                best = jax.ops.segment_min(order_t, keys2, num_segments=nseg)
                keep = keep & (best[key_g] == order) & (best[key_l] == order)
            rows = []
            h_rows = []
            group2 = jnp.concatenate([gain_b, lose_b])
            h_group2 = jnp.concatenate([state.host[gain_b], state.host[lose_b]])
            for g in (goal, *priors):
                got = g.leadership_cumulative_slack(gctx, placement, agg,
                                                    cand, old_safe)
                if got is None:
                    continue
                dg, dl, up, low, up_h = got
                d2 = jnp.concatenate([dg, dl])
                pos2 = jnp.maximum(d2, 0.0)
                rows.append((pos2, up[group2]))
                if low is not None:
                    rows.append((jnp.maximum(-d2, 0.0), low[group2]))
                if up_h is not None:
                    h_rows.append((pos2, up_h[h_group2]))
            order2 = jnp.concatenate([order * 2, order * 2 + 1])
            act2 = jnp.concatenate([keep, keep])
            if rows:
                ok2 = _cumulative_group_ok(order2, group2, act2, rows, 2 * c)
                keep = keep & ok2[:c] & ok2[c:]
            if h_rows:
                ok2h = _cumulative_group_ok(order2, h_group2,
                                            jnp.concatenate([keep, keep]),
                                            h_rows, 2 * c)
                keep = keep & ok2h[:c] & ok2h[c:]
        else:
            keep = (keep
                    & _group_winners(order, gain_b, b)
                    & _group_winners(order, lose_b, b))

        # Non-kept rows scatter to an out-of-range dummy (mode='drop'): their
        # old_safe values repeat across rows (every non-candidate/padded row
        # gathers SOME partition's leader), and a stale write would clobber
        # the kept row's demotion (duplicate-index set is last-write-wins).
        dummy = state.num_replicas_padded
        is_leader = (placement.is_leader
                     .at[jnp.where(keep, cand, dummy)].set(True, mode="drop")
                     .at[jnp.where(keep, old_safe, dummy)].set(False, mode="drop"))
        placement = placement.replace(is_leader=is_leader)
        applied = jnp.sum(keep.astype(jnp.int32))
        agg = apply_leadership_moves_batch(gctx, placement, agg,
                                           cand, old_safe, keep)
        return placement, agg, applied

    return phase


def _swap_phase(goal: Goal, priors: Sequence[Goal], num_candidates: int,
                jitter_frac: float = 1.0):
    """Batched replica SWAP round (ResourceDistributionGoal.java:543-725).

    top-k heavy replicas on loaded brokers × top-k light replicas on
    less-loaded brokers → C×C pair feasibility (both directions structurally
    legit ∧ every prior goal accepts the swap ∧ this goal's band math says the
    exchange helps) → per-out-candidate best partner by residual imbalance →
    conflict-free selection.  Each partition/in-partner is used once; brokers
    and hosts take EITHER at most one kept swap (fallback) OR — when every
    in-play goal declares multi-swap composition — as many swaps as their
    cumulative transferred deltas fit (the convergence-rate fix for brokers
    whose only legal mechanism is exchanging load, e.g. count-banded
    NW-full brokers starving for CPU).
    """
    accept = _chain_accept_swap(priors)
    multi_swap = all(getattr(g, "multi_swap_safe", False)
                     for g in (goal, *priors))
    topic_group = any(getattr(g, "needs_topic_group", False)
                      or getattr(g, "swap_topic_group", False)
                      for g in (goal, *priors))

    def phase(gctx: GoalContext, placement: Placement, agg: Aggregates,
              ridx, force_exact=None):
        c = num_candidates
        out_top, out_c = _top_candidates(
            goal.swap_out_score(gctx, placement, agg, ridx), c,
            exact=goal.is_hard, force_exact=force_exact)
        in_top, in_c = _top_candidates(
            goal.swap_in_score(gctx, placement, agg, ridx), c,
            exact=goal.is_hard, force_exact=force_exact)
        # No exchange possible without candidates on BOTH sides — skip the
        # C×C pair tile entirely (see _replica_phase).
        any_pair = (jnp.any(out_top > _SCORE_FLOOR)
                    & jnp.any(in_top > _SCORE_FLOOR))
        return jax.lax.cond(
            any_pair,
            lambda pl, ag: _swap_body(gctx, pl, ag, ridx, out_top, out_c,
                                      in_top, in_c),
            lambda pl, ag: (pl, ag, jnp.int32(0)),
            placement, agg)

    def _swap_body(gctx: GoalContext, placement: Placement, agg: Aggregates,
                   ridx, out_top, out_c, in_top, in_c):
        state = gctx.state
        c = num_candidates
        b = state.num_brokers_padded

        ro = out_c[:, None]                      # [C,1]
        ri = in_c[None, :]                       # [1,C]
        bo = placement.broker[ro]
        bi = placement.broker[ri]
        ok = ((out_top[:, None] > _SCORE_FLOOR) & (in_top[None, :] > _SCORE_FLOOR)
              & (bo != bi)
              & (state.partition[ro] != state.partition[ri])
              & goal.swap_ok(gctx, placement, agg, ro, ri)
              & accept(gctx, placement, agg, ro, ri, bo, bi))
        cost_raw = goal.swap_cost(gctx, placement, agg, ro, ri)
        # Partner jitter spreads rows over distinct in-partners (otherwise
        # many rows argmin onto the same partner and uniqueness drops them).
        pos = jnp.arange(c, dtype=jnp.int32)[None, :]
        cost = jnp.where(ok, _jittered(cost_raw, ok, out_c, pos, ridx,
                                       frac=jitter_frac), _INF_COST)
        # Rank matching (same mechanism as the replica phase's destination
        # assignment): the i-th out-candidate gets the i-th cheapest partner
        # COLUMN — distinct partners by construction.  Jitter alone cannot
        # spread rows when a few partners are distinctly cheapest (measured
        # at north-star scale: 1024 feasible rows argmin onto ~35 partners
        # on the 4 deepest-gap brokers, so in-partner uniqueness kept 35 of
        # 1024 and the deficient-broker tail burned ~20 rounds).  Rows whose
        # assigned pair is infeasible fall back to their own argmin.
        proxy = jnp.min(cost, axis=0)                        # f32[C] per-partner
        # (ranked already has length c — row i simply takes rank i, unlike
        # the replica phase where B != C forces a wrap.)
        assign = jnp.argsort(proxy).astype(jnp.int32)        # cheap → expensive
        ok_assign = jnp.take_along_axis(ok, assign[:, None], axis=1)[:, 0]
        fallback = jnp.argmin(cost, axis=1).astype(jnp.int32)
        sel = jnp.where(ok_assign, assign, fallback)
        feasible = jnp.take_along_axis(ok, sel[:, None], axis=1)[:, 0]

        r_in_sel = in_c[sel]
        b_out_row = placement.broker[out_c]
        b_in_sel = placement.broker[r_in_sel]
        order = jnp.where(feasible, jnp.arange(c, dtype=jnp.int32), c)

        # A kept swap touches 2 brokers, 2 hosts, 2 partitions; for the
        # at-most-once rules, uniqueness runs over both roles' keys.
        def both_roles_winner(key_out, key_in, num_groups):
            keys = jnp.concatenate([key_out, key_in])
            order2 = jnp.concatenate([order, order])
            best = jax.ops.segment_min(order2, keys, num_segments=num_groups)
            return (best[key_out] == order) & (best[key_in] == order)

        keep = (feasible
                & both_roles_winner(state.partition[out_c],
                                    state.partition[r_in_sel],
                                    gctx.num_partitions)
                # Every in-partner is used by at most one row.
                & _group_winners(order, r_in_sel, state.num_replicas_padded))

        disk_for_out = _pick_dst_disk(gctx, agg, b_in_sel)   # r_out lands on b_in
        disk_for_in = _pick_dst_disk(gctx, agg, b_out_row)   # r_in lands on b_out

        if multi_swap:
            if topic_group:
                # One swap per (topic, broker) TOUCH per round: each row
                # touches (t_out, b_out/b_in) and (t_in, b_out/b_in).
                t_out = state.topic[out_c]
                t_in = state.topic[r_in_sel]
                nseg = gctx.num_topics * b
                keep = (keep
                        & both_roles_winner(t_out * b + b_out_row,
                                            t_out * b + b_in_sel, nseg)
                        & both_roles_winner(t_in * b + b_out_row,
                                            t_in * b + b_in_sel, nseg))
            # Cumulative per-broker bounds on the transferred deltas.
            d_load = (replica_role_load(gctx, placement, out_c)
                      - replica_role_load(gctx, placement, r_in_sel))  # [C,4]
            lnwout = state.leader_load[:, Resource.NW_OUT]
            d_pot = lnwout[out_c] - lnwout[r_in_sel]
            lnwin = state.leader_load[:, Resource.NW_IN]
            d_lbi = (placement.is_leader[out_c] * lnwin[out_c]
                     - placement.is_leader[r_in_sel] * lnwin[r_in_sel])
            d_lead = (placement.is_leader[out_c].astype(jnp.float32)
                      - placement.is_leader[r_in_sel].astype(jnp.float32))
            # Both role streams share ONE cumulative check per broker: swap
            # tiles today draw gainers and shedders from disjoint broker sets
            # (above- vs below-average), but a broker appearing in both
            # streams must not spend its up/low slack once per role, so the
            # check is structural, not an invariant to trip over later
            # (mirrors the host- and leadership-phase budgets).
            b_rows = []
            b_group2 = jnp.concatenate([b_in_sel, b_out_row])
            for g in (goal, *priors):
                got = g.swap_cumulative_slack(gctx, placement, agg,
                                              d_load, d_pot, d_lbi, d_lead)
                if got is None:
                    continue
                delta, up, low = got
                p_w = jnp.maximum(delta, 0.0)
                n_w = jnp.maximum(-delta, 0.0)
                b_rows.append((jnp.concatenate([p_w, n_w]), up[b_group2]))
                if low is not None:
                    b_rows.append((jnp.concatenate([n_w, p_w]),
                                   low[b_group2]))
            if b_rows:
                b_order2 = jnp.concatenate([order * 2, order * 2 + 1])
                b_act2 = jnp.concatenate([keep, keep])
                ok_b = _cumulative_group_ok(b_order2, b_group2, b_act2,
                                            b_rows, 2 * c)
                keep = keep & ok_b[:c] & ok_b[c:]
            # Host-scoped bounds (upper only; same-host swaps are neutral).
            # Both role streams share ONE check per host — a host holding a
            # hot AND a cold broker must not absorb its slack once per role.
            h_in = state.host[b_in_sel]
            h_out = state.host[b_out_row]
            same_h = h_in == h_out
            h_rows = []
            h_group2 = jnp.concatenate([h_in, h_out])
            for g in (goal, *priors):
                got = g.swap_host_cumulative_slack(gctx, placement, agg, d_load)
                if got is None:
                    continue
                delta, up_h = got
                p_w = jnp.where(same_h, 0.0, jnp.maximum(delta, 0.0))
                n_w = jnp.where(same_h, 0.0, jnp.maximum(-delta, 0.0))
                h_rows.append((jnp.concatenate([p_w, n_w]), up_h[h_group2]))
            if h_rows:
                h_order2 = jnp.concatenate([order * 2, order * 2 + 1])
                h_act2 = jnp.concatenate([keep, keep])
                ok_h = _cumulative_group_ok(h_order2, h_group2, h_act2,
                                            h_rows, 2 * c)
                keep = keep & ok_h[:c] & ok_h[c:]
            # JBOD fill guard: both arrival streams (r_out→b_in's logdir,
            # r_in→b_out's logdir) must cumulatively fit their target disks.
            d_n = state.num_disks_per_broker
            if d_n > 1:
                size_out = replica_role_load(gctx, placement, out_c)[:, Resource.DISK]
                size_in = replica_role_load(gctx, placement, r_in_sel)[:, Resource.DISK]
                key_in_arr = b_in_sel * d_n + disk_for_out
                key_out_arr = b_out_row * d_n + disk_for_in
                disk_limit = (gctx.capacity_threshold[Resource.DISK]
                              * state.disk_capacity)
                disk_slack = (disk_limit - agg.disk_load).reshape(-1)
                order2 = jnp.concatenate([order * 2, order * 2 + 1])
                group2 = jnp.concatenate([key_in_arr, key_out_arr])
                act2 = jnp.concatenate([keep, keep])
                w2 = jnp.concatenate([size_out, size_in])
                ok2 = _cumulative_group_ok(
                    order2, group2, act2, [(w2, disk_slack[group2])], 2 * c)
                keep = keep & ok2[:c] & ok2[c:]
        else:
            keep = (keep
                    & both_roles_winner(b_out_row, b_in_sel, b)
                    & both_roles_winner(state.host[b_out_row],
                                        state.host[b_in_sel], gctx.num_hosts))

        # Incremental apply: a swap is two conflict-free moves.  r_out rows
        # are distinct (top-k indices) so no-ops encode as dst==src; r_in
        # rows may repeat across non-kept rows, so they are keep-masked.
        dst_out = jnp.where(keep, b_in_sel, b_out_row)
        ddisk_out = jnp.where(keep, disk_for_out, placement.disk[out_c])
        placement, agg = apply_replica_moves_batch(
            gctx, placement, agg, out_c, dst_out, ddisk_out)
        placement, agg = apply_replica_moves_batch(
            gctx, placement, agg, r_in_sel, b_out_row, disk_for_in, keep=keep)
        applied = jnp.sum(keep.astype(jnp.int32))
        return placement, agg, applied

    return phase


def _intra_disk_phase(goal: Goal, num_candidates: int):
    def phase(gctx: GoalContext, placement: Placement, agg: Aggregates,
              ridx, force_exact=None):
        del ridx
        state = gctx.state
        d_n = state.num_disks_per_broker
        c = num_candidates
        score = goal.disk_candidate_score(gctx, placement, agg)
        top_score, cand = _top_candidates(score, c, exact=goal.is_hard,
                                          force_exact=force_exact)
        is_cand = top_score > _SCORE_FLOOR

        r2 = cand[:, None]
        d2 = jnp.arange(d_n)[None, :]
        ok = goal.disk_move_ok(gctx, placement, agg, r2, d2)
        b2 = placement.broker[cand][:, None]
        frac = ((agg.disk_load[b2, d2] + state.leader_load[r2, 3])
                / jnp.maximum(state.disk_capacity[b2, d2], 1e-9))
        cost = jnp.where(ok, frac, _INF_COST)
        best = jnp.argmin(cost, axis=1).astype(jnp.int32)
        feasible = jnp.any(ok, axis=1) & is_cand

        # One move per source logdir and per destination logdir.
        b_of = placement.broker[cand]
        src_key = b_of * d_n + placement.disk[cand]
        dst_key = b_of * d_n + best
        order = jnp.where(feasible, jnp.arange(c, dtype=jnp.int32), c)
        nseg = state.num_brokers_padded * d_n
        keep = (feasible
                & _group_winners(order, src_key, nseg)
                & _group_winners(order, dst_key, nseg))

        new_disk = jnp.where(keep, best, placement.disk[cand])
        # Incremental: only disk_load changes for intra-broker moves.  Use the
        # ROLE-based disk size — a follower's follower_load DISK is what the
        # aggregate holds for it.
        size = jnp.where(keep, replica_role_load(gctx, placement, cand)[:, Resource.DISK], 0.0)
        disk_load = (agg.disk_load
                     .at[b_of, placement.disk[cand]].add(-size)
                     .at[b_of, new_disk].add(size))
        placement = placement.replace(disk=placement.disk.at[cand].set(new_disk))
        applied = jnp.sum(keep.astype(jnp.int32))
        return placement, agg.replace(disk_load=disk_load), applied

    return phase


class _CompileTracked:
    """Callable proxy over a jitted function that feeds compile telemetry.

    jit retraces per input *shape*, so one executable family (one
    ``_round_cache`` entry) can hide several XLA compiles — e.g. the batch
    solve recompiles for each new lane count.  A growth of the jit cache
    around a call marks that call as a compile; its wall time (trace +
    compile + that first execution) is the compile timer.  Attribute access
    delegates to the wrapped jit function so ``lower()``/AOT callers keep
    working.
    """

    def __init__(self, fn, label_fn):
        self._fn = fn
        self._label_fn = label_fn
        self._ever_called = False

    def __call__(self, *args, **kwargs):
        size_fn = getattr(self._fn, "_cache_size", None)
        before = size_fn() if size_fn is not None else None
        t0 = time.monotonic()
        out = self._fn(*args, **kwargs)
        elapsed = time.monotonic() - t0
        fresh = (size_fn() > before if before is not None
                 else not self._ever_called)
        self._ever_called = True
        if fresh:
            label = self._label_fn(*args, **kwargs)
            _compile_telemetry().record_compile(label, elapsed)
            # Cost-ledger hook: host-side re-lower on abstract avals (the
            # jit dispatch cache is untouched), exception-isolated so
            # accounting can never break a solve.
            from cruise_control_tpu.obsvc.memory import memory_ledger
            memory_ledger().observe_compile(label, self._fn, args, kwargs)
        return out

    def __getattr__(self, name):
        return getattr(self._fn, name)


class GoalSolver:
    """Owns the per-goal jitted round functions; reused across optimizations
    with identical shapes (jit caches on (goal key, priors key, shapes))."""

    def __init__(self, max_candidates_per_round: int = 4096,
                 max_rounds_per_goal: int = 96,
                 # Swap pairs are C'xC'; 1024 measurably cuts rounds at north-star
                 # scale (NW-distribution 15->10, total 28s->25s at 1M
                 # replicas on CPU) for ~4 ms/round of extra tile cost.
                 max_swap_candidates: int = 1024,
                 mesh=None,
                 dst_jitter_frac: float = 1.0,
                 stall_limit: int = 8,
                 # Destination-axis tile for goals declaring dst_prune_score:
                 # the C×B pair matrices dominate solve cost once B is in the
                 # thousands, and band/count goals only ever send load to the
                 # top few hundred headroom brokers in one round.  0 disables.
                 max_dst_candidates: int = 1024,
                 # Rounds per segment for budgeted (anytime) solves: smaller
                 # segments = tighter deadline adherence, more host↔device
                 # round-trips.  Never affects budget-less solves.  None =
                 # the process default (solver.segment.rounds).
                 segment_rounds: Optional[int] = None):
        self.max_candidates = max_candidates_per_round
        self.max_rounds = max_rounds_per_goal
        self.max_swap_candidates = max_swap_candidates
        self.max_dst_candidates = max_dst_candidates
        self.segment_rounds = (segment_rounds if segment_rounds is not None
                               else _SEGMENT_ROUNDS_DEFAULT)
        # Soft-goal churn cutoff: stop a goal's while_loop after this many
        # consecutive rounds with neither a violation-count drop nor a
        # relative stats-metric improvement (>1e-4).
        self.stall_limit = stall_limit
        # Destination-jitter span as a fraction of each candidate's feasible
        # cost range.  1.0 maximizes batch width (fast convergence); 0.0 is
        # pure greedy argmin (narrow batches).  The measured trade-off is
        # asserted in tests/test_quality_breadth.py::test_jitter_frac_sweep.
        self.dst_jitter_frac = dst_jitter_frac
        # Optional jax.sharding.Mesh: inputs are committed with replica-axis
        # shardings (parallel/mesh.py) and GSPMD partitions every solve —
        # the multi-chip path (SURVEY §5).  None = single device.
        self.mesh = mesh
        self._round_cache = {}

    def shard_inputs(self, gctx: GoalContext, placement: Placement):
        """Commit (gctx, placement) to this solver's mesh (no-op without one).
        Call once per optimization; outputs stay sharded through the run."""
        if self.mesh is None:
            return gctx, placement
        from cruise_control_tpu.parallel import replica_shardings
        shardings = replica_shardings(self.mesh, (gctx, placement),
                                      gctx.state.num_replicas_padded)
        return jax.device_put((gctx, placement), shardings)

    def _width(self, goal: Goal, num_replicas_padded: int) -> int:
        # Narrowing hints (band-bounded goals) always win: scoring past the
        # band is wasted work.  WIDENING hints (rack) are honored only when
        # this goal's destination axis is actually tiled — the wide tile is
        # affordable only because the other axis shrank — and are bounded so
        # the pair-tile area stays within what the configured cap already
        # implies (cap² as the affordability proxy for cap×B); with pruning
        # disabled or a deliberately small operator cap, the hint never
        # exceeds the cap, so a memory-guard config keeps guarding.
        cap = self.max_candidates
        hint = getattr(goal, "candidate_width_hint", None)
        if hint is None:
            return min(cap, num_replicas_padded)
        if hint > cap:
            prunes = (self.max_dst_candidates > 0
                      and type(goal).dst_prune_score
                      is not Goal.dst_prune_score)
            if not prunes:
                hint = cap
            else:
                hint = min(hint, cap * max(1, cap // self.max_dst_candidates))
        return min(hint, num_replicas_padded)

    def _phases(self, goal: Goal, priors: Tuple[Goal, ...], c: int):
        """(kind, phase_fn) pairs in execution order."""
        phases = []
        if getattr(goal, "is_direct", False):
            def direct(gctx, placement, agg, ridx, force_exact=None,
                       _goal=goal):
                del ridx, force_exact
                new_pl = _goal.direct_apply(gctx, placement, agg)
                changed = jnp.sum((new_pl.is_leader != placement.is_leader)
                                  .astype(jnp.int32)) // 2
                return new_pl, compute_aggregates(gctx, new_pl), changed
            phases.append(("direct", direct))
        if goal.uses_leadership_moves:
            phases.append(("leadership", _leadership_phase(goal, priors, c)))
        if goal.uses_replica_moves:
            # Priors-aware receiver ranking when the goal offers it (the
            # prune is a heuristic ORDER, so priors only shape which
            # receivers make the tile — acceptance stays exact either way).
            prune_vs = getattr(goal, "dst_prune_score_vs", None)
            prune = (
                (lambda gctx, pl, ag, _f=prune_vs, _p=priors:
                 _f(gctx, pl, ag, _p))
                if prune_vs is not None else goal.dst_prune_score)
            phases.append(("move",
                           _replica_phase(goal, priors, c,
                                          goal.candidate_score, goal.self_ok,
                                          jitter_frac=self.dst_jitter_frac,
                                          prune_fn=prune,
                                          max_dst=self.max_dst_candidates)))
        if goal.has_pull_phase:
            # Pull destinations are the under-band brokers; the mask alone
            # does not shrink the C×B pair tile, so they prune too (by
            # deficit) — measured 147 -> ~60 ms/round at north-star scale.
            phases.append(("pull",
                           _replica_phase(goal, priors, c,
                                          goal.pull_candidate_score, goal.self_ok,
                                          dst_mask_fn=goal.pull_dst_mask,
                                          jitter_frac=self.dst_jitter_frac,
                                          prune_fn=goal.pull_dst_prune_score,
                                          max_dst=self.max_dst_candidates)))
        if goal.has_swap_phase:
            # Swap pairs are C×C; the tile stays modest (multi-swap keeps
            # whole sub-batches of it per round).
            phases.append(("swap",
                           _swap_phase(goal, priors,
                                       min(self.max_swap_candidates, c),
                                       jitter_frac=self.dst_jitter_frac)))
        if getattr(goal, "intra_disk", False):
            phases.append(("intra_disk", _intra_disk_phase(goal, c)))
        return phases

    def _phases_runner(self, goal: Goal, priors: Tuple[Goal, ...], c: int):
        """One round given CALLER-SUPPLIED aggregates; returns the updated
        aggregates so the solve loop can carry them across rounds."""
        phases = self._phases(goal, priors, c)

        def run(gctx: GoalContext, placement: Placement, agg: Aggregates,
                ridx, force_exact=None):
            applied = jnp.int32(0)
            # NOTE: all phases run every round, including swap.  Gating the
            # swap tile on "cheaper phases applied nothing" (the reference's
            # escalation order) was measured and REVERTED: swaps converge in
            # parallel with moves here — deferring them took the NW
            # distribution goals from 3-4 rounds to 8 at north-star scale.
            for _kind, phase in phases:
                placement, agg, n = phase(gctx, placement, agg, ridx,
                                          force_exact)
                applied = applied + n
            violated = jnp.sum(goal.violated_brokers(gctx, placement, agg)
                               .astype(jnp.int32))
            stranded = jnp.sum(currently_offline(gctx, placement).astype(jnp.int32))
            metric = goal.stats_metric(gctx, placement, agg)
            return placement, agg, applied, violated, stranded, metric

        return run

    def _round_body(self, goal: Goal, priors: Tuple[Goal, ...], c: int):
        runner = self._phases_runner(goal, priors, c)

        def round_body(gctx: GoalContext, placement: Placement, ridx,
                       force_exact=None):
            agg = compute_aggregates(gctx, placement)
            placement, _, applied, violated, stranded, metric = runner(
                gctx, placement, agg, ridx, force_exact)
            return placement, applied, violated, stranded, metric

        return round_body

    def _cached_executable(self, key, bucket: str, build, label_fn=None):
        """``_round_cache`` get-or-create with compilesvc telemetry: a hit is
        a found executable family, a miss builds one, and the returned proxy
        reports each actual XLA compile inside the family (per-shape
        retraces) under its bucket label."""
        cached = self._round_cache.get(key)
        tel = _compile_telemetry()
        if cached is not None:
            tel.record_hit(bucket)
            return cached
        tel.record_miss(bucket)
        fn = _CompileTracked(build(), label_fn or (lambda *a, **k: bucket))
        self._round_cache[key] = fn
        return fn

    def relax_cached(self, key, bucket: str, build, label_fn=None):
        """Cache slot for the convex-relaxation executables (analyzer/relax.py).

        Namespaced under ``("relax",) + key`` and bucketed with an ``-X``
        suffix so the fast path's cache keys and compilesvc buckets stay
        disjoint from the greedy family — with relaxation off, no key in this
        namespace is ever created (the bitwise fall-through guarantee)."""
        return self._cached_executable(("relax",) + tuple(key),
                                       bucket + "-X", build, label_fn)

    def _round_fn(self, goal: Goal, priors: Tuple[Goal, ...], num_replicas_padded: int):
        """One jitted solver round (kept for the driver's single-chip
        compile check and for round-granular tests)."""
        c = self._width(goal, num_replicas_padded)
        key = ("round", goal.key(), tuple(g.key() for g in priors), c)
        return self._cached_executable(
            key, f"R{num_replicas_padded}-C{c}",
            lambda: jax.jit(self._round_body(goal, priors, c)))

    def _solve_fn(self, goal: Goal, priors: Tuple[Goal, ...], num_replicas_padded: int):
        """The whole per-goal convergence loop as ONE jitted dispatch.

        The reference's ``while !finished`` loop (GoalOptimizer.java:437-462)
        is a single Java call; a host-side Python loop here would pay a
        dispatch+sync round-trip per round — fatal over a tunneled TPU
        backend.  ``lax.while_loop`` keeps every round on-device; the carry is
        (placement, rounds, applied_last, moves_total, violated, stranded,
        metric) and the condition mirrors the host loop exactly:
        work remains ∧ last round made progress ∧ round budget left.
        """
        c = self._width(goal, num_replicas_padded)
        # trace.solver.rounds joins BOTH the cache key and the bucket label:
        # the recording executable is a different program, and the default-off
        # key tuple stays byte-identical to a build without the recorder.
        rec = _RECORD_ROUNDS
        key = ("solve", goal.key(), tuple(g.key() for g in priors), c)
        bucket = f"R{num_replicas_padded}-C{c}"
        if rec:
            key = key + ("rounds",)
            bucket += "-T"
        return self._cached_executable(
            key, bucket,
            lambda: jax.jit(self._solve_body(goal, priors, c, record=rec)))

    # Aggregates carried across rounds are re-synced from a full O(R)
    # recompute every this-many rounds, bounding incremental scatter-drift
    # while saving the per-round recompute the phases' incremental updates
    # make redundant.
    AGG_RESYNC_ROUNDS = 4

    def _loop_pieces(self, goal: Goal, priors: Tuple[Goal, ...], c: int,
                     record: bool = False):
        """The convergence loop's cond/body as a per-trace factory, shared by
        the fused solve (:meth:`_solve_body`) and the segmented anytime solve
        (:meth:`_segment_fns`) so both paths run literally the same round
        math.  cond/body close over ``gctx``, so the factory is called inside
        each trace."""
        runner = self._phases_runner(goal, priors, c)
        max_rounds = jnp.int32(self.max_rounds)
        stall_limit = jnp.int32(self.stall_limit)
        resync = jnp.int32(self.AGG_RESYNC_ROUNDS)
        # Soft goals only: a hard goal must exhaust its round budget before
        # the hard-goal check declares failure, but a soft goal that keeps
        # applying moves without lowering its violation count or improving
        # its stats metric is just churning — cut the tail.
        use_stall_cutoff = not goal.is_hard

        def make(gctx: GoalContext):
            def cond(carry):
                (_, _, rounds, applied_last, _, violated, stranded, _,
                 _, _, stall) = carry[:11]
                work = (violated > 0) | (stranded > 0)
                progress = (rounds == 0) | (applied_last > 0)
                ok = work & progress & (rounds < max_rounds)
                if use_stall_cutoff:
                    ok = ok & (stall < stall_limit)
                return ok

            def body(carry):
                (pl, agg, rounds, _, moves, _, _, _, best_work, best_metric,
                 stall) = carry[:11]
                # Stalled soft-goal rounds retry with exact top-k so a
                # deterministic approx recall miss can't silently ride the
                # stall cutoff into an accepted residual (see _top_candidates).
                force = (stall > 0) if use_stall_cutoff else None
                # Periodic re-sync of the carried aggregates (every phase
                # keeps them incrementally exact up to float accumulation).
                resync_now = (rounds % resync == 0) & (rounds > 0)
                agg = jax.lax.cond(
                    resync_now,
                    lambda _pl, _ag: compute_aggregates(gctx, _pl),
                    lambda _pl, _ag: _ag,
                    pl, agg)
                pl, agg, applied, violated, stranded, metric = runner(
                    gctx, pl, agg, rounds, force)
                work_now = violated + stranded
                improved = ((work_now < best_work)
                            | (metric < best_metric
                               - 1e-4 * jnp.abs(best_metric) - 1e-12))
                stall = jnp.where(improved, jnp.int32(0), stall + 1)
                best_work = jnp.minimum(best_work, work_now)
                best_metric = jnp.minimum(best_metric, metric)
                out = (pl, agg, rounds + 1, applied, moves + applied,
                       violated, stranded, metric, best_work, best_metric,
                       stall)
                if record:
                    # One dynamic-index scatter per round into the
                    # preallocated stats buffer riding the carry.
                    row = jnp.stack([
                        applied.astype(jnp.float32),
                        violated.astype(jnp.float32),
                        stranded.astype(jnp.float32),
                        metric.astype(jnp.float32),
                        resync_now.astype(jnp.float32),
                        stall.astype(jnp.float32)])
                    out = out + (carry[11].at[rounds].set(row),)
                return out

            return cond, body

        return make

    @staticmethod
    def _loop_init(placement: Placement, agg0: Aggregates, violated0,
                   stranded0, metric0, buf_rounds: int, record: bool):
        """The while_loop's initial carry (shared fused/segmented)."""
        init = (placement, agg0, jnp.int32(0), jnp.int32(1), jnp.int32(0),
                violated0, stranded0, metric0,
                violated0 + stranded0, metric0, jnp.int32(0))
        if record:
            init = init + (jnp.zeros((buf_rounds, ROUND_STATS_COLS),
                                     jnp.float32),)
        return init

    def _solve_body(self, goal: Goal, priors: Tuple[Goal, ...], c: int,
                    record: bool = False):
        make = self._loop_pieces(goal, priors, c, record)
        buf_rounds = self.max_rounds

        def solve(gctx: GoalContext, placement: Placement, agg0: Aggregates):
            # agg0 is caller-supplied: between goals the placement does not
            # change, so goal N's fresh final recompute IS goal N+1's exact
            # starting aggregates — threading it saves one O(R) segment-sum
            # pass per goal in the stack.
            violated0 = jnp.sum(goal.violated_brokers(gctx, placement, agg0)
                                .astype(jnp.int32))
            stranded0 = jnp.sum(currently_offline(gctx, placement)
                                .astype(jnp.int32))
            metric0 = goal.stats_metric(gctx, placement, agg0)
            cond, body = make(gctx)
            init = self._loop_init(placement, agg0, violated0, stranded0,
                                   metric0, buf_rounds, record)
            final = jax.lax.while_loop(cond, body, init)
            # The RETURNED residuals are computed from one fresh recompute:
            # the in-loop values ride the carried aggregates (exact up to
            # float scatter-drift between resyncs — fine for driving the
            # loop, not for the hard-goal verdict / stats-comparator checks
            # the caller runs on these numbers).  Zero-round solves (already-
            # satisfied goals) skip the O(R) recompute: nothing moved, so the
            # entry aggregates and residuals are still exact — this keeps a
            # satisfied goal's solve at O(B) instead of O(R).
            return self._finalize_tail(goal, gctx, final, violated0,
                                       stranded0, metric0, record)

        return solve

    @staticmethod
    def _finalize_tail(goal: Goal, gctx: GoalContext, final, violated0,
                       stranded0, metric0, record: bool):
        """Fresh-residual tail shared by the fused solve and the segmented
        finalize executable (see the zero-round rationale above)."""
        pl, agg_c, rounds, _, moves = final[:5]

        def _fresh(pl):
            agg_f = compute_aggregates(gctx, pl)
            violated_f = jnp.sum(goal.violated_brokers(gctx, pl, agg_f)
                                 .astype(jnp.int32))
            stranded_f = jnp.sum(currently_offline(gctx, pl)
                                 .astype(jnp.int32))
            metric_f = goal.stats_metric(gctx, pl, agg_f)
            return agg_f, violated_f, stranded_f, metric_f

        agg_f, violated_f, stranded_f, metric_f = jax.lax.cond(
            rounds > 0, _fresh,
            lambda pl: (agg_c, violated0, stranded0, metric0), pl)
        out = (pl, agg_f, rounds, moves, violated_f, stranded_f, metric_f,
               violated0, metric0)
        if record:
            out = out + (final[11],)
        return out

    def _segment_fns(self, goal: Goal, priors: Tuple[Goal, ...],
                     num_replicas_padded: int):
        """(init, step, finalize) executables for the segmented anytime solve.

        The fused solve is one while_loop dispatch; a budgeted solve instead
        dispatches ``step`` repeatedly — the same cond/body (via
        :meth:`_loop_pieces`) bounded by a TRACED segment-end round, carry
        threaded through the host — and checks the budget between dispatches.
        Because the round math is identical and each segment resumes from the
        exact carry the fused loop would have had, running to convergence
        segmented is bitwise-equal to the fused solve on a deterministic
        backend.  ``seg_end`` is a traced int32 so one step executable serves
        every boundary.  The cache keys/bucket get a ``segment``/``-S``
        marker: budget-less solves never build these, keeping the default
        path's executables and cache keys byte-identical to pre-segmentation
        builds (same discipline as the PR 9 rounds recorder).
        """
        c = self._width(goal, num_replicas_padded)
        rec = _RECORD_ROUNDS
        base_key = ("segment", goal.key(), tuple(g.key() for g in priors), c)
        bucket = f"R{num_replicas_padded}-C{c}-S"
        if rec:
            base_key = base_key + ("rounds",)
            bucket += "-T"
        make = self._loop_pieces(goal, priors, c, rec)
        buf_rounds = self.max_rounds

        def build_init():
            def init_fn(gctx: GoalContext, placement: Placement,
                        agg0: Aggregates):
                violated0 = jnp.sum(
                    goal.violated_brokers(gctx, placement, agg0)
                    .astype(jnp.int32))
                stranded0 = jnp.sum(currently_offline(gctx, placement)
                                    .astype(jnp.int32))
                metric0 = goal.stats_metric(gctx, placement, agg0)
                carry = self._loop_init(placement, agg0, violated0, stranded0,
                                        metric0, buf_rounds, rec)
                return carry, violated0, stranded0, metric0
            return jax.jit(init_fn)

        def build_step():
            def step_fn(gctx: GoalContext, carry, seg_end):
                cond, body = make(gctx)

                def seg_cond(cr):
                    return cond(cr) & (cr[2] < seg_end)

                out = jax.lax.while_loop(seg_cond, body, carry)
                # done = the REAL loop condition is exhausted (converged /
                # round budget), not merely the segment boundary.
                return out, ~cond(out)
            return jax.jit(step_fn)

        def build_fin():
            def fin_fn(gctx: GoalContext, carry, violated0, stranded0,
                       metric0):
                return self._finalize_tail(goal, gctx, carry, violated0,
                                           stranded0, metric0, rec)
            return jax.jit(fin_fn)

        return (
            self._cached_executable(base_key + ("init",), bucket, build_init),
            self._cached_executable(base_key + ("step",), bucket, build_step),
            self._cached_executable(base_key + ("fin",), bucket, build_fin),
        )

    def _batch_solve_fn(self, goal: Goal, priors: Tuple[Goal, ...],
                        num_replicas_padded: int, num_candidates: int):
        """Vmapped per-goal solve over a SCENARIO axis (BASELINE config #5 /
        'jit once, vmap over scenarios', SURVEY §7).

        Each scenario supplies its own broker-liveness and exclusion masks
        (a remove-broker what-if kills different brokers); scenario-dependent
        context entries (host capacity) are recomputed in-trace so every
        lane's band/capacity math sees its own cluster.
        """
        c = min(num_candidates, num_replicas_padded)
        key = ("batch", goal.key(), tuple(g.key() for g in priors), c)

        def build():
            solve_body = self._solve_body(goal, priors, c)

            @jax.jit
            def batch(gctx: GoalContext, alive_s, excl_move_s, excl_lead_s,
                      placement_s):
                def one(alive, excl_move, excl_lead, placement):
                    state = gctx.state.replace(alive=alive)
                    ok = alive & state.broker_valid
                    host_cap = jax.ops.segment_sum(
                        jnp.where(ok[:, None], state.capacity, 0.0),
                        state.host, num_segments=gctx.num_hosts)
                    g2 = gctx.replace(
                        state=state, host_capacity=host_cap,
                        excluded_for_replica_move=excl_move,
                        excluded_for_leadership=excl_lead)
                    out = solve_body(g2, placement,
                                     compute_aggregates(g2, placement))
                    # Drop the final aggregates from the vmapped outputs: a
                    # [scenarios, topics, brokers] leader-count stack is hundreds
                    # of MB at north-star scale and no lane consumer wants it.
                    return (out[0],) + out[2:]
                return jax.vmap(one)(alive_s, excl_move_s, excl_lead_s,
                                     placement_s)
            return batch

        # Lane count is a shape, not part of the cache key — the proxy labels
        # each per-width compile with its own -L bucket.
        return self._cached_executable(
            key, f"R{num_replicas_padded}-C{c}", build,
            label_fn=lambda gctx, alive_s, *a, **k:
                f"R{num_replicas_padded}-C{c}-L{alive_s.shape[0]}")

    def optimize_goal(self, goal: Goal, priors: Sequence[Goal], gctx: GoalContext,
                      placement: Placement, agg: Optional[Aggregates] = None,
                      budget=None,
                      ) -> Tuple[Placement, Aggregates, GoalOptimizationInfo]:
        """Run rounds until converged (the reference's per-goal
        ``while !finished`` loop, GoalOptimizer.java:437-462) — one device
        dispatch and one host sync per goal.

        ``agg`` lets the caller thread one goal's exact final aggregates into
        the next goal's solve (the placement is unchanged in between); the
        returned aggregates are a fresh full recompute — or, for zero-round
        solves, the caller-supplied entry aggregates unchanged (exact either
        way, since nothing moved).

        ``budget`` (a :class:`~cruise_control_tpu.analyzer.budget.SolveBudget`
        with ``segmented`` set) routes the solve through the segmented
        anytime path; ``None`` (or a cancel-only budget) keeps the fused
        single-dispatch loop, byte-identical to a budget-less build."""
        if agg is None:
            agg = self.aggregates(gctx, placement)
        if budget is not None and budget.segmented:
            return self._optimize_goal_segmented(goal, tuple(priors), gctx,
                                                 placement, agg, budget)
        solve = self._solve_fn(goal, tuple(priors), gctx.state.num_replicas_padded)
        tr = _obsvc_tracer()
        if tr.enabled:
            # Fence the dispatch so device time lands on THIS span instead
            # of whichever later host sync happens to block: annotate the
            # XLA timeline for /profile captures, then block on the full
            # output pytree before reading the clock.
            t0 = time.monotonic()
            with jax.profiler.TraceAnnotation(f"cc.solve.{goal.name}"):
                out = jax.block_until_ready(solve(gctx, placement, agg))
            span = tr.current()
            if span is not None:
                span.add_ms("device_ms",
                            round((time.monotonic() - t0) * 1000.0, 3))
        else:
            out = solve(gctx, placement, agg)
        (placement, agg, rounds, moves, violated, stranded, metric, violated0,
         metric0) = out[:9]
        # With trace.solver.rounds on, the solve returned the round-stats
        # buffer as a tenth output; slice it to the rounds actually run.
        curve = None
        if len(out) > 9:
            curve = np.asarray(out[9])[:int(rounds)]
        info = GoalOptimizationInfo(
            goal_name=goal.name,
            rounds=int(rounds),
            moves_applied=int(moves),
            violated_brokers_before=int(violated0),
            violated_brokers_after=int(violated),
            stranded_after=int(stranded),
            metric_before=float(metric0),
            metric_after=float(metric) if int(rounds) > 0 else float(metric0),
            round_curve=curve,
        )
        return placement, agg, info

    def _optimize_goal_segmented(self, goal: Goal, priors: Tuple[Goal, ...],
                                 gctx: GoalContext, placement: Placement,
                                 agg: Aggregates, budget
                                 ) -> Tuple[Placement, Aggregates,
                                            GoalOptimizationInfo]:
        """Anytime convergence under a budget: dispatch fixed-round segments,
        checking the budget at every boundary.  On expiry/cancel the current
        carry is finalized as-is — every round's placement is feasible and
        prior-goal-safe (acceptance-checked moves only), so the partial
        result is always returnable."""
        init_fn, step_fn, fin_fn = self._segment_fns(
            goal, priors, gctx.state.num_replicas_padded)
        seg = max(1, int(self.segment_rounds))
        tr = _obsvc_tracer()
        carry, violated0, stranded0, metric0 = init_fn(gctx, placement, agg)
        stop = budget.stop_reason()
        seg_end, seg_idx = 0, 0
        done = False
        while stop is None and not done:
            seg_end = min(seg_end + seg, self.max_rounds)
            if tr.enabled:
                t0 = time.monotonic()
                with tr.span("solve.segment", goal=goal.name,
                             segment=seg_idx, seg_end=seg_end) as sp:
                    with jax.profiler.TraceAnnotation(
                            f"cc.solve.{goal.name}.seg{seg_idx}"):
                        carry, done_dev = jax.block_until_ready(
                            step_fn(gctx, carry, jnp.int32(seg_end)))
                    done = bool(done_dev)
                    sp.set("rounds", int(carry[2]))
                    sp.add_ms("device_ms",
                              round((time.monotonic() - t0) * 1000.0, 3))
            else:
                carry, done_dev = step_fn(gctx, carry, jnp.int32(seg_end))
                done = bool(done_dev)  # host sync per segment by design
            seg_idx += 1
            if not done:
                stop = budget.stop_reason()
        preempted = stop is not None and not done
        out = fin_fn(gctx, carry, violated0, stranded0, metric0)
        (placement, agg, rounds, moves, violated, stranded, metric,
         violated0, metric0) = out[:9]
        curve = None
        if len(out) > 9:
            curve = np.asarray(out[9])[:int(rounds)]
        info = GoalOptimizationInfo(
            goal_name=goal.name,
            rounds=int(rounds),
            moves_applied=int(moves),
            violated_brokers_before=int(violated0),
            violated_brokers_after=int(violated),
            stranded_after=int(stranded),
            metric_before=float(metric0),
            metric_after=float(metric) if int(rounds) > 0 else float(metric0),
            round_curve=curve,
            preempted=preempted,
            preempt_reason=stop if preempted else None,
        )
        return placement, agg, info

    def aggregates(self, gctx: GoalContext, placement: Placement) -> Aggregates:
        """Jitted full-aggregate recompute for host-side callers (the eager
        path runs the same segment-sums unfused — measurably slower at 1M
        replicas)."""
        if "aggregates" not in self._round_cache:
            self._round_cache["aggregates"] = jax.jit(compute_aggregates)
        return self._round_cache["aggregates"](gctx, placement)

    def violations(self, goals: Sequence[Goal], gctx: GoalContext,
                   placement: Placement, agg: Aggregates):
        """Per-goal violated-broker counts as ONE jitted dispatch (i32[G]).

        The optimizer needs the full stack's violation vector before and
        after a run (`violated_before`/`violated_after`, and the polish
        pass's re-violation scan); fusing the G checks avoids G eager
        multi-kernel passes over replica-sized arrays."""
        if not goals:
            return np.zeros(0, dtype=np.int32)
        key = ("violations", tuple(g.key() for g in goals))
        if key not in self._round_cache:
            gs = tuple(goals)

            def fn(gctx, placement, agg):
                return jnp.stack([
                    jnp.sum(g.violated_brokers(gctx, placement, agg)
                            .astype(jnp.int32)) for g in gs])

            self._round_cache[key] = jax.jit(fn)
        return np.asarray(self._round_cache[key](gctx, placement, agg))


_DEFAULT_SOLVER: Optional["GoalSolver"] = None


def default_solver() -> "GoalSolver":
    """Process-wide solver so jitted round functions are compiled once and
    shared across GoalOptimizer instances (shapes + goal keys cache-key them)."""
    global _DEFAULT_SOLVER
    if _DEFAULT_SOLVER is None:
        _DEFAULT_SOLVER = GoalSolver()
    return _DEFAULT_SOLVER


def check_hard_goal(goal: Goal, info: GoalOptimizationInfo,
                    stranded_offline: int) -> None:
    """Hard-goal failure aborts the optimization (reference:
    OptimizationFailureError thrown from goal.optimize)."""
    if goal.is_hard and info.violated_brokers_after > 0:
        raise OptimizationFailureError(
            f"[{goal.name}] Violated {info.violated_brokers_after} brokers remain "
            f"after {info.rounds} rounds / {info.moves_applied} moves.")
    if goal.is_hard and stranded_offline > 0:
        raise OptimizationFailureError(
            f"[{goal.name}] {stranded_offline} offline replicas could not be "
            "relocated to alive brokers.")
