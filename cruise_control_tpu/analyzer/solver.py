"""The batched greedy solver.

Replaces the reference's per-goal greedy search (``AbstractGoal.optimize``
:78-130 — ``while !finished: for broker: rebalanceForBroker`` with every
candidate action re-checked against all previously-optimized goals at
``AbstractGoal.maybeApplyBalancingAction`` :214-256).  The TPU formulation
batches the heavy part and keeps the sequential part cheap:

round (one jitted call per goal class)
 1. score all R replicas; ``lax.top_k`` picks ≤C candidates        (O(R))
 2. build the C×B feasibility mask: structural legitMove ∧ this
    goal's self-condition ∧ every prior goal's actionAcceptance    (O(C·B))
 3. per-candidate best destination by goal cost ``argmin``         (O(C·B))
 4. ``lax.scan`` over candidates in priority order: re-check the
    chosen move against the *updated* aggregates (the same predicate
    functions, now scalar) and apply it with O(1) scatter updates   (O(C))

Rounds repeat from the host until no move applies or the goal reports no
violated broker.  Sequential-greedy fidelity therefore holds at candidate
granularity — every applied move was valid at apply time, exactly like the
reference's immediate-mutation loop — while all O(R·B) scoring runs as one
fused XLA program per round.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from cruise_control_tpu.analyzer.context import (
    Aggregates,
    GoalContext,
    apply_intra_disk_move,
    apply_leadership_move,
    apply_replica_move,
    base_leadership_ok,
    base_replica_move_ok,
    compute_aggregates,
    currently_offline,
)
from cruise_control_tpu.analyzer.goals.base import Goal
from cruise_control_tpu.common.exceptions import OptimizationFailureError
from cruise_control_tpu.model.state import Placement

_SCORE_FLOOR = -1e29  # candidate scores below this are "not a candidate"
_INF_COST = jnp.float32(3.4e38)


@dataclass
class GoalOptimizationInfo:
    """Host-side result of optimizing one goal."""

    goal_name: str
    rounds: int = 0
    moves_applied: int = 0
    leadership_moves: int = 0
    violated_brokers_before: int = 0
    violated_brokers_after: int = 0
    metric_before: float = 0.0
    metric_after: float = 0.0

    @property
    def succeeded(self) -> bool:
        return self.violated_brokers_after == 0


def _chain_accept_replica(priors: Sequence[Goal]):
    def accept(gctx, placement, agg, r, dst):
        ok = base_replica_move_ok(gctx, placement, r, dst)
        for g in priors:
            ok = ok & g.accept_replica_move(gctx, placement, agg, r, dst)
        return ok
    return accept


def _chain_accept_leadership(priors: Sequence[Goal]):
    def accept(gctx, placement, agg, f):
        ok = base_leadership_ok(gctx, placement, f)
        for g in priors:
            ok = ok & g.accept_leadership_move(gctx, placement, agg, f)
        return ok
    return accept


def _pick_dst_disk(gctx: GoalContext, agg: Aggregates, dst):
    """Emptiest alive logdir of dst (disk chosen at move-apply time)."""
    frac = agg.disk_load[dst] / jnp.maximum(gctx.state.disk_capacity[dst], 1e-9)
    frac = jnp.where(gctx.state.disk_alive[dst], frac, jnp.inf)
    return jnp.argmin(frac, axis=-1)


def _replica_phase(goal: Goal, priors: Sequence[Goal], num_candidates: int,
                   score_fn: Callable, self_ok_fn: Callable,
                   dst_mask_fn: Optional[Callable] = None):
    """Build one replica-move phase function (gctx, placement, agg) ->
    (placement, agg, applied)."""
    accept = _chain_accept_replica(priors)

    def phase(gctx: GoalContext, placement: Placement, agg: Aggregates):
        b = gctx.state.num_brokers_padded
        score = score_fn(gctx, placement, agg)
        top_score, cand = jax.lax.top_k(score, num_candidates)
        is_cand = top_score > _SCORE_FLOOR

        r2 = cand[:, None]
        d2 = jnp.arange(b)[None, :]
        ok = accept(gctx, placement, agg, r2, d2)
        ok = ok & self_ok_fn(gctx, placement, agg, r2, d2)
        if dst_mask_fn is not None:
            ok = ok & dst_mask_fn(gctx, placement, agg)[None, :]
        cost = jnp.where(ok, goal.dst_cost(gctx, placement, agg, r2, d2), _INF_COST)
        best_dst = jnp.argmin(cost, axis=1).astype(jnp.int32)
        feasible = jnp.any(ok, axis=1) & is_cand

        def step(carry, i):
            placement, agg, n = carry
            r = cand[i]
            d = best_dst[i]
            ok_now = (feasible[i]
                      & accept(gctx, placement, agg, r, d)
                      & self_ok_fn(gctx, placement, agg, r, d))
            if dst_mask_fn is not None:
                # dst-mask is a round-level target set; no re-check needed
                # beyond the predicates (they see updated aggregates).
                pass

            def do(args):
                pl, ag = args
                return apply_replica_move(gctx, pl, ag, r, d,
                                          _pick_dst_disk(gctx, ag, d))

            placement, agg = jax.lax.cond(ok_now, do, lambda a: a, (placement, agg))
            return (placement, agg, n + ok_now.astype(jnp.int32)), None

        (placement, agg, applied), _ = jax.lax.scan(
            step, (placement, agg, jnp.int32(0)), jnp.arange(num_candidates))
        return placement, agg, applied

    return phase


def _leadership_phase(goal: Goal, priors: Sequence[Goal], num_candidates: int):
    accept = _chain_accept_leadership(priors)

    def phase(gctx: GoalContext, placement: Placement, agg: Aggregates):
        score = goal.leadership_candidate_score(gctx, placement, agg)
        top_score, cand = jax.lax.top_k(score, num_candidates)
        is_cand = top_score > _SCORE_FLOOR

        def step(carry, i):
            placement, agg, n = carry
            f = cand[i]
            ok_now = (is_cand[i]
                      & accept(gctx, placement, agg, f)
                      & goal.leadership_self_ok(gctx, placement, agg, f))

            def do(args):
                pl, ag = args
                return apply_leadership_move(gctx, pl, ag, f)

            placement, agg = jax.lax.cond(ok_now, do, lambda a: a, (placement, agg))
            return (placement, agg, n + ok_now.astype(jnp.int32)), None

        (placement, agg, applied), _ = jax.lax.scan(
            step, (placement, agg, jnp.int32(0)), jnp.arange(num_candidates))
        return placement, agg, applied

    return phase


def _intra_disk_phase(goal: Goal, num_candidates: int):
    def phase(gctx: GoalContext, placement: Placement, agg: Aggregates):
        d_n = gctx.state.num_disks_per_broker
        score = goal.disk_candidate_score(gctx, placement, agg)
        top_score, cand = jax.lax.top_k(score, num_candidates)
        is_cand = top_score > _SCORE_FLOOR

        r2 = cand[:, None]
        d2 = jnp.arange(d_n)[None, :]
        ok = goal.disk_move_ok(gctx, placement, agg, r2, d2)
        b2 = placement.broker[r2]
        frac = ((agg.disk_load[b2, d2] + gctx.state.leader_load[r2, 3])
                / jnp.maximum(gctx.state.disk_capacity[b2, d2], 1e-9))
        cost = jnp.where(ok, frac, _INF_COST)
        best = jnp.argmin(cost, axis=1).astype(jnp.int32)
        feasible = jnp.any(ok, axis=1) & is_cand

        def step(carry, i):
            placement, agg, n = carry
            r = cand[i]
            d = best[i]
            ok_now = feasible[i] & goal.disk_move_ok(gctx, placement, agg, r, d)

            def do(args):
                pl, ag = args
                return apply_intra_disk_move(gctx, pl, ag, r, d)

            placement, agg = jax.lax.cond(ok_now, do, lambda a: a, (placement, agg))
            return (placement, agg, n + ok_now.astype(jnp.int32)), None

        (placement, agg, applied), _ = jax.lax.scan(
            step, (placement, agg, jnp.int32(0)), jnp.arange(num_candidates))
        return placement, agg, applied

    return phase


class GoalSolver:
    """Owns the per-goal jitted round functions; reused across optimizations
    with identical shapes (jit caches on (goal key, priors key, shapes))."""

    def __init__(self, max_candidates_per_round: int = 1024,
                 max_rounds_per_goal: int = 64):
        self.max_candidates = max_candidates_per_round
        self.max_rounds = max_rounds_per_goal
        self._round_cache = {}

    def _round_fn(self, goal: Goal, priors: Tuple[Goal, ...], num_replicas_padded: int):
        c = min(self.max_candidates, num_replicas_padded)
        key = (goal.key(), tuple(g.key() for g in priors), c)
        if key in self._round_cache:
            return self._round_cache[key]

        phases = []
        if getattr(goal, "is_direct", False):
            def direct(gctx, placement, agg, _goal=goal):
                new_pl = _goal.direct_apply(gctx, placement, agg)
                changed = jnp.sum((new_pl.is_leader != placement.is_leader)
                                  .astype(jnp.int32)) // 2
                return new_pl, compute_aggregates(gctx, new_pl), changed
            phases.append(direct)
        if goal.uses_leadership_moves:
            phases.append(_leadership_phase(goal, priors, c))
        if goal.uses_replica_moves:
            phases.append(_replica_phase(goal, priors, c,
                                         goal.candidate_score, goal.self_ok))
        if goal.has_pull_phase:
            phases.append(_replica_phase(goal, priors, c,
                                         goal.pull_candidate_score, goal.self_ok,
                                         dst_mask_fn=goal.pull_dst_mask))
        if getattr(goal, "intra_disk", False):
            phases.append(_intra_disk_phase(goal, c))

        @jax.jit
        def round_fn(gctx: GoalContext, placement: Placement):
            agg = compute_aggregates(gctx, placement)
            applied = jnp.int32(0)
            for phase in phases:
                placement, agg, n = phase(gctx, placement, agg)
                applied = applied + n
            violated = jnp.sum(goal.violated_brokers(gctx, placement, agg)
                               .astype(jnp.int32))
            stranded = jnp.sum(currently_offline(gctx, placement).astype(jnp.int32))
            metric = goal.stats_metric(gctx, placement, agg)
            return placement, applied, violated, stranded, metric

        self._round_cache[key] = round_fn
        return round_fn

    def optimize_goal(self, goal: Goal, priors: Sequence[Goal], gctx: GoalContext,
                      placement: Placement) -> Tuple[Placement, GoalOptimizationInfo]:
        """Run rounds until converged (the reference's per-goal
        ``while !finished`` loop, GoalOptimizer.java:437-462)."""
        round_fn = self._round_fn(goal, tuple(priors), gctx.state.num_replicas_padded)
        info = GoalOptimizationInfo(goal_name=goal.name)

        agg0 = compute_aggregates(gctx, placement)
        info.violated_brokers_before = int(jnp.sum(
            goal.violated_brokers(gctx, placement, agg0)))
        info.metric_before = float(goal.stats_metric(gctx, placement, agg0))

        violated = info.violated_brokers_before
        stranded = 1  # force at least one round when offline replicas exist
        for _ in range(self.max_rounds):
            if violated == 0 and stranded == 0 and info.rounds > 0:
                break
            placement, applied, violated_d, stranded_d, metric_d = round_fn(
                gctx, placement)
            applied = int(applied)
            violated = int(violated_d)
            stranded = int(stranded_d)
            info.rounds += 1
            info.moves_applied += applied
            info.metric_after = float(metric_d)
            if applied == 0:
                break
        info.violated_brokers_after = violated
        return placement, info


_DEFAULT_SOLVER: Optional["GoalSolver"] = None


def default_solver() -> "GoalSolver":
    """Process-wide solver so jitted round functions are compiled once and
    shared across GoalOptimizer instances (shapes + goal keys cache-key them)."""
    global _DEFAULT_SOLVER
    if _DEFAULT_SOLVER is None:
        _DEFAULT_SOLVER = GoalSolver()
    return _DEFAULT_SOLVER


def check_hard_goal(goal: Goal, info: GoalOptimizationInfo,
                    stranded_offline: int) -> None:
    """Hard-goal failure aborts the optimization (reference:
    OptimizationFailureError thrown from goal.optimize)."""
    if goal.is_hard and info.violated_brokers_after > 0:
        raise OptimizationFailureError(
            f"[{goal.name}] Violated {info.violated_brokers_after} brokers remain "
            f"after {info.rounds} rounds / {info.moves_applied} moves.")
    if goal.is_hard and stranded_offline > 0:
        raise OptimizationFailureError(
            f"[{goal.name}] {stranded_offline} offline replicas could not be "
            "relocated to alive brokers.")
