"""Analyzer: goals, solver kernels, optimizer orchestration.

TPU-native replacement for the reference analyzer
(``analyzer/GoalOptimizer.java``, ``analyzer/goals/*``): goal semantics become
mask/cost kernels over the SoA cluster tensors, and the per-broker greedy
search becomes batched rounds of score → mask → argmin → scan-apply.
"""

from cruise_control_tpu.analyzer.constraint import BalancingConstraint
from cruise_control_tpu.analyzer.options import OptimizationOptions
from cruise_control_tpu.analyzer.optimizer import GoalOptimizer, OptimizerResult

__all__ = [
    "BalancingConstraint",
    "OptimizationOptions",
    "GoalOptimizer",
    "OptimizerResult",
]
