"""Goal SPI: each goal is a set of pure, broadcastable kernels.

Reference contract: ``analyzer/goals/Goal.java:39-156`` (optimize /
actionAcceptance / ClusterModelStatsComparator / isHardGoal) and the
``AbstractGoal.optimize`` template (AbstractGoal.java:78-130).  The object-
oriented template method becomes data: a goal supplies

- ``violated_brokers``            — which brokers still need work (bool[B]);
- ``candidate_score``             — which replicas to move, in what order (f32[R]);
- ``self_ok`` / ``dst_cost``      — per-(replica, destination) feasibility and
                                    preference, broadcastable to C×B;
- ``accept_replica_move`` / ``accept_leadership_move`` — the actionAcceptance
  veto this goal exercises over *later* goals' actions;
- ``stats_metric``                — scalar "lower is better" for the
                                    ClusterModelStatsComparator post-check.

All kernels take (gctx, placement, agg) plus broadcast index arguments, carry
no Python state, and are shape-polymorphic: the same function evaluates a
C×B feasibility matrix during batched scoring and a scalar re-check inside the
apply scan.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from cruise_control_tpu.analyzer.context import (
    Aggregates,
    GoalContext,
    currently_offline,
    replica_role_load,
)
from cruise_control_tpu.model.state import Placement

NEG_INF = -jnp.inf
# Offline replicas (dead broker / dead disk) are moved before anything else —
# the reference does this at the top of every goal's optimize().
OFFLINE_BONUS = 1e30


class Goal:
    """Base goal: permissive defaults; subclasses override what they constrain."""

    name: str = "Goal"
    is_hard: bool = False
    uses_replica_moves: bool = True
    uses_leadership_moves: bool = False
    has_pull_phase: bool = False
    has_swap_phase: bool = False
    # True when accept_replica_move depends on the SOURCE broker's state —
    # the solver then limits batches to one outbound move per source.
    src_sensitive_accept: bool = False
    # Multi-accept: True when this goal's band/capacity math is expressible
    # as CUMULATIVE per-broker slacks (dst/src_cumulative_slack below), so a
    # destination may absorb several candidates in ONE round as long as their
    # cumulative consumption fits the headroom.  False forces the solver back
    # to one-move-per-destination batches whenever this goal is in play.
    multi_accept_safe: bool = False
    # True when the goal constrains per-(topic, broker) counts — the solver
    # then keeps at most one move per (topic, destination) and (topic,
    # source) pair per round.
    needs_topic_group: bool = False
    # Multi-swap: True when this goal's swap acceptance composes over several
    # swaps per broker in one round — either the goal is swap-neutral
    # (counts/racks unchanged by an exchange) or it bounds the transferred
    # quantity via ``swap_cumulative_slack`` below.  False forces the swap
    # phase back to one-swap-per-broker whenever this goal is in play.
    multi_swap_safe: bool = False
    # True when multi-swap safety additionally needs at most ONE swap per
    # (topic, broker) touch per round (per-topic count/leader constraints).
    swap_topic_group: bool = False
    # Multi-leadership: True when this goal's leadership acceptance composes
    # over several promotions per broker in one round — neutral, or bounded
    # via ``leadership_cumulative_slack`` below.  False forces the leadership
    # phase back to one-promotion-per-gaining/losing-broker.
    multi_leadership_safe: bool = False
    # True when multi-leadership safety additionally needs at most ONE
    # promotion per (topic, broker) touch per round (per-topic LEADER-count
    # acceptance).  Distinct from needs_topic_group/swap_topic_group, which
    # protect replica-count acceptances that are leadership-neutral.
    leadership_topic_group: bool = False
    # True when this goal's accept_replica_move reads no destination
    # AGGREGATE state (partition-/source-local predicates only) — exempts it
    # from the trace-time dst-slack invariant check below.
    dst_slack_exempt: bool = False
    # Optional candidate-tile width for this goal's move phases.  Narrowing
    # hints always apply (band-bounded goals keep far fewer moves per round
    # than the default width, so a narrower tile cuts the dominant C×B
    # feasibility cost without costing rounds).  A hint ABOVE the solver's
    # configured cap is honored only when this goal also declares
    # ``dst_prune_score`` and destination tiling is enabled — the solver
    # bounds the widened pair-tile area to what the cap already implies
    # (GoalSolver._width).  None = solver default.
    candidate_width_hint: Optional[int] = None
    # Convex-relaxation fast path (analyzer/relax.py): True when this goal's
    # objective lowers to a single scalar channel per broker — a per-replica
    # weight plus a per-broker target — so the fractional mass solve + wave
    # rounding can warm-start the greedy kernel.  Eligible goals implement
    # ``relax_weights``/``relax_channel`` below.  False (the default) means
    # the goal always takes the greedy path, bit-for-bit.
    relax_eligible: bool = False

    def key(self) -> str:
        """Jit-cache key; goals with numeric config should include it here."""
        return self.name

    # ----------------------------------------------------- convex relaxation

    def relax_weights(self, gctx: GoalContext,
                      placement: Placement) -> jnp.ndarray:
        """f32[R]: each replica's mass in this goal's relaxation channel
        (resource load, 1.0 for counts, is_leader for leader counts).  Only
        called for ``relax_eligible`` goals."""
        raise NotImplementedError(f"{self.name} is not relax-eligible")

    def relax_channel(self, gctx: GoalContext, agg: Aggregates):
        """(load f32[B], target f32[B], scale f32[B]): the per-broker channel
        the fractional solve balances — current channel load, the band
        center each broker should sit at, and the normalization the squared
        residual divides by (capacity for resource goals, 1.0 for counts).
        Only called for ``relax_eligible`` goals."""
        raise NotImplementedError(f"{self.name} is not relax-eligible")

    # ---------------------------------------------------------------- rounds

    def violated_brokers(self, gctx: GoalContext, placement: Placement,
                         agg: Aggregates) -> jnp.ndarray:
        return jnp.zeros(gctx.state.num_brokers_padded, dtype=bool)

    def candidate_score(self, gctx: GoalContext, placement: Placement,
                        agg: Aggregates) -> jnp.ndarray:
        """f32[R]: -inf = not a candidate; higher = move first."""
        return self.score_on_violated(gctx, placement, agg,
                                      self.replica_priority(gctx, placement, agg))

    def replica_priority(self, gctx: GoalContext, placement: Placement,
                         agg: Aggregates) -> jnp.ndarray:
        """Default ordering: heaviest replicas first (total effective load)."""
        load = jnp.where(placement.is_leader[:, None],
                         gctx.state.leader_load, gctx.state.follower_load)
        return jnp.sum(load / jnp.maximum(jnp.mean(
            gctx.state.capacity, axis=0, keepdims=True), 1e-9), axis=-1)

    def score_on_violated(self, gctx: GoalContext, placement: Placement,
                          agg: Aggregates, priority: jnp.ndarray) -> jnp.ndarray:
        """Candidates = valid replicas on violated brokers, plus offline
        replicas (with a bonus so they are handled first)."""
        state = gctx.state
        vb = self.violated_brokers(gctx, placement, agg)
        on_violated = vb[placement.broker] & state.valid & ~gctx.replica_excluded
        score = jnp.where(on_violated, priority, NEG_INF)
        offline = currently_offline(gctx, placement)
        return jnp.where(offline, priority + OFFLINE_BONUS, score)

    # ------------------------------------------------- replica-move kernels

    def self_ok(self, gctx: GoalContext, placement: Placement, agg: Aggregates,
                r, dst):
        """Would moving replica r to dst satisfy/improve THIS goal."""
        return jnp.broadcast_to(jnp.asarray(True), jnp.broadcast_shapes(
            jnp.shape(r), jnp.shape(dst)))

    def dst_cost(self, gctx: GoalContext, placement: Placement, agg: Aggregates,
                 r, dst):
        """Lower = preferred destination. Default: emptiest broker after move."""
        load = replica_role_load(gctx, placement, r)
        after = agg.broker_load[dst] + load
        frac = after / jnp.maximum(gctx.state.capacity[dst], 1e-9)
        return jnp.sum(frac, axis=-1)

    def dst_prune_score(self, gctx: GoalContext, placement: Placement,
                        agg: Aggregates):
        """Optional f32[B], higher = more attractive destination.

        Declaring it lets the solver restrict this goal's move-phase pair
        tile to the top-D brokers (rack-stratified, solver
        ``max_dst_candidates``) instead of all B — the C×B matrices are the
        dominant solve cost at north-star scale.  Pruning is a per-round
        heuristic, not a constraint: anything missed is re-scored against
        fresh aggregates next round, and the stall/polish safety nets catch
        residuals.  None (default) = scan every broker."""
        return None

    def accept_replica_move(self, gctx: GoalContext, placement: Placement,
                            agg: Aggregates, r, dst):
        """actionAcceptance for later goals' replica moves (True = ACCEPT)."""
        return jnp.broadcast_to(jnp.asarray(True), jnp.broadcast_shapes(
            jnp.shape(r), jnp.shape(dst)))

    # -------------------------------------------------- leadership kernels

    def leadership_candidate_score(self, gctx: GoalContext, placement: Placement,
                                   agg: Aggregates) -> jnp.ndarray:
        """f32[R] over FOLLOWER replicas: promote which, in what order."""
        return jnp.full(gctx.state.num_replicas_padded, NEG_INF)

    def leadership_self_ok(self, gctx: GoalContext, placement: Placement,
                           agg: Aggregates, f):
        return jnp.broadcast_to(jnp.asarray(True), jnp.shape(f))

    def accept_leadership_move(self, gctx: GoalContext, placement: Placement,
                               agg: Aggregates, f):
        """actionAcceptance for later goals' leadership promotions."""
        return jnp.broadcast_to(jnp.asarray(True), jnp.shape(f))

    # --------------------------------------------------- multi-accept slack

    def dst_cumulative_slack(self, gctx: GoalContext, placement: Placement,
                             agg: Aggregates, cand_load, is_lead_cand):
        """Optional (weight f32[C], slack f32[B]) arrival-side constraint:
        the cumulative ``weight`` of candidates accepted by a destination in
        one round must stay within ``slack[dst]``.  None = unconstrained.
        ``cand_load`` is the candidates' role load f32[C,4]."""
        return None

    def src_cumulative_slack(self, gctx: GoalContext, placement: Placement,
                             agg: Aggregates, cand_load, is_lead_cand):
        """Departure-side analog: cumulative weight leaving one source."""
        return None

    # ----------------------------------------------------------------- swap
    # The reference's third rebalancing mechanism
    # (ResourceDistributionGoal.java:543-725 rebalanceBySwappingLoadOut/In):
    # exchange a heavy replica on a loaded broker with a light replica on a
    # less-loaded one, transferring the load *difference* without changing
    # replica counts — the only mechanism that works when no broker has
    # one-way headroom.  Batched form: top-k out-candidates × top-k
    # in-candidates, a C×C pair-feasibility matrix, conflict-free selection.

    def swap_out_score(self, gctx: GoalContext, placement: Placement,
                       agg: Aggregates, salt) -> jnp.ndarray:
        """f32[R]: -inf = not a swap-out candidate; higher = try first.
        ``salt`` (round index) reseeds any randomized interleave so a draw
        is never frozen across rounds."""
        return jnp.full(gctx.state.num_replicas_padded, NEG_INF)

    def swap_in_score(self, gctx: GoalContext, placement: Placement,
                      agg: Aggregates, salt) -> jnp.ndarray:
        """f32[R]: -inf = not a swap-in candidate; higher = try first."""
        return jnp.full(gctx.state.num_replicas_padded, NEG_INF)

    def swap_ok(self, gctx: GoalContext, placement: Placement, agg: Aggregates,
                r_out, r_in):
        """Would swapping r_out ↔ r_in satisfy/improve THIS goal (pairwise)."""
        return jnp.broadcast_to(jnp.asarray(False), jnp.broadcast_shapes(
            jnp.shape(r_out), jnp.shape(r_in)))

    def swap_cost(self, gctx: GoalContext, placement: Placement, agg: Aggregates,
                  r_out, r_in):
        """Lower = preferred pair (default: residual imbalance after swap)."""
        return jnp.zeros(jnp.broadcast_shapes(jnp.shape(r_out), jnp.shape(r_in)),
                         dtype=jnp.float32)

    def accept_swap(self, gctx: GoalContext, placement: Placement,
                    agg: Aggregates, r_out, r_in, b_out, b_in):
        """actionAcceptance for later goals' SWAP actions.  Default: accept
        iff both directional moves are individually acceptable (conservative —
        each direction is checked against pre-swap aggregates, so the vacated
        headroom is not credited)."""
        return (self.accept_replica_move(gctx, placement, agg, r_out, b_in)
                & self.accept_replica_move(gctx, placement, agg, r_in, b_out))

    def swap_cumulative_slack(self, gctx: GoalContext, placement: Placement,
                              agg: Aggregates, d_load, d_pot, d_lbi, d_lead):
        """Optional (delta f32[C], upper_slack f32[B], lower_slack f32[B]|None):
        cumulative bound on the quantity each selected swap pair transfers
        b_out → b_in.  The solver enforces per round, per receiving broker:
        summed positive deltas fit ``upper_slack`` and summed negative deltas
        fit ``lower_slack``; mirrored on the shedding side.  ``d_load`` is the
        pairs' role-load delta f32[C,4]; ``d_pot``/``d_lbi``/``d_lead`` the potential-NW-out /
        leader-bytes-in / leader-count deltas f32[C].  None = swap-neutral."""
        return None

    def swap_host_cumulative_slack(self, gctx: GoalContext, placement: Placement,
                                   agg: Aggregates, d_load):
        """(delta f32[C], upper_slack f32[H]) host-scoped analog (upper bound
        only; same-host swaps are zero-weighted by the solver).  None = no
        host-level constraint."""
        return None

    # ------------------------------------------- multi-leadership composition

    def leadership_cumulative_slack(self, gctx: GoalContext, placement: Placement,
                                    agg: Aggregates, f, old):
        """Optional (delta_gain f32[C], delta_lose f32[C], up_slack f32[B],
        low_slack f32[B]|None, up_host f32[H]|None): cumulative bound on what
        each kept promotion adds to the promoted replica f's broker
        (``delta_gain``, usually > 0) and to the demoted leader ``old``'s
        broker (``delta_lose``, usually < 0).  The solver checks both brokers'
        summed positive deltas against ``up_slack`` (and, when given, their
        hosts against ``up_host``) and summed negative deltas against
        ``low_slack``.  None = leadership-neutral."""
        return None

    # ------------------------------------------------------ pull (move-in)

    def pull_dst_prune_score(self, gctx: GoalContext, placement: Placement,
                             agg: Aggregates):
        """Optional f32[B] for tiling the PULL phase's destination axis
        (same contract as dst_prune_score): higher = needier receiver.
        None (default) = scan every broker."""
        return None

    def pull_dst_mask(self, gctx: GoalContext, placement: Placement,
                      agg: Aggregates) -> jnp.ndarray:
        """bool[B]: brokers that need load moved IN (e.g. empty new brokers)."""
        return jnp.zeros(gctx.state.num_brokers_padded, dtype=bool)

    def pull_candidate_score(self, gctx: GoalContext, placement: Placement,
                             agg: Aggregates) -> jnp.ndarray:
        return jnp.full(gctx.state.num_replicas_padded, NEG_INF)

    # ------------------------------------------------------------- metrics

    def stats_metric(self, gctx: GoalContext, placement: Placement,
                     agg: Aggregates):
        """Scalar, lower = better (ClusterModelStatsComparator equivalent)."""
        return jnp.sum(self.violated_brokers(gctx, placement, agg).astype(jnp.float32))

    def __repr__(self) -> str:
        return f"<{self.name} hard={self.is_hard}>"


def alive_mask(gctx: GoalContext) -> jnp.ndarray:
    return gctx.state.alive & gctx.state.broker_valid


def broker_util(gctx: GoalContext, agg: Aggregates, resource: int) -> jnp.ndarray:
    """f32[B]: absolute load for one resource (capacity-relative forms divide)."""
    return agg.broker_load[:, resource]


def avg_alive_util_fraction(gctx: GoalContext, agg: Aggregates, resource: int):
    alive = alive_mask(gctx)
    total = jnp.sum(jnp.where(alive, agg.broker_load[:, resource], 0.0))
    cap = jnp.sum(jnp.where(alive, gctx.state.capacity[:, resource], 0.0))
    return total / jnp.maximum(cap, 1e-9)
