"""Intra-broker disk balance (soft).

Reference: ``analyzer/goals/IntraBrokerDiskUsageDistributionGoal.java`` —
keep each JBOD broker's logdirs within a band around the broker's own mean
disk utilization, via intra-broker replica moves (``alterReplicaLogDirs`` at
execution time).
"""

from __future__ import annotations

import jax.numpy as jnp

from cruise_control_tpu.analyzer.context import Aggregates, GoalContext
from cruise_control_tpu.analyzer.goals.base import Goal, NEG_INF
from cruise_control_tpu.common.resources import Resource
from cruise_control_tpu.model.state import Placement


class IntraBrokerDiskUsageDistributionGoal(Goal):
    name = "IntraBrokerDiskUsageDistributionGoal"
    is_hard = False
    uses_replica_moves = False
    intra_disk = True
    # Inter-broker swaps land on each side's emptiest logdir; the solver's
    # JBOD fill guard bounds multi-swap arrivals per logdir.
    multi_swap_safe = True
    multi_leadership_safe = True   # leadership does not move data between disks

    def _bands(self, gctx, agg):
        """(upper f32[B,D], lower f32[B,D]) absolute per-disk load bounds."""
        cap = gctx.state.disk_capacity
        alive = gctx.state.disk_alive
        total = jnp.sum(jnp.where(alive, agg.disk_load, 0.0), axis=1, keepdims=True)
        tcap = jnp.sum(jnp.where(alive, cap, 0.0), axis=1, keepdims=True)
        avg_frac = total / jnp.maximum(tcap, 1e-9)            # [B,1]
        t = gctx.balance_threshold[Resource.DISK]
        upper = avg_frac * t * cap
        lower = avg_frac * (2.0 - t) * cap
        return upper, lower

    def violated_disks(self, gctx, placement, agg):
        upper, lower = self._bands(gctx, agg)
        alive = gctx.state.disk_alive
        multi = jnp.sum(alive.astype(jnp.int32), axis=1, keepdims=True) > 1
        out = (agg.disk_load > upper) | (agg.disk_load < lower)
        return out & alive & multi

    def violated_brokers(self, gctx, placement, agg):
        return jnp.any(self.violated_disks(gctx, placement, agg), axis=-1)

    def disk_candidate_score(self, gctx, placement, agg):
        state = gctx.state
        upper, _ = self._bands(gctx, agg)
        over = (agg.disk_load > upper) & state.disk_alive
        on_over = over[placement.broker, placement.disk]
        dead = ~state.disk_alive[placement.broker, placement.disk]
        size = state.leader_load[:, Resource.DISK]
        cand = (on_over | dead) & state.valid
        return jnp.where(cand, size, NEG_INF)

    def disk_move_ok(self, gctx, placement, agg, r, d):
        upper, lower = self._bands(gctx, agg)
        b = placement.broker[jnp.asarray(r)]
        size = gctx.state.leader_load[jnp.asarray(r), Resource.DISK]
        src_d = placement.disk[jnp.asarray(r)]
        dst_after = agg.disk_load[b, d] + size
        src_after = agg.disk_load[b, src_d] - size
        ok = ((dst_after <= upper[b, d]) & (src_after >= lower[b, src_d])
              & gctx.state.disk_alive[b, d] & (d != src_d))
        dead_src = ~gctx.state.disk_alive[b, src_d]
        return jnp.where(dead_src, gctx.state.disk_alive[b, d] & (d != src_d), ok)

    def stats_metric(self, gctx, placement, agg):
        """Mean per-broker stdev of disk utilization fractions."""
        cap = jnp.maximum(gctx.state.disk_capacity, 1e-9)
        frac = agg.disk_load / cap
        alive = gctx.state.disk_alive
        n = jnp.maximum(jnp.sum(alive, axis=1), 1)
        mean = jnp.sum(jnp.where(alive, frac, 0.0), axis=1) / n
        var = jnp.sum(jnp.where(alive, (frac - mean[:, None]) ** 2, 0.0), axis=1) / n
        return jnp.mean(jnp.sqrt(var))
