"""Leadership-centric goals.

Reference: ``analyzer/goals/PreferredLeaderElectionGoal.java:35-208`` (move
leadership to the first eligible replica in each partition's replica list —
used by broker demotion) and ``MinTopicLeadersPerBrokerGoal.java`` (each
alive broker must lead at least N partitions of configured topics).
"""

from __future__ import annotations

import jax.numpy as jnp

from cruise_control_tpu.analyzer.context import (
    Aggregates,
    GoalContext,
    current_leader_of,
    currently_offline,
)
from cruise_control_tpu.analyzer.goals.base import Goal, NEG_INF, alive_mask
from cruise_control_tpu.model.state import Placement

# Plain int: a module-level jnp scalar would initialize the JAX backend
# at IMPORT time, before callers can force the CPU platform.
_BIG = 1 << 30


class PreferredLeaderElectionGoal(Goal):
    """Direct transform, not a search: for every partition, leadership goes to
    the lowest-position eligible replica (alive broker, not offline, broker
    not excluded from leadership)."""

    name = "PreferredLeaderElectionGoal"
    multi_accept_safe = True
    multi_swap_safe = True     # swaps keep per-replica roles; PLE unaffected
    multi_leadership_safe = True   # PLE never vetoes (permissive accepts)
    is_hard = False
    is_direct = True
    uses_replica_moves = False

    def _preferred(self, gctx: GoalContext, placement: Placement):
        """Per partition: (chosen replica row, any eligible?, real partition?)."""
        state = gctx.state
        sibs = gctx.partition_replicas                       # [P, RF]
        safe = jnp.maximum(sibs, 0)
        sib_b = placement.broker[safe]
        off = currently_offline(gctx, placement)
        eligible = ((sibs >= 0) & state.valid[safe] & ~off[safe]
                    & state.alive[sib_b] & ~gctx.excluded_for_leadership[sib_b]
                    & ~gctx.replica_excluded[safe])
        key = jnp.where(eligible, state.pos[safe], _BIG)     # [P, RF]
        choice_slot = jnp.argmin(key, axis=-1)               # [P]
        any_ok = jnp.any(eligible, axis=-1)
        chosen = jnp.take_along_axis(safe, choice_slot[:, None], axis=1)[:, 0]
        real_p = jnp.any(sibs >= 0, axis=-1)
        return chosen, any_ok, real_p

    def direct_apply(self, gctx: GoalContext, placement: Placement,
                     agg: Aggregates) -> Placement:
        chosen, any_ok, real_p = self._preferred(gctx, placement)

        # Keep the current leader where no replica is eligible.
        cur_leader = _current_leaders(gctx, placement)        # i32[P]
        final = jnp.where(any_ok, chosen, jnp.maximum(cur_leader, 0))
        has_any = any_ok | (cur_leader >= 0)
        # Padded partitions (all sibs -1) map to replica 0 — mask them out.
        is_leader = jnp.zeros_like(placement.is_leader).at[final].max(has_any & real_p)
        return placement.replace(is_leader=is_leader)

    def violated_brokers(self, gctx, placement, agg):
        """A broker is violated while it leads a partition whose preferred
        (lowest-position eligible) replica lives elsewhere — meaningful so the
        solver's nothing-to-do early exit and convergence check both work
        (round-1 regression: constant-False made direct_apply unreachable)."""
        chosen, any_ok, real_p = self._preferred(gctx, placement)
        cur = _current_leaders(gctx, placement)               # i32[P]
        wrong = real_p & any_ok & (chosen != cur)             # covers cur == -1
        holder = jnp.where(cur >= 0, placement.broker[jnp.maximum(cur, 0)],
                           placement.broker[chosen])
        out = jnp.zeros(gctx.state.num_brokers_padded, dtype=bool)
        return out.at[holder].max(wrong)


def _current_leaders(gctx: GoalContext, placement: Placement) -> jnp.ndarray:
    """i32[P]: current leader replica row per partition (-1 if none)."""
    sibs = gctx.partition_replicas
    safe = jnp.maximum(sibs, 0)
    is_l = (sibs >= 0) & placement.is_leader[safe]
    slot = jnp.argmax(is_l, axis=-1)
    got = jnp.take_along_axis(safe, slot[:, None], axis=1)[:, 0]
    return jnp.where(jnp.any(is_l, axis=-1), got, -1)


class MinTopicLeadersPerBrokerGoal(Goal):
    """Each alive broker leads ≥ N partitions of each configured topic
    (MinTopicLeadersPerBrokerGoal.java).  No configured topics → no-op.

    Two mechanisms, like the reference: promote an existing follower on a
    deficit broker (``MinTopicLeadersPerBrokerGoal.java:333``,
    LEADERSHIP_MOVEMENT), and — when the deficit broker holds no promotable
    follower at all (e.g. an empty broker) — move a surplus broker's leader
    replica onto it (``:360,430``, INTER_BROKER_REPLICA_MOVEMENT)."""

    name = "MinTopicLeadersPerBrokerGoal"
    is_hard = True
    src_sensitive_accept = True
    # Acceptance reads only per-(topic, source) leader counts; one move per
    # (topic, broker) pair per round keeps each delta within the -1 that the
    # pairwise acceptance already checked.
    multi_accept_safe = True
    needs_topic_group = True
    # One swap per (topic, broker) touch per round keeps each per-topic
    # leader-count delta within the -1 each pairwise acceptance checked.
    multi_swap_safe = True
    swap_topic_group = True
    # Same argument for batched leadership promotions: acceptance and
    # self-checks read only per-(topic, broker) leader counts, and the
    # (topic, broker) single-touch rule in the multi-leadership path caps
    # every pair's per-round delta at the ±1 those predicates evaluated.
    multi_leadership_safe = True
    leadership_topic_group = True
    uses_replica_moves = True
    uses_leadership_moves = True

    def _deficit(self, gctx, agg):
        """i32[T, B]: missing leaders per (relevant topic, alive broker)."""
        need = jnp.where(gctx.min_leader_topic_mask[:, None], gctx.min_topic_leaders, 0)
        deficit = jnp.maximum(need - agg.topic_leader_counts, 0)
        return jnp.where(alive_mask(gctx)[None, :], deficit, 0)

    def violated_brokers(self, gctx, placement, agg):
        return jnp.any(self._deficit(gctx, agg) > 0, axis=0)

    def leadership_candidate_score(self, gctx, placement, agg):
        """Promote followers of relevant topics sitting on deficit brokers,
        when the current leader's broker has surplus."""
        state = gctx.state
        deficit = self._deficit(gctx, agg)
        f = jnp.arange(state.num_replicas_padded)
        t = state.topic
        b = placement.broker
        my_deficit = deficit[t, b] > 0
        lead = current_leader_of(gctx, placement, state.partition[f])
        lb = placement.broker[jnp.maximum(lead, 0)]
        donor_ok = (lead >= 0) & (
            (agg.topic_leader_counts[t, lb] - 1 >= gctx.min_topic_leaders)
            | ~gctx.min_leader_topic_mask[t])
        cand = (my_deficit & donor_ok & ~placement.is_leader & state.valid
                & ~currently_offline(gctx, placement) & ~gctx.replica_excluded
                & gctx.min_leader_topic_mask[t])
        return jnp.where(cand, deficit[t, b].astype(jnp.float32), NEG_INF)

    def leadership_self_ok(self, gctx, placement, agg, f):
        f = jnp.asarray(f)
        t = gctx.state.topic[f]
        b = placement.broker[f]
        return self._deficit(gctx, agg)[t, b] > 0

    def candidate_score(self, gctx, placement, agg):
        """Leader replicas of relevant topics on surplus brokers, when their
        topic still has a deficit broker somewhere — the replica-movement
        fallback for deficit brokers no promotion can reach."""
        state = gctx.state
        deficit = self._deficit(gctx, agg)                    # i32[T, B]
        topic_needs = jnp.any(deficit > 0, axis=1)            # bool[T]
        t = state.topic
        src = placement.broker
        surplus = (agg.topic_leader_counts[t, src]
                   - gctx.min_topic_leaders)                  # i32[R]
        cand = (placement.is_leader & state.valid & ~gctx.replica_excluded
                & ~currently_offline(gctx, placement)
                & gctx.min_leader_topic_mask[t] & topic_needs[t]
                & (surplus > 0))
        # Richest sources shed first (most headroom above the minimum).
        return jnp.where(cand, surplus.astype(jnp.float32), NEG_INF)

    def self_ok(self, gctx, placement, agg, r, dst):
        r = jnp.asarray(r)
        t = gctx.state.topic[r]
        src = placement.broker[r]
        deficit = self._deficit(gctx, agg)
        donor_ok = (agg.topic_leader_counts[t, src] - 1
                    >= gctx.min_topic_leaders)
        return (deficit[t, jnp.asarray(dst)] > 0) & donor_ok

    def dst_cost(self, gctx, placement, agg, r, dst):
        """Deepest deficit first; the default load tiebreak would spread a
        topic's spare leaders to already-satisfied brokers."""
        r = jnp.asarray(r)
        t = gctx.state.topic[r]
        return -self._deficit(gctx, agg)[t, jnp.asarray(dst)].astype(jnp.float32)

    def accept_leadership_move(self, gctx, placement, agg, f):
        """Later goals may not demote a leader off a broker already at minimum."""
        f = jnp.asarray(f)
        t = gctx.state.topic[f]
        lead = current_leader_of(gctx, placement, gctx.state.partition[f])
        lb = placement.broker[jnp.maximum(lead, 0)]
        relevant = gctx.min_leader_topic_mask[t] & (lead >= 0)
        donor_ok = agg.topic_leader_counts[t, lb] - 1 >= gctx.min_topic_leaders
        return ~relevant | donor_ok

    def accept_replica_move(self, gctx, placement, agg, r, dst):
        """Moving a relevant-topic leader off a broker at minimum is vetoed."""
        r = jnp.asarray(r)
        t = gctx.state.topic[r]
        src = placement.broker[r]
        relevant = gctx.min_leader_topic_mask[t] & placement.is_leader[r]
        src_ok = (agg.topic_leader_counts[t, src] - 1 >= gctx.min_topic_leaders)
        return ~relevant | src_ok | ~gctx.state.alive[src]

    def stats_metric(self, gctx, placement, agg):
        return jnp.sum(self._deficit(gctx, agg)).astype(jnp.float32)
