"""Goal implementations.

Each reference goal class (``analyzer/goals/*.java``) maps to one Goal object
here exposing mask/cost kernels instead of a per-broker greedy loop; the
solver (``analyzer/solver.py``) provides the shared search skeleton the way
``AbstractGoal.optimize`` does for the reference.
"""

from cruise_control_tpu.analyzer.goals.base import Goal
from cruise_control_tpu.analyzer.goals.registry import (
    DEFAULT_GOALS,
    DEFAULT_HARD_GOALS,
    DEFAULT_ANOMALY_DETECTION_GOALS,
    get_goals_by_priority,
    goal_by_name,
)

__all__ = [
    "Goal",
    "DEFAULT_GOALS",
    "DEFAULT_HARD_GOALS",
    "DEFAULT_ANOMALY_DETECTION_GOALS",
    "get_goals_by_priority",
    "goal_by_name",
]
