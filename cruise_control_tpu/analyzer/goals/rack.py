"""Rack-awareness goals.

Reference: ``analyzer/goals/RackAwareGoal.java:31-221`` (strict: no two
replicas of a partition on one rack), ``RackAwareDistributionGoal.java``
(relaxed: replicas spread as evenly as possible, >1 per rack allowed when
replicas > racks), base ``AbstractRackAwareGoal.java``.

All checks reduce to RF-wide gathers over ``partition_replicas``: a replica's
sibling racks are ``rack[broker[sibs]]`` — never a P×B or P×K materialization
inside the move loop.
"""

from __future__ import annotations

import jax.numpy as jnp

from cruise_control_tpu.analyzer.context import Aggregates, GoalContext, currently_offline
from cruise_control_tpu.analyzer.goals.base import Goal, alive_mask
from cruise_control_tpu.model.state import Placement


def _sibling_info(gctx: GoalContext, placement: Placement, r):
    """(is_sib bool[...,RF], sib_rack i32[...,RF]) for replica r's partition."""
    r = jnp.asarray(r)
    sibs = gctx.partition_replicas[gctx.state.partition[r]]
    is_sib = (sibs >= 0) & (sibs != r[..., None])
    sib_rack = gctx.state.rack[placement.broker[jnp.maximum(sibs, 0)]]
    return is_sib, sib_rack


def replicas_violating_rack(gctx: GoalContext, placement: Placement) -> jnp.ndarray:
    """bool[R]: replica shares its rack with a sibling (strict violation)."""
    r = jnp.arange(gctx.state.num_replicas_padded)
    is_sib, sib_rack = _sibling_info(gctx, placement, r)
    own = gctx.state.rack[placement.broker][:, None]
    return jnp.any(is_sib & (sib_rack == own), axis=-1) & gctx.state.valid


def num_alive_racks(gctx: GoalContext) -> jnp.ndarray:
    alive = alive_mask(gctx)
    present = jnp.zeros(gctx.num_racks, dtype=jnp.int32).at[gctx.state.rack].max(
        alive.astype(jnp.int32))
    return jnp.maximum(jnp.sum(present), 1)


def _emptiest_broker_score(gctx, agg):
    """Shared rack-goal dst prune score: emptiest alive brokers first (the
    default dst_cost in headroom form)."""
    frac = agg.broker_load / jnp.maximum(gctx.state.capacity, 1e-9)
    return jnp.where(alive_mask(gctx), -jnp.sum(frac, axis=-1), -jnp.inf)


# NOTE: a load-independent dst_cost for the rack goals (per-broker fraction
# broadcast instead of the generic [C,D,4] after-move tensor) was measured
# and reverted: the round got marginally cheaper but the changed placement
# pattern cost CpuUsageDistribution two extra rounds downstream — the
# candidate's own load in the ranking is NOT noise at rack-repair scale.


class RackAwareGoal(Goal):
    """Strict rack-awareness (hard)."""

    name = "RackAwareGoal"
    is_hard = True
    multi_accept_safe = True
    multi_swap_safe = True     # partition-unique swaps cannot interact rack-wise
    multi_leadership_safe = True   # leadership never changes rack placement
    dst_slack_exempt = True        # acceptance reads sibling placement, not dst aggregates
    # Wide candidate tile + pruned destination axis.  Widening alone is a
    # regression (a 16K×B tile fell out of cache: 13.5 s vs 3.0 s steady at
    # north-star scale); with the dst axis tiled to max_dst_candidates the
    # pair matrices stay cache-resident while each round repairs ~2× the
    # violations.  Rack feasibility survives pruning because the dst tile is
    # rack-stratified (_stratified_top_dst).
    candidate_width_hint = 8192

    def dst_prune_score(self, gctx, placement, agg):
        return _emptiest_broker_score(gctx, agg)

    def violated_brokers(self, gctx, placement, agg):
        viol = replicas_violating_rack(gctx, placement)
        b = gctx.state.num_brokers_padded
        per_broker = jnp.zeros(b, dtype=bool).at[placement.broker].max(viol)
        return per_broker

    def candidate_score(self, gctx, placement, agg):
        # Only the violating replicas themselves move (not whole brokers).
        viol = replicas_violating_rack(gctx, placement)
        prio = self.replica_priority(gctx, placement, agg)
        score = jnp.where(viol & ~gctx.replica_excluded, prio, -jnp.inf)
        offline = currently_offline(gctx, placement)
        return jnp.where(offline, prio + 1e30, score)

    def self_ok(self, gctx, placement, agg, r, dst):
        return self.accept_replica_move(gctx, placement, agg, r, dst)

    def accept_replica_move(self, gctx, placement, agg, r, dst):
        """Destination rack must hold no sibling replica."""
        is_sib, sib_rack = _sibling_info(gctx, placement, r)
        dst_rack = gctx.state.rack[jnp.asarray(dst)]
        return ~jnp.any(is_sib & (sib_rack == dst_rack[..., None]), axis=-1)

    def accept_leadership_move(self, gctx, placement, agg, f):
        return jnp.broadcast_to(jnp.asarray(True), jnp.shape(f))

    def stats_metric(self, gctx, placement, agg):
        return jnp.sum(replicas_violating_rack(gctx, placement).astype(jnp.float32))


class RackAwareDistributionGoal(Goal):
    """Relaxed rack-awareness (hard): per-partition rack counts must not
    differ by more than what pigeonholing forces, i.e. every rack holds at
    most ceil(RF / alive_racks) replicas of a partition."""

    name = "RackAwareDistributionGoal"
    is_hard = True
    multi_accept_safe = True
    multi_swap_safe = True     # partition-unique swaps cannot interact rack-wise
    multi_leadership_safe = True   # leadership never changes rack placement
    dst_slack_exempt = True        # acceptance reads sibling placement, not dst aggregates
    candidate_width_hint = 8192    # same trade as RackAwareGoal

    def dst_prune_score(self, gctx, placement, agg):
        return _emptiest_broker_score(gctx, agg)

    def _rack_cap(self, gctx, r):
        """i32[...]: max allowed replicas of r's partition per rack."""
        sibs = gctx.partition_replicas[gctx.state.partition[jnp.asarray(r)]]
        rf = jnp.sum((sibs >= 0).astype(jnp.int32), axis=-1)
        k = num_alive_racks(gctx)
        return -(-rf // k)  # ceil division

    def _own_rack_count(self, gctx, placement, r):
        """i32[...]: replicas of r's partition currently on r's rack (incl. r)."""
        is_sib, sib_rack = _sibling_info(gctx, placement, r)
        own = gctx.state.rack[placement.broker[jnp.asarray(r)]]
        return 1 + jnp.sum((is_sib & (sib_rack == own[..., None])).astype(jnp.int32),
                           axis=-1)

    def violated_replicas(self, gctx, placement):
        r = jnp.arange(gctx.state.num_replicas_padded)
        over = self._own_rack_count(gctx, placement, r) > self._rack_cap(gctx, r)
        return over & gctx.state.valid

    def violated_brokers(self, gctx, placement, agg):
        viol = self.violated_replicas(gctx, placement)
        b = gctx.state.num_brokers_padded
        return jnp.zeros(b, dtype=bool).at[placement.broker].max(viol)

    def candidate_score(self, gctx, placement, agg):
        viol = self.violated_replicas(gctx, placement)
        prio = self.replica_priority(gctx, placement, agg)
        score = jnp.where(viol & ~gctx.replica_excluded, prio, -jnp.inf)
        offline = currently_offline(gctx, placement)
        return jnp.where(offline, prio + 1e30, score)

    def self_ok(self, gctx, placement, agg, r, dst):
        return self.accept_replica_move(gctx, placement, agg, r, dst)

    def accept_replica_move(self, gctx, placement, agg, r, dst):
        """After the move, the destination rack stays within the pigeonhole cap."""
        is_sib, sib_rack = _sibling_info(gctx, placement, r)
        dst_rack = gctx.state.rack[jnp.asarray(dst)]
        dst_count = jnp.sum((is_sib & (sib_rack == dst_rack[..., None])).astype(jnp.int32),
                            axis=-1)
        return dst_count + 1 <= self._rack_cap(gctx, r)

    def stats_metric(self, gctx, placement, agg):
        return jnp.sum(self.violated_replicas(gctx, placement).astype(jnp.float32))
