"""Goal registry: reference class names → goal factories.

Reference: goal instantiation by priority in ``analyzer/AnalyzerUtils.java``
``getGoalsByPriority`` :200 and the config lists in
``config/cruisecontrol.properties:99-108`` — the ``goals`` /
``default.goals`` / ``hard.goals`` / ``anomaly.detection.goals`` /
``intra.broker.goals`` switch-in point the new framework must honor
(BASELINE.json north star).  Both bare names and fully-qualified Java class
names resolve.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Sequence

from cruise_control_tpu.analyzer.goals.base import Goal
from cruise_control_tpu.analyzer.goals.capacity import (
    CpuCapacityGoal,
    DiskCapacityGoal,
    IntraBrokerDiskCapacityGoal,
    NetworkInboundCapacityGoal,
    NetworkOutboundCapacityGoal,
    ReplicaCapacityGoal,
)
from cruise_control_tpu.analyzer.goals.counts import (
    LeaderReplicaDistributionGoal,
    ReplicaDistributionGoal,
    TopicReplicaDistributionGoal,
)
from cruise_control_tpu.analyzer.goals.disk import IntraBrokerDiskUsageDistributionGoal
from cruise_control_tpu.analyzer.goals.distribution import (
    CpuUsageDistributionGoal,
    DiskUsageDistributionGoal,
    LeaderBytesInDistributionGoal,
    NetworkInboundUsageDistributionGoal,
    NetworkOutboundUsageDistributionGoal,
    PotentialNwOutGoal,
)
from cruise_control_tpu.analyzer.goals.kafka_assigner import (
    KafkaAssignerDiskUsageDistributionGoal,
    KafkaAssignerEvenRackAwareGoal,
)
from cruise_control_tpu.analyzer.goals.leadership import (
    MinTopicLeadersPerBrokerGoal,
    PreferredLeaderElectionGoal,
)
from cruise_control_tpu.analyzer.goals.rack import (
    RackAwareDistributionGoal,
    RackAwareGoal,
)

_FACTORIES: Dict[str, Callable[[], Goal]] = {
    "RackAwareGoal": RackAwareGoal,
    "RackAwareDistributionGoal": RackAwareDistributionGoal,
    "MinTopicLeadersPerBrokerGoal": MinTopicLeadersPerBrokerGoal,
    "ReplicaCapacityGoal": ReplicaCapacityGoal,
    "DiskCapacityGoal": DiskCapacityGoal,
    "NetworkInboundCapacityGoal": NetworkInboundCapacityGoal,
    "NetworkOutboundCapacityGoal": NetworkOutboundCapacityGoal,
    "CpuCapacityGoal": CpuCapacityGoal,
    "ReplicaDistributionGoal": ReplicaDistributionGoal,
    "PotentialNwOutGoal": PotentialNwOutGoal,
    "DiskUsageDistributionGoal": DiskUsageDistributionGoal,
    "NetworkInboundUsageDistributionGoal": NetworkInboundUsageDistributionGoal,
    "NetworkOutboundUsageDistributionGoal": NetworkOutboundUsageDistributionGoal,
    "CpuUsageDistributionGoal": CpuUsageDistributionGoal,
    "TopicReplicaDistributionGoal": TopicReplicaDistributionGoal,
    "LeaderReplicaDistributionGoal": LeaderReplicaDistributionGoal,
    "LeaderBytesInDistributionGoal": LeaderBytesInDistributionGoal,
    "PreferredLeaderElectionGoal": PreferredLeaderElectionGoal,
    "IntraBrokerDiskCapacityGoal": IntraBrokerDiskCapacityGoal,
    "IntraBrokerDiskUsageDistributionGoal": IntraBrokerDiskUsageDistributionGoal,
    "KafkaAssignerEvenRackAwareGoal": KafkaAssignerEvenRackAwareGoal,
    "KafkaAssignerDiskUsageDistributionGoal": KafkaAssignerDiskUsageDistributionGoal,
}

# Priority order per config/cruisecontrol.properties:99 (default.goals).
DEFAULT_GOALS: List[str] = [
    "RackAwareGoal",
    "ReplicaCapacityGoal",
    "DiskCapacityGoal",
    "NetworkInboundCapacityGoal",
    "NetworkOutboundCapacityGoal",
    "CpuCapacityGoal",
    "ReplicaDistributionGoal",
    "PotentialNwOutGoal",
    "DiskUsageDistributionGoal",
    "NetworkInboundUsageDistributionGoal",
    "NetworkOutboundUsageDistributionGoal",
    "CpuUsageDistributionGoal",
    "TopicReplicaDistributionGoal",
    "LeaderReplicaDistributionGoal",
    "LeaderBytesInDistributionGoal",
]

# config/cruisecontrol.properties:108.
DEFAULT_HARD_GOALS: List[str] = [
    "RackAwareGoal",
    "ReplicaCapacityGoal",
    "DiskCapacityGoal",
    "NetworkInboundCapacityGoal",
    "NetworkOutboundCapacityGoal",
    "CpuCapacityGoal",
]

# config/cruisecontrol.properties:214.
DEFAULT_ANOMALY_DETECTION_GOALS: List[str] = list(DEFAULT_HARD_GOALS)

# RunnableUtils.java isKafkaAssignerMode: the pair swapped in when a request
# carries kafka_assigner=true (even goal MUST run first — it assumes no prior
# optimized goals, KafkaAssignerEvenRackAwareGoal.java:108-111).
KAFKA_ASSIGNER_GOALS: List[str] = [
    "KafkaAssignerEvenRackAwareGoal",
    "KafkaAssignerDiskUsageDistributionGoal",
]

# config/cruisecontrol.properties:105.
DEFAULT_INTRA_BROKER_GOALS: List[str] = [
    "IntraBrokerDiskCapacityGoal",
    "IntraBrokerDiskUsageDistributionGoal",
]

# The full supported list (config/cruisecontrol.properties:102 `goals`).
SUPPORTED_GOALS: List[str] = list(_FACTORIES)

# Goals the convex-relaxation fast path (analyzer/relax.py) may lower to a
# fractional solve: the resource- and count-distribution families, whose
# objective is one scalar channel per broker.  Everything else — rack,
# capacity, topic/leadership structure, kafka_assigner, swap-only balancing —
# falls through to the greedy path bit-for-bit.  Derived from the goal
# classes' ``relax_eligible`` attribute so a new subclass cannot drift from
# this list silently.
RELAX_ELIGIBLE_GOALS: List[str] = [
    name for name, factory in _FACTORIES.items()
    if getattr(factory, "relax_eligible", False)
]


def is_relax_eligible(name: str) -> bool:
    """True when the (bare or fully-qualified) goal name may take the
    relax→repair path; unknown names are simply ineligible."""
    factory = _FACTORIES.get(_bare(name))
    return bool(factory is not None
                and getattr(factory, "relax_eligible", False))


def _bare(name: str) -> str:
    return name.rsplit(".", 1)[-1]


def goal_by_name(name: str) -> Goal:
    bare = _bare(name)
    try:
        return _FACTORIES[bare]()
    except KeyError:
        raise ValueError(f"unknown goal: {name!r} (known: {sorted(_FACTORIES)})") from None


def get_goals_by_priority(names: Sequence[str] | None = None) -> List[Goal]:
    """Instantiate goals in priority order (AnalyzerUtils.getGoalsByPriority)."""
    return [goal_by_name(n) for n in (names or DEFAULT_GOALS)]
