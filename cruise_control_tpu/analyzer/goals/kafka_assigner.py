"""kafka-assigner emulation goals.

Reference: ``analyzer/kafkaassigner/KafkaAssignerEvenRackAwareGoal.java``
(position-even rack-aware placement: for every replica position p, each
partition's position-p replica sits on the alive broker with the fewest
position-p replicas among brokers whose rack holds no lower-position replica
of that partition) and ``KafkaAssignerDiskUsageDistributionGoal.java``
(disk balance across brokers achieved by SWAPPING replicas between broker
pairs so replica counts never change).  The pair is selected when a request
carries ``kafka_assigner=true`` (``RunnableUtils.java`` isKafkaAssignerMode).

TPU formulation: the reference's per-position TreeSet of (count, broker) and
its one-replica-at-a-time pops become per-position count planes
``i32[RF, B]`` (one segment-sum) with an even band
``[floor(total_p/alive), ceil(total_p/alive)]``, and rack eligibility is the
usual RF-wide sibling gather restricted to LOWER positions.  The shared
batched solver then fills min-count brokers in parallel; the disk goal is the
generic swap phase with replica moves disabled.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from cruise_control_tpu.analyzer.context import (
    GoalContext,
    current_leader_of,
    currently_offline,
)
from cruise_control_tpu.analyzer.goals.base import (
    Goal,
    NEG_INF,
    OFFLINE_BONUS,
    alive_mask,
)
from cruise_control_tpu.analyzer.goals.distribution import ResourceDistributionGoal
from cruise_control_tpu.common.resources import Resource
from cruise_control_tpu.model.state import Placement

_CONFLICT_BONUS = 1e6


class KafkaAssignerEvenRackAwareGoal(Goal):
    """Position-even, rack-aware placement (kafka-assigner mode, hard)."""

    name = "KafkaAssignerEvenRackAwareGoal"
    is_hard = True
    src_sensitive_accept = True
    # Position swaps: when a broker has excess leaders, transferring
    # leadership to a follower on a leader-poor broker swaps the pair's
    # positions (the reference's maybeApplyMove case 2 at position 0,
    # KafkaAssignerEvenRackAwareGoal.java:192-201).
    uses_leadership_moves = True

    # ------------------------------------------------------------- plumbing

    def _eff_pos(self, gctx: GoalContext, placement: Placement) -> jnp.ndarray:
        """i32[R] effective replica position with the leader at 0.

        The reference's STEP1 swaps the leader into list position 0
        (KafkaAssignerEvenRackAwareGoal.java:115-120); here positions are
        static snapshot data, so the swap is computed: the leader takes 0 and
        the position-0 replica (if a follower) takes the leader's old slot.
        """
        state = gctx.state
        lead = current_leader_of(gctx, placement, state.partition)     # [R]
        lead_pos = jnp.where(lead >= 0, state.pos[jnp.maximum(lead, 0)], 0)
        eff = jnp.where(placement.is_leader, 0,
                        jnp.where((state.pos == 0) & (lead >= 0),
                                  lead_pos, state.pos))
        return jnp.clip(eff, 0, gctx.max_rf - 1)

    def _pos_counts(self, gctx: GoalContext, placement: Placement,
                    eff: jnp.ndarray) -> jnp.ndarray:
        """i32[RF, B] valid-replica count per (position, broker)."""
        b = gctx.state.num_brokers_padded
        flat = eff * b + placement.broker
        return jax.ops.segment_sum(
            gctx.state.valid.astype(jnp.int32), flat,
            num_segments=gctx.max_rf * b).reshape(gctx.max_rf, b)

    def _bounds(self, gctx: GoalContext, counts: jnp.ndarray):
        """(upper i32[RF], lower i32[RF]) even band per position."""
        nb = jnp.maximum(jnp.sum(alive_mask(gctx)), 1)
        total = jnp.sum(counts, axis=1)
        upper = -(-total // nb)          # ceil
        lower = total // nb
        return upper, lower

    def _rack_conflict(self, gctx: GoalContext, placement: Placement,
                       eff: jnp.ndarray) -> jnp.ndarray:
        """bool[R]: a LOWER-position sibling occupies this replica's rack."""
        state = gctx.state
        r = jnp.arange(state.num_replicas_padded)
        sibs = gctx.partition_replicas[state.partition]                # [R, RF]
        safe = jnp.maximum(sibs, 0)
        is_sib = (sibs >= 0) & (sibs != r[:, None])
        sib_rack = state.rack[placement.broker[safe]]
        own = state.rack[placement.broker][:, None]
        lower_pos = eff[safe] < eff[:, None]
        return jnp.any(is_sib & lower_pos & (sib_rack == own), axis=-1) \
            & state.valid

    def _rack_eligible(self, gctx: GoalContext, placement: Placement,
                       eff: jnp.ndarray, r, dst):
        """bool: dst's rack holds no lower-position sibling of r (the
        reference's ineligibleRackIds check, :166-172)."""
        state = gctx.state
        r = jnp.asarray(r)
        sibs = gctx.partition_replicas[state.partition[r]]             # [...,RF]
        safe = jnp.maximum(sibs, 0)
        is_sib = (sibs >= 0) & (sibs != r[..., None])
        sib_rack = state.rack[placement.broker[safe]]
        lower_pos = eff[safe] < eff[r][..., None]
        dst_rack = state.rack[jnp.asarray(dst)]
        return ~jnp.any(is_sib & lower_pos
                        & (sib_rack == dst_rack[..., None]), axis=-1)

    def _rack_eligible_strict(self, gctx: GoalContext, placement: Placement,
                              r, dst):
        """bool: dst's rack holds NO sibling of r at all.  Used for the
        acceptance vetoes over LATER goals' actions: once this goal has
        finished, placements are rack-distinct, and a later move/swap must
        not co-locate racks regardless of position (a lower-position-only
        check is vacuous for position-0 replicas)."""
        state = gctx.state
        r = jnp.asarray(r)
        sibs = gctx.partition_replicas[state.partition[r]]
        safe = jnp.maximum(sibs, 0)
        is_sib = (sibs >= 0) & (sibs != r[..., None])
        sib_rack = state.rack[placement.broker[safe]]
        dst_rack = state.rack[jnp.asarray(dst)]
        return ~jnp.any(is_sib & (sib_rack == dst_rack[..., None]), axis=-1)

    # --------------------------------------------------------------- rounds

    def violated_brokers(self, gctx, placement, agg):
        """Rack conflicts, dead brokers holding replicas, and FIXABLE
        count-band overflow.

        The reference's asserted postconditions are only
        ``ensureNoOfflineReplicas`` + ``ensureRackAware``
        (KafkaAssignerEvenRackAwareGoal.java:142-145); position-evenness is
        its greedy TreeSet *heuristic* — and cannot be a hard bound: a rack
        with fewer brokers (DeterministicCluster racks {0,0,1}) holds one
        replica of EVERY partition, forcing its brokers over any even band.
        What the greedy does guarantee is the absence of a surplus replica
        that some rack-eligible under-ceiling broker could absorb — so that,
        and only that, is what counts as an evenness violation here."""
        state = gctx.state
        eff = self._eff_pos(gctx, placement)
        counts = self._pos_counts(gctx, placement, eff)
        upper, _ = self._bounds(gctx, counts)
        b = state.num_brokers_padded
        k = gctx.num_racks

        # under[p, k]: rack k has an alive broker below the position-p ceiling.
        alive = alive_mask(gctx)
        can_take = alive[None, :] & (counts + 1 <= upper[:, None])     # [RF,B]
        # segment_SUM: an empty rack segment must read False (segment_max's
        # empty-segment identity is INT32_MIN, which casts to True).
        under = (jax.ops.segment_sum(
            can_take.astype(jnp.int32).T, state.rack,
            num_segments=k).T > 0)                                     # [RF,K]

        # blocked[r, k]: a LOWER-position sibling of r occupies rack k.
        r = jnp.arange(state.num_replicas_padded)
        sibs = gctx.partition_replicas[state.partition]                # [R,RF]
        safe = jnp.maximum(sibs, 0)
        is_sib = (sibs >= 0) & (sibs != r[:, None])
        lower = is_sib & (eff[safe] < eff[:, None])
        sib_rack = jnp.where(lower, state.rack[placement.broker[safe]], k)
        blocked = jnp.zeros((state.num_replicas_padded, k + 1), dtype=bool)
        blocked = blocked.at[r[:, None], sib_rack].set(True)[:, :k]    # [R,K]

        over_r = (counts[eff, placement.broker] > upper[eff]) & state.valid
        fixable = over_r & jnp.any(under[eff] & ~blocked, axis=-1)

        dead_with = ((~state.alive) & state.broker_valid
                     & (agg.replica_counts > 0))
        conflict = self._rack_conflict(gctx, placement, eff)
        flag_r = fixable | conflict
        flagged_b = jnp.zeros(b, dtype=bool).at[placement.broker].max(flag_r)
        return dead_with | flagged_b

    def candidate_score(self, gctx, placement, agg):
        state = gctx.state
        eff = self._eff_pos(gctx, placement)
        counts = self._pos_counts(gctx, placement, eff)
        upper, _ = self._bounds(gctx, counts)
        over = counts[eff, placement.broker] > upper[eff]
        conflict = self._rack_conflict(gctx, placement, eff)
        offline = currently_offline(gctx, placement)
        cand = (over | conflict) & state.valid & ~gctx.replica_excluded
        # Leaders (position 0) first, like the reference's ascending-position
        # sweep; rack conflicts outrank plain over-counts.
        prio = (-eff.astype(jnp.float32)
                + jnp.where(conflict, _CONFLICT_BONUS, 0.0))
        score = jnp.where(cand, prio, NEG_INF)
        return jnp.where(offline, prio + OFFLINE_BONUS, score)

    def self_ok(self, gctx, placement, agg, r, dst):
        eff = self._eff_pos(gctx, placement)
        counts = self._pos_counts(gctx, placement, eff)
        upper, _ = self._bounds(gctx, counts)
        r = jnp.asarray(r)
        count_ok = counts[eff[r], dst] + 1 <= upper[eff[r]]
        # Offline/conflicted replicas may exceed the band rather than strand.
        must_move = (currently_offline(gctx, placement, r)
                     | self._rack_conflict(gctx, placement, eff)[r])
        return (count_ok | must_move) & self._rack_eligible(
            gctx, placement, eff, r, dst)

    def dst_cost(self, gctx, placement, agg, r, dst):
        """Fewest position-p replicas first (the reference's TreeSet order)."""
        eff = self._eff_pos(gctx, placement)
        counts = self._pos_counts(gctx, placement, eff)
        return counts[eff[jnp.asarray(r)], dst].astype(jnp.float32)

    # ----------------------------------------------------- leadership phase

    def leadership_candidate_score(self, gctx, placement, agg):
        """Followers whose leader sits on a leader-rich broker and who sit on
        a leader-poor broker themselves."""
        state = gctx.state
        eff = self._eff_pos(gctx, placement)
        counts = self._pos_counts(gctx, placement, eff)
        upper, _ = self._bounds(gctx, counts)
        lead = current_leader_of(gctx, placement, state.partition)
        lead_b = placement.broker[jnp.maximum(lead, 0)]
        over = counts[0, lead_b] > upper[0]
        own = placement.broker
        cand = ((lead >= 0) & over & ~placement.is_leader & state.valid
                & ~currently_offline(gctx, placement) & ~gctx.replica_excluded)
        return jnp.where(cand, -counts[0, own].astype(jnp.float32), NEG_INF)

    def leadership_self_ok(self, gctx, placement, agg, f):
        eff = self._eff_pos(gctx, placement)
        counts = self._pos_counts(gctx, placement, eff)
        upper, _ = self._bounds(gctx, counts)
        b = placement.broker[jnp.asarray(f)]
        return counts[0, b] + 1 <= upper[0]

    def accept_leadership_move(self, gctx, placement, agg, f):
        eff = self._eff_pos(gctx, placement)
        counts = self._pos_counts(gctx, placement, eff)
        upper, _ = self._bounds(gctx, counts)
        b = placement.broker[jnp.asarray(f)]
        return counts[0, b] + 1 <= upper[0]

    # --------------------------------------------------- acceptance (vetoes)

    def accept_replica_move(self, gctx, placement, agg, r, dst):
        eff = self._eff_pos(gctx, placement)
        counts = self._pos_counts(gctx, placement, eff)
        upper, _ = self._bounds(gctx, counts)
        r = jnp.asarray(r)
        return ((counts[eff[r], dst] + 1 <= upper[eff[r]])
                & self._rack_eligible_strict(gctx, placement, r, dst))

    def accept_swap(self, gctx, placement, agg, r_out, r_in, b_out, b_in):
        """Same-position swaps are count-neutral; cross-position swaps shift
        one count each way.  Rack eligibility applies in both directions."""
        eff = self._eff_pos(gctx, placement)
        counts = self._pos_counts(gctx, placement, eff)
        upper, lower = self._bounds(gctx, counts)
        r_out = jnp.asarray(r_out)
        r_in = jnp.asarray(r_in)
        p_out, p_in = eff[r_out], eff[r_in]
        same = p_out == p_in
        counts_ok = ((counts[p_out, b_in] + 1 <= upper[p_out])
                     & (counts[p_in, b_out] + 1 <= upper[p_in])
                     & (counts[p_out, b_out] - 1 >= lower[p_out])
                     & (counts[p_in, b_in] - 1 >= lower[p_in]))
        return ((same | counts_ok)
                & self._rack_eligible_strict(gctx, placement, r_out, b_in)
                & self._rack_eligible_strict(gctx, placement, r_in, b_out))

    def stats_metric(self, gctx, placement, agg):
        eff = self._eff_pos(gctx, placement)
        counts = self._pos_counts(gctx, placement, eff)
        upper, _ = self._bounds(gctx, counts)
        excess = jnp.maximum(counts - upper[:, None], 0).sum()
        conflicts = jnp.sum(self._rack_conflict(gctx, placement, eff))
        return (excess + conflicts).astype(jnp.float32)


class KafkaAssignerDiskUsageDistributionGoal(ResourceDistributionGoal):
    """Disk balance via replica SWAPS only (kafka-assigner mode).

    The reference (KafkaAssignerDiskUsageDistributionGoal.java:84-233) sorts
    brokers by disk utilization and swaps replicas between the most- and
    least-utilized pairs until both ends fall inside
    ``mean ± balance-margin``; counts never change.  Here that is the shared
    batched swap phase with the move/pull/leadership phases disabled.
    """

    uses_replica_moves = False
    has_pull_phase = False
    has_swap_phase = True
    # Swap-only balancing: the fractional fast path rounds to MOVES, which
    # this mode forbids — always take the greedy swap path.
    relax_eligible = False

    def __init__(self):
        super().__init__(Resource.DISK, "KafkaAssignerDiskUsageDistributionGoal")
