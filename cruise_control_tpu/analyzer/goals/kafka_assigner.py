"""kafka-assigner emulation goals.

Reference: ``analyzer/kafkaassigner/KafkaAssignerEvenRackAwareGoal.java`` and
``KafkaAssignerDiskUsageDistributionGoal.java`` — legacy goal pair selected
when a request carries ``kafka_assigner=true`` (RunnableUtils.isKafkaAssignerMode).

The even-rack goal's contract (replicas of a partition land on distinct racks,
spread evenly by replica position) is the strict-rack invariant plus even
spread — realised here as the relaxed-rack kernels with the strict cap; the
disk goal is broker-level disk balance with the kafka-assigner's swap-style
threshold semantics, which the shared solver covers via moves.
"""

from __future__ import annotations

from cruise_control_tpu.analyzer.goals.distribution import ResourceDistributionGoal
from cruise_control_tpu.analyzer.goals.rack import RackAwareGoal
from cruise_control_tpu.common.resources import Resource


class KafkaAssignerEvenRackAwareGoal(RackAwareGoal):
    name = "KafkaAssignerEvenRackAwareGoal"
    is_hard = True


class KafkaAssignerDiskUsageDistributionGoal(ResourceDistributionGoal):
    def __init__(self):
        super().__init__(Resource.DISK, "KafkaAssignerDiskUsageDistributionGoal")
