"""Replica-count distribution (soft) goals.

Reference: ``analyzer/goals/ReplicaDistributionAbstractGoal.java`` and
subclasses ``ReplicaDistributionGoal.java``,
``LeaderReplicaDistributionGoal.java``, ``TopicReplicaDistributionGoal.java``.

Count bands mirror the load bands: with avg = alive replicas / alive brokers,
a broker should hold between ``floor(avg*(2-T))`` and ``ceil(avg*T)`` replicas
(leader replicas / per-topic replicas for the sibling goals).
"""

from __future__ import annotations

import jax.numpy as jnp

from cruise_control_tpu.analyzer.context import (
    Aggregates,
    GoalContext,
    current_leader_of,
    currently_offline,
)
from cruise_control_tpu.analyzer.goals.base import Goal, NEG_INF, OFFLINE_BONUS, alive_mask
from cruise_control_tpu.model.state import Placement


def _count_bounds(counts, alive, threshold):
    """(upper i32, lower i32) band around the alive-broker average count."""
    n = jnp.maximum(jnp.sum(alive), 1)
    avg = jnp.sum(jnp.where(alive, counts, 0)) / n
    upper = jnp.ceil(avg * threshold).astype(jnp.int32)
    lower = jnp.floor(avg * (2.0 - threshold)).astype(jnp.int32)
    return jnp.maximum(upper, 1), jnp.maximum(lower, 0)


class ReplicaDistributionGoal(Goal):
    """Even replica counts across brokers (ReplicaDistributionGoal.java)."""

    name = "ReplicaDistributionGoal"
    is_hard = False
    has_pull_phase = True
    src_sensitive_accept = True
    multi_accept_safe = True
    multi_swap_safe = True          # swaps are replica-count-neutral
    multi_leadership_safe = True    # promotions are replica-count-neutral
    # Count channel: unit mass per replica vs the alive-broker average
    # (leader subclass inherits with is_leader mass).  TopicReplicaDistribution
    # is NOT eligible — its band is per (topic, broker), a T×B channel.
    relax_eligible = True

    def _counts(self, gctx, agg):
        return agg.replica_counts

    def _threshold(self, gctx):
        return gctx.replica_balance_threshold

    def _bounds(self, gctx, agg):
        return _count_bounds(self._counts(gctx, agg), alive_mask(gctx),
                             self._threshold(gctx))

    def violated_brokers(self, gctx, placement, agg):
        upper, lower = self._bounds(gctx, agg)
        c = self._counts(gctx, agg)
        alive = alive_mask(gctx)
        dead_with = (~gctx.state.alive) & gctx.state.broker_valid & (c > 0)
        return ((c > upper) | (c < lower)) & alive | dead_with

    def _over_brokers(self, gctx, agg):
        upper, _ = self._bounds(gctx, agg)
        return (self._counts(gctx, agg) > upper) & alive_mask(gctx)

    def candidate_score(self, gctx, placement, agg):
        state = gctx.state
        over = self._over_brokers(gctx, agg)
        prio = self.replica_priority(gctx, placement, agg)
        cand = over[placement.broker] & state.valid & ~gctx.replica_excluded
        score = jnp.where(cand, prio, NEG_INF)
        offline = currently_offline(gctx, placement)
        return jnp.where(offline, prio + OFFLINE_BONUS, score)

    def replica_priority(self, gctx, placement, agg):
        # Lightest replicas first: count goals shouldn't disturb load balance.
        load = jnp.where(placement.is_leader[:, None],
                         gctx.state.leader_load, gctx.state.follower_load)
        return -jnp.sum(load / jnp.maximum(
            jnp.mean(gctx.state.capacity, axis=0, keepdims=True), 1e-9), axis=-1)

    def self_ok(self, gctx, placement, agg, r, dst):
        return self.accept_replica_move(gctx, placement, agg, r, dst)

    def accept_replica_move(self, gctx, placement, agg, r, dst):
        upper, lower = self._bounds(gctx, agg)
        c = self._counts(gctx, agg)
        src = placement.broker[jnp.asarray(r)]
        dst_ok = c[dst] + 1 <= upper
        src_ok = (c[src] - 1 >= lower) | ~gctx.state.alive[src]
        offline = currently_offline(gctx, placement, r)
        return dst_ok & (src_ok | offline)

    def relax_weights(self, gctx, placement):
        return gctx.state.valid.astype(jnp.float32)

    def relax_channel(self, gctx, agg):
        alive = alive_mask(gctx)
        c = self._counts(gctx, agg).astype(jnp.float32)
        n = jnp.maximum(jnp.sum(alive), 1)
        avg = jnp.sum(jnp.where(alive, c, 0.0)) / n
        ones = jnp.ones_like(c)
        return c, avg * ones, ones

    def dst_cost(self, gctx, placement, agg, r, dst):
        del r
        return self._counts(gctx, agg)[dst].astype(jnp.float32)

    def dst_prune_score(self, gctx, placement, agg):
        """Count headroom: receivers are the lowest-count brokers."""
        upper, _ = self._bounds(gctx, agg)
        head = (upper - self._counts(gctx, agg)).astype(jnp.float32)
        return jnp.where(alive_mask(gctx), head, -jnp.inf)

    def dst_cumulative_slack(self, gctx, placement, agg, cand_load, is_lead_cand):
        upper, _ = self._bounds(gctx, agg)
        w = self._count_weight(cand_load, is_lead_cand)
        return w, (upper - self._counts(gctx, agg)).astype(jnp.float32)

    def src_cumulative_slack(self, gctx, placement, agg, cand_load, is_lead_cand):
        _, lower = self._bounds(gctx, agg)
        w = self._count_weight(cand_load, is_lead_cand)
        return w, (self._counts(gctx, agg) - lower).astype(jnp.float32)

    def _count_weight(self, cand_load, is_lead_cand):
        return jnp.ones(cand_load.shape[0], dtype=jnp.float32)

    def accept_swap(self, gctx, placement, agg, r_out, r_in, b_out, b_in):
        """A swap is count-neutral on both brokers — always acceptable."""
        return jnp.broadcast_to(jnp.asarray(True), jnp.broadcast_shapes(
            jnp.shape(r_out), jnp.shape(r_in)))

    def pull_dst_mask(self, gctx, placement, agg):
        _, lower = self._bounds(gctx, agg)
        return (self._counts(gctx, agg) < lower) & alive_mask(gctx)

    def pull_dst_prune_score(self, gctx, placement, agg):
        """Largest count deficit first."""
        _, lower = self._bounds(gctx, agg)
        deficit = (lower - self._counts(gctx, agg)).astype(jnp.float32)
        return jnp.where(alive_mask(gctx), deficit, -jnp.inf)

    def pull_candidate_score(self, gctx, placement, agg):
        state = gctx.state
        c = self._counts(gctx, agg)
        alive = alive_mask(gctx)
        n = jnp.maximum(jnp.sum(alive), 1)
        avg = jnp.sum(jnp.where(alive, c, 0)) / n
        hot = c > avg
        prio = self.replica_priority(gctx, placement, agg)
        cand = (hot[placement.broker] & state.valid & ~currently_offline(gctx, placement)
                & ~gctx.replica_excluded)
        return jnp.where(cand, prio, NEG_INF)

    def stats_metric(self, gctx, placement, agg):
        alive = alive_mask(gctx)
        c = self._counts(gctx, agg).astype(jnp.float32)
        n = jnp.maximum(jnp.sum(alive), 1)
        mean = jnp.sum(jnp.where(alive, c, 0.0)) / n
        var = jnp.sum(jnp.where(alive, (c - mean) ** 2, 0.0)) / n
        return jnp.sqrt(var)


class LeaderReplicaDistributionGoal(ReplicaDistributionGoal):
    """Even *leader* counts (LeaderReplicaDistributionGoal.java): leadership
    transfers first, leader-replica moves as fallback."""

    name = "LeaderReplicaDistributionGoal"
    uses_leadership_moves = True
    # Leader replicas pulled INTO under-count brokers (the reference's
    # rebalanceByMovingLeaderReplicasIn fallback).
    has_pull_phase = True
    # Count-band headroom keeps rounds narrower than the default tile, but
    # the under-fill pull needs reach (1024 measurably loses residuals).
    candidate_width_hint = 2048

    def relax_weights(self, gctx, placement):
        # Only leader replicas carry mass in the leader-count channel.
        return (gctx.state.valid & placement.is_leader).astype(jnp.float32)

    def leadership_cumulative_slack(self, gctx, placement, agg, f, old):
        upper, lower = self._bounds(gctx, agg)
        c = self._counts(gctx, agg).astype(jnp.float32)
        ones = jnp.ones(jnp.shape(f), dtype=jnp.float32)
        return ones, -ones, upper - c, c - lower, None

    def swap_cumulative_slack(self, gctx, placement, agg, d_load, d_pot,
                              d_lbi, d_lead):
        """Leader counts shift by is_leader(r_out) - is_leader(r_in)."""
        upper, lower = self._bounds(gctx, agg)
        c = self._counts(gctx, agg).astype(jnp.float32)
        return d_lead, upper - c, c - lower

    def _count_weight(self, cand_load, is_lead_cand):
        # Only leader candidates move leader counts.
        return is_lead_cand.astype(jnp.float32)

    def _counts(self, gctx, agg):
        return agg.leader_counts

    def _threshold(self, gctx):
        return gctx.leader_replica_balance_threshold

    def candidate_score(self, gctx, placement, agg):
        # Only leader replicas on over-count brokers are move candidates.
        base = super().candidate_score(gctx, placement, agg)
        return jnp.where(placement.is_leader, base, NEG_INF)

    def accept_replica_move(self, gctx, placement, agg, r, dst):
        """Follower moves don't change leader counts; leader moves do."""
        upper, lower = self._bounds(gctx, agg)
        c = self._counts(gctx, agg)
        r = jnp.asarray(r)
        is_lead = placement.is_leader[r]
        src = placement.broker[r]
        dst_ok = c[dst] + 1 <= upper
        src_ok = ((c[src] - 1 >= lower) | ~gctx.state.alive[src]
                  | currently_offline(gctx, placement, r))
        return ~is_lead | (dst_ok & src_ok)

    def leadership_candidate_score(self, gctx, placement, agg):
        """Promotions serve BOTH band ends: shed over-count brokers (promote
        their partitions' followers elsewhere) and fill under-count brokers
        (promote their own followers, demoting donors that stay above the
        lower band)."""
        state = gctx.state
        _, lower = self._bounds(gctx, agg)
        c = self._counts(gctx, agg)
        over = self._over_brokers(gctx, agg)
        under = self.pull_dst_mask(gctx, placement, agg)
        f = jnp.arange(state.num_replicas_padded)
        lead = current_leader_of(gctx, placement, state.partition[f])
        lb = placement.broker[jnp.maximum(lead, 0)]
        b = placement.broker
        base = ((lead >= 0) & ~placement.is_leader & state.valid
                & ~currently_offline(gctx, placement) & ~gctx.replica_excluded)
        cand_over = base & over[lb]
        cand_under = base & under[b] & (c[lb] - 1 >= lower)
        # Under-fill tier strictly above the over-shed tier (counts are
        # bounded by R, so the tiers stay disjoint and f32-exact), then
        # prefer promoting onto the emptiest brokers within each tier.
        rmax = jnp.float32(state.num_replicas_padded)
        score = (under[b].astype(jnp.float32) * 2.0 * rmax
                 + (rmax - c[b].astype(jnp.float32)))
        return jnp.where(cand_over | cand_under, score, NEG_INF)

    def pull_candidate_score(self, gctx, placement, agg):
        """Only LEADER replicas carry leader counts into an under broker."""
        base = super().pull_candidate_score(gctx, placement, agg)
        return jnp.where(placement.is_leader, base, NEG_INF)

    def leadership_self_ok(self, gctx, placement, agg, f):
        upper, _ = self._bounds(gctx, agg)
        c = self._counts(gctx, agg)
        return c[placement.broker[jnp.asarray(f)]] + 1 <= upper

    def accept_leadership_move(self, gctx, placement, agg, f):
        """Promotion adds one leader to f's broker — veto when that would
        reach or deepen an upper-bound violation."""
        upper, _ = self._bounds(gctx, agg)
        c = self._counts(gctx, agg)
        b = placement.broker[jnp.asarray(f)]
        return c[b] + 1 <= upper

    def accept_swap(self, gctx, placement, agg, r_out, r_in, b_out, b_in):
        """Leader counts shift only when the swapped replicas' roles differ:
        b_in nets is_leader(r_out) - is_leader(r_in).  Only the gaining end is
        held to the upper bound and the losing end to the lower bound, and a
        move in the improving direction on an already-violated broker is
        never vetoed (matches the was_over escape in the other acceptances)."""
        upper, lower = self._bounds(gctx, agg)
        c = self._counts(gctx, agg)
        d = (placement.is_leader[jnp.asarray(r_out)].astype(jnp.int32)
             - placement.is_leader[jnp.asarray(r_in)].astype(jnp.int32))
        in_after = c[b_in] + d
        out_after = c[b_out] - d
        gain_ok = (in_after <= upper) | (d <= 0)      # b_in gains when d > 0
        lose_ok = (out_after >= lower) | (d <= 0)     # b_out loses when d > 0
        gain_ok2 = (out_after <= upper) | (d >= 0)    # b_out gains when d < 0
        lose_ok2 = (in_after >= lower) | (d >= 0)     # b_in loses when d < 0
        return gain_ok & lose_ok & gain_ok2 & lose_ok2

    def stats_metric(self, gctx, placement, agg):
        return super().stats_metric(gctx, placement, agg)


class TopicReplicaDistributionGoal(Goal):
    """Even per-topic replica counts (TopicReplicaDistributionGoal.java)."""

    name = "TopicReplicaDistributionGoal"
    is_hard = False
    src_sensitive_accept = True
    multi_accept_safe = True
    needs_topic_group = True
    # One swap per (topic, broker) touch per round keeps every per-topic
    # count delta within the +/-1 each pairwise accept_swap already checked.
    multi_swap_safe = True
    swap_topic_group = True
    multi_leadership_safe = True    # promotions keep per-topic replica counts

    def _bounds(self, gctx, agg):
        """(upper i32[T], lower i32[T]) per-topic count bands."""
        alive = alive_mask(gctx)
        n = jnp.maximum(jnp.sum(alive), 1)
        totals = jnp.sum(jnp.where(alive[None, :], agg.topic_counts, 0), axis=1)  # [T]
        avg = totals / n
        t = gctx.topic_replica_balance_threshold
        gap = gctx.topic_replica_balance_min_gap
        upper = jnp.maximum(jnp.ceil(avg * t), jnp.ceil(avg) + gap).astype(jnp.int32)
        lower = jnp.maximum(jnp.floor(avg * (2.0 - t)), 0.0).astype(jnp.int32)
        return upper, lower

    def violated_brokers(self, gctx, placement, agg):
        upper, lower = self._bounds(gctx, agg)
        over = agg.topic_counts > upper[:, None]
        under = agg.topic_counts < lower[:, None]
        return jnp.any(over | under, axis=0) & alive_mask(gctx)

    def candidate_score(self, gctx, placement, agg):
        state = gctx.state
        upper, _ = self._bounds(gctx, agg)
        c_rt = agg.topic_counts[state.topic, placement.broker]     # [R]
        over = (c_rt > upper[state.topic]) & alive_mask(gctx)[placement.broker]
        prio = c_rt.astype(jnp.float32)
        cand = over & state.valid & ~gctx.replica_excluded
        score = jnp.where(cand, prio, NEG_INF)
        offline = currently_offline(gctx, placement)
        return jnp.where(offline, prio + OFFLINE_BONUS, score)

    def self_ok(self, gctx, placement, agg, r, dst):
        return self.accept_replica_move(gctx, placement, agg, r, dst)

    def accept_replica_move(self, gctx, placement, agg, r, dst):
        upper, lower = self._bounds(gctx, agg)
        r = jnp.asarray(r)
        t = gctx.state.topic[r]
        src = placement.broker[r]
        dst_ok = agg.topic_counts[t, dst] + 1 <= upper[t]
        src_ok = ((agg.topic_counts[t, src] - 1 >= lower[t])
                  | ~gctx.state.alive[src] | currently_offline(gctx, placement, r))
        return dst_ok & src_ok

    def dst_cost(self, gctx, placement, agg, r, dst):
        t = gctx.state.topic[jnp.asarray(r)]
        return agg.topic_counts[t, dst].astype(jnp.float32)

    def accept_swap(self, gctx, placement, agg, r_out, r_in, b_out, b_in):
        """Same-topic swaps are neutral; cross-topic swaps move one count of
        each topic in opposite directions."""
        upper, lower = self._bounds(gctx, agg)
        t_out = gctx.state.topic[jnp.asarray(r_out)]
        t_in = gctx.state.topic[jnp.asarray(r_in)]
        same = t_out == t_in
        in_gain_ok = agg.topic_counts[t_out, b_in] + 1 <= upper[t_out]
        in_lose_ok = agg.topic_counts[t_in, b_in] - 1 >= lower[t_in]
        out_gain_ok = agg.topic_counts[t_in, b_out] + 1 <= upper[t_in]
        out_lose_ok = agg.topic_counts[t_out, b_out] - 1 >= lower[t_out]
        return same | (in_gain_ok & in_lose_ok & out_gain_ok & out_lose_ok)

    def stats_metric(self, gctx, placement, agg):
        upper, lower = self._bounds(gctx, agg)
        over = jnp.maximum(agg.topic_counts - upper[:, None], 0)
        under = jnp.maximum(lower[:, None] - agg.topic_counts, 0)
        alive = alive_mask(gctx)
        return jnp.sum(jnp.where(alive[None, :], over + under, 0)).astype(jnp.float32)
