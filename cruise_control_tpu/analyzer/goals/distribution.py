"""Load-distribution (soft) goals.

Reference: ``analyzer/goals/ResourceDistributionGoal.java:54-1016`` and its
four resource subclasses, ``PotentialNwOutGoal.java``,
``LeaderBytesInDistributionGoal.java``.

ResourceDistribution semantics (initGoalState :236-263): every alive broker's
utilization for the resource must sit inside ``[avg*(2-T), avg*T]`` where avg
is the cluster-wide alive utilization fraction scaled by broker capacity.
Mechanisms (rebalanceForBroker :349-405): move replicas out of hot brokers,
pull replicas into cold ones, and move leadership for CPU/NW_OUT.  Here each
mechanism is a phase of the shared solver; the acceptance veto (``accept_*``)
is the same band predicate applied to later goals' candidate actions.
"""

from __future__ import annotations

import jax.numpy as jnp

from cruise_control_tpu.analyzer.context import (
    Aggregates,
    GoalContext,
    current_leader_of,
    currently_offline,
    hash01,
    replica_role_load,
)
from cruise_control_tpu.analyzer.goals.base import (
    Goal,
    NEG_INF,
    OFFLINE_BONUS,
    alive_mask,
    avg_alive_util_fraction,
)
from cruise_control_tpu.common.resources import Resource
from cruise_control_tpu.model.state import Placement


class ResourceDistributionGoal(Goal):
    """Keep one resource's per-broker utilization inside the balance band."""

    is_hard = False
    has_pull_phase = True
    has_swap_phase = True
    src_sensitive_accept = True
    multi_accept_safe = True
    multi_swap_safe = True
    multi_leadership_safe = True
    # Band headroom keeps per-round acceptance far below the structural
    # goals' tile width; 1024 candidates lose no rounds (measured) and cut
    # the C×B feasibility cost 4x at north-star scale.
    candidate_width_hint = 1024
    # One scalar channel per broker (this resource's load vs avg·cap):
    # exactly the shape the fractional fast path lowers (analyzer/relax.py).
    relax_eligible = True
    resource: int = Resource.DISK

    def __init__(self, resource: int, name: str):
        self.resource = int(resource)
        self.name = name
        # Leadership shifts load only for CPU/NW_OUT (follower NW_IN ≈ leader NW_IN).
        self.uses_leadership_moves = resource in (Resource.CPU, Resource.NW_OUT)

    # ----------------------------------------------------------- band maths

    def _bounds(self, gctx: GoalContext, agg: Aggregates):
        """(upper f32[B], lower f32[B], lower_active bool): absolute load bounds."""
        res = self.resource
        avg = avg_alive_util_fraction(gctx, agg, res)
        t = gctx.balance_threshold[res]
        cap = gctx.state.capacity[:, res]
        upper = avg * t * cap
        lower = avg * (2.0 - t) * cap
        # Low-utilization guard: when the cluster barely uses this resource,
        # only the upper bound matters (reference: low.utilization.threshold).
        lower_active = avg >= gctx.low_utilization_threshold[res]
        return upper, lower, lower_active

    def violated_brokers(self, gctx, placement, agg):
        res = self.resource
        upper, lower, lower_active = self._bounds(gctx, agg)
        load = agg.broker_load[:, res]
        over = load > upper
        under = (load < lower) & lower_active
        return (over | under) & alive_mask(gctx)

    def _over_brokers(self, gctx, agg):
        upper, _, _ = self._bounds(gctx, agg)
        return (agg.broker_load[:, self.resource] > upper) & alive_mask(gctx)

    # ------------------------------------------------------- move-out phase

    def candidate_score(self, gctx, placement, agg):
        # NOTE: heaviest-replica-first, deliberately.  A gap-weighted
        # interleave across violated brokers (the swap-tile design) was
        # measured here and REVERTED: the tail rounds at north-star scale
        # are acceptance-bound (prior goals' bands veto the moves), not
        # tile-membership-bound, so fair tile shares bought nothing and the
        # changed priority order cost LeaderReplicaDistribution a residual
        # violation.
        state = gctx.state
        over = self._over_brokers(gctx, agg)
        prio = self.replica_priority(gctx, placement, agg)
        cand = over[placement.broker] & state.valid & ~gctx.replica_excluded
        score = jnp.where(cand, prio, NEG_INF)
        offline = currently_offline(gctx, placement)
        return jnp.where(offline, prio + OFFLINE_BONUS, score)

    def replica_priority(self, gctx, placement, agg):
        load = jnp.where(placement.is_leader[:, None],
                         gctx.state.leader_load, gctx.state.follower_load)
        return load[:, self.resource]

    def self_ok(self, gctx, placement, agg, r, dst):
        """Move keeps dst inside the band and strictly reduces deviation."""
        res = self.resource
        upper, lower, lower_active = self._bounds(gctx, agg)
        load = replica_role_load(gctx, placement, r)[..., res]
        src = placement.broker[jnp.asarray(r)]
        src_after = agg.broker_load[src, res] - load
        dst_after = agg.broker_load[dst, res] + load
        dst_ok = dst_after <= upper[dst]
        # Don't overshoot the source below its lower bound...
        src_ok = jnp.where(lower_active, src_after >= lower[src], True)
        # ...unless the replica is bigger than the band itself.
        ok = dst_ok & src_ok
        offline = currently_offline(gctx, placement, r)
        return jnp.where(offline, dst_ok, ok)

    def accept_replica_move(self, gctx, placement, agg, r, dst):
        """actionAcceptance (:803-871): later goals may not push dst over the
        upper bound nor drain src below the lower bound."""
        res = self.resource
        upper, lower, lower_active = self._bounds(gctx, agg)
        load = replica_role_load(gctx, placement, r)[..., res]
        src = placement.broker[jnp.asarray(r)]
        src_after = agg.broker_load[src, res] - load
        dst_after = agg.broker_load[dst, res] + load
        dst_before = agg.broker_load[dst, res]
        # If dst was already over (shouldn't happen post-optimization), only
        # reject when the move makes it worse.
        dst_ok = (dst_after <= upper[dst]) | ((dst_before > upper[dst]) & (load <= 0))
        src_ok = jnp.where(lower_active, (src_after >= lower[src]) | (load <= 0), True)
        return dst_ok & src_ok

    def dst_cost(self, gctx, placement, agg, r, dst):
        res = self.resource
        load = replica_role_load(gctx, placement, r)[..., res]
        after = agg.broker_load[dst, res] + load
        return after / jnp.maximum(gctx.state.capacity[dst, res], 1e-9)

    def dst_prune_score(self, gctx, placement, agg):
        """Band headroom: a round only ever fills the emptiest receivers."""
        upper, _, _ = self._bounds(gctx, agg)
        head = upper - agg.broker_load[:, self.resource]
        return jnp.where(alive_mask(gctx), head, -jnp.inf)

    def dst_prune_score_vs(self, gctx, placement, agg, priors):
        """Priors-aware receiver ranking (worst in-play band first, own-
        resource tiebreak).  Ranking by THIS resource's headroom alone
        starves tail rounds at north-star scale: the emptiest receivers for
        this resource often sit ON a prior distribution goal's upper band,
        so that prior vetoes every arrival and the round fixes almost
        nothing.  A receiver's real acceptance odds are bounded by its worst
        normalized headroom across the bands actually IN PLAY — this goal's
        plus each prior ResourceDistributionGoal's (goals solved later veto
        nothing and must not skew the ranking)."""
        resources = sorted({self.resource} | {
            g.resource for g in priors
            if isinstance(g, ResourceDistributionGoal)})
        if len(resources) == 1:
            return self.dst_prune_score(gctx, placement, agg)
        res_idx = jnp.asarray(resources)
        alive = alive_mask(gctx)[:, None]
        caps = jnp.maximum(gctx.state.capacity[:, res_idx], 1e-9)   # [B,K]
        load = agg.broker_load[:, res_idx]                          # [B,K]
        total = jnp.sum(jnp.where(alive, load, 0.0), axis=0)        # [K]
        cap_tot = jnp.sum(jnp.where(
            alive, gctx.state.capacity[:, res_idx], 0.0), axis=0)
        avg = total / jnp.maximum(cap_tot, 1e-9)                    # [K]
        upper = avg * gctx.balance_threshold[res_idx] * caps        # [B,K]
        head_frac = (upper - load) / caps                           # [B,K]
        own = head_frac[:, resources.index(self.resource)]
        score = jnp.min(head_frac, axis=-1) + 1e-3 * own
        return jnp.where(alive_mask(gctx), score, -jnp.inf)

    def relax_weights(self, gctx, placement):
        load = jnp.where(placement.is_leader[:, None],
                         gctx.state.leader_load, gctx.state.follower_load)
        return load[:, self.resource]

    def relax_channel(self, gctx, agg):
        res = self.resource
        avg = avg_alive_util_fraction(gctx, agg, res)
        cap = gctx.state.capacity[:, res]
        return agg.broker_load[:, res], avg * cap, jnp.maximum(cap, 1e-9)

    def dst_cumulative_slack(self, gctx, placement, agg, cand_load, is_lead_cand):
        upper, _, _ = self._bounds(gctx, agg)
        return cand_load[:, self.resource], upper - agg.broker_load[:, self.resource]

    def src_cumulative_slack(self, gctx, placement, agg, cand_load, is_lead_cand):
        _, lower, lower_active = self._bounds(gctx, agg)
        load = agg.broker_load[:, self.resource]
        slack = jnp.where(lower_active, load - lower, jnp.inf)
        return cand_load[:, self.resource], slack

    # ------------------------------------------------------------ swap phase
    # ResourceDistributionGoal.java:543-725: when no broker has one-way
    # headroom, exchange a heavy replica on an over/above-average broker with
    # a lighter one on an under/below-average broker — only the load DELTA
    # transfers, so bands that reject any full replica move can still accept
    # a swap.

    def _swap_base_mask(self, gctx, placement):
        state = gctx.state
        return (state.valid & ~gctx.replica_excluded
                & ~currently_offline(gctx, placement))

    def swap_out_score(self, gctx, placement, agg, salt):
        """Shedding-side tile: replicas on above-average brokers, with each
        broker's expected tile share proportional to how far above average it
        sits (gap-weighted random interleave, reseeded per round) and a mild
        heaviness tilt."""
        res = self.resource
        avg = avg_alive_util_fraction(gctx, agg, res)
        cap = jnp.maximum(gctx.state.capacity[:, res], 1e-9)
        load = agg.broker_load[:, res]
        hot = (load > avg * cap) & alive_mask(gctx)
        height = jnp.maximum(load / cap - avg, 0.0)
        prio = self.replica_priority(gctx, placement, agg)
        b = placement.broker
        cand = hot[b] & self._swap_base_mask(gctx, placement)
        # Gap-weighted random interleave: each replica draws
        # height[broker] * U(0,1), so a broker's expected tile share grows
        # with how far above average it sits WITHOUT the worst broker
        # monopolizing the tile (a deterministic gap bonus collapses the
        # 1024-slot tile onto ~3 brokers at north-star scale; a binary tier
        # starves the worst ones — both measured).  Within the tile, pair
        # choice is swap_cost's argmin, so per-replica ordering can be
        # random; a mild heaviness tilt keeps deltas meaningful.
        r = jnp.arange(gctx.state.num_replicas_padded)
        u = 0.25 + 0.75 * hash01(r + salt * 7919, 1.0)
        tilt = 1.0 + prio / jnp.maximum(jnp.max(prio), 1e-9)
        return jnp.where(cand, height[b] * u * tilt, NEG_INF)

    def swap_in_score(self, gctx, placement, agg, salt):
        """Receiving-side tile: replicas on below-average brokers, with each
        broker's expected tile share proportional to how far below average it
        sits (gap-weighted random interleave, reseeded per round; pair choice
        within the tile is swap_cost's argmin)."""
        res = self.resource
        avg = avg_alive_util_fraction(gctx, agg, res)
        cap = jnp.maximum(gctx.state.capacity[:, res], 1e-9)
        load = agg.broker_load[:, res]
        cold = (load < avg * cap) & alive_mask(gctx)
        depth = jnp.maximum(avg - load / cap, 0.0)
        b = placement.broker
        cand = cold[b] & self._swap_base_mask(gctx, placement)
        # Gap-weighted random interleave (see swap_out_score).
        r = jnp.arange(gctx.state.num_replicas_padded)
        u = 0.25 + 0.75 * hash01(r + salt * 7919, 1.0)
        return jnp.where(cand, depth[b] * u, NEG_INF)

    def _swap_after(self, gctx, placement, agg, r_out, r_in):
        """(delta, b_out, b_in, load-after both sides) for the pair tile."""
        res = self.resource
        lo = replica_role_load(gctx, placement, r_out)[..., res]
        li = replica_role_load(gctx, placement, r_in)[..., res]
        delta = lo - li
        b_out = placement.broker[jnp.asarray(r_out)]
        b_in = placement.broker[jnp.asarray(r_in)]
        out_after = agg.broker_load[b_out, res] - delta
        in_after = agg.broker_load[b_in, res] + delta
        return delta, b_out, b_in, out_after, in_after

    def swap_ok(self, gctx, placement, agg, r_out, r_in):
        res = self.resource
        upper, lower, lower_active = self._bounds(gctx, agg)
        delta, b_out, b_in, out_after, in_after = self._swap_after(
            gctx, placement, agg, r_out, r_in)
        over_out = agg.broker_load[b_out, res] > upper[b_out]
        under_in = (agg.broker_load[b_in, res] < lower[b_in]) & lower_active
        helps = over_out | under_in
        ok = (delta > 0) & helps
        ok = ok & (in_after <= upper[b_in])
        ok = ok & jnp.where(lower_active, out_after >= lower[b_out], True)
        return ok

    def swap_cost(self, gctx, placement, agg, r_out, r_in):
        """Residual capacity-normalized deviation of both ends from the mean."""
        res = self.resource
        avg = avg_alive_util_fraction(gctx, agg, res)
        _, b_out, b_in, out_after, in_after = self._swap_after(
            gctx, placement, agg, r_out, r_in)
        cap_out = jnp.maximum(gctx.state.capacity[b_out, res], 1e-9)
        cap_in = jnp.maximum(gctx.state.capacity[b_in, res], 1e-9)
        return (jnp.abs(out_after / cap_out - avg)
                + jnp.abs(in_after / cap_in - avg))

    def swap_cumulative_slack(self, gctx, placement, agg, d_load, d_pot, d_lbi, d_lead):
        res = self.resource
        upper, lower, lower_active = self._bounds(gctx, agg)
        load = agg.broker_load[:, res]
        low_slack = jnp.where(lower_active, load - lower,
                              jnp.full_like(load, jnp.inf))
        return d_load[:, res], upper - load, low_slack

    def leadership_cumulative_slack(self, gctx, placement, agg, f, old):
        """Mirrors accept_leadership_move: positive deltas are held to the
        upper band (the pairwise check's only bound); DISK is leadership-
        neutral exactly as the pairwise acceptance waives it."""
        res = self.resource
        if not self.uses_leadership_moves and res != Resource.NW_IN:
            return None
        state = gctx.state
        dg = state.leader_load[f, res] - state.follower_load[f, res]
        dl = state.follower_load[old, res] - state.leader_load[old, res]
        upper, _, _ = self._bounds(gctx, agg)
        return dg, dl, upper - agg.broker_load[:, res], None, None

    def accept_swap(self, gctx, placement, agg, r_out, r_in, b_out, b_in):
        """Exact pairwise band check: neither end may leave the band in the
        wrong direction once the DELTA (not the full replica load) moves."""
        res = self.resource
        upper, lower, lower_active = self._bounds(gctx, agg)
        delta, _, _, out_after, in_after = self._swap_after(
            gctx, placement, agg, r_out, r_in)
        in_ok = (in_after <= upper[b_in]) | (delta <= 0)
        out_ok = jnp.where(lower_active,
                           (out_after >= lower[b_out]) | (delta <= 0), True)
        # delta < 0 mirrors: load flows b_in -> b_out.
        out_ok2 = (out_after <= upper[b_out]) | (delta >= 0)
        in_ok2 = jnp.where(lower_active,
                           (in_after >= lower[b_in]) | (delta >= 0), True)
        return in_ok & out_ok & out_ok2 & in_ok2

    # ------------------------------------------------------ leadership phase

    def _leader_broker_of(self, gctx, placement, f):
        lead = current_leader_of(gctx, placement, gctx.state.partition[jnp.asarray(f)])
        return placement.broker[jnp.maximum(lead, 0)], lead >= 0

    def leadership_candidate_score(self, gctx, placement, agg):
        """Followers whose leader sits on an over-band broker."""
        res = self.resource
        state = gctx.state
        over = self._over_brokers(gctx, agg)
        f = jnp.arange(state.num_replicas_padded)
        lb, has = self._leader_broker_of(gctx, placement, f)
        gain = state.leader_load[:, res] - state.follower_load[:, res]
        cand = (has & over[lb] & ~placement.is_leader & state.valid
                & ~currently_offline(gctx, placement) & ~gctx.replica_excluded & (gain > 0))
        return jnp.where(cand, gain, NEG_INF)

    def leadership_self_ok(self, gctx, placement, agg, f):
        res = self.resource
        upper, _, _ = self._bounds(gctx, agg)
        f = jnp.asarray(f)
        delta = gctx.state.leader_load[f, res] - gctx.state.follower_load[f, res]
        b = placement.broker[f]
        return agg.broker_load[b, res] + delta <= upper[b]

    def accept_leadership_move(self, gctx, placement, agg, f):
        res = self.resource
        if not self.uses_leadership_moves and res != Resource.NW_IN:
            # DISK unaffected by leadership.
            return jnp.broadcast_to(jnp.asarray(True), jnp.shape(f))
        upper, lower, lower_active = self._bounds(gctx, agg)
        f = jnp.asarray(f)
        delta = gctx.state.leader_load[f, res] - gctx.state.follower_load[f, res]
        b = placement.broker[f]
        after = agg.broker_load[b, res] + delta
        return (after <= upper[b]) | (delta <= 0)

    # ------------------------------------------------------------ pull phase

    def pull_dst_mask(self, gctx, placement, agg):
        res = self.resource
        _, lower, lower_active = self._bounds(gctx, agg)
        under = (agg.broker_load[:, res] < lower) & alive_mask(gctx)
        return under & lower_active

    def pull_dst_prune_score(self, gctx, placement, agg):
        """Neediest under-band brokers first (deficit to the lower bound)."""
        _, lower, lower_active = self._bounds(gctx, agg)
        deficit = lower - agg.broker_load[:, self.resource]
        return jnp.where(alive_mask(gctx) & lower_active, deficit, -jnp.inf)

    def pull_candidate_score(self, gctx, placement, agg):
        """Pull from brokers above cluster-average utilization."""
        res = self.resource
        state = gctx.state
        avg = avg_alive_util_fraction(gctx, agg, res)
        src_hot = agg.broker_load[:, res] > avg * state.capacity[:, res]
        prio = self.replica_priority(gctx, placement, agg)
        cand = (src_hot[placement.broker] & state.valid & ~currently_offline(gctx, placement)
                & ~gctx.replica_excluded)
        return jnp.where(cand, prio, NEG_INF)

    # -------------------------------------------------------------- metrics

    def stats_metric(self, gctx, placement, agg):
        """Utilization-fraction stdev over alive brokers (the comparator at
        ResourceDistributionGoal.java:977-1008 compares stdev)."""
        res = self.resource
        alive = alive_mask(gctx)
        frac = agg.broker_load[:, res] / jnp.maximum(gctx.state.capacity[:, res], 1e-9)
        n = jnp.maximum(jnp.sum(alive), 1)
        mean = jnp.sum(jnp.where(alive, frac, 0.0)) / n
        var = jnp.sum(jnp.where(alive, (frac - mean) ** 2, 0.0)) / n
        return jnp.sqrt(var)


class CpuUsageDistributionGoal(ResourceDistributionGoal):
    def __init__(self):
        super().__init__(Resource.CPU, "CpuUsageDistributionGoal")


class NetworkInboundUsageDistributionGoal(ResourceDistributionGoal):
    def __init__(self):
        super().__init__(Resource.NW_IN, "NetworkInboundUsageDistributionGoal")


class NetworkOutboundUsageDistributionGoal(ResourceDistributionGoal):
    def __init__(self):
        super().__init__(Resource.NW_OUT, "NetworkOutboundUsageDistributionGoal")


class DiskUsageDistributionGoal(ResourceDistributionGoal):
    """Broker-level disk balance (reference DiskUsageDistributionGoal.java —
    the non-kafka-assigner subclass balances % disk usage across brokers)."""

    def __init__(self):
        super().__init__(Resource.DISK, "DiskUsageDistributionGoal")


class PotentialNwOutGoal(Goal):
    """Cap *potential* network-out — NW_OUT if the broker led everything it
    hosts — under the hard NW_OUT capacity (PotentialNwOutGoal.java)."""

    name = "PotentialNwOutGoal"
    is_hard = False
    multi_accept_safe = True
    multi_swap_safe = True
    multi_leadership_safe = True   # potential NW-out counts every replica as-if-leader

    def _limit(self, gctx, b):
        return (gctx.capacity_threshold[Resource.NW_OUT]
                * gctx.state.capacity[b, Resource.NW_OUT])

    def violated_brokers(self, gctx, placement, agg):
        b = jnp.arange(gctx.state.num_brokers_padded)
        return (agg.potential_nw_out > self._limit(gctx, b)) & alive_mask(gctx)

    def replica_priority(self, gctx, placement, agg):
        return gctx.state.leader_load[:, Resource.NW_OUT]

    def self_ok(self, gctx, placement, agg, r, dst):
        return self.accept_replica_move(gctx, placement, agg, r, dst)

    def accept_replica_move(self, gctx, placement, agg, r, dst):
        pot = gctx.state.leader_load[jnp.asarray(r), Resource.NW_OUT]
        after = agg.potential_nw_out[dst] + pot
        # Accept if dst stays under its potential limit, or the cluster is
        # already hopeless there and the move doesn't originate from this goal
        # (mirrors PotentialNwOutGoal acceptance: reject only when dst becomes
        # newly violated).
        was_over = agg.potential_nw_out[dst] > self._limit(gctx, dst)
        return (after <= self._limit(gctx, dst)) | was_over & (pot <= 0)

    def dst_cost(self, gctx, placement, agg, r, dst):
        pot = gctx.state.leader_load[jnp.asarray(r), Resource.NW_OUT]
        return (agg.potential_nw_out[dst] + pot) / jnp.maximum(
            gctx.state.capacity[dst, Resource.NW_OUT], 1e-9)

    def dst_cumulative_slack(self, gctx, placement, agg, cand_load, is_lead_cand):
        b = jnp.arange(gctx.state.num_brokers_padded)
        # Marker weight: the solver substitutes the candidates' potential
        # (leader-role NW_OUT regardless of current role).
        return ("potential_nw_out", self._limit(gctx, b) - agg.potential_nw_out)

    def swap_cumulative_slack(self, gctx, placement, agg, d_load, d_pot, d_lbi, d_lead):
        b = jnp.arange(gctx.state.num_brokers_padded)
        return d_pot, self._limit(gctx, b) - agg.potential_nw_out, None

    def accept_swap(self, gctx, placement, agg, r_out, r_in, b_out, b_in):
        """Only the potential-NW-out DELTA lands on each end."""
        d = (gctx.state.leader_load[jnp.asarray(r_out), Resource.NW_OUT]
             - gctx.state.leader_load[jnp.asarray(r_in), Resource.NW_OUT])
        in_ok = (agg.potential_nw_out[b_in] + d <= self._limit(gctx, b_in)) | (d <= 0)
        out_ok = (agg.potential_nw_out[b_out] - d <= self._limit(gctx, b_out)) | (d >= 0)
        return in_ok & out_ok

    def stats_metric(self, gctx, placement, agg):
        b = jnp.arange(gctx.state.num_brokers_padded)
        excess = jnp.maximum(agg.potential_nw_out - self._limit(gctx, b), 0.0)
        return jnp.sum(jnp.where(alive_mask(gctx), excess, 0.0))


class LeaderBytesInDistributionGoal(Goal):
    """Even out leader bytes-in across brokers
    (LeaderBytesInDistributionGoal.java — balances only above the mean)."""

    name = "LeaderBytesInDistributionGoal"
    is_hard = False
    uses_replica_moves = False
    uses_leadership_moves = True
    multi_accept_safe = True
    multi_swap_safe = True
    multi_leadership_safe = True

    def _limit(self, gctx, agg):
        alive = alive_mask(gctx)
        n = jnp.maximum(jnp.sum(alive), 1)
        avg = jnp.sum(jnp.where(alive, agg.leader_bytes_in, 0.0)) / n
        return avg * gctx.balance_threshold[Resource.NW_IN]

    def violated_brokers(self, gctx, placement, agg):
        return (agg.leader_bytes_in > self._limit(gctx, agg)) & alive_mask(gctx)

    def leadership_candidate_score(self, gctx, placement, agg):
        state = gctx.state
        over = self.violated_brokers(gctx, placement, agg)
        f = jnp.arange(state.num_replicas_padded)
        lead = current_leader_of(gctx, placement, state.partition[f])
        lb = placement.broker[jnp.maximum(lead, 0)]
        nw_in = state.leader_load[:, Resource.NW_IN]
        cand = ((lead >= 0) & over[lb] & ~placement.is_leader & state.valid
                & ~currently_offline(gctx, placement) & ~gctx.replica_excluded)
        return jnp.where(cand, nw_in, NEG_INF)

    def leadership_self_ok(self, gctx, placement, agg, f):
        f = jnp.asarray(f)
        limit = self._limit(gctx, agg)
        b = placement.broker[f]
        after = agg.leader_bytes_in[b] + gctx.state.leader_load[f, Resource.NW_IN]
        return after <= limit

    def accept_leadership_move(self, gctx, placement, agg, f):
        f = jnp.asarray(f)
        limit = self._limit(gctx, agg)
        b = placement.broker[f]
        nw_in = gctx.state.leader_load[f, Resource.NW_IN]
        after = agg.leader_bytes_in[b] + nw_in
        was_over = agg.leader_bytes_in[b] > limit
        return (after <= limit) | was_over & (nw_in <= 0)

    def accept_replica_move(self, gctx, placement, agg, r, dst):
        """Leader replica moves carry their bytes-in to dst."""
        r = jnp.asarray(r)
        nw_in = jnp.where(placement.is_leader[r],
                          gctx.state.leader_load[r, Resource.NW_IN], 0.0)
        limit = self._limit(gctx, agg)
        after = agg.leader_bytes_in[dst] + nw_in
        was_over = agg.leader_bytes_in[dst] > limit
        return (after <= limit) | was_over & (nw_in <= 0)

    def dst_cumulative_slack(self, gctx, placement, agg, cand_load, is_lead_cand):
        limit = self._limit(gctx, agg)
        # weight = leader bytes-in carried only by LEADER candidates; the
        # solver multiplies by is_lead_cand via the special marker below.
        return ("leader_nw_in", limit - agg.leader_bytes_in)

    def swap_cumulative_slack(self, gctx, placement, agg, d_load, d_pot, d_lbi, d_lead):
        return d_lbi, self._limit(gctx, agg) - agg.leader_bytes_in, None

    def leadership_cumulative_slack(self, gctx, placement, agg, f, old):
        nw = gctx.state.leader_load[:, Resource.NW_IN]
        return (nw[f], -nw[old],
                self._limit(gctx, agg) - agg.leader_bytes_in, None, None)

    def accept_swap(self, gctx, placement, agg, r_out, r_in, b_out, b_in):
        """Only the leader-bytes-in DELTA lands on each end."""
        r_out = jnp.asarray(r_out)
        r_in = jnp.asarray(r_in)
        lbi_out = jnp.where(placement.is_leader[r_out],
                            gctx.state.leader_load[r_out, Resource.NW_IN], 0.0)
        lbi_in = jnp.where(placement.is_leader[r_in],
                           gctx.state.leader_load[r_in, Resource.NW_IN], 0.0)
        d = lbi_out - lbi_in
        limit = self._limit(gctx, agg)
        in_ok = (agg.leader_bytes_in[b_in] + d <= limit) | (d <= 0)
        out_ok = (agg.leader_bytes_in[b_out] - d <= limit) | (d >= 0)
        return in_ok & out_ok

    def stats_metric(self, gctx, placement, agg):
        alive = alive_mask(gctx)
        excess = jnp.maximum(agg.leader_bytes_in - self._limit(gctx, agg), 0.0)
        return jnp.sum(jnp.where(alive, excess, 0.0))
