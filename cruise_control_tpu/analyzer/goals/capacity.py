"""Hard capacity goals.

Reference: ``analyzer/goals/CapacityGoal.java:40-466`` (+ the four resource
subclasses), ``ReplicaCapacityGoal.java``, ``IntraBrokerDiskCapacityGoal.java``.

A broker (and, for host-scoped resources, its host) must stay under
``capacity_threshold[res] * capacity``.  As kernels: violation = util over
limit; self_ok = destination stays under limit after the move; acceptance =
identical predicate applied to later goals' actions.
"""

from __future__ import annotations

import jax.numpy as jnp

from cruise_control_tpu.analyzer.context import (
    Aggregates,
    GoalContext,
    replica_role_load,
)
from cruise_control_tpu.analyzer.goals.base import Goal, NEG_INF, alive_mask
from cruise_control_tpu.common.resources import IS_HOST_RESOURCE, Resource
from cruise_control_tpu.model.state import Placement


class CapacityGoal(Goal):
    """One resource's hard utilization cap (CapacityGoal.java:40-466)."""

    is_hard = True
    multi_accept_safe = True
    multi_swap_safe = True
    multi_leadership_safe = True
    resource: int = Resource.DISK

    def __init__(self, resource: int, name: str):
        self.resource = int(resource)
        self.name = name

    def _limit(self, gctx: GoalContext, b):
        return gctx.capacity_threshold[self.resource] * gctx.state.capacity[b, self.resource]

    def _host_limit(self, gctx: GoalContext, h):
        return gctx.capacity_threshold[self.resource] * gctx.host_capacity[h, self.resource]

    def violated_brokers(self, gctx, placement, agg):
        res = self.resource
        over = agg.broker_load[:, res] > self._limit(gctx, jnp.arange(
            gctx.state.num_brokers_padded))
        if IS_HOST_RESOURCE[res]:
            host_over = agg.host_load[:, res] > (
                gctx.capacity_threshold[res] * gctx.host_capacity[:, res])
            over = over | host_over[gctx.state.host]
        return over & alive_mask(gctx)

    def replica_priority(self, gctx, placement, agg):
        load = jnp.where(placement.is_leader[:, None],
                         gctx.state.leader_load, gctx.state.follower_load)
        return load[:, self.resource]

    def self_ok(self, gctx, placement, agg, r, dst):
        return self.accept_replica_move(gctx, placement, agg, r, dst)

    # NOTE: an own-resource dst_cost + hard-cap dst_prune_score were
    # measured here and REVERTED: CpuCapacityGoal's round got 190 ms
    # cheaper, but the single-resource placement of its ~4K moves cost
    # CpuUsageDistributionGoal two extra rounds downstream (+380 ms) at
    # north-star scale — the generic all-resource emptiest-after-move cost
    # is load-bearing for the goals solved later.

    def accept_replica_move(self, gctx, placement, agg, r, dst):
        res = self.resource
        load = replica_role_load(gctx, placement, r)[..., res]
        b_ok = agg.broker_load[dst, res] + load <= self._limit(gctx, dst)
        if not IS_HOST_RESOURCE[res]:
            return b_ok
        h = gctx.state.host[dst]
        same_host = gctx.state.host[placement.broker[r]] == h
        h_after = agg.host_load[h, res] + load * (~same_host)
        return b_ok & (h_after <= self._host_limit(gctx, h))

    def accept_leadership_move(self, gctx, placement, agg, f):
        """Promotion shifts load onto f's broker for CPU/NW_OUT."""
        res = self.resource
        if res not in (Resource.CPU, Resource.NW_OUT):
            return jnp.broadcast_to(jnp.asarray(True), jnp.shape(f))
        delta = (gctx.state.leader_load[f, res] - gctx.state.follower_load[f, res])
        b = placement.broker[f]
        b_ok = agg.broker_load[b, res] + delta <= self._limit(gctx, b)
        h = gctx.state.host[b]
        h_ok = agg.host_load[h, res] + delta <= self._host_limit(gctx, h)
        return b_ok & h_ok

    def dst_cost(self, gctx, placement, agg, r, dst):
        res = self.resource
        load = replica_role_load(gctx, placement, r)[..., res]
        after = agg.broker_load[dst, res] + load
        return after / jnp.maximum(gctx.state.capacity[dst, res], 1e-9)

    def dst_cumulative_slack(self, gctx, placement, agg, cand_load, is_lead_cand):
        res = self.resource
        limit = gctx.capacity_threshold[res] * gctx.state.capacity[:, res]
        return cand_load[:, res], limit - agg.broker_load[:, res]

    def host_cumulative_slack(self, gctx, placement, agg, cand_load, is_lead_cand):
        res = self.resource
        if not IS_HOST_RESOURCE[res]:
            return None
        limit = gctx.capacity_threshold[res] * gctx.host_capacity[:, res]
        return cand_load[:, res], limit - agg.host_load[:, res]

    def swap_cumulative_slack(self, gctx, placement, agg, d_load, d_pot, d_lbi, d_lead):
        res = self.resource
        limit = gctx.capacity_threshold[res] * gctx.state.capacity[:, res]
        return d_load[:, res], limit - agg.broker_load[:, res], None

    def leadership_cumulative_slack(self, gctx, placement, agg, f, old):
        res = self.resource
        if res not in (Resource.CPU, Resource.NW_OUT):
            return None
        state = gctx.state
        dg = state.leader_load[f, res] - state.follower_load[f, res]
        dl = state.follower_load[old, res] - state.leader_load[old, res]
        limit = gctx.capacity_threshold[res] * state.capacity[:, res]
        up_h = (gctx.capacity_threshold[res] * gctx.host_capacity[:, res]
                - agg.host_load[:, res]) if IS_HOST_RESOURCE[res] else None
        return dg, dl, limit - agg.broker_load[:, res], None, up_h

    def swap_host_cumulative_slack(self, gctx, placement, agg, d_load):
        res = self.resource
        if not IS_HOST_RESOURCE[res]:
            return None
        limit = gctx.capacity_threshold[res] * gctx.host_capacity[:, res]
        return d_load[:, res], limit - agg.host_load[:, res]

    def accept_swap(self, gctx, placement, agg, r_out, r_in, b_out, b_in):
        """Exact: only the load DELTA lands on each end (the directional
        default would double-count and veto swaps near the cap)."""
        res = self.resource
        delta = (replica_role_load(gctx, placement, r_out)[..., res]
                 - replica_role_load(gctx, placement, r_in)[..., res])
        b_ok = ((agg.broker_load[b_in, res] + delta <= self._limit(gctx, b_in))
                | (delta <= 0))
        b_ok = b_ok & ((agg.broker_load[b_out, res] - delta
                        <= self._limit(gctx, b_out)) | (delta >= 0))
        if not IS_HOST_RESOURCE[res]:
            return b_ok
        h_in = gctx.state.host[b_in]
        h_out = gctx.state.host[b_out]
        same = h_in == h_out
        h_ok_in = ((agg.host_load[h_in, res] + delta <= self._host_limit(gctx, h_in))
                   | (delta <= 0))
        h_ok_out = ((agg.host_load[h_out, res] - delta <= self._host_limit(gctx, h_out))
                    | (delta >= 0))
        return b_ok & (same | (h_ok_in & h_ok_out))

    def stats_metric(self, gctx, placement, agg):
        """Total over-limit load (lower better, 0 == satisfied)."""
        res = self.resource
        limit = gctx.capacity_threshold[res] * gctx.state.capacity[:, res]
        excess = jnp.maximum(agg.broker_load[:, res] - limit, 0.0)
        return jnp.sum(jnp.where(alive_mask(gctx), excess, 0.0))


class CpuCapacityGoal(CapacityGoal):
    def __init__(self):
        super().__init__(Resource.CPU, "CpuCapacityGoal")


class NetworkInboundCapacityGoal(CapacityGoal):
    def __init__(self):
        super().__init__(Resource.NW_IN, "NetworkInboundCapacityGoal")


class NetworkOutboundCapacityGoal(CapacityGoal):
    def __init__(self):
        super().__init__(Resource.NW_OUT, "NetworkOutboundCapacityGoal")


class DiskCapacityGoal(CapacityGoal):
    def __init__(self):
        super().__init__(Resource.DISK, "DiskCapacityGoal")


class ReplicaCapacityGoal(Goal):
    """Max replicas per broker (ReplicaCapacityGoal.java).

    Dead brokers are violated by definition (their replicas must vacate);
    alive brokers by count > ``max_replicas_per_broker``.
    """

    name = "ReplicaCapacityGoal"
    is_hard = True
    multi_accept_safe = True
    multi_swap_safe = True          # swaps are replica-count-neutral
    multi_leadership_safe = True    # promotions are replica-count-neutral

    def violated_brokers(self, gctx, placement, agg):
        alive = alive_mask(gctx)
        over = agg.replica_counts > gctx.max_replicas_per_broker
        dead_with_replicas = (~gctx.state.alive) & gctx.state.broker_valid & (
            agg.replica_counts > 0)
        return (over & alive) | dead_with_replicas

    def replica_priority(self, gctx, placement, agg):
        # Light replicas first: vacating over-count brokers moves minimal load.
        load = jnp.where(placement.is_leader[:, None],
                         gctx.state.leader_load, gctx.state.follower_load)
        return -jnp.sum(load, axis=-1)

    def self_ok(self, gctx, placement, agg, r, dst):
        return self.accept_replica_move(gctx, placement, agg, r, dst)

    def accept_replica_move(self, gctx, placement, agg, r, dst):
        del r
        return agg.replica_counts[dst] + 1 <= gctx.max_replicas_per_broker

    def dst_cumulative_slack(self, gctx, placement, agg, cand_load, is_lead_cand):
        slack = (gctx.max_replicas_per_broker - agg.replica_counts).astype(jnp.float32)
        return jnp.ones(cand_load.shape[0], dtype=jnp.float32), slack

    def accept_swap(self, gctx, placement, agg, r_out, r_in, b_out, b_in):
        """Swaps are count-neutral."""
        return jnp.broadcast_to(jnp.asarray(True), jnp.broadcast_shapes(
            jnp.shape(r_out), jnp.shape(r_in)))

    def dst_cost(self, gctx, placement, agg, r, dst):
        del r
        return agg.replica_counts[dst].astype(jnp.float32)

    def stats_metric(self, gctx, placement, agg):
        over = jnp.maximum(agg.replica_counts - gctx.max_replicas_per_broker, 0)
        return jnp.sum(jnp.where(alive_mask(gctx), over, 0)).astype(jnp.float32)


class IntraBrokerDiskCapacityGoal(Goal):
    """Per-logdir capacity inside JBOD brokers (IntraBrokerDiskCapacityGoal.java).

    Uses intra-broker disk moves: violation = disk load over
    ``capacity_threshold[DISK] * disk_capacity``; fix = move replicas to a
    sibling disk with headroom.  Solved by the solver's intra-disk phase.
    """

    name = "IntraBrokerDiskCapacityGoal"
    is_hard = True
    uses_replica_moves = False
    intra_disk = True
    # Inter-broker swaps land on each side's emptiest logdir; the solver's
    # JBOD cumulative fill guard bounds multi-swap arrivals per logdir.
    multi_swap_safe = True
    multi_leadership_safe = True    # leadership does not move data between disks

    def violated_disks(self, gctx, placement, agg):
        limit = gctx.capacity_threshold[Resource.DISK] * gctx.state.disk_capacity
        return (agg.disk_load > limit) & gctx.state.disk_alive

    def violated_brokers(self, gctx, placement, agg):
        return jnp.any(self.violated_disks(gctx, placement, agg), axis=-1)

    def disk_candidate_score(self, gctx, placement, agg):
        """f32[R]: replicas on over-limit or dead disks, largest first."""
        state = gctx.state
        vd = self.violated_disks(gctx, placement, agg)
        on_bad = vd[placement.broker, placement.disk]
        dead_disk = ~state.disk_alive[placement.broker, placement.disk]
        size = state.leader_load[:, Resource.DISK]
        cand = (on_bad | dead_disk) & state.valid
        return jnp.where(cand, size, NEG_INF)

    def disk_move_ok(self, gctx, placement, agg, r, d):
        """bool: replica r may move to disk d of its own broker."""
        b = placement.broker[r]
        size = gctx.state.leader_load[r, Resource.DISK]
        limit = gctx.capacity_threshold[Resource.DISK] * gctx.state.disk_capacity[b, d]
        return (gctx.state.disk_alive[b, d] & (d != placement.disk[r])
                & (agg.disk_load[b, d] + size <= limit))

    def stats_metric(self, gctx, placement, agg):
        limit = gctx.capacity_threshold[Resource.DISK] * gctx.state.disk_capacity
        excess = jnp.maximum(agg.disk_load - limit, 0.0) * gctx.state.disk_alive
        return jnp.sum(excess)
