"""Placement diff → execution proposals.

Reference: ``analyzer/AnalyzerUtils.getDiff`` :50-117 — compare the initial
replica distribution + leadership against the optimized ClusterModel and emit
one ``ExecutionProposal`` per changed partition, new leader first.

Host-side and vectorized with numpy: one pass over the changed-partition set,
no per-replica Python in the common (unchanged) case.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from cruise_control_tpu.common.actions import (
    ExecutionProposal,
    ReplicaPlacementInfo,
    TopicPartition,
)
from cruise_control_tpu.common.resources import Resource
from cruise_control_tpu.model.state import ClusterMeta, ClusterState, Placement


def diff_proposals(
    state: ClusterState,
    initial: Placement,
    final: Placement,
    meta: ClusterMeta,
    provenance: Optional[Dict[int, dict]] = None,
) -> List[ExecutionProposal]:
    """Proposals for every partition whose placement or leadership changed.

    ``provenance`` (execution observatory) maps partition id → the
    optimizer's per-move provenance record; when given, each proposal is
    stamped with its partition's record."""
    n = meta.num_replicas
    part = np.asarray(state.partition)[:n]
    pos = np.asarray(state.pos)[:n]
    disk_size = np.asarray(state.leader_load)[:n, Resource.DISK]
    has_disks = np.asarray(state.disk_capacity).shape[1] > 1

    b0 = np.asarray(initial.broker)[:n]
    b1 = np.asarray(final.broker)[:n]
    d0 = np.asarray(initial.disk)[:n]
    d1 = np.asarray(final.disk)[:n]
    l0 = np.asarray(initial.is_leader)[:n]
    l1 = np.asarray(final.is_leader)[:n]

    changed = (b0 != b1) | (l0 != l1) | (has_disks & (d0 != d1))
    changed_parts = np.unique(part[changed])
    if changed_parts.size == 0:
        return []

    # Group replica rows by partition, ordered by (partition, pos).
    order = np.lexsort((pos, part))
    sorted_part = part[order]
    starts = np.searchsorted(sorted_part, changed_parts, side="left")
    ends = np.searchsorted(sorted_part, changed_parts, side="right")

    broker_ids = np.asarray(meta.broker_ids)
    # The assembly loop below is pure Python over ~#changed partitions; at
    # north-star scale that is tens of thousands of iterations, so every
    # per-replica numpy scalar index matters.  Compact the sorted view down
    # to ONLY the changed partitions' rows first (a goal pass that touches 10
    # partitions must not pay O(R) Python-list conversion), then precompute
    # each field as a Python list in one vectorized pass and intern the
    # (broker, logdir) info objects (a few thousand distinct values vs 100K+
    # replicas).  Per-partition sizes come from reduceat over the compacted
    # view (sentinel keeps the final boundary valid).
    lengths = ends - starts
    bounds = np.zeros(part.size + 1, dtype=np.int64)
    np.add.at(bounds, starts, 1)
    np.add.at(bounds, ends, -1)
    in_seg = np.cumsum(bounds[:-1]) > 0
    sel = order[in_seg]                      # changed partitions' rows, sorted
    new_ends = np.cumsum(lengths)
    new_starts = (new_ends - lengths).tolist()
    new_ends = new_ends.tolist()

    gb0 = broker_ids[b0[sel]].tolist()
    gb1 = broker_ids[b1[sel]].tolist()
    ld0 = d0[sel].tolist() if has_disks else None
    ld1 = d1[sel].tolist() if has_disks else None
    ll0 = l0[sel].tolist()
    ll1 = l1[sel].tolist()
    csize = disk_size[sel]
    pairs = np.stack([new_starts, new_ends], axis=1).ravel()
    sorted_sizes = np.append(csize, csize.dtype.type(0))
    sizes = np.maximum.reduceat(sorted_sizes, pairs)[::2].tolist()

    info_cache = {}

    def info(bid: int, dk) -> ReplicaPlacementInfo:
        key = (bid, dk)
        r = info_cache.get(key)
        if r is None:
            r = info_cache[key] = ReplicaPlacementInfo(bid, dk)
        return r

    topics = meta.topics
    partitions = meta.partitions
    proposals: List[ExecutionProposal] = []
    # ``rows`` below are POSITIONS into the compacted per-field lists.
    for p, s, e, size in zip(changed_parts.tolist(), new_starts,
                             new_ends, sizes):
        rows = range(s, e)
        t_idx, p_num = partitions[p]
        tp = TopicPartition(topics[t_idx], p_num)

        if has_disks:
            old_list = [info(gb0[r], ld0[r]) for r in rows]
        else:
            old_list = [info(gb0[r], None) for r in rows]
        old_leader = old_list[0]
        for i, r in enumerate(rows):
            if ll0[r]:
                old_leader = old_list[i]
                break

        lead_row = rows[0]
        for r in rows:
            if ll1[r]:
                lead_row = r
                break
        if has_disks:
            new_list = ([info(gb1[lead_row], ld1[lead_row])]
                        + [info(gb1[r], ld1[r]) for r in rows if r != lead_row])
        else:
            new_list = ([info(gb1[lead_row], None)]
                        + [info(gb1[r], None) for r in rows if r != lead_row])

        proposals.append(ExecutionProposal(
            topic_partition=tp,
            partition_size=float(size),
            old_leader=old_leader,
            old_replicas=tuple(old_list),
            new_replicas=tuple(new_list),
            provenance=provenance.get(p) if provenance else None,
        ))
    return proposals
