"""Placement diff → execution proposals.

Reference: ``analyzer/AnalyzerUtils.getDiff`` :50-117 — compare the initial
replica distribution + leadership against the optimized ClusterModel and emit
one ``ExecutionProposal`` per changed partition, new leader first.

Host-side and vectorized with numpy: one pass over the changed-partition set,
no per-replica Python in the common (unchanged) case.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from cruise_control_tpu.common.actions import (
    ExecutionProposal,
    ReplicaPlacementInfo,
    TopicPartition,
)
from cruise_control_tpu.common.resources import Resource
from cruise_control_tpu.model.state import ClusterMeta, ClusterState, Placement


def diff_proposals(
    state: ClusterState,
    initial: Placement,
    final: Placement,
    meta: ClusterMeta,
) -> List[ExecutionProposal]:
    """Proposals for every partition whose placement or leadership changed."""
    n = meta.num_replicas
    part = np.asarray(state.partition)[:n]
    pos = np.asarray(state.pos)[:n]
    disk_size = np.asarray(state.leader_load)[:n, Resource.DISK]
    has_disks = np.asarray(state.disk_capacity).shape[1] > 1

    b0 = np.asarray(initial.broker)[:n]
    b1 = np.asarray(final.broker)[:n]
    d0 = np.asarray(initial.disk)[:n]
    d1 = np.asarray(final.disk)[:n]
    l0 = np.asarray(initial.is_leader)[:n]
    l1 = np.asarray(final.is_leader)[:n]

    changed = (b0 != b1) | (l0 != l1) | (has_disks & (d0 != d1))
    changed_parts = np.unique(part[changed])
    if changed_parts.size == 0:
        return []

    # Group replica rows by partition, ordered by (partition, pos).
    order = np.lexsort((pos, part))
    sorted_part = part[order]
    starts = np.searchsorted(sorted_part, changed_parts, side="left")
    ends = np.searchsorted(sorted_part, changed_parts, side="right")

    broker_ids = np.asarray(meta.broker_ids)
    proposals: List[ExecutionProposal] = []
    for p, s, e in zip(changed_parts.tolist(), starts.tolist(), ends.tolist()):
        rows = order[s:e]
        t_idx, p_num = meta.partitions[p]
        tp = TopicPartition(meta.topics[t_idx], p_num)

        def placement_info(r: int, brokers, disks) -> ReplicaPlacementInfo:
            return ReplicaPlacementInfo(
                int(broker_ids[brokers[r]]),
                int(disks[r]) if has_disks else None)

        old_list = [placement_info(r, b0, d0) for r in rows]
        old_leader_rows = [r for r in rows if l0[r]]
        old_leader = (placement_info(old_leader_rows[0], b0, d0)
                      if old_leader_rows else old_list[0])

        new_leader_rows = [r for r in rows if l1[r]]
        lead_row = new_leader_rows[0] if new_leader_rows else rows[0]
        new_list = ([placement_info(lead_row, b1, d1)]
                    + [placement_info(r, b1, d1) for r in rows if r != lead_row])

        proposals.append(ExecutionProposal(
            topic_partition=tp,
            partition_size=float(disk_size[rows].max(initial=0.0)),
            old_leader=old_leader,
            old_replicas=tuple(old_list),
            new_replicas=tuple(new_list),
        ))
    return proposals
