"""Traced optimization context and incremental aggregates.

The reference mutates a ``ClusterModel`` object graph and pushes load deltas up
the replica→broker→host→rack tree on every action
(``model/ClusterModel.java:375-434``).  Here the same bookkeeping is a small
set of dense arrays (``Aggregates``) carried through a ``lax.scan``: applying a
move is a handful of scatter-adds, and every goal predicate is a broadcastable
function of (context, aggregates, replica-index, destination) usable both for
the batched C×B feasibility matrices and for the scalar re-check at apply time.

Key structural trick: partition membership never changes during optimization,
so ``partition_replicas: i32[P, RF_max]`` (replica rows per partition, -1 pad)
is precomputed once per snapshot.  "Does broker b already hold partition p" is
then an RF-wide gather instead of a P×B matrix — the reason this scales to
1M replicas × 2.6K brokers without materializing replica×broker state.
"""

from __future__ import annotations

from typing import Optional

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np

from cruise_control_tpu.analyzer.constraint import BalancingConstraint
from cruise_control_tpu.analyzer.options import OptimizationOptions
from cruise_control_tpu.common.resources import (
    IS_BROKER_RESOURCE,
    IS_HOST_RESOURCE,
    NUM_RESOURCES,
    Resource,
)
from cruise_control_tpu.model.state import ClusterMeta, ClusterState, Placement
from cruise_control_tpu.ops import broker_channel_sums, pallas_aggregates_enabled

NEG_INF = -jnp.inf


def hash01(a: jnp.ndarray, b) -> jnp.ndarray:
    """Deterministic pseudo-uniform [0,1) from two index/seed arrays
    (broadcast).  The solver's tie-breaking jitter and the swap tiles'
    weighted-random interleave both ride this."""
    x = jnp.sin(jnp.asarray(a).astype(jnp.float32) * 12.9898
                + jnp.asarray(b).astype(jnp.float32) * 78.233)
    v = x * 43758.5453
    return v - jnp.floor(v)


@flax.struct.dataclass
class GoalContext:
    """Per-optimization constants (traced, but never change across rounds)."""

    state: ClusterState
    partition_replicas: jnp.ndarray       # i32[P, RF_max], -1 padded
    host_capacity: jnp.ndarray            # f32[H, 4] sum of alive member broker capacity
    balance_threshold: jnp.ndarray        # f32[4] (>= 1)
    capacity_threshold: jnp.ndarray       # f32[4] (<= 1)
    low_utilization_threshold: jnp.ndarray  # f32[4]
    max_replicas_per_broker: jnp.ndarray  # i32 scalar
    excluded_topics: jnp.ndarray          # bool[T]
    excluded_for_leadership: jnp.ndarray  # bool[B]
    excluded_for_replica_move: jnp.ndarray  # bool[B]
    requested_dst: jnp.ndarray            # bool[B]
    only_move_immigrants: jnp.ndarray     # bool scalar
    # Per-replica precomputed masks.
    replica_excluded: jnp.ndarray         # bool[R]: topic excluded
    # ReplicaDistribution/TopicReplicaDistribution numeric knobs.
    replica_balance_threshold: jnp.ndarray         # f32 scalar
    leader_replica_balance_threshold: jnp.ndarray  # f32 scalar
    topic_replica_balance_threshold: jnp.ndarray   # f32 scalar
    topic_replica_balance_min_gap: jnp.ndarray     # i32 scalar
    min_topic_leaders: jnp.ndarray                 # i32 scalar
    min_leader_topic_mask: jnp.ndarray             # bool[T] topics subject to MinTopicLeaders
    num_racks: int = flax.struct.field(pytree_node=False, default=1)

    @property
    def num_partitions(self) -> int:
        return self.partition_replicas.shape[0]

    @property
    def max_rf(self) -> int:
        return self.partition_replicas.shape[1]

    @property
    def num_hosts(self) -> int:
        return self.host_capacity.shape[0]

    @property
    def num_topics(self) -> int:
        return self.excluded_topics.shape[0]


@flax.struct.dataclass
class Aggregates:
    """Incrementally-maintained cluster aggregates (the scan carry).

    Everything a goal predicate needs at apply time, kept O(B)+O(H)+O(T·B)
    so per-move updates are scatter-adds, never O(R) recomputes.
    """

    broker_load: jnp.ndarray      # f32[B, 4]
    host_load: jnp.ndarray        # f32[H, 4]
    replica_counts: jnp.ndarray   # i32[B]
    leader_counts: jnp.ndarray    # i32[B]
    topic_counts: jnp.ndarray     # i32[T, B]
    topic_leader_counts: jnp.ndarray  # i32[T, B]
    disk_load: jnp.ndarray        # f32[B, D]
    potential_nw_out: jnp.ndarray  # f32[B]
    leader_bytes_in: jnp.ndarray  # f32[B]


def _pad2(n: int, floor: int = 8) -> int:
    """Round up to a power-of-two size class (min ``floor``) so jitted kernels
    recompile only when a dimension crosses a size class, not on every
    snapshot (brokers die, partitions appear)."""
    n = max(n, 1)
    p = floor
    while p < n:
        p *= 2
    return p


def build_context(
    state: ClusterState,
    placement: Placement,
    meta: ClusterMeta,
    constraint: BalancingConstraint,
    options: OptimizationOptions,
) -> GoalContext:
    """Host-side packing of constraint/option tensors for one optimization."""
    b_pad = state.num_brokers_padded

    # partition_replicas from the (host-visible) partition array.
    part = np.asarray(state.partition)
    valid = np.asarray(state.valid)
    num_p = _pad2(meta.num_partitions)
    order = np.argsort(part[valid], kind="stable")
    valid_idx = np.nonzero(valid)[0][order]
    max_rf = 1
    if valid_idx.size:
        counts = np.bincount(part[valid_idx], minlength=num_p)
        max_rf = max(int(counts.max()), 1)
    max_rf = _pad2(max_rf, floor=2)
    pr = np.full((num_p, max_rf), -1, dtype=np.int64)
    slot = np.zeros(len(valid_idx), dtype=np.int64)
    # Slot within partition = running index among same-partition rows
    # (valid_idx is sorted by partition, stable).
    pp = part[valid_idx]
    if len(pp):
        firsts = np.searchsorted(pp, pp, side="left")
        slot = np.arange(len(pp)) - firsts
        pr[pp, slot] = valid_idx

    # Host capacity: sum of alive member brokers' capacity.
    host = np.asarray(state.host)
    alive = np.asarray(state.alive) & np.asarray(state.broker_valid)
    cap = np.asarray(state.capacity)
    num_h = _pad2(meta.num_hosts)
    host_cap = np.zeros((num_h, NUM_RESOURCES), dtype=np.float32)
    np.add.at(host_cap, host[alive], cap[alive])

    num_t = _pad2(meta.num_topics)
    excluded_topics = np.zeros(num_t, dtype=bool)
    excluded_topics[:meta.num_topics] = options.excluded_topic_mask(meta)
    topic_arr = np.asarray(state.topic)
    replica_excluded = excluded_topics[np.clip(topic_arr, 0, num_t - 1)]
    replica_excluded = replica_excluded & valid

    min_leader_topics = np.zeros(num_t, dtype=bool)
    for i, t in enumerate(meta.topics):
        if t in constraint.min_leader_topic_names:
            min_leader_topics[i] = True

    return GoalContext(
        state=state,
        partition_replicas=jnp.asarray(pr, dtype=jnp.int32),
        host_capacity=jnp.asarray(host_cap),
        balance_threshold=jnp.asarray(
            constraint.balance_band(options.is_triggered_by_goal_violation)),
        capacity_threshold=jnp.asarray(constraint.capacity_threshold, dtype=jnp.float32),
        low_utilization_threshold=jnp.asarray(
            constraint.low_utilization_threshold, dtype=jnp.float32),
        max_replicas_per_broker=jnp.asarray(constraint.max_replicas_per_broker, dtype=jnp.int32),
        excluded_topics=jnp.asarray(excluded_topics),
        excluded_for_leadership=jnp.asarray(options.leadership_exclusion_mask(meta, b_pad)),
        excluded_for_replica_move=jnp.asarray(options.replica_move_exclusion_mask(meta, b_pad)),
        requested_dst=jnp.asarray(options.destination_mask(meta, b_pad)),
        only_move_immigrants=jnp.asarray(options.only_move_immigrant_replicas),
        replica_excluded=jnp.asarray(replica_excluded),
        replica_balance_threshold=jnp.asarray(constraint.replica_balance_threshold,
                                              dtype=jnp.float32),
        leader_replica_balance_threshold=jnp.asarray(
            constraint.leader_replica_balance_threshold, dtype=jnp.float32),
        topic_replica_balance_threshold=jnp.asarray(
            constraint.topic_replica_balance_threshold, dtype=jnp.float32),
        topic_replica_balance_min_gap=jnp.asarray(
            constraint.topic_replica_balance_min_gap, dtype=jnp.int32),
        min_topic_leaders=jnp.asarray(constraint.min_topic_leaders_per_broker, dtype=jnp.int32),
        min_leader_topic_mask=jnp.asarray(min_leader_topics),
        num_racks=_pad2(meta.num_racks),
    )


# --------------------------------------------------------------------- loads


def replica_role_load(gctx: GoalContext, placement: Placement, r) -> jnp.ndarray:
    """f32[..., 4]: effective load of replica r in its current role."""
    lead = gctx.state.leader_load[r]
    foll = gctx.state.follower_load[r]
    return jnp.where(placement.is_leader[r][..., None], lead, foll)


def compute_aggregates(gctx: GoalContext, placement: Placement) -> Aggregates:
    """Full recompute (round boundaries); scans update incrementally."""
    state = gctx.state
    b = state.num_brokers_padded
    t = gctx.num_topics
    load = jnp.where(placement.is_leader[:, None], state.leader_load, state.follower_load)
    load = load * state.valid[:, None]
    valid_i = state.valid.astype(jnp.int32)
    leader_i = (state.valid & placement.is_leader).astype(jnp.int32)
    if pallas_aggregates_enabled():
        # TPU kernel path (ops/pallas_aggregate.py): all eight broker-axis
        # channels reduced in ONE pass over the replica stream — one-hot
        # MXU matmuls into a VMEM accumulator instead of XLA's sort-based
        # scatter.  Channel order: 4 resources, valid, leader, potential
        # NW-out, leader bytes-in.
        channels = jnp.concatenate([
            load,
            valid_i[:, None].astype(jnp.float32),
            leader_i[:, None].astype(jnp.float32),
            (state.leader_load[:, Resource.NW_OUT] * state.valid)[:, None],
            (state.leader_load[:, Resource.NW_IN]
             * leader_i.astype(jnp.float32))[:, None],
        ], axis=1)
        sums = broker_channel_sums(channels, placement.broker, b)
        broker_load = sums[:, :4]
        # Counts are exact in f32 up to 2^24 — far beyond padded R.
        replica_counts = sums[:, 4].astype(jnp.int32)
        leader_counts = sums[:, 5].astype(jnp.int32)
        potential = sums[:, 6]
        leader_bytes_in = sums[:, 7]
    else:
        broker_load = jax.ops.segment_sum(load, placement.broker, num_segments=b)
        replica_counts = jax.ops.segment_sum(valid_i, placement.broker, num_segments=b)
        leader_counts = jax.ops.segment_sum(leader_i, placement.broker, num_segments=b)
        potential = jax.ops.segment_sum(
            state.leader_load[:, Resource.NW_OUT] * state.valid,
            placement.broker, num_segments=b)
        leader_bytes_in = jax.ops.segment_sum(
            state.leader_load[:, Resource.NW_IN] * leader_i.astype(jnp.float32),
            placement.broker, num_segments=b)
    host_load = jax.ops.segment_sum(broker_load, state.host, num_segments=gctx.num_hosts)
    flat = state.topic * b + placement.broker
    topic_counts = jax.ops.segment_sum(valid_i, flat, num_segments=t * b).reshape(t, b)
    topic_leader_counts = jax.ops.segment_sum(leader_i, flat, num_segments=t * b).reshape(t, b)
    dflat = placement.broker * state.num_disks_per_broker + placement.disk
    disk_load = jax.ops.segment_sum(
        load[:, Resource.DISK], dflat,
        num_segments=b * state.num_disks_per_broker,
    ).reshape(b, state.num_disks_per_broker)
    return Aggregates(
        broker_load=broker_load, host_load=host_load,
        replica_counts=replica_counts, leader_counts=leader_counts,
        topic_counts=topic_counts, topic_leader_counts=topic_leader_counts,
        disk_load=disk_load, potential_nw_out=potential,
        leader_bytes_in=leader_bytes_in,
    )


def currently_offline(gctx: GoalContext, placement: Placement, r=None):
    """bool: replica sits on a dead broker or dead logdir *under the current
    placement* (unlike ``state.offline``, which is snapshot-time truth —
    replicas already moved to a live broker are no longer offline)."""
    state = gctx.state
    if r is None:
        b = placement.broker
        return state.valid & (~state.alive[b] | ~state.disk_alive[b, placement.disk])
    r = jnp.asarray(r)
    b = placement.broker[r]
    return state.valid[r] & (~state.alive[b] | ~state.disk_alive[b, placement.disk[r]])


# ----------------------------------------------------------- move application


def apply_replica_move(gctx: GoalContext, placement: Placement, agg: Aggregates,
                       r, dst, dst_disk):
    """Scalar convenience wrapper over ``apply_replica_moves_batch`` (one
    source of truth for the nine aggregate updates)."""
    return apply_replica_moves_batch(
        gctx, placement, agg,
        jnp.asarray(r)[None], jnp.asarray(dst)[None], jnp.asarray(dst_disk)[None])


def apply_replica_moves_batch(gctx: GoalContext, placement: Placement,
                              agg: Aggregates, r: jnp.ndarray,
                              dst: jnp.ndarray, dst_disk: jnp.ndarray,
                              keep: Optional[jnp.ndarray] = None):
    """Apply a conflict-free BATCH of inter-broker moves incrementally.

    ``r/dst/dst_disk`` are [C]; rows whose ``dst`` equals the replica's
    current broker are no-ops (their +/- deltas cancel), which is how phases
    encode "not kept".  O(C) scatter-adds instead of the O(R) full
    ``compute_aggregates`` recompute — the per-phase cost at 1M replicas.

    ``keep`` (bool[C], optional) is REQUIRED when ``r`` can contain duplicate
    rows (e.g. the swap phase's shared in-partners): non-kept rows' deltas are
    zeroed and their placement writes dropped, so a duplicate no-op row can
    never clobber a kept row's scatter (duplicate-index ``set`` is
    last-write-wins).  Returns (placement, agg).
    """
    state = gctx.state
    src = placement.broker[r]
    src_disk = placement.disk[r]
    load = replica_role_load(gctx, placement, r)          # [C,4]
    is_lead = placement.is_leader[r]
    topic = state.topic[r]
    pot = state.leader_load[r, Resource.NW_OUT]
    lbi = jnp.where(is_lead, state.leader_load[r, Resource.NW_IN], 0.0)
    inc = is_lead.astype(jnp.int32)
    one = jnp.ones_like(r, dtype=jnp.int32)
    if keep is not None:
        load = load * keep[:, None]
        pot = pot * keep
        lbi = lbi * keep
        inc = inc * keep
        one = one * keep

    broker_load = agg.broker_load.at[src].add(-load).at[dst].add(load)
    host_load = (agg.host_load.at[state.host[src]].add(-load)
                 .at[state.host[dst]].add(load))
    replica_counts = agg.replica_counts.at[src].add(-one).at[dst].add(one)
    leader_counts = agg.leader_counts.at[src].add(-inc).at[dst].add(inc)
    topic_counts = (agg.topic_counts.at[topic, src].add(-one)
                    .at[topic, dst].add(one))
    topic_leader_counts = (agg.topic_leader_counts.at[topic, src].add(-inc)
                           .at[topic, dst].add(inc))
    disk_load = (agg.disk_load.at[src, src_disk].add(-load[:, Resource.DISK])
                 .at[dst, dst_disk].add(load[:, Resource.DISK]))
    potential = agg.potential_nw_out.at[src].add(-pot).at[dst].add(pot)
    leader_bytes_in = agg.leader_bytes_in.at[src].add(-lbi).at[dst].add(lbi)

    if keep is None:
        r_set = r
    else:
        r_set = jnp.where(keep, r, state.num_replicas_padded)
    placement = placement.replace(
        broker=placement.broker.at[r_set].set(dst, mode="drop"),
        disk=placement.disk.at[r_set].set(dst_disk, mode="drop"),
    )
    agg = Aggregates(
        broker_load=broker_load, host_load=host_load,
        replica_counts=replica_counts, leader_counts=leader_counts,
        topic_counts=topic_counts, topic_leader_counts=topic_leader_counts,
        disk_load=disk_load, potential_nw_out=potential,
        leader_bytes_in=leader_bytes_in,
    )
    return placement, agg


def apply_leadership_moves_batch(gctx: GoalContext, placement: Placement,
                                 agg: Aggregates, f: jnp.ndarray,
                                 old: jnp.ndarray, keep: jnp.ndarray,
                                 demote: Optional[jnp.ndarray] = None):
    """Apply a conflict-free batch of promotions (f gains, old loses),
    gated by ``keep`` — non-kept rows contribute zero deltas.  ``demote``
    separately gates the old-leader side (default: same as ``keep``; the
    leaderless-partition case promotes without demoting anyone).  The caller
    has already flipped ``placement.is_leader``; this updates only the
    aggregates, O(C)."""
    state = gctx.state
    demote = keep if demote is None else demote
    k = keep[:, None]
    kd = demote[:, None]
    f_b = placement.broker[f]
    o_b = placement.broker[old]
    d_new = jnp.where(k, state.leader_load[f] - state.follower_load[f], 0.0)
    d_old = jnp.where(kd, state.follower_load[old] - state.leader_load[old], 0.0)
    inc = keep.astype(jnp.int32)
    dec = demote.astype(jnp.int32)

    broker_load = agg.broker_load.at[f_b].add(d_new).at[o_b].add(d_old)
    host_load = (agg.host_load.at[state.host[f_b]].add(d_new)
                 .at[state.host[o_b]].add(d_old))
    leader_counts = agg.leader_counts.at[f_b].add(inc).at[o_b].add(-dec)
    topic_leader_counts = (agg.topic_leader_counts
                           .at[state.topic[f], f_b].add(inc)
                           .at[state.topic[old], o_b].add(-dec))
    disk_load = (agg.disk_load.at[f_b, placement.disk[f]]
                 .add(d_new[:, Resource.DISK])
                 .at[o_b, placement.disk[old]].add(d_old[:, Resource.DISK]))
    lbi_gain = jnp.where(keep, state.leader_load[f, Resource.NW_IN], 0.0)
    lbi_lose = jnp.where(demote, -state.leader_load[old, Resource.NW_IN], 0.0)
    leader_bytes_in = (agg.leader_bytes_in.at[f_b].add(lbi_gain)
                       .at[o_b].add(lbi_lose))
    return agg.replace(
        broker_load=broker_load, host_load=host_load,
        leader_counts=leader_counts, topic_leader_counts=topic_leader_counts,
        disk_load=disk_load, leader_bytes_in=leader_bytes_in,
    )


def apply_intra_disk_move(gctx: GoalContext, placement: Placement, agg: Aggregates,
                          r, dst_disk):
    """Move replica r to another logdir of its own broker (JBOD)."""
    b = placement.broker[r]
    size = gctx.state.leader_load[r, Resource.DISK]
    disk_load = (agg.disk_load.at[b, placement.disk[r]].add(-size)
                 .at[b, dst_disk].add(size))
    placement = placement.replace(disk=placement.disk.at[r].set(dst_disk))
    return placement, agg.replace(disk_load=disk_load)


def current_leader_of(gctx: GoalContext, placement: Placement, p):
    """i32[...]: replica row of partition p's current leader (-1 if none).
    Shape-polymorphic: p may be scalar or batched."""
    sibs = gctx.partition_replicas[jnp.asarray(p)]         # [..., RF]
    ok = (sibs >= 0) & placement.is_leader[jnp.maximum(sibs, 0)]
    any_leader = jnp.any(ok, axis=-1)
    idx = jnp.argmax(ok, axis=-1)
    got = jnp.take_along_axis(sibs, idx[..., None], axis=-1)[..., 0]
    return jnp.where(any_leader, got, -1)


def apply_leadership_move(gctx: GoalContext, placement: Placement, agg: Aggregates, f):
    """Promote follower replica f to leader (demoting the current leader).

    Load semantics per ``ClusterModel.relocateLeadership`` :402-434: the old
    leader keeps only its follower-role load; the new leader takes leader-role
    load.  Scalar convenience wrapper over ``apply_leadership_moves_batch``
    (one source of truth for the aggregate deltas).
    """
    state = gctx.state
    old = current_leader_of(gctx, placement, state.partition[f])
    old_safe = jnp.maximum(old, 0)
    has_old = old >= 0

    is_leader = placement.is_leader.at[f].set(True)
    is_leader = jnp.where(has_old, is_leader.at[old_safe].set(False), is_leader)
    placement = placement.replace(is_leader=is_leader)
    agg = apply_leadership_moves_batch(
        gctx, placement, agg, jnp.asarray(f)[None], old_safe[None],
        keep=jnp.asarray(True)[None], demote=has_old[None])
    return placement, agg


# --------------------------------------------------------- base feasibility


def sibling_on_broker(gctx: GoalContext, placement: Placement, r, b):
    """bool[...]: does broker b already hold another replica of r's partition.

    r, b broadcast (e.g. r:[C,1], b:[1,B] for the feasibility matrix;
    scalars at scan time).  RF-wide gather, never P×B.
    """
    r = jnp.asarray(r)
    b = jnp.asarray(b)
    p = gctx.state.partition[r]                      # [...]
    sibs = gctx.partition_replicas[p]                # [..., RF]
    sib_b = placement.broker[jnp.maximum(sibs, 0)]   # [..., RF]
    is_sib = (sibs >= 0) & (sibs != r[..., None])
    return jnp.any(is_sib & (sib_b == b[..., None]), axis=-1)


def base_replica_move_ok(gctx: GoalContext, placement: Placement, r, dst):
    """The ``legitMove`` equivalent (GoalUtils): structural feasibility of
    moving replica r to broker dst, independent of any goal."""
    state = gctx.state
    r = jnp.asarray(r)
    dst = jnp.asarray(dst)
    src = placement.broker[r]
    dst_ok = (state.alive[dst] & state.broker_valid[dst]
              & ~gctx.excluded_for_replica_move[dst]
              & gctx.requested_dst[dst]
              & jnp.any(state.disk_alive[dst], axis=-1))
    offline = currently_offline(gctx, placement, r)
    r_ok = state.valid[r] & ~gctx.replica_excluded[r]
    immigrant = (src != state.orig_broker[r]) | offline
    r_ok = r_ok & (~gctx.only_move_immigrants | immigrant)
    # Excluded-topic replicas still must leave dead brokers (reference
    # GoalUtils: offline replicas of excluded topics are movable).
    r_ok = r_ok | offline
    return (r_ok & dst_ok & (dst != src)
            & ~sibling_on_broker(gctx, placement, r, dst))


def base_leadership_ok(gctx: GoalContext, placement: Placement, f):
    """Can follower f be promoted to leader (structurally)."""
    state = gctx.state
    f = jnp.asarray(f)
    b = placement.broker[f]
    return (state.valid[f] & ~placement.is_leader[f] & ~state.offline[f]
            & state.alive[b] & ~gctx.excluded_for_leadership[b]
            & ~gctx.replica_excluded[f])


def capacity_limit(gctx: GoalContext, b) -> jnp.ndarray:
    """f32[..., 4]: broker b's hard capacity limit (threshold * capacity)."""
    return gctx.capacity_threshold * gctx.state.capacity[b]


def within_capacity_after_move(gctx: GoalContext, agg: Aggregates, placement: Placement,
                               r, dst):
    """bool: dst (broker + host scoped resources) stays under the hard
    capacity threshold after receiving replica r (CapacityGoal semantics)."""
    state = gctx.state
    load = replica_role_load(gctx, placement, r)                 # [...,4]
    b_after = agg.broker_load[dst] + load
    b_ok = b_after <= capacity_limit(gctx, dst)
    h = state.host[dst]
    same_host = (state.host[placement.broker[r]] == h)           # no host-level delta
    h_after = agg.host_load[h] + load * (~same_host[..., None])
    h_ok = h_after <= gctx.capacity_threshold * gctx.host_capacity[h]
    is_host = jnp.asarray(IS_HOST_RESOURCE)
    is_broker = jnp.asarray(IS_BROKER_RESOURCE)
    ok = jnp.where(is_broker, b_ok, True) & jnp.where(is_host, h_ok, True)
    return jnp.all(ok, axis=-1)
