"""cruise_control_tpu — a TPU-native cluster-balancing framework.

A ground-up, JAX/XLA-first re-design of the capabilities of LinkedIn Cruise
Control (reference: /root/reference, Java).  The reference keeps a mutable
object graph (racks -> hosts -> brokers -> disks -> replicas, each with a
windowed ``Load``) and runs a priority-ordered list of greedy per-broker goal
optimizers over it.  Here the cluster is a frozen structure-of-arrays snapshot
(``model.ClusterState``), goals are vectorized violation/cost/acceptance
functions over that state (``goals``), and the greedy search is a batched,
jit-compiled move-selection kernel (``analyzer.solver``) that evaluates whole
replica x broker cost/feasibility tensors per round on the MXU.

Subpackage map (reference layer in parentheses — see SURVEY.md):

- ``common``    actions, resources, exceptions          (common/, analyzer/BalancingAction)
- ``config``    typed config system + defaults          (config/, cruise-control-core ConfigDef)
- ``model``     tensor cluster model + builder + stats  (model/)
- ``goals``     goal semantics as masks & costs         (analyzer/goals/)
- ``analyzer``  goal optimizer + solver kernels         (analyzer/GoalOptimizer)
- ``monitor``   windowed metric aggregation -> snapshots (monitor/, cruise-control-core aggregator)
- ``executor``  proposal execution state machine        (executor/)
- ``detector``  anomaly detection + self-healing        (detector/)
- ``server``    REST API + user task manager            (servlet/)
- ``client``    CLI client                              (cruise-control-client/)
- ``parallel``  mesh/sharding for multi-chip solves     (no reference analog; ICI scale-out)
- ``ops``       low-level JAX/Pallas kernels            (no reference analog)
"""

__version__ = "0.1.0"
