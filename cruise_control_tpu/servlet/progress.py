"""Operation progress tracking.

Reference: ``servlet/handler/async/progress/OperationProgress.java:1-129`` —
explicit step-tracing of async operations, surfaced live to clients polling
an unfinished task.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List


@dataclass
class ProgressStep:
    description: str
    started_ms: float
    completed_ms: float = 0.0

    def to_dict(self) -> Dict:
        pct = 100.0 if self.completed_ms else 0.0
        return {"step": self.description, "completionPercentage": pct,
                "time-in-ms": round((self.completed_ms or time.time() * 1000)
                                    - self.started_ms, 1)}


class OperationProgress:
    def __init__(self):
        self._lock = threading.Lock()
        self._steps: List[ProgressStep] = []

    def add_step(self, description: str) -> None:
        with self._lock:
            now = time.time() * 1000
            if self._steps and not self._steps[-1].completed_ms:
                self._steps[-1].completed_ms = now
            self._steps.append(ProgressStep(description, now))

    def finish(self) -> None:
        with self._lock:
            if self._steps and not self._steps[-1].completed_ms:
                self._steps[-1].completed_ms = time.time() * 1000

    def to_list(self) -> List[Dict]:
        with self._lock:
            return [s.to_dict() for s in self._steps]

    def refer(self, other: "OperationProgress") -> None:
        """Share another operation's steps (GoalOptimizer :318)."""
        with self._lock:
            self._steps = other._steps
