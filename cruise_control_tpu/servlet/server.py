"""REST API server.

Reference: ``servlet/KafkaCruiseControlServlet.java:107-219`` dispatch over
the endpoint enum (``CruiseControlEndPoint.java:17-36``), parameter parsing
(``servlet/parameters/ParameterUtils.java``), async 202-until-done responses
via UserTaskManager, and two-step verification through the Purgatory.

Implementation: stdlib ThreadingHTTPServer — the service is control-plane
(tens of requests/min), so a dependency-free server keeps the runtime
hermetic; the layering (app → façade → components) mirrors
``KafkaCruiseControlApp``.

Endpoint inventory note: the mounted reference tree has no ``rightsize``
endpoint (it post-dates this version; ``CruiseControlEndPoint.java`` lists
20 endpoints without it), so it is intentionally absent here; the provision
signals it would act on are exported as the AnomalyDetector
under/over/right-sized gauges.
"""

from __future__ import annotations

import concurrent.futures
import contextvars
import json
import logging
import threading
import urllib.parse
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple

from cruise_control_tpu.analyzer import OptimizationOptions
from cruise_control_tpu.common.exceptions import (
    CruiseControlError,
    OngoingExecutionError,
    UserRequestError,
)
from cruise_control_tpu.detector.anomalies import AnomalyType
from cruise_control_tpu.facade import CruiseControl
from cruise_control_tpu.obsvc import oplog as _oplog
from cruise_control_tpu.obsvc.tracer import tracer as _obsvc_tracer
from cruise_control_tpu.servlet.purgatory import Purgatory
from cruise_control_tpu.servlet.user_tasks import TaskState, UserTaskManager

LOG = logging.getLogger(__name__)

USER_TASK_HEADER = "User-Task-ID"
REQUEST_ID_HEADER = "X-Request-ID"

GET_ENDPOINTS = {"bootstrap", "train", "load", "partition_load", "proposals",
                 "state", "kafka_cluster_state", "user_tasks", "review_board",
                 "metrics", "compile_cache", "trace", "health",
                 "solver_stats", "metrics/history", "memory", "profile",
                 "execution_progress", "model_quality"}
POST_ENDPOINTS = {"add_broker", "remove_broker", "fix_offline_replicas",
                  "rebalance", "stop_proposal_execution", "pause_sampling",
                  "resume_sampling", "demote_broker", "admin", "review",
                  "topic_configuration", "profile", "cancel_user_task"}
# POSTs subject to two-step verification (mutating cluster state).
REVIEWABLE = {"add_broker", "remove_broker", "fix_offline_replicas", "rebalance",
              "demote_broker", "topic_configuration"}
# Endpoints that generate/execute proposals: refused with 503 + Retry-After
# while /health reports unhealthy (degraded still serves — a CPU-fallback
# solve or a stale model is slow/conservative, not wrong).  Reads and the
# stop/pause controls always pass: an operator must be able to stop an
# execution precisely when things are on fire.
PROPOSE_ENDPOINTS = {"proposals", "rebalance", "add_broker", "remove_broker",
                     "demote_broker", "fix_offline_replicas",
                     "topic_configuration"}


def _parse_params(query: str) -> Dict[str, str]:
    return {k.lower(): v[-1] for k, v in urllib.parse.parse_qs(query).items()}


def _bool(params: Dict[str, str], name: str, default: bool) -> bool:
    raw = params.get(name)
    if raw is None:
        return default
    return raw.strip().lower() in ("true", "1", "yes")


def _ints(params: Dict[str, str], name: str) -> List[int]:
    raw = params.get(name, "")
    return [int(x) for x in raw.split(",") if x.strip()]


def _restricted_goals(names: List[str], allowed: List[str],
                      label: str) -> List[str]:
    """Empty request → the full allowed list; otherwise reject names outside
    it and keep the allowed list's canonical order."""
    if not names:
        return list(allowed)
    bad = [n for n in names if n not in allowed]
    if bad:
        raise UserRequestError(
            f"goals {bad} are not {label} goals (allowed: {allowed})")
    return [g for g in allowed if g in names]


def _goals(params: Dict[str, str],
           allow_rebalance_disk: bool = False) -> Optional[List[str]]:
    """Requested goal list; ``kafka_assigner=true`` swaps in the assigner
    pair (reference RunnableUtils.java isKafkaAssignerMode) and — on the
    rebalance endpoint only, as in RebalanceParameters —
    ``rebalance_disk=true`` swaps in the intra-broker goal list; explicit
    subsets are validated against the mode's allowed set (the reference's
    sanityCheckOptimizationOptions)."""
    raw = params.get("goals", "")
    names = [g.strip().rsplit(".", 1)[-1] for g in raw.split(",") if g.strip()]
    if allow_rebalance_disk and _bool(params, "rebalance_disk", False):
        from cruise_control_tpu.analyzer.goals.registry import (
            DEFAULT_INTRA_BROKER_GOALS,
        )
        if _bool(params, "kafka_assigner", False):
            raise UserRequestError(
                "rebalance_disk and kafka_assigner are mutually exclusive")
        return _restricted_goals(names, DEFAULT_INTRA_BROKER_GOALS,
                                 "intra-broker")
    if _bool(params, "kafka_assigner", False):
        from cruise_control_tpu.analyzer.goals.registry import KAFKA_ASSIGNER_GOALS
        # Canonical order: the even goal must run before the disk goal (it
        # assumes no prior optimized goals).
        return _restricted_goals(names, KAFKA_ASSIGNER_GOALS, "kafka_assigner")
    return names or None


def _deadline_ms(params: Dict[str, str]) -> Optional[float]:
    """``?deadline_ms=`` — wall-clock budget for this operation's solve.
    Absent → None (the facade falls back to solver.default.deadline.ms)."""
    raw = params.get("deadline_ms")
    if raw is None:
        return None
    try:
        value = float(raw)
    except ValueError:
        raise UserRequestError("deadline_ms must be a number")
    if value <= 0:
        raise UserRequestError("deadline_ms must be positive")
    return value


def _options(params: Dict[str, str]) -> OptimizationOptions:
    return OptimizationOptions(
        excluded_topics=frozenset(
            t for t in params.get("excluded_topics", "").split(",") if t),
        requested_destination_broker_ids=frozenset(
            _ints(params, "destination_broker_ids")),
        only_move_immigrant_replicas=_bool(
            params, "only_move_immigrant_replicas", False),
    )


class CruiseControlApp:
    """HTTP front over the façade (KafkaCruiseControlApp.java:36-68)."""

    def __init__(self, cc: CruiseControl, host: str = "127.0.0.1", port: int = 0,
                 two_step_verification: bool = False,
                 max_active_user_tasks: int = 25,
                 security=None,
                 ssl_certfile: Optional[str] = None,
                 ssl_keyfile: Optional[str] = None,
                 ssl_keyfile_password: Optional[str] = None,
                 ui_diskpath: Optional[str] = None,
                 ui_urlprefix: str = "/*",
                 api_urlprefix: str = "/kafkacruisecontrol/*",
                 user_task_retention_ms: float = 86_400_000,
                 user_task_timeout_ms: Optional[float] = None):
        self.cc = cc
        self.user_tasks = UserTaskManager(
            max_active_tasks=max_active_user_tasks,
            completed_retention_ms=user_task_retention_ms,
            task_timeout_ms=user_task_timeout_ms)
        # webserver.api.urlprefix (WebServerConfig): the mount point of the
        # REST API, normalized to a trailing-slash prefix for dispatch.  A
        # root mount ("/*" or "/") is honored — the API then owns every
        # path and any configured UI is unreachable, which is the
        # operator's explicit choice, not a fallback.
        self.api_prefix = api_urlprefix.rstrip("*").rstrip("/") + "/"
        self.purgatory = Purgatory() if two_step_verification else None
        # Static frontend serving (KafkaCruiseControlApp.setupWebUi + Jetty
        # DefaultServlet; WebServerConfig webserver.ui.diskpath/.urlprefix):
        # GETs outside the API prefix serve files from ``ui_diskpath``.
        self.ui_diskpath = ui_diskpath
        self.ui_urlprefix = ui_urlprefix
        # Optional servlet security provider (servlet/security.py): when set,
        # every request is authenticated and role-checked before dispatch.
        self.security = security
        handler = _make_handler(self)
        self.server = ThreadingHTTPServer((host, port), handler)
        # TLS listener (KafkaCruiseControlApp.java:100-120 SSL connector):
        # PEM cert/key via config; requests then ride https.
        if ssl_certfile:
            import ssl
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(ssl_certfile, keyfile=ssl_keyfile,
                                password=ssl_keyfile_password)
            # Defer the handshake to the per-request handler thread: with
            # do_handshake_on_connect=True the accept loop performs the full
            # handshake synchronously, so one stalled client would block
            # every other connection.
            self.server.socket = ctx.wrap_socket(
                self.server.socket, server_side=True,
                do_handshake_on_connect=False)
        self.ssl_enabled = bool(ssl_certfile)
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self.server.server_address[1]

    def start(self) -> None:
        self._thread = threading.Thread(target=self.server.serve_forever,
                                        daemon=True, name="http-server")
        self._thread.start()

    def stop(self) -> None:
        self.server.shutdown()
        self.server.server_close()
        self.user_tasks.shutdown()

    # ------------------------------------------------------------ endpoints

    def handle(self, method: str, endpoint: str, params: Dict[str, str],
               task_id: Optional[str]) -> Tuple[int, Dict, Dict[str, str]]:
        """(status, body, extra_headers)."""
        if method == "GET" and endpoint not in GET_ENDPOINTS:
            return 404, {"error": f"unknown GET endpoint {endpoint}"}, {}
        if method == "POST" and endpoint not in POST_ENDPOINTS:
            return 404, {"error": f"unknown POST endpoint {endpoint}"}, {}

        # Degraded-mode admission: while the service is unhealthy, proposing
        # new work would either fail (backend down) or act on a broken view
        # of the cluster — shed it up front instead of queueing doomed tasks.
        if endpoint in PROPOSE_ENDPOINTS:
            rejected = self._admission_check()
            if rejected is not None:
                return rejected

        # Two-step verification: park reviewable POSTs without approval.
        if (method == "POST" and self.purgatory is not None
                and endpoint in REVIEWABLE):
            review_id = params.get("review_id")
            if review_id is None:
                info = self.purgatory.add(
                    endpoint, urllib.parse.urlencode(params))
                return 202, {"reviewResult": info.to_dict(),
                             "message": "pending review"}, {}
            self.purgatory.take_approved(int(review_id))

        # Slash endpoints (metrics/history) dispatch to underscore methods.
        # A verb-specific handler (``_ep_get_profile``) wins over the shared
        # one for routes served under both verbs.
        ep_name = endpoint.replace("/", "_")
        handler = (getattr(self, f"_ep_{method.lower()}_{ep_name}", None)
                   or getattr(self, f"_ep_{ep_name}", None))
        if handler is None:
            return 501, {"error": f"{endpoint} not implemented"}, {}
        # Per-endpoint servlet sensors (Sensors.md: <endpoint>-request-rate,
        # <endpoint>-successful-request-execution-timer).
        from cruise_control_tpu.common.metrics import registry
        reg = registry()
        reg.counter(f"KafkaCruiseControlServlet.{endpoint}-request-rate").inc()
        import time as _time
        t0 = _time.monotonic()
        try:
            status, body, headers = handler(params, task_id)
        except UserRequestError as e:
            return 400, {"error": str(e)}, {}
        if status < 400:
            reg.timer(
                f"KafkaCruiseControlServlet.{endpoint}"
                "-successful-request-execution-timer"
            ).update_ms((_time.monotonic() - t0) * 1000.0)
        return status, body, headers

    def _admission_check(self) -> Optional[Tuple[int, Dict, Dict[str, str]]]:
        """503 + Retry-After for propose traffic while unhealthy, else None.
        A broken probe must never turn into a request failure — admission
        fails open."""
        from cruise_control_tpu import resilience
        try:
            health = self.cc.health()
        except Exception:  # noqa: BLE001 — probes must not break admission
            LOG.exception("health probe failed during admission; admitting")
            return None
        if health.get("status") != "unhealthy":
            return None
        from cruise_control_tpu.common.metrics import registry
        registry().counter(resilience.ADMISSION_REJECTIONS_SENSOR).inc()
        retry_after = resilience.settings().health_retry_after_s
        unhealthy = sorted(name for name, p in health["probes"].items()
                           if p["status"] == "unhealthy")
        return 503, {
            "error": "ServiceUnhealthy",
            "message": ("service unhealthy "
                        f"({', '.join(unhealthy) or 'unknown'}); "
                        "proposal traffic is shed until it recovers"),
            "health": health,
        }, {"Retry-After": str(retry_after)}

    # ---- sync GETs

    def _ep_health(self, params, task_id):
        """Component probes + rollup; 503 while unhealthy so plain HTTP
        checks (load balancers, k8s) need no body parsing."""
        body = self.cc.health()
        if body["status"] == "unhealthy":
            from cruise_control_tpu import resilience
            return 503, body, {
                "Retry-After": str(resilience.settings().health_retry_after_s)}
        return 200, body, {}

    def _ep_state(self, params, task_id):
        body = self.cc.state()
        if not _bool(params, "verbose", False):
            body["AnalyzerState"].pop("goalReadiness", None)
        return 200, body, {}

    def _ep_load(self, params, task_id):
        return 200, self.cc.broker_stats(), {}

    def _ep_metrics(self, params, task_id):
        """Sensor surface: JSON snapshot (?json=true) or Prometheus text."""
        from cruise_control_tpu.common.metrics import registry
        if _bool(params, "json", False):
            return 200, {"sensors": registry().snapshot()}, {}
        return 200, registry().prometheus_text(), {}

    def _ep_trace(self, params, task_id):
        """Recent root span trees + per-phase rollup (obsvc tracer)."""
        tr = _obsvc_tracer()
        return 200, {"enabled": tr.enabled, "traces": tr.traces(),
                     "rollup": tr.rollup()}, {}

    def _ep_solver_stats(self, params, task_id):
        """Convergence observatory: the flight-recorder ring of per-solve
        per-goal round curves (trace.solver.rounds) plus derived stats."""
        from cruise_control_tpu.obsvc.convergence import convergence
        rec = convergence()
        records = rec.records()
        try:
            limit = int(params.get("limit", "0"))
        except ValueError:
            return 400, {"error": "limit must be an integer"}, {}
        if limit > 0:
            records = records[-limit:]
        summary = rec.state_summary()
        return 200, {"enabled": summary["enabled"],
                     "recorded": summary["recorded"],
                     "ringSize": summary["ringSize"],
                     "records": records}, {}

    def _ep_metrics_history(self, params, task_id):
        """Sensor time-series rings sampled by the obsvc history thread.
        ``sensor`` accepts an exact name or an fnmatch glob (prefix queries
        like ``Memory.*``); the response is bounded to ``limit`` series
        (default 64, capped) with a ``truncated`` flag."""
        from cruise_control_tpu.obsvc import history
        hist = history()
        since_raw = params.get("since_ms")
        try:
            since_ms = float(since_raw) if since_raw is not None else None
        except ValueError:
            return 400, {"error": "since_ms must be a number"}, {}
        try:
            limit = int(params.get("limit", str(hist.DEFAULT_SERIES_LIMIT)))
        except ValueError:
            return 400, {"error": "limit must be an integer"}, {}
        if limit <= 0:
            return 400, {"error": "limit must be positive"}, {}
        series, truncated = hist.history_bounded(
            pattern=params.get("sensor"), since_ms=since_ms, limit=limit)
        from cruise_control_tpu.obsvc.history import SAMPLES_SENSOR
        from cruise_control_tpu.common.metrics import registry
        return 200, {"enabled": hist.running,
                     "intervalMs": hist.interval_s * 1000.0,
                     "ringSize": hist.ring_size,
                     "samples": registry().counter(SAMPLES_SENSOR).count,
                     "truncated": truncated,
                     "series": series}, {}

    def _ep_compile_cache(self, params, task_id):
        """Compile-service admin view: bucket policy, compiled lane widths,
        persistent-cache state, warmup progress, per-bucket hit/miss/compile
        counters (the raw sensors also ride /metrics)."""
        from cruise_control_tpu.compilesvc import compile_service
        body = compile_service().snapshot()
        daemon = getattr(self.cc, "warmup_daemon", None)
        body["warmup"] = daemon.snapshot() if daemon is not None else None
        return 200, body, {}

    def _ep_partition_load(self, params, task_id):
        n = int(params.get("entries", "100"))
        return 200, {"records": self.cc.partition_load(max_entries=n)}, {}

    def _ep_kafka_cluster_state(self, params, task_id):
        md = self.cc.load_monitor.metadata_client.refresh_metadata()
        return 200, {
            "KafkaBrokerState": {
                "Summary": {"brokers": len(md.brokers),
                            "alive": len(md.alive_broker_ids())},
                "brokers": [{"id": b.broker_id, "rack": b.rack, "host": b.host,
                             "alive": b.alive} for b in md.brokers],
            },
            "KafkaPartitionState": {
                "offline": [f"{p.topic}-{p.partition}" for p in md.partitions
                            if p.leader is None],
                "urp": [f"{p.topic}-{p.partition}" for p in md.partitions
                        if len(p.in_sync) < len(p.replicas)],
            },
        }, {}

    def _ep_user_tasks(self, params, task_id):
        return 200, {"userTasks": [t.to_dict()
                                   for t in self.user_tasks.all_tasks()]}, {}

    def _ep_review_board(self, params, task_id):
        if self.purgatory is None:
            return 400, {"error": "two-step verification disabled"}, {}
        return 200, {"RequestInfo": self.purgatory.board()}, {}

    def _ep_bootstrap(self, params, task_id):
        if self.cc.task_runner is None:
            return 400, {"error": "no task runner"}, {}
        start = float(params.get("start", 0))
        end = float(params.get("end", 0))
        n = self.cc.task_runner.bootstrap(start, end)
        return 200, {"message": f"bootstrapped {n} samples"}, {}

    def _ep_train(self, params, task_id):
        from cruise_control_tpu.model.cpu_model import LinearRegressionCpuModel
        model = LinearRegressionCpuModel(min_samples=1)
        from cruise_control_tpu.monitor import metric_def as md
        try:
            result = self.cc.load_monitor.broker_aggregator.aggregate(
                float(params.get("start", 0)), float(params.get("end", 1e18)))
        except CruiseControlError as e:
            return 400, {"error": str(e)}, {}
        bdef = md.BROKER_METRIC_DEF
        for _, vae in result.values_and_extrapolations.items():
            for w in range(vae.values.shape[1]):
                model.add_sample(
                    vae.values[bdef.metric_id("LEADER_BYTES_IN"), w],
                    vae.values[bdef.metric_id("LEADER_BYTES_OUT"), w],
                    vae.values[bdef.metric_id("REPLICATION_BYTES_IN_RATE"), w],
                    vae.values[bdef.metric_id("CPU_USAGE"), w])
        coef = model.fit()
        return 200, {"message": "training done",
                     "coefficients": None if coef is None else coef.tolist()}, {}

    def _ep_profile(self, params, task_id):
        """Admin: open a JAX profiler capture window for ``duration_s``
        seconds on a background thread and answer 202 immediately — poll
        ``GET /profile`` for busy/done/trace_dir.  A second POST while a
        window is open (sync or async) answers 409."""
        from cruise_control_tpu.obsvc import profiler
        try:
            duration_s = float(params.get("duration_s", "2.0"))
        except ValueError:
            return 400, {"error": "duration_s must be a number"}, {}
        try:
            out = profiler.start_async(duration_s)
        except ValueError as e:
            return 400, {"error": str(e)}, {}
        except profiler.ProfileInProgress as e:
            return 409, {"error": str(e)}, {}
        except Exception as e:   # noqa: BLE001 — profiler backend seam
            LOG.exception("profile capture failed to start")
            return 500, {"error": type(e).__name__, "message": str(e)}, {}
        return 202, {"message": "profile capture started",
                     "status": "started", **out}, {}

    def _ep_get_profile(self, params, task_id):
        """Pollable capture status: busy while a window is open, done +
        trace_dir once the last async capture landed."""
        from cruise_control_tpu.obsvc import profiler
        return 200, profiler.status(), {}

    def _ep_memory(self, params, task_id):
        """Device-memory observatory: per-subsystem live-bytes ledger,
        backend reconciliation, headroom-guard counters, and the
        per-executable compile-cost rows (404 while memory.enabled=false)."""
        from cruise_control_tpu.obsvc.memory import memory_ledger
        ledger = memory_ledger()
        if not ledger.enabled:
            return 404, {"error": "memory ledger disabled "
                                  "(memory.enabled=false)"}, {}
        return 200, ledger.snapshot(), {}

    def _ep_model_quality(self, params, task_id):
        """Fidelity observatory: the current model fingerprint with its
        staleness verdict, the per-window quality ring, broker-liveness
        flaps and the last fetch summary (404 while
        monitor.fidelity.enabled=false)."""
        from cruise_control_tpu.obsvc.fidelity import fidelity
        rec = fidelity()
        if not rec.enabled:
            return 404, {"error": "fidelity observatory disabled "
                                  "(monitor.fidelity.enabled=false)"}, {}
        return 200, rec.quality(), {}

    def _ep_execution_progress(self, params, task_id):
        """Execution observatory: the active batch's per-task state joined
        with each move's provenance record, per-broker inflight counts, the
        EWMA throughput/ETA estimate, recent batch summaries and AIMD tuner
        events (404 while execution.observatory.enabled=false)."""
        from cruise_control_tpu.obsvc.execution import execution
        rec = execution()
        if not rec.enabled:
            return 404, {"error": "execution observatory disabled "
                                  "(execution.observatory.enabled=false)"}, {}
        return 200, rec.progress(), {}

    # ---- async operations (202-until-done)

    def _async(self, endpoint: str, params: Dict[str, str], task_id: Optional[str],
               op: Callable) -> Tuple[int, Dict, Dict[str, str]]:
        """``op`` takes the task's cancellation token (a threading.Event the
        façade folds into the operation's SolveBudget) and returns the
        OperationResult."""
        query = urllib.parse.urlencode(params)
        existing = self.user_tasks.get(task_id) if task_id else None
        if existing is not None:
            task = existing
        else:
            # Snapshot this request's context (most importantly the active
            # trace span) so the user-task worker thread parents its spans
            # under the request's root instead of starting orphan traces.
            ctx = contextvars.copy_context()
            cancel_token = threading.Event()
            task = self.user_tasks.get_or_create(
                task_id, endpoint, query,
                lambda progress: ctx.run(op, cancel_token),
                cancel_token=cancel_token)
            _oplog.record("start", task_id=task.task_id, endpoint=endpoint,
                          params=query)
            task.future.add_done_callback(
                lambda f, t=task, e=endpoint, q=query, p=_oplog.current_principal():
                self._oplog_outcome(t, e, q, p))
        headers = {USER_TASK_HEADER: task.task_id}
        # ?explain=true is a render-time flag, not part of the operation:
        # re-polling a cached task with a different explain value re-renders
        # the same result, it never re-runs the solve.
        explain = _bool(params, "explain", False)
        if task.state is TaskState.ACTIVE:
            try:
                result = task.future.result(timeout=5.0)
                return 200, self._render(result, explain), headers
            except concurrent.futures.TimeoutError:
                # On 3.11+ this is the builtin TimeoutError; on 3.10 it is a
                # distinct class, and catching only the builtin returned 500
                # instead of the 202-in-progress contract.
                return 202, {"progress": task.progress.to_list(),
                             "message": "operation in progress"}, headers
            except CruiseControlError as e:
                return 500, {"error": type(e).__name__, "message": str(e)}, headers
        if task.state is TaskState.COMPLETED_WITH_ERROR:
            e = task.future.exception()
            code = 409 if isinstance(e, OngoingExecutionError) else 500
            return code, {"error": type(e).__name__, "message": str(e)}, headers
        return 200, self._render(task.future.result(), explain), headers

    @staticmethod
    def _oplog_outcome(task, endpoint: str, query: str,
                       principal: str) -> None:
        """Terminal oplog event for a finished user task.  Runs on the
        worker thread via the future's done callback — the request context
        is gone, so the captured principal is passed explicitly."""
        try:
            if task.future.exception() is not None:
                _oplog.record("abort", task_id=task.task_id,
                              endpoint=endpoint, params=query,
                              principal=principal,
                              reason=type(task.future.exception()).__name__)
                return
            result = task.future.result()
            if getattr(result, "partial", False):
                _oplog.record("preempted", task_id=task.task_id,
                              endpoint=endpoint, params=query,
                              principal=principal,
                              reason=task.cancel_reason or "deadline",
                              executed=getattr(result, "executed", None))
            else:
                _oplog.record("finish", task_id=task.task_id,
                              endpoint=endpoint, params=query,
                              principal=principal,
                              executed=getattr(result, "executed", None))
        except Exception:   # noqa: BLE001 — audit must never break a task
            LOG.exception("operation log emit failed")

    @staticmethod
    def _render(result, explain: bool = False) -> Dict:
        if not hasattr(result, "to_dict"):
            return {"result": result}
        try:
            return result.to_dict(explain=explain)
        except TypeError:   # result types without an explain view
            return result.to_dict()

    def _ep_proposals(self, params, task_id):
        goals = _goals(params)
        options = _options(params)
        dl = _deadline_ms(params)
        return self._async("proposals", params, task_id,
                           lambda ev: self.cc.proposals(
                               goals, options, deadline_ms=dl,
                               cancel_event=ev))

    def _ep_rebalance(self, params, task_id):
        goals = _goals(params, allow_rebalance_disk=True)
        dryrun = _bool(params, "dryrun", True)
        options = _options(params)
        dl = _deadline_ms(params)
        return self._async("rebalance", params, task_id,
                           lambda ev: self.cc.rebalance(
                               goals, dryrun, options, deadline_ms=dl,
                               cancel_event=ev))

    def _ep_add_broker(self, params, task_id):
        ids = _ints(params, "brokerid")
        if not ids:
            return 400, {"error": "brokerid parameter required"}, {}
        dl = _deadline_ms(params)
        return self._async("add_broker", params, task_id,
                           lambda ev: self.cc.add_brokers(
                               ids, _goals(params), _bool(params, "dryrun", True),
                               deadline_ms=dl, cancel_event=ev))

    def _ep_remove_broker(self, params, task_id):
        ids = _ints(params, "brokerid")
        if not ids:
            return 400, {"error": "brokerid parameter required"}, {}
        dl = _deadline_ms(params)
        return self._async("remove_broker", params, task_id,
                           lambda ev: self.cc.remove_brokers(
                               ids, _goals(params), _bool(params, "dryrun", True),
                               deadline_ms=dl, cancel_event=ev))

    def _ep_demote_broker(self, params, task_id):
        ids = _ints(params, "brokerid")
        if not ids:
            return 400, {"error": "brokerid parameter required"}, {}
        dl = _deadline_ms(params)
        return self._async("demote_broker", params, task_id,
                           lambda ev: self.cc.demote_brokers(
                               ids, _bool(params, "dryrun", True),
                               deadline_ms=dl, cancel_event=ev))

    def _ep_fix_offline_replicas(self, params, task_id):
        dl = _deadline_ms(params)
        return self._async("fix_offline_replicas", params, task_id,
                           lambda ev: self.cc.fix_offline_replicas(
                               _goals(params), _bool(params, "dryrun", True),
                               deadline_ms=dl, cancel_event=ev))

    def _ep_topic_configuration(self, params, task_id):
        topic = params.get("topic")
        rf = params.get("replication_factor")
        if not topic or rf is None:
            return 400, {"error": "topic and replication_factor required"}, {}
        dl = _deadline_ms(params)
        return self._async("topic_configuration", params, task_id,
                           lambda ev: self.cc.change_topic_replication_factor(
                               topic, int(rf), _goals(params),
                               _bool(params, "dryrun", True),
                               deadline_ms=dl, cancel_event=ev))

    # ---- sync POSTs

    def _ep_cancel_user_task(self, params, task_id):
        """POST /cancel_user_task — abort an in-flight 202 operation at its
        next budget checkpoint (segment or goal boundary).  The task then
        completes with its anytime-safe partial result, never executed."""
        tid = params.get("user_task_id") or task_id
        if not tid:
            return 400, {"error": "user_task_id parameter (or User-Task-ID "
                                  "header) required"}, {}
        task = self.user_tasks.get(tid)
        if task is None:
            return 404, {"error": f"unknown user task {tid}"}, {}
        if task.state is not TaskState.ACTIVE:
            return 400, {"error": f"task {tid} is not active "
                                  f"({task.state.value})"}, {}
        if not task.cancel("user"):
            return 400, {"error": f"task {tid} carries no cancellation "
                                  "token"}, {}
        _oplog.record("abort", task_id=tid, endpoint=task.endpoint,
                      params=task.query, reason="user-cancel-requested")
        return 200, {"message": "cancellation requested; the operation "
                                "stops at its next segment boundary",
                     "UserTaskId": tid}, {USER_TASK_HEADER: tid}

    def _ep_stop_proposal_execution(self, params, task_id):
        self.cc.stop_execution()
        return 200, {"message": "execution stop requested"}, {}

    def _ep_pause_sampling(self, params, task_id):
        try:
            self.cc.pause_sampling(params.get("reason", "via API"))
        except UserRequestError as e:
            return 400, {"error": str(e)}, {}
        return 200, {"message": "sampling paused"}, {}

    def _ep_resume_sampling(self, params, task_id):
        try:
            self.cc.resume_sampling(params.get("reason", "via API"))
        except UserRequestError as e:
            return 400, {"error": str(e)}, {}
        return 200, {"message": "sampling resumed"}, {}

    def _ep_admin(self, params, task_id):
        out: Dict[str, Any] = {}
        if "enable_self_healing_for" in params:
            for name in params["enable_self_healing_for"].split(","):
                t = AnomalyType[name.strip().upper()]
                out.setdefault("selfHealingEnabledBefore", {})[t.name] = \
                    self.cc.set_self_healing(t, True)
        if "disable_self_healing_for" in params:
            for name in params["disable_self_healing_for"].split(","):
                t = AnomalyType[name.strip().upper()]
                out.setdefault("selfHealingEnabledBefore", {})[t.name] = \
                    self.cc.set_self_healing(t, False)
        if "concurrent_partition_movements_per_broker" in params:
            n = int(params["concurrent_partition_movements_per_broker"])
            self.cc.executor.config.concurrent_partition_movements_per_broker = n
            out["concurrency"] = n
        return 200, out or {"message": "no-op"}, {}

    def _ep_review(self, params, task_id):
        if self.purgatory is None:
            return 400, {"error": "two-step verification disabled"}, {}
        approve = _ints(params, "approve")
        discard = _ints(params, "discard")
        results = []
        for rid in approve:
            results.append(self.purgatory.review(
                rid, True, params.get("reason", "")).to_dict())
        for rid in discard:
            results.append(self.purgatory.review(
                rid, False, params.get("reason", "")).to_dict())
        return 200, {"RequestInfo": results}, {}


def _make_handler(app: CruiseControlApp):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):   # NCSA log → logger, not stderr
            LOG.debug("http: " + fmt, *args)

        def _dispatch(self, method: str):
            parsed = urllib.parse.urlparse(self.path)
            if not parsed.path.startswith(app.api_prefix):
                # The API prefix always wins; anything else is the static
                # frontend when one is configured (Jetty DefaultServlet
                # semantics: GET only, index.html for the root).  The
                # security handler covers the UI exactly as it covers the
                # API (the reference mounts both in one secured context):
                # any authenticated principal may fetch frontend assets.
                if method == "GET" and app.ui_diskpath:
                    if app.security is not None \
                            and self._authenticate_or_401() is None:
                        return
                    self._serve_ui(parsed.path)
                else:
                    self._send(404, {"error": "not found"})
                return
            endpoint = parsed.path[len(app.api_prefix):].strip("/").lower()
            if app.security is not None:
                from cruise_control_tpu.servlet.security import (
                    permits,
                    required_role,
                )
                principal = self._authenticate_or_401()
                if principal is None:
                    return
                # Bind the authenticated identity for the operation audit
                # log; user-task workers inherit it via the copied context.
                _oplog.set_principal(principal.name)
                need = required_role(method, endpoint)
                if not permits(principal.role, need):
                    self._send(403, {
                        "error": f"role {principal.role.value} may not access "
                                 f"{method} {endpoint} (requires {need.value})",
                        "version": 1}, {})
                    return
            params = _parse_params(parsed.query)
            if method == "POST" and self.headers.get("Content-Length"):
                body = self.rfile.read(int(self.headers["Content-Length"]))
                ctype = self.headers.get("Content-Type", "")
                if "application/x-www-form-urlencoded" in ctype:
                    params.update(_parse_params(body.decode()))
            task_id = self.headers.get(USER_TASK_HEADER)
            # Request id in/out: honor a caller-supplied X-Request-ID (so
            # operators can correlate across proxies), mint one otherwise;
            # the root span carries it into /trace.
            request_id = self.headers.get(REQUEST_ID_HEADER) or uuid.uuid4().hex[:16]
            # Bind it alongside the principal so user-task workers (copied
            # context) stamp executor batches with the originating request.
            _oplog.set_request_id(request_id)
            with _obsvc_tracer().span(f"http.{endpoint}", method=method,
                                      request_id=request_id):
                try:
                    status, payload, headers = app.handle(method, endpoint,
                                                          params, task_id)
                except OngoingExecutionError as e:
                    status, payload, headers = 409, {"error": str(e)}, {}
                except CruiseControlError as e:
                    status, payload, headers = 500, {
                        "error": type(e).__name__, "message": str(e)}, {}
                except Exception as e:   # noqa: BLE001 — never kill the server
                    LOG.exception("request failed")
                    status, payload, headers = 500, {
                        "error": type(e).__name__, "message": str(e)}, {}
            if isinstance(payload, dict):
                payload.setdefault("version", 1)
            headers = {**(headers or {}), REQUEST_ID_HEADER: request_id,
                       **self._mutual_auth_headers()}
            self._send(status, payload, headers)

        def _authenticate_or_401(self):
            """Shared auth gate for API and UI requests: returns the
            Principal, or sends the 401 challenge and returns None."""
            try:
                principal = app.security.authenticate(
                    dict(self.headers), self.client_address[0])
            except Exception:   # noqa: BLE001 — provider bug reads as 401
                LOG.exception("security provider failed")
                principal = None
            if principal is None:
                self._send(401, {"error": "authentication required",
                                 "version": 1},
                           app.security.challenge())
            return principal

        def _mutual_auth_headers(self) -> Dict[str, str]:
            """SPNEGO mutual auth: the provider may carry a GSS reply token
            for this thread's successful exchange (RFC 4559 §4.2); every
            authenticated response — API or UI asset — must return it."""
            mutual = getattr(app.security, "mutual_auth_header", None)
            return mutual() if mutual is not None else {}

        def _serve_ui(self, raw_path: str):
            import mimetypes
            import os
            # Everything filesystem-touching sits inside one guard: a
            # null-byte path (realpath raises ValueError), an unreadable
            # file, or a delete between the isfile check and open() must
            # surface as an HTTP 404, not a dropped connection.
            # Every response of an authenticated exchange carries the
            # mutual-auth reply token, 404s included (RFC 4559 §4.2).
            mutual = self._mutual_auth_headers()
            try:
                prefix = app.ui_urlprefix.rstrip("*").rstrip("/")  # "/*" → ""
                path = urllib.parse.unquote(raw_path)
                if prefix and not (path == prefix
                                   or path.startswith(prefix + "/")):
                    self._send(404, {"error": "not found"}, mutual)
                    return
                rel = path[len(prefix):].lstrip("/") or "index.html"
                root = os.path.realpath(app.ui_diskpath)
                full = os.path.realpath(os.path.join(root, rel))
                # realpath + prefix check: symlinks and ../ cannot escape
                # the configured frontend directory.
                inside = full == root or full.startswith(root + os.sep)
                if not inside or not os.path.isfile(full):
                    self._send(404, {"error": "not found"}, mutual)
                    return
                with open(full, "rb") as f:
                    body = f.read()
            except (OSError, ValueError):
                self._send(404, {"error": "not found"}, mutual)
                return
            ctype = mimetypes.guess_type(full)[0] or "application/octet-stream"
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            for k, v in mutual.items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _send(self, status: int, payload: Dict,
                  headers: Optional[Dict[str, str]] = None):
            if isinstance(payload, str):      # text endpoints (/metrics)
                body = payload.encode()
                ctype = "text/plain; version=0.0.4"
            else:
                body = json.dumps(payload).encode()
                ctype = "application/json"
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            self._dispatch("GET")

        def do_POST(self):
            self._dispatch("POST")

    return Handler
