"""Response schemas + a minimal JSON-schema checker.

Reference: ``cruise-control/src/yaml/{endpoints,responses}/**`` — OpenAPI
response schemas — and the ``ResponseTest`` pattern that validates live
endpoint payloads against them in CI.  The subset of JSON Schema used by
those files (type/properties/required/items/enum) is implemented here with
the stdlib so schema checks can run inside the server tests (and optionally
at serving time for debugging).
"""

from __future__ import annotations

from typing import Any, Dict, List

from cruise_control_tpu.common.exceptions import CruiseControlError


class SchemaViolation(CruiseControlError):
    pass


_TYPES = {
    "object": dict,
    "array": list,
    "string": str,
    "boolean": bool,
    "null": type(None),
}


def _type_ok(value: Any, expected: str) -> bool:
    if expected == "number":
        return isinstance(value, (int, float)) and not isinstance(value, bool)
    if expected == "integer":
        return isinstance(value, int) and not isinstance(value, bool)
    return isinstance(value, _TYPES[expected])


def validate(value: Any, schema: Dict, path: str = "$") -> None:
    """Raise SchemaViolation on the first mismatch."""
    expected = schema.get("type")
    if expected is not None:
        types = expected if isinstance(expected, list) else [expected]
        if not any(_type_ok(value, t) for t in types):
            raise SchemaViolation(
                f"{path}: expected {expected}, got {type(value).__name__}")
    if "enum" in schema and value not in schema["enum"]:
        raise SchemaViolation(f"{path}: {value!r} not in {schema['enum']}")
    if isinstance(value, dict):
        for key in schema.get("required", []):
            if key not in value:
                raise SchemaViolation(f"{path}: missing required key {key!r}")
        props = schema.get("properties", {})
        for key, sub in props.items():
            if key in value:
                validate(value[key], sub, f"{path}.{key}")
        extra = schema.get("additionalProperties")
        if isinstance(extra, dict):
            for key, v in value.items():
                if key not in props:
                    validate(v, extra, f"{path}.{key}")
    if isinstance(value, list) and "items" in schema:
        for i, item in enumerate(value):
            validate(item, schema["items"], f"{path}[{i}]")


# ------------------------------------------------------- endpoint schemas

STATE_SCHEMA = {
    "type": "object",
    "required": ["MonitorState", "ExecutorState", "AnalyzerState",
                 "AnomalyDetectorState", "version"],
    "properties": {
        "MonitorState": {
            "type": "object",
            "required": ["state", "numValidWindows",
                         "monitoredPartitionsPercentage"],
            "properties": {
                "state": {"type": "string"},
                "numValidWindows": {"type": "integer"},
                "monitoredPartitionsPercentage": {"type": "number"},
            },
        },
        "ExecutorState": {
            "type": "object",
            "required": ["state"],
            "properties": {"state": {"type": "string"}},
        },
        "AnalyzerState": {"type": "object"},
        "AnomalyDetectorState": {"type": "object"},
        "version": {"type": "integer"},
    },
}

_STAT_ROW = {
    "type": "object",
    "required": ["cpu", "networkInbound", "networkOutbound", "disk"],
    "properties": {k: {"type": "number"} for k in
                   ("cpu", "networkInbound", "networkOutbound", "disk",
                    "replicas")},
}

LOAD_SCHEMA = {
    "type": "object",
    "required": ["statistics", "numBrokers", "numReplicas", "numLeaders",
                 "version"],
    "properties": {
        "statistics": {
            "type": "object",
            "required": ["AVG", "MAX", "MIN", "STD"],
            "properties": {k: _STAT_ROW for k in ("AVG", "MAX", "MIN", "STD")},
        },
        "numBrokers": {"type": "integer"},
        "numReplicas": {"type": "integer"},
        "numLeaders": {"type": "integer"},
        "version": {"type": "integer"},
    },
}

PARTITION_LOAD_SCHEMA = {
    "type": "object",
    "required": ["records", "version"],
    "properties": {
        "records": {
            "type": "array",
            "items": {
                "type": "object",
                "required": ["topic", "partition", "cpu", "networkInbound",
                             "networkOutbound", "disk"],
                "properties": {
                    "topic": {"type": "string"},
                    "partition": {"type": "integer"},
                    "cpu": {"type": "number"},
                    "networkInbound": {"type": "number"},
                    "networkOutbound": {"type": "number"},
                    "disk": {"type": "number"},
                },
            },
        },
    },
}

OPERATION_RESULT_SCHEMA = {
    "type": "object",
    "required": ["dryrun", "executed", "result", "version"],
    "properties": {
        "dryrun": {"type": "boolean"},
        "executed": {"type": "boolean"},
        "partial": {"type": "boolean"},
        "result": {
            "type": "object",
            "required": ["numLeaderMovements", "violatedGoalsBefore",
                         "violatedGoalsAfter", "goals"],
            "properties": {
                "numLeaderMovements": {"type": "integer"},
                "violatedGoalsBefore": {"type": "array",
                                        "items": {"type": "string"}},
                "violatedGoalsAfter": {"type": "array",
                                       "items": {"type": "string"}},
                "balancednessScore": {"type": "number"},
                "goals": {
                    "type": "array",
                    "items": {
                        "type": "object",
                        "required": ["goal", "violatedBrokersBefore",
                                     "violatedBrokersAfter"],
                    },
                },
                # ?explain=true only: per-move provenance and the
                # relax/rounding/repair/greedy path histogram.
                "proposals": {"type": "array", "items": {"type": "object"}},
                "provenancePaths": {
                    "type": "object",
                    "additionalProperties": {"type": "integer"},
                },
            },
        },
    },
}

USER_TASKS_SCHEMA = {
    "type": "object",
    "required": ["userTasks", "version"],
    "properties": {
        "userTasks": {
            "type": "array",
            "items": {
                "type": "object",
                "required": ["UserTaskId", "Status", "RequestURL", "StartMs"],
                "properties": {
                    "UserTaskId": {"type": "string"},
                    "Status": {"type": "string"},
                    "RequestURL": {"type": "string"},
                    "StartMs": {"type": "integer"},
                },
            },
        },
    },
}

KAFKA_CLUSTER_STATE_SCHEMA = {
    "type": "object",
    "required": ["KafkaBrokerState", "KafkaPartitionState", "version"],
    "properties": {
        "KafkaBrokerState": {"type": "object"},
        "KafkaPartitionState": {"type": "object"},
    },
}

#: Plain acknowledgement bodies (bootstrap, sampling toggles, stop).
MESSAGE_SCHEMA = {
    "type": "object",
    "required": ["message"],
    "properties": {"message": {"type": "string"}},
}

TRAIN_SCHEMA = {
    "type": "object",
    "required": ["message", "coefficients"],
    "properties": {
        "message": {"type": "string"},
        "coefficients": {"type": ["array", "null"],
                         "items": {"type": "number"}},
    },
}

_REVIEW_ROW = {
    "type": "object",
    "required": ["Id", "EndPoint", "Status"],
    "properties": {
        "Id": {"type": "integer"},
        "EndPoint": {"type": "string"},
        "Query": {"type": "string"},
        "Submitter": {"type": "string"},
        "Status": {"type": "string"},
        "Reason": {"type": "string"},
    },
}

REVIEW_BOARD_SCHEMA = {
    "type": "object",
    "required": ["RequestInfo"],
    "properties": {"RequestInfo": {"type": "array", "items": _REVIEW_ROW}},
}

ADMIN_SCHEMA = {
    "type": "object",
    "properties": {
        "selfHealingEnabledBefore": {"type": "object"},
        "concurrency": {"type": "integer"},
        "message": {"type": "string"},
    },
}

METRICS_JSON_SCHEMA = {
    "type": "object",
    "required": ["sensors"],
    "properties": {"sensors": {"type": "object"}},
}

COMPILE_CACHE_SCHEMA = {
    "type": "object",
    "required": ["policy", "telemetry"],
    "properties": {
        "policy": {"type": "object"},
        "chunking_enabled": {"type": "boolean"},
        "warmup_enabled": {"type": "boolean"},
        "compiled_lane_widths": {"type": "object"},
        "persistent_cache": {"type": "object"},
        "telemetry": {"type": "object"},
        "warmup": {"type": ["object", "null"]},
    },
}

_SPAN_SCHEMA = {
    "type": "object",
    "required": ["span_id", "name", "start_ms"],
    "properties": {
        "span_id": {"type": "integer"},
        "parent_id": {"type": ["integer", "null"]},
        "name": {"type": "string"},
        "start_ms": {"type": "number"},
        # null while the span (or a late-finishing child) is in progress.
        "wall_ms": {"type": ["number", "null"]},
        "attrs": {"type": "object"},
        "children": {"type": "array", "items": {"type": "object"}},
    },
}

TRACE_SCHEMA = {
    "type": "object",
    "required": ["enabled", "traces", "rollup"],
    "properties": {
        "enabled": {"type": "boolean"},
        "traces": {"type": "array", "items": _SPAN_SCHEMA},
        "rollup": {
            "type": "object",
            "additionalProperties": {
                "type": "object",
                "required": ["count", "total_ms", "mean_ms"],
                "properties": {
                    "count": {"type": "integer"},
                    "total_ms": {"type": "number"},
                    "mean_ms": {"type": "number"},
                },
            },
        },
    },
}

PROFILE_SCHEMA = {
    "type": "object",
    "required": ["message", "status", "trace_dir", "duration_s"],
    "properties": {
        "message": {"type": "string"},
        "status": {"type": "string"},
        "trace_dir": {"type": "string"},
        "duration_s": {"type": "number"},
    },
}

# GET /profile — pollable async-capture state.
PROFILE_STATUS_SCHEMA = {
    "type": "object",
    "required": ["busy", "done"],
    "properties": {
        "busy": {"type": "boolean"},
        "done": {"type": "boolean"},
        "trace_dir": {"type": ["string", "null"]},
        "duration_s": {"type": "number"},
        "started_ms": {"type": "integer"},
        "error": {"type": ["string", "null"]},
    },
}

MEMORY_SCHEMA = {
    "type": "object",
    "required": ["enabled", "analysisMode", "liveBytes", "subsystems",
                 "guard", "reconcile", "costs"],
    "properties": {
        "enabled": {"type": "boolean"},
        "analysisMode": {"type": "string"},
        "headroomFraction": {"type": "number"},
        "deviceBudgetBytes": {"type": ["integer", "null"]},
        "liveBytes": {"type": "integer"},
        # subsystem -> {liveBytes, peakBytes, pins}
        "subsystems": {
            "type": "object",
            "additionalProperties": {
                "type": "object",
                "properties": {"liveBytes": {"type": "integer"},
                               "peakBytes": {"type": "integer"},
                               "pins": {"type": "integer"}},
            },
        },
        "events": {"type": "object",
                   "additionalProperties": {"type": "integer"}},
        "guard": {"type": "object",
                  "properties": {"shrinks": {"type": "integer"},
                                 "refusals": {"type": "integer"}}},
        "reconcile": {"type": "object"},
        # bucket label -> compile-cost row (flops, bytes_accessed,
        # arg/out/temp/generated bytes, derived peak_bytes)
        "costs": {"type": "object",
                  "additionalProperties": {"type": "object"}},
    },
}

_CURVE_ROW_SCHEMA = {
    "type": "object",
    "required": ["applied", "violated", "stranded", "metric"],
    "properties": {
        "applied": {"type": "integer"},
        "violated": {"type": "integer"},
        "stranded": {"type": "integer"},
        "metric": {"type": "number"},
        "resync": {"type": "boolean"},
        "stall": {"type": "integer"},
    },
}

_SOLVE_RECORD_SCHEMA = {
    "type": "object",
    "required": ["id", "timestampMs", "kind"],
    "properties": {
        "id": {"type": "integer"},
        "timestampMs": {"type": "number"},
        "kind": {"type": "string", "enum": ["propose", "what_if"]},
        "goals": {
            "type": "array",
            "items": {
                "type": "object",
                "required": ["goal", "rounds", "moves"],
                "properties": {
                    "goal": {"type": "string"},
                    "rounds": {"type": "integer"},
                    "moves": {"type": "integer"},
                    "stats": {"type": "object"},
                    "curve": {"type": "array", "items": _CURVE_ROW_SCHEMA},
                },
            },
        },
        # what_if records: per-lane early-exit rounds instead of curves.
        "lanes": {"type": "integer"},
        "warmStart": {"type": "boolean"},
        "laneRounds": {"type": "object"},
    },
}

SOLVER_STATS_SCHEMA = {
    "type": "object",
    "required": ["enabled", "records", "version"],
    "properties": {
        "enabled": {"type": "boolean"},
        "recorded": {"type": "integer"},
        "ringSize": {"type": "integer"},
        "records": {"type": "array", "items": _SOLVE_RECORD_SCHEMA},
    },
}

METRICS_HISTORY_SCHEMA = {
    "type": "object",
    "required": ["enabled", "intervalMs", "ringSize", "series", "version"],
    "properties": {
        "enabled": {"type": "boolean"},
        "intervalMs": {"type": "number"},
        "ringSize": {"type": "integer"},
        "samples": {"type": "integer"},
        # True when the series cap (limit param) dropped matching rings.
        "truncated": {"type": "boolean"},
        # sensor name -> [[ts_ms, value], ...] oldest first
        "series": {
            "type": "object",
            "additionalProperties": {
                "type": "array",
                "items": {"type": "array", "items": {"type": "number"}},
            },
        },
    },
}

_PROVENANCE_SCHEMA = {
    # Move provenance: which goal's solve emitted the move and through
    # which pipeline path it reached the final placement.
    "type": ["object", "null"],
    "properties": {
        "goal": {"type": "string"},
        "round": {"type": "integer"},
        "solveId": {"type": ["integer", "null"]},
        "path": {"type": "string",
                 "enum": ["relax", "rounding", "repair", "greedy"]},
        "costDelta": {"type": "number"},
    },
}

EXECUTION_PROGRESS_SCHEMA = {
    "type": "object",
    "required": ["enabled", "active", "version"],
    "properties": {
        "enabled": {"type": "boolean"},
        "active": {"type": "boolean"},
        "batch": {
            "type": "object",
            "properties": {
                "executionId": {"type": "integer"},
                "startedMs": {"type": "number"},
                "principal": {"type": ["string", "null"]},
                "requestId": {"type": ["string", "null"]},
                "total": {"type": "integer"},
                "pathHistogram": {"type": "object",
                                  "additionalProperties": {"type": "integer"}},
                "tunerIncreases": {"type": "integer"},
                "tunerDecreases": {"type": "integer"},
            },
        },
        "tasks": {
            "type": "array",
            "items": {
                "type": "object",
                "required": ["topicPartition", "type", "state"],
                "properties": {
                    "topicPartition": {"type": "string"},
                    "type": {"type": "string"},
                    "state": {"type": "string"},
                    "provenance": _PROVENANCE_SCHEMA,
                },
            },
        },
        "throughput": {
            "type": "object",
            "properties": {
                "completed": {"type": "integer"},
                "remaining": {"type": "integer"},
                "inflight": {"type": "integer"},
                "secondsPerMove": {"type": ["number", "null"]},
                "movesPerSecond": {"type": ["number", "null"]},
                "etaSeconds": {"type": ["number", "null"]},
            },
        },
        "inflightPerBroker": {"type": "object",
                              "additionalProperties": {"type": "integer"}},
        "tunerEvents": {"type": "array", "items": {"type": "object"}},
        "recentBatches": {"type": "array", "items": {"type": "object"}},
    },
}

_FINGERPRINT_SCHEMA = {
    # Model fingerprint: the quality of the monitor snapshot a solve (or
    # the current moment) sees — stamped onto proposals at solve time.
    "type": ["object", "null"],
    "properties": {
        "generation": {"type": "integer"},
        "windowEndMs": {"type": ["number", "null"]},
        "ageMs": {"type": ["number", "null"]},
        "validWindows": {"type": "integer"},
        "validPartitionRatio": {"type": "number"},
        "extrapolatedFraction": {
            "type": "object",
            "properties": {k: {"type": "number"}
                           for k in ("AVG_AVAILABLE", "AVG_ADJACENT",
                                     "FORECAST")},
        },
        "deadBrokers": {"type": "array", "items": {"type": "integer"}},
        "capacitySource": {"type": "string"},
        "kind": {"type": "string", "enum": ["freeze", "delta"]},
        "frozenAtMs": {"type": "number"},
    },
}

MODEL_QUALITY_SCHEMA = {
    "type": "object",
    "required": ["enabled", "fingerprint", "stale", "thresholds",
                 "windowQuality"],
    "properties": {
        "enabled": {"type": "boolean"},
        "fingerprint": _FINGERPRINT_SCHEMA,
        "stale": {"type": ["string", "null"]},
        "thresholds": {
            "type": "object",
            "properties": {
                "minValidPartitionRatio": {"type": "number"},
                "maxAgeMs": {"type": "integer"},
            },
        },
        "windowQuality": {
            "type": "array",
            "items": {
                "type": "object",
                "required": ["window", "windowEndMs", "closedAtMs",
                             "ingestCommitMs"],
                "properties": {
                    "window": {"type": "integer"},
                    "windowEndMs": {"type": "number"},
                    "closedAtMs": {"type": "number"},
                    "ingestCommitMs": {"type": "number"},
                },
            },
        },
        "recentFingerprints": {"type": "array",
                               "items": _FINGERPRINT_SCHEMA},
        "livenessFlaps": {
            "type": "array",
            "items": {
                "type": "object",
                "properties": {
                    "broker": {"type": "integer"},
                    "alive": {"type": "boolean"},
                    "atMs": {"type": "number"},
                },
            },
        },
        "lastFetch": {
            "type": "object",
            "properties": {
                "partitionSamples": {"type": "integer"},
                "brokerSamples": {"type": "integer"},
                "atMs": {"type": ["number", "null"]},
            },
        },
    },
}

_HEALTH_PROBE_SCHEMA = {
    "type": "object",
    "required": ["status"],
    "properties": {
        "status": {"type": "string",
                   "enum": ["ready", "degraded", "unhealthy"]},
        "reason": {"type": "string"},
    },
}

HEALTH_SCHEMA = {
    "type": "object",
    "required": ["status", "probes"],
    "properties": {
        "status": {"type": "string",
                   "enum": ["ready", "degraded", "unhealthy"]},
        "probes": {
            "type": "object",
            "required": ["model", "backend", "device", "journal"],
            "properties": {k: _HEALTH_PROBE_SCHEMA
                           for k in ("model", "backend", "device", "journal")},
        },
    },
}

ENDPOINT_SCHEMAS: Dict[str, Dict] = {
    "state": STATE_SCHEMA,
    "load": LOAD_SCHEMA,
    "partition_load": PARTITION_LOAD_SCHEMA,
    "proposals": OPERATION_RESULT_SCHEMA,
    "rebalance": OPERATION_RESULT_SCHEMA,
    "add_broker": OPERATION_RESULT_SCHEMA,
    "remove_broker": OPERATION_RESULT_SCHEMA,
    "demote_broker": OPERATION_RESULT_SCHEMA,
    "fix_offline_replicas": OPERATION_RESULT_SCHEMA,
    "topic_configuration": OPERATION_RESULT_SCHEMA,
    "user_tasks": USER_TASKS_SCHEMA,
    "kafka_cluster_state": KAFKA_CLUSTER_STATE_SCHEMA,
    "bootstrap": MESSAGE_SCHEMA,
    "train": TRAIN_SCHEMA,
    "cancel_user_task": MESSAGE_SCHEMA,
    "stop_proposal_execution": MESSAGE_SCHEMA,
    "pause_sampling": MESSAGE_SCHEMA,
    "resume_sampling": MESSAGE_SCHEMA,
    "review_board": REVIEW_BOARD_SCHEMA,
    "review": REVIEW_BOARD_SCHEMA,
    "admin": ADMIN_SCHEMA,
    "metrics": METRICS_JSON_SCHEMA,
    "metrics/history": METRICS_HISTORY_SCHEMA,
    "solver_stats": SOLVER_STATS_SCHEMA,
    "compile_cache": COMPILE_CACHE_SCHEMA,
    "trace": TRACE_SCHEMA,
    "profile": PROFILE_SCHEMA,
    "memory": MEMORY_SCHEMA,
    "execution_progress": EXECUTION_PROGRESS_SCHEMA,
    "model_quality": MODEL_QUALITY_SCHEMA,
    "health": HEALTH_SCHEMA,
}
