"""OpenAPI spec assembled from the live endpoint surface.

Reference: ``cruise-control/src/yaml/base.yaml`` + ``yaml/endpoints/*.yaml``
+ ``yaml/responses/*.yaml`` — the reference ships a hand-maintained OpenAPI
tree and ``ResponseTest.java`` validates live responses against it.  Here
the spec is GENERATED from the same tables the server dispatches on
(``GET_ENDPOINTS``/``POST_ENDPOINTS``) and the same response schemas the
tests validate (``schemas.ENDPOINT_SCHEMAS``), so it cannot drift from the
implementation: a new endpoint without spec metadata fails the build, and
the committed ``docs/openapi.yaml`` is asserted current by
``tests/test_servlet.py``.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from cruise_control_tpu.servlet import schemas
from cruise_control_tpu.servlet.server import GET_ENDPOINTS, POST_ENDPOINTS

API_PREFIX = "/kafkacruisecontrol"

#: endpoint -> (summary, [(param, type, description)], minimum role)
ENDPOINT_INFO: Dict[str, Tuple[str, List[Tuple[str, str, str]], str]] = {
    "state": ("Monitor/Executor/Analyzer/AnomalyDetector state", [
        ("substates", "string", "comma list restricting the sections"),
        ("verbose", "boolean", "include per-window/selfheal detail"),
    ], "USER"),
    "load": ("Per-broker load statistics (ClusterModelStats)", [
        ("allow_capacity_estimation", "boolean",
         "permit estimated broker capacities"),
    ], "USER"),
    "partition_load": ("Partitions sorted by utilization", [
        ("entries", "integer", "max records returned"),
    ], "USER"),
    "kafka_cluster_state": ("Partition/replica placement as the cluster "
                            "reports it", [], "VIEWER"),
    "user_tasks": ("Active and completed async tasks", [], "VIEWER"),
    "review_board": ("Two-step-verification queue", [], "VIEWER"),
    "proposals": ("Cached/derived rebalance proposals (dryrun)", [
        ("goals", "string", "comma list of goal names"),
        ("kafka_assigner", "boolean", "assigner-mode goal pair"),
        ("excluded_topics", "string", "regex of topics to exclude"),
        ("deadline_ms", "number", "wall-clock solve budget; on expiry the "
         "best-so-far placement returns tagged partial"),
        ("explain", "boolean", "include per-move provenance (originating "
         "goal, solve round/id, relax/rounding/repair/greedy path, cost "
         "delta) and the provenancePaths histogram in the response"),
    ], "USER"),
    "bootstrap": ("Re-ingest historical samples", [
        ("start", "number", "range start ms"),
        ("end", "number", "range end ms"),
    ], "ADMIN"),
    "train": ("Fit the linear CPU estimation model", [
        ("start", "number", "range start ms"),
        ("end", "number", "range end ms"),
    ], "ADMIN"),
    "metrics": ("Sensor registry (Prometheus text, or JSON with "
                "?json=true)", [
        ("json", "boolean", "JSON snapshot instead of Prometheus text"),
    ], "VIEWER"),
    "solver_stats": ("Solver convergence observatory: flight-recorder ring "
                     "of per-solve per-goal round curves (applied moves, "
                     "violated count, stranded, goal metric, resync/stall "
                     "flags) with derived stats; per-lane early-exit rounds "
                     "for what-if batches; empty unless trace.solver.rounds", [
        ("limit", "integer", "return only the newest N records"),
    ], "VIEWER"),
    "metrics/history": ("Bounded per-sensor time-series rings sampled from "
                        "the metric registry by the obsvc history thread "
                        "(obs.history.*); the SLO burn-rate evaluator reads "
                        "the same rings", [
        ("sensor", "string", "fnmatch pattern restricting the sensors "
         "(glob, e.g. Memory.*)"),
        ("since_ms", "number", "drop samples older than this epoch ms"),
        ("limit", "integer", "max series returned (default 64, cap 1024); "
         "truncated=true in the body when matches were dropped"),
    ], "VIEWER"),
    "execution_progress": ("Execution observatory: the active batch's "
                           "per-task live state joined with each move's "
                           "provenance record (originating goal, solve "
                           "round/id, relax/rounding/repair/greedy path, "
                           "cost delta), per-broker inflight counts, the "
                           "EWMA moves-per-second throughput and batch ETA, "
                           "recent batch summaries and AIMD concurrency-"
                           "tuner events; 404 while "
                           "execution.observatory.enabled=false", [],
                           "VIEWER"),
    "model_quality": ("Fidelity observatory: the current model fingerprint "
                      "(generation, newest-valid-window age, valid-partition "
                      "ratio, per-kind extrapolated fractions, dead brokers, "
                      "capacity source) with its staleness verdict against "
                      "the anomaly.model.* thresholds, the per-window "
                      "quality ring (ingest→commit latency per close), "
                      "broker-liveness flaps and the last fetch summary; "
                      "404 while monitor.fidelity.enabled=false", [],
                      "VIEWER"),
    "memory": ("Device-memory observatory: per-subsystem live-bytes ledger, "
               "backend reconciliation, headroom-guard shrink/refusal "
               "counters, and per-executable compile-cost rows "
               "(flops, bytes-accessed, arg/out/temp bytes, derived peak); "
               "404 while memory.enabled=false", [], "VIEWER"),
    "compile_cache": ("Compile-service state: shape-bucket policy, compiled "
                      "lane widths, persistent XLA cache, warmup progress, "
                      "per-bucket compile/hit/miss counters", [], "VIEWER"),
    "trace": ("Recent root span trees (per-request / precompute / executor "
              "batch) and the per-phase time rollup; empty unless "
              "trace.enabled", [], "VIEWER"),
    "health": ("Component health probes (model freshness, admin backend "
               "circuit, accelerator liveness, crash-journal lag) with a "
               "ready/degraded/unhealthy rollup; 503 + Retry-After while "
               "unhealthy", [], "VIEWER"),
    "profile": ("Open a JAX device+host profile capture window for "
                "duration_s seconds on a background thread (202; poll "
                "GET /profile) writing a TensorBoard trace directory; "
                "409 while a window is already open", [
        ("duration_s", "number", "capture window seconds (default 2, "
         "max 600)"),
    ], "ADMIN"),
    "rebalance": ("Full-cluster rebalance", [
        ("dryrun", "boolean", "propose only (default true)"),
        ("goals", "string", "comma list of goal names"),
        ("kafka_assigner", "boolean", "assigner-mode goal pair"),
        ("rebalance_disk", "boolean", "balance between each broker's disks"),
        ("destination_broker_ids", "string", "comma list of allowed targets"),
        ("excluded_topics", "string", "regex of topics to exclude"),
        ("only_move_immigrant_replicas", "boolean",
         "restrict to immigrant replicas"),
        ("deadline_ms", "number", "wall-clock solve budget; on expiry the "
         "best-so-far placement returns tagged partial"),
        ("explain", "boolean", "include per-move provenance and the "
         "provenancePaths histogram in the response"),
    ], "ADMIN"),
    "add_broker": ("Move load onto new brokers", [
        ("brokerid", "string", "comma list of broker ids"),
        ("dryrun", "boolean", "propose only"),
        ("goals", "string", "comma list of goal names"),
        ("throttle_added_broker", "boolean", "apply replication throttle"),
        ("deadline_ms", "number", "wall-clock solve budget"),
        ("explain", "boolean", "include per-move provenance in the response"),
    ], "ADMIN"),
    "remove_broker": ("Decommission brokers", [
        ("brokerid", "string", "comma list of broker ids"),
        ("dryrun", "boolean", "propose only"),
        ("goals", "string", "comma list of goal names"),
        ("destination_broker_ids", "string", "comma list of allowed targets"),
        ("deadline_ms", "number", "wall-clock solve budget"),
        ("explain", "boolean", "include per-move provenance in the response"),
    ], "ADMIN"),
    "demote_broker": ("Shed leadership from brokers", [
        ("brokerid", "string", "comma list of broker ids"),
        ("dryrun", "boolean", "propose only"),
        ("deadline_ms", "number", "wall-clock solve budget"),
        ("explain", "boolean", "include per-move provenance in the response"),
    ], "ADMIN"),
    "fix_offline_replicas": ("Re-replicate offline replicas", [
        ("dryrun", "boolean", "propose only"),
        ("goals", "string", "comma list of goal names"),
        ("deadline_ms", "number", "wall-clock solve budget"),
        ("explain", "boolean", "include per-move provenance in the response"),
    ], "ADMIN"),
    "topic_configuration": ("Change topic replication factor", [
        ("topic", "string", "topic regex"),
        ("replication_factor", "integer", "target RF"),
        ("dryrun", "boolean", "propose only"),
        ("goals", "string", "comma list of goal names"),
        ("deadline_ms", "number", "wall-clock solve budget"),
        ("explain", "boolean", "include per-move provenance in the response"),
    ], "ADMIN"),
    "cancel_user_task": ("Abort an in-flight 202 operation: fires its solve "
                         "budget's cancellation token; the solve stops at "
                         "the next segment/goal boundary and the task "
                         "completes with its partial result (never "
                         "executed)", [
        ("user_task_id", "string",
         "task to cancel (or User-Task-ID header)"),
    ], "ADMIN"),
    "stop_proposal_execution": ("Abort the in-flight execution", [], "ADMIN"),
    "pause_sampling": ("Pause metric sampling", [
        ("reason", "string", "audit note"),
    ], "ADMIN"),
    "resume_sampling": ("Resume metric sampling", [
        ("reason", "string", "audit note"),
    ], "ADMIN"),
    "admin": ("Runtime admin toggles", [
        ("enable_self_healing_for", "string", "comma list of anomaly types"),
        ("disable_self_healing_for", "string", "comma list of anomaly types"),
        ("concurrent_partition_movements_per_broker", "integer",
         "executor concurrency cap"),
    ], "ADMIN"),
    "review": ("Approve/discard parked two-step requests", [
        ("approve", "string", "comma list of review ids"),
        ("discard", "string", "comma list of review ids"),
        ("reason", "string", "audit note"),
    ], "ADMIN"),
}

#: Routes served under BOTH verbs: ENDPOINT_INFO describes the POST
#: operation; this table supplies the GET operation (summary, params,
#: role, component name, response schema).
DUAL_GET_INFO: Dict[str, Tuple[str, List[Tuple[str, str, str]], str,
                               str, Dict]] = {
    "profile": ("Pollable profile-capture status: busy while a window is "
                "open, done + trace_dir once the last async capture landed",
                [], "VIEWER", "ProfileStatusResponse",
                schemas.PROFILE_STATUS_SCHEMA),
}

#: Schema components referenced by more than one endpoint get one shared
#: component name; everything else is named after its endpoint.
_SHARED = {
    id(schemas.OPERATION_RESULT_SCHEMA): "OptimizationResult",
    id(schemas.MESSAGE_SCHEMA): "Message",
    id(schemas.REVIEW_BOARD_SCHEMA): "ReviewBoard",
}

ERROR_SCHEMA = {
    "type": "object",
    "required": ["error"],
    "properties": {"error": {"type": "string"}},
}

PROGRESS_SCHEMA = {
    "type": "object",
    "required": ["progress"],
    "properties": {"progress": {"type": "array",
                                "items": {"type": "object"}}},
}


def _component_name(endpoint: str) -> str:
    schema = schemas.ENDPOINT_SCHEMAS[endpoint]
    # Slash endpoints (metrics/history) camel-case like underscores do.
    return _SHARED.get(id(schema)) or "".join(
        part.capitalize()
        for part in endpoint.replace("/", "_").split("_")) + "Response"


def build_spec() -> Dict:
    """The OpenAPI 3.0 document as a plain dict (YAML-ready)."""
    missing = (GET_ENDPOINTS | POST_ENDPOINTS) - set(ENDPOINT_INFO)
    if missing:
        raise AssertionError(
            f"endpoints without OpenAPI metadata: {sorted(missing)} — add "
            "them to servlet/openapi.py ENDPOINT_INFO")

    components: Dict[str, Dict] = {"Error": ERROR_SCHEMA,
                                   "AsyncProgress": PROGRESS_SCHEMA}
    paths: Dict[str, Dict] = {}
    for endpoint, (summary, params, role) in sorted(ENDPOINT_INFO.items()):
        # Dual-verb routes: ENDPOINT_INFO is the POST operation, the GET
        # operation comes from DUAL_GET_INFO below.
        if endpoint in POST_ENDPOINTS:
            method = "post"
        else:
            method = "get"
        cname = _component_name(endpoint)
        components.setdefault(cname, schemas.ENDPOINT_SCHEMAS[endpoint])
        ref = {"$ref": f"#/components/schemas/{cname}"}
        responses = {
            "200": {"description": "success",
                    "content": {"application/json": {"schema": ref}}},
            "400": {"description": "client error",
                    "content": {"application/json": {"schema":
                                {"$ref": "#/components/schemas/Error"}}}},
        }
        if endpoint == "health":
            responses["503"] = {
                "description": "service unhealthy; Retry-After header set",
                "content": {"application/json": {"schema": ref}}}
        if method == "post" or endpoint in ("proposals",):
            # Long-running operations return 202 + User-Task-ID until done
            # (async servlet machinery; poll with the same header).
            responses["202"] = {
                "description": "operation in progress; poll with the "
                               "returned User-Task-ID header",
                "content": {"application/json": {"schema":
                            {"$ref": "#/components/schemas/AsyncProgress"}}}}
        if endpoint == "memory":
            responses["404"] = {
                "description": "memory ledger disabled (memory.enabled="
                               "false)",
                "content": {"application/json": {"schema":
                            {"$ref": "#/components/schemas/Error"}}}}
        if endpoint == "execution_progress":
            responses["404"] = {
                "description": "execution observatory disabled "
                               "(execution.observatory.enabled=false)",
                "content": {"application/json": {"schema":
                            {"$ref": "#/components/schemas/Error"}}}}
        if endpoint == "model_quality":
            responses["404"] = {
                "description": "fidelity observatory disabled "
                               "(monitor.fidelity.enabled=false)",
                "content": {"application/json": {"schema":
                            {"$ref": "#/components/schemas/Error"}}}}
        if endpoint == "profile":
            responses["409"] = {
                "description": "a capture window is already open",
                "content": {"application/json": {"schema":
                            {"$ref": "#/components/schemas/Error"}}}}
        ops = {method: {
            "operationId": endpoint.replace("/", "_"),
            "summary": summary,
            "description": f"Minimum role: {role}.",
            "parameters": [
                {"name": n, "in": "query", "required": False,
                 "description": d, "schema": {"type": t}}
                for n, t, d in params
            ],
            "responses": responses,
        }}
        if endpoint in GET_ENDPOINTS and method == "post":
            gsummary, gparams, grole, gcname, gschema = \
                DUAL_GET_INFO[endpoint]
            components.setdefault(gcname, gschema)
            ops["get"] = {
                "operationId": f"{endpoint.replace('/', '_')}_status",
                "summary": gsummary,
                "description": f"Minimum role: {grole}.",
                "parameters": [
                    {"name": n, "in": "query", "required": False,
                     "description": d, "schema": {"type": t}}
                    for n, t, d in gparams
                ],
                "responses": {"200": {
                    "description": "success",
                    "content": {"application/json": {"schema":
                                {"$ref": f"#/components/schemas/"
                                         f"{gcname}"}}}}},
            }
        paths[f"{API_PREFIX}/{endpoint}"] = ops
    return {
        "openapi": "3.0.3",
        "info": {
            "title": "cruise-control-tpu REST API",
            "description": "Generated from servlet/openapi.py — do not edit "
                           "docs/openapi.yaml by hand; run "
                           "scripts/gen_openapi.py.",
            "version": "1",
        },
        "paths": paths,
        "components": {"schemas": components},
    }


def render_yaml() -> str:
    import yaml

    return yaml.safe_dump(build_spec(), sort_keys=False,
                          default_flow_style=False, width=79)
