"""Two-step verification purgatory.

Reference: ``servlet/purgatory/Purgatory.java:1-280`` + ``ReviewBoard`` —
when two-step verification is enabled, mutating POST requests park here with
a review id until an admin approves (``REVIEW`` endpoint), then execute by
submitting the approved request.
"""

from __future__ import annotations

import enum
import itertools
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional


class ReviewStatus(enum.Enum):
    PENDING_REVIEW = "PENDING_REVIEW"
    APPROVED = "APPROVED"
    SUBMITTED = "SUBMITTED"
    DISCARDED = "DISCARDED"


_ids = itertools.count()


@dataclass
class RequestInfo:
    review_id: int
    endpoint: str
    query: str
    submitter: str
    status: ReviewStatus = ReviewStatus.PENDING_REVIEW
    reason: str = ""
    submitted_ms: float = field(default_factory=lambda: time.time() * 1000)

    def to_dict(self) -> Dict:
        return {"Id": self.review_id, "EndPoint": self.endpoint,
                "Query": self.query, "Submitter": self.submitter,
                "Status": self.status.value, "Reason": self.reason}


class Purgatory:
    def __init__(self, retention_ms: float = 86_400_000):
        self._requests: Dict[int, RequestInfo] = {}
        self._lock = threading.Lock()
        self.retention_ms = retention_ms

    def add(self, endpoint: str, query: str, submitter: str = "") -> RequestInfo:
        with self._lock:
            info = RequestInfo(next(_ids), endpoint, query, submitter)
            self._requests[info.review_id] = info
            return info

    def review(self, review_id: int, approve: bool, reason: str = "") -> RequestInfo:
        with self._lock:
            info = self._requests[review_id]
            if info.status is not ReviewStatus.PENDING_REVIEW:
                raise ValueError(f"request {review_id} is {info.status.value}")
            info.status = (ReviewStatus.APPROVED if approve
                           else ReviewStatus.DISCARDED)
            info.reason = reason
            return info

    def take_approved(self, review_id: int) -> RequestInfo:
        """Mark an approved request as submitted and return it for execution."""
        with self._lock:
            info = self._requests[review_id]
            if info.status is not ReviewStatus.APPROVED:
                raise ValueError(
                    f"request {review_id} is {info.status.value}, not APPROVED")
            info.status = ReviewStatus.SUBMITTED
            return info

    def board(self) -> List[Dict]:
        with self._lock:
            now = time.time() * 1000
            for rid, info in list(self._requests.items()):
                if (info.status in (ReviewStatus.SUBMITTED, ReviewStatus.DISCARDED)
                        and now - info.submitted_ms > self.retention_ms):
                    del self._requests[rid]
            return [i.to_dict() for i in self._requests.values()]
