"""API layer: REST endpoints, async user tasks, two-step verification.

Reference: ``servlet/KafkaCruiseControlServlet.java`` + the 20-endpoint enum
(``servlet/CruiseControlEndPoint.java:17-36``), ``servlet/UserTaskManager``
async machinery, and ``servlet/purgatory`` two-step review.
"""

from cruise_control_tpu.servlet.user_tasks import UserTaskManager, TaskState
from cruise_control_tpu.servlet.server import CruiseControlApp

__all__ = ["UserTaskManager", "TaskState", "CruiseControlApp"]
