"""Async user-task machinery.

Reference: ``servlet/UserTaskManager.java:66-835`` (session → UUID mapping,
active/completed task rings, per-endpoint retention, 202-until-done
semantics) and ``servlet/handler/async/runnable/OperationFuture.java``.
"""

from __future__ import annotations

import enum
import threading
import time
import uuid
from concurrent.futures import Future, ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from cruise_control_tpu.servlet.progress import OperationProgress


class TaskState(enum.Enum):
    ACTIVE = "Active"
    COMPLETED = "Completed"
    COMPLETED_WITH_ERROR = "CompletedWithError"
    # Terminal: the servlet.user.task.timeout.ms wall-clock cap fired and
    # cancelled the operation's solve budget before it finished on its own.
    TIMED_OUT = "TimedOut"


@dataclass
class UserTask:
    task_id: str
    endpoint: str
    query: str
    future: Future
    progress: OperationProgress
    start_ms: float = field(default_factory=lambda: time.time() * 1000)
    end_ms: float = 0.0
    # Cancellation token shared with the operation's SolveBudget: setting it
    # stops the solve at its next segment / goal boundary.
    cancel_token: Optional[threading.Event] = None
    # Set by the manager's timeout timer IF it fired while still active.
    timed_out: bool = False

    @property
    def state(self) -> TaskState:
        if not self.future.done():
            return TaskState.ACTIVE
        if self.timed_out:
            return TaskState.TIMED_OUT
        return (TaskState.COMPLETED_WITH_ERROR if self.future.exception()
                else TaskState.COMPLETED)

    def cancel(self, reason: str = "user") -> bool:
        """Request cancellation; the operation observes it at its next
        budget checkpoint.  False when the task carries no token (purely
        synchronous or pre-budget tasks)."""
        if self.cancel_token is None:
            return False
        # First reason wins — mirrors SolveBudget.cancel's contract so both
        # wrappers of the shared event report the same reason.
        if getattr(self.cancel_token, "cancel_reason", None) is None:
            self.cancel_token.cancel_reason = reason
        self.cancel_token.set()
        return True

    @property
    def cancel_reason(self) -> Optional[str]:
        if self.cancel_token is None or not self.cancel_token.is_set():
            return None
        return getattr(self.cancel_token, "cancel_reason", "cancelled")

    def to_dict(self) -> Dict:
        d = {
            "UserTaskId": self.task_id,
            "RequestURL": f"{self.endpoint}?{self.query}" if self.query else self.endpoint,
            "Status": self.state.value,
            "StartMs": int(self.start_ms),
        }
        reason = self.cancel_reason
        if reason is not None:
            d["CancelReason"] = reason
        return d


class UserTaskManager:
    """Runs operations on a pool; serves results/progress by task id."""

    def __init__(self, max_active_tasks: int = 25,
                 completed_retention_ms: float = 86_400_000,
                 num_threads: int = 4,
                 task_timeout_ms: Optional[float] = None):
        self._pool = ThreadPoolExecutor(max_workers=num_threads,
                                        thread_name_prefix="user-task")
        # Wall-clock cap on background tasks (servlet.user.task.timeout.ms):
        # when a task outlives it, its cancel token fires with reason
        # "timeout" and the task lands in the TIMED_OUT terminal state.
        # None/<=0 disables.
        self.task_timeout_ms = (task_timeout_ms
                                if task_timeout_ms and task_timeout_ms > 0
                                else None)
        self._tasks: Dict[str, UserTask] = {}
        self._lock = threading.Lock()
        self.max_active = max_active_tasks
        self.retention_ms = completed_retention_ms
        from cruise_control_tpu.common.metrics import registry

        def _active():
            with self._lock:
                return sum(1 for t in self._tasks.values()
                           if t.state is TaskState.ACTIVE)

        def _total():
            with self._lock:
                return len(self._tasks)

        registry().gauge("UserTaskManager.num-active-user-tasks", _active)
        registry().gauge("UserTaskManager.num-user-tasks", _total)

    def submit(self, endpoint: str, query: str,
               operation: Callable[[OperationProgress], Any],
               task_id: Optional[str] = None,
               cancel_token: Optional[threading.Event] = None) -> UserTask:
        with self._lock:
            self._expire_locked()
            active = sum(1 for t in self._tasks.values()
                         if t.state is TaskState.ACTIVE)
            if active >= self.max_active:
                raise RuntimeError(
                    f"too many active user tasks ({active} >= {self.max_active})")
            tid = task_id or str(uuid.uuid4())
            progress = OperationProgress()
            fut = self._pool.submit(self._run, operation, progress)
            task = UserTask(tid, endpoint, query, fut, progress,
                            cancel_token=cancel_token)
            timer: Optional[threading.Timer] = None
            if self.task_timeout_ms is not None and cancel_token is not None:
                def _timeout(t=task):
                    # Benign race with completion: only flag TIMED_OUT when
                    # the operation was actually still running.
                    if not t.future.done():
                        t.timed_out = True
                        t.cancel("timeout")
                timer = threading.Timer(self.task_timeout_ms / 1000.0,
                                        _timeout)
                timer.daemon = True
                timer.start()

            def _done(f, t=task, timer=timer):
                t.end_ms = time.time() * 1000
                if timer is not None:
                    timer.cancel()
            fut.add_done_callback(_done)
            self._tasks[tid] = task
            return task

    @staticmethod
    def _run(operation, progress):
        try:
            return operation(progress)
        finally:
            progress.finish()

    def get(self, task_id: str) -> Optional[UserTask]:
        with self._lock:
            return self._tasks.get(task_id)

    def get_or_create(self, task_id: Optional[str], endpoint: str, query: str,
                      operation,
                      cancel_token: Optional[threading.Event] = None
                      ) -> UserTask:
        """202-until-done semantics: an existing id returns the SAME task."""
        if task_id:
            existing = self.get(task_id)
            if existing is not None:
                return existing
        return self.submit(endpoint, query, operation, task_id=task_id,
                           cancel_token=cancel_token)

    def all_tasks(self) -> List[UserTask]:
        with self._lock:
            self._expire_locked()
            return sorted(self._tasks.values(), key=lambda t: -t.start_ms)

    def _expire_locked(self) -> None:
        now = time.time() * 1000
        for tid, t in list(self._tasks.items()):
            if (t.state is not TaskState.ACTIVE and t.end_ms
                    and now - t.end_ms > self.retention_ms):
                del self._tasks[tid]

    def shutdown(self) -> None:
        self._pool.shutdown(wait=False)
