"""Servlet security providers.

Reference: ``servlet/security/**`` — ``SecurityProvider`` SPI,
``DefaultRoleSecurityProvider.java:33-81`` (three roles: VIEWER →
kafka_cluster_state/user_tasks/review_board, ADMIN → bootstrap/train + every
POST, USER → the remaining GETs), ``BasicSecurityProvider`` (Jetty
HashLoginService over a ``realm.properties``-style credentials file),
``JwtSecurityProvider`` (token auth; HS256 here via stdlib hmac), and
``TrustedProxySecurityProvider`` (auth delegated to an upstream proxy that
asserts the user via header from an allow-listed address).

Everything is stdlib: the server is control-plane and must stay hermetic.

SPNEGO (``servlet/security/spnego/SpnegoSecurityProvider.java`` +
``SpnegoUserStoreAuthorizationService.java``) is implemented as Negotiate
header parsing over a PLUGGABLE ticket validator: the GSSAPI exchange itself
belongs to a Kerberos library this control plane does not vendor, so the
validator is injected (``webserver.auth.spnego.validator.class``) and the
role lookup reuses the same realm-properties user store the reference's
``UserStoreAuthorizationService`` reads.
"""

from __future__ import annotations

import base64
import binascii
import hashlib
import hmac
import json
import threading
import time
from dataclasses import dataclass
from enum import Enum
from typing import Dict, Iterable, Optional, Protocol, Tuple


class Role(Enum):
    VIEWER = "VIEWER"
    USER = "USER"
    ADMIN = "ADMIN"


_RANK = {Role.VIEWER: 0, Role.USER: 1, Role.ADMIN: 2}

# DefaultRoleSecurityProvider.java:50-62.  compile_cache and trace ride the
# VIEWER tier like metrics: pure observability (no cluster data beyond
# shapes and phase timings).
_VIEWER_GET = {"kafka_cluster_state", "user_tasks", "review_board", "metrics",
               "compile_cache", "trace", "health", "solver_stats",
               "metrics/history", "memory", "profile"}
_ADMIN_GET = {"bootstrap", "train"}


def required_role(method: str, endpoint: str) -> Role:
    if method == "POST":
        return Role.ADMIN
    if endpoint in _ADMIN_GET:
        return Role.ADMIN
    if endpoint in _VIEWER_GET:
        return Role.VIEWER
    return Role.USER


def permits(granted: Role, required: Role) -> bool:
    return _RANK[granted] >= _RANK[required]


@dataclass
class Principal:
    name: str
    role: Role


def header_get(headers: Dict[str, str], name: str) -> Optional[str]:
    """Case-insensitive header lookup (HTTP header names are
    case-insensitive; HTTP/2 and many proxies lowercase them)."""
    lower = name.lower()
    for k, v in headers.items():
        if k.lower() == lower:
            return v
    return None


class SecurityProvider(Protocol):
    """authenticate() → Principal, or None when credentials are absent/bad."""

    def authenticate(self, headers: Dict[str, str],
                     client_ip: str) -> Optional[Principal]: ...

    def challenge(self) -> Dict[str, str]:
        """Extra headers for the 401 response."""
        ...


# ----------------------------------------------------------------- HTTP Basic


def parse_credentials_file(path: str) -> Dict[str, Tuple[str, Role]]:
    """Jetty realm.properties style: ``username: password [,ROLE]``."""
    users: Dict[str, Tuple[str, Role]] = {}
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            name, _, rest = line.partition(":")
            parts = [p.strip() for p in rest.split(",")]
            password = parts[0]
            role = Role(parts[1].upper()) if len(parts) > 1 else Role.USER
            users[name.strip()] = (password, role)
    return users


class BasicSecurityProvider:
    """HTTP Basic over a credentials dict or realm-properties file."""

    def __init__(self, users: Optional[Dict[str, Tuple[str, Role]]] = None,
                 credentials_file: Optional[str] = None):
        if users is None and credentials_file is None:
            raise ValueError("BasicSecurityProvider needs users or a file")
        self.users = dict(users or {})
        if credentials_file:
            self.users.update(parse_credentials_file(credentials_file))

    def authenticate(self, headers: Dict[str, str],
                     client_ip: str) -> Optional[Principal]:
        auth = header_get(headers, "Authorization") or ""
        if not auth.startswith("Basic "):
            return None
        try:
            decoded = base64.b64decode(auth[6:], validate=True).decode("utf-8")
            name, _, password = decoded.partition(":")
        except (binascii.Error, UnicodeDecodeError):
            return None
        entry = self.users.get(name)
        # Compare bytes (compare_digest on str raises for non-ASCII), and
        # ALWAYS compare — an early return on unknown usernames would be a
        # timing oracle for username enumeration.
        expected = entry[0].encode() if entry else b"\x00invalid"
        ok = hmac.compare_digest(expected, password.encode())
        if entry is None or not ok:
            return None
        return Principal(name=name, role=entry[1])

    def challenge(self) -> Dict[str, str]:
        return {"WWW-Authenticate": 'Basic realm="cruise-control"'}


# ------------------------------------------------------------------------ JWT


def _b64url(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


def _b64url_decode(data: str) -> bytes:
    pad = "=" * (-len(data) % 4)
    return base64.urlsafe_b64decode(data + pad)


def make_jwt(claims: Dict, secret: str) -> str:
    """HS256 token mint (for tests and the CLI)."""
    header = _b64url(json.dumps({"alg": "HS256", "typ": "JWT"}).encode())
    payload = _b64url(json.dumps(claims).encode())
    signing_input = f"{header}.{payload}".encode()
    sig = hmac.new(secret.encode(), signing_input, hashlib.sha256).digest()
    return f"{header}.{payload}.{_b64url(sig)}"


class JwtSecurityProvider:
    """Bearer-token auth (JwtSecurityProvider ~536 LoC in the reference;
    HS256-only here — the asymmetric variants belong to a deployment's
    identity provider integration)."""

    def __init__(self, secret: str, role_claim: str = "role",
                 subject_claim: str = "sub", clock=time.time):
        self.secret = secret
        self.role_claim = role_claim
        self.subject_claim = subject_claim
        self._clock = clock

    def authenticate(self, headers: Dict[str, str],
                     client_ip: str) -> Optional[Principal]:
        auth = header_get(headers, "Authorization") or ""
        if not auth.startswith("Bearer "):
            return None
        token = auth[7:].strip()
        try:
            header_b64, payload_b64, sig_b64 = token.split(".")
            signing_input = f"{header_b64}.{payload_b64}".encode()
            expected = hmac.new(self.secret.encode(), signing_input,
                                hashlib.sha256).digest()
            if not hmac.compare_digest(expected, _b64url_decode(sig_b64)):
                return None
            header = json.loads(_b64url_decode(header_b64))
            if header.get("alg") != "HS256":
                return None
            claims = json.loads(_b64url_decode(payload_b64))
            # Malformed-but-signed claims (string exp from a misconfigured
            # IdP, array payload) must read as auth failure, not a crash.
            exp = claims.get("exp")
            if exp is not None and self._clock() > float(exp):
                return None
            role = Role(str(claims.get(self.role_claim, "USER")).upper())
            name = str(claims.get(self.subject_claim, "jwt-user"))
        except (ValueError, TypeError, AttributeError, binascii.Error):
            return None
        return Principal(name=name, role=role)

    def challenge(self) -> Dict[str, str]:
        return {"WWW-Authenticate": 'Bearer realm="cruise-control"'}


# -------------------------------------------------------------- trusted proxy


class TrustedProxySecurityProvider:
    """Auth asserted by an upstream proxy: the request must originate from an
    allow-listed address and carry the asserted-user header
    (TrustedProxySecurityProvider in the reference; commonly paired with
    SPNEGO at the proxy)."""

    def __init__(self, trusted_ips: Iterable[str],
                 user_header: str = "X-Forwarded-User",
                 role: Role = Role.ADMIN):
        self.trusted_ips = frozenset(trusted_ips)
        if not self.trusted_ips:
            # Fail at startup: an empty allow-list rejects every request with
            # nothing in the logs pointing at the misconfiguration.
            raise ValueError("TrustedProxySecurityProvider needs at least one "
                             "trusted ip (webserver.auth.trusted.proxy.ips)")
        self.user_header = user_header
        self.role = role

    def authenticate(self, headers: Dict[str, str],
                     client_ip: str) -> Optional[Principal]:
        if client_ip not in self.trusted_ips:
            return None
        user = header_get(headers, self.user_header)
        if not user:
            return None
        return Principal(name=user, role=self.role)

    def challenge(self) -> Dict[str, str]:
        return {}


# --------------------------------------------------------------------- SPNEGO


class SpnegoSecurityProvider:
    """Kerberos Negotiate auth (SpnegoSecurityProvider.java:36-70 +
    SpnegoUserStoreAuthorizationService.java).

    The HTTP side — ``Authorization: Negotiate <base64 GSS token>`` parsing,
    the 401 challenge, principal short-naming (``user/host@REALM`` → user,
    KerberosShortNamer's DEFAULT_TO_LOCAL rule), and user-store role lookup —
    is all here.  The cryptographic ticket validation is delegated to
    ``ticket_validator(token_bytes)``, which returns the authenticated
    principal name (optionally ``(principal, mutual_auth_token_bytes)``) or
    None/raises on a bad ticket.  Deployments supply a GSSAPI-backed
    validator; tests a fake.
    """

    def __init__(self, ticket_validator,
                 roles_by_user: Optional[Dict[str, Role]] = None,
                 credentials_file: Optional[str] = None,
                 default_role: Optional[Role] = Role.USER):
        self.ticket_validator = ticket_validator
        self.roles_by_user = dict(roles_by_user or {})
        if credentials_file:
            for name, (_pw, role) in parse_credentials_file(credentials_file).items():
                self.roles_by_user[name] = role
        # None = users absent from the store are rejected (the reference's
        # user-store authorization returns no roles → 403).
        self.default_role = default_role
        # Per-THREAD: one provider instance serves every request of a
        # ThreadingHTTPServer concurrently; a shared slot would hand one
        # request's GSS mutual-auth material to another's response.
        self._tls = threading.local()

    @staticmethod
    def short_name(principal: str) -> str:
        """``alice/admin.example.com@EXAMPLE.COM`` → ``alice``."""
        return principal.split("@", 1)[0].split("/", 1)[0]

    def authenticate(self, headers: Dict[str, str],
                     client_ip: str) -> Optional[Principal]:
        self._tls.mutual_token = None   # cleared on EVERY path, success or not
        auth = header_get(headers, "Authorization") or ""
        if not auth.startswith("Negotiate "):
            return None
        try:
            token = base64.b64decode(auth[len("Negotiate "):], validate=True)
        except binascii.Error:
            return None
        try:
            result = self.ticket_validator(token)
        except Exception:
            return None
        if isinstance(result, tuple):
            result, self._tls.mutual_token = result
        if not result:
            return None
        name = self.short_name(str(result))
        role = self.roles_by_user.get(name, self.default_role)
        if role is None:
            return None
        return Principal(name=name, role=role)

    def challenge(self) -> Dict[str, str]:
        # RFC 4559: bare challenge on 401; mutual-auth token after success is
        # attached by the caller via mutual_auth_header().
        return {"WWW-Authenticate": "Negotiate"}

    def mutual_auth_header(self) -> Dict[str, str]:
        """Success-response headers for the CURRENT thread's exchange; the
        servlet merges these into the 2xx reply (RFC 4559 §4.2)."""
        token = getattr(self._tls, "mutual_token", None)
        if token is None:
            return {}
        return {"WWW-Authenticate":
                "Negotiate " + base64.b64encode(token).decode()}
