"""Regenerate docs/openapi.yaml from the live endpoint tables.

The spec is built from servlet/openapi.py (parameter metadata) +
servlet/schemas.py (response schemas) + servlet/server.py (endpoint sets),
so it tracks the implementation; tests/test_servlet.py asserts the
committed artifact matches this generator's output.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cruise_control_tpu.servlet.openapi import render_yaml


def main() -> None:
    out = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "docs", "openapi.yaml")
    with open(out, "w") as f:
        f.write(render_yaml())
    print(f"wrote {out}")


if __name__ == "__main__":
    main()
