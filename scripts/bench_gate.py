"""Benchmark regression gate: diff a bench/profile snapshot against the
committed baselines with per-metric tolerances.

Usage:
    python scripts/bench_gate.py                        # self-diff, exits 0
    python scripts/bench_gate.py --bench NEW.json       # gate a fresh run
    python scripts/bench_gate.py --profile NEW.json
    python scripts/bench_gate.py --bench-baseline BENCH_r04.json ...

With no arguments the committed snapshots are compared against themselves
— a structural smoke (parsers work, every metric extracts, tolerances
resolve) that always exits 0.  Point ``--bench`` / ``--profile`` at a
freshly captured artifact to gate it: any "higher is worse" metric (wall
clock, per-goal ms, peak/temp bytes) exceeding ``baseline * ratio +
slack`` is a regression; the gate lists them all and exits 1.  Runnable
in CI and wrapped as a slow test (tests/test_memory.py).

Accepted bench formats: the committed driver wrapper ``{n, cmd, rc,
tail}`` whose ``tail`` holds JSON-lines rows (the first line may be
truncated mid-object — tolerated), a plain JSON list of rows, or a
.jsonl file.  Duplicate metrics keep the LATEST row, matching how the
driver tail overwrites earlier runs.
"""

from __future__ import annotations

import fnmatch
import json
import os
import sys
from typing import Dict, List, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_BENCH_BASELINE = os.path.join(REPO, "BENCH_r05.json")
DEFAULT_PROFILE_BASELINE = os.path.join(REPO, "profile_r05.json")

# (check-name glob, ratio, absolute slack) — first match wins.  Ratios sit
# well under 2 so an injected 2x regression always trips; the absolute
# slack keeps sub-hundredth-of-a-second metrics from flapping on noise.
TOLERANCES: List[Tuple[str, float, float]] = [
    ("bench:*:peak_bytes", 1.25, float(1 << 20)),
    ("bench:*:temp_bytes", 1.25, float(1 << 20)),
    ("bench:*:value", 1.5, 0.05),            # seconds
    ("profile:*:total_s", 1.5, 0.5),
    ("profile:*:ms", 1.6, 50.0),
    ("profile:*:peak_bytes", 1.25, float(1 << 20)),
    ("*", 1.5, 0.0),
]


def tolerance_for(name: str) -> Tuple[float, float]:
    for pattern, ratio, slack in TOLERANCES:
        if fnmatch.fnmatch(name, pattern):
            return ratio, slack
    return 1.5, 0.0


# ---------------------------------------------------------------- parsing

def _bench_rows(doc) -> List[dict]:
    if isinstance(doc, list):
        return [r for r in doc if isinstance(r, dict)]
    if isinstance(doc, dict) and "tail" in doc:
        rows = []
        for line in str(doc["tail"]).splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except ValueError:
                continue        # tail's first line is often cut mid-object
            if isinstance(row, dict) and "metric" in row:
                rows.append(row)
        return rows
    raise ValueError("unrecognized bench snapshot format")


def load_bench(path: str) -> Dict[str, float]:
    """Flatten a bench snapshot to ``bench:<metric>:<col> -> value`` for
    every higher-is-worse numeric column.  Duplicate metrics: latest wins
    (rows are ordered; dict assignment overwrites)."""
    with open(path) as f:
        raw = f.read()
    try:
        doc = json.loads(raw)
    except ValueError:
        # .jsonl: one row per line
        rows = _bench_rows({"tail": raw})
    else:
        rows = _bench_rows(doc)     # unrecognized JSON shape: ValueError
    out: Dict[str, float] = {}
    for row in rows:
        metric = row.get("metric")
        if not metric:
            continue
        for col in ("value", "value_per_lane", "peak_bytes", "temp_bytes"):
            v = row.get(col)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                out[f"bench:{metric}:{col}"] = float(v)
    return out


def load_profile(path: str) -> Dict[str, float]:
    """Flatten a profile artifact to ``profile:<pass>[:<goal>]:<col>``."""
    with open(path) as f:
        doc = json.load(f)
    out: Dict[str, float] = {}
    for pass_name, p in (doc.get("passes") or {}).items():
        if isinstance(p.get("total_s"), (int, float)):
            out[f"profile:{pass_name}:total_s"] = float(p["total_s"])
        for g in p.get("goals") or []:
            goal = g.get("goal", "?")
            for col in ("ms", "peak_bytes"):
                v = g.get(col)
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    out[f"profile:{pass_name}:{goal}:{col}"] = float(v)
    return out


# ---------------------------------------------------------------- compare

def compare(baseline: Dict[str, float],
            current: Dict[str, float]) -> Tuple[int, List[str]]:
    """(metrics compared, regression descriptions).  Only metrics present
    on BOTH sides are gated — new columns (e.g. peak_bytes against an
    older baseline) pass by default, removed ones are reported too."""
    regressions: List[str] = []
    shared = sorted(set(baseline) & set(current))
    for name in shared:
        base, cur = baseline[name], current[name]
        ratio, slack = tolerance_for(name)
        limit = base * ratio + slack
        if cur > limit:
            regressions.append(
                f"{name}: {cur:g} > limit {limit:g} "
                f"(baseline {base:g}, x{ratio:g} + {slack:g})")
    return len(shared), regressions


def main(argv: List[str]) -> int:
    args = list(argv)

    def opt(flag: str, default: str) -> str:
        if flag in args:
            i = args.index(flag)
            value = args[i + 1]
            del args[i:i + 2]
            return value
        return default

    bench_baseline = opt("--bench-baseline", DEFAULT_BENCH_BASELINE)
    profile_baseline = opt("--profile-baseline", DEFAULT_PROFILE_BASELINE)
    bench_current = opt("--bench", bench_baseline)
    profile_current = opt("--profile", profile_baseline)
    if args:
        print(f"bench_gate: unknown arguments {args}", file=sys.stderr)
        return 2

    compared = 0
    regressions: List[str] = []
    for label, loader, base_path, cur_path in (
            ("bench", load_bench, bench_baseline, bench_current),
            ("profile", load_profile, profile_baseline, profile_current)):
        if not (os.path.exists(base_path) and os.path.exists(cur_path)):
            print(f"bench_gate: {label}: snapshot missing "
                  f"({base_path} / {cur_path}) — skipped")
            continue
        try:
            base = loader(base_path)
            cur = loader(cur_path)
        except (ValueError, OSError, KeyError) as e:
            print(f"bench_gate: {label}: unreadable snapshot: {e}",
                  file=sys.stderr)
            return 2
        n, regs = compare(base, cur)
        print(f"bench_gate: {label}: {n} metrics compared "
              f"({os.path.basename(cur_path)} vs "
              f"{os.path.basename(base_path)}), {len(regs)} regressions")
        compared += n
        regressions.extend(regs)

    if compared == 0:
        print("bench_gate: nothing compared (no snapshots found)",
              file=sys.stderr)
        return 2
    if regressions:
        print(f"bench_gate: FAIL — {len(regressions)} regression(s):",
              file=sys.stderr)
        for r in regressions:
            print(f"  {r}", file=sys.stderr)
        return 1
    print(f"bench_gate: OK — {compared} metrics within tolerance")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
