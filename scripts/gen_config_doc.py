"""Regenerate docs/CONFIGURATION.md from the live ConfigDef."""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cruise_control_tpu.utils.hermetic import force_cpu

force_cpu()

from cruise_control_tpu.config.cruise_control_config import CruiseControlConfig

HEADER = """# Configuration reference

Key names match the reference's `cruisecontrol.properties` (a reference
properties file parses directly; goal lists also accept fully-qualified Java
class names).  Generated from `cruise_control_tpu/config/cruise_control_config.py`
by `scripts/gen_config_doc.py`.

| Key | Type | Default | Notes |
|---|---|---|---|
"""


def main() -> None:
    cfg = CruiseControlConfig()
    out = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "docs", "CONFIGURATION.md")
    with open(out, "w") as f:
        f.write(HEADER)
        for name, k in sorted(cfg.definition.keys().items()):
            dv = "" if k.default is None else str(k.default)
            if len(dv) > 60:
                dv = dv[:57] + "..."
            f.write(f"| `{name}` | {k.config_type.value} "
                    f"| `{dv.replace('|', chr(92) + '|')}` "
                    f"| {(k.doc or '').replace('|', chr(92) + '|')} |\n")
    print(f"wrote {out}")


if __name__ == "__main__":
    main()
