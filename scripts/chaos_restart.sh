#!/usr/bin/env bash
# Crash-recovery drill: start an execution against a live (out-of-process)
# broker simulator, kill -9 the executor process mid-flight, restart, and
# assert the write-ahead journal reconciles every task — re-adopted tasks
# drain to completion, never-submitted tasks roll back, nothing is lost —
# with the health view going degraded (journal lag) -> ready.
#
# Usage:   ./scripts/chaos_restart.sh
# Exit 0 + "PASS" when the drill holds; nonzero with context otherwise.
set -euo pipefail

cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
WORK="$(mktemp -d)"
JOURNAL="$WORK/executor-journal.jsonl"
SIM_OUT="$WORK/sim.out"

cleanup() {
  [[ -n "${SIM_PID:-}" ]] && kill -9 "$SIM_PID" 2>/dev/null || true
  rm -rf "$WORK"
}
trap cleanup EXIT

# --- live admin peer: out-of-process simulator on an ephemeral port -------
python -m cruise_control_tpu.executor.broker_simulator \
  --listen 0 --polls-to-finish 3 >"$SIM_OUT" &
SIM_PID=$!
for _ in $(seq 50); do
  grep -q listening "$SIM_OUT" 2>/dev/null && break
  sleep 0.1
done
PORT="$(python -c "import json,sys; print(json.load(open('$SIM_OUT'))['listening'])")"
echo "simulator up on :$PORT (pid $SIM_PID)"

# --- phase 1: journal a batch, get tasks in flight, kill -9 ourselves -----
# The SIGKILL is the point: no atexit, no finally, no end_batch record —
# exactly what a crashed or OOM-killed executor leaves behind.
set +e
JOURNAL="$JOURNAL" PORT="$PORT" python - <<'EOF'
import os, signal, time

from cruise_control_tpu.common.actions import (ExecutionProposal,
                                               ReplicaPlacementInfo,
                                               TopicPartition)
from cruise_control_tpu.executor.executor import Executor, ExecutorConfig
from cruise_control_tpu.executor.journal import ExecutionJournal
from cruise_control_tpu.executor.subprocess_backend import SocketClusterBackend

backend = SocketClusterBackend("127.0.0.1", int(os.environ["PORT"]),
                               request_timeout_s=5.0)
backend.request("bootstrap", partitions=[
    {"topic": "T", "partition": p, "replicas": [0, 1], "leader": 0,
     "logdirs": {"0": 0, "1": 0}} for p in range(4)])

ex = Executor(backend, ExecutorConfig(progress_check_interval_s=0.01))
ex.set_journal(ExecutionJournal(os.environ["JOURNAL"]))


def proposal(p):
    return ExecutionProposal(
        topic_partition=TopicPartition("T", p), partition_size=100.0,
        old_leader=ReplicaPlacementInfo(0),
        old_replicas=(ReplicaPlacementInfo(0), ReplicaPlacementInfo(1)),
        new_replicas=(ReplicaPlacementInfo(2), ReplicaPlacementInfo(1)))


ex.execute_proposals([proposal(p) for p in range(4)], wait=False)
deadline = time.monotonic() + 10.0
while not backend.in_progress_reassignments():
    if time.monotonic() > deadline:
        raise SystemExit("tasks never reached the cluster")
    time.sleep(0.01)
print("phase 1: batch journaled, tasks in flight -- kill -9", flush=True)
os.kill(os.getpid(), signal.SIGKILL)
EOF
RC=$?
set -e
if [[ "$RC" -ne 137 && "$RC" -ne 9 ]]; then
  echo "FAIL: phase 1 exited rc=$RC, expected SIGKILL (137)" >&2
  exit 1
fi
if [[ ! -s "$JOURNAL" ]]; then
  echo "FAIL: no journal left behind at $JOURNAL" >&2
  exit 1
fi

# --- phase 2: restart, reconcile, drain, assert nothing was lost ----------
JOURNAL="$JOURNAL" PORT="$PORT" python - <<'EOF'
import json, os, time

from cruise_control_tpu.executor.executor import Executor, ExecutorConfig
from cruise_control_tpu.executor.journal import ExecutionJournal
from cruise_control_tpu.executor.subprocess_backend import SocketClusterBackend

path = os.environ["JOURNAL"]
journal = ExecutionJournal(path)
lag = journal.lag()
assert lag > 0, "restart should see journal lag (health: degraded)"
print(f"phase 2: journal lag {lag} -> health degraded; reconciling")

backend = SocketClusterBackend("127.0.0.1", int(os.environ["PORT"]),
                               request_timeout_s=5.0)
ex = Executor(backend, ExecutorConfig(progress_check_interval_s=0.01))
ex.set_journal(journal)
summary = ex.recover_from_journal(adoption_timeout_s=30.0)
print("recovery:", json.dumps(summary, sort_keys=True))

assert summary["status"] == "reconciled", summary
accounted = (summary["reAdopted"] + summary["completed"]
             + summary["rolledBack"] + summary["stillInFlight"])
assert accounted == summary["journaledTasks"], summary
assert summary["stillInFlight"] == 0, summary
assert not os.path.exists(path), "journal should be retired after reconcile"
assert ExecutionJournal(path).lag() == 0, "health: ready"
assert backend.in_progress_reassignments() == set(), "cluster fully drained"
print("phase 2: every journaled task re-adopted/completed/rolled back; "
      "health degraded -> ready")
backend.close()
EOF

echo PASS
