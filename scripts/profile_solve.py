"""Per-goal timing/rounds breakdown of the headline bench config.

Usage: python scripts/profile_solve.py [cpu|tpu] [small|big] [--json PATH]

Mirrors GoalOptimizer.optimizations goal-by-goal with explicit per-goal
timing (block_until_ready between goals), after a full warmup pass.
``--json PATH`` additionally writes the machine-readable artifact
(per-goal warmup/steady ms, rounds, moves, violations, plus per-bucket
executable cost columns from the memory observatory's full-analysis
ledger; the committed profile_r{N}.json files are produced this way).
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax


def main() -> None:
    args = list(sys.argv[1:])
    json_path = None
    if "--json" in args:
        i = args.index("--json")
        json_path = args[i + 1]
        del args[i:i + 2]
    want = args[0] if args else "tpu"
    size = args[1] if len(args) > 1 else "small"
    from cruise_control_tpu.utils.hermetic import (
        enable_persistent_compilation_cache,
        force_cpu,
        probe_tpu,
    )
    cache_warm = enable_persistent_compilation_cache()
    if want != "tpu" or not probe_tpu():
        force_cpu()
        backend = "cpu"
    else:
        backend = "tpu"

    from bench import GOALS

    from cruise_control_tpu.analyzer import GoalOptimizer
    from cruise_control_tpu.analyzer.context import build_context
    from cruise_control_tpu.analyzer.goals.registry import get_goals_by_priority
    from cruise_control_tpu.analyzer.options import OptimizationOptions
    from cruise_control_tpu.testing import random_cluster as rc

    if size == "big":
        props = rc.ClusterProperties(
            num_brokers=2600, num_racks=40, num_topics=2000,
            num_replicas=1_000_000, mean_cpu=0.0035, mean_disk=90.0,
            mean_nw_in=90.0, mean_nw_out=90.0, seed=3141)
    else:
        props = rc.ClusterProperties(
            num_brokers=200, num_racks=10, num_topics=1000,
            num_replicas=50_000, mean_cpu=0.006, mean_disk=90.0,
            mean_nw_in=90.0, mean_nw_out=90.0, seed=3140)
    state, placement, meta = rc.generate(props)
    # Memory observatory in FULL analysis mode: cost rows (flops /
    # bytes-accessed / peak) per executable bucket.  The AOT recompile is
    # deferred to finalize_full(), paid once after the warmup pass so the
    # steady-state timings stay untouched.
    from cruise_control_tpu.obsvc.memory import cost_ledger, memory_ledger
    memory_ledger().configure(enabled=True, analysis_mode="full")
    optimizer = GoalOptimizer(goal_names=GOALS)
    goals = get_goals_by_priority(GOALS)
    gctx = build_context(state, placement, meta, optimizer.constraint,
                         OptimizationOptions())
    solver = optimizer.solver

    artifact = {"backend": backend, "size": size,
                "cache_dir_nonempty": bool(cache_warm), "passes": {}}

    def one_pass(label, pl):
        total0 = time.monotonic()
        priors = []
        rows = []
        agg = None
        for goal in goals:
            labels_before = set(cost_ledger().rows())
            t0 = time.monotonic()
            pl, agg, info = solver.optimize_goal(goal, priors, gctx, pl, agg)
            jax.block_until_ready(pl.broker)
            dt = time.monotonic() - t0
            print(f"  {goal.name:44s} {dt*1000:9.1f} ms rounds={info.rounds:3d} "
                  f"moves={info.moves_applied:6d} "
                  f"violated {info.violated_brokers_before:4d}->"
                  f"{info.violated_brokers_after:4d}")
            rows.append({"goal": goal.name, "ms": round(dt * 1000, 1),
                         "rounds": info.rounds,
                         "ms_per_round": round(dt * 1000 / max(info.rounds, 1), 1),
                         "moves": info.moves_applied,
                         "violated_before": info.violated_brokers_before,
                         "violated_after": info.violated_brokers_after,
                         # Buckets whose first compile landed in this goal's
                         # window — cost columns attach after finalize_full.
                         "cost_labels": sorted(
                             set(cost_ledger().rows()) - labels_before)})
            priors.append(goal)
        total = time.monotonic() - total0
        print(f"{label} total={total:.3f}s")
        artifact["passes"][label] = {"total_s": round(total, 3), "goals": rows}
        return pl

    def attach_costs():
        """Finalize deferred full-mode analysis, then fill per-goal cost
        columns (sum of flops/bytes-accessed, max peak over the buckets the
        goal compiled) and the top-level per-bucket costs table."""
        cost_ledger().finalize_full()
        all_rows = cost_ledger().rows()
        artifact["costs"] = all_rows
        for p in artifact["passes"].values():
            for g in p["goals"]:
                labels = g.pop("cost_labels", [])
                crows = [all_rows[l] for l in labels if l in all_rows]
                g["flops"] = sum(r.get("flops") or 0.0 for r in crows)
                g["bytes_accessed"] = sum(
                    r.get("bytes_accessed") or 0.0 for r in crows)
                g["peak_bytes"] = max(
                    (r.get("peak_bytes") or 0 for r in crows), default=0)

    print(f"backend={backend} size={size}")
    # cache_warm only says the cache DIR holds entries (possibly for a
    # different backend/size) — the label stays neutral.
    print("warmup (compile or cache read; cache dir %s):"
          % ("non-empty" if cache_warm else "empty"))
    one_pass("warmup", placement)
    print("steady-state:")
    one_pass("steady", placement)
    attach_costs()
    print(f"cost rows: {len(artifact['costs'])} buckets "
          f"(max peak_bytes={max((r.get('peak_bytes') or 0 for r in artifact['costs'].values()), default=0)})")
    if json_path:
        import json
        with open(json_path, "w") as f:
            json.dump(artifact, f, indent=1)
        print(f"wrote {json_path}")


if __name__ == "__main__":
    main()
