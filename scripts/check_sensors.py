"""Sensor-doc drift guard: boot the service, scrape /metrics, diff the doc.

docs/SENSORS.md is machine-parsable — one backticked sensor name (or fnmatch
glob for fan-out families) in the first column of each table row.  This
script boots the demo service, drives the endpoints that lazily register
sensors (state, proposals to completion), scrapes ``/metrics?json=true``,
and fails if either side drifted:

- a documented exact name absent from the live scrape, or a documented glob
  matching nothing, means the doc promises a sensor the service no longer
  exports;
- a live sensor matched by no documented row means a sensor was added
  without documenting it.

docs/ENDPOINTS.md rides the same guard: every backticked token in the first
column of its tables is a servlet route, diffed against the live dispatch
tables (``GET_ENDPOINTS`` | ``POST_ENDPOINTS``) — a new endpoint without a
documented row, or a documented row whose route is gone, fails the run.

Run standalone (``python scripts/check_sensors.py``) or via the tier-1
suite — tests/test_sensors.py imports ``parse_sensors_md`` / ``diff`` /
``parse_endpoints_md`` / ``endpoints_diff`` / ``collect_live`` from here
and asserts no drift.
"""

from __future__ import annotations

import fnmatch
import json
import os
import re
import sys
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SENSORS_MD = os.path.join(REPO, "docs", "SENSORS.md")
ENDPOINTS_MD = os.path.join(REPO, "docs", "ENDPOINTS.md")

_BACKTICK = re.compile(r"`([^`]+)`")


def parse_sensors_md(path: str = SENSORS_MD):
    """Documented sensor patterns: the first backticked token in the first
    column of every table body row (header/separator rows have none)."""
    patterns = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line.startswith("|"):
                continue
            first_cell = line.split("|")[1]
            m = _BACKTICK.search(first_cell)
            if m:
                patterns.append(m.group(1))
    return patterns


def parse_endpoints_md(path: str = ENDPOINTS_MD):
    """Documented endpoint routes: EVERY backticked token in the first
    column of each table body row (a cell like ``pause_sampling /
    resume_sampling`` documents two routes)."""
    endpoints = set()
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line.startswith("|"):
                continue
            endpoints.update(_BACKTICK.findall(line.split("|")[1]))
    return endpoints


def endpoints_diff(documented):
    """``(undocumented, stale)`` against the live servlet dispatch tables —
    routes the server dispatches with no documented row, and documented
    rows whose route the server no longer serves."""
    from cruise_control_tpu.servlet.server import (
        GET_ENDPOINTS, POST_ENDPOINTS)
    live = GET_ENDPOINTS | POST_ENDPOINTS
    return sorted(live - documented), sorted(documented - live)


def diff(documented, live):
    """``(missing, undocumented)`` — documented patterns matching no live
    sensor, and live sensors matched by no documented pattern."""
    live = sorted(live)
    missing = [p for p in documented if not fnmatch.filter(live, p)]
    undocumented = [n for n in live
                    if not any(fnmatch.fnmatch(n, p) for p in documented)]
    return missing, undocumented


def collect_live(timeout_s: float = 90.0):
    """Boot the demo service (tracing ON so ``Trace.*`` timers exist), wait
    for a valid window, run /proposals to completion (first optimization
    registers the GoalOptimizer / provision / CompileService sensors), and
    return the JSON sensor snapshot plus the Prometheus text body."""
    from cruise_control_tpu.config.cruise_control_config import (
        CruiseControlConfig)
    from cruise_control_tpu.main import build_app

    cfg = CruiseControlConfig({"metric.sampling.interval.ms": 300,
                               "partition.metrics.window.ms": 600,
                               "trace.enabled": True,
                               # Relaxation ON so the /proposals run below
                               # EXERCISES the Solver.relax.* sensors (the
                               # distribution goal takes the relax→repair
                               # path), not just registers them at boot.
                               "solver.relaxation.enabled": True})
    app = build_app(cfg, port=0)
    app.cc.start_up()
    app.start()
    try:
        base = f"http://127.0.0.1:{app.port}/kafkacruisecontrol"

        def get(path, headers=None):
            req = urllib.request.Request(base + path, headers=headers or {})
            with urllib.request.urlopen(req) as r:
                return r.status, r.read().decode(), dict(r.headers)

        get("/state")
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            _, body, _ = get("/metrics?json=true")
            snap = json.loads(body)["sensors"]
            if snap.get("LoadMonitor.valid-windows", {}).get("value", 0) > 0:
                break
            time.sleep(0.5)
        # One goal keeps the compile bill small; run it twice — the cold
        # pass registers the GoalOptimizer/provision/compile-count sensors,
        # the warm pass the cache-HIT counters (ignore_cache so the second
        # request re-optimizes instead of returning the cached proposal).
        for attempt in ("", "&ignore_cache=true"):
            qs = "?goals=ReplicaDistributionGoal" + attempt
            status, _, headers = get("/proposals" + qs)
            task_id = headers.get("User-Task-ID")
            while status == 202 and time.time() < deadline:
                time.sleep(0.5)
                status, _, headers = get("/proposals" + qs,
                                         headers={"User-Task-ID": task_id})
            if status != 200:
                raise RuntimeError(f"/proposals did not complete: {status}")
        # memory.enabled defaults True, so the boot activated the device
        # ledger; exercise the endpoint so Memory.* gauges reflect a live
        # drive, not just eager materialization.
        status, _, _ = get("/memory")
        if status != 200:
            raise RuntimeError(f"/memory not serving: {status}")
        # Same for the execution observatory (also default-on): the gauges
        # must read idle zeros through a live /execution_progress drive.
        status, _, _ = get("/execution_progress")
        if status != 200:
            raise RuntimeError(f"/execution_progress not serving: {status}")
        # And the fidelity observatory (default-on): the boot above has the
        # sampler live, so /model_quality must serve window-quality rings
        # and a fingerprint from the /proposals solves.
        status, _, _ = get("/model_quality")
        if status != 200:
            raise RuntimeError(f"/model_quality not serving: {status}")
        _, body, _ = get("/metrics?json=true")
        _, text, _ = get("/metrics")
        return json.loads(body)["sensors"], text
    finally:
        app.stop()
        app.cc.shutdown()
        # Hermeticity for in-suite callers: build_app enabled the process
        # tracer and memory ledger; later test modules expect the
        # default-off state.
        from cruise_control_tpu.obsvc.tracer import tracer
        tracer().configure(enabled=False, ring_size=32)
        tracer().reset()
        from cruise_control_tpu.obsvc.memory import memory_ledger
        memory_ledger().reset()
        memory_ledger().configure(enabled=False)
        # The execution flight recorder defaults ON — reset its rings but
        # leave it enabled (that IS the default state).
        from cruise_control_tpu.obsvc.execution import execution
        execution().reset()
        # Likewise the fidelity recorder (default ON, thresholds default
        # disabled): drop the boot's fingerprints and rings.
        from cruise_control_tpu.obsvc.fidelity import fidelity
        fidelity().reset()
        fidelity().configure(enabled=True, min_valid_partition_ratio=0.0,
                             max_age_ms=0)


def main() -> int:
    documented = parse_sensors_md()
    if not documented:
        print(f"no sensor rows parsed from {SENSORS_MD}", file=sys.stderr)
        return 1
    doc_eps = parse_endpoints_md()
    if not doc_eps:
        print(f"no endpoint rows parsed from {ENDPOINTS_MD}", file=sys.stderr)
        return 1
    undoc_eps, stale_eps = endpoints_diff(doc_eps)
    for e in undoc_eps:
        print(f"SERVED BUT NOT DOCUMENTED: {e}", file=sys.stderr)
    for e in stale_eps:
        print(f"DOCUMENTED BUT NOT SERVED: {e}", file=sys.stderr)
    if undoc_eps or stale_eps:
        print(f"\nendpoint drift: {len(undoc_eps)} undocumented, "
              f"{len(stale_eps)} stale — update docs/ENDPOINTS.md",
              file=sys.stderr)
        return 1
    snap, _ = collect_live()
    missing, undocumented = diff(documented, set(snap))
    for p in missing:
        print(f"DOCUMENTED BUT NOT EXPORTED: {p}", file=sys.stderr)
    for n in undocumented:
        print(f"EXPORTED BUT NOT DOCUMENTED: {n}", file=sys.stderr)
    if missing or undocumented:
        print(f"\nsensor drift: {len(missing)} missing, "
              f"{len(undocumented)} undocumented — update docs/SENSORS.md",
              file=sys.stderr)
        return 1
    print(f"OK: {len(snap)} live sensors covered by "
          f"{len(documented)} documented rows")
    return 0


if __name__ == "__main__":
    sys.path.insert(0, REPO)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    raise SystemExit(main())
