"""TPU-window harvester for the flaky axon tunnel.

The tunneled TPU backend on this box dies unpredictably: probes pass and the
tunnel then drops mid-compile, or init hangs for hours (BASELINE.md round-4
status).  Waiting for the round-end bench run to coincide with a live window
has failed for two rounds.  This daemon inverts the strategy:

- probe the tunnel out-of-process every ``--interval`` seconds;
- on a live probe, run ``bench.py --tpu-child --only <cfg>`` for each config
  not yet captured, SMALLEST COMPILE FIRST (3 → 1 → 2 → 4 → 5), so even a
  short window yields a datapoint;
- persist the XLA compile cache across attempts (``CC_TPU_PERSIST_CACHE=1``
  — TPU executables don't hit the XLA:CPU machine-feature SIGILL documented
  in tests/conftest.py), so a second window skips straight to the big
  configs' execution;
- append every captured ``"backend": "tpu"`` JSON row to
  ``tpu_attempts/captured.jsonl`` (bench.py replays these into the round-end
  artifact with ``"replayed": true``), and every probe/attempt outcome to
  ``tpu_attempts/log.jsonl`` — the honest failure trail if no window ever
  stays alive long enough.

Run detached:  nohup python scripts/tpu_capture.py >/dev/null 2>&1 &
Stop:          touch tpu_attempts/STOP
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DIR = os.path.join(REPO, "tpu_attempts")
BENCH = os.path.join(REPO, "bench.py")

# Config id -> (metric substring proving capture, attempt timeout seconds).
# Ordered smallest-compile-first.
CONFIGS = [
    (3, "200brokers_50k_replicas_full_goals", 1800),
    (1, "deterministic_6brokers_200replicas", 1200),
    (2, "single_resource_distribution_goal", 1200),
    (4, "2600brokers_1m_replicas_full_goals", 2700),
    (5, "remove_broker_what_ifs", 3600),
]


def log(event: str, **extra) -> None:
    row = {"ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
           "event": event, **extra}
    with open(os.path.join(DIR, "log.jsonl"), "a") as f:
        f.write(json.dumps(row) + "\n")


def captured_metrics() -> set:
    out = set()
    try:
        with open(os.path.join(DIR, "captured.jsonl")) as f:
            for line in f:
                if line.strip():
                    out.add(json.loads(line).get("metric", ""))
    except OSError:
        pass
    return out


def probe(timeout_s: float = 180.0) -> bool:
    # Scrub a forced-CPU environment exactly like attempt() does — a daemon
    # launched from a JAX_PLATFORMS=cpu shell must still SEE the TPU, or it
    # reports the tunnel dead forever and never captures anything.
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    try:
        p = subprocess.run(
            [sys.executable, "-c",
             "import jax, sys; d = jax.devices(); "
             "sys.exit(0 if d and d[0].platform != 'cpu' else 1)"],
            timeout=timeout_s, capture_output=True, env=env)
        return p.returncode == 0
    except (subprocess.TimeoutExpired, OSError):
        return False


def attempt(cfg: int, timeout_s: float) -> bool:
    """One bench child on the TPU for one config; harvest its TPU rows."""
    env = dict(os.environ, CC_TPU_PERSIST_CACHE="1")
    env.pop("JAX_PLATFORMS", None)
    t0 = time.monotonic()
    try:
        p = subprocess.run(
            [sys.executable, BENCH, "--tpu-child", "--only", str(cfg)],
            timeout=timeout_s, capture_output=True, text=True, env=env,
            cwd=REPO)
    except subprocess.TimeoutExpired as e:
        log("attempt_timeout", config=cfg, timeout_s=timeout_s,
            stdout_tail=(e.stdout or b"")[-500:].decode("utf-8", "replace")
            if isinstance(e.stdout, bytes) else (e.stdout or "")[-500:])
        return False
    rows = []
    for line in (p.stdout or "").splitlines():
        try:
            row = json.loads(line)
        except ValueError:
            continue
        if row.get("backend") == "tpu" and "metric" in row:
            row["captured_at"] = time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                               time.gmtime())
            rows.append(row)
    if rows:
        with open(os.path.join(DIR, "captured.jsonl"), "a") as f:
            for row in rows:
                f.write(json.dumps(row) + "\n")
    log("attempt_done", config=cfg, rc=p.returncode,
        seconds=round(time.monotonic() - t0, 1), rows_captured=len(rows),
        stderr_tail=(p.stderr or "")[-400:] if p.returncode else "")
    return p.returncode == 0 and bool(rows)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--interval", type=float, default=900.0)
    ap.add_argument("--once", action="store_true",
                    help="single probe+attempt pass, no loop")
    args = ap.parse_args()
    os.makedirs(DIR, exist_ok=True)
    log("daemon_start", interval=args.interval, pid=os.getpid())
    while True:
        if os.path.exists(os.path.join(DIR, "STOP")):
            log("daemon_stop", reason="STOP file")
            return
        have = captured_metrics()
        todo = [(c, t) for c, sub, t in CONFIGS
                if not any(sub in m for m in have)]
        if not todo:
            log("daemon_stop", reason="all configs captured")
            return
        if probe():
            log("probe_live", todo=[c for c, _ in todo])
            for cfg, timeout_s in todo:
                if os.path.exists(os.path.join(DIR, "STOP")):
                    break
                if not attempt(cfg, timeout_s):
                    # Window likely died; back off to the probe loop rather
                    # than burn the remaining configs against a dead tunnel.
                    break
        else:
            log("probe_dead")
        if args.once:
            return
        time.sleep(args.interval)


if __name__ == "__main__":
    main()
