#!/usr/bin/env bash
# Nightly fuzz soak: a larger seed sweep than the tier-1 smoke, with chaos
# storms enabled.  The tier-1 suite pins 8 fixed seeds (tests/test_fuzzsvc.py)
# so CI stays deterministic; this script is where NEW seeds get explored.
#
# Usage:   ./scripts/fuzz_nightly.sh [num_scenarios] [base_seed]
# Output:  one line per scenario; failing scenarios land in
#          ${FUZZ_CORPUS_DIR:-.fuzz-corpus}/failing/*.json together with a
#          shrunk *.min.json, and the replay one-liner is printed at the end.
#
# Pick base_seed from the date by default so every night covers fresh seeds
# while any single night stays reproducible from its log line.
set -euo pipefail

cd "$(dirname "$0")/.."

NUM="${1:-64}"
BASE="${2:-$(date +%Y%m%d)}"
CORPUS="${FUZZ_CORPUS_DIR:-.fuzz-corpus}"

echo "[fuzz-nightly] ${NUM} scenarios from base seed ${BASE} -> ${CORPUS}"
exec env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m cruise_control_tpu.fuzzsvc \
    --num "${NUM}" \
    --base-seed "${BASE}" \
    --storm-cycles "${FUZZ_STORM_CYCLES:-2}" \
    --budget-s "${FUZZ_BUDGET_S:-120}" \
    --corpus-dir "${CORPUS}"
