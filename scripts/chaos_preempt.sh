#!/usr/bin/env bash
# Graceful-drain drill: boot the demo service out-of-process, start a large
# (cold-compile) rebalance solve, SIGTERM mid-solve, and assert the process
# exits within the shutdown grace budget with a clean executor journal —
# i.e. the drain cancelled the in-flight solve and it unwound through its
# next segment boundary instead of running to convergence, and no execution
# state was left behind.
#
# Usage:   ./scripts/chaos_preempt.sh
# Exit 0 + "PASS" when the drill holds; nonzero with context otherwise.
set -euo pipefail

cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
GRACE_MS="${GRACE_MS:-60000}"      # solver.shutdown.grace.ms under test
# Teardown allowance past the grace window: the cancel fires immediately,
# but the solve cannot probe its budget until the in-flight XLA compile
# returns, and that compile is the bulk of a cold "large solve".
SLACK_S="${SLACK_S:-60}"
WORK="$(mktemp -d)"
JOURNAL="$WORK/executor-journal.jsonl"
SVC_OUT="$WORK/svc.out"
CFG="$WORK/drill.properties"

cleanup() {
  [[ -n "${SVC_PID:-}" ]] && kill -9 "$SVC_PID" 2>/dev/null || true
  rm -rf "$WORK"
}
trap cleanup EXIT

cat >"$CFG" <<EOF
metric.sampling.interval.ms=300
partition.metrics.window.ms=600
solver.shutdown.grace.ms=$GRACE_MS
solver.segment.rounds=1
resilience.journal.path=$JOURNAL
EOF

# --- boot the demo service on an ephemeral port ---------------------------
python -m cruise_control_tpu.main --demo --platform cpu \
  --config "$CFG" --port 0 >"$SVC_OUT" 2>&1 &
SVC_PID=$!
for _ in $(seq 300); do
  grep -q "listening on" "$SVC_OUT" 2>/dev/null && break
  kill -0 "$SVC_PID" 2>/dev/null || { cat "$SVC_OUT" >&2; exit 1; }
  sleep 0.2
done
BASE="$(sed -n 's#.*listening on \(http[s]*://[^ ]*\).*#\1#p' "$SVC_OUT" | head -1)"
if [[ -z "$BASE" ]]; then
  echo "FAIL: service never reported its listen address" >&2
  cat "$SVC_OUT" >&2
  exit 1
fi
echo "service up at $BASE (pid $SVC_PID)"

# --- wait for a valid monitoring window, then launch the big solve --------
# One goal keeps the compile bill bounded; the cold XLA compile IS the
# "large solve" — the SIGTERM lands while it is in flight.
BASE="$BASE" python - <<'EOF'
import json, os, time, urllib.request

base = os.environ["BASE"] + "/kafkacruisecontrol"


def get(path, method="GET", headers=None):
    req = urllib.request.Request(base + path, headers=headers or {},
                                 method=method)
    with urllib.request.urlopen(req) as r:
        return r.status, r.read().decode(), dict(r.headers)


deadline = time.monotonic() + 90.0
while time.monotonic() < deadline:
    _, body, _ = get("/metrics?json=true")
    snap = json.loads(body)["sensors"]
    if snap.get("LoadMonitor.valid-windows", {}).get("value", 0) > 0:
        break
    time.sleep(0.5)
else:
    raise SystemExit("monitor never produced a valid window")

status, _, headers = get(
    "/rebalance?dryrun=true&goals=ReplicaDistributionGoal", method="POST")
assert status == 202, f"expected 202, got {status}"
print("rebalance submitted, task", headers.get("User-Task-ID"), flush=True)

# The budget registers when the worker thread enters the facade; wait for
# the analyzer to report the solve in flight before pulling the trigger.
while time.monotonic() < deadline:
    _, body, _ = get("/state?substates=analyzer")
    if '"activeSolves": 0' not in body:
        break
    time.sleep(0.05)
else:
    raise SystemExit("solve never became active")
print("solve in flight -- ready for SIGTERM", flush=True)
EOF

# --- SIGTERM mid-solve; the exit must beat grace + teardown slack ---------
T0="$(date +%s)"
kill -TERM "$SVC_PID"
set +e
wait "$SVC_PID"
RC=$?
set -e
ELAPSED=$(( $(date +%s) - T0 ))
SVC_PID=""
BOUND=$(( GRACE_MS / 1000 + SLACK_S ))
echo "exit rc=$RC after ${ELAPSED}s (grace $((GRACE_MS / 1000))s + ${SLACK_S}s slack)"
if [[ "$RC" -ne 0 ]]; then
  echo "FAIL: service exited rc=$RC, expected clean 0" >&2
  tail -40 "$SVC_OUT" >&2
  exit 1
fi
if (( ELAPSED > BOUND )); then
  echo "FAIL: shutdown took ${ELAPSED}s > ${BOUND}s bound" >&2
  tail -40 "$SVC_OUT" >&2
  exit 1
fi
if ! grep -q "in-flight solve" "$SVC_OUT"; then
  echo "FAIL: drain never cancelled the in-flight solve" >&2
  tail -40 "$SVC_OUT" >&2
  exit 1
fi

# --- clean journal: a dryrun solve must leave no execution state ----------
JOURNAL="$JOURNAL" python - <<'EOF'
import os

from cruise_control_tpu.executor.journal import ExecutionJournal

path = os.environ["JOURNAL"]
lag = ExecutionJournal(path).lag()
assert lag == 0, f"journal lag {lag} after drain -- execution state leaked"
print("journal clean (lag 0)")
EOF

echo PASS
