#!/usr/bin/env bash
# Time the reference Java GoalOptimizer on the same configs bench.py times,
# so the vs_java ratio can finally be computed — IN AN ENVIRONMENT WITH A
# JDK.  This box has none (no `java`, no /usr/lib/jvm, zero egress; see
# BASELINE.md "Java baseline status"), so this script is the ready-to-run
# kit, not something that has ever produced a number here.
#
# Usage:   ./scripts/bench_java.sh [/path/to/reference-checkout]
# Output:  one JSON line per config on stdout, same metric names as bench.py
#          (configs #1 and #2/#3 — the rows directly comparable to the
#          Python/TPU implementation's numbers).
#
# What it does:
#   1. Drops a JUnit driver (original code, written below) into the
#      reference's test tree.  The driver builds the SAME fixtures the
#      reference's own tests use (DeterministicCluster.unbalanced / 200
#      replicas harness, RandomCluster.generate at 200 brokers / 50K
#      replicas) and times GoalOptimizer.optimizations — the exact call the
#      proposal path drives (GoalOptimizer.java:123,168).
#   2. Runs it via the gradle wrapper with the test JVM pinned to one
#      warmup + five timed iterations, and prints min/median wall-clock.
#
# Compare the resulting numbers to the matching rows of BENCH_r*.json and
# verify quality with the reference's own OptimizationVerifier if desired.
set -euo pipefail

REF="${1:-/root/reference}"
command -v java >/dev/null || {
    echo "no java binary on PATH — this script needs a JDK environment" >&2
    exit 2
}
[ -x "$REF/gradlew" ] || {
    echo "no gradle wrapper at $REF/gradlew" >&2
    exit 2
}

DRIVER_DIR="$REF/cruise-control/src/test/java/com/linkedin/kafka/cruisecontrol/analyzer"
DRIVER="$DRIVER_DIR/TpuBaselineBenchTest.java"

cat > "$DRIVER" <<'JAVA'
// Baseline timing driver for the cruise-control-tpu comparison.  Original
// code: builds the reference's own test fixtures and times the production
// GoalOptimizer.optimizations call.  Written by scripts/bench_java.sh;
// delete after the run.
package com.linkedin.kafka.cruisecontrol.analyzer;

import com.codahale.metrics.MetricRegistry;
import com.linkedin.kafka.cruisecontrol.common.ClusterProperty;
import com.linkedin.kafka.cruisecontrol.common.DeterministicCluster;
import com.linkedin.kafka.cruisecontrol.common.TestConstants;
import com.linkedin.kafka.cruisecontrol.config.KafkaCruiseControlConfig;
import com.linkedin.kafka.cruisecontrol.config.constants.AnalyzerConfig;
import com.linkedin.kafka.cruisecontrol.config.constants.ExecutorConfig;
import com.linkedin.kafka.cruisecontrol.config.constants.MonitorConfig;
import com.linkedin.kafka.cruisecontrol.executor.Executor;
import com.linkedin.kafka.cruisecontrol.model.ClusterModel;
import com.linkedin.kafka.cruisecontrol.model.RandomCluster;
import com.linkedin.kafka.cruisecontrol.monitor.LoadMonitor;
import com.linkedin.kafka.cruisecontrol.async.progress.OperationProgress;
import java.util.HashMap;
import java.util.Map;
import java.util.Properties;
import org.apache.kafka.clients.admin.AdminClient;
import org.apache.kafka.common.utils.SystemTime;
import org.easymock.EasyMock;
import org.junit.Test;

public class TpuBaselineBenchTest {

  private GoalOptimizer optimizer() {
    Properties props = new Properties();
    props.setProperty(MonitorConfig.BOOTSTRAP_SERVERS_CONFIG, "bootstrap.servers");
    props.setProperty(ExecutorConfig.ZOOKEEPER_CONNECT_CONFIG, "connect:1234");
    props.setProperty(AnalyzerConfig.NUM_PROPOSAL_PRECOMPUTE_THREADS_CONFIG, "0");
    props.setProperty(AnalyzerConfig.DEFAULT_GOALS_CONFIG, TestConstants.DEFAULT_GOALS_VALUES);
    KafkaCruiseControlConfig config = new KafkaCruiseControlConfig(props);
    return new GoalOptimizer(config, EasyMock.mock(LoadMonitor.class), new SystemTime(),
                             new MetricRegistry(), EasyMock.mock(Executor.class),
                             EasyMock.mock(AdminClient.class));
  }

  private void time(String metric, ClusterModelSupplier supplier) throws Exception {
    GoalOptimizer opt = optimizer();
    // Warmup (JIT) + 5 timed runs on FRESH models (optimizations mutates).
    opt.optimizations(supplier.get(), new OperationProgress());
    long best = Long.MAX_VALUE;
    for (int i = 0; i < 5; i++) {
      ClusterModel model = supplier.get();
      long t0 = System.nanoTime();
      opt.optimizations(model, new OperationProgress());
      best = Math.min(best, System.nanoTime() - t0);
    }
    System.out.printf("{\"metric\": \"%s\", \"value\": %.4f, \"unit\": \"seconds\", \"impl\": \"java\"}%n",
                      metric, best / 1e9);
  }

  interface ClusterModelSupplier { ClusterModel get() throws Exception; }

  @Test
  public void benchConfigs() throws Exception {
    // Config #1: the DeterministicCluster harness (6 brokers / 3 racks).
    time("proposal_generation_wall_clock_deterministic_6brokers_200replicas",
         DeterministicCluster::unbalanced);

    // Config #2/#3 shape: RandomCluster 200 brokers / 50K replicas.
    Map<ClusterProperty, Number> properties = new HashMap<>(TestConstants.BASE_PROPERTIES);
    properties.put(ClusterProperty.NUM_BROKERS, 200);
    properties.put(ClusterProperty.NUM_RACKS, 10);
    properties.put(ClusterProperty.NUM_REPLICAS, 50000);
    properties.put(ClusterProperty.NUM_TOPICS, 1000);
    time("proposal_generation_wall_clock_200brokers_50k_replicas_full_goals",
         () -> {
           ClusterModel model = RandomCluster.generate(properties);
           RandomCluster.populate(model, properties, TestConstants.Distribution.UNIFORM);
           return model;
         });
  }
}
JAVA

cleanup() { rm -f "$DRIVER"; }
trap cleanup EXIT

cd "$REF"
./gradlew :cruise-control:test --tests '*TpuBaselineBenchTest*' -i 2>&1 \
  | grep -E '^\{"metric"' || {
    echo "driver ran but emitted no metric lines — check gradle test output" >&2
    exit 1
}
