"""Tier-1 smoke for fuzzsvc: fixed-seed scenario corpus, invariants, one
storm cycle, shrinker + replay, and the random_cluster extensions it rides on.

Budget discipline: every smoke scenario shares one padded shape
(1024 replicas / 16 brokers) and one goal stack, so the 8-kind sweep pays
one solver compile and reuses it seven times.  The long chaos soak lives
behind ``@pytest.mark.slow`` (scripts/fuzz_nightly.sh).
"""

import dataclasses
import json

import numpy as np
import pytest

from cruise_control_tpu.common.metrics import registry
from cruise_control_tpu.fuzzsvc import invariants as fuzz_invariants
from cruise_control_tpu.fuzzsvc import runner as fuzz_runner
from cruise_control_tpu.fuzzsvc.runner import (
    FuzzConfig,
    fuzz_sensors,
    run_fuzz,
    run_one,
)
from cruise_control_tpu.fuzzsvc.scenario import (
    SCENARIO_KINDS,
    SMOKE_GOALS,
    Scenario,
    generate_scenario,
    shrink_steps,
)
from cruise_control_tpu.fuzzsvc.storm import audit_coherence, run_storm
from cruise_control_tpu.testing import random_cluster as rc

SMOKE_BASE_SEED = 100


# --------------------------------------------------------------- generator

class TestScenarioGenerator:
    def test_seed_determinism(self):
        a = generate_scenario(123)
        b = generate_scenario(123)
        assert a.to_json() == b.to_json()
        assert generate_scenario(124).to_json() != a.to_json()

    def test_forced_kind_keeps_stream(self):
        # The kind is drawn from the stream even when forced, so the rest of
        # the scenario (topic/replica counts) matches the bare-seed draw.
        free = generate_scenario(55)
        forced = generate_scenario(55, kind=free.kind)
        assert forced.to_json() == free.to_json()

    @pytest.mark.parametrize("kind", SCENARIO_KINDS)
    def test_every_kind_shapes_its_scenario(self, kind):
        s = generate_scenario(77, kind=kind)
        assert s.kind == kind and s.name == f"{kind}-s77"
        assert list(s.goal_names) == list(SMOKE_GOALS)
        if kind == "dead_brokers":
            assert len(s.props.dead_broker_ids) == 2
            assert "stranded_cleared" in s.invariants
        elif kind == "dead_disks":
            assert s.props.num_disks == 3
            assert len(s.props.dead_disk_ids) == 2
        elif kind == "maintenance_window":
            assert s.events and s.events[0].plan == "remove_broker"
        elif kind == "broker_add":
            assert s.whatif_add and "chunked_parity" in s.invariants
        elif kind == "broker_remove":
            assert s.whatif_remove and "chunked_parity" in s.invariants
        elif kind == "hetero_racks":
            assert s.props.rack_skew > 0 and s.props.capacity_tiers == 3
        elif kind == "exp_skew":
            assert s.props.distribution is rc.Distribution.EXPONENTIAL

    def test_unknown_kind_rejected(self):
        with pytest.raises(ValueError, match="unknown scenario kind"):
            generate_scenario(1, kind="nope")

    @pytest.mark.parametrize("kind",
                             ["dead_disks", "maintenance_window", "broker_add"])
    def test_json_roundtrip(self, kind):
        s = generate_scenario(31, kind=kind)
        back = Scenario.from_json(s.to_json())
        assert back.to_json() == s.to_json()
        assert back.props == s.props   # enums/tuples restored, not strings

    def test_replay_command_forms(self):
        s = generate_scenario(9, kind="exp_skew")
        assert s.replay_command().endswith("--seed 9 --kind exp_skew")
        assert "--replay /tmp/x.json" in s.replay_command("/tmp/x.json")

    def test_shrink_steps_strictly_simpler(self):
        s = generate_scenario(77, kind="dead_disks")
        labels = [label for label, _ in shrink_steps(s)]
        assert len(labels) == len(set(labels))
        assert "halve-topics" in labels and "halve-replicas" in labels
        assert any(label.startswith("drop-dead-disk-") for label in labels)
        for _, cand in shrink_steps(s):
            assert cand.to_json() != s.to_json()


# ------------------------------------------------- random_cluster extensions

class TestRandomClusterExtensions:
    SMALL = dict(num_brokers=8, num_racks=4, num_topics=6, num_replicas=60,
                 min_replication=3, max_replication=3, seed=5)

    def test_rack_skew_apportions_all_brokers(self):
        state, _, _ = rc.generate(
            rc.ClusterProperties(**self.SMALL, rack_skew=2.0),
            pad_replicas_to=64, pad_brokers_to=8)
        sizes = np.bincount(np.asarray(state.rack)[:8], minlength=4)
        assert sizes.sum() == 8 and (sizes >= 1).all()
        assert sizes.max() > sizes.min()   # skew produced unequal racks

    def test_capacity_tiers_differentiate_brokers(self):
        state, _, _ = rc.generate(
            rc.ClusterProperties(**self.SMALL, capacity_tiers=3),
            pad_replicas_to=64, pad_brokers_to=8)
        per_broker = np.asarray(state.disk_capacity)[:8].sum(axis=1)
        assert len(np.unique(np.round(per_broker, 3))) >= 2

    def test_explicit_dead_ids_take_precedence(self):
        state, _, _ = rc.generate(
            rc.ClusterProperties(**self.SMALL, num_disks=2,
                                 dead_broker_ids=(2,),
                                 dead_disk_ids=((4, 1),)),
            pad_replicas_to=64, pad_brokers_to=8)
        alive = np.asarray(state.alive)[:8]
        assert not alive[2] and alive[[0, 1, 3, 4, 5, 6, 7]].all()
        disk_alive = np.asarray(state.disk_alive)[:8]
        assert not disk_alive[4, 1] and disk_alive[4, 0]

    def test_defaults_leave_cluster_healthy(self):
        state, _, _ = rc.generate(rc.ClusterProperties(**self.SMALL),
                                  pad_replicas_to=64, pad_brokers_to=8)
        assert np.asarray(state.alive)[:8].all()
        assert np.asarray(state.disk_alive)[:8].all()


# -------------------------------------------------------------- smoke sweep

class TestFuzzSmoke:
    @pytest.mark.parametrize("i,kind", list(enumerate(SCENARIO_KINDS)))
    def test_fixed_seed_corpus_invariants(self, i, kind):
        """The acceptance smoke: 8 fixed-seed scenarios (one per kind), every
        scenario's full invariant set — mesh/chunked parity included."""
        out = run_one(generate_scenario(SMOKE_BASE_SEED + i, kind=kind),
                      storm_cycles=0)
        assert out.ok, f"{kind}: {out.failures}"

    def test_storm_cycle_converges_with_coherent_audit(self):
        rep = run_storm(generate_scenario(205, kind="maintenance_window"),
                        cycles=1)
        assert rep.ok, rep.problems
        assert rep.cycles_run == 1
        assert rep.anomalies_detected >= 1
        assert rep.audit, "storm must leave an audit trail"
        assert audit_coherence(rep.audit) == []

    def test_fuzz_counters_advance(self):
        sensors = fuzz_sensors()
        before = {k: c.count for k, c in sensors.items()}
        # Warm seed/kind from the parametrized sweep, one cheap invariant:
        # this test is about the counters, not the solve.
        out = run_one(generate_scenario(SMOKE_BASE_SEED,
                                        kind=SCENARIO_KINDS[0]),
                      storm_cycles=0, which=("load_conservation",))
        assert out.ok
        assert sensors["scenarios"].count == before["scenarios"] + 1
        assert sensors["failures"].count == before["failures"]
        assert registry().counter("Fuzz.scenarios-run") is sensors["scenarios"]


# ------------------------------------------------- shrinker + replay loop

class TestShrinkAndReplay:
    def test_injected_failure_shrinks_and_replays(self, tmp_path, monkeypatch):
        # Break every invariant lookup: run_invariants reports unknown names
        # as failures, so each scenario fails cheaply (no solver involved).
        monkeypatch.setattr(fuzz_invariants, "INVARIANTS", {})
        logs = []
        cfg = FuzzConfig(num_scenarios=1, base_seed=42, storm_cycles=0,
                         corpus_dir=str(tmp_path / "corpus"),
                         shrink_max_steps=3, kinds=("hetero_racks",))
        report = run_fuzz(cfg, log=logs.append)
        assert not report.ok
        assert report.replay_lines

        # The failing scenario and its shrunk form are both on disk.
        saved = sorted((tmp_path / "corpus" / "failing").glob("*.json"))
        assert any(p.name.endswith(".min.json") for p in saved)
        shrunk = next(p for p in saved if p.name.endswith(".min.json"))
        assert Scenario.from_json(shrunk.read_text()).kind == "hetero_racks"
        assert any("shrunk via" in line for line in logs)

        # The printed replay command reproduces the failure bit-for-bit.
        replay = next(line for line in report.replay_lines
                      if "--replay" in line)
        path = replay.split("--replay ", 1)[1].split()[0]
        rc_code = fuzz_runner.main(["--replay", path, "--storm-cycles", "0"])
        assert rc_code == 1

        # ... and so does the bare --seed/--kind form.
        bare = next(line for line in report.replay_lines
                    if "--seed" in line)
        args = bare.split("cruise_control_tpu.fuzzsvc ", 1)[1].split()
        assert fuzz_runner.main(args + ["--storm-cycles", "0"]) == 1

    def test_cli_list_kinds(self, capsys):
        assert fuzz_runner.main(["--list-kinds"]) == 0
        assert capsys.readouterr().out.split() == list(SCENARIO_KINDS)

    def test_fuzz_config_from_cc_config(self):
        from cruise_control_tpu.config.cruise_control_config import (
            CruiseControlConfig,
        )
        cfg = FuzzConfig.from_cc_config(CruiseControlConfig(
            {"fuzz.num.scenarios": 3, "fuzz.storm.cycles": 0,
             "fuzz.corpus.dir": "/tmp/fz"}))
        assert cfg.num_scenarios == 3
        assert cfg.storm_cycles == 0
        assert cfg.corpus_dir == "/tmp/fz"
        assert cfg.base_seed == 100   # defaulted from the config def


# ------------------------------------------------------------ nightly soak

@pytest.mark.slow
class TestStormSoak:
    def test_multi_cycle_storm_every_kind(self, tmp_path):
        cfg = FuzzConfig(num_scenarios=len(SCENARIO_KINDS), base_seed=300,
                         storm_cycles=2, corpus_dir=str(tmp_path / "corpus"))
        report = run_fuzz(cfg, log=lambda *_: None)
        assert report.ok, [f for o in report.outcomes for f in o.failures]
