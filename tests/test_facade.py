"""Façade integration tests — the full wiring: monitor → analyzer → executor
→ detectors, against the fake cluster backend (the reference's
CruiseControlIntegrationTestHarness role, minus HTTP)."""

import time

import numpy as np
import pytest

from cruise_control_tpu.common.exceptions import OngoingExecutionError
from cruise_control_tpu.detector.anomalies import AnomalyType, MaintenanceEvent
from cruise_control_tpu.detector.notifier import SelfHealingNotifier
from cruise_control_tpu.executor.backend import FakeClusterBackend
from cruise_control_tpu.executor.executor import Executor, ExecutorConfig
from cruise_control_tpu.facade import CruiseControl
from cruise_control_tpu.monitor.load_monitor import LoadMonitor
from cruise_control_tpu.monitor.metadata import (
    BrokerInfo,
    FakeMetadataBackend,
    MetadataClient,
    PartitionInfo,
)
from cruise_control_tpu.monitor.sampler import SyntheticWorkloadSampler
from cruise_control_tpu.monitor.task_runner import LoadMonitorTaskRunner

W = 1000


def build_stack(num_brokers=4, partitions=8, rf=2, self_healing=False):
    brokers = [BrokerInfo(i, rack=str(i % 2), host=f"h{i}")
               for i in range(num_brokers)]
    parts = [PartitionInfo("T", p, leader=p % num_brokers,
                           replicas=tuple((p + i) % num_brokers for i in range(rf)),
                           in_sync=(p % num_brokers,))
             for p in range(partitions)]
    backend = FakeMetadataBackend(brokers, parts)
    client = MetadataClient(backend, ttl_ms=0)
    lm = LoadMonitor(client, num_windows=5, window_ms=W, min_samples_per_window=1)
    runner = LoadMonitorTaskRunner(lm, SyntheticWorkloadSampler(),
                                   sampling_interval_ms=W)
    runner.bootstrap(0, 6 * W)
    cluster = FakeClusterBackend(backend, polls_to_finish=1)
    ex = Executor(cluster, ExecutorConfig(progress_check_interval_s=0.001))
    notifier = SelfHealingNotifier(
        self_healing_enabled=self_healing, clock=lambda: time.time() * 1000,
        broker_failure_alert_threshold_ms=0,
        broker_failure_self_healing_threshold_ms=0)
    cc = CruiseControl(lm, ex, task_runner=runner, notifier=notifier)
    return cc, backend, cluster


def _wait_executor_idle(cc, timeout=10.0):
    deadline = time.time() + timeout
    while cc.executor.has_ongoing_execution and time.time() < deadline:
        time.sleep(0.01)
    assert not cc.executor.has_ongoing_execution


def test_rebalance_dryrun_and_state():
    cc, backend, cluster = build_stack()
    r = cc.rebalance(goals=["ReplicaDistributionGoal"], dryrun=True)
    assert r.dryrun and not r.executed
    s = cc.state()
    assert s["MonitorState"]["numValidWindows"] == 5
    assert s["ExecutorState"]["state"] == "NO_TASK_IN_PROGRESS"
    assert cc.broker_stats()["numBrokers"] == 4
    assert len(cc.partition_load(max_entries=5)) == 5


def test_remove_broker_executes_against_cluster():
    cc, backend, cluster = build_stack()
    r = cc.remove_brokers([3], goals=["RackAwareGoal", "ReplicaCapacityGoal"],
                          dryrun=False)
    assert r.executed
    _wait_executor_idle(cc)
    md = backend.fetch()
    for p in md.partitions:
        assert 3 not in p.replicas
    # Executor went back to idle and sampled reassignments happened.
    assert len(cluster.reassignment_log) == len(r.optimizer_result.proposals)


def test_demote_broker_moves_leadership():
    cc, backend, cluster = build_stack()
    r = cc.demote_brokers([0], dryrun=False)
    if r.executed:
        _wait_executor_idle(cc)
        md = backend.fetch()
        assert all(p.leader != 0 for p in md.partitions)


def test_topic_rf_change():
    cc, backend, cluster = build_stack()
    r = cc.change_topic_replication_factor(
        "T", 3, goals=["RackAwareDistributionGoal", "ReplicaCapacityGoal"],
        dryrun=False)
    assert r.optimizer_result is not None
    if r.executed:
        _wait_executor_idle(cc)
        md = backend.fetch()
        for p in md.partitions:
            assert len(p.replicas) == 3


def test_concurrent_operation_guard():
    cc, backend, cluster = build_stack()
    cluster.polls_to_finish = 500
    r = cc.remove_brokers([3], goals=["ReplicaCapacityGoal"], dryrun=False)
    assert r.executed
    with pytest.raises(OngoingExecutionError):
        cc.rebalance(dryrun=False)
    cc.stop_execution()
    _wait_executor_idle(cc)


def test_self_healing_broker_failure_end_to_end():
    cc, backend, cluster = build_stack(self_healing=True)
    backend.kill_broker(2)
    n = cc.anomaly_detector.run_detection_once()
    assert n >= 1
    _wait_executor_idle(cc)
    md = backend.fetch()
    for p in md.partitions:
        assert 2 not in p.replicas, f"{p} still references dead broker"
    summary = cc.anomaly_detector.state_summary()
    assert summary["metrics"].get("FIX_STARTED", 0) >= 1


def test_maintenance_event_routes_through_fixer():
    cc, backend, cluster = build_stack(self_healing=True)
    det = cc.anomaly_detector.detectors[AnomalyType.MAINTENANCE_EVENT]
    det.submit(MaintenanceEvent(plan="remove_broker", broker_ids=(1,)))
    cc.anomaly_detector.run_detection_once()
    _wait_executor_idle(cc)
    md = backend.fetch()
    for p in md.partitions:
        assert 1 not in p.replicas


def test_self_healing_toggle():
    cc, *_ = build_stack(self_healing=False)
    assert cc.set_self_healing(AnomalyType.BROKER_FAILURE, True) is False
    assert cc.notifier.self_healing_enabled()[AnomalyType.BROKER_FAILURE] is True


def test_background_proposal_precompute_warms_cache():
    """The precompute daemon (GoalOptimizer.java:137-188 analog) refreshes the
    generation-keyed proposal cache so a later /proposals read is a hit."""
    cc, backend, cluster = build_stack()
    cc._precompute_interval_s = 0.05
    cc.start_up()
    try:
        # Generous deadline: when this test runs first in a fresh process the
        # precompute's solve pays the cold JIT compile (can exceed a minute).
        deadline = time.time() + 300.0
        while cc._precomputed_generation is None and time.time() < deadline:
            time.sleep(0.02)
        assert cc._precomputed_generation is not None
        assert cc.optimizer._cached, "precompute left no cached result"
        # With the generation frozen, /proposals reads are cache hits (the
        # generation may have advanced DURING the precompute solve, so only
        # same-generation identity is asserted, not daemon-vs-now equality).
        cc.task_runner.pause_sampling("test")
        # Pause stops NEW sampling ticks but not one already in flight; wait
        # for the model generation to settle or the two reads below can
        # straddle a generation bump and legitimately miss the cache (seen
        # once on the 1-core box where recompiles stretch the window).
        settle_deadline = time.time() + 30.0
        g = cc.load_monitor.model_generation
        while time.time() < settle_deadline:
            time.sleep(0.1)
            g2 = cc.load_monitor.model_generation
            if g2 == g:
                break
            g = g2
        r1 = cc.proposals()
        r2 = cc.proposals()
        assert r2.optimizer_result is r1.optimizer_result
    finally:
        cc.shutdown()
