"""obsvc tests: span tracer, self-healing audit log, /trace + /profile
end-to-end (tentpole of the observability PR — the reference has only flat
Dropwizard sensors; the span tree is this port's addition)."""

import json
import os
import time
import urllib.error
import urllib.request

import pytest

from cruise_control_tpu import resilience
from cruise_control_tpu.obsvc.audit import AuditLog
from cruise_control_tpu.obsvc.tracer import Tracer, tracer

USER_TASK_HEADER = "User-Task-ID"


# ------------------------------------------------------------------ tracer


def test_tracer_disabled_is_noop():
    tr = Tracer(enabled=False)
    ctx = tr.span("anything", x=1)
    ctx2 = tr.span("other")
    assert ctx is ctx2                      # shared no-op context manager
    with ctx as span:
        span.set("k", "v")                  # swallowed
        span.add_ms("ms", 5.0)
        assert tr.current() is None
    assert tr.traces() == []
    assert tr.rollup() == {}


def test_tracer_nesting_attrs_and_ring_bound():
    tr = Tracer(enabled=True, ring_size=2)
    for i in range(3):
        with tr.span(f"root{i}", idx=i) as root:
            assert tr.current() is root
            with tr.span("child") as child:
                child.set("moves", 7)
            assert tr.current() is root
    roots = tr.traces()
    assert [r["name"] for r in roots] == ["root1", "root2"]   # oldest evicted
    assert roots[-1]["attrs"]["idx"] == 2
    (child,) = roots[-1]["children"]
    assert child["name"] == "child"
    assert child["parent_id"] == roots[-1]["span_id"]
    assert child["attrs"]["moves"] == 7
    assert child["wall_ms"] is not None and roots[-1]["wall_ms"] is not None
    roll = tr.rollup()
    assert roll["child"]["count"] == 3
    assert tr.rollup(reset=True)["child"]["total_ms"] >= 0.0
    assert tr.rollup() == {}                # reset drained it


def test_tracer_late_child_renders_in_progress():
    """202 shape: the root (http request) closes while a child (user task)
    still runs — /trace must render the child with wall_ms null, then pick
    up the final number once it closes (tree mutates in place)."""
    import contextvars

    tr = Tracer(enabled=True)
    root_ctx = tr.span("http.rebalance")
    root_ctx.__enter__()
    # What servlet._async does at submit time: the worker runs in a COPY of
    # the request context, so its tokens never interleave with this one's.
    ctx = contextvars.copy_context()
    child_ctx = tr.span("operation")
    ctx.run(child_ctx.__enter__)
    root_ctx.__exit__(None, None, None)     # request returned 202
    (snap,) = tr.traces()
    assert snap["children"][0]["wall_ms"] is None
    ctx.run(child_ctx.__exit__, None, None, None)
    (snap,) = tr.traces()
    assert snap["children"][0]["wall_ms"] is not None


def test_span_error_attr_and_execute_split():
    tr = Tracer(enabled=True)
    with pytest.raises(RuntimeError):
        with tr.span("boom"):
            raise RuntimeError("x")
    assert tr.traces()[-1]["attrs"]["error"] == "RuntimeError"
    with tr.span("goal.X") as span:
        span.set("compile_ms", 1.0)
    d = tr.traces()[-1]
    assert d["attrs"]["execute_ms"] == round(max(d["wall_ms"] - 1.0, 0.0), 3)


def test_tracer_mirrors_rollup_into_registry_timer():
    from cruise_control_tpu.common.metrics import registry
    tr = Tracer(enabled=True)
    before = registry().timer("Trace.phase-mirror").stats()["count"]
    with tr.span("phase-mirror"):
        pass
    assert registry().timer("Trace.phase-mirror").stats()["count"] == before + 1


# ---------------------------------------------------------------- audit log


def test_audit_chain_and_bound():
    log = AuditLog(maxlen=4)
    eid = log.record("GOAL_VIOLATION", "3 goals violated", "FIX")
    log.set_action("GOAL_VIOLATION", "rebalance")
    log.set_outcome(eid, "FIX_STARTED")
    log.attach_execution_outcome(completed=5, dead=1, aborted=0, moved_mb=42.0)
    (entry,) = log.entries()
    assert entry["decision"] == "FIX" and entry["action"] == "rebalance"
    assert entry["outcome"] == "FIX_STARTED"
    assert entry["executionOutcome"]["completed"] == 5
    assert entry["executionOutcome"]["movedMB"] == 42.0
    # User-triggered executions (no FIX_STARTED entry pending) are dropped.
    log.attach_execution_outcome(completed=9, dead=0, aborted=0, moved_mb=1.0)
    assert log.entries()[0]["executionOutcome"]["completed"] == 5
    for _ in range(6):
        log.record("BROKER_FAILURE", "b", "IGNORED")
    assert len(log.entries()) == 4          # bounded


def test_audit_set_action_targets_newest_open_entry():
    log = AuditLog()
    log.record("BROKER_FAILURE", "old", "FIX")
    log.set_action("BROKER_FAILURE", "remove_broker")
    log.record("BROKER_FAILURE", "new", "FIX")
    log.set_action("BROKER_FAILURE", "fix_offline_replicas")
    first, second = log.entries()
    assert first["action"] == "remove_broker"
    assert second["action"] == "fix_offline_replicas"


# ------------------------------------------------- convergence recorder


def test_convergence_recorder_ring_bounds_drain_and_disable():
    import numpy as np
    from cruise_control_tpu.obsvc.convergence import ConvergenceRecorder

    rec = ConvergenceRecorder(enabled=True, ring_size=3)
    curve = np.array([[2, 1, 0, 0.5, 0, 0]], dtype=np.float32)
    for _ in range(5):
        assert rec.record_solve(
            [{"goal": "G", "curve": curve, "metric_before": 1.0,
              "rounds": 1, "moves": 2}]) is not None
    recs = rec.records()
    assert len(recs) == 3                       # oldest two evicted
    assert recs[0]["id"] < recs[-1]["id"]       # oldest first
    assert recs[-1]["goals"][0]["stats"]["moves_total"] == 2
    assert rec.state_summary()["recorded"] == 5
    assert len(rec.drain()) == 5                # pending survives eviction
    assert rec.drain() == []
    assert len(rec.records()) == 3              # drain leaves the ring alone
    rec.configure(enabled=True, ring_size=2)
    assert len(rec.records()) == 2              # resize keeps newest
    rec.configure(enabled=False, ring_size=2)
    assert rec.record_solve([{"goal": "G", "rounds": 1, "moves": 0}]) is None
    assert rec.state_summary()["recorded"] == 5
    rec.configure(enabled=True, ring_size=4)
    rec.record_batch(["G1", "G2"], [[3, 1], [2, 1]], warm_start=True)
    last = rec.records()[-1]
    assert last["kind"] == "what_if" and last["lanes"] == 2
    assert last["warmStart"] is True
    assert last["laneRounds"] == {"G1": [3, 2], "G2": [1, 1]}


def test_curve_stats_derivations():
    import numpy as np
    from cruise_control_tpu.obsvc.convergence import (
        ROUND_COL_APPLIED, ROUND_COL_METRIC, ROUND_COL_STALL, curve_stats)

    curve = np.zeros((4, 6), dtype=np.float32)
    curve[:, ROUND_COL_APPLIED] = [10, 5, 1, 0]
    curve[:, ROUND_COL_METRIC] = [0.5, 0.2, 0.12, 0.1]
    curve[3, ROUND_COL_STALL] = 1
    s = curve_stats(curve, metric_before=1.0)
    assert s["rounds_total"] == 4
    assert s["moves_total"] == 16
    assert s["acceptance_rate"] == 0.4          # 16 / (4 rounds * peak 10)
    # 90% of the 0.9 total gain is reached at metric 0.12 — round 3.
    assert s["rounds_to_90pct"] == 3
    assert s["stall_rounds"] == 1
    empty = curve_stats(np.zeros((0, 6), dtype=np.float32), 0.0)
    assert empty["rounds_total"] == 0 and empty["acceptance_rate"] == 0.0


def test_round_recording_off_path_cache_keys_unchanged():
    """Acceptance: with trace.solver.rounds=false (the default) the solver
    compiles exactly the executables it compiled before the recorder
    existed — no 'rounds' marker in any jit-cache key, no curve on the
    infos.  Flipping the flag adds SEPARATE keyed entries rather than
    perturbing the off-path ones, and the curves it returns are coherent."""
    import numpy as np
    from cruise_control_tpu.analyzer import GoalOptimizer
    from cruise_control_tpu.analyzer import solver as solver_mod
    from cruise_control_tpu.obsvc.convergence import ROUND_COL_APPLIED
    from cruise_control_tpu.testing import deterministic as det

    assert not solver_mod.round_recording_enabled()     # process default
    state, placement, meta = det.unbalanced2().freeze(pad_replicas_to=64,
                                                      pad_brokers_to=8)
    # A fresh solver so the shared default_solver() cache (warm from earlier
    # modules, possibly including recorded fuzz solves) can't mask the delta;
    # one goal keeps the four-executable compile bill at two.
    opt = GoalOptimizer(goal_names=["ReplicaDistributionGoal"],
                        solver=solver_mod.GoalSolver())
    res_off = opt.optimizations(state, placement, meta)
    solve_keys = lambda: {k for k in opt.solver._round_cache
                          if isinstance(k, tuple) and k and k[0] == "solve"}
    off_keys = solve_keys()
    assert off_keys and all("rounds" not in k for k in off_keys)
    assert all(i.round_curve is None for i in res_off.goal_infos)

    solver_mod.set_round_recording(True)
    try:
        res_on = opt.optimizations(state, placement, meta)
    finally:
        solver_mod.set_round_recording(False)
    on_keys = solve_keys() - off_keys
    assert on_keys and all(k[-1] == "rounds" for k in on_keys)
    assert off_keys <= solve_keys()             # off-path entries untouched
    checked = 0
    for info in res_on.goal_infos:
        curve = np.asarray(info.round_curve)
        assert len(curve) == info.rounds
        assert int(curve[:, ROUND_COL_APPLIED].sum()) == info.moves_applied
        checked += info.rounds
    assert checked > 0                          # at least one goal iterated


# ------------------------------------------------- history rings + SLO


def test_history_recorder_ring_bounds_and_filters(monkeypatch):
    import importlib

    from cruise_control_tpu.common.metrics import MetricRegistry
    from cruise_control_tpu.obsvc.history import SAMPLES_SENSOR, HistoryRecorder

    # The package attribute ``obsvc.history`` is the accessor function (the
    # eager from-import shadows the submodule); patch the module itself.
    history_mod = importlib.import_module("cruise_control_tpu.obsvc.history")

    # A private registry so the sensor-doc drift guard never sees HistTest.*.
    reg = MetricRegistry()
    monkeypatch.setattr(history_mod, "registry", lambda: reg)
    clock = {"now": 1000.0}
    rec = HistoryRecorder(interval_s=3600.0, ring_size=2,
                          clock=lambda: clock["now"])
    reg.settable_gauge("HistTest.value").set(1.0)
    before = reg.counter(SAMPLES_SENSOR).count
    for _ in range(3):
        clock["now"] += 1.0
        rec.sample_once()
    assert reg.counter(SAMPLES_SENSOR).count == before + 3
    series = rec.series("HistTest.value")
    assert len(series) == 2                     # ring bound: oldest evicted
    assert series[0][0] < series[1][0]          # [ts_ms, value] ascending
    assert series[-1][1] == 1.0
    hist = rec.history(pattern="HistTest.*")
    assert set(hist) == {"HistTest.value"}
    assert rec.history(pattern="HistTest.*",
                       since_ms=clock["now"] * 1000.0 + 1)["HistTest.value"] == []
    assert not rec.running                      # sample_once needs no thread


def _stub_history(series):
    class _Stub:
        def history(self, pattern=None, since_ms=None):
            import fnmatch
            return {k: v for k, v in series.items()
                    if pattern is None or fnmatch.fnmatch(k, pattern)}
    return _Stub()


def test_slo_empty_history_is_no_verdict():
    from cruise_control_tpu.obsvc.slo import SloEvaluator, SloObjective

    obj = SloObjective(name="o", pattern="X.*", threshold=10.0)
    ev = SloEvaluator([obj], recorder=_stub_history({}), clock=lambda: 1000.0)
    assert ev.evaluate() == []                  # no rings at all
    ev = SloEvaluator([obj], recorder=_stub_history({"X.a": []}),
                      clock=lambda: 1000.0)
    assert ev.evaluate() == []                  # an empty ring is skipped
    # Samples entirely outside both windows: burns are None, not violating.
    old = [[1.0, 99.0]]                         # ts 1 ms, far in the past
    ev = SloEvaluator([obj], short_window_s=60, long_window_s=600,
                      recorder=_stub_history({"X.a": old}),
                      clock=lambda: 1_000_000.0)
    (v,) = ev.evaluate()
    assert v["burnShort"] is None and v["burnLong"] is None
    assert v["violating"] is False


def test_slo_clock_skew_and_both_window_gate():
    from cruise_control_tpu.obsvc.slo import (
        SloEvaluator, SloObjective, SloViolationDetector)

    now_s = 10_000.0
    now_ms = now_s * 1000.0
    obj = SloObjective(name="o", pattern="X.*", threshold=10.0)

    # Future-stamped samples (sampler clock ahead of the evaluator) are
    # clamped to now and count in BOTH windows instead of being dropped.
    future = [[now_ms + 600_000.0, 99.0]]
    ev = SloEvaluator([obj], error_budget=0.5, short_window_s=60,
                      long_window_s=600, recorder=_stub_history({"X.a": future}),
                      clock=lambda: now_s)
    (v,) = ev.evaluate()
    assert v["violating"] is True and v["burnShort"] == 2.0

    # Short window burning but the long window under threshold: de-flapped.
    mixed = ([[now_ms - 500_000.0, 1.0]] * 8          # old, healthy
             + [[now_ms - 1_000.0, 99.0]] * 2)        # fresh spike
    ev = SloEvaluator([obj], error_budget=0.5, short_window_s=60,
                      long_window_s=600, recorder=_stub_history({"X.a": mixed}),
                      clock=lambda: now_s)
    (v,) = ev.evaluate()
    assert v["burnShort"] == 2.0                # 2/2 violating / 0.5 budget
    assert v["burnLong"] == 0.4                 # 2/10 violating / 0.5 budget
    assert v["violating"] is False
    assert SloViolationDetector(ev).detect() == []

    # Sustained burn in both windows: one anomaly, unfixable, typed.
    from cruise_control_tpu.detector.anomalies import AnomalyType
    bad = [[now_ms - 500_000.0, 99.0]] * 8 + [[now_ms - 1_000.0, 99.0]] * 2
    ev = SloEvaluator([obj], error_budget=0.5, short_window_s=60,
                      long_window_s=600, recorder=_stub_history({"X.a": bad}),
                      clock=lambda: now_s)
    (anomaly,) = SloViolationDetector(ev).detect()
    assert anomaly.anomaly_type is AnomalyType.SLO_VIOLATION
    assert anomaly.fixable is False
    assert anomaly.describe()["sensor"] == "X.a"


# ------------------------------------------------------------------- e2e


def _get(base, path, headers=None):
    req = urllib.request.Request(base + path, headers=headers or {})
    with urllib.request.urlopen(req) as r:
        return r.status, r.read().decode(), dict(r.headers)


def _post(base, path, headers=None):
    req = urllib.request.Request(base + path, headers=headers or {},
                                 method="POST")
    try:
        with urllib.request.urlopen(req) as r:
            return r.status, r.read().decode(), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode(), dict(e.headers)


def _get_tolerant(base, path, headers=None):
    """GET that returns (status, body, headers) instead of raising — for
    polling endpoints that 500 transiently while the model warms up."""
    req = urllib.request.Request(base + path, headers=headers or {})
    try:
        with urllib.request.urlopen(req) as r:
            return r.status, r.read().decode(), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode(), dict(e.headers)


def _find(span, prefix):
    """All descendant spans (incl. self) whose name starts with prefix."""
    hits = [span] if span["name"].startswith(prefix) else []
    for c in span.get("children", ()):
        hits.extend(_find(c, prefix))
    return hits


GOALS = ["ReplicaDistributionGoal", "LeaderReplicaDistributionGoal"]


def test_trace_and_profile_endpoints_end_to_end(tmp_path):
    """Acceptance: after one /rebalance?dryrun=true the /trace tree has a
    root covering the request with >= one goal span per configured goal,
    each with wall-ms and a compile/execute split; /profile writes a
    TensorBoard trace dir; X-Request-ID is echoed."""
    from cruise_control_tpu.config.cruise_control_config import (
        CruiseControlConfig)
    from cruise_control_tpu.main import build_app

    # A stale OPEN breaker published by an earlier test's app would make
    # this boot's /health shed the rebalance with a 503.
    resilience.set_backend_circuit(None)

    cfg = CruiseControlConfig({"metric.sampling.interval.ms": 300,
                               "partition.metrics.window.ms": 600,
                               "trace.enabled": True,
                               # Every poll closes an http.* root; the ring
                               # must outlive the polling loops below.
                               "trace.ring.size": 256,
                               "trace.profile.dir": str(tmp_path)})
    app = build_app(cfg, port=0)
    tracer().reset()
    app.cc.start_up()
    app.start()
    try:
        base = f"http://127.0.0.1:{app.port}/kafkacruisecontrol"
        deadline = time.time() + 60
        while time.time() < deadline:
            _, body, _ = _get(base, "/metrics?json=true")
            snap = json.loads(body)["sensors"]
            if snap.get("LoadMonitor.valid-windows", {}).get("value", 0) > 0:
                break
            time.sleep(0.5)

        # Request-id: echoed when supplied, minted when absent.
        _, _, headers = _get(base, "/state",
                             headers={"X-Request-ID": "req-abc"})
        assert headers.get("X-Request-ID") == "req-abc"
        _, _, headers = _get(base, "/state")
        assert headers.get("X-Request-ID")

        goals = ",".join(GOALS)
        status, body, headers = _post(
            base, f"/rebalance?dryrun=true&goals={goals}")
        task_id = headers.get(USER_TASK_HEADER)
        # 500 is retryable here: the model can be valid-windowed but not yet
        # proposal-ready (completeness gate), which surfaces as a transient
        # model-not-ready CruiseControlError.
        while status in (202, 500) and time.time() < deadline:
            time.sleep(0.5)
            hdrs = {USER_TASK_HEADER: task_id} if task_id else {}
            status, body, headers = _post(
                base, f"/rebalance?dryrun=true&goals={goals}", headers=hdrs)
            task_id = headers.get(USER_TASK_HEADER) or task_id
        assert status == 200, body

        _, body, _ = _get(base, "/trace")
        trace = json.loads(body)
        assert trace["enabled"] is True
        # The 202-async operation's spans land UNDER the ORIGINATING http
        # span (contextvars copied into the user-task thread); later polls
        # of the same task are thin http.rebalance roots with no children.
        roots = [t for t in trace["traces"]
                 if t["name"] == "http.rebalance" and _find(t, "operation")]
        assert roots, [t["name"] for t in trace["traces"]]
        root = roots[-1]
        for goal in GOALS:
            gspans = _find(root, f"goal.{goal}")
            assert gspans, f"no goal span for {goal}"
            for gspan in gspans:
                assert gspan["wall_ms"] is not None
                assert "compile_ms" in gspan["attrs"]
                assert "execute_ms" in gspan["attrs"]
                assert "fresh_compiles" in gspan["attrs"]
        assert _find(root, "optimize")
        assert trace["rollup"]["http.rebalance"]["count"] >= 1

        # Async capture: 202 immediately, GET /profile polls to done, the
        # trace dir materializes by the time done flips.
        status, body, _ = _post(base, "/profile?duration_s=0.4")
        assert status == 202, body
        out = json.loads(body)
        assert out["status"] == "started"
        assert out["trace_dir"].startswith(str(tmp_path))
        # 409 while the window is open (the second POST races the 0.4 s
        # window — tolerate it landing after close on a slow machine).
        status, body, _ = _post(base, "/profile?duration_s=0.1")
        assert status in (202, 409), body
        poll_deadline = time.time() + 30
        while time.time() < poll_deadline:
            _, sbody, _ = _get(base, "/profile")
            st = json.loads(sbody)
            if st["done"] and not st["busy"]:
                break
            time.sleep(0.1)
        assert st["done"] and not st["busy"], st
        assert st["error"] is None
        assert os.path.isdir(st["trace_dir"])

        status, body, _ = _post(base, "/profile?duration_s=nope")
        assert status == 400
        status, body, _ = _post(base, "/profile?duration_s=-1")
        assert status == 400
    finally:
        app.stop()
        app.cc.shutdown()
        tracer().configure(enabled=False, ring_size=32)
        tracer().reset()


def test_trace_disabled_path_adds_no_spans():
    """With trace.enabled=false (default) the proposal path must not
    produce spans — the acceptance bar for zero-overhead-when-off."""
    from cruise_control_tpu.analyzer import GoalOptimizer
    from cruise_control_tpu.testing import deterministic as det

    tr = tracer()
    tr.configure(enabled=False, ring_size=32)
    tr.reset()
    state, placement, meta = det.unbalanced().freeze(pad_replicas_to=64,
                                                     pad_brokers_to=8)
    GoalOptimizer(goal_names=GOALS).optimizations(state, placement, meta)
    assert tr.traces() == []
    assert tr.rollup() == {}


def test_solver_stats_and_history_endpoints_end_to_end():
    """Acceptance: with trace.solver.rounds=true a served /proposals leaves
    records on /solver_stats whose per-goal curve length equals the reported
    rounds; /metrics/history serves Solver.* rings; the convergence summary
    rides /state AnalyzerState."""
    from cruise_control_tpu.config.cruise_control_config import (
        CruiseControlConfig)
    from cruise_control_tpu.main import build_app

    # See test_trace_and_profile_endpoints_end_to_end: a stale published
    # breaker must not shed this test's proposal traffic.
    resilience.set_backend_circuit(None)

    cfg = CruiseControlConfig({"metric.sampling.interval.ms": 300,
                               "partition.metrics.window.ms": 600,
                               # One goal keeps the recording-variant compile
                               # bill small; curves don't need a second goal.
                               "default.goals": GOALS[:1],
                               "trace.solver.rounds": True,
                               "obs.history.interval.ms": 200,
                               # Keep the detector tick out of the way — a
                               # mid-test detection run races the /proposals
                               # task for the optimizer.
                               "anomaly.detection.interval.ms": 10 ** 9,
                               "proposal.expiration.ms": 0})
    app = build_app(cfg, port=0)
    app.cc.start_up()
    app.start()
    try:
        base = f"http://127.0.0.1:{app.port}/kafkacruisecontrol"
        deadline = time.time() + 120
        while time.time() < deadline:
            _, body, _ = _get(base, "/metrics?json=true")
            snap = json.loads(body)["sensors"]
            if snap.get("LoadMonitor.valid-windows", {}).get("value", 0) > 0:
                break
            time.sleep(0.5)

        _, body, _ = _get(base, "/solver_stats")
        pre = json.loads(body)
        assert pre["enabled"] is True

        # A valid window does not yet mean the model is proposal-ready —
        # /proposals 500s (model-not-ready CruiseControlError) until the
        # monitor's completeness gate opens, so retry those like a poll.
        status, body, headers = _get_tolerant(base, "/proposals")
        task_id = headers.get(USER_TASK_HEADER)
        while status in (202, 500) and time.time() < deadline:
            time.sleep(0.5)
            hdrs = {USER_TASK_HEADER: task_id} if task_id else {}
            status, body, headers = _get_tolerant(
                base, "/proposals", headers=hdrs)
            task_id = headers.get(USER_TASK_HEADER) or task_id
        assert status == 200, body

        _, body, _ = _get(base, "/solver_stats?limit=5")
        stats = json.loads(body)
        recs = [r for r in stats["records"] if r.get("goals")]
        assert recs, stats
        for g in recs[-1]["goals"]:
            assert len(g["curve"]) == g["rounds"], g["goal"]
            assert g["stats"]["moves_total"] == sum(
                r["applied"] for r in g["curve"])

        # History rings: the 200 ms sampler has run by now; Solver.* gauges
        # were registered by the solve above.
        hist_deadline = time.time() + 10
        while time.time() < hist_deadline:
            _, body, _ = _get(base, "/metrics/history?sensor=Solver.*")
            hist = json.loads(body)
            if hist["samples"] > 0 and hist["series"]:
                break
            time.sleep(0.3)
        assert hist["enabled"] is True
        assert any(k.startswith("Solver.") for k in hist["series"]), hist
        _, body, _ = _get(base, "/metrics/history?since_ms=99999999999999")
        future = json.loads(body)
        assert all(len(v) == 0 for v in future["series"].values())

        _, body, _ = _get(base, "/state")
        conv = json.loads(body)["AnalyzerState"]["convergence"]
        assert conv["enabled"] and conv["recorded"] >= 1
        assert conv["lastSolve"] and conv["lastSolve"]["goals"]

        _, body, _ = _get(base, "/metrics?json=true")
        snap = json.loads(body)["sensors"]
        assert "p99_ms" in snap["GoalOptimizer.proposal-computation-timer"]
        assert snap["Obs.history-samples"]["count"] > 0
        assert any(k.startswith("Solver.") and k.endswith(".rounds")
                   for k in snap)
    finally:
        app.stop()
        app.cc.shutdown()
        # Hermeticity: these singletons are process-wide.
        from cruise_control_tpu.analyzer import solver as solver_mod
        from cruise_control_tpu.obsvc.convergence import convergence
        from cruise_control_tpu.obsvc.history import history
        solver_mod.set_round_recording(False)
        convergence().configure(enabled=False, ring_size=64)
        convergence().reset()
        history().stop()
        history().configure(interval_s=10.0, ring_size=360)
        history().reset()


def test_state_exposes_self_healing_audit():
    from cruise_control_tpu.obsvc.audit import audit_log
    from tests.test_facade import build_stack

    cc, _backend, _cluster = build_stack(num_brokers=4, partitions=8)
    audit_log().clear()
    audit_log().record("GOAL_VIOLATION", "test entry", "FIX")
    try:
        detector_state = cc.state()["AnomalyDetectorState"]
        audit = detector_state["selfHealingAudit"]
        assert any(e["anomalyType"] == "GOAL_VIOLATION" for e in audit)
    finally:
        audit_log().clear()
        cc.shutdown()
