"""obsvc tests: span tracer, self-healing audit log, /trace + /profile
end-to-end (tentpole of the observability PR — the reference has only flat
Dropwizard sensors; the span tree is this port's addition)."""

import json
import os
import time
import urllib.error
import urllib.request

import pytest

from cruise_control_tpu.obsvc.audit import AuditLog
from cruise_control_tpu.obsvc.tracer import Tracer, tracer

USER_TASK_HEADER = "User-Task-ID"


# ------------------------------------------------------------------ tracer


def test_tracer_disabled_is_noop():
    tr = Tracer(enabled=False)
    ctx = tr.span("anything", x=1)
    ctx2 = tr.span("other")
    assert ctx is ctx2                      # shared no-op context manager
    with ctx as span:
        span.set("k", "v")                  # swallowed
        span.add_ms("ms", 5.0)
        assert tr.current() is None
    assert tr.traces() == []
    assert tr.rollup() == {}


def test_tracer_nesting_attrs_and_ring_bound():
    tr = Tracer(enabled=True, ring_size=2)
    for i in range(3):
        with tr.span(f"root{i}", idx=i) as root:
            assert tr.current() is root
            with tr.span("child") as child:
                child.set("moves", 7)
            assert tr.current() is root
    roots = tr.traces()
    assert [r["name"] for r in roots] == ["root1", "root2"]   # oldest evicted
    assert roots[-1]["attrs"]["idx"] == 2
    (child,) = roots[-1]["children"]
    assert child["name"] == "child"
    assert child["parent_id"] == roots[-1]["span_id"]
    assert child["attrs"]["moves"] == 7
    assert child["wall_ms"] is not None and roots[-1]["wall_ms"] is not None
    roll = tr.rollup()
    assert roll["child"]["count"] == 3
    assert tr.rollup(reset=True)["child"]["total_ms"] >= 0.0
    assert tr.rollup() == {}                # reset drained it


def test_tracer_late_child_renders_in_progress():
    """202 shape: the root (http request) closes while a child (user task)
    still runs — /trace must render the child with wall_ms null, then pick
    up the final number once it closes (tree mutates in place)."""
    import contextvars

    tr = Tracer(enabled=True)
    root_ctx = tr.span("http.rebalance")
    root_ctx.__enter__()
    # What servlet._async does at submit time: the worker runs in a COPY of
    # the request context, so its tokens never interleave with this one's.
    ctx = contextvars.copy_context()
    child_ctx = tr.span("operation")
    ctx.run(child_ctx.__enter__)
    root_ctx.__exit__(None, None, None)     # request returned 202
    (snap,) = tr.traces()
    assert snap["children"][0]["wall_ms"] is None
    ctx.run(child_ctx.__exit__, None, None, None)
    (snap,) = tr.traces()
    assert snap["children"][0]["wall_ms"] is not None


def test_span_error_attr_and_execute_split():
    tr = Tracer(enabled=True)
    with pytest.raises(RuntimeError):
        with tr.span("boom"):
            raise RuntimeError("x")
    assert tr.traces()[-1]["attrs"]["error"] == "RuntimeError"
    with tr.span("goal.X") as span:
        span.set("compile_ms", 1.0)
    d = tr.traces()[-1]
    assert d["attrs"]["execute_ms"] == round(max(d["wall_ms"] - 1.0, 0.0), 3)


def test_tracer_mirrors_rollup_into_registry_timer():
    from cruise_control_tpu.common.metrics import registry
    tr = Tracer(enabled=True)
    before = registry().timer("Trace.phase-mirror").stats()["count"]
    with tr.span("phase-mirror"):
        pass
    assert registry().timer("Trace.phase-mirror").stats()["count"] == before + 1


# ---------------------------------------------------------------- audit log


def test_audit_chain_and_bound():
    log = AuditLog(maxlen=4)
    eid = log.record("GOAL_VIOLATION", "3 goals violated", "FIX")
    log.set_action("GOAL_VIOLATION", "rebalance")
    log.set_outcome(eid, "FIX_STARTED")
    log.attach_execution_outcome(completed=5, dead=1, aborted=0, moved_mb=42.0)
    (entry,) = log.entries()
    assert entry["decision"] == "FIX" and entry["action"] == "rebalance"
    assert entry["outcome"] == "FIX_STARTED"
    assert entry["executionOutcome"]["completed"] == 5
    assert entry["executionOutcome"]["movedMB"] == 42.0
    # User-triggered executions (no FIX_STARTED entry pending) are dropped.
    log.attach_execution_outcome(completed=9, dead=0, aborted=0, moved_mb=1.0)
    assert log.entries()[0]["executionOutcome"]["completed"] == 5
    for _ in range(6):
        log.record("BROKER_FAILURE", "b", "IGNORED")
    assert len(log.entries()) == 4          # bounded


def test_audit_set_action_targets_newest_open_entry():
    log = AuditLog()
    log.record("BROKER_FAILURE", "old", "FIX")
    log.set_action("BROKER_FAILURE", "remove_broker")
    log.record("BROKER_FAILURE", "new", "FIX")
    log.set_action("BROKER_FAILURE", "fix_offline_replicas")
    first, second = log.entries()
    assert first["action"] == "remove_broker"
    assert second["action"] == "fix_offline_replicas"


# ------------------------------------------------------------------- e2e


def _get(base, path, headers=None):
    req = urllib.request.Request(base + path, headers=headers or {})
    with urllib.request.urlopen(req) as r:
        return r.status, r.read().decode(), dict(r.headers)


def _post(base, path, headers=None):
    req = urllib.request.Request(base + path, headers=headers or {},
                                 method="POST")
    try:
        with urllib.request.urlopen(req) as r:
            return r.status, r.read().decode(), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode(), dict(e.headers)


def _find(span, prefix):
    """All descendant spans (incl. self) whose name starts with prefix."""
    hits = [span] if span["name"].startswith(prefix) else []
    for c in span.get("children", ()):
        hits.extend(_find(c, prefix))
    return hits


GOALS = ["ReplicaDistributionGoal", "LeaderReplicaDistributionGoal"]


def test_trace_and_profile_endpoints_end_to_end(tmp_path):
    """Acceptance: after one /rebalance?dryrun=true the /trace tree has a
    root covering the request with >= one goal span per configured goal,
    each with wall-ms and a compile/execute split; /profile writes a
    TensorBoard trace dir; X-Request-ID is echoed."""
    from cruise_control_tpu.config.cruise_control_config import (
        CruiseControlConfig)
    from cruise_control_tpu.main import build_app

    cfg = CruiseControlConfig({"metric.sampling.interval.ms": 300,
                               "partition.metrics.window.ms": 600,
                               "trace.enabled": True,
                               # Every poll closes an http.* root; the ring
                               # must outlive the polling loops below.
                               "trace.ring.size": 256,
                               "trace.profile.dir": str(tmp_path)})
    app = build_app(cfg, port=0)
    tracer().reset()
    app.cc.start_up()
    app.start()
    try:
        base = f"http://127.0.0.1:{app.port}/kafkacruisecontrol"
        deadline = time.time() + 60
        while time.time() < deadline:
            _, body, _ = _get(base, "/metrics?json=true")
            snap = json.loads(body)["sensors"]
            if snap.get("LoadMonitor.valid-windows", {}).get("value", 0) > 0:
                break
            time.sleep(0.5)

        # Request-id: echoed when supplied, minted when absent.
        _, _, headers = _get(base, "/state",
                             headers={"X-Request-ID": "req-abc"})
        assert headers.get("X-Request-ID") == "req-abc"
        _, _, headers = _get(base, "/state")
        assert headers.get("X-Request-ID")

        goals = ",".join(GOALS)
        status, body, headers = _post(
            base, f"/rebalance?dryrun=true&goals={goals}")
        task_id = headers.get(USER_TASK_HEADER)
        while status == 202 and time.time() < deadline:
            time.sleep(0.5)
            status, body, headers = _post(
                base, f"/rebalance?dryrun=true&goals={goals}",
                headers={USER_TASK_HEADER: task_id})
        assert status == 200, body

        _, body, _ = _get(base, "/trace")
        trace = json.loads(body)
        assert trace["enabled"] is True
        # The 202-async operation's spans land UNDER the ORIGINATING http
        # span (contextvars copied into the user-task thread); later polls
        # of the same task are thin http.rebalance roots with no children.
        roots = [t for t in trace["traces"]
                 if t["name"] == "http.rebalance" and _find(t, "operation")]
        assert roots, [t["name"] for t in trace["traces"]]
        root = roots[-1]
        for goal in GOALS:
            gspans = _find(root, f"goal.{goal}")
            assert gspans, f"no goal span for {goal}"
            for gspan in gspans:
                assert gspan["wall_ms"] is not None
                assert "compile_ms" in gspan["attrs"]
                assert "execute_ms" in gspan["attrs"]
                assert "fresh_compiles" in gspan["attrs"]
        assert _find(root, "optimize")
        assert trace["rollup"]["http.rebalance"]["count"] >= 1

        status, body, _ = _post(base, "/profile?duration_s=0.2")
        assert status == 200, body
        out = json.loads(body)
        assert os.path.isdir(out["trace_dir"])
        assert out["trace_dir"].startswith(str(tmp_path))

        status, body, _ = _post(base, "/profile?duration_s=nope")
        assert status == 400
        status, body, _ = _post(base, "/profile?duration_s=-1")
        assert status == 400
    finally:
        app.stop()
        app.cc.shutdown()
        tracer().configure(enabled=False, ring_size=32)
        tracer().reset()


def test_trace_disabled_path_adds_no_spans():
    """With trace.enabled=false (default) the proposal path must not
    produce spans — the acceptance bar for zero-overhead-when-off."""
    from cruise_control_tpu.analyzer import GoalOptimizer
    from cruise_control_tpu.testing import deterministic as det

    tr = tracer()
    tr.configure(enabled=False, ring_size=32)
    tr.reset()
    state, placement, meta = det.unbalanced().freeze(pad_replicas_to=64,
                                                     pad_brokers_to=8)
    GoalOptimizer(goal_names=GOALS).optimizations(state, placement, meta)
    assert tr.traces() == []
    assert tr.rollup() == {}


def test_state_exposes_self_healing_audit():
    from cruise_control_tpu.obsvc.audit import audit_log
    from tests.test_facade import build_stack

    cc, _backend, _cluster = build_stack(num_brokers=4, partitions=8)
    audit_log().clear()
    audit_log().record("GOAL_VIOLATION", "test entry", "FIX")
    try:
        detector_state = cc.state()["AnomalyDetectorState"]
        audit = detector_state["selfHealingAudit"]
        assert any(e["anomalyType"] == "GOAL_VIOLATION" for e in audit)
    finally:
        audit_log().clear()
        cc.shutdown()
