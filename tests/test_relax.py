"""Convex-relaxation fast path: eligibility registry, flag-off bitwise
parity + cache-key discipline (the PR-10 segmented-kernel pattern), the
relax+repair soundness contract through the verifier, lane-batch parity,
the servlet budget gate (cancel-only relaxes, deadline stays greedy), and
the warmup-daemon CPU compile smoke for the relax executable."""

import numpy as np
import pytest

from cruise_control_tpu.analyzer import GoalOptimizer
from cruise_control_tpu.analyzer import relax as relax_mod
from cruise_control_tpu.analyzer import solver as solver_mod
from cruise_control_tpu.analyzer.goals.registry import (
    RELAX_ELIGIBLE_GOALS,
    is_relax_eligible,
)
from cruise_control_tpu.common.metrics import registry
from cruise_control_tpu.testing import deterministic as det
from cruise_control_tpu.testing.verifier import verify_placement

GOALS = ["ReplicaCapacityGoal", "ReplicaDistributionGoal"]


@pytest.fixture(scope="module")
def snapshot():
    return det.unbalanced2().freeze(pad_replicas_to=64, pad_brokers_to=8)


@pytest.fixture(autouse=True)
def restore_relaxation():
    """Every test leaves the process-wide flag where it found it."""
    prev_on = relax_mod.relaxation_enabled()
    prev = relax_mod.relaxation_params()
    yield
    relax_mod.set_relaxation(prev_on, iterations=prev[0], candidates=prev[1],
                             waves=prev[2], tolerance=prev[3])


def _relax_keys(solver):
    return {k for k in solver._round_cache
            if isinstance(k, tuple) and k and k[0] == "relax"}


# ------------------------------------------------------------- eligibility


def test_eligibility_registry():
    """The relax family is exactly the resource/count-distribution goals;
    rack/capacity/swap-based and kafka_assigner goals never take the path."""
    assert set(RELAX_ELIGIBLE_GOALS) == {
        "ReplicaDistributionGoal",
        "DiskUsageDistributionGoal",
        "NetworkInboundUsageDistributionGoal",
        "NetworkOutboundUsageDistributionGoal",
        "CpuUsageDistributionGoal",
        "LeaderReplicaDistributionGoal",
    }
    assert is_relax_eligible("ReplicaDistributionGoal")
    # Fully-qualified reference names resolve to the bare class name.
    assert is_relax_eligible("com.linkedin.kafka.cruisecontrol.analyzer."
                             "goals.ReplicaDistributionGoal")
    assert not is_relax_eligible("RackAwareGoal")
    # kafka_assigner inherits from ResourceDistributionGoal but opts OUT.
    assert not is_relax_eligible("KafkaAssignerDiskUsageDistributionGoal")
    assert not is_relax_eligible("NoSuchGoal")


# ------------------------------------------- bitwise fall-through (PR 10)


def test_off_bitwise_equals_today_and_cache_keys(snapshot):
    """Acceptance: with the flag off, NO relax executables exist and the
    solve is byte-identical to today's solver; turning the flag on adds
    only ``("relax", ...)`` keys; turning it back off reuses the original
    executables untouched and reproduces the original result bitwise."""
    state, placement, meta = snapshot
    solver = solver_mod.GoalSolver()
    opt = GoalOptimizer(goal_names=GOALS, solver=solver)

    res_off = opt.optimizations(state, placement, meta)
    keys_off = set(solver._round_cache)
    assert not _relax_keys(solver)

    relax_mod.set_relaxation(True)
    res_on = opt.optimizations(state, placement, meta)
    new = set(solver._round_cache) - keys_off
    assert new and all(k[0] == "relax" for k in new)
    assert keys_off <= set(solver._round_cache)  # off-path entries untouched
    assert not res_on.goal_infos[0].relaxed      # capacity goal: ineligible
    assert res_on.goal_infos[1].relaxed

    keys_on = set(solver._round_cache)
    relax_mod.set_relaxation(False)
    res_off2 = opt.optimizations(state, placement, meta)
    assert set(solver._round_cache) == keys_on   # off run builds nothing new
    assert all(not i.relaxed for i in res_off2.goal_infos)
    for name in ("broker", "disk", "is_leader"):
        assert np.array_equal(
            np.asarray(getattr(res_off2.final_placement, name)),
            np.asarray(getattr(res_off.final_placement, name))), name
    for a, b in zip(res_off2.goal_infos, res_off.goal_infos):
        assert (a.rounds, a.moves_applied, a.violated_brokers_after) == \
               (b.rounds, b.moves_applied, b.violated_brokers_after)


def test_ineligible_stack_untouched_when_on(snapshot):
    """A stack with no eligible goal builds no relax executables even with
    the flag ON, and its result matches the flag-off solve bitwise."""
    state, placement, meta = snapshot
    solver = solver_mod.GoalSolver()
    opt = GoalOptimizer(goal_names=["RackAwareGoal", "ReplicaCapacityGoal"],
                        solver=solver)
    res_off = opt.optimizations(state, placement, meta)
    keys_off = set(solver._round_cache)

    relax_mod.set_relaxation(True)
    res_on = opt.optimizations(state, placement, meta)
    assert set(solver._round_cache) == keys_off
    assert all(not i.relaxed for i in res_on.goal_infos)
    for name in ("broker", "disk", "is_leader"):
        assert np.array_equal(
            np.asarray(getattr(res_on.final_placement, name)),
            np.asarray(getattr(res_off.final_placement, name))), name


# ------------------------------------------------------ relax + repair


def test_relax_repair_sound_and_sensors(snapshot):
    """The relax→round→repair pass is a drop-in: the placement passes the
    full verifier, the info is re-anchored at the pre-relax state, and the
    ``Solver.relax.*`` sensors record the attempt."""
    state, placement, meta = snapshot
    solver = solver_mod.GoalSolver()
    opt = GoalOptimizer(goal_names=GOALS, solver=solver)
    relax_mod.relax_sensors()
    a0 = registry().counter(relax_mod.ATTEMPTS_SENSOR).count

    relax_mod.set_relaxation(True)
    res = opt.optimizations(state, placement, meta)
    info = res.goal_infos[1]
    assert info.relaxed
    assert info.relax_ms >= 0.0
    assert info.repair_rounds == info.rounds
    assert registry().counter(relax_mod.ATTEMPTS_SENSOR).count == a0 + 1
    fails = verify_placement(state, placement, meta, res.final_placement,
                             goal_infos=res.goal_infos)
    assert not fails, [str(f) for f in fails]


def test_batch_lanes_relax_parity(snapshot):
    """What-if lanes with the flag on compile the vmapped relax kernel and
    end no worse than pure greedy: every lane still evacuates fully and
    the violated-broker total does not regress."""
    state, placement, meta = snapshot
    solver = solver_mod.GoalSolver()
    opt = GoalOptimizer(goal_names=GOALS, solver=solver)
    sets = [[0], [1]]
    res_off = opt.batch_remove_scenarios(state, placement, meta, sets,
                                         num_candidates=16)
    assert not _relax_keys(solver)

    relax_mod.set_relaxation(True)
    res_on = opt.batch_remove_scenarios(state, placement, meta, sets,
                                        num_candidates=16)
    assert _relax_keys(solver)                   # lane kernel compiled (-X)
    assert int(res_on.stranded_after.sum()) == 0
    assert (int(res_on.violated_after.sum())
            <= int(res_off.violated_after.sum()))
    for s in range(res_on.num_scenarios):
        assert res_on.balancedness(s) >= res_off.balancedness(s) - 1e-6


def test_budget_gate_cancel_only_relaxes_deadline_stays_greedy(snapshot):
    """The service path always carries a cancel-only ``SolveBudget`` (every
    servlet operation has a cancellation token), so the gate must be on
    ``segmented``, not budget-is-None: cancel-only budgets take the fast
    path, deadline (segmented) budgets stay pure greedy."""
    import threading

    from cruise_control_tpu.analyzer.budget import SolveBudget

    state, placement, meta = snapshot
    solver = solver_mod.GoalSolver()
    opt = GoalOptimizer(goal_names=["ReplicaDistributionGoal"], solver=solver)
    relax_mod.set_relaxation(True)

    cancel_only = SolveBudget(cancel_event=threading.Event())
    assert not cancel_only.segmented
    res = opt.optimizations(state, placement, meta, budget=cancel_only)
    assert res.goal_infos[0].relaxed
    assert _relax_keys(solver)

    keys = set(solver._round_cache)
    deadline = SolveBudget(deadline_ms=600_000.0)
    assert deadline.segmented
    res2 = opt.optimizations(state, placement, meta, budget=deadline)
    assert not res2.goal_infos[0].relaxed
    assert _relax_keys(solver) <= keys           # deadline built no relax


# --------------------------------------------------- warmup daemon smoke


def test_warmup_daemon_compiles_relax_kernel_cpu():
    """Satellite: the relax executable compiles on JAX_PLATFORMS=cpu inside
    the existing warmup-daemon ladder — the ``("relax", goals)`` task is
    registered and, run synchronously, leaves exactly one relax executable
    per eligible goal in the solver cache."""
    from tests.test_facade import build_stack

    relax_mod.set_relaxation(True)
    cc, _, _ = build_stack()
    cc.default_goals = list(GOALS)
    daemon = cc._build_warmup_daemon()
    tasks = dict(daemon._tasks)
    key = ("relax", tuple(cc.default_goals))
    assert key in tasks
    before = _relax_keys(cc.optimizer.solver)
    tasks[key]()                                 # the ladder task, inline
    after = _relax_keys(cc.optimizer.solver)
    assert len(after - before) == 1              # one eligible goal in stack
