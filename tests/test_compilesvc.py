"""compilesvc: shape buckets, lane chunking, persistent cache, warmup.

The integration tests at the bottom drive the REAL solver and assert on the
compile telemetry — "zero recompiles" means the ``CompileService.compile-
count`` sensor did not move, which is the subsystem's whole point.

NOTE: the persistent-cache tests point JAX's compilation-cache config at a
tmp_path and restore it afterwards — the suite must never leave a
persistent CPU cache active (tests/conftest.py SIGILL warning).
"""

from __future__ import annotations

import json
import os
import threading
import time

import pytest

from cruise_control_tpu.compilesvc import (
    CompileService,
    LaneChunk,
    PersistentCompileCache,
    ShapeBucketPolicy,
    WarmupDaemon,
    compile_service,
    plan_lane_chunks,
    set_compile_service,
    telemetry,
)
from cruise_control_tpu.compilesvc.buckets import (
    DEFAULT_LANE_LADDER,
    geometric_bucket,
    ladder_bucket,
)
from cruise_control_tpu.compilesvc.cache import (
    SCHEMA_VERSION,
    jaxlib_version,
    machine_fingerprint,
)
from cruise_control_tpu.compilesvc.service import goal_stack_hash


@pytest.fixture
def fresh_service():
    """Swap in a default process service and reset it afterwards."""
    set_compile_service(None)
    yield compile_service()
    set_compile_service(None)


@pytest.fixture
def jax_cache_config_guard():
    """Snapshot/restore the JAX persistent-cache config keys that
    ``PersistentCompileCache.activate`` mutates."""
    import jax
    keys = ("jax_compilation_cache_dir",
            "jax_persistent_cache_min_entry_size_bytes",
            "jax_persistent_cache_min_compile_time_secs")
    before = {k: getattr(jax.config, k) for k in keys}
    yield
    for k, v in before.items():
        jax.config.update(k, v)


# ---------------------------------------------------------------- buckets

def test_geometric_bucket_grows_from_floor():
    assert geometric_bucket(1, 64, 2.0) == 64
    assert geometric_bucket(64, 64, 2.0) == 64
    assert geometric_bucket(65, 64, 2.0) == 128
    assert geometric_bucket(129, 64, 2.0) == 256


def test_ladder_bucket_snaps_up():
    assert ladder_bucket(1, (1, 2, 4, 8)) == 1
    assert ladder_bucket(3, (1, 2, 4, 8)) == 4
    assert ladder_bucket(9, (1, 2, 4, 8)) == 8    # above the top rung: cap


def test_pad_targets_round_trip():
    policy = ShapeBucketPolicy()
    # Historical facade floors: small clusters land on the legacy shapes.
    assert policy.pad_targets(1, 1) == (64, 8)
    assert policy.pad_targets(100, 5) == (128, 8)
    assert policy.pad_targets(65, 9) == (128, 16)
    for n_r in (1, 63, 64, 65, 100, 511, 512, 513):
        for n_b in (1, 8, 9, 100):
            r, b = policy.pad_targets(n_r, n_b)
            assert r >= n_r and b >= n_b
            # Idempotent: a bucket is its own bucket (stable cache keys).
            assert policy.pad_targets(r, b) == (r, b)


def test_bucket_label_format():
    policy = ShapeBucketPolicy()
    assert policy.bucket_label(512, 64) == "R512-C64"
    assert policy.bucket_label(512, 64, lanes=16) == "R512-C64-L16"


def test_freeze_at_bucketed_targets_yields_bucket_shapes():
    from cruise_control_tpu.testing import deterministic as det
    policy = ShapeBucketPolicy()
    cm = det.homogeneous_cluster({0: 0, 1: 0, 2: 1, 3: 1, 4: 2, 5: 2})
    for p in range(30):
        cm.create_replica("T1", p, broker_id=p % 6, index=0, is_leader=True)
        cm.set_replica_load("T1", p, p % 6, det.load(0.1, 1.0, 1.0, 1.0))
    r_pad, b_pad = policy.pad_targets(30, 6)
    assert (r_pad, b_pad) == (64, 8)
    state, _placement, _meta = cm.freeze(pad_replicas_to=r_pad,
                                         pad_brokers_to=b_pad)
    # freeze pads to the next MULTIPLE; a bucket >= n pads to exactly it.
    assert state.num_replicas_padded == r_pad
    assert len(state.alive) == b_pad


# --------------------------------------------------------------- chunking

def test_plan_spec_example_cold():
    # ISSUE spec: 70 lanes, nothing compiled -> 4x16 + one 8-wide tail
    # carrying 6 real lanes.
    plan = plan_lane_chunks(70, DEFAULT_LANE_LADDER, compiled=(),
                            max_chunk=16)
    assert plan[:4] == [LaneChunk(16, 0, 16), LaneChunk(16, 16, 16),
                        LaneChunk(16, 32, 16), LaneChunk(16, 48, 16)]
    assert plan[4] == LaneChunk(8, 64, 6)


def test_plan_64_through_16s():
    plan = plan_lane_chunks(64, DEFAULT_LANE_LADDER, compiled={16},
                            max_chunk=16)
    assert plan == [LaneChunk(16, s, 16) for s in (0, 16, 32, 48)]


def test_plan_reuses_compiled_width_for_ragged_tail():
    # With a 16-wide executable already compiled, riding it for the 6-lane
    # tail beats compiling a fresh 8-wide program.
    plan = plan_lane_chunks(70, DEFAULT_LANE_LADDER, compiled={16},
                            max_chunk=16)
    assert plan[4] == LaneChunk(16, 64, 6)


def test_plan_covers_every_lane_exactly_once():
    for n in (1, 2, 5, 16, 17, 63, 64, 70, 100):
        for compiled in ((), {4}, {16}, {4, 16}):
            plan = plan_lane_chunks(n, DEFAULT_LANE_LADDER,
                                    compiled=compiled, max_chunk=16)
            assert sum(c.n_real for c in plan) == n
            pos = 0
            for c in plan:
                assert c.start == pos
                assert 1 <= c.n_real <= c.size <= 16
                pos += c.n_real


def test_plan_identity_when_chunking_disabled():
    svc = CompileService(chunking_enabled=False)
    assert svc.plan_lanes(70) == [LaneChunk(70, 0, 70)]


def test_lane_registry_round_trip():
    svc = CompileService()
    key = svc.lane_key(["RackAwareGoal"], 512, 16, 64)
    assert svc.compiled_lane_widths(key) == set()
    svc.note_lanes_compiled(key, 16)
    svc.note_lanes_compiled(key, 16)
    svc.note_lanes_compiled(key, 8)
    assert svc.compiled_lane_widths(key) == {8, 16}
    # Key is goal-stack sensitive: another stack sees nothing.
    other = svc.lane_key(["ReplicaCapacityGoal"], 512, 16, 64)
    assert svc.compiled_lane_widths(other) == set()


# ----------------------------------------------------------------- cache

def test_cache_dir_carries_every_version_axis(tmp_path):
    cache = PersistentCompileCache(root=str(tmp_path), enabled=True)
    stack = goal_stack_hash(["RackAwareGoal"])
    path = cache.cache_dir("cpu", stack, "R512-C64")
    parts = os.path.relpath(path, str(tmp_path)).split(os.sep)
    assert parts == [f"v{SCHEMA_VERSION}",
                     f"cpu-{machine_fingerprint()}",
                     f"jaxlib-{jaxlib_version()}", stack, "R512-C64"]


def test_cache_activate_cold_then_warm(tmp_path, jax_cache_config_guard):
    cache = PersistentCompileCache(root=str(tmp_path), enabled=True,
                                   cpu_probe=False)
    assert cache.activate("cpu", "stackA", "R64-C64") is False
    assert cache.active_dir is not None
    # Simulate an XLA write-through, then a fresh process at the same key.
    with open(os.path.join(cache.active_dir, "xla_entry.bin"), "wb") as f:
        f.write(b"\x00" * 64)
    cache2 = PersistentCompileCache(root=str(tmp_path), enabled=True,
                                    cpu_probe=False)
    assert cache2.activate("cpu", "stackA", "R64-C64") is True
    assert cache2.stats()["entries"] == 1
    # A different goal stack or bucket is a different (cold) directory.
    assert cache2.activate("cpu", "stackB", "R64-C64") is False
    assert cache2.activate("cpu", "stackA", "R128-C64") is False


def test_cache_quarantines_unreadable_manifest(tmp_path,
                                               jax_cache_config_guard):
    cache = PersistentCompileCache(root=str(tmp_path), enabled=True,
                                   cpu_probe=False)
    path = cache.cache_dir("cpu", "stackA", "R64-C64")
    os.makedirs(path)
    with open(os.path.join(path, "cc-cache-manifest.json"), "w") as f:
        f.write("{not json")
    with open(os.path.join(path, "xla_entry.bin"), "wb") as f:
        f.write(b"\x00" * 64)
    assert cache.activate("cpu", "stackA", "R64-C64") is False
    assert os.path.isdir(path + ".quarantined")
    assert os.path.exists(
        os.path.join(path + ".quarantined", "xla_entry.bin"))
    # The recreated directory holds a fresh, valid manifest.
    with open(os.path.join(path, "cc-cache-manifest.json")) as f:
        assert json.load(f)["schema"] == SCHEMA_VERSION


def test_cache_quarantines_version_mismatch(tmp_path,
                                            jax_cache_config_guard):
    cache = PersistentCompileCache(root=str(tmp_path), enabled=True,
                                   cpu_probe=False)
    path = cache.cache_dir("cpu", "stackA", "R64-C64")
    os.makedirs(path)
    with open(os.path.join(path, "cc-cache-manifest.json"), "w") as f:
        json.dump({"schema": SCHEMA_VERSION, "jaxlib": "0.0.0",
                   "fingerprint": machine_fingerprint()}, f)
    with open(os.path.join(path, "xla_entry.bin"), "wb") as f:
        f.write(b"\x00" * 64)
    assert cache.activate("cpu", "stackA", "R64-C64") is False
    assert os.path.isdir(path + ".quarantined")


def test_cache_disabled_is_inert(tmp_path):
    cache = PersistentCompileCache(root=str(tmp_path), enabled=False)
    assert cache.activate("cpu") is False
    assert cache.active_dir is None
    assert list(tmp_path.iterdir()) == []


def test_cache_evicts_oldest_first(tmp_path):
    cache = PersistentCompileCache(root=str(tmp_path), max_bytes=150,
                                   enabled=True)
    old = tmp_path / "old.bin"
    new = tmp_path / "new.bin"
    old.write_bytes(b"\x00" * 100)
    new.write_bytes(b"\x00" * 100)
    past = time.time() - 3600
    os.utime(old, (past, past))
    removed = cache.evict(str(tmp_path))
    assert removed == 100
    assert not old.exists() and new.exists()


# ---------------------------------------------------------------- warmup

def test_warmup_duplicate_key_runs_once():
    calls = []
    d = WarmupDaemon()
    d.add_task("k1", lambda: calls.append(1))
    d.add_task("k1", lambda: calls.append(2))
    d.start()
    d.join(timeout=10)
    assert calls == [1]
    assert d.snapshot()["state"] == "done"
    assert d.warmed_keys() == {"k1"}


def test_warmup_restart_skips_warmed_keys():
    calls = []
    d = WarmupDaemon()
    d.add_task("k1", lambda: calls.append(1))
    d.start()
    d.join(timeout=10)
    d.start()                      # restart after completion
    d.join(timeout=10)
    assert calls == [1]


def test_warmup_errors_are_captured_not_raised():
    def boom():
        raise RuntimeError("no backend")
    ran = []
    d = WarmupDaemon()
    d.add_task("bad", boom)
    d.add_task("good", lambda: ran.append(1))
    d.start()
    d.join(timeout=10)
    snap = d.snapshot()
    assert snap["state"] == "done"
    assert ran == [1]
    assert len(snap["errors"]) == 1 and "no backend" in snap["errors"][0]


def test_warmup_stop_aborts_between_tasks():
    release = threading.Event()
    ran = []
    d = WarmupDaemon()
    d.add_task("slow", lambda: release.wait(10))
    d.add_task("never", lambda: ran.append(1))
    d.start()
    d._stop.set()                  # request stop while task 1 is in flight
    release.set()
    d.join(timeout=10)
    assert d.snapshot()["state"] == "stopped"
    assert ran == []


# --------------------------------------------------------------- service

def test_configure_reads_compile_keys(fresh_service):
    from cruise_control_tpu.compilesvc import configure
    from cruise_control_tpu.config import CruiseControlConfig
    cfg = CruiseControlConfig({
        "compile.replica.pad.floor": "128",
        "compile.max.lane.bucket": "8",
        "compile.warmup.enabled": "false",
        "compile.persistent.cache.max.bytes": "1024",
    })
    svc = configure(cfg)
    assert svc is compile_service()
    assert svc.policy.replica_floor == 128
    assert svc.policy.max_lane_bucket == 8
    assert svc.warmup_enabled is False
    assert svc.cache.max_bytes == 1024
    # Persistent cache stays OFF unless explicitly opted in (XLA:CPU
    # cross-process SIGILL hazard — see conftest.py).
    assert svc.cache.enabled is False


def test_configure_defaults(fresh_service):
    from cruise_control_tpu.compilesvc import configure
    from cruise_control_tpu.config import CruiseControlConfig
    svc = configure(CruiseControlConfig({}))
    assert svc.policy.replica_floor == 64
    assert svc.policy.broker_floor == 8
    assert svc.chunking_enabled is True
    assert svc.warmup_enabled is True
    assert svc.warmup_lanes == 4


def test_snapshot_matches_admin_schema(fresh_service):
    from cruise_control_tpu.servlet.schemas import (COMPILE_CACHE_SCHEMA,
                                                    validate)
    svc = fresh_service
    svc.note_lanes_compiled(svc.lane_key(["RackAwareGoal"], 64, 8, 64), 4)
    body = svc.snapshot()
    body["warmup"] = WarmupDaemon().snapshot()
    validate(body, COMPILE_CACHE_SCHEMA)
    validate({**svc.snapshot(), "warmup": None}, COMPILE_CACHE_SCHEMA)


def test_goal_stack_hash_is_order_sensitive():
    a = goal_stack_hash(["A", "B"])
    assert a == goal_stack_hash(["A", "B"])
    assert a != goal_stack_hash(["B", "A"])
    assert len(a) == 12


# ------------------------------------------------------------ integration

def _tiny_cluster(n_partitions):
    from cruise_control_tpu.testing import deterministic as det
    cm = det.homogeneous_cluster({0: 0, 1: 0, 2: 1, 3: 1, 4: 2, 5: 2})
    for p in range(n_partitions):
        lead, foll = p % 6, (p + 1 + p % 3) % 6
        cm.create_replica("T1", p, broker_id=lead, index=0, is_leader=True)
        cm.create_replica("T1", p, broker_id=foll, index=1, is_leader=False)
        cm.set_replica_load("T1", p, lead, det.load(0.2, 10.0, 12.0, 20.0))
        cm.set_replica_load("T1", p, foll, det.load(0.05, 10.0, 0.0, 20.0))
    return cm


def test_second_solve_in_same_bucket_is_zero_recompiles(fresh_service):
    """The subsystem's acceptance property: two snapshots with different
    raw replica counts that land in the SAME shape bucket share every
    executable — the compile sensor must not move on the second solve."""
    from cruise_control_tpu.analyzer import GoalOptimizer
    svc = fresh_service
    opt = GoalOptimizer(goal_names=["RackAwareGoal", "ReplicaCapacityGoal"])

    def solve(n_partitions):
        cm = _tiny_cluster(n_partitions)
        r_pad, b_pad = svc.pad_targets(2 * n_partitions, 6)
        state, placement, meta = cm.freeze(pad_replicas_to=r_pad,
                                           pad_brokers_to=b_pad)
        return opt.optimizations(state, placement, meta)

    # 20 and 25 partitions -> 40 vs 50 replicas, both bucket R64.
    assert svc.pad_targets(40, 6) == svc.pad_targets(50, 6)
    solve(20)
    before = telemetry().compile_count()
    result = solve(25)
    assert telemetry().compile_count() == before
    assert result.balancedness_score >= 0.0


def test_chunked_batch_matches_unchunked(fresh_service):
    """Routing a lane batch through smaller compiled widths must be
    invisible in the results (vmap lanes are independent)."""
    import numpy as np
    from cruise_control_tpu.analyzer import GoalOptimizer
    cm = _tiny_cluster(24)
    state, placement, meta = cm.freeze(pad_replicas_to=64, pad_brokers_to=8)
    sets = [[0], [1], [2], [3], [4], [5], [0, 1], [2, 3]]

    # Chunked: cap lane buckets at 4 so the 8-lane batch becomes 2x4.
    svc = CompileService(policy=ShapeBucketPolicy(max_lane_bucket=4))
    set_compile_service(svc)
    opt = GoalOptimizer(goal_names=["RackAwareGoal", "ReplicaCapacityGoal"])
    chunked = opt.batch_remove_scenarios(state, placement, meta, sets,
                                         num_candidates=64)
    key = svc.lane_key(["RackAwareGoal", "ReplicaCapacityGoal"],
                       state.num_replicas_padded, len(state.alive), 64)
    assert svc.compiled_lane_widths(key) == {4}

    # Unchunked reference (identity plan).
    set_compile_service(CompileService(chunking_enabled=False))
    opt2 = GoalOptimizer(goal_names=["RackAwareGoal", "ReplicaCapacityGoal"])
    plain = opt2.batch_remove_scenarios(state, placement, meta, sets,
                                        num_candidates=64)

    np.testing.assert_array_equal(chunked.violated_after,
                                  plain.violated_after)
    np.testing.assert_array_equal(chunked.moves, plain.moves)
    np.testing.assert_array_equal(chunked.stranded_after,
                                  plain.stranded_after)
    for s in range(len(sets)):
        a, b = chunked.placement_for(s), plain.placement_for(s)
        np.testing.assert_array_equal(np.asarray(a.broker),
                                      np.asarray(b.broker))
        np.testing.assert_array_equal(np.asarray(a.is_leader),
                                      np.asarray(b.is_leader))
        assert chunked.quality(s) == plain.quality(s)


# ------------------------------------------------------- cpu loader probe

def _stub_probe(verdict):
    """An injectable probe runner recording its calls."""
    calls = []

    def run(workdir, timeout_s):
        calls.append(workdir)
        return verdict

    return run, calls


def test_probe_memoizes_verdict_per_host(tmp_path):
    from cruise_control_tpu.compilesvc.cache import probe_cpu_cache_loader
    ok_run, ok_calls = _stub_probe(True)
    assert probe_cpu_cache_loader(str(tmp_path), runner=ok_run) is True
    assert len(ok_calls) == 1
    # Marker carries the verdict: a later (even contradictory) runner never
    # executes until the memo is refreshed.
    fail_run, fail_calls = _stub_probe(False)
    assert probe_cpu_cache_loader(str(tmp_path), runner=fail_run) is True
    assert fail_calls == []
    assert probe_cpu_cache_loader(str(tmp_path), runner=fail_run,
                                  refresh=True) is False
    assert len(fail_calls) == 1
    assert probe_cpu_cache_loader(str(tmp_path), runner=ok_run) is False


def test_probe_marker_keys_on_jaxlib_and_fingerprint(tmp_path):
    from cruise_control_tpu.compilesvc.cache import probe_cpu_cache_loader
    run, _ = _stub_probe(True)
    probe_cpu_cache_loader(str(tmp_path), runner=run)
    marker = (tmp_path / f"v{SCHEMA_VERSION}" /
              f"cpu-probe-{jaxlib_version()}-{machine_fingerprint()}.json")
    assert marker.exists()
    data = json.loads(marker.read_text())
    assert data == {"ok": True, "jaxlib": jaxlib_version(),
                    "fingerprint": machine_fingerprint()}


def test_probe_runner_exception_means_unsupported(tmp_path):
    from cruise_control_tpu.compilesvc.cache import probe_cpu_cache_loader

    def boom(workdir, timeout_s):
        raise RuntimeError("child died")

    assert probe_cpu_cache_loader(str(tmp_path), runner=boom) is False


def test_activate_gates_cpu_on_failed_probe(tmp_path, jax_cache_config_guard):
    from cruise_control_tpu.compilesvc.cache import probe_cpu_cache_loader
    fail_run, _ = _stub_probe(False)
    probe_cpu_cache_loader(str(tmp_path), runner=fail_run)   # memoize "no"
    cache = PersistentCompileCache(root=str(tmp_path), enabled=True)
    assert cache.activate("cpu", "stackA", "R64-C64") is False
    assert cache.active_dir is None    # never touched jax.config


def test_activate_proceeds_on_passed_probe(tmp_path, jax_cache_config_guard):
    from cruise_control_tpu.compilesvc.cache import probe_cpu_cache_loader
    ok_run, _ = _stub_probe(True)
    probe_cpu_cache_loader(str(tmp_path), runner=ok_run)     # memoize "yes"
    cache = PersistentCompileCache(root=str(tmp_path), enabled=True)
    assert cache.activate("cpu", "stackA", "R64-C64") is False   # cold
    assert cache.active_dir is not None


def test_activate_probe_opt_out_restores_blind_trust(tmp_path,
                                                     jax_cache_config_guard):
    cache = PersistentCompileCache(root=str(tmp_path), enabled=True,
                                   cpu_probe=False)
    cache.activate("cpu", "stackA", "R64-C64")
    assert cache.active_dir is not None
    # No probe marker was ever written.
    assert not list((tmp_path / f"v{SCHEMA_VERSION}").glob("cpu-probe-*"))


def test_activate_never_probes_non_cpu(tmp_path, jax_cache_config_guard):
    from cruise_control_tpu.compilesvc.cache import probe_cpu_cache_loader
    fail_run, _ = _stub_probe(False)
    probe_cpu_cache_loader(str(tmp_path), runner=fail_run)   # memoize "no"
    cache = PersistentCompileCache(root=str(tmp_path), enabled=True)
    cache.activate("tpu", "stackA", "R64-C64")               # gate is CPU-only
    assert cache.active_dir is not None


def test_configure_env_default_on(fresh_service, monkeypatch):
    from cruise_control_tpu.compilesvc import configure
    from cruise_control_tpu.config.cruise_control_config import (
        CruiseControlConfig,
    )
    monkeypatch.setenv("CC_TPU_PERSIST_CACHE", "1")
    svc = configure(CruiseControlConfig({}))
    assert svc.cache.enabled is True
    # A path-valued env var doubles as the cache root.
    monkeypatch.setenv("CC_TPU_PERSIST_CACHE", "/tmp/cc-cache-root")
    svc = configure(CruiseControlConfig({}))
    assert svc.cache.enabled is True
    assert svc.cache.root == "/tmp/cc-cache-root"
    set_compile_service(None)


def test_configure_explicit_config_beats_env(fresh_service, monkeypatch):
    from cruise_control_tpu.compilesvc import configure
    from cruise_control_tpu.config.cruise_control_config import (
        CruiseControlConfig,
    )
    monkeypatch.setenv("CC_TPU_PERSIST_CACHE", "1")
    svc = configure(CruiseControlConfig(
        {"compile.persistent.cache.enabled": False}))
    assert svc.cache.enabled is False
    monkeypatch.delenv("CC_TPU_PERSIST_CACHE")
    svc = configure(CruiseControlConfig(
        {"compile.persistent.cache.cpu.probe": False}))
    assert svc.cache.cpu_probe is False
    assert svc.cache.enabled is False   # env unset: config default stands
    set_compile_service(None)
