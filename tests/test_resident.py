"""Resident cluster model: delta collection, scatter apply, and the
device-resident service.

The contract under test is BITWISE equality: a resident (state, placement)
updated by ``apply_deltas`` must be indistinguishable from a fresh
``freeze()`` of the same builder — same dtypes, same rounding, same padding.
Anything weaker would let solver answers drift between the delta path and
the re-freeze path.
"""

import threading

import numpy as np
import pytest

from cruise_control_tpu.common.metrics import registry
from cruise_control_tpu.model.builder import ClusterModel, builder_from_snapshot
from cruise_control_tpu.model.resident import ResidentModelService
from cruise_control_tpu.model.state import apply_deltas, empty_delta
from cruise_control_tpu.testing import deterministic as det

PAD_R, PAD_B = 16, 4

STATE_FIELDS = ("leader_load", "follower_load", "partition", "topic", "pos",
                "orig_broker", "offline", "valid", "capacity", "host", "rack",
                "alive", "new_broker", "broker_valid", "disk_capacity",
                "disk_alive")
PLACEMENT_FIELDS = ("broker", "disk", "is_leader")


def _freeze(cm):
    return cm.freeze(pad_replicas_to=PAD_R, pad_brokers_to=PAD_B)


def assert_bitwise_equal(got, want):
    gs, gp = got
    ws, wp = want
    for name in STATE_FIELDS:
        a, b = np.asarray(getattr(gs, name)), np.asarray(getattr(ws, name))
        assert a.dtype == b.dtype and a.shape == b.shape, name
        assert (a == b).all(), f"state.{name} diverged"
    for name in PLACEMENT_FIELDS:
        a, b = np.asarray(getattr(gp, name)), np.asarray(getattr(wp, name))
        assert a.dtype == b.dtype and (a == b).all(), \
            f"placement.{name} diverged"


def tracked_cluster():
    cm = det.small_cluster_model()
    cm.enable_delta_tracking()
    return cm


# ----------------------------------------------------------- delta collection


def test_counts_maintained_incrementally():
    cm = det.small_cluster_model()
    n_r = sum(len(rs) for rs in cm.partitions().values())
    assert cm.counts() == (n_r, len(cm.brokers()))
    cm.create_replica("T9", 0, broker_id=0, index=0, is_leader=True)
    assert cm.counts()[0] == n_r + 1
    cm.delete_replica("T9", 0, 0)
    assert cm.counts()[0] == n_r


def test_sparse_delta_bitwise_equal_to_fresh_freeze():
    cm = tracked_cluster()
    state, placement, meta = _freeze(cm)
    v0 = cm.version

    cm.set_replica_load("T1", 0, 0, det.load(33.0, 101.5, 77.25, 13.0))
    cm.set_broker_state(2, alive=False)          # liveness flip: delta rows
    cm.relocate_leadership("T2", 1, 0, 2)

    delta = cm.collect_delta()
    assert delta is not None and delta.perm is None
    assert delta.from_version == v0 and delta.to_version == cm.version
    assert delta.num_updates > 0
    got_s, got_p = apply_deltas(state, placement, delta,
                                pad_replica_updates_to=8,
                                pad_broker_updates_to=4)
    want_s, want_p, want_m = _freeze(cm)
    assert_bitwise_equal((got_s, got_p), (want_s, want_p))
    assert want_m.extra["model_version"] == cm.version


def test_structural_delta_uses_perm_and_matches():
    cm = tracked_cluster()
    state, placement, meta = _freeze(cm)

    cm.delete_replica("T2", 0, 2)                # shifts row ordering
    cm.create_replica("T2", 3, broker_id=1, index=0, is_leader=True)
    cm.set_replica_load("T2", 3, 1, det.load(1.0, 2.0, 3.0, 4.0))

    delta = cm.collect_delta()
    assert delta is not None and delta.perm is not None
    assert delta.meta is not None
    got_s, got_p = apply_deltas(state, placement, delta,
                                pad_replica_updates_to=16,
                                pad_broker_updates_to=4)
    want_s, want_p, want_m = _freeze(cm)
    assert_bitwise_equal((got_s, got_p), (want_s, want_p))
    assert delta.meta.num_replicas == want_m.num_replicas
    assert list(delta.meta.topics) == list(want_m.topics)


def test_delta_after_delta_chain():
    """Several consecutive deltas replayed into the same buffers stay
    bitwise-faithful (the chain the resident service runs in steady state)."""
    cm = tracked_cluster()
    state, placement, _ = _freeze(cm)
    rng = np.random.default_rng(7)
    pairs = [(t, p) for (t, p) in cm.partitions().keys()]
    for step in range(5):
        t, p = pairs[int(rng.integers(len(pairs)))]
        for r in cm.partition(t, p):
            cm.set_replica_load(t, p, r.broker_id,
                                rng.uniform(1.0, 50.0, size=4))
        delta = cm.collect_delta()
        assert delta is not None
        state, placement = apply_deltas(state, placement, delta,
                                        pad_replica_updates_to=8,
                                        pad_broker_updates_to=4)
    want_s, want_p, _ = _freeze(cm)
    assert_bitwise_equal((state, placement), (want_s, want_p))


def test_overflow_and_inexpressible_edits_refuse_delta():
    cm = tracked_cluster()
    _freeze(cm)
    for (t, p), rs in cm.partitions().items():
        for r in rs:
            cm.set_replica_load(t, p, r.broker_id, det.load(1, 1, 1, 1))
    assert cm.collect_delta(max_updates=2) is None   # overflow → full freeze

    cm2 = tracked_cluster()
    _freeze(cm2)
    cm2.create_broker(rack="9", host="h9", broker_id=9,
                      capacity=dict(det.BROKER_CAPACITY))
    assert cm2.collect_delta() is None               # new broker: refreeze

    cm3 = tracked_cluster()
    assert cm3.collect_delta() is None               # never frozen


def test_builder_from_snapshot_roundtrip():
    cm = det.small_cluster_model()
    frozen = cm.freeze(pad_replicas_to=PAD_R, pad_brokers_to=PAD_B)
    rebuilt = builder_from_snapshot(*frozen)
    assert rebuilt.counts() == cm.counts()
    again = rebuilt.freeze(pad_replicas_to=PAD_R, pad_brokers_to=PAD_B)
    assert_bitwise_equal((again[0], again[1]), (frozen[0], frozen[1]))


# ------------------------------------------------------------ resident service


def _pad_fn(n_r, n_b):
    return (PAD_R, PAD_B)


def test_resident_service_lifecycle():
    svc = ResidentModelService()
    cm = det.small_cluster_model()
    full0 = svc.stats()["fullFreezes"]

    s1, p1, m1 = svc.snapshot(cm, _pad_fn)
    st = svc.stats()
    assert st["resident"] and st["fullFreezes"] == full0 + 1

    # Same version: zero-work identity return of the resident tensors.
    s2, p2, m2 = svc.snapshot(cm, _pad_fn)
    assert s2 is s1 and p2 is p1
    assert svc.stats()["fullFreezes"] == full0 + 1

    # A journalled edit rides the delta path (and donates the old buffers).
    cm.set_replica_load("T1", 0, 0, det.load(9.0, 9.0, 9.0, 9.0))
    s3, p3, m3 = svc.snapshot(cm, _pad_fn)
    st = svc.stats()
    assert st["deltaApplies"] >= 1 and st["fullFreezes"] == full0 + 1
    want = cm.freeze(pad_replicas_to=PAD_R, pad_brokers_to=PAD_B)
    assert_bitwise_equal((s3, p3), (want[0], want[1]))

    # freeze() above reset the journal: invalidation forces a re-freeze.
    svc.invalidate("test")
    assert not svc.stats()["resident"]
    svc.snapshot(cm, _pad_fn)
    st = svc.stats()
    assert st["fullFreezes"] == full0 + 2
    assert st["invalidationReasons"].get("test") == 1


def test_resident_bucket_change_forces_full_freeze():
    svc = ResidentModelService()
    cm = det.small_cluster_model()
    buckets = {"pad": (PAD_R, PAD_B)}
    svc.snapshot(cm, lambda r, b: buckets["pad"])
    full = svc.stats()["fullFreezes"]
    cm.set_replica_load("T1", 0, 0, det.load(3, 3, 3, 3))
    buckets["pad"] = (PAD_R * 2, PAD_B)          # cluster crossed a boundary
    s, p, m = svc.snapshot(cm, lambda r, b: buckets["pad"])
    assert int(np.asarray(s.valid).shape[0]) == PAD_R * 2
    assert svc.stats()["fullFreezes"] == full + 1


def test_resident_pins_block_donation():
    """A pinned snapshot's buffers must survive until release(): the delta
    apply donates them, so it has to wait for the pin to drain."""
    svc = ResidentModelService(pin_wait_s=30.0)
    cm = det.small_cluster_model()
    s1, p1, _ = svc.snapshot(cm, _pad_fn, pin=True)
    cm.set_replica_load("T1", 0, 0, det.load(2.0, 2.0, 2.0, 2.0))

    applied = threading.Event()

    def deltaing():
        svc.snapshot(cm, _pad_fn)
        applied.set()

    t = threading.Thread(target=deltaing)
    t.start()
    # While the pin is held the apply must not have run (donation would
    # delete s1's buffers out from under the in-flight "solve").
    assert not applied.wait(timeout=0.5)
    assert float(np.asarray(s1.leader_load).sum()) >= 0.0   # still readable
    svc.release()
    assert applied.wait(timeout=10.0)
    t.join()
    assert svc.stats()["deltaApplies"] >= 1


def test_resident_disabled_always_freezes():
    svc = ResidentModelService(enabled=False)
    cm = det.small_cluster_model()
    s0 = svc.stats()
    svc.snapshot(cm, _pad_fn)
    svc.snapshot(cm, _pad_fn)
    st = svc.stats()
    assert st["fullFreezes"] == s0["fullFreezes"] + 2 and not st["resident"]
    assert st["deltaApplies"] == s0["deltaApplies"]


def test_warm_scatter_compiles_both_kernels():
    svc = ResidentModelService()
    svc.warm_scatter(PAD_R, PAD_B, num_disks=2)   # must not raise


def test_delta_chain_cap_forces_refreeze():
    svc = ResidentModelService(max_delta_chain=1)
    cm = det.small_cluster_model()
    svc.snapshot(cm, _pad_fn)
    s0 = svc.stats()
    cm.set_replica_load("T1", 0, 0, det.load(4, 4, 4, 4))
    svc.snapshot(cm, _pad_fn)                     # chain 0 → 1: delta
    cm.set_replica_load("T1", 0, 0, det.load(5, 5, 5, 5))
    svc.snapshot(cm, _pad_fn)                     # chain at cap: full freeze
    st = svc.stats()
    assert st["deltaApplies"] == s0["deltaApplies"] + 1
    assert st["fullFreezes"] == s0["fullFreezes"] + 1


# ------------------------------------------------------- monitor resident path


def test_monitor_resident_builder_fresh_then_diff():
    from tests.test_facade import build_stack

    cc, backend, _ = build_stack()
    lm = cc.load_monitor
    cm, fresh = lm.resident_model_builder()
    assert fresh and cm.delta_tracking
    cm2, fresh2 = lm.resident_model_builder()
    assert cm2 is cm and not fresh2

    # Changed workload → sparse journal on the SAME builder object.
    cm.freeze(pad_replicas_to=64, pad_brokers_to=8)
    cc.task_runner.sampler.mean_bytes_in *= 1.25
    cc.task_runner.bootstrap(6_000, 12_000)
    cm3, fresh3 = lm.resident_model_builder()
    assert cm3 is cm and not fresh3
    delta = cm.collect_delta()
    assert delta is not None and delta.num_updates > 0

    # Structural metadata change (new partition) → fingerprint flip → fresh.
    from cruise_control_tpu.monitor.metadata import PartitionInfo
    md = backend.fetch()
    backend.partitions = list(md.partitions) + [
        PartitionInfo("T", 99, leader=0, replicas=(0, 1), in_sync=(0,))]
    cm4, fresh4 = lm.resident_model_builder()
    assert fresh4 and cm4 is not cm
    cc.shutdown()
