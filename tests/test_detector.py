"""Detector + self-healing tests (models AnomalyDetectorManagerTest: mock
detectors + the real queue/handler, and detector-specific scenarios)."""

import numpy as np
import pytest

from cruise_control_tpu.detector.anomalies import (
    Anomaly,
    AnomalyType,
    BrokerFailures,
    GoalViolations,
    MaintenanceEvent,
)
from cruise_control_tpu.detector.detectors import (
    BrokerFailureDetector,
    DiskFailureDetector,
    GoalViolationDetector,
    MaintenanceEventDetector,
    MetricAnomalyDetector,
    TopicAnomalyDetector,
)
from cruise_control_tpu.detector.manager import AnomalyDetectorManager
from cruise_control_tpu.detector.notifier import (
    AnomalyNotificationResult,
    SelfHealingNotifier,
)
from cruise_control_tpu.monitor import metric_def as md
from cruise_control_tpu.monitor.aggregator import MetricSampleAggregator
from cruise_control_tpu.monitor.load_monitor import LoadMonitor
from cruise_control_tpu.monitor.metadata import (
    BrokerInfo,
    FakeMetadataBackend,
    MetadataClient,
    PartitionInfo,
)
from cruise_control_tpu.monitor.sampler import SyntheticWorkloadSampler
from cruise_control_tpu.monitor.task_runner import LoadMonitorTaskRunner

W = 1000


def _cluster(num_brokers=4):
    brokers = [BrokerInfo(i, rack=str(i % 2), host=f"h{i}") for i in range(num_brokers)]
    parts = [PartitionInfo("T", p, leader=p % num_brokers,
                           replicas=(p % num_brokers, (p + 1) % num_brokers),
                           in_sync=(p % num_brokers,))
             for p in range(8)]
    return FakeMetadataBackend(brokers, parts)


def _monitored(backend):
    client = MetadataClient(backend, ttl_ms=0)
    lm = LoadMonitor(client, num_windows=5, window_ms=W, min_samples_per_window=1)
    runner = LoadMonitorTaskRunner(lm, SyntheticWorkloadSampler(),
                                   sampling_interval_ms=W)
    runner.bootstrap(0, 6 * W)
    return lm


def test_broker_failure_detector_tracks_and_persists(tmp_path):
    backend = _cluster()
    client = MetadataClient(backend, ttl_ms=0)
    path = str(tmp_path / "failed.json")
    clock = {"now": 1_000.0}
    det = BrokerFailureDetector(client, persist_path=path,
                                clock=lambda: clock["now"])
    assert det.detect() == []
    backend.kill_broker(2)
    found = det.detect()
    assert len(found) == 1 and isinstance(found[0], BrokerFailures)
    assert found[0].failed_brokers == {2: 1_000.0}
    # Restart: timestamps survive via the persisted record.
    clock["now"] = 9_999.0
    det2 = BrokerFailureDetector(client, persist_path=path,
                                 clock=lambda: clock["now"])
    assert det2.detect()[0].failed_brokers == {2: 1_000.0}


def test_goal_violation_detector_flags_and_skips_same_generation():
    backend = _cluster()
    lm = _monitored(backend)
    backend.kill_broker(3)
    det = GoalViolationDetector(lm, goal_names=["ReplicaCapacityGoal"])
    found = det.detect()
    assert len(found) == 1
    assert found[0].fixable_violated_goals == ["ReplicaCapacityGoal"]
    # Same model generation → detector skips (reference :114-121).
    assert det.detect() == []


def test_disk_failure_detector():
    det = DiskFailureDetector(lambda: {1: [0]})
    found = det.detect()
    assert found[0].failed_disks == {1: [0]}
    det2 = DiskFailureDetector(lambda: {})
    assert det2.detect() == []


def test_metric_anomaly_detector_flags_slow_broker():
    agg = MetricSampleAggregator(md.BROKER_METRIC_DEF, num_windows=5, window_ms=W,
                                 min_samples_per_window=1)
    flush = md.BROKER_METRIC_DEF.metric_id("BROKER_LOG_FLUSH_TIME_MS_MEAN")

    def metrics(v):
        m = np.zeros(md.BROKER_METRIC_DEF.size)
        m[flush] = v
        return m

    for w in range(6):
        for b in range(4):
            slow = b == 3 and w == 4
            agg.add_sample(b, w * W + 10, metrics(100.0 if slow else 1.0))
    det = MetricAnomalyDetector(agg, percentile=90, margin=1.5,
                                slow_broker_demotion_score=1)
    found = det.detect()
    assert any(a.broker_id == 3 for a in found)


def test_topic_anomaly_detector_rf():
    backend = _cluster()
    client = MetadataClient(backend, ttl_ms=0)
    det = TopicAnomalyDetector(client, target_replication_factor=3)
    found = det.detect()
    assert len(found) == 1 and found[0].topic == "T"
    assert found[0].target_replication_factor == 3


def test_maintenance_event_idempotence():
    det = MaintenanceEventDetector(idempotence_ttl_ms=1e9)
    e = MaintenanceEvent(plan="rebalance")
    assert det.submit(e) is True
    assert det.submit(MaintenanceEvent(plan="rebalance")) is False  # duplicate
    assert det.submit(MaintenanceEvent(plan="remove_broker", broker_ids=(1,)))
    found = det.detect()
    assert len(found) == 2
    assert det.detect() == []


def test_self_healing_notifier_broker_failure_grace_periods():
    clock = {"now": 0.0}
    alerts = []
    notifier = SelfHealingNotifier(
        self_healing_enabled=True,
        alert_callback=lambda a, fix: alerts.append(fix),
        clock=lambda: clock["now"],
        broker_failure_alert_threshold_ms=100,
        broker_failure_self_healing_threshold_ms=200,
    )
    a = BrokerFailures(failed_brokers={1: 0.0})
    # Before alert threshold: delayed check.
    act = notifier.on_anomaly(a)
    assert act.result is AnomalyNotificationResult.CHECK
    # Past alert, before fix: alert fired, still check.
    clock["now"] = 150.0
    act = notifier.on_anomaly(a)
    assert act.result is AnomalyNotificationResult.CHECK
    assert len(alerts) == 1
    # Past the self-healing threshold: fix.
    clock["now"] = 250.0
    assert notifier.on_anomaly(a).result is AnomalyNotificationResult.FIX


def test_manager_priority_and_fix_dispatch():
    fixed = []

    class StubDetector:
        def __init__(self, anomaly):
            self.anomaly = anomaly
            self.fired = False

        def detect(self):
            if self.fired:
                return []
            self.fired = True
            return [self.anomaly]

    gv = GoalViolations(fixable=["ReplicaDistributionGoal"])
    bf = BrokerFailures(failed_brokers={1: 0.0})
    notifier = SelfHealingNotifier(
        self_healing_enabled=True, clock=lambda: 1e12,
        broker_failure_alert_threshold_ms=0,
        broker_failure_self_healing_threshold_ms=0)
    mgr = AnomalyDetectorManager(
        {AnomalyType.GOAL_VIOLATION: StubDetector(gv),
         AnomalyType.BROKER_FAILURE: StubDetector(bf)},
        notifier=notifier,
        fixer=lambda a: fixed.append(a.anomaly_type) or True)
    mgr.run_detection_once()
    # Broker failure (priority 0) handled before goal violation (priority 3).
    assert fixed == [AnomalyType.BROKER_FAILURE, AnomalyType.GOAL_VIOLATION]
    summary = mgr.state_summary()
    assert summary["metrics"]["FIX_STARTED"] == 2


def test_webhook_notifier_posts_and_survives_failure():
    from cruise_control_tpu.detector.notifier import WebhookSelfHealingNotifier
    from cruise_control_tpu.detector.anomalies import GoalViolations

    posts = []
    n = WebhookSelfHealingNotifier("http://hook.invalid/x", channel="#alerts",
                                   post_fn=posts.append,
                                   self_healing_enabled=False)
    a = GoalViolations(fixable=["RackAwareGoal"])
    action = n.on_anomaly(a)
    assert action.result.name == "IGNORE"   # self-healing disabled -> alert only
    assert posts and "GOAL_VIOLATION" in posts[0]["text"]
    assert posts[0]["channel"] == "#alerts"

    def boom(payload):
        raise OSError("webhook down")
    n2 = WebhookSelfHealingNotifier("http://hook.invalid/x", post_fn=boom,
                                    self_healing_enabled=False)
    n2.on_anomaly(a)    # must not raise


# --------------------------------------------------------------------------
# Maintenance plans from the message bus (MaintenanceEventTopicReader analog)


def test_maintenance_plan_serde_roundtrip_and_rejects():
    from cruise_control_tpu.detector import maintenance_reader as mr

    rec = mr.serialize_plan("remove_broker", time_ms=1000.0,
                            broker_ids=(3, 1))
    plan = mr.deserialize_plan(rec)
    assert plan["planType"] == "remove_broker"
    assert plan["brokers"] == [1, 3]
    assert plan["timeMs"] == 1000.0

    # Content tamper -> CRC mismatch (MaintenancePlanSerde.verifyCrc).
    import json
    obj = json.loads(rec)
    obj["brokers"] = [1, 2]
    with pytest.raises(ValueError, match="crc"):
        mr.deserialize_plan(json.dumps(obj).encode())
    # Unknown type and future version are deserialization errors.
    with pytest.raises(ValueError, match="unknown maintenance plan"):
        mr.serialize_plan("repartition", time_ms=0.0)
    future = json.loads(mr.serialize_plan("rebalance", time_ms=0.0))
    del future["crc"]
    future["version"] = 99
    future["crc"] = mr._content_crc(future)
    with pytest.raises(ValueError, match="latest supported"):
        mr.deserialize_plan(json.dumps(
            {k: future[k] for k in sorted(future)}).encode())
    with pytest.raises(ValueError, match="undecodable"):
        mr.deserialize_plan(b"\xff\x00 not json")


def test_maintenance_reader_expires_dedups_and_resumes(tmp_path):
    from cruise_control_tpu.detector import maintenance_reader as mr
    from cruise_control_tpu.reporter import FileTransport

    now = 10_000_000.0
    bus = FileTransport(str(tmp_path / "bus"), num_partitions=2)
    bus.append(0, mr.serialize_plan("remove_broker", time_ms=now - 1000,
                                    broker_ids=(2,)))
    bus.append(1, mr.serialize_plan("remove_broker", time_ms=now - 2000,
                                    broker_ids=(2,)))        # duplicate plan
    bus.append(0, mr.serialize_plan("rebalance", time_ms=now - 999_999))
    bus.append(1, b"garbage record")                         # skipped, logged
    det = MaintenanceEventDetector(idempotence_ttl_ms=1e9)
    offsets = tmp_path / "offsets.json"
    reader = mr.MaintenanceEventReader(bus, det, offsets_path=str(offsets),
                                       expiration_ms=900_000,
                                       clock=lambda: now)
    accepted, dropped = reader.poll_once()
    assert accepted == 1          # fresh remove_broker
    assert dropped == 3           # duplicate + expired + garbage
    events = det.detect()
    assert len(events) == 1 and events[0].plan == "remove_broker"
    assert events[0].broker_ids == (2,)

    # Committed offsets: a restarted reader (fresh detector, same offsets
    # file) resumes past everything already processed.
    det2 = MaintenanceEventDetector(idempotence_ttl_ms=1e9)
    reader2 = mr.MaintenanceEventReader(bus, det2, offsets_path=str(offsets),
                                        expiration_ms=900_000,
                                        clock=lambda: now)
    assert reader2.poll_once() == (0, 0)
    assert det2.detect() == []
    # New plans appended after the restart ARE picked up.
    bus.append(0, mr.serialize_plan("demote_broker", time_ms=now,
                                    broker_ids=(0,)))
    assert reader2.poll_once() == (1, 0)
    assert det2.detect()[0].plan == "demote_broker"


def test_maintenance_plans_posted_from_second_process_over_tcp(tmp_path):
    """A second OS process posts plans over the TCP transport face (the role
    of the reference's Kafka producer posting to __MaintenanceEvent); the
    in-service reader consumes them and the detector manager routes the
    event to the fixer."""
    import subprocess
    import sys
    import time as _time

    from cruise_control_tpu.detector import maintenance_reader as mr
    from cruise_control_tpu.reporter import InProcessTransport, TransportServer

    bus = InProcessTransport(num_partitions=2)
    server = TransportServer(bus, host="127.0.0.1", port=0)
    server.start()
    try:
        now_ms = _time.time() * 1000
        child = (
            "import sys\n"
            "from cruise_control_tpu.reporter import SocketTransport\n"
            "from cruise_control_tpu.detector.maintenance_reader import "
            "serialize_plan\n"
            "t = SocketTransport('127.0.0.1:%d')\n"
            "t.append(0, serialize_plan('remove_broker', time_ms=%f, "
            "broker_ids=(1,)))\n"
            "t.append(1, serialize_plan('rebalance', time_ms=%f))\n"
            "t.close()\n" % (server.port, now_ms, now_ms))
        proc = subprocess.run([sys.executable, "-c", child], timeout=120,
                              capture_output=True, text=True)
        assert proc.returncode == 0, proc.stderr

        det = MaintenanceEventDetector(idempotence_ttl_ms=1e9)
        reader = mr.MaintenanceEventReader(
            bus, det, offsets_path=str(tmp_path / "off.json"))
        assert reader.poll_once() == (2, 0)

        fixed = []
        mgr = AnomalyDetectorManager(
            {AnomalyType.MAINTENANCE_EVENT: det},
            notifier=SelfHealingNotifier(self_healing_enabled=True),
            fixer=lambda a: fixed.append((a.anomaly_type, a.plan)) or True)
        # Events were drained into the manager path on this detect cycle.
        reader.poll_once()      # nothing new
        mgr.run_detection_once()
        assert (AnomalyType.MAINTENANCE_EVENT, "remove_broker") in fixed
        assert (AnomalyType.MAINTENANCE_EVENT, "rebalance") in fixed
    finally:
        server.stop()


def test_slo_violation_flows_to_audit_as_ignored():
    """An SloViolationAnomaly is unfixable: the notifier must IGNORE it (no
    fixer dispatch) and the manager must still land it in the self-healing
    audit ring with its burn-rate detail."""
    from cruise_control_tpu.detector.anomalies import SloViolationAnomaly
    from cruise_control_tpu.obsvc.audit import audit_log

    fixed = []

    class StubSloDetector:
        def __init__(self):
            self.fired = False

        def detect(self):
            if self.fired:
                return []
            self.fired = True
            return [SloViolationAnomaly(
                objective="solve-time", sensor="GoalOptimizer.x",
                threshold=100.0, worst_value=250.0,
                burn_rate_short=3.0, burn_rate_long=2.0)]

    notifier = SelfHealingNotifier(
        self_healing_enabled=True, clock=lambda: 1e12,
        broker_failure_alert_threshold_ms=0,
        broker_failure_self_healing_threshold_ms=0)
    mgr = AnomalyDetectorManager(
        {AnomalyType.SLO_VIOLATION: StubSloDetector()},
        notifier=notifier,
        fixer=lambda a: fixed.append(a.anomaly_type) or True)
    audit_log().clear()
    try:
        mgr.run_detection_once()
        assert fixed == []                      # unfixable -> never dispatched
        entries = [e for e in audit_log().entries()
                   if e["anomalyType"] == "SLO_VIOLATION"]
        assert entries, audit_log().entries()
        entry = entries[-1]
        assert entry["decision"] == "IGNORED"
        assert entry["description"]["objective"] == "solve-time"
        assert entry["description"]["burnRateShort"] == 3.0
        assert mgr.state_summary()["metrics"].get("FIX_STARTED", 0) == 0
    finally:
        audit_log().clear()
