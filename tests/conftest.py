"""Test bootstrap: run JAX on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is unavailable in CI; sharding correctness is validated
on host-platform virtual devices instead.  Must run before the first backend
initialization.

Two traps this guards against (handled by ``utils.hermetic.force_cpu``):
- ``JAX_PLATFORMS`` is preset to ``axon`` in the environment, so ``setdefault``
  would silently leave tests running on the real TPU chip.
- The axon PJRT plugin registers at interpreter start (sitecustomize) and
  ``jax.backends()`` initializes *every* registered plugin regardless of
  ``JAX_PLATFORMS`` — if the TPU tunnel is down, that init hangs forever.
  Deregistering the factory before the first backend lookup keeps tests
  hermetic and CPU-only.
"""

from cruise_control_tpu.utils.hermetic import force_cpu

force_cpu(n_devices=8)
