"""Test bootstrap: run JAX on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is unavailable in CI; sharding correctness is validated
on host-platform virtual devices instead.  Must run before the first jax import.

Two traps this guards against:
- ``JAX_PLATFORMS`` is preset to ``axon`` in the environment, so ``setdefault``
  would silently leave tests running on the real TPU chip.
- The axon PJRT plugin registers at interpreter start (sitecustomize) and
  ``jax.backends()`` initializes *every* registered plugin regardless of
  ``JAX_PLATFORMS`` — if the TPU tunnel is down, that init hangs forever.
  Deregistering the factory before the first backend lookup keeps tests
  hermetic and CPU-only.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402
from jax._src import xla_bridge as _xb  # noqa: E402

# sitecustomize imported jax before this file ran, so the config already
# captured JAX_PLATFORMS=axon — override it through the config API too.
jax.config.update("jax_platforms", "cpu")
_xb._backend_factories.pop("axon", None)
