"""Test bootstrap: run JAX on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is unavailable in CI; sharding correctness is validated
on host-platform virtual devices instead.  Must run before the first backend
initialization.

Two traps this guards against (handled by ``utils.hermetic.force_cpu``):
- ``JAX_PLATFORMS`` is preset to ``axon`` in the environment, so ``setdefault``
  would silently leave tests running on the real TPU chip.
- The axon PJRT plugin registers at interpreter start (sitecustomize) and
  ``jax.backends()`` initializes *every* registered plugin regardless of
  ``JAX_PLATFORMS`` — if the TPU tunnel is down, that init hangs forever.
  Deregistering the factory before the first backend lookup keeps tests
  hermetic and CPU-only.
"""

import pytest

from cruise_control_tpu.utils.hermetic import force_cpu

force_cpu(n_devices=8)
# NOTE: do NOT enable the persistent XLA compilation cache here.  On this
# box XLA:CPU detects different machine features across processes and a
# cross-process cache entry can SIGILL/segfault the loader (bench.py carries
# the same warning); a round-4 attempt segfaulted the suite mid-run twice.


@pytest.fixture(autouse=True, scope="module")
def _reset_compile_service():
    """A module that installs a configured CompileService (main.build_app,
    compilesvc tests) must not leak it — warmup/chunking flags would bleed
    into unrelated modules' facade and optimizer runs."""
    yield
    from cruise_control_tpu.compilesvc import set_compile_service

    set_compile_service(None)


@pytest.fixture(autouse=True, scope="module")
def _bound_resident_xla_executables():
    """XLA:CPU segfaults inside ``backend_compile_and_load`` once a single
    process accumulates enough compiled executables (reproduced twice at the
    ~500th in-suite compile, test #173 of 181; the same test passes in any
    smaller run).  Dropping the compilation caches at module boundaries keeps
    the resident-executable count bounded; modules pay a recompile for shapes
    they share with an earlier module, which is cheaper than a segfault."""
    yield
    import jax

    jax.clear_caches()
