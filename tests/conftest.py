"""Test bootstrap: run JAX on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is unavailable in CI; sharding correctness is validated
on host-platform virtual devices instead.  Must run before the first jax import.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
