"""Device-memory & executable-cost observatory tests (docs/MEMORY.md):
ledger post/reconcile accounting, resident pin/donation bookkeeping, the
compile-cost ledger in lowered/full modes, the dispatch headroom guard
(shrink + refusal tagging), the /memory endpoint, ledger-on/off cache-key
identity, and the bench_gate regression gate."""

import importlib.util
import json
import os
import subprocess
import sys
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from cruise_control_tpu.obsvc.memory import (
    SUBSYS_LANES,
    SUBSYS_RESIDENT,
    DeviceMemoryLedger,
    ExecutableCostLedger,
    measure_bytes,
    memory_ledger,
    set_memory_ledger,
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def ledger():
    """A scenario-private enabled ledger swapped into the singleton seam,
    restored afterwards (counters are process-registry sensors — tests diff
    them, they never assume zero)."""
    prev = memory_ledger()
    led = DeviceMemoryLedger()
    led.configure(enabled=True, analysis_mode="off")
    set_memory_ledger(led)
    yield led
    set_memory_ledger(prev)


# ---------------------------------------------------------------- ledger


def test_measure_bytes_counts_array_leaves():
    tree = {"a": np.zeros((4, 8), np.float32), "b": [np.zeros(3, np.int32)],
            "c": "not-an-array", "d": None}
    assert measure_bytes(tree) == 4 * 8 * 4 + 3 * 4
    assert measure_bytes(None) == 0
    assert measure_bytes({}) == 0


def test_ledger_post_balance_clamp_and_events(ledger):
    imb0 = ledger.imbalance_count
    ledger.post(SUBSYS_LANES, 1000, kind="alloc")
    ledger.post(SUBSYS_RESIDENT, 500, kind="alloc")
    assert ledger.live_bytes() == 1500
    assert ledger.live_bytes(SUBSYS_LANES) == 1000
    ledger.post(SUBSYS_LANES, 1000, kind="free")
    assert ledger.live_bytes(SUBSYS_LANES) == 0
    # Donation: counted, never summed.
    ledger.post(SUBSYS_RESIDENT, 500, kind="donate")
    assert ledger.live_bytes(SUBSYS_RESIDENT) == 500
    # Pin/release refcounts.
    ledger.post(SUBSYS_RESIDENT, 0, kind="pin")
    assert ledger.pins(SUBSYS_RESIDENT) == 1
    ledger.post(SUBSYS_RESIDENT, 0, kind="release")
    assert ledger.pins(SUBSYS_RESIDENT) == 0
    assert ledger.imbalance_count == imb0
    # Over-free clamps at zero and bumps the imbalance counter instead of
    # going negative; a release without a pin does the same.
    ledger.post(SUBSYS_RESIDENT, 10_000, kind="free")
    assert ledger.live_bytes(SUBSYS_RESIDENT) == 0
    ledger.post(SUBSYS_RESIDENT, 0, kind="release")
    assert ledger.imbalance_count == imb0 + 2
    ev = ledger.events()
    assert ev["alloc"] == 2 and ev["free"] == 2 and ev["donate"] == 1
    snap = ledger.snapshot()
    assert snap["enabled"] is True
    assert snap["liveBytes"] == 0
    assert snap["subsystems"][SUBSYS_RESIDENT]["peakBytes"] >= 500
    json.dumps(snap)                          # endpoint body is serializable


def test_ledger_disabled_is_noop():
    led = DeviceMemoryLedger()                # module default: disabled
    assert led.enabled is False
    led.post(SUBSYS_LANES, 1000, kind="alloc")
    assert led.live_bytes() == 0
    assert led.events() == {}
    plan, refused = led.guard_lane_plan([], 0, "R64-C64", (1, 2, 4))
    assert plan == [] and refused is False


def test_verify_balanced_flags_undrained_state(ledger):
    assert ledger.verify_balanced() == []
    ledger.post(SUBSYS_RESIDENT, 0, kind="pin")
    problems = ledger.verify_balanced()
    assert any("pin" in p for p in problems)
    ledger.post(SUBSYS_RESIDENT, 0, kind="release")
    assert ledger.verify_balanced() == []


def test_reconcile_without_backend_stats_is_none_drift(ledger):
    rec = ledger.reconcile()
    assert rec["trackedBytes"] == 0
    # XLA:CPU exposes no memory_stats; driftBytes is None, not 0-as-fact.
    if rec["backend"] is None:
        assert rec["driftBytes"] is None


# ------------------------------------------------------------ cost ledger


def _jit_add():
    import jax

    @jax.jit
    def add(a, b):
        return a + b

    return add


def test_cost_ledger_lowered_mode_rows_and_dispatch_cache_untouched():
    import jax

    costs = ExecutableCostLedger()
    add = _jit_add()
    a = np.zeros((8, 4), np.float32)
    out = add(a, a)
    jax.block_until_ready(out)
    cache0 = add._cache_size()
    costs.observe_compile("R8-C4", add, (a, a), {}, mode="lowered")
    row = costs.row("R8-C4")
    assert row is not None and row["mode"] == "lowered"
    assert row["count"] == 1
    assert row["flops"] > 0
    assert row["bytes_accessed"] > 0
    assert row["arg_bytes"] == 2 * a.nbytes
    assert row["out_bytes"] == a.nbytes
    assert row["peak_bytes"] == row["arg_bytes"] + row["out_bytes"]
    # The analysis re-lowers on abstract avals: jit's dispatch cache must
    # hold exactly what it held before (bitwise-identical executables).
    assert add._cache_size() == cache0
    # A repeat observation of the same label only bumps the count.
    costs.observe_compile("R8-C4", add, (a, a), {}, mode="lowered")
    assert costs.row("R8-C4")["count"] == 2
    json.dumps(costs.rows())


def test_cost_ledger_full_mode_defers_compile_to_finalize():
    costs = ExecutableCostLedger()
    add = _jit_add()
    a = np.zeros((16,), np.float32)
    costs.observe_compile("R16-C1", add, (a, a), {}, mode="full")
    row = costs.row("R16-C1")
    assert row["pending"] is True
    assert row["temp_bytes"] is None
    assert "_lowered" not in row              # private stash never exposed
    json.dumps(costs.rows())
    assert costs.finalize_full() == 1
    row = costs.row("R16-C1")
    assert row["pending"] is False
    assert row["temp_bytes"] is not None
    assert row["generated_code_bytes"] is not None
    assert row["peak_bytes"] >= row["arg_bytes"] + row["out_bytes"]
    assert costs.finalize_full() == 0         # nothing left pending
    m = costs.maxima()
    assert m["peak_bytes"] == row["peak_bytes"]


def test_cost_ledger_analysis_failure_is_swallowed():
    costs = ExecutableCostLedger()
    costs.observe_compile("bad", object(), (), {}, mode="lowered")
    assert costs.row("bad") is None           # no row, no exception


def test_peak_for_lanes_exact_and_rescaled():
    costs = ExecutableCostLedger()
    costs.ingest("R64-C64-L4", {"peak_bytes": 400})
    assert costs.peak_for_lanes("R64-C64", 4) == 400
    # No exact row: linear rescale from the nearest recorded width.
    assert costs.peak_for_lanes("R64-C64", 8) == 800
    assert costs.peak_for_lanes("R64-C64", 2) == 200
    # No family data at all: no projection, guard has no basis.
    assert costs.peak_for_lanes("R128-C64", 8) is None


# --------------------------------------------------------- headroom guard


def test_guard_shrinks_then_refuses(ledger):
    from cruise_control_tpu.compilesvc.chunking import plan_lane_chunks

    ladder = (1, 2, 4, 8)
    ledger.configure(enabled=True, headroom_fraction=0.5, budget_bytes=1000,
                     analysis_mode="off")       # limit = 500 bytes
    ledger.costs.ingest("R64-C64-L1", {"peak_bytes": 200})
    plan = plan_lane_chunks(8, ladder)          # one 8-wide chunk
    # Width 8 projects 1600 > 500; width 2 projects 400 <= 500 — shrink.
    shrunk, refused = ledger.guard_lane_plan(plan, 8, "R64-C64", ladder)
    assert refused is False
    assert max(c.size for c in shrunk) == 2
    assert sum(c.n_real for c in shrunk) == 8
    # Even width 1 (200 bytes) over a 100-byte limit: refuse outright.
    ledger.configure(enabled=True, headroom_fraction=0.1, budget_bytes=1000,
                     analysis_mode="off")
    _, refused = ledger.guard_lane_plan(plan, 8, "R64-C64", ladder)
    assert refused is True
    # No recorded projection for the family: pass through untouched.
    out, refused = ledger.guard_lane_plan(plan, 8, "R999-C64", ladder)
    assert out is plan and refused is False


def test_batch_refusal_degrades_without_crash(ledger):
    """A refused what-if dispatch returns a degraded-tagged result — seed
    placements, stranded -1, memory_refused — never an allocator crash."""
    from cruise_control_tpu.analyzer import GoalOptimizer
    from cruise_control_tpu.testing import random_cluster as rc

    props = rc.ClusterProperties(num_brokers=8, num_racks=4, num_topics=12,
                                 num_replicas=256, seed=11)
    state, placement, meta = rc.generate(props)
    r_pad = state.num_replicas_padded
    c = min(64, r_pad)
    ledger.configure(enabled=True, headroom_fraction=0.5, budget_bytes=1000,
                     analysis_mode="off")
    # Every lane width of this family projects far over the 500-byte limit.
    ledger.costs.ingest(f"R{r_pad}-C{c}-L1", {"peak_bytes": 10 ** 9})
    opt = GoalOptimizer(goal_names=["ReplicaDistributionGoal"])
    res = opt.batch_remove_scenarios(state, placement, meta,
                                     [[0], [1], [2]], num_candidates=64)
    assert res.memory_refused is True
    assert res.preempted is True
    assert res.goal_names == []
    assert (np.asarray(res.stranded_after) == -1).all()
    assert res.num_scenarios == 3
    # Lanes carry the untouched seed placement.
    for s in range(3):
        np.testing.assert_array_equal(
            np.asarray(res.placement_for(s).broker),
            np.asarray(placement.broker))
    snap = ledger.snapshot()
    assert snap["guard"]["refusals"] >= 1


# ------------------------------------------------- resident-model posting


def test_resident_lifecycle_posts_balance(ledger):
    """Pinned freeze allocs, delta-apply donates (net zero), invalidate
    frees back to zero — the fuzz invariant's accounting, unit-sized."""
    from cruise_control_tpu.model.builder import builder_from_snapshot
    from cruise_control_tpu.model.resident import ResidentModelService
    from cruise_control_tpu.testing import random_cluster as rc

    props = rc.ClusterProperties(num_brokers=6, num_racks=3, num_topics=8,
                                 num_replicas=96, seed=7)
    state, placement, meta = rc.generate(props, pad_replicas_to=128,
                                         pad_brokers_to=8)
    imb0 = ledger.imbalance_count
    svc = ResidentModelService(enabled=True)
    cm = builder_from_snapshot(state, placement, meta)
    svc.snapshot(cm, lambda r, b: (128, 8), pin=True)
    frozen = ledger.live_bytes(SUBSYS_RESIDENT)
    assert frozen > 0
    assert ledger.pins(SUBSYS_RESIDENT) == 1
    svc.release()
    assert ledger.pins(SUBSYS_RESIDENT) == 0
    # Journalled edit → delta (donation) path: bytes must not move.
    (t, p), _ = next(iter(cm.partitions().items()))
    rs = cm.partition(t, p)
    cm.set_replica_load(t, p, rs[0].broker_id,
                        np.full(4, 7.0, dtype=np.float64))
    svc.snapshot(cm, lambda r, b: (128, 8))
    assert ledger.live_bytes(SUBSYS_RESIDENT) == frozen
    assert ledger.events().get("donate", 0) >= 1
    svc.invalidate("test_resident_lifecycle_posts_balance")
    assert ledger.live_bytes() == 0
    ev = ledger.events()
    assert ev["alloc"] == ev["free"]
    assert ev["pin"] == ev["release"]
    assert ledger.imbalance_count == imb0
    assert ledger.verify_balanced() == []


# --------------------------------------- cache-key identity (ledger on/off)


def test_ledger_on_off_cache_keys_identical():
    """Acceptance: the ledger is strictly host-side — a build with
    memory.enabled=true compiles exactly the executables (same jit cache
    keys) as a ledger-free build, and observing compiles adds no dispatch
    cache entries (PR-9 style assertion)."""
    from cruise_control_tpu.analyzer import GoalOptimizer
    from cruise_control_tpu.analyzer import solver as solver_mod
    from cruise_control_tpu.testing import deterministic as det

    state, placement, meta = det.unbalanced().freeze(pad_replicas_to=64,
                                                     pad_brokers_to=8)

    def run(enabled):
        prev = memory_ledger()
        led = DeviceMemoryLedger()
        led.configure(enabled=enabled, analysis_mode="lowered")
        set_memory_ledger(led)
        try:
            opt = GoalOptimizer(goal_names=["ReplicaDistributionGoal"],
                                solver=solver_mod.GoalSolver())
            opt.optimizations(state, placement, meta)
            keys = {k for k in opt.solver._round_cache
                    if isinstance(k, tuple) and k and k[0] == "solve"}
            return keys, led
        finally:
            set_memory_ledger(prev)

    keys_off, led_off = run(False)
    keys_on, led_on = run(True)
    assert keys_off == keys_on
    assert led_off.costs.rows() == {}          # disabled: no analysis at all
    rows = led_on.costs.rows()                 # enabled: rows observed,
    assert rows                                # keyed by bucket labels
    assert all(label.startswith("R64-") for label in rows)


# ----------------------------------------------------------------- /memory


def _get(base, path):
    try:
        with urllib.request.urlopen(base + path) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def _boot(extra_cfg):
    from cruise_control_tpu.config.cruise_control_config import (
        CruiseControlConfig)
    from cruise_control_tpu.main import build_app

    cfg = CruiseControlConfig({"metric.sampling.interval.ms": 300,
                               "partition.metrics.window.ms": 600,
                               **extra_cfg})
    app = build_app(cfg, port=0)
    app.cc.start_up()
    app.start()
    return app


def _shutdown(app):
    app.stop()
    app.cc.shutdown()
    memory_ledger().reset()
    memory_ledger().configure(enabled=False)


def test_memory_endpoint_end_to_end():
    """GET /memory serves the ledger snapshot on a default boot
    (memory.enabled=true), memoryState rides /state, and Memory.* rings are
    queryable through the glob + limit parameters of /metrics/history."""
    app = _boot({"obs.history.interval.ms": 200})
    try:
        base = f"http://127.0.0.1:{app.port}/kafkacruisecontrol"
        status, body = _get(base, "/memory")
        assert status == 200, body
        snap = json.loads(body)
        assert snap["enabled"] is True
        assert snap["analysisMode"] == "lowered"
        assert SUBSYS_RESIDENT in snap["subsystems"]
        assert isinstance(snap["costs"], dict)
        assert "driftBytes" in snap["reconcile"]

        status, body = _get(base, "/state")
        assert status == 200
        mem_state = json.loads(body)["AnalyzerState"]["memoryState"]
        assert mem_state["enabled"] is True
        assert "costs" not in mem_state
        assert "costRows" in mem_state

        # Memory.* gauges ride the history rings once the sampler ticks.
        deadline = time.time() + 15
        hist = {}
        while time.time() < deadline:
            _, body = _get(base, "/metrics/history?sensor=Memory.*")
            hist = json.loads(body)
            if hist.get("series"):
                break
            time.sleep(0.3)
        assert any(k.startswith("Memory.") for k in hist["series"]), hist
        assert hist["truncated"] is False

        # limit bounds the series count and flags the truncation.
        _, body = _get(base, "/metrics/history?limit=1")
        bounded = json.loads(body)
        assert len(bounded["series"]) <= 1
        assert bounded["truncated"] is True
        status, _ = _get(base, "/metrics/history?limit=nope")
        assert status == 400
        status, _ = _get(base, "/metrics/history?limit=0")
        assert status == 400
    finally:
        _shutdown(app)


def test_memory_endpoint_404_when_disabled():
    app = _boot({"memory.enabled": False})
    try:
        base = f"http://127.0.0.1:{app.port}/kafkacruisecontrol"
        status, body = _get(base, "/memory")
        assert status == 404
        assert "memory.enabled" in json.loads(body)["error"]
        # The rest of the surface is unaffected.
        status, _ = _get(base, "/state")
        assert status == 200
    finally:
        _shutdown(app)


# -------------------------------------------------------------- bench gate


def _bench_gate_module():
    spec = importlib.util.spec_from_file_location(
        "bench_gate", os.path.join(_REPO, "scripts", "bench_gate.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _wrapper_doc(rows, truncate_first=False):
    lines = [json.dumps(r) for r in rows]
    if truncate_first and lines:
        lines[0] = lines[0][len(lines[0]) // 2:]   # cut mid-object
    return {"n": 5, "cmd": "python bench.py", "rc": 0,
            "tail": "\n".join(lines)}


_ROWS = [
    {"metric": "solve_small", "value": 0.5, "unit": "seconds",
     "peak_bytes": 1 << 30},
    {"metric": "solve_big", "value": 8.0, "unit": "seconds",
     "peak_bytes": 4 << 30, "temp_bytes": 1 << 30},
]


def test_bench_gate_parses_wrapper_and_truncated_tail(tmp_path):
    gate = _bench_gate_module()
    path = tmp_path / "bench.json"
    path.write_text(json.dumps(_wrapper_doc(_ROWS, truncate_first=True)))
    metrics = gate.load_bench(str(path))
    # The cut first row is skipped, the intact one extracts fully.
    assert "bench:solve_small:value" not in metrics
    assert metrics["bench:solve_big:value"] == 8.0
    assert metrics["bench:solve_big:peak_bytes"] == float(4 << 30)
    # Duplicate metrics: the LATEST row wins.
    dup = _ROWS + [{"metric": "solve_big", "value": 9.5, "unit": "seconds"}]
    path.write_text(json.dumps(_wrapper_doc(dup)))
    assert gate.load_bench(str(path))["bench:solve_big:value"] == 9.5
    # A plain JSON list of rows parses too.
    path.write_text(json.dumps(_ROWS))
    assert gate.load_bench(str(path))["bench:solve_small:value"] == 0.5


def test_bench_gate_pass_and_injected_regression(tmp_path):
    gate = _bench_gate_module()
    baseline = tmp_path / "base.json"
    baseline.write_text(json.dumps(_wrapper_doc(_ROWS)))
    profile = tmp_path / "profile.json"
    profile.write_text(json.dumps({"backend": "cpu", "size": "small",
                                   "passes": {"steady": {
                                       "total_s": 10.0,
                                       "goals": [{"goal": "G", "ms": 800.0,
                                                  "rounds": 3}]}}}))
    args = ["--bench-baseline", str(baseline),
            "--profile-baseline", str(profile)]
    # Self-diff: identical snapshots pass.
    assert gate.main(args) == 0
    # Injected 2x regression on a big metric fails the gate.
    bad_rows = [dict(r) for r in _ROWS]
    bad_rows[1]["value"] *= 2
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps(_wrapper_doc(bad_rows)))
    assert gate.main(args + ["--bench", str(bad)]) == 1
    # A 2x profile regression (per-goal ms and total_s) fails too.
    bad_profile = tmp_path / "bad_profile.json"
    bad_profile.write_text(json.dumps({"backend": "cpu", "size": "small",
                                       "passes": {"steady": {
                                           "total_s": 20.0,
                                           "goals": [{"goal": "G",
                                                      "ms": 1600.0,
                                                      "rounds": 3}]}}}))
    assert gate.main(args + ["--profile", str(bad_profile)]) == 1
    # New columns absent from the baseline (peak_bytes against an old
    # snapshot) are not gated — only shared metrics compare.
    old_rows = [{k: v for k, v in r.items()
                 if k not in ("peak_bytes", "temp_bytes")} for r in _ROWS]
    old = tmp_path / "old.json"
    old.write_text(json.dumps(_wrapper_doc(old_rows)))
    assert gate.main(["--bench-baseline", str(old), "--bench", str(baseline),
                      "--profile-baseline", str(profile)]) == 0
    # Unreadable snapshot: distinct exit code, not a crash or a pass.
    empty = tmp_path / "empty.json"
    empty.write_text("{}")
    assert gate.main(args + ["--bench", str(empty)]) == 2


@pytest.mark.slow
def test_bench_gate_committed_snapshots_self_diff():
    """CI wiring: the gate run with no arguments diffs the committed r05
    snapshots against themselves and exits 0."""
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "scripts", "bench_gate.py")],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "OK" in proc.stdout


# --------------------------------------------------- history sibling rings


def test_history_timer_sibling_rings_and_bounded(monkeypatch):
    import importlib

    from cruise_control_tpu.common.metrics import MetricRegistry
    from cruise_control_tpu.obsvc.history import HistoryRecorder

    history_mod = importlib.import_module("cruise_control_tpu.obsvc.history")
    reg = MetricRegistry()
    monkeypatch.setattr(history_mod, "registry", lambda: reg)
    t = reg.timer("MemTest.timer")
    for ms in (10.0, 20.0, 90.0):
        t.update_ms(ms)
    rec = HistoryRecorder(interval_s=3600.0, ring_size=8,
                          clock=lambda: 1000.0)
    rec.sample_once()
    # The bare ring stays p99 (SLO windows read it unchanged); the sibling
    # rings carry p50/max under dotted names.
    stats = t.stats()
    assert rec.series("MemTest.timer")[-1][1] == stats["p99_ms"]
    assert rec.series("MemTest.timer.p50_ms")[-1][1] == stats["p50_ms"]
    assert rec.series("MemTest.timer.max_ms")[-1][1] == stats["max_ms"]
    # Sibling rings are plain 2-tuple rings, SLO-burn compatible.
    for name in ("MemTest.timer.p50_ms", "MemTest.timer.max_ms"):
        for point in rec.series(name):
            assert len(point) == 2
    # history_bounded: name-sorted cap + truncation flag.
    out, truncated = rec.history_bounded(pattern="MemTest.*", limit=2)
    assert truncated is True and len(out) == 2
    assert list(out) == sorted(out)
    out, truncated = rec.history_bounded(pattern="MemTest.*", limit=50)
    assert truncated is False and len(out) == 3
