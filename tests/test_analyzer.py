"""Analyzer tests on deterministic fixtures.

Models the reference's ``analyzer/DeterministicClusterTest.java`` (goal lists
run over hand-built models, outcomes asserted) with
``OptimizationVerifier``-style postcondition checks
(``testing/verifier.py``).
"""

import numpy as np
import pytest

from cruise_control_tpu.analyzer import BalancingConstraint, GoalOptimizer, OptimizationOptions
from cruise_control_tpu.analyzer.context import build_context, compute_aggregates
from cruise_control_tpu.analyzer.goals.registry import goal_by_name
from cruise_control_tpu.common.exceptions import OptimizationFailureError
from cruise_control_tpu.common.resources import Resource
from cruise_control_tpu.model import ops
from cruise_control_tpu.testing import deterministic as det
from cruise_control_tpu.testing.verifier import execute_goals_for

PAD_R, PAD_B = 64, 8

HARD_GOALS = [
    "RackAwareGoal", "ReplicaCapacityGoal", "DiskCapacityGoal",
    "NetworkInboundCapacityGoal", "NetworkOutboundCapacityGoal", "CpuCapacityGoal",
]


def freeze(cm):
    return cm.freeze(pad_replicas_to=PAD_R, pad_brokers_to=PAD_B)


def test_unbalanced_capacity_fixed():
    """unbalanced(): both 1-replica partitions sit on broker 0 at half-capacity
    load each; capacity goals must split them."""
    state, placement, meta = freeze(det.unbalanced())
    report = execute_goals_for(state, placement, meta, HARD_GOALS)
    assert report.ok, report.failures
    final = report.result.final_placement
    bl = np.asarray(ops.broker_load(state, final))
    # No broker above capacity threshold for any resource.
    cap = np.asarray(state.capacity)
    thresh = BalancingConstraint().capacity_threshold
    alive = np.asarray(state.alive & state.broker_valid)
    assert (bl[alive] <= cap[alive] * thresh + 1e-3).all()
    assert len(report.result.proposals) >= 1


def test_rack_aware_satisfiable():
    """Two replicas on the same rack get separated."""
    state, placement, meta = freeze(det.rack_aware_satisfiable())
    report = execute_goals_for(state, placement, meta, ["RackAwareGoal"])
    assert report.ok, report.failures
    final = report.result.final_placement
    rack = np.asarray(state.rack)
    brokers = np.asarray(final.broker)[:meta.num_replicas]
    assert rack[brokers[0]] != rack[brokers[1]]


def test_rack_aware_already_satisfied_no_moves():
    state, placement, meta = freeze(det.rack_aware_satisfiable2())
    report = execute_goals_for(state, placement, meta, ["RackAwareGoal"])
    assert report.ok
    assert len(report.result.proposals) == 0


def test_rack_aware_unsatisfiable_raises():
    """3 replicas, 2 racks — strict rack-awareness must fail."""
    state, placement, meta = freeze(det.rack_aware_unsatisfiable())
    with pytest.raises(OptimizationFailureError):
        execute_goals_for(state, placement, meta, ["RackAwareGoal"])


def test_rack_aware_distribution_allows_pigeonhole():
    """The relaxed goal accepts 3 replicas / 2 racks as long as the spread is
    even (2+1), like RackAwareDistributionGoal.java."""
    state, placement, meta = freeze(det.rack_aware_unsatisfiable())
    report = execute_goals_for(state, placement, meta, ["RackAwareDistributionGoal"])
    assert report.ok, report.failures


def test_dead_broker_replicas_move():
    """Killing a broker strands replicas; hard goals must relocate them all
    (4 brokers / 2 racks so a rack-aware destination exists)."""
    cm = det.homogeneous_cluster(det.RACK_BY_BROKER3)
    cm.create_replica(det.T1, 0, broker_id=0, index=0, is_leader=True)
    cm.create_replica(det.T1, 0, broker_id=1, index=1, is_leader=False)
    cm.set_replica_load(det.T1, 0, 0, det.load(40.0, 100.0, 130.0, 75.0))
    cm.set_replica_load(det.T1, 0, 1, det.load(5.0, 100.0, 0.0, 75.0))
    cm.set_broker_state(1, alive=False)
    state, placement, meta = freeze(cm)
    report = execute_goals_for(
        state, placement, meta, HARD_GOALS,
        verifications=("GOAL_VIOLATION", "DEAD_BROKERS", "REGRESSION"))
    assert report.ok, report.failures
    final = report.result.final_placement
    alive = np.asarray(state.alive)
    valid = np.asarray(state.valid)
    assert alive[np.asarray(final.broker)[valid]].all()


def test_replica_distribution_balances_counts():
    """unbalanced2(): 6 single-replica partitions, 5 on broker 0."""
    state, placement, meta = freeze(det.unbalanced2())
    report = execute_goals_for(state, placement, meta, ["ReplicaDistributionGoal"])
    assert report.ok, report.failures
    final = report.result.final_placement
    counts = np.asarray(ops.replica_counts(state, final))[:meta.num_brokers]
    assert counts.max() - counts.min() <= 2
    assert counts.max() <= 3


def test_preferred_leader_election():
    """unbalanced3(): leaders at replica-list position 1 move to position 0."""
    state, placement, meta = freeze(det.unbalanced3())
    report = execute_goals_for(state, placement, meta, ["PreferredLeaderElectionGoal"],
                               verifications=())
    final = report.result.final_placement
    pos = np.asarray(state.pos)
    lead = np.asarray(final.is_leader)
    valid = np.asarray(state.valid)
    assert (pos[lead & valid] == 0).all()
    # Both partitions changed leadership → leadership-only proposals.
    assert len(report.result.proposals) == 2
    for p in report.result.proposals:
        assert p.has_leader_action and not p.has_replica_action


def test_excluded_topics_stay_put():
    state, placement, meta = freeze(det.unbalanced())
    opts = OptimizationOptions(excluded_topics=frozenset({"T1", "T2"}))
    optimizer = GoalOptimizer(goal_names=["ReplicaDistributionGoal"])
    res = optimizer.optimizations(state, placement, meta, options=opts)
    assert len(res.proposals) == 0


def test_excluded_brokers_for_replica_move():
    state, placement, meta = freeze(det.unbalanced())
    opts = OptimizationOptions(excluded_brokers_for_replica_move=frozenset({1, 2}))
    optimizer = GoalOptimizer(goal_names=["ReplicaDistributionGoal"])
    res = optimizer.optimizations(state, placement, meta, options=opts)
    # Both other brokers excluded → nothing can move.
    assert len(res.proposals) == 0


def test_requested_destination_brokers():
    state, placement, meta = freeze(det.unbalanced())
    opts = OptimizationOptions(requested_destination_broker_ids=frozenset({2}))
    optimizer = GoalOptimizer(goal_names=["ReplicaDistributionGoal"])
    res = optimizer.optimizations(state, placement, meta, options=opts)
    for p in res.proposals:
        added = {r.broker_id for r in p.replicas_to_add}
        assert added <= {2}


def test_proposals_apply_back_to_model():
    """Diff → proposals → builder apply_placement round-trip stays consistent."""
    cm = det.unbalanced2()
    goals = ["RackAwareGoal", "ReplicaCapacityGoal", "ReplicaDistributionGoal"]
    state, placement, meta = freeze(cm)
    report = execute_goals_for(state, placement, meta, goals)
    assert report.ok, report.failures
    cm.apply_placement(report.result.final_placement, meta)
    state2, placement2, meta2 = freeze(cm)
    # Re-running the same goals on the optimized model produces no proposals.
    report2 = execute_goals_for(state2, placement2, meta2, goals)
    assert report2.ok
    assert len(report2.result.proposals) == 0


def test_unbalanced2_capacity_infeasible():
    """unbalanced2 carries 6 half-capacity replicas over 3 brokers — more disk
    than the 0.8 capacity threshold can host; the hard goal must fail loudly."""
    state, placement, meta = freeze(det.unbalanced2())
    with pytest.raises(OptimizationFailureError):
        execute_goals_for(state, placement, meta, ["DiskCapacityGoal"])


def test_balancedness_score_improves():
    state, placement, meta = freeze(det.unbalanced())
    optimizer = GoalOptimizer()
    res = optimizer.optimizations(state, placement, meta)
    assert 0.0 <= res.balancedness_score <= 100.0
    assert len(res.violated_goals_after) <= len(res.violated_goals_before)


def test_proposal_cache_by_generation():
    state, placement, meta = freeze(det.unbalanced())
    optimizer = GoalOptimizer(goal_names=["ReplicaDistributionGoal"])
    r1 = optimizer.optimizations(state, placement, meta, model_generation=7)
    r2 = optimizer.optimizations(state, placement, meta, model_generation=7)
    assert r1 is r2
    r3 = optimizer.optimizations(state, placement, meta, model_generation=8)
    assert r3 is not r1


def test_incremental_aggregates_match_recompute():
    """apply_replica_move / apply_leadership_move scatter updates must agree
    with a full compute_aggregates recompute (solver-carry drift check)."""
    import jax.tree_util as jtu

    from cruise_control_tpu.analyzer.context import (
        apply_leadership_move,
        apply_replica_move,
    )

    state, placement, meta = freeze(det.unbalanced_with_a_follower())
    gctx = build_context(state, placement, meta, BalancingConstraint(),
                         OptimizationOptions())
    agg = compute_aggregates(gctx, placement)
    # Move replica 0 (leader of T1-0 on broker 0) to broker 1, disk 0.
    placement2, agg2 = apply_replica_move(gctx, placement, agg, 0, 1, 0)
    fresh = compute_aggregates(gctx, placement2)
    for got, want in zip(jtu.tree_leaves(agg2), jtu.tree_leaves(fresh)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-3)
    # Promote the follower of T1-0 (now the only other replica of p0).
    follower = int(np.nonzero(
        (np.asarray(state.partition) == 0) & ~np.asarray(placement2.is_leader)
        & np.asarray(state.valid))[0][0])
    placement3, agg3 = apply_leadership_move(gctx, placement2, agg2, follower)
    fresh3 = compute_aggregates(gctx, placement3)
    for got, want in zip(jtu.tree_leaves(agg3), jtu.tree_leaves(fresh3)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-3)


def test_intra_broker_disk_balance():
    """unbalanced4(): JBOD brokers with skewed logdirs; the intra-broker goals
    move replicas between disks of the same broker only."""
    state, placement, meta = freeze(det.unbalanced4())
    constraint = BalancingConstraint()
    constraint.capacity_threshold = np.array([0.7, 0.8, 0.8, 0.95], dtype=np.float32)
    report = execute_goals_for(
        state, placement, meta,
        ["IntraBrokerDiskCapacityGoal", "IntraBrokerDiskUsageDistributionGoal"],
        constraint=constraint,
        verifications=("GOAL_VIOLATION",))
    assert report.ok, report.failures
    final = report.result.final_placement
    # Broker assignment untouched; only disks may change.
    assert (np.asarray(final.broker) == np.asarray(placement.broker)).all()


def test_swap_balances_low_headroom_cluster():
    """swap_only_balanceable(): no single move fits the band; only a swap
    (reference's third mechanism, ResourceDistributionGoal.java:543-725)
    balances NW_IN.  Replica counts per broker must not change."""
    state, placement, meta = freeze(det.swap_only_balanceable())
    report = execute_goals_for(state, placement, meta,
                               ["NetworkInboundUsageDistributionGoal"],
                               verifications=("GOAL_VIOLATION",))
    assert report.ok, report.failures
    final = report.result.final_placement
    bl = np.asarray(ops.broker_load(state, final))
    nw = bl[:2, Resource.NW_IN]
    cap = np.asarray(state.capacity)[:2, Resource.NW_IN]
    avg = nw.sum() / cap.sum()
    upper = avg * 1.1 * cap
    lower = avg * (2 - 1.1) * cap
    assert (nw <= upper + 1e-4).all() and (nw >= lower - 1e-4).all(), nw
    counts = np.bincount(np.asarray(final.broker)[:meta.num_replicas], minlength=2)
    assert counts[0] == 2 and counts[1] == 2, counts
    moved = (np.asarray(final.broker) != np.asarray(placement.broker))[:meta.num_replicas]
    assert moved.sum() >= 2  # a swap relocates two replicas


def test_batch_remove_scenarios():
    """Vmapped what-if batch: each scenario decommissions a different broker;
    every lane's dead broker must end up empty, and lanes must differ."""
    from cruise_control_tpu.testing import random_cluster as rc
    props = rc.ClusterProperties(num_brokers=8, num_racks=4, num_topics=12,
                                 num_replicas=256, seed=11)
    state, placement, meta = rc.generate(props)
    opt = GoalOptimizer(goal_names=[
        "RackAwareGoal", "ReplicaCapacityGoal", "DiskCapacityGoal",
        "ReplicaDistributionGoal"])
    removal_sets = [[0], [1], [2], [3]]
    res = opt.batch_remove_scenarios(state, placement, meta, removal_sets,
                                     num_candidates=64)
    assert res.num_scenarios == 4
    for s, ids in enumerate(removal_sets):
        assert int(res.stranded_after[s]) == 0, (s, res.stranded_after)
        pl = res.placement_for(s)
        brokers = np.asarray(pl.broker)[np.asarray(state.valid)]
        for bid in ids:
            assert (brokers != bid).all(), f"scenario {s}: broker {bid} not evacuated"
    # Lanes are independent: scenario 0 keeps broker 1 populated.
    pl0 = np.asarray(res.placement_for(0).broker)[np.asarray(state.valid)]
    assert (pl0 == 1).any()


def test_solution_quality_stdev_contract():
    """Solution-quality ratchet on the DeterministicCluster fixtures: the full
    default stack must cut the per-resource utilization CV (stdev/avg) on the
    unbalanced fixtures and never worsen it, and every fixture's post-solve CV
    must stay under a recorded bound (quality, not just violation counts —
    reference ClusterModelStatsComparator semantics, Goal.java:137-156)."""
    from cruise_control_tpu.analyzer.goals.registry import DEFAULT_GOALS
    from cruise_control_tpu.model.stats import compute_stats

    # Recorded post-optimization CV upper bounds per fixture (ratchet: tighten
    # when the solver improves; never loosen without a quality argument).
    # (unbalanced2/3/5 are capacity-infeasible by construction with default
    # thresholds and cannot run the full default stack.)  Per-resource CV
    # bounds (cpu, nw_in, nw_out, disk); nw_out on the follower fixture stays
    # concentrated because the promoted follower carries zero nw_out load.
    bounds = {"unbalanced": [0.75, 0.75, 0.75, 0.75],
              "unbalanced_with_a_follower": [0.80, 0.05, 1.42, 0.05]}
    fixtures = {"unbalanced": det.unbalanced,
                "unbalanced_with_a_follower": det.unbalanced_with_a_follower}
    for name, fx in fixtures.items():
        state, placement, meta = freeze(fx())
        report = execute_goals_for(state, placement, meta, list(DEFAULT_GOALS))
        assert report.ok, (name, report.failures)
        before = report.result.stats_before
        after = report.result.stats_after
        cv_b, cv_a = before.cv(), after.cv()
        # Never worsen a resource that mattered (avg > 0).
        active = np.asarray(before.avg_util) > 1e-9
        assert (cv_a[active] <= cv_b[active] + 1e-6).all(), (name, cv_b, cv_a)
        assert (cv_a <= np.asarray(bounds[name]) + 1e-6).all(), (name, cv_a)


def test_batch_appliers_match_recompute():
    """The incremental batch appliers (the solver's per-phase path) must stay
    in lockstep with a full compute_aggregates recompute — mixed kept/no-op
    batches, leadership with demotions, and intra-disk sizes included."""
    import jax.numpy as jnp
    import jax.tree_util as jtu
    from cruise_control_tpu.analyzer.constraint import BalancingConstraint
    from cruise_control_tpu.analyzer.context import (
        apply_leadership_moves_batch,
        apply_replica_moves_batch,
        build_context,
        current_leader_of,
    )
    from cruise_control_tpu.analyzer.options import OptimizationOptions
    from cruise_control_tpu.testing import random_cluster as rc

    props = rc.ClusterProperties(num_brokers=8, num_racks=4, num_topics=10,
                                 num_replicas=256, seed=17)
    state, placement, meta = rc.generate(props, pad_replicas_to=256)
    gctx = build_context(state, placement, meta, BalancingConstraint(),
                         OptimizationOptions())
    agg = compute_aggregates(gctx, placement)

    # Mixed batch: rows 0-3 really move, rows 4-7 are no-ops (dst == src).
    valid_rows = np.nonzero(np.asarray(state.valid))[0][:8]
    r = jnp.asarray(valid_rows, dtype=jnp.int32)
    src = placement.broker[r]
    dst = jnp.where(jnp.arange(8) < 4, (src + 1) % 8, src)
    placement2, agg2 = apply_replica_moves_batch(
        gctx, placement, agg, r, dst, placement.disk[r])
    fresh = compute_aggregates(gctx, placement2)
    for got, want in zip(jtu.tree_leaves(agg2), jtu.tree_leaves(fresh)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-3)

    # Leadership batch: promote two followers (their partitions' leaders
    # demote), with one non-kept row contributing nothing.
    lead = np.asarray(current_leader_of(gctx, placement2, gctx.state.partition))
    followers = np.nonzero(~np.asarray(placement2.is_leader)
                           & np.asarray(state.valid) & (lead >= 0))[0]
    parts = np.asarray(state.partition)[followers]
    _, first_idx = np.unique(parts, return_index=True)
    followers = followers[np.sort(first_idx)][:3]
    f = jnp.asarray(followers, dtype=jnp.int32)
    old = jnp.maximum(jnp.asarray(lead[followers], dtype=jnp.int32), 0)
    keep = jnp.asarray([True, True, False])
    dummy = gctx.state.num_replicas_padded
    is_leader = (placement2.is_leader
                 .at[jnp.where(keep, f, dummy)].set(True, mode="drop")
                 .at[jnp.where(keep, old, dummy)].set(False, mode="drop"))
    placement3 = placement2.replace(is_leader=is_leader)
    agg3 = apply_leadership_moves_batch(gctx, placement3, agg2, f, old, keep)
    fresh3 = compute_aggregates(gctx, placement3)
    for got, want in zip(jtu.tree_leaves(agg3), jtu.tree_leaves(fresh3)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-3)


def test_dst_pruned_tiles_match_full_scan_quality():
    """Destination tiling (dst_prune_score + max_dst_candidates) at a broker
    count ABOVE the tile width must still satisfy every goal, including the
    rack goals whose tile is widened past the candidate cap only because the
    dst axis shrank.  The stratified selection guarantees every rack keeps
    slots, so hard rack feasibility must be unaffected; quality must match
    the full-B scan's violation outcome (zero) on the same snapshot."""
    from cruise_control_tpu.analyzer.solver import GoalSolver
    from cruise_control_tpu.testing import random_cluster as rc

    props = rc.ClusterProperties(num_brokers=48, num_racks=6, num_topics=24,
                                 num_replicas=900, mean_cpu=0.004,
                                 mean_disk=80.0, mean_nw_in=80.0,
                                 mean_nw_out=80.0, seed=77)
    state, placement, meta = rc.generate(props)
    goals = ["RackAwareGoal", "ReplicaCapacityGoal", "DiskCapacityGoal",
             "CpuCapacityGoal", "ReplicaDistributionGoal",
             "NetworkInboundUsageDistributionGoal",
             "CpuUsageDistributionGoal", "LeaderReplicaDistributionGoal"]
    pruned = GoalOptimizer(goal_names=goals,
                           solver=GoalSolver(max_dst_candidates=16))
    r_pruned = pruned.optimizations(state, placement, meta)
    assert r_pruned.violated_goals_after == [], r_pruned.violated_goals_after

    full = GoalOptimizer(goal_names=goals,
                         solver=GoalSolver(max_dst_candidates=0))
    r_full = full.optimizations(state, placement, meta)
    assert r_full.violated_goals_after == []
    # The pruned run must not need wildly more work than the full scan.
    rounds_p = sum(g.rounds for g in r_pruned.goal_infos)
    rounds_f = sum(g.rounds for g in r_full.goal_infos)
    assert rounds_p <= 3 * max(rounds_f, 1), (rounds_p, rounds_f)


def test_batch_add_scenarios():
    """Add-broker what-if lanes: candidate brokers are provisioned dead in
    the base snapshot; each lane revives a different subset and the count/
    distribution goals must pull load onto exactly the revived ones."""
    from cruise_control_tpu.testing import random_cluster as rc
    props = rc.ClusterProperties(num_brokers=8, num_racks=4, num_topics=12,
                                 num_replicas=256, seed=13)
    state, placement, meta = rc.generate(props)
    # Provision two candidate brokers as present-but-dead (no replicas).
    alive = np.asarray(state.alive).copy()
    valid = np.asarray(state.broker_valid)
    candidates = [6, 7]
    for b in candidates:
        assert valid[b]
        alive[b] = False
    # Their replicas must move off first so the base snapshot is a cluster
    # of 6 with two empty expansion brokers: re-home via a remove solve.
    opt = GoalOptimizer(goal_names=[
        "RackAwareGoal", "ReplicaCapacityGoal", "ReplicaDistributionGoal"])
    base = opt.batch_remove_scenarios(state, placement, meta,
                                      [candidates], num_candidates=64)
    assert int(base.stranded_after[0]) == 0
    placement0 = base.placement_for(0)
    import jax
    state6 = state.replace(alive=jax.numpy.asarray(alive))

    addition_sets = [[6], [7], [6, 7]]
    res = opt.batch_add_scenarios(state6, placement0, meta, addition_sets,
                                  num_candidates=64)
    assert res.num_scenarios == 3
    for s, ids in enumerate(addition_sets):
        assert int(res.violated_after[s].sum()) == 0, (s, res.violated_after[s])
        brokers = np.asarray(res.placement_for(s).broker)[np.asarray(state.valid)]
        for bid in ids:
            assert (brokers == bid).any(), f"lane {s}: broker {bid} got nothing"
        for bid in set(candidates) - set(ids):
            assert (brokers != bid).all(), \
                f"lane {s}: dead candidate {bid} received replicas"
