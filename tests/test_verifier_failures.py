"""Failure paths of testing/verifier.verify_placement.

Each VerificationFailure check gets a purpose-built broken placement that
makes it — and only the intended checks — fire.  The final test breaks a
placement three ways at once and asserts the verifier names every cause
(accumulation, not first-failure short-circuit), which is the contract the
fuzz harness leans on when classifying a failing scenario.

JAX_PLATFORMS=cpu; shapes are tiny (64 replicas / 8 brokers) so the whole
module compiles in a few seconds.
"""

import dataclasses
from types import SimpleNamespace

import jax.numpy as jnp
import numpy as np
import pytest

from cruise_control_tpu.model import ops
from cruise_control_tpu.testing import random_cluster as rc
from cruise_control_tpu.testing.verifier import VerificationFailure, verify_placement

SMALL = dict(num_brokers=6, num_racks=3, num_topics=5, num_replicas=48,
             min_replication=3, max_replication=3, mean_cpu=0.02,
             num_disks=1, seed=11)
PADS = dict(pad_replicas_to=64, pad_brokers_to=8)


@pytest.fixture(scope="module")
def healthy():
    props = rc.ClusterProperties(**SMALL)
    return rc.generate(props, **PADS)


@pytest.fixture(scope="module")
def with_dead_broker():
    props = rc.ClusterProperties(**SMALL, dead_broker_ids=(1,))
    return rc.generate(props, **PADS)


def _with_broker(placement, broker_arr):
    return dataclasses.replace(placement, broker=jnp.asarray(
        np.asarray(broker_arr, dtype=np.int32)))


def _all_on_broker_zero(state, placement):
    """Every valid replica co-located on broker 0 — maximally rack-unaware,
    yet load-consistent (loads are recomputed from the final placement)."""
    valid = np.asarray(state.valid)
    broker = np.asarray(placement.broker).copy()
    broker[valid] = 0
    return _with_broker(placement, broker)


def _info(name="ReplicaDistributionGoal", rounds=1, before=1.0, after=1.0):
    return SimpleNamespace(goal_name=name, rounds=rounds,
                           metric_before=before, metric_after=after)


class TestIndividualChecks:
    def test_clean_placement_passes_every_check(self, healthy):
        state, placement, meta = healthy
        # The random initial placement is not rack-aware by construction, so
        # build one that is: replica pos k of every partition lands on the
        # first broker of rack k (3 racks, RF=3 -> all racks distinct).
        rack = np.asarray(state.rack)[:6]
        first_in_rack = np.array([int(np.flatnonzero(rack == k)[0])
                                  for k in range(3)])
        broker = np.asarray(placement.broker).copy()
        valid = np.asarray(state.valid)
        broker[valid] = first_in_rack[np.asarray(state.pos)[valid] % 3]
        final = _with_broker(placement, broker)
        failures = verify_placement(
            state, placement, meta, final,
            goal_names=("RackAwareGoal",),
            verifications=("GOAL_VIOLATION", "DEAD_BROKERS", "REGRESSION",
                           "NEW_BROKERS"),
            goal_infos=(_info(before=2.0, after=1.5),))
        assert failures == []

    def test_goal_violation_fires_on_colocated_replicas(self, healthy):
        state, placement, meta = healthy
        final = _all_on_broker_zero(state, placement)
        failures = verify_placement(
            state, placement, meta, final,
            goal_names=("RackAwareGoal",), verifications=("GOAL_VIOLATION",))
        assert [f.check for f in failures] == ["GOAL_VIOLATION"]
        assert "RackAwareGoal" in failures[0].detail
        # VerificationFailure is an AssertionError rendering "[CHECK] detail".
        assert isinstance(failures[0], AssertionError)
        assert str(failures[0]).startswith("[GOAL_VIOLATION]")

    def test_dead_brokers_fires_on_stranded_replicas(self, with_dead_broker):
        state, placement, meta = with_dead_broker
        stranded = int(np.sum(
            (np.asarray(placement.broker) == 1) & np.asarray(state.valid)))
        assert stranded > 0, "generator must leave replicas on the dead broker"
        failures = verify_placement(
            state, placement, meta, placement, verifications=("DEAD_BROKERS",))
        assert [f.check for f in failures] == ["DEAD_BROKERS"]
        assert str(stranded) in failures[0].detail

    def test_dead_brokers_passes_once_evacuated(self, with_dead_broker):
        state, placement, meta = with_dead_broker
        valid = np.asarray(state.valid)
        broker = np.asarray(placement.broker).copy()
        broker[valid & (broker == 1)] = 0   # evacuate the dead broker
        failures = verify_placement(
            state, placement, meta, _with_broker(placement, broker),
            verifications=("DEAD_BROKERS",))
        assert failures == []

    def test_regression_fires_only_on_worsened_rounds(self, healthy):
        state, placement, meta = healthy
        infos = (
            _info("GoalA", rounds=1, before=1.0, after=2.0),   # worsened
            _info("GoalB", rounds=0, before=1.0, after=9.0),   # rounds==0: skip
            _info("GoalC", rounds=3, before=1.0, after=1.0),   # unchanged: ok
        )
        failures = verify_placement(
            state, placement, meta, placement,
            verifications=("REGRESSION",), goal_infos=infos)
        assert [f.check for f in failures] == ["REGRESSION"]
        assert "GoalA" in failures[0].detail and "GoalB" not in failures[0].detail

    def test_new_brokers_fires_on_move_to_old_broker(self, healthy):
        state, placement, meta = healthy
        new_broker = np.zeros(int(np.asarray(state.broker_valid).shape[0]),
                              dtype=bool)
        new_broker[4] = True
        state_nb = dataclasses.replace(state,
                                       new_broker=jnp.asarray(new_broker))
        broker = np.asarray(placement.broker).copy()
        r = int(np.flatnonzero(np.asarray(state.valid) & (broker != 2))[0])
        broker[r] = 2   # healthy replica moved to an OLD broker
        failures = verify_placement(
            state_nb, placement, meta, _with_broker(placement, broker),
            verifications=("NEW_BROKERS",))
        assert [f.check for f in failures] == ["NEW_BROKERS"]

    def test_new_brokers_allows_moves_onto_new_broker(self, healthy):
        state, placement, meta = healthy
        new_broker = np.zeros(int(np.asarray(state.broker_valid).shape[0]),
                              dtype=bool)
        new_broker[4] = True
        state_nb = dataclasses.replace(state,
                                       new_broker=jnp.asarray(new_broker))
        broker = np.asarray(placement.broker).copy()
        r = int(np.flatnonzero(np.asarray(state.valid) & (broker != 4))[0])
        broker[r] = 4   # moving TO the new broker is the sanctioned direction
        failures = verify_placement(
            state_nb, placement, meta, _with_broker(placement, broker),
            verifications=("NEW_BROKERS",))
        assert failures == []

    def test_new_brokers_vacuous_without_new_brokers(self, healthy):
        state, placement, meta = healthy
        broker = np.asarray(placement.broker).copy()
        r = int(np.flatnonzero(np.asarray(state.valid))[0])
        broker[r] = (int(broker[r]) + 1) % 6
        failures = verify_placement(
            state, placement, meta, _with_broker(placement, broker),
            verifications=("NEW_BROKERS",))
        assert failures == []

    def test_load_consistency_always_runs(self, healthy, monkeypatch):
        state, placement, meta = healthy
        real = ops.broker_load
        monkeypatch.setattr(ops, "broker_load",
                            lambda s, p: np.asarray(real(s, p)) + 1.0)
        failures = verify_placement(
            state, placement, meta, placement, verifications=())
        assert [f.check for f in failures] == ["LOAD_CONSISTENCY"]

    def test_empty_verifications_runs_only_load_consistency(self, healthy):
        state, placement, meta = healthy
        # Placement broken for every opt-in check — but with verifications=()
        # only the always-on load invariant runs, and it recomputes from the
        # final placement, so nothing fires.
        final = _all_on_broker_zero(state, placement)
        failures = verify_placement(
            state, placement, meta, final, goal_names=("RackAwareGoal",),
            verifications=(), goal_infos=(_info(after=99.0),))
        assert failures == []


class TestAccumulation:
    def test_multi_way_breakage_reports_every_check(self, with_dead_broker,
                                                    monkeypatch):
        """One placement broken four ways -> four distinct checks reported."""
        state, placement, meta = with_dead_broker
        valid = np.asarray(state.valid)
        broker = np.asarray(placement.broker).copy()
        # Co-locate partition 0's replicas on broker 0 (GOAL_VIOLATION) while
        # leaving the dead broker 1's replicas stranded (DEAD_BROKERS).
        broker[valid & (np.asarray(state.partition) == 0)] = 0
        final = _with_broker(placement, broker)
        real = ops.broker_load
        monkeypatch.setattr(ops, "broker_load",
                            lambda s, p: np.asarray(real(s, p)) + 1.0)
        failures = verify_placement(
            state, placement, meta, final,
            goal_names=("RackAwareGoal",),
            verifications=("GOAL_VIOLATION", "DEAD_BROKERS", "REGRESSION"),
            goal_infos=(_info("GoalA", rounds=1, before=1.0, after=2.0),))
        checks = [f.check for f in failures]
        assert set(checks) == {"GOAL_VIOLATION", "DEAD_BROKERS", "REGRESSION",
                               "LOAD_CONSISTENCY"}
        assert len(checks) == 4, "every violated check reported exactly once"
        assert all(isinstance(f, VerificationFailure) for f in failures)
