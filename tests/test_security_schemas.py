"""Security-provider and response-schema tests.

Reference models: ``servlet/security/**`` (Basic/JWT/trusted-proxy, the
DefaultRoleSecurityProvider role structure) and the ``ResponseTest`` pattern
validating live endpoint payloads against the response schemas.
"""

import base64
import json
import time
import urllib.error
import urllib.request

import pytest

from cruise_control_tpu.servlet.schemas import (
    ENDPOINT_SCHEMAS,
    SchemaViolation,
    validate,
)
from cruise_control_tpu.servlet.security import (
    BasicSecurityProvider,
    JwtSecurityProvider,
    Principal,
    Role,
    TrustedProxySecurityProvider,
    make_jwt,
    permits,
    required_role,
)


def test_role_structure():
    """DefaultRoleSecurityProvider.java:50-62."""
    assert required_role("GET", "kafka_cluster_state") is Role.VIEWER
    assert required_role("GET", "user_tasks") is Role.VIEWER
    assert required_role("GET", "review_board") is Role.VIEWER
    assert required_role("GET", "state") is Role.USER
    assert required_role("GET", "proposals") is Role.USER
    assert required_role("GET", "bootstrap") is Role.ADMIN
    assert required_role("GET", "train") is Role.ADMIN
    assert required_role("POST", "rebalance") is Role.ADMIN
    assert permits(Role.ADMIN, Role.VIEWER)
    assert not permits(Role.VIEWER, Role.USER)


def _basic_header(user, password):
    token = base64.b64encode(f"{user}:{password}".encode()).decode()
    return {"Authorization": f"Basic {token}"}


def test_basic_provider(tmp_path):
    creds = tmp_path / "realm.properties"
    creds.write_text("admin: secret,ADMIN\nviewer: look,VIEWER\n# comment\n")
    p = BasicSecurityProvider(credentials_file=str(creds))
    assert p.authenticate(_basic_header("admin", "secret"), "1.2.3.4") == \
        Principal("admin", Role.ADMIN)
    assert p.authenticate(_basic_header("viewer", "look"), "x").role is Role.VIEWER
    assert p.authenticate(_basic_header("admin", "wrong"), "x") is None
    assert p.authenticate({}, "x") is None
    assert "WWW-Authenticate" in p.challenge()


def test_jwt_provider():
    p = JwtSecurityProvider("s3cret")
    token = make_jwt({"sub": "alice", "role": "USER",
                      "exp": time.time() + 60}, "s3cret")
    got = p.authenticate({"Authorization": f"Bearer {token}"}, "x")
    assert got == Principal("alice", Role.USER)
    expired = make_jwt({"sub": "alice", "role": "USER",
                        "exp": time.time() - 1}, "s3cret")
    assert p.authenticate({"Authorization": f"Bearer {expired}"}, "x") is None
    forged = make_jwt({"sub": "alice", "role": "ADMIN"}, "other-secret")
    assert p.authenticate({"Authorization": f"Bearer {forged}"}, "x") is None


def test_trusted_proxy_provider():
    p = TrustedProxySecurityProvider(["10.0.0.1"])
    headers = {"X-Forwarded-User": "bob"}
    assert p.authenticate(headers, "10.0.0.1") == Principal("bob", Role.ADMIN)
    assert p.authenticate(headers, "10.0.0.2") is None
    assert p.authenticate({}, "10.0.0.1") is None


def test_spnego_provider(tmp_path):
    """SpnegoSecurityProvider.java:36-70 semantics with a fake GSS validator:
    Negotiate header parsing, principal short-naming, user-store role lookup,
    mutual-auth token passthrough, bad-ticket → None."""
    from cruise_control_tpu.servlet.security import SpnegoSecurityProvider

    store = tmp_path / "realm.properties"
    store.write_text("alice: x, ADMIN\nbob: x, VIEWER\n")

    def validator(token: bytes):
        if token == b"good-alice":
            return "alice/host.example.com@EXAMPLE.COM", b"mutual-tok"
        if token == b"good-bob":
            return "bob@EXAMPLE.COM"
        raise ValueError("bad ticket")

    p = SpnegoSecurityProvider(validator, credentials_file=str(store),
                               default_role=None)

    def hdr(tok: bytes):
        return {"Authorization": "Negotiate " + base64.b64encode(tok).decode()}

    assert p.authenticate(hdr(b"good-alice"), "1.2.3.4") == \
        Principal("alice", Role.ADMIN)
    assert p.mutual_auth_header() == {
        "WWW-Authenticate": "Negotiate " + base64.b64encode(b"mutual-tok").decode()}
    assert p.authenticate(hdr(b"good-bob"), "1.2.3.4") == \
        Principal("bob", Role.VIEWER)
    assert p.mutual_auth_header() == {}          # no mutual token this time
    assert p.authenticate(hdr(b"forged"), "1.2.3.4") is None
    assert p.authenticate({}, "1.2.3.4") is None
    assert p.authenticate({"Authorization": "Negotiate !!!"}, "1.2.3.4") is None
    assert p.challenge() == {"WWW-Authenticate": "Negotiate"}

    # Unknown-but-authenticated principals: rejected without a default role,
    # admitted with one (UserStoreAuthorizationService returns no roles → 403).
    def v2(token):
        return "mallory@EXAMPLE.COM"
    assert SpnegoSecurityProvider(v2, default_role=None).authenticate(
        hdr(b"t"), "") is None
    assert SpnegoSecurityProvider(v2).authenticate(
        hdr(b"t"), "") == Principal("mallory", Role.USER)


def test_spnego_provider_from_config(tmp_path):
    """main._security_provider must RESOLVE validator.class (a dotted path
    string after config parsing) via get_configured_instance, not hand the
    raw string to the provider."""
    from cruise_control_tpu.config.cruise_control_config import CruiseControlConfig
    from cruise_control_tpu.main import _security_provider
    from cruise_control_tpu.servlet.security import SpnegoSecurityProvider

    store = tmp_path / "realm.properties"
    store.write_text("carol: x, ADMIN\n")
    cfg = CruiseControlConfig({
        "webserver.security.enable": "true",
        "webserver.security.provider": "spnego",
        "webserver.auth.credentials.file": str(store),
        "webserver.auth.spnego.validator.class":
            "cruise_control_tpu.testing.fake_gss.FakeGssValidator",
    })
    provider = _security_provider(cfg)
    assert isinstance(provider, SpnegoSecurityProvider)

    def hdr(principal: bytes):
        return {"Authorization":
                "Negotiate " + base64.b64encode(b"principal:" + principal).decode()}

    assert provider.authenticate(hdr(b"carol"), "1.2.3.4") == \
        Principal("carol", Role.ADMIN)
    # Authenticated-but-unknown principals are REJECTED (user-store
    # authorization, not a default role — the reference 403s them).
    assert provider.authenticate(hdr(b"mallory"), "1.2.3.4") is None

    with pytest.raises(ValueError, match="validator.class required"):
        _security_provider(CruiseControlConfig({
            "webserver.security.enable": "true",
            "webserver.security.provider": "spnego",
            "webserver.auth.credentials.file": str(store)}))
    with pytest.raises(ValueError, match="credentials.file required"):
        _security_provider(CruiseControlConfig({
            "webserver.security.enable": "true",
            "webserver.security.provider": "spnego",
            "webserver.auth.spnego.validator.class":
                "cruise_control_tpu.testing.fake_gss.FakeGssValidator"}))


def test_schema_checker():
    schema = {"type": "object", "required": ["a"],
              "properties": {"a": {"type": "integer"},
                             "b": {"type": "array", "items": {"type": "string"}}}}
    validate({"a": 1, "b": ["x"]}, schema)
    with pytest.raises(SchemaViolation):
        validate({"b": []}, schema)
    with pytest.raises(SchemaViolation):
        validate({"a": "nope"}, schema)
    with pytest.raises(SchemaViolation):
        validate({"a": 1, "b": [2]}, schema)


@pytest.fixture(scope="module")
def secured_app():
    from cruise_control_tpu.config.cruise_control_config import CruiseControlConfig
    from cruise_control_tpu.main import build_app
    import tempfile, os
    fd, path = tempfile.mkstemp(suffix=".properties")
    with os.fdopen(fd, "w") as f:
        f.write("admin: pw,ADMIN\nviewer: look,VIEWER\nuser: go,USER\n")
    cfg = CruiseControlConfig({
        "metric.sampling.interval.ms": 300,
        "partition.metrics.window.ms": 600,
        "webserver.security.enable": True,
        "webserver.auth.credentials.file": path,
    })
    app = build_app(cfg, port=0)
    app.cc.start_up()
    app.start()
    yield app
    app.stop()
    app.cc.shutdown()
    os.unlink(path)


def _get(app, path, user=None, password=None, method="GET"):
    req = urllib.request.Request(
        f"http://127.0.0.1:{app.port}/kafkacruisecontrol{path}", method=method)
    if user:
        token = base64.b64encode(f"{user}:{password}".encode()).decode()
        req.add_header("Authorization", f"Basic {token}")
    return urllib.request.urlopen(req)


def test_secured_endpoints(secured_app):
    app = secured_app
    with pytest.raises(urllib.error.HTTPError) as e:
        _get(app, "/state")
    assert e.value.code == 401
    assert e.value.headers.get("WWW-Authenticate")

    with pytest.raises(urllib.error.HTTPError) as e:
        _get(app, "/state", "viewer", "look")
    assert e.value.code == 403

    assert _get(app, "/state", "user", "go").status == 200
    assert _get(app, "/kafka_cluster_state", "viewer", "look").status == 200

    with pytest.raises(urllib.error.HTTPError) as e:
        _get(app, "/rebalance?dryrun=true", "user", "go", method="POST")
    assert e.value.code == 403
    # Admin is AUTHORIZED (the op itself may 500 until windows accumulate).
    try:
        code = _get(app, "/rebalance?dryrun=true", "admin", "pw",
                    method="POST").status
    except urllib.error.HTTPError as e:
        code = e.code
    assert code not in (401, 403), code


def test_live_responses_match_schemas(secured_app):
    """ResponseTest pattern: fetch each schema'd endpoint and validate."""
    app = secured_app

    def fetch_done(path, method="GET"):
        # Per-endpoint budget: the first proposals/rebalance call compiles
        # the full goal stack (~1 min on the CPU test backend).
        deadline = time.time() + 150
        task_id = None
        while time.time() < deadline:
            req = urllib.request.Request(
                f"http://127.0.0.1:{app.port}/kafkacruisecontrol{path}",
                method=method)
            token = base64.b64encode(b"admin:pw").decode()
            req.add_header("Authorization", f"Basic {token}")
            if task_id:
                req.add_header("User-Task-ID", task_id)
            try:
                r = urllib.request.urlopen(req)
            except urllib.error.HTTPError:
                time.sleep(0.5)
                continue
            task_id = r.headers.get("User-Task-ID", task_id)
            body = json.load(r)
            if "progress" not in body:
                return body
            time.sleep(0.5)
        raise AssertionError(f"{path} never completed")

    for endpoint, path, method in (
        ("state", "/state", "GET"),
        ("load", "/load", "GET"),
        ("partition_load", "/partition_load", "GET"),
        ("kafka_cluster_state", "/kafka_cluster_state", "GET"),
        ("user_tasks", "/user_tasks", "GET"),
        ("proposals", "/proposals", "GET"),
        ("rebalance", "/rebalance?dryrun=true", "POST"),
    ):
        body = fetch_done(path, method)
        validate(body, ENDPOINT_SCHEMAS[endpoint])


def test_cli_auth_against_secured_server(secured_app):
    """tpucc must be able to authenticate against a secured server."""
    from cruise_control_tpu.client.cccli import ENDPOINTS, Responder
    app = secured_app
    base = f"http://127.0.0.1:{app.port}"
    spec = ENDPOINTS["state"]
    unauth = Responder(base).request(spec, {})
    assert unauth["httpStatus"] == 401
    token = base64.b64encode(b"user:go").decode()
    ok = Responder(base, auth_header=f"Basic {token}").request(spec, {})
    assert ok["httpStatus"] == 200 and "MonitorState" in ok
