"""Breadth tests the reference's multi-tier suite covers (SURVEY §4):
random goal orderings (RandomGoalTest), new-broker pull scenarios,
excluded-brokers-for-leadership, randomized self-healing, and the measured
destination-jitter trade-off study."""

import random

import numpy as np
import pytest

from cruise_control_tpu.analyzer import (
    BalancingConstraint,
    GoalOptimizer,
    OptimizationOptions,
)
from cruise_control_tpu.analyzer.goals.registry import (
    DEFAULT_GOALS,
    DEFAULT_HARD_GOALS,
    get_goals_by_priority,
)
from cruise_control_tpu.analyzer.solver import GoalSolver
from cruise_control_tpu.model import ops
from cruise_control_tpu.testing import random_cluster as rc


def _cluster(seed=21, brokers=12, replicas=1024):
    props = rc.ClusterProperties(num_brokers=brokers, num_racks=4,
                                 num_topics=16, num_replicas=replicas,
                                 seed=seed)
    return rc.generate(props, pad_replicas_to=1024)


def test_random_goal_order():
    """RandomGoalTest: the hard-goal guarantees must hold under any goal
    permutation (priors change the acceptance chains but not feasibility)."""
    state, placement, meta = _cluster()
    rng = random.Random(7)
    for trial in range(3):
        goal_names = list(DEFAULT_HARD_GOALS)
        rng.shuffle(goal_names)
        goals = get_goals_by_priority(goal_names)
        result = GoalOptimizer(goal_names=goal_names).optimizations(
            state, placement, meta, goals=goals)
        assert not [g for g in result.violated_goals_after
                    if g in DEFAULT_HARD_GOALS], (trial, goal_names)


def test_new_broker_receives_load():
    """add_broker semantics: distribution goals pull replicas onto an empty
    new broker (the reference's new-broker scenario tests)."""
    props = rc.ClusterProperties(num_brokers=8, num_racks=4, num_topics=16,
                                 num_replicas=1024, seed=3)
    state, placement, meta = rc.generate(props, pad_replicas_to=1024)
    # Empty broker 7: move everything it holds to broker 0's rack-mates first.
    b = np.asarray(placement.broker)
    state_np = np.asarray(state.alive)
    donors = [i for i in range(8) if i != 7]
    newb = b.copy()
    rng = np.random.default_rng(5)
    newb[b == 7] = rng.choice(donors, size=(b == 7).sum())
    placement = placement.replace(broker=np.asarray(newb))
    result = GoalOptimizer(goal_names=["ReplicaDistributionGoal"]).optimizations(
        state, placement, meta)
    final = np.asarray(result.final_placement.broker)[np.asarray(state.valid)]
    assert (final == 7).sum() > 0, "new broker received nothing"
    counts = np.bincount(final, minlength=8)[:8]
    assert counts.max() - counts.min() <= max(2, int(0.3 * counts.mean())), counts


def test_excluded_brokers_for_leadership():
    """No NEW leadership may land on excluded brokers; PLE demotes where a
    preferred replica exists elsewhere (DemoteBrokerRunnable semantics)."""
    state, placement, meta = _cluster(seed=9)
    excluded = {int(meta.broker_ids[0]), int(meta.broker_ids[1])}
    options = OptimizationOptions(
        excluded_brokers_for_leadership=frozenset(excluded))
    result = GoalOptimizer(goal_names=["PreferredLeaderElectionGoal"]).optimizations(
        state, placement, meta, options=options)
    final = result.final_placement
    lead_b = np.asarray(final.broker)[np.asarray(state.valid)
                                      & np.asarray(final.is_leader)]
    before_b = np.asarray(placement.broker)[np.asarray(state.valid)
                                            & np.asarray(placement.is_leader)]
    # Leadership on excluded brokers must not grow.
    for e in excluded:
        assert (lead_b == e).sum() <= (before_b == e).sum(), e


def test_randomized_self_healing_remove():
    """Self-healing sweep: kill a random broker, heal with the anomaly-
    detection goal stack, assert full evacuation — repeated over seeds."""
    for seed in (1, 2, 3):
        props = rc.ClusterProperties(num_brokers=10, num_racks=5,
                                     num_topics=12, num_replicas=512,
                                     seed=seed)
        state, placement, meta = rc.generate(props, pad_replicas_to=512)
        rng = np.random.default_rng(seed)
        dead = int(rng.integers(0, 10))
        alive = np.array(state.alive)
        alive[dead] = False
        state = state.replace(alive=alive)
        result = GoalOptimizer(goal_names=DEFAULT_HARD_GOALS).optimizations(
            state, placement, meta)
        final = np.asarray(result.final_placement.broker)[np.asarray(state.valid)]
        assert (final != dead).all(), (seed, dead)


def test_jitter_frac_sweep():
    """The measured destination-jitter trade-off (solver dst_jitter_frac):
    full jitter must converge in strictly fewer rounds than pure argmin, and
    its solution quality (post-solve CV) must stay within 15% of the pure-
    greedy result — the trade-off the default frac=1.0 encodes."""
    props = rc.ClusterProperties(num_brokers=24, num_racks=4, num_topics=32,
                                 num_replicas=4096, seed=31,
                                 mean_nw_in=90.0)
    state, placement, meta = rc.generate(props, pad_replicas_to=4096)
    outcomes = {}
    for frac in (0.0, 1.0):
        solver = GoalSolver(dst_jitter_frac=frac)
        opt = GoalOptimizer(goal_names=["NetworkInboundUsageDistributionGoal"],
                            solver=solver)
        result = opt.optimizations(state, placement, meta)
        cv = float(np.asarray(result.stats_after.cv())[1])   # NW_IN
        rounds = result.goal_infos[0].rounds
        outcomes[frac] = (cv, rounds)
    cv_greedy, rounds_greedy = outcomes[0.0]
    cv_full, rounds_full = outcomes[1.0]
    # Throughput: jitter must not be slower than pure greedy.
    assert rounds_full <= rounds_greedy, outcomes
    # Quality: within 15% of the greedy CV (absolute floor for tiny CVs).
    assert cv_full <= cv_greedy * 1.15 + 0.01, outcomes


def test_full_stack_goal_convergence():
    """Every default goal's per-goal solve converges to zero violated
    brokers on a mid-size random cluster, and the polished final state
    satisfies every goal — the regression ratchet for the multi-accept/
    multi-swap/multi-leadership batching machinery."""
    props = rc.ClusterProperties(num_brokers=40, num_racks=4, num_topics=60,
                                 num_replicas=6000, mean_cpu=0.006,
                                 seed=11)
    state, placement, meta = rc.generate(props)
    res = GoalOptimizer().optimizations(state, placement, meta)
    for info in res.goal_infos:
        assert info.violated_brokers_after == 0, (
            f"{info.goal_name}: {info.violated_brokers_before} -> "
            f"{info.violated_brokers_after} violated after "
            f"{info.rounds} rounds / {info.moves_applied} moves")
    # With the post-stack polish pass, the FINAL state satisfies every goal
    # (the sequential reference ships whatever its single pass produced).
    assert res.violated_goals_after == [], res.violated_goals_after
    assert res.balancedness_score == 100.0


def test_all_load_distributions_converge():
    """RandomClusterTest parameter decks: the reference populates random
    clusters with UNIFORM, LINEAR and EXPONENTIAL resource distributions
    (common/TestConstants.java) and asserts the goal stack still succeeds.
    The skewed decks are the hard ones — a few replicas carry most of the
    load — so the full default stack must end with zero violated goals on
    each."""
    from cruise_control_tpu.testing.random_cluster import Distribution

    for dist in (Distribution.UNIFORM, Distribution.LINEAR,
                 Distribution.EXPONENTIAL):
        props = rc.ClusterProperties(num_brokers=12, num_racks=4,
                                     num_topics=16, num_replicas=1000,
                                     distribution=dist, seed=33)
        state, placement, meta = rc.generate(props, pad_replicas_to=1024)
        result = GoalOptimizer(goal_names=list(DEFAULT_GOALS)).optimizations(
            state, placement, meta)
        assert result.violated_goals_after == [], (
            dist, result.violated_goals_after)
