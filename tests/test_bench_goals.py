"""Drift guards for bench.py: the benchmark's goal stack must be the
registry's, byte for byte — config #4's "full default stack" claim is only
comparable across rounds if a registry change cannot silently diverge from
what the bench actually times.  Also covers the bench's pure helpers
(``--only`` parsing, derived compile fields, quality extraction)."""

from __future__ import annotations

import importlib.util
import os

import numpy as np
import pytest

from cruise_control_tpu.analyzer.goals.registry import (
    DEFAULT_GOALS,
    DEFAULT_HARD_GOALS,
)

_BENCH_PATH = os.path.join(os.path.dirname(__file__), os.pardir, "bench.py")


def _load_bench():
    spec = importlib.util.spec_from_file_location("bench", _BENCH_PATH)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)   # no jax import at module level
    return mod


bench = _load_bench()


def test_bench_goals_match_registry_default_goals():
    assert bench.GOALS == DEFAULT_GOALS


def test_bench_hard_goals_match_registry_hard_goals():
    assert bench.HARD_GOALS == DEFAULT_HARD_GOALS


def test_parse_only_absent_and_valid():
    assert bench._parse_only(["bench.py"]) is None
    assert bench._parse_only(["bench.py", "--only", "3"]) == {3}
    assert bench._parse_only(["bench.py", "--only", "1,5"]) == {1, 5}


@pytest.mark.parametrize("argv", [
    ["bench.py", "--only"],             # missing argument
    ["bench.py", "--only", "x"],        # non-numeric
    ["bench.py", "--only", "1,,x"],     # partially numeric
])
def test_parse_only_rejects_bad_argv(argv):
    with pytest.raises(SystemExit) as exc:
        bench._parse_only(argv)
    assert exc.value.code == 2


def test_compile_fields_are_derived_from_the_counter_delta():
    assert bench._compile_fields(0) == {
        "fresh_compiles": 0, "includes_compile": False,
        "compile_cache": "warm"}
    assert bench._compile_fields(3) == {
        "fresh_compiles": 3, "includes_compile": True,
        "compile_cache": "cold"}


def test_batch_quality_reports_total_and_worst_lane():
    class FakeBatch:
        num_scenarios = 3
        violated_after = np.array([[0, 0], [2, 1], [0, 0]])

        def balancedness(self, s):
            return [100.0, 25.0, 100.0][s]

    q = bench._batch_quality(FakeBatch())
    assert q == {"violated_after": 3, "balancedness": 25.0}
