"""``${env:VAR}`` config-value indirection: secrets stay out of properties
files and are resolved from the process environment at load time — in
``load_properties`` for file-sourced values and in ``ConfigDef._coerce``
for programmatic overrides."""

from __future__ import annotations

import pytest

from cruise_control_tpu.common.exceptions import ConfigError
from cruise_control_tpu.config import CruiseControlConfig
from cruise_control_tpu.config.config_def import (
    load_properties,
    resolve_env_refs,
)


def test_plain_values_pass_through():
    assert resolve_env_refs("plain") == "plain"
    assert resolve_env_refs("") == ""
    assert resolve_env_refs(42) == 42
    assert resolve_env_refs(None) is None
    assert resolve_env_refs(True) is True


def test_single_ref_resolves(monkeypatch):
    monkeypatch.setenv("CC_TEST_SECRET", "s3cr3t")
    assert resolve_env_refs("${env:CC_TEST_SECRET}") == "s3cr3t"


def test_embedded_and_multiple_refs(monkeypatch):
    monkeypatch.setenv("CC_TEST_USER", "alice")
    monkeypatch.setenv("CC_TEST_PW", "hunter2")
    assert (resolve_env_refs("${env:CC_TEST_USER}:${env:CC_TEST_PW}@host")
            == "alice:hunter2@host")


def test_unset_var_is_a_config_error(monkeypatch):
    monkeypatch.delenv("CC_TEST_MISSING", raising=False)
    with pytest.raises(ConfigError, match="CC_TEST_MISSING"):
        resolve_env_refs("${env:CC_TEST_MISSING}")


def test_malformed_ref_passes_through_verbatim():
    # Not the documented syntax -> not an indirection (no silent surprises).
    assert resolve_env_refs("${envCC_X}") == "${envCC_X}"
    assert resolve_env_refs("$env:CC_X") == "$env:CC_X"


def test_load_properties_resolves_secrets(tmp_path, monkeypatch):
    monkeypatch.setenv("CC_TEST_WEBHOOK_TOKEN", "tok-123")
    path = tmp_path / "cc.properties"
    path.write_text(
        "# comment\n"
        "compile.persistent.cache.path=${env:CC_TEST_WEBHOOK_TOKEN}\n"
        "compile.warmup.lanes=8\n")
    props = load_properties(str(path))
    assert props["compile.persistent.cache.path"] == "tok-123"
    assert props["compile.warmup.lanes"] == "8"


def test_load_properties_unset_secret_fails_loud(tmp_path, monkeypatch):
    monkeypatch.delenv("CC_TEST_MISSING", raising=False)
    path = tmp_path / "cc.properties"
    path.write_text("compile.persistent.cache.path=${env:CC_TEST_MISSING}\n")
    with pytest.raises(ConfigError, match="CC_TEST_MISSING"):
        load_properties(str(path))


def test_programmatic_overrides_get_the_same_indirection(monkeypatch):
    # Dict-passed values go through ConfigDef._coerce, including coercion
    # of a numeric secret to its declared type.
    monkeypatch.setenv("CC_TEST_CACHE_DIR", "/var/cache/cc")
    monkeypatch.setenv("CC_TEST_MAX_BYTES", "1048576")
    cfg = CruiseControlConfig({
        "compile.persistent.cache.path": "${env:CC_TEST_CACHE_DIR}",
        "compile.persistent.cache.max.bytes": "${env:CC_TEST_MAX_BYTES}",
    })
    assert cfg.get("compile.persistent.cache.path") == "/var/cache/cc"
    assert cfg.get("compile.persistent.cache.max.bytes") == 1048576
