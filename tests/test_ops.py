"""Pallas kernel differential tests (ops/pallas_aggregate.py).

The kernel is validated in interpret mode against the XLA fallback it
replaces — same inputs, bit-comparable sums — including the padded-row and
odd-shape edges, plus the graceful-fallback paths (non-TPU lowering, vmap).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cruise_control_tpu.ops import broker_channel_sums
from cruise_control_tpu.ops.pallas_aggregate import CHUNK


@pytest.mark.parametrize("r,k,b", [
    (256, 8, 16),            # one partial chunk, tiny broker axis
    (CHUNK, 8, 128),         # exactly one chunk, lane-aligned brokers
    (3 * CHUNK + 77, 8, 37), # ragged replica axis, ragged broker axis
    (2048, 4, 200),          # the bench's broker count class
])
def test_kernel_matches_segment_sum(r, k, b):
    rng = np.random.default_rng(r + k + b)
    ch = jnp.asarray(rng.normal(size=(r, k)), jnp.float32)
    br = jnp.asarray(rng.integers(0, b, size=r), jnp.int32)
    ref = jax.ops.segment_sum(ch, br, num_segments=b)
    got = broker_channel_sums(ch, br, b, interpret=True)
    assert got.shape == ref.shape
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-6, atol=1e-4)


def test_kernel_with_masked_padding_rows():
    """The solver contract: padded replicas carry zero channels and point
    at broker 0 — they must not perturb broker 0's sums."""
    rng = np.random.default_rng(7)
    r, k, b, valid_n = 1024, 8, 64, 700
    ch = np.asarray(rng.normal(size=(r, k)), np.float32)
    br = np.asarray(rng.integers(0, b, size=r), np.int32)
    ch[valid_n:] = 0.0
    br[valid_n:] = 0
    ref = jax.ops.segment_sum(jnp.asarray(ch), jnp.asarray(br),
                              num_segments=b)
    got = broker_channel_sums(jnp.asarray(ch), jnp.asarray(br), b,
                              interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-6, atol=1e-4)


def test_counts_channel_exact():
    """Count channels (ones) must be exact, not approximately equal."""
    r, b = 4 * CHUNK, 333
    br = jnp.asarray(np.random.default_rng(3).integers(0, b, size=r),
                     jnp.int32)
    ones = jnp.ones((r, 1), jnp.float32)
    got = broker_channel_sums(ones, br, b, interpret=True)
    ref = jax.ops.segment_sum(ones, br, num_segments=b)
    assert (np.asarray(got) == np.asarray(ref)).all()


def test_non_tpu_lowering_falls_back():
    """prefer_pallas on a CPU backend must degrade to segment_sum, not
    raise — the gate may be flipped on in a mixed fleet."""
    r, k, b = 300, 8, 20
    rng = np.random.default_rng(1)
    ch = jnp.asarray(rng.normal(size=(r, k)), jnp.float32)
    br = jnp.asarray(rng.integers(0, b, size=r), jnp.int32)
    ref = jax.ops.segment_sum(ch, br, num_segments=b)
    got = broker_channel_sums(ch, br, b, prefer_pallas=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-6, atol=1e-4)


def test_compute_aggregates_pallas_gate(monkeypatch):
    """compute_aggregates with the kernel gate on must produce the same
    Aggregates as the default path (on CPU via the fallback; the channel
    packing itself is what this checks)."""
    from cruise_control_tpu.analyzer.constraint import BalancingConstraint
    from cruise_control_tpu.analyzer.context import build_context, compute_aggregates
    from cruise_control_tpu.analyzer.options import OptimizationOptions
    from cruise_control_tpu.testing import deterministic as det

    cm = det.unbalanced()
    state, placement, meta = cm.freeze(pad_replicas_to=16, pad_brokers_to=4)
    gctx = build_context(state, placement, meta, BalancingConstraint(),
                         OptimizationOptions())
    base = compute_aggregates(gctx, placement)
    monkeypatch.setenv("CC_PALLAS_AGG", "1")
    gated = compute_aggregates(gctx, placement)
    for name in ("broker_load", "replica_counts", "leader_counts",
                 "potential_nw_out", "leader_bytes_in", "host_load"):
        np.testing.assert_allclose(np.asarray(getattr(gated, name)),
                                   np.asarray(getattr(base, name)),
                                   rtol=1e-6, atol=1e-4, err_msg=name)


def test_vmap_does_not_crash():
    """Under vmap the Pallas path either batches or falls back — either
    way the result matches the per-lane segment_sum."""
    r, k, b, lanes = 256, 4, 10, 3
    rng = np.random.default_rng(5)
    ch = jnp.asarray(rng.normal(size=(lanes, r, k)), jnp.float32)
    br = jnp.asarray(rng.integers(0, b, size=(lanes, r)), jnp.int32)

    def one(c, ids):
        return broker_channel_sums(c, ids, b, prefer_pallas=True)

    got = jax.vmap(one)(ch, br)
    ref = jax.vmap(lambda c, ids: jax.ops.segment_sum(
        c, ids, num_segments=b))(ch, br)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-6, atol=1e-4)
