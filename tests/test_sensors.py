"""Observability sensor tests (reference: docs/wiki Sensors.md — the
Dropwizard sensor surface across Executor / LoadMonitor / UserTaskManager /
AnomalyDetector / GoalOptimizer / MetricFetcherManager / Servlet)."""

import importlib.util
import os
import re

import pytest

from cruise_control_tpu.common.metrics import (SCRAPE_ERRORS_SENSOR,
                                               MetricRegistry, registry)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _check_sensors_module():
    """scripts/ is not a package; load the drift guard by path."""
    spec = importlib.util.spec_from_file_location(
        "check_sensors", os.path.join(_REPO, "scripts", "check_sensors.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_registry_instruments():
    reg = MetricRegistry()
    c = reg.counter("x.count")
    c.inc(); c.inc(3)
    assert c.count == 4
    assert c.rate() > 0
    t = reg.timer("x.timer")
    t.update_ms(10.0); t.update_ms(30.0)
    s = t.stats()
    assert s["count"] == 2 and s["mean_ms"] == 20.0 and s["max_ms"] == 30.0
    reg.gauge("x.gauge", lambda: 7)
    g = reg.settable_gauge("x.set")
    g.set(3.5)
    snap = reg.snapshot()
    assert snap["x.gauge"]["value"] == 7
    assert snap["x.set"]["value"] == 3.5
    text = reg.prometheus_text()
    assert "kafka_cruisecontrol_x_count 4" in text
    assert "# TYPE kafka_cruisecontrol_x_gauge gauge" in text


def test_registry_bad_gauge_is_isolated():
    reg = MetricRegistry()
    reg.gauge("bad", lambda: 1 / 0)
    reg.gauge("good", lambda: 1)
    snap = reg.snapshot()
    assert "error" in snap["bad"]
    assert snap["good"]["value"] == 1


def test_counter_rate_uses_observed_lifetime():
    """A counter younger than the 60 s window divides by its lifetime
    (floored at 1 s), not the full window — 4 events in the first second
    must read ~4/s, not 4/60 (the fresh-boot under-reporting bug)."""
    c = MetricRegistry().counter("young")
    for _ in range(4):
        c.inc()
    # Wall-clock tolerant: even a very slow run keeps lifetime << 60 s.
    assert c.rate() > 4 / 30.0
    assert c.rate() <= 4.0 + 1e-9          # floor keeps bursts bounded


def test_scrape_errors_counter_always_materialized():
    """Raising gauge callbacks are not silent: snapshot() bumps the
    scrape-errors counter IN THE SAME scrape, and a clean registry still
    exports the sensor at 0 so dashboards can alert on it existing."""
    clean = MetricRegistry()
    snap = clean.snapshot()
    assert snap[SCRAPE_ERRORS_SENSOR]["count"] == 0
    reg = MetricRegistry()
    reg.gauge("bad", lambda: 1 / 0)
    snap = reg.snapshot()
    assert snap[SCRAPE_ERRORS_SENSOR]["count"] == 1
    snap = reg.snapshot()
    assert snap[SCRAPE_ERRORS_SENSOR]["count"] == 2   # bumps per scrape


def test_prometheus_name_collisions_rejected_at_registration():
    """Two sensors that sanitize to one Prometheus series would silently
    shadow each other in /metrics text; same name re-registered as another
    kind would emit duplicate TYPE lines — both fail loudly instead."""
    reg = MetricRegistry()
    reg.counter("a.b-c")
    with pytest.raises(ValueError, match="collides"):
        reg.counter("a.b_c")
    with pytest.raises(ValueError, match="already registered"):
        reg.timer("a.b-c")
    # Same name, same kind is the normal get-or-create path.
    assert reg.counter("a.b-c") is reg.counter("a.b-c")


@pytest.fixture(scope="module")
def service_scrape():
    """ONE booted-and-driven service scrape shared by the surface,
    exposition-validity, and doc-drift tests (a boot + proposals run is the
    expensive part; three separate boots would triple it).  Returns the
    check_sensors module plus its (json snapshot, prometheus text)."""
    mod = _check_sensors_module()
    snap, text = mod.collect_live()
    return mod, snap, text


def test_service_sensor_surface(service_scrape):
    """Boot the demo service, hit /metrics, and check the reference's sensor
    families are present with live values."""
    _, snap, text = service_scrape
    names = set(snap)
    for expected in (
        "Executor.replica-action-in-progress",
        "Executor.leadership-movements-global-cap",
        "LoadMonitor.valid-windows",
        "LoadMonitor.monitored-partitions-percentage",
        "LoadMonitor.cluster-model-creation-timer",
        "UserTaskManager.num-active-user-tasks",
        "MetricFetcherManager.partition-samples-fetcher-timer",
        "KafkaCruiseControlServlet.state-request-rate",
        "KafkaCruiseControlServlet.state-successful-request-execution-timer",
    ):
        assert expected in names, expected
    assert snap["LoadMonitor.valid-windows"]["value"] > 0
    # Prometheus text endpoint renders.
    assert "kafka_cruisecontrol_LoadMonitor_valid_windows" in text


_SERIES_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")


def _assert_exposition_valid(text):
    """Strict line-format check of the Prometheus text exposition: every
    line is a well-formed TYPE declaration or a sample; TYPE precedes its
    family's samples; no duplicate TYPE or sample series; every value
    parses as a float; every summary family exports its full quantile
    spread (count, p50/p99/max)."""
    typed = {}
    samples = set()
    for lineno, line in enumerate(text.splitlines(), 1):
        assert line and line == line.strip(), f"line {lineno}: blank/padded"
        if line.startswith("#"):
            parts = line.split(" ")
            assert parts[:2] == ["#", "TYPE"] and len(parts) == 4, \
                f"line {lineno}: malformed comment {line!r}"
            _, _, name, mtype = parts
            assert _SERIES_RE.match(name), f"line {lineno}: bad name {name!r}"
            assert mtype in ("counter", "gauge", "summary"), line
            assert name not in typed, f"line {lineno}: duplicate TYPE {name}"
            typed[name] = mtype
        else:
            parts = line.split(" ")
            assert len(parts) == 2, f"line {lineno}: {line!r}"
            name, value = parts
            assert _SERIES_RE.match(name), f"line {lineno}: bad name {name!r}"
            try:
                float(value)
            except ValueError:
                raise AssertionError(
                    f"line {lineno}: non-numeric value {value!r}") from None
            assert name not in samples, f"line {lineno}: duplicate {name}"
            samples.add(name)
            assert any(name == base or name.startswith(base + "_")
                       for base in typed), \
                f"line {lineno}: sample {name} precedes its TYPE line"
    for base, mtype in typed.items():
        if mtype == "summary":
            for stat in ("count", "p50_ms", "p99_ms", "max_ms"):
                assert f"{base}_{stat}" in samples, \
                    f"summary {base} missing {stat} sample"
    assert typed and samples


def test_exposition_checker_catches_junk():
    _assert_exposition_valid(MetricRegistry().prometheus_text())
    for bad in ("# TYPE x counter\nx 1\nx 2\n",          # duplicate series
                "x 1\n",                                  # sample before TYPE
                "# TYPE x counter\nx one\n",              # non-float value
                "# TYPE x counter\n# TYPE x gauge\nx 1\n",    # dup TYPE
                # summary missing its quantile spread (no p50/max)
                "# TYPE t summary\nt_count 1\nt_p99_ms 2.0\n"):
        with pytest.raises(AssertionError):
            _assert_exposition_valid(bad)


def test_metrics_exposition_valid(service_scrape):
    """Strict line-format check of booted-service /metrics output."""
    _, _, text = service_scrape
    _assert_exposition_valid(text)


def test_sensor_docs_current(service_scrape):
    """Fail on drift between docs/SENSORS.md and the live sensor surface —
    the tier-1 wiring of scripts/check_sensors.py."""
    mod, snap, _ = service_scrape
    documented = mod.parse_sensors_md()
    assert documented, "docs/SENSORS.md parsed to zero sensor rows"
    missing, undocumented = mod.diff(documented, set(snap))
    assert not missing, f"documented but not exported: {missing}"
    assert not undocumented, f"exported but not documented: {undocumented}"


def test_endpoint_docs_current():
    """Fail on drift between docs/ENDPOINTS.md and the servlet dispatch
    tables — no service boot needed, the guard diffs the route sets."""
    mod = _check_sensors_module()
    documented = mod.parse_endpoints_md()
    assert documented, "docs/ENDPOINTS.md parsed to zero endpoint rows"
    undocumented, stale = mod.endpoints_diff(documented)
    assert not undocumented, f"served but not documented: {undocumented}"
    assert not stale, f"documented but not served: {stale}"


def test_optimizer_sensors():
    import numpy as np
    from cruise_control_tpu.analyzer import GoalOptimizer
    from cruise_control_tpu.testing import deterministic as det

    state, placement, meta = det.unbalanced().freeze(pad_replicas_to=64,
                                                     pad_brokers_to=8)
    GoalOptimizer().optimizations(state, placement, meta)
    snap = registry().snapshot()
    assert snap["GoalOptimizer.proposal-computation-timer"]["count"] >= 1
    assert snap["AnomalyDetector.balancedness-score"]["value"] > 0
    assert snap["AnomalyDetector.right-sized"]["value"] == 1
    assert snap["AnomalyDetector.under-provisioned"]["value"] == 0
