"""Observability sensor tests (reference: docs/wiki Sensors.md — the
Dropwizard sensor surface across Executor / LoadMonitor / UserTaskManager /
AnomalyDetector / GoalOptimizer / MetricFetcherManager / Servlet)."""

import json
import time
import urllib.request

from cruise_control_tpu.common.metrics import MetricRegistry, registry


def test_registry_instruments():
    reg = MetricRegistry()
    c = reg.counter("x.count")
    c.inc(); c.inc(3)
    assert c.count == 4
    assert c.rate() > 0
    t = reg.timer("x.timer")
    t.update_ms(10.0); t.update_ms(30.0)
    s = t.stats()
    assert s["count"] == 2 and s["mean_ms"] == 20.0 and s["max_ms"] == 30.0
    reg.gauge("x.gauge", lambda: 7)
    g = reg.settable_gauge("x.set")
    g.set(3.5)
    snap = reg.snapshot()
    assert snap["x.gauge"]["value"] == 7
    assert snap["x.set"]["value"] == 3.5
    text = reg.prometheus_text()
    assert "kafka_cruisecontrol_x_count 4" in text
    assert "# TYPE kafka_cruisecontrol_x_gauge gauge" in text


def test_registry_bad_gauge_is_isolated():
    reg = MetricRegistry()
    reg.gauge("bad", lambda: 1 / 0)
    reg.gauge("good", lambda: 1)
    snap = reg.snapshot()
    assert "error" in snap["bad"]
    assert snap["good"]["value"] == 1


def test_service_sensor_surface():
    """Boot the demo service, hit /metrics, and check the reference's sensor
    families are present with live values."""
    from cruise_control_tpu.config.cruise_control_config import CruiseControlConfig
    from cruise_control_tpu.main import build_app

    cfg = CruiseControlConfig({"metric.sampling.interval.ms": 300,
                               "partition.metrics.window.ms": 600})
    app = build_app(cfg, port=0)
    app.cc.start_up()
    app.start()
    try:
        base = f"http://127.0.0.1:{app.port}/kafkacruisecontrol"
        # Drive one state request so servlet sensors exist, wait for sampling.
        urllib.request.urlopen(base + "/state")
        deadline = time.time() + 30
        while time.time() < deadline:
            snap = json.load(urllib.request.urlopen(base + "/metrics?json=true"))["sensors"]
            if snap.get("LoadMonitor.valid-windows", {}).get("value", 0) > 0:
                break
            time.sleep(0.5)
        names = set(snap)
        for expected in (
            "Executor.replica-action-in-progress",
            "Executor.leadership-movements-global-cap",
            "LoadMonitor.valid-windows",
            "LoadMonitor.monitored-partitions-percentage",
            "LoadMonitor.cluster-model-creation-timer",
            "UserTaskManager.num-active-user-tasks",
            "MetricFetcherManager.partition-samples-fetcher-timer",
            "KafkaCruiseControlServlet.state-request-rate",
            "KafkaCruiseControlServlet.state-successful-request-execution-timer",
        ):
            assert expected in names, expected
        assert snap["LoadMonitor.valid-windows"]["value"] > 0
        # Prometheus text endpoint renders.
        text = urllib.request.urlopen(base + "/metrics").read().decode()
        assert "kafka_cruisecontrol_LoadMonitor_valid_windows" in text
    finally:
        app.stop()
        app.cc.shutdown()


def test_optimizer_sensors():
    import numpy as np
    from cruise_control_tpu.analyzer import GoalOptimizer
    from cruise_control_tpu.testing import deterministic as det

    state, placement, meta = det.unbalanced().freeze(pad_replicas_to=64,
                                                     pad_brokers_to=8)
    GoalOptimizer().optimizations(state, placement, meta)
    snap = registry().snapshot()
    assert snap["GoalOptimizer.proposal-computation-timer"]["count"] >= 1
    assert snap["AnomalyDetector.balancedness-score"]["value"] > 0
    assert snap["AnomalyDetector.right-sized"]["value"] == 1
    assert snap["AnomalyDetector.under-provisioned"]["value"] == 0
