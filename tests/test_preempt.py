"""Deadline-aware preemptible solves: SolveBudget semantics, segmented
anytime kernels (bitwise parity + cache-key discipline), budget expiry and
cancellation through optimizer/facade/servlet, user-task timeouts, and the
operation audit log."""

import logging
import threading
import time

import numpy as np
import pytest

from cruise_control_tpu.analyzer import GoalOptimizer
from cruise_control_tpu.analyzer import solver as solver_mod
from cruise_control_tpu.analyzer.budget import SolveBudget
from cruise_control_tpu.common.metrics import registry
from cruise_control_tpu.servlet.user_tasks import TaskState, UserTaskManager
from cruise_control_tpu.testing import deterministic as det
from cruise_control_tpu.testing.verifier import verify_placement

GOALS = ["ReplicaCapacityGoal", "ReplicaDistributionGoal"]


@pytest.fixture(scope="module")
def snapshot():
    return det.unbalanced2().freeze(pad_replicas_to=64, pad_brokers_to=8)


def _tick_clock(step=0.1):
    """Deterministic monotonic clock: each read advances by ``step``.
    Returns (clock, cell) so tests can read the final virtual time."""
    t = {"v": 0.0}

    def clock():
        t["v"] += step
        return t["v"]
    return clock, t


def _narrow_solver(**kw):
    """One accepted move per round: multi-round convergence on the tiny
    deterministic clusters, so there are segment boundaries to preempt at."""
    return solver_mod.GoalSolver(max_candidates_per_round=1, **kw)


# ----------------------------------------------------------------- budget


def test_budget_semantics():
    b = SolveBudget()
    assert not b.should_stop() and b.stop_reason() is None
    assert b.remaining_ms() is None
    assert not b.segmented                      # cancel-only stays fused

    b = SolveBudget(deadline_ms=100, clock=_tick_clock(0.06)[0])
    assert b.segmented                          # a deadline implies segments
    assert b.stop_reason() is None              # t=0.12 < 0.16
    assert b.stop_reason() == "deadline"        # t=0.18 >= 0.16

    # Cancellation outranks the deadline and the first reason wins.
    b = SolveBudget(deadline_ms=1, clock=_tick_clock(10.0)[0])
    b.cancel("slo-preempt")
    b.cancel("shutdown")
    assert b.stop_reason() == "slo-preempt"
    assert b.cancel_reason == "slo-preempt"

    # The reason is pinned on the shared event: a second budget wrapping the
    # same token (the facade's view of a servlet task token) agrees.
    ev = threading.Event()
    first = SolveBudget(cancel_event=ev)
    first.cancel("user")
    second = SolveBudget(cancel_event=ev)
    assert second.cancelled() and second.cancel_reason == "user"

    # segmented=True without a deadline is an explicit opt-in.
    assert SolveBudget(segmented=True).segmented


# -------------------------------------------------- optimizer + solver


def test_cancel_before_start_returns_input_placement(snapshot):
    state, placement, meta = snapshot
    budget = SolveBudget()
    budget.cancel("user")
    c0 = registry().counter("Solver.partial-solves").count
    x0 = registry().counter("Solver.cancellations").count
    opt = GoalOptimizer(goal_names=GOALS, solver=solver_mod.GoalSolver())
    res = opt.optimizations(state, placement, meta, budget=budget)
    assert res.partial and res.preempt_reason == "user"
    assert all(i.preempted and i.rounds == 0 for i in res.goal_infos)
    assert not res.proposals
    assert np.array_equal(np.asarray(res.final_placement.broker),
                          np.asarray(placement.broker))
    assert registry().counter("Solver.partial-solves").count == c0 + 1
    assert registry().counter("Solver.cancellations").count == x0 + 1


def test_segmented_bitwise_equals_fused_and_cache_keys(snapshot):
    """Acceptance: a budget-less solve builds NO segment executables (its
    cache keys and results are byte-identical to a pre-segmentation build),
    and a segmented solve run to convergence is bitwise-equal to the fused
    single-dispatch loop."""
    state, placement, meta = snapshot
    solver = solver_mod.GoalSolver(segment_rounds=1)
    opt = GoalOptimizer(goal_names=GOALS, solver=solver)

    res_fused = opt.optimizations(state, placement, meta)
    keys_off = set(solver._round_cache)
    assert not any(isinstance(k, tuple) and k and k[0] == "segment"
                   for k in keys_off)

    budget = SolveBudget(segmented=True)        # never cancelled, no deadline
    res_seg = opt.optimizations(state, placement, meta, budget=budget)
    assert not res_seg.partial

    new = set(solver._round_cache) - keys_off
    assert new and all(k[0] == "segment" for k in new)
    assert keys_off <= set(solver._round_cache)  # off-path entries untouched

    for name in ("broker", "disk", "is_leader"):
        assert np.array_equal(np.asarray(getattr(res_seg.final_placement, name)),
                              np.asarray(getattr(res_fused.final_placement, name))), name
    for a, b in zip(res_seg.goal_infos, res_fused.goal_infos):
        assert (a.rounds, a.moves_applied, a.violated_brokers_after) == \
               (b.rounds, b.moves_applied, b.violated_brokers_after)


def test_deadline_expires_mid_goal(snapshot):
    state, placement, meta = snapshot
    # Deadline at t=0.55 on a 0.1-step clock: the budget survives the first
    # goal's probes and expires after the second goal's first one-round
    # segment — a MID-GOAL preemption, deterministic, no wall-clock.
    budget = SolveBudget(deadline_ms=450, clock=_tick_clock(0.1)[0])
    opt = GoalOptimizer(goal_names=GOALS,
                        solver=_narrow_solver(segment_rounds=1))
    res = opt.optimizations(state, placement, meta, budget=budget)
    assert res.partial and res.preempt_reason == "deadline"
    assert any(i.preempted and i.rounds > 0 for i in res.goal_infos)
    # The partial placement is still safe: executable proposals, no dead
    # replicas manufactured, no soft-goal regression.
    fails = verify_placement(state, placement, meta, res.final_placement,
                             goal_infos=res.goal_infos)
    assert not fails, [str(f) for f in fails]


def test_half_budget_partial_passes_verifier(snapshot):
    """Acceptance: with the deadline at 50% of the (virtual) time the solve
    needs to converge, the result is partial=True with strictly fewer rounds
    than convergence, and the placement passes the verifier."""
    state, placement, meta = snapshot
    solver = _narrow_solver(segment_rounds=1)
    opt = GoalOptimizer(goal_names=GOALS, solver=solver)

    # Calibrate: run to convergence on a tick clock that never expires; the
    # final virtual time is the budget a full solve needs.
    clock, cell = _tick_clock(0.1)
    full = opt.optimizations(state, placement, meta,
                             budget=SolveBudget(deadline_ms=1e12, clock=clock))
    assert not full.partial
    full_rounds = sum(i.rounds for i in full.goal_infos)
    assert full_rounds >= 2, "scenario converges too fast to preempt"

    clock2, _ = _tick_clock(0.1)
    res = opt.optimizations(state, placement, meta, budget=SolveBudget(
        deadline_ms=cell["v"] * 0.5 * 1000.0, clock=clock2))
    assert res.partial and res.preempt_reason == "deadline"
    assert sum(i.rounds for i in res.goal_infos) < full_rounds
    fails = verify_placement(state, placement, meta, res.final_placement,
                             goal_infos=res.goal_infos)
    assert not fails, [str(f) for f in fails]


# -------------------------------------------------------------- user tasks


def test_user_task_timeout_terminal_state():
    utm = UserTaskManager(num_threads=1, task_timeout_ms=50)
    token = threading.Event()
    t = utm.submit("rebalance", "", lambda p: token.wait(5.0),
                   cancel_token=token)
    assert t.future.result(timeout=5.0) is True  # woken by the timeout
    assert t.state is TaskState.TIMED_OUT
    assert t.cancel_reason == "timeout"
    assert t.to_dict()["Status"] == "TimedOut"
    assert t.to_dict()["CancelReason"] == "timeout"
    utm.shutdown()


def test_user_task_completion_beats_timeout():
    utm = UserTaskManager(num_threads=1, task_timeout_ms=10_000)
    token = threading.Event()
    t = utm.submit("rebalance", "", lambda p: 42, cancel_token=token)
    assert t.future.result(timeout=5.0) == 42
    assert t.state is TaskState.COMPLETED and not t.timed_out
    utm.shutdown()


def test_user_task_cancel_first_reason_wins():
    utm = UserTaskManager(num_threads=1)
    token = threading.Event()
    t = utm.submit("rebalance", "", lambda p: token.wait(5.0),
                   cancel_token=token)
    assert t.cancel("user")
    t.cancel("timeout")
    t.future.result(timeout=5.0)
    assert t.cancel_reason == "user"
    # A budget wrapping the same event (the facade side) reports the same.
    assert SolveBudget(cancel_event=token).cancel_reason == "user"
    # A task with no token cannot be cancelled.
    t2 = utm.submit("rebalance", "", lambda p: 1)
    assert not t2.cancel("user")
    utm.shutdown()


# ------------------------------------------------------------------ facade


def test_facade_cancel_event_yields_partial_result():
    from tests.test_facade import build_stack

    cc, _, _ = build_stack()
    ev = threading.Event()
    ev.set()                                     # cancelled before start
    r = cc.rebalance(goals=["ReplicaDistributionGoal"], dryrun=False,
                     cancel_event=ev)
    assert r.partial and not r.executed          # cancels never execute
    d = r.to_dict()
    assert d["partial"] is True
    statuses = [g["status"] for g in d["result"]["goals"]]
    assert "preempted" in statuses
    assert cc.active_solves() == 0               # budget unregistered
    assert cc.cancel_active_solves() == 0
    assert cc.state()["AnalyzerState"]["activeSolves"] == 0


def test_facade_deadline_completes_when_generous():
    from tests.test_facade import build_stack

    cc, _, _ = build_stack()
    r = cc.rebalance(goals=["ReplicaDistributionGoal"], dryrun=True,
                     deadline_ms=600_000)
    assert not r.partial
    assert "partial" not in r.to_dict()


def test_slo_preempt_detector_flips_fixable_for_solve_time():
    from cruise_control_tpu.detector.anomalies import SloViolationAnomaly
    from cruise_control_tpu.facade import _SloPreemptDetector

    class Inner:
        def detect(self):
            return [SloViolationAnomaly(objective="solve-time", sensor="s"),
                    SloViolationAnomaly(objective="balancedness", sensor="b")]

    wrapped = _SloPreemptDetector(Inner())
    a, b = wrapped.detect()
    assert a.fixable and not b.fixable


# ----------------------------------------------------------------- servlet


@pytest.fixture(scope="module")
def app():
    from cruise_control_tpu.servlet.server import CruiseControlApp
    from tests.test_facade import build_stack

    cc, _, _ = build_stack(num_brokers=4, partitions=8)
    application = CruiseControlApp(cc, port=0)
    application.start()
    yield application
    application.stop()


def test_deadline_ms_param_validation(app):
    from tests.test_servlet import _post

    code, body, _ = _post(app, "rebalance", dryrun="true", deadline_ms="abc")
    assert code == 400 and "deadline_ms" in body["error"]
    code, body, _ = _post(app, "rebalance", dryrun="true", deadline_ms="-5")
    assert code == 400


def test_cancel_user_task_endpoint(app):
    from tests.test_servlet import _post

    code, body, _ = _post(app, "cancel_user_task")
    assert code == 400
    code, body, _ = _post(app, "cancel_user_task", user_task_id="nope")
    assert code == 404

    # An in-flight task with a token: cancel returns 200 and wakes it.
    token = threading.Event()
    t = app.user_tasks.submit("rebalance", "dryrun=true",
                              lambda p: token.wait(10.0), cancel_token=token)
    code, body, _ = _post(app, "cancel_user_task", user_task_id=t.task_id)
    assert code == 200 and body["UserTaskId"] == t.task_id
    assert t.future.result(timeout=5.0) is True
    assert t.cancel_reason == "user"

    # A finished task is no longer cancellable.
    code, body, _ = _post(app, "cancel_user_task", user_task_id=t.task_id)
    assert code == 400 and "not active" in body["error"]


def test_rebalance_with_deadline_roundtrip(app):
    from cruise_control_tpu.servlet.server import USER_TASK_HEADER
    from tests.test_servlet import _post

    status, body, headers = _post(app, "rebalance", dryrun="true",
                                  goals="ReplicaDistributionGoal",
                                  deadline_ms="600000")
    task_id = headers.get(USER_TASK_HEADER)
    assert task_id
    deadline = time.time() + 30
    while status == 202 and time.time() < deadline:
        time.sleep(0.1)
        status, body, headers = _post(app, "rebalance",
                                      headers={USER_TASK_HEADER: task_id},
                                      dryrun="true",
                                      goals="ReplicaDistributionGoal",
                                      deadline_ms="600000")
    assert status == 200
    assert "partial" not in body                 # generous budget: converged


# ------------------------------------------------------------------- oplog


def test_oplog_record_format_and_principal(caplog):
    from cruise_control_tpu.obsvc import oplog

    with caplog.at_level(logging.INFO, logger="cruise_control_tpu.operations"):
        oplog.record("start", task_id="tid-1", endpoint="rebalance",
                     params="dryrun=true", extra_note="two words")
        tok = oplog.set_principal("alice")
        try:
            oplog.record("finish", task_id="tid-1", endpoint="rebalance")
        finally:
            oplog._principal.reset(tok)
    first, second = caplog.messages[-2:]
    assert "op=start" in first and "principal=anonymous" in first
    assert 'extra_note="two words"' in first
    assert "endpoint=rebalance" in first and "task=tid-1" in first
    assert "op=finish" in second and "principal=alice" in second
    with pytest.raises(ValueError):
        oplog.record("explode")
