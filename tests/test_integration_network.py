"""The full loop across EVERY network face at once.

One test: a logdir failure injected into the out-of-process broker
simulator → the assembled service's disk-failure detector reads it over the
authenticated admin SOCKET → self-healing runs fix_offline_replicas on a
model that marks those replicas offline → the executor's moves ride the
same socket back to the simulator → while broker metrics keep flowing over
the authenticated TCP metrics bus.  Reference analog:
``BrokerFailureDetectorTest`` + ``ExecutorTest`` against embedded brokers —
here every hop crosses a real process/socket boundary.
"""

import json
import subprocess
import sys
import time
import urllib.request

GOALS = "RackAwareGoal,ReplicaCapacityGoal,DiskCapacityGoal,ReplicaDistributionGoal"


def _free_port():
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _release(backend):
    """Drop a SocketClusterBackend's connection WITHOUT the shutdown op
    (close() would stop the simulator for everyone)."""
    backend._rstream.close()
    backend._wstream.close()
    backend._sock.close()


def _get_state(port):
    url = f"http://127.0.0.1:{port}/kafkacruisecontrol/state"
    return json.load(urllib.request.urlopen(url, timeout=10))


def test_disk_failure_self_heals_across_all_network_faces(tmp_path):
    from cruise_control_tpu.config.cruise_control_config import (
        CruiseControlConfig,
    )
    from cruise_control_tpu.executor.subprocess_backend import (
        SocketClusterBackend,
    )
    from cruise_control_tpu.main import build_app, demo_metadata
    from cruise_control_tpu.reporter import SocketTransport

    admin_token = tmp_path / "admin.secret"
    admin_token.write_text("integration-admin-token\n")
    bus_secret = tmp_path / "bus.secret"
    bus_secret.write_text("integration-bus-secret\n")

    # --- out-of-process cluster: the broker simulator on a TCP listener,
    # bootstrapped to EXACTLY the demo metadata topology (6 brokers, 48
    # demo-topic partitions, rf=2) so executor tasks apply cleanly.
    sim = subprocess.Popen(
        [sys.executable, "-m", "cruise_control_tpu.executor.broker_simulator",
         "--listen", "0", "--polls-to-finish", "1",
         "--auth-token-file", str(admin_token)],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True)
    try:
        banner = json.loads(sim.stdout.readline())
        sim_port = int(banner["listening"])
        meta = demo_metadata()
        parts = [{"topic": p.topic, "partition": p.partition,
                  "replicas": list(p.replicas), "leader": p.leader,
                  "logdirs": {str(b): 0 for b in p.replicas}}
                 for p in meta.fetch().partitions]
        setup = SocketClusterBackend("127.0.0.1", sim_port,
                                     auth_secret="integration-admin-token")
        setup.request("bootstrap", partitions=parts)
        _release(setup)

        # --- the assembled service: reporter-mode sampling, TCP metrics bus
        # (authenticated), socket admin driver (authenticated), self-healing
        # on a short detection interval, restricted goal list to keep the
        # self-healing solve's compile bounded on the test box.
        bus_port = _free_port()
        config = CruiseControlConfig({
            "metric.sampler.mode": "reporter",
            "metrics.transport.listen.port": str(bus_port),
            "metrics.transport.auth.secret.file": str(bus_secret),
            "executor.admin.backend.address": f"127.0.0.1:{sim_port}",
            "executor.admin.backend.auth.secret.file": str(admin_token),
            "self.healing.enabled": "true",
            "anomaly.detection.interval.ms": "1500",
            "execution.progress.check.interval.ms": "200",
            "partition.metrics.window.ms": "400",
            "broker.metrics.window.ms": "400",
            "metric.sampling.interval.ms": "150",
            "min.samples.per.partition.metrics.window": "1",
            "proposal.expiration.ms": "0",      # no precompute daemon noise
            "default.goals": GOALS,
            "anomaly.detection.goals": GOALS,
        })
        app = build_app(config, port=0)
        app.cc.start_up()
        app.start()
        try:
            # --- metrics flow over the authenticated TCP bus (the network
            # face remote reporter agents use).
            bus = SocketTransport(f"127.0.0.1:{bus_port}",
                                  auth_secret="integration-bus-secret")
            deadline = time.time() + 60
            seen = 0
            while time.time() < deadline and not seen:
                seen = sum(len(bus.poll(p, 0, 10)[0])
                           for p in range(bus.num_partitions))
                time.sleep(1)
            assert seen > 0, "no metrics crossed the TCP bus"
            bus.close()

            # --- monitor forms windows from the reporter pipeline.
            deadline = time.time() + 120
            while time.time() < deadline:
                if _get_state(app.port)["MonitorState"]["numValidWindows"] >= 2:
                    break
                time.sleep(2)
            else:
                raise AssertionError("monitor never formed valid windows")

            # --- inject the failure in the SIMULATOR process, mid-run, over
            # a second authenticated admin connection.
            injector = SocketClusterBackend(
                "127.0.0.1", sim_port, auth_secret="integration-admin-token")
            injector.request("fail_logdir", broker=0, logdir=0)
            assert injector.request("describe_log_dirs")["offline"] == {"0": [0]}
            _release(injector)

            # --- detector (over the admin socket) → self-healing fix →
            # executor moves (over the same socket).  The fix evacuates
            # broker 0's dead logdir: eventually no demo-topic partition
            # keeps a replica on broker 0.
            deadline = time.time() + 900
            fix_started = False
            evacuated = False
            while time.time() < deadline and not evacuated:
                ad = _get_state(app.port)["AnomalyDetectorState"]
                rows = [a for v in ad.get("recentAnomalies", {}).values()
                        for a in v]
                fix_started = fix_started or any(
                    a.get("type") == "DISK_FAILURE"
                    and a.get("status") in ("FIX_STARTED", "FIX_FAILED_TO_START")
                    for a in rows)
                checker = SocketClusterBackend(
                    "127.0.0.1", sim_port,
                    auth_secret="integration-admin-token")
                final = checker.request("describe_topics")["partitions"]
                _release(checker)
                evacuated = all(0 not in d["replicas"] for d in final)
                time.sleep(3)
            assert fix_started, "disk failure was never routed to the fixer"
            assert evacuated, \
                "broker 0's replicas were not evacuated over the admin socket"

            # --- the metrics bus face survived the whole loop.
            bus2 = SocketTransport(f"127.0.0.1:{bus_port}",
                                   auth_secret="integration-bus-secret")
            assert bus2.num_partitions > 0
            bus2.close()
        finally:
            app.stop()
            app.cc.shutdown()
    finally:
        sim.kill()


def test_maintenance_plans_over_authed_tcp_through_assembled_service(tmp_path):
    """The address-mode maintenance stream end-to-end: the assembled service
    consumes plans from an AUTHENTICATED TransportServer over TCP (the
    Kafka-topic analog with listener security), posted by a second client
    connection."""
    from cruise_control_tpu.config.cruise_control_config import (
        CruiseControlConfig,
    )
    from cruise_control_tpu.detector.anomalies import AnomalyType
    from cruise_control_tpu.detector.maintenance_reader import serialize_plan
    from cruise_control_tpu.main import build_app
    from cruise_control_tpu.reporter import (
        InProcessTransport,
        SocketTransport,
        TransportServer,
    )

    secret = tmp_path / "maint.secret"
    secret.write_text("maint-secret\n")
    bus = TransportServer(InProcessTransport(num_partitions=4),
                          auth_secret="maint-secret")
    bus.start()
    reader = None
    try:
        config = CruiseControlConfig({
            "maintenance.event.transport.address": f"127.0.0.1:{bus.port}",
            "maintenance.event.transport.auth.secret.file": str(secret),
            "maintenance.event.offsets.path": str(tmp_path / "off.json"),
            "self.healing.enabled": "true",
        })
        app = build_app(config, port=0)
        reader = app.cc.maintenance_reader
        assert reader is not None
        # Producer side: a second authenticated client posts a plan.
        producer = SocketTransport(f"127.0.0.1:{bus.port}",
                                   auth_secret="maint-secret")
        producer.append(2, serialize_plan("remove_broker",
                                          time_ms=time.time() * 1000,
                                          broker_ids=(3,)))
        producer.close()
        accepted, dropped = reader.poll_once()
        assert (accepted, dropped) == (1, 0)
        det = app.cc.anomaly_detector.detectors[AnomalyType.MAINTENANCE_EVENT]
        events = det.detect()
        assert len(events) == 1 and events[0].plan == "remove_broker"
        assert events[0].broker_ids == (3,)
    finally:
        if reader is not None:
            reader._transport.close()
        bus.stop()
