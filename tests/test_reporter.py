"""Ingestion-edge tests: reporter agent → wire serde → transport → fan-out
consuming sampler → processor, plus the Prometheus sampler and the full
reporter-mode service pipeline (reporter → aggregator → snapshot → solver).

Reference models: MetricSerdeTest, CruiseControlMetricsReporterTest (sans
embedded broker), MetricFetcherManagerTest, PrometheusMetricSamplerTest.
"""

import os

import numpy as np
import pytest

from cruise_control_tpu.monitor import metric_def as md
from cruise_control_tpu.monitor.fetcher import (
    ConsumingMetricSampler,
    DefaultMetricSamplerPartitionAssignor,
)
from cruise_control_tpu.monitor.metadata import (
    BrokerInfo,
    FakeMetadataBackend,
    PartitionInfo,
)
from cruise_control_tpu.monitor.prometheus import (
    PrometheusMetricSampler,
    PrometheusSeries,
)
from cruise_control_tpu.monitor.samples import (
    CruiseControlMetric,
    RawMetricScope,
    RawMetricType,
    broker_metric_types_for_version,
)
from cruise_control_tpu.reporter import (
    DemoBrokerMetricsSource,
    FileTransport,
    InProcessTransport,
    MetricsReporter,
    UnknownVersionError,
    deserialize_metric,
    serialize_metric,
)


def _backend(num_brokers=3, num_partitions=9, rf=2):
    brokers = [BrokerInfo(i, rack=str(i % 2), host=f"host{i}")
               for i in range(num_brokers)]
    parts = [PartitionInfo("T1", p, leader=p % num_brokers,
                           replicas=tuple((p + i) % num_brokers for i in range(rf)),
                           in_sync=tuple((p + i) % num_brokers for i in range(rf)))
             for p in range(num_partitions)]
    return FakeMetadataBackend(brokers, parts)


def test_serde_round_trip_all_types():
    for t in RawMetricType:
        m = CruiseControlMetric(
            raw_type=t, time_ms=123_456.0, broker_id=7,
            topic="T1" if t.scope is not RawMetricScope.BROKER else None,
            partition=3 if t.scope is RawMetricScope.PARTITION else None,
            value=42.5)
        got = deserialize_metric(serialize_metric(m))
        assert got == m, t


def test_serde_rejects_newer_version_and_skips_unknown_type():
    m = CruiseControlMetric(raw_type=RawMetricType.ALL_TOPIC_BYTES_IN,
                            time_ms=1.0, broker_id=0, value=1.0)
    buf = bytearray(serialize_metric(m))
    newer = bytes([buf[0] + 1]) + bytes(buf[1:])
    with pytest.raises(UnknownVersionError):
        deserialize_metric(newer)
    unknown_type = bytes([buf[0], 200]) + bytes(buf[2:])
    assert deserialize_metric(unknown_type) is None


def test_raw_type_inventory_matches_reference():
    assert len(RawMetricType) == 63
    assert RawMetricType.PARTITION_SIZE.wire_id == 4
    assert RawMetricType.BROKER_LOG_FLUSH_TIME_MS_999TH.wire_id == 62
    v4 = broker_metric_types_for_version(4)
    v5 = broker_metric_types_for_version(5)
    assert len(v5) - len(v4) == 20   # the 20 percentile types arrive in v5


_REFERENCE_ENUM = ("/root/reference/cruise-control-metrics-reporter/src/main/"
                   "java/com/linkedin/kafka/cruisecontrol/metricsreporter/"
                   "metric/RawMetricType.java")


@pytest.mark.skipif(not os.path.exists(_REFERENCE_ENUM),
                    reason="reference tree not mounted")
def test_raw_type_inventory_is_exhaustive_vs_reference_source():
    """Parse the reference enum itself: our inventory must match it entry for
    entry — name, wire id, scope, supported-since version.  This pins the
    'complete inventory' claim to the reference source, not to a hardcoded
    count (RawMetricType.java defines ids 0..62: 63 types total — its enum
    body ends at BROKER_LOG_FLUSH_TIME_MS_999TH(BROKER, 62, 5))."""
    import re
    src = open(_REFERENCE_ENUM, encoding="utf-8").read()
    pat = re.compile(r"^\s+([A-Z_0-9]+)\((BROKER|TOPIC|PARTITION),\s*"
                     r"\(byte\)\s*(\d+)(?:,\s*\(byte\)\s*(\d+))?\)", re.M)
    ref = {m.group(1): (m.group(2).lower(), int(m.group(3)),
                        int(m.group(4)) if m.group(4) else -1)
           for m in pat.finditer(src)}
    assert ref, "failed to parse reference enum"
    ours = {t.name: (t.scope.value, t.wire_id, t.supported_since)
            for t in RawMetricType}
    assert ours == ref


def test_reporter_emits_full_inventory():
    backend = _backend()
    transport = InProcessTransport(num_partitions=4)
    rep = MetricsReporter(0, DemoBrokerMetricsSource(backend), transport,
                          clock=lambda: 1000.0)
    n = rep.report_once()
    assert n > 60
    records, _ = transport.poll(0, 0, max_records=100_000)
    types = {deserialize_metric(r).raw_type for r in records if deserialize_metric(r)}
    broker_types = {t for t in types if t.scope is RawMetricScope.BROKER}
    assert broker_types == {t for t in RawMetricType
                            if t.scope is RawMetricScope.BROKER}


def test_partition_assignor_round_robin():
    sets = DefaultMetricSamplerPartitionAssignor.assign(8, 3)
    assert sets == [[0, 3, 6], [1, 4, 7], [2, 5]]
    assert DefaultMetricSamplerPartitionAssignor.assign(2, 4) == [[0], [1], [], []]


def _report_all(backend, transport, time_ms):
    source = DemoBrokerMetricsSource(backend)
    for b in backend.fetch().brokers:
        MetricsReporter(b.broker_id, source, transport,
                        clock=lambda: time_ms).report_once()


def test_consuming_sampler_end_to_end():
    backend = _backend()
    transport = InProcessTransport(num_partitions=4)
    _report_all(backend, transport, 5_000.0)
    sampler = ConsumingMetricSampler(transport, num_fetchers=3)
    result = sampler.get_samples(backend.fetch(), 0.0, 10_000.0)
    assert len(result.broker_samples) == 3
    assert len(result.partition_samples) == 9
    ps = result.partition_samples[0]
    assert ps.metrics[md.DISK_USAGE] > 0
    assert ps.metrics[md.LEADER_BYTES_IN] > 0
    # Offsets advanced: a second poll round returns nothing new.
    again = sampler.get_samples(backend.fetch(), 0.0, 10_000.0)
    assert not again.partition_samples


def test_file_transport_round_trip(tmp_path):
    transport = FileTransport(str(tmp_path), num_partitions=2)
    backend = _backend()
    _report_all(backend, transport, 5_000.0)
    sampler = ConsumingMetricSampler(transport, num_fetchers=2)
    result = sampler.get_samples(backend.fetch(), 0.0, 10_000.0)
    assert len(result.broker_samples) == 3
    assert len(result.partition_samples) == 9


def test_consumer_offsets_survive_restart(tmp_path):
    """Committed consumer positions (the reference's Kafka consumer-group
    offsets): a NEW sampler over the same durable bus must not re-ingest
    history, only records appended after the last commit."""
    transport = FileTransport(str(tmp_path / "bus"), num_partitions=2)
    offsets = str(tmp_path / "offsets.json")
    backend = _backend()
    _report_all(backend, transport, 5_000.0)
    s1 = ConsumingMetricSampler(transport, num_fetchers=2,
                                offsets_path=offsets)
    assert len(s1.get_samples(backend.fetch(), 0.0, 10_000.0)
               .partition_samples) == 9

    # "Restart": a fresh sampler; the old records must NOT come back.
    s2 = ConsumingMetricSampler(transport, num_fetchers=2,
                                offsets_path=offsets)
    assert not s2.get_samples(backend.fetch(), 0.0, 10_000.0).partition_samples
    # New records do.
    _report_all(backend, transport, 15_000.0)
    assert len(s2.get_samples(backend.fetch(), 10_000.0, 20_000.0)
               .partition_samples) == 9


def test_prometheus_sampler_with_fake_adapter():
    backend = _backend()
    meta = backend.fetch()

    def query_fn(promql, start_ms, end_ms):
        out = []
        if "topic, partition" in promql:              # partition-size query
            for p in meta.partitions:
                out.append(PrometheusSeries(
                    labels={"instance": f"host{p.leader}:9092", "topic": p.topic,
                            "partition": str(p.partition)},
                    values=[(end_ms / 1000, 5000.0)]))
        elif "topic" in promql:                       # per-topic queries
            for b in meta.brokers:
                out.append(PrometheusSeries(
                    labels={"instance": f"host{b.broker_id}:9092", "topic": "T1"},
                    values=[(end_ms / 1000, 900.0)]))
        else:                                         # broker-scope queries
            for b in meta.brokers:
                out.append(PrometheusSeries(
                    labels={"instance": f"host{b.broker_id}:9092"},
                    values=[(end_ms / 1000, 0.4), (end_ms / 1000 + 60, 0.6)]))
            # A series from a foreign cluster must be skipped, not fatal.
            out.append(PrometheusSeries(labels={"instance": "other:9092"},
                                        values=[(end_ms / 1000, 1.0)]))
        return out

    sampler = PrometheusMetricSampler(query_fn=query_fn)
    result = sampler.get_samples(meta, 0.0, 120_000.0)
    assert len(result.broker_samples) == 3
    assert len(result.partition_samples) == 9
    bdef = md.BROKER_METRIC_DEF
    bs = result.broker_samples[0]
    assert bs.metrics[bdef.metric_id("CPU_USAGE")] == pytest.approx(0.5)


def test_reporter_mode_service_pipeline():
    """Full path: reporter agents → transport → fan-out sampler → windows →
    snapshot → solver, through the real service bootstrap."""
    from cruise_control_tpu.config.cruise_control_config import CruiseControlConfig
    from cruise_control_tpu.main import build_app

    cfg = CruiseControlConfig({
        "metric.sampler.mode": "reporter",
        "metric.sampling.interval.ms": 200,
        "partition.metrics.window.ms": 400,
        "num.metric.fetchers": 3,
    })
    app = build_app(cfg, port=0)
    app.cc.start_up()
    try:
        import time
        deadline = time.time() + 60
        result = None
        while time.time() < deadline:
            try:
                result = app.cc.proposals()
                break
            except Exception:
                time.sleep(0.5)
        assert result is not None, "proposals never became available"
        assert result.optimizer_result.stats_after is not None
    finally:
        app.cc.shutdown()


def test_socket_transport_pipeline():
    """Network face of the metrics bus: remote reporter agents publish over
    TCP (the role Kafka producers play for __CruiseControlMetrics), the
    service's consuming sampler reads the same log — here via a second
    socket client to prove both directions of the wire."""
    from cruise_control_tpu.reporter import SocketTransport, TransportServer

    backend = _backend()
    local = InProcessTransport(num_partitions=4)
    server = TransportServer(local)
    server.start()
    try:
        addr = f"127.0.0.1:{server.port}"
        publish = SocketTransport(addr)
        assert publish.num_partitions == 4
        _report_all(backend, publish, 5_000.0)
        consume = SocketTransport(addr)
        sampler = ConsumingMetricSampler(consume, num_fetchers=2)
        result = sampler.get_samples(backend.fetch(), 0.0, 10_000.0)
        assert len(result.broker_samples) == 3
        assert len(result.partition_samples) == 9
        # Raw round-trip: bytes survive the wire exactly.
        local2, _ = local.poll(0, 0, 5)
        wire2, _ = SocketTransport(addr).poll(0, 0, 5)
        assert local2 == wire2
        publish.close(); consume.close()
    finally:
        server.stop()


def test_service_assembly_serves_metrics_bus():
    """metrics.transport.listen.port through build_app: the assembled
    service's bus is reachable over TCP and an external append lands in
    the same log the consuming sampler reads."""
    import socket

    from cruise_control_tpu.config.cruise_control_config import (
        CruiseControlConfig,
    )
    from cruise_control_tpu.main import build_app
    from cruise_control_tpu.reporter import SocketTransport

    # Probe-then-bind has a TOCTOU window; retry a couple of fresh ports.
    for attempt in range(3):
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        cfg = CruiseControlConfig({
            "metric.sampler.mode": "reporter",
            "metric.sampling.interval.ms": 200,
            "partition.metrics.window.ms": 500,
            "num.partition.metrics.windows": 3,
            "metrics.transport.listen.port": port,
        })
        try:
            app = build_app(cfg, port=0)
            break
        except OSError:
            if attempt == 2:
                raise
    try:
        app.cc.start_up()
        t = SocketTransport(f"127.0.0.1:{port}")
        assert t.num_partitions == 8
        _, end = t.poll(2, 0, 100000)
        t.append(2, b"external-record")
        recs, _ = t.poll(2, end, 100000)
        assert b"external-record" in recs
        t.close()
    finally:
        app.cc.shutdown()
        app.user_tasks.shutdown()


def test_transport_server_shared_secret_auth():
    """Authenticated metrics bus (the role Kafka SASL/ACLs play for
    __CruiseControlMetrics): the right secret can append/poll; a wrong
    secret or an op-before-auth is rejected and disconnected, so an
    unauthenticated peer can neither forge metrics nor read them."""
    import socket

    from cruise_control_tpu.reporter import (
        InProcessTransport,
        SocketTransport,
        TransportServer,
    )

    local = InProcessTransport(num_partitions=2)
    server = TransportServer(local, auth_secret="bus-secret")
    server.start()
    try:
        addr = f"127.0.0.1:{server.port}"
        good = SocketTransport(addr, auth_secret="bus-secret")
        good.append(0, b"metric-record")
        recs, _ = good.poll(0, 0)
        assert recs == [b"metric-record"]
        good.close()

        with pytest.raises((ConnectionError, OSError)):
            SocketTransport(addr, auth_secret="wrong").append(0, b"forged")
        assert local.record_count(0) == 1        # nothing forged

        # Op before auth: one error frame, then disconnect.
        import json as _json
        with socket.create_connection(("127.0.0.1", server.port),
                                      timeout=10) as s:
            s.sendall(b'{"op": "poll", "p": 0, "off": 0}\n')
            resp = _json.loads(s.makefile("rb").readline())
            assert resp["ok"] is False and "auth" in resp["error"]
    finally:
        server.stop()


def test_transport_server_oversized_frame_rejected(monkeypatch):
    """A single unbounded line cannot buffer the service into OOM: frames
    past MAX_FRAME_BYTES get one error reply and a disconnect."""
    import socket

    from cruise_control_tpu.reporter import InProcessTransport, TransportServer
    from cruise_control_tpu.reporter import transport as transport_mod

    monkeypatch.setattr(transport_mod, "MAX_FRAME_BYTES", 1024)
    server = TransportServer(InProcessTransport(num_partitions=1))
    server.start()
    try:
        import json as _json
        with socket.create_connection(("127.0.0.1", server.port),
                                      timeout=10) as s:
            s.sendall(b'{"op": "append", "p": 0, "rec": "' +
                      b"A" * 4096 + b'"}\n')
            f = s.makefile("rb")
            resp = _json.loads(f.readline())
            assert resp["ok"] is False and "MAX_FRAME" in resp["error"]
            assert f.readline() == b""           # peer disconnected us
    finally:
        server.stop()


@pytest.mark.skipif(__import__("shutil").which("openssl") is None,
                    reason="openssl CLI not available")
def test_transport_server_tls(tmp_path):
    """TLS metrics bus (webserver.ssl-shaped PEM config): a CA-pinned
    authenticated client round-trips records; a plaintext client cannot."""
    import subprocess
    import sys as _sys

    from cruise_control_tpu.reporter import (
        InProcessTransport,
        SocketTransport,
        TransportServer,
    )

    cert, key = tmp_path / "cert.pem", tmp_path / "key.pem"
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", str(key), "-out", str(cert), "-days", "1",
         "-subj", "/CN=localhost"],
        check=True, capture_output=True)
    server = TransportServer(InProcessTransport(num_partitions=2),
                             auth_secret="bus-secret",
                             ssl_certfile=str(cert), ssl_keyfile=str(key))
    server.start()
    try:
        addr = f"127.0.0.1:{server.port}"
        client = SocketTransport(addr, auth_secret="bus-secret",
                                 ssl_cafile=str(cert))
        client.append(1, b"over-tls")
        recs, _ = client.poll(1, 0)
        assert recs == [b"over-tls"]
        client.close()

        plain = SocketTransport(addr, auth_secret="bus-secret",
                                timeout_s=5.0)
        with pytest.raises((ConnectionError, OSError)):
            plain.append(0, b"plaintext")
    finally:
        server.stop()


def test_transport_server_preauth_garbage_disconnects():
    """Unparseable pre-auth frames must disconnect, not loop as per-frame
    errors — an unauthenticated peer may not pin a server thread."""
    import socket

    from cruise_control_tpu.reporter import InProcessTransport, TransportServer

    server = TransportServer(InProcessTransport(num_partitions=1),
                             auth_secret="s")
    server.start()
    try:
        import json as _json
        with socket.create_connection(("127.0.0.1", server.port),
                                      timeout=10) as s:
            s.sendall(b"not json at all\n")
            f = s.makefile("rb")
            resp = _json.loads(f.readline())
            assert resp["ok"] is False and "auth" in resp["error"]
            assert f.readline() == b""           # disconnected
    finally:
        server.stop()
