"""Differential tests against the reference's own asserted outcomes.

Port of the FULL parameterized deck of
``cruise-control/src/test/java/com/linkedin/kafka/cruisecontrol/analyzer/
DeterministicClusterTest.java:97-247``: every (constraint, fixture, goal list)
row the reference asserts must succeed has a row here asserting our solver is
never *worse* than that documented behavior — same fixtures
(``testing/deterministic.py`` ports of ``common/DeterministicCluster.java``),
same OptimizationVerifier postconditions (``testing/verifier.py``), same
expected-exception rows.

The reference's test tolerates OptimizationFailureException whose message is
"Insufficient healthy cluster capacity for resource" (DeterministicClusterTest
.java:269-274) — the SMALL_BROKER_CAPACITY deck rows are physically
infeasible.  We tolerate our OptimizationFailureError the same way, but only
on those rows.
"""

import pytest

from cruise_control_tpu.analyzer import BalancingConstraint
from cruise_control_tpu.common.exceptions import OptimizationFailureError
from cruise_control_tpu.common.resources import Resource
from cruise_control_tpu.testing import deterministic as det
from cruise_control_tpu.testing.verifier import execute_goals_for

PAD_R, PAD_B = 64, 8

# DeterministicClusterTest.java:101-118 — the 18-goal priority list.
GOAL_NAMES_BY_PRIORITY = [
    "RackAwareGoal",
    "RackAwareDistributionGoal",
    "MinTopicLeadersPerBrokerGoal",
    "ReplicaCapacityGoal",
    "DiskCapacityGoal",
    "NetworkInboundCapacityGoal",
    "NetworkOutboundCapacityGoal",
    "CpuCapacityGoal",
    "ReplicaDistributionGoal",
    "PotentialNwOutGoal",
    "DiskUsageDistributionGoal",
    "NetworkInboundUsageDistributionGoal",
    "NetworkOutboundUsageDistributionGoal",
    "CpuUsageDistributionGoal",
    "LeaderReplicaDistributionGoal",
    "LeaderBytesInDistributionGoal",
    "TopicReplicaDistributionGoal",
    "PreferredLeaderElectionGoal",
]

KAFKA_ASSIGNER_GOALS = [
    "KafkaAssignerEvenRackAwareGoal",
    "KafkaAssignerDiskUsageDistributionGoal",
]

VERIFICATIONS = ("GOAL_VIOLATION", "DEAD_BROKERS", "REGRESSION", "NEW_BROKERS")


def _constraint(balance: float = 1.1, capacity: float = None,
                min_leader_topics: tuple = (), min_leaders: int = 1,
                ) -> BalancingConstraint:
    """DeterministicClusterTest.getDefaultCruiseControlProperties:249-254
    (max 6 replicas/broker) + the per-deck-row overrides."""
    c = BalancingConstraint()
    c.balance_threshold = det.np.full(4, balance, dtype=det.np.float32)
    if capacity is not None:
        c.capacity_threshold = det.np.full(4, capacity, dtype=det.np.float32)
    c.max_replicas_per_broker = 6
    c.overprovisioned_max_replicas_per_broker = 6
    c.min_leader_topic_names = min_leader_topics
    c.min_topic_leaders_per_broker = min_leaders
    return c


def _run(model, goal_names, constraint, expect_failure=False,
         tolerate_capacity_infeasible=False):
    state, placement, meta = model.freeze(pad_replicas_to=PAD_R,
                                          pad_brokers_to=PAD_B)
    if expect_failure:
        with pytest.raises(OptimizationFailureError):
            execute_goals_for(state, placement, meta, goal_names,
                              constraint=constraint,
                              verifications=VERIFICATIONS)
        return
    try:
        report = execute_goals_for(state, placement, meta, goal_names,
                                   constraint=constraint,
                                   verifications=VERIFICATIONS)
    except OptimizationFailureError:
        if tolerate_capacity_infeasible:
            return  # DeterministicClusterTest.java:269-274 tolerance
        raise
    assert report.ok, report.failures


# ----------------------------------------------------- replica swap deck rows
# (DeterministicClusterTest.java:122-129, ZERO_BALANCE_PERCENTAGE)

def test_swap_unbalanced4_disk_usage_distribution():
    _run(det.unbalanced4(), ["DiskUsageDistributionGoal"],
         _constraint(balance=det.ZERO_BALANCE_PERCENTAGE))


def test_swap_unbalanced4_intra_broker_disk_usage_distribution():
    _run(det.unbalanced4(), ["IntraBrokerDiskUsageDistributionGoal"],
         _constraint(balance=det.ZERO_BALANCE_PERCENTAGE))


# ------------------------------------------------------- balance-percentage deck
# (:131-156 — small cluster with min-leader topic T2, medium with TOPIC_A)

@pytest.mark.parametrize("balance", [det.HIGH_BALANCE_PERCENTAGE,
                                     det.MEDIUM_BALANCE_PERCENTAGE,
                                     det.LOW_BALANCE_PERCENTAGE])
def test_balance_percentage_small_cluster(balance):
    _run(det.small_cluster_model(), GOAL_NAMES_BY_PRIORITY,
         _constraint(balance=balance, capacity=det.MEDIUM_CAPACITY_THRESHOLD,
                     min_leader_topics=(det.T2,)))


@pytest.mark.parametrize("balance", [det.HIGH_BALANCE_PERCENTAGE,
                                     det.MEDIUM_BALANCE_PERCENTAGE,
                                     det.LOW_BALANCE_PERCENTAGE])
def test_balance_percentage_medium_cluster(balance):
    _run(det.medium_cluster_model(), GOAL_NAMES_BY_PRIORITY,
         _constraint(balance=balance, capacity=det.MEDIUM_CAPACITY_THRESHOLD,
                     min_leader_topics=(det.TOPIC_A,)))


# ------------------------------------------------------- capacity-threshold deck
# (:158-179)

@pytest.mark.parametrize("capacity", [det.HIGH_CAPACITY_THRESHOLD,
                                      det.MEDIUM_CAPACITY_THRESHOLD,
                                      det.LOW_CAPACITY_THRESHOLD])
def test_capacity_threshold_small_cluster(capacity):
    _run(det.small_cluster_model(), GOAL_NAMES_BY_PRIORITY,
         _constraint(balance=det.MEDIUM_BALANCE_PERCENTAGE, capacity=capacity))


@pytest.mark.parametrize("capacity", [det.HIGH_CAPACITY_THRESHOLD,
                                      det.MEDIUM_CAPACITY_THRESHOLD,
                                      det.LOW_CAPACITY_THRESHOLD])
def test_capacity_threshold_medium_cluster(capacity):
    _run(det.medium_cluster_model(), GOAL_NAMES_BY_PRIORITY,
         _constraint(balance=det.MEDIUM_BALANCE_PERCENTAGE, capacity=capacity))


# --------------------------------------------------------- broker-capacity deck
# (:181-199 — the reference carries the last constraint of the previous loop:
# balance 1.25, capacity threshold 0.7.  SMALL_BROKER_CAPACITY rows are
# physically infeasible; the reference's try/catch tolerates exactly that.)

@pytest.mark.parametrize("cap_value,infeasible", [
    (det.LARGE_BROKER_CAPACITY, False),
    (det.MEDIUM_BROKER_CAPACITY, False),
    (det.SMALL_BROKER_CAPACITY, True),
])
@pytest.mark.parametrize("model_fn", [det.small_cluster_model,
                                      det.medium_cluster_model])
def test_broker_capacity_deck(model_fn, cap_value, infeasible):
    capacity = {r: cap_value for r in Resource}
    _run(model_fn(capacity), GOAL_NAMES_BY_PRIORITY,
         _constraint(balance=det.MEDIUM_BALANCE_PERCENTAGE,
                     capacity=det.LOW_CAPACITY_THRESHOLD),
         tolerate_capacity_infeasible=infeasible)


# ----------------------------------------------------------- kafka-assigner deck
# (:201-215)

@pytest.mark.parametrize("model_fn", [det.small_cluster_model,
                                      det.medium_cluster_model,
                                      det.rack_aware_satisfiable])
def test_kafka_assigner_deck(model_fn):
    _run(model_fn(), KAFKA_ASSIGNER_GOALS,
         _constraint(balance=det.MEDIUM_BALANCE_PERCENTAGE,
                     capacity=det.LOW_CAPACITY_THRESHOLD))


def test_kafka_assigner_rack_unsatisfiable():
    _run(det.rack_aware_unsatisfiable(), KAFKA_ASSIGNER_GOALS,
         _constraint(balance=det.MEDIUM_BALANCE_PERCENTAGE,
                     capacity=det.LOW_CAPACITY_THRESHOLD),
         expect_failure=True)


# ------------------------------------------------------------ min-leader deck
# (:217-245.  satisfiable3/4 have EMPTY brokers — they pass only because the
# goal, like the reference's (MinTopicLeadersPerBrokerGoal.java:360,430),
# falls back to moving surplus leader replicas when no promotion can reach
# the deficit broker.  This also exercises the solver's multi-leadership
# (topic, broker) single-touch branch, whose only user is this goal.)

MIN_LEADER_GOAL = ["MinTopicLeadersPerBrokerGoal"]


def test_min_leader_satisfiable():
    _run(det.min_leader_satisfiable(), MIN_LEADER_GOAL,
         _constraint(min_leader_topics=(det.TOPIC_L,)))


def test_min_leader_satisfiable2():
    _run(det.min_leader_satisfiable2(), MIN_LEADER_GOAL,
         _constraint(min_leader_topics=(det.TOPIC_L,)))


def test_min_leader_satisfiable3_requires_replica_moves():
    _run(det.min_leader_satisfiable3(), MIN_LEADER_GOAL,
         _constraint(min_leader_topics=(det.TOPIC_L,), min_leaders=4))


def test_min_leader_satisfiable4_two_topics():
    _run(det.min_leader_satisfiable4(), MIN_LEADER_GOAL,
         _constraint(min_leader_topics=(det.TOPIC0, det.TOPIC1)))


def test_min_leader_unsatisfiable():
    _run(det.min_leader_unsatisfiable(), MIN_LEADER_GOAL,
         _constraint(min_leader_topics=(det.TOPIC_L,)),
         expect_failure=True)
