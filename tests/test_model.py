"""Core tensor model tests: builder -> freeze -> ops/stats/sanity.

Mirrors the reference's model-layer invariants (ClusterModel.sanityCheck,
LoadConsistencyTest) on the SoA representation.
"""

import numpy as np
import pytest

from cruise_control_tpu.common.resources import Resource
from cruise_control_tpu.model import ops, sanity_check, compute_stats
from cruise_control_tpu.model.builder import ClusterModel
from cruise_control_tpu.testing import deterministic as det


def test_resource_enum_matches_reference():
    assert Resource.CPU == 0 and Resource.NW_IN == 1
    assert Resource.NW_OUT == 2 and Resource.DISK == 3
    assert Resource.CPU.is_host_resource and Resource.CPU.is_broker_resource
    assert Resource.NW_IN.is_host_resource and not Resource.NW_IN.is_broker_resource
    assert not Resource.DISK.is_host_resource and Resource.DISK.is_broker_resource


def test_unbalanced_freeze_shapes():
    state, placement, meta = det.unbalanced().freeze()
    assert meta.num_replicas == 2
    assert meta.num_brokers == 3
    assert meta.num_racks == 2
    assert sanity_check(state, placement, meta) == []


def test_broker_load_segment_sum():
    state, placement, meta = det.unbalanced().freeze()
    load = np.asarray(ops.broker_load(state, placement))
    # Both partitions (leaders) on broker 0; each (50, 150000, 100000, 150000).
    np.testing.assert_allclose(load[0], [100.0, 300000.0, 200000.0, 300000.0], rtol=1e-5)
    np.testing.assert_allclose(load[1], 0.0)
    np.testing.assert_allclose(load[2], 0.0)


def test_follower_load_derivation():
    state, placement, meta = det.unbalanced3().freeze()
    # Followers carry no NW_OUT and a reduced CPU share.
    eff = np.asarray(ops.effective_load(state, placement))
    is_leader = np.asarray(placement.is_leader)
    assert (eff[~is_leader][:, Resource.NW_OUT] == 0).all()
    assert (eff[~is_leader][:, Resource.CPU] < eff[is_leader][:, Resource.CPU]).all()
    # Follower NW_IN and DISK equal the leader-role values.
    np.testing.assert_allclose(eff[~is_leader][:, Resource.NW_IN],
                               eff[is_leader][:, Resource.NW_IN], rtol=1e-6)


def test_leadership_flip_transfers_nw_out():
    state, placement, meta = det.unbalanced3().freeze()
    before = np.asarray(ops.broker_load(state, placement))
    # Flip leadership of both partitions from broker 0 to broker 1 (mask flip only).
    is_leader = np.asarray(placement.is_leader)
    new_leader = ~is_leader
    flipped = placement.replace(is_leader=np.asarray(new_leader))
    after = np.asarray(ops.broker_load(state, flipped))
    # NW_OUT moved entirely from broker 0 to broker 1.
    assert before[0, Resource.NW_OUT] > 0
    assert after[0, Resource.NW_OUT] == 0
    np.testing.assert_allclose(after[1, Resource.NW_OUT], before[0, Resource.NW_OUT], rtol=1e-6)
    # DISK unchanged on both (leadership does not move disk).
    np.testing.assert_allclose(after[:, Resource.DISK], before[:, Resource.DISK], rtol=1e-6)


def test_potential_leadership_load():
    state, placement, meta = det.unbalanced3().freeze()
    pot = np.asarray(ops.potential_leadership_load(state, placement))
    # Each broker holds 2 replicas which would each emit NW_OUT/2 as leader.
    np.testing.assert_allclose(pot[0], 200000.0, rtol=1e-5)
    np.testing.assert_allclose(pot[1], 200000.0, rtol=1e-5)


def test_counts_and_rack_ops():
    state, placement, meta = det.rack_aware_unsatisfiable().freeze()
    rc = np.asarray(ops.replica_counts(state, placement))
    assert rc[:3].tolist() == [1, 1, 1]
    same = np.asarray(ops.replicas_on_same_rack(state, placement, meta.num_racks,
                                                meta.num_partitions))
    # Brokers 0,1 share rack 0 -> each of those replicas sees one sibling.
    assert same[:3].tolist() == [1, 1, 0]

    state2, placement2, meta2 = det.rack_aware_satisfiable2().freeze()
    same2 = np.asarray(ops.replicas_on_same_rack(state2, placement2, meta2.num_racks,
                                                 meta2.num_partitions))
    assert (same2[:2] == 0).all()


def test_partition_leader_broker():
    state, placement, meta = det.unbalanced3().freeze()
    leaders = np.asarray(ops.partition_leader_broker(state, placement, meta.num_partitions))
    assert (leaders == 0).all()  # broker id 0 leads both partitions


def test_disk_load_jbod():
    state, placement, meta = det.unbalanced4().freeze()
    assert state.num_disks_per_broker == 2
    dl = np.asarray(ops.disk_load(state, placement))
    bl = np.asarray(ops.broker_load(state, placement))
    np.testing.assert_allclose(dl.sum(axis=1), bl[:, Resource.DISK], rtol=1e-5)
    assert (dl[:2] > 0).all()  # every logdir of brokers 0,1 holds something


def test_sanity_check_catches_duplicates_and_leaderless():
    cm = det.unbalanced()
    state, placement, meta = cm.freeze()
    no_leader = placement.replace(is_leader=np.zeros_like(np.asarray(placement.is_leader)))
    problems = sanity_check(state, no_leader, meta)
    assert any("without a leader" in p for p in problems)

    # Two replicas of one partition on the same broker (via a rigged placement).
    cm2 = det.rack_aware_satisfiable()
    state2, placement2, meta2 = cm2.freeze()
    dup = placement2.replace(broker=np.zeros_like(np.asarray(placement2.broker)))
    problems2 = sanity_check(state2, dup, meta2)
    assert any(">1 replica on one broker" in p for p in problems2)


def test_offline_tracking_with_dead_disk_and_revived_broker():
    # Dead disk stays offline even after the broker is marked dead then alive.
    cm = det.unbalanced4()
    cm.mark_disk_dead(0, 0)
    cm.set_broker_state(0, alive=False)
    cm.set_broker_state(0, alive=True)
    state, placement, meta = cm.freeze()
    assert np.asarray(state.offline).sum() == 2  # the two logdir-0 replicas


def test_rf_reduction_below_one_rejected():
    cm = det.unbalanced()
    with pytest.raises(ValueError, match="only the leader remains"):
        cm.create_or_delete_replicas("T1", target_rf=0)


def test_negative_replica_index_rejected():
    cm = det.unbalanced()
    with pytest.raises(ValueError, match="index"):
        cm.create_replica("T1", 5, broker_id=0, index=-1, is_leader=True)


def test_stats():
    state, placement, meta = det.unbalanced().freeze()
    stats = compute_stats(state, placement)
    assert stats.num_brokers == 3
    assert stats.num_replicas == 2
    assert stats.num_leaders == 2
    assert stats.max_replicas == 2 and stats.min_replicas == 0
    # Broker 0 carries everything -> CPU avg is 100/3.
    np.testing.assert_allclose(stats.avg_util[Resource.CPU], 100.0 / 3, rtol=1e-4)
    assert stats.num_balanced_brokers[Resource.CPU] == 0  # all out of band


def test_mark_disk_dead_and_broker_dead():
    cm = det.unbalanced4()
    cm.mark_disk_dead(0, 0)
    state, placement, meta = cm.freeze()
    assert np.asarray(state.offline).sum() == 2  # two replicas were on logdir 0 of broker 0
    problems = sanity_check(state, placement, meta)
    assert any("dead" in p for p in problems)
    assert sanity_check(state, placement, meta, allow_offline=True) == []

    cm2 = det.unbalanced()
    cm2.set_broker_state(0, alive=False)
    state2, placement2, meta2 = cm2.freeze()
    assert np.asarray(state2.offline).sum() == 2


def test_padding_and_masks():
    state, placement, meta = det.unbalanced().freeze(pad_replicas_to=16, pad_brokers_to=8)
    assert state.num_replicas_padded == 16
    assert state.num_brokers_padded == 8
    assert np.asarray(state.valid).sum() == 2
    assert np.asarray(state.broker_valid).sum() == 3
    # Padded entries contribute nothing.
    load = np.asarray(ops.broker_load(state, placement))
    np.testing.assert_allclose(load[3:], 0.0)
    assert sanity_check(state, placement, meta) == []


def test_rf_change():
    cm = det.unbalanced()
    cm.create_or_delete_replicas("T1", target_rf=2)
    state, placement, meta = cm.freeze()
    assert meta.num_replicas == 3
    assert sanity_check(state, placement, meta) == []


def test_apply_placement_roundtrip():
    cm = det.unbalanced()
    state, placement, meta = cm.freeze()
    moved = placement.replace(broker=np.asarray([1, 2], dtype=np.int32))
    cm.apply_placement(moved, meta)
    assert cm.replica("T1", 0, 1).broker_id == 1
    assert cm.replica("T2", 0, 2).broker_id == 2
    state2, placement2, meta2 = cm.freeze()
    assert sanity_check(state2, placement2, meta2) == []
