"""Multi-host solver execution (parallel/multihost.py).

Spawns two REAL coordinated processes (jax.distributed over the gRPC
coordinator — the DCN control channel) each with 4 virtual CPU devices,
forming one 8-device global mesh, and runs a full sharded proposal
generation on it.  This is the same mechanism a multi-host TPU deployment
uses; only the transport under the collectives differs (Gloo here,
ICI/DCN there).  SURVEY §5 distributed-backend requirement.
"""

import hashlib
import json
import os
import socket
import subprocess
import sys
import textwrap

import pytest

_CHILD = textwrap.dedent("""
    import hashlib, json, sys
    sys.path.insert(0, __REPO__)
    from cruise_control_tpu.utils.hermetic import force_cpu
    force_cpu(n_devices=4)
    import jax
    pid = int(sys.argv[1])
    from cruise_control_tpu.parallel import multihost
    multihost.initialize(__ADDR__, num_processes=2, process_id=pid)
    multihost.initialize(__ADDR__, num_processes=2, process_id=pid)  # no-op repeat
    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 8, len(jax.devices())

    from cruise_control_tpu.testing import random_cluster as rc
    props = rc.ClusterProperties(num_brokers=8, num_racks=4, num_topics=10,
                                 num_replicas=192, mean_cpu=0.01,
                                 mean_disk=60.0, mean_nw_in=60.0,
                                 mean_nw_out=60.0, seed=11)
    # Both processes build the same-shaped snapshot (same seed here; a
    # worker could equally pass zeros — process 0's content is broadcast).
    state, placement, meta = rc.generate(props, pad_replicas_to=256)
    if pid == 1:
        import jax.numpy as jnp
        placement = placement.replace(
            broker=jnp.zeros_like(placement.broker))   # garbage content
    result = multihost.propose_multihost(
        state, placement, meta,
        goal_names=["RackAwareGoal", "ReplicaCapacityGoal",
                    "ReplicaDistributionGoal"])
    digest = sorted((str(p.topic_partition),
                     tuple(r.broker_id for r in p.new_replicas))
                    for p in result.proposals)
    print("RESULT " + json.dumps({
        "pid": pid,
        "violated_after": result.violated_goals_after,
        "n_proposals": len(result.proposals),
        "digest_hash": hashlib.sha256(
            json.dumps(digest).encode()).hexdigest(),
        "digest": digest[:5],
    }), flush=True)
""")


def test_two_process_global_mesh_propose(tmp_path):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    addr = f"127.0.0.1:{port}"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = tmp_path / "child.py"
    script.write_text(_CHILD.replace("__REPO__", repr(repo))
                      .replace("__ADDR__", repr(addr)))
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    procs = [subprocess.Popen([sys.executable, str(script), str(pid)],
                              stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True, env=env)
             for pid in (0, 1)]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=840)
        outs.append(out)
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out[-3000:]
    results = {}
    for out in outs:
        line = [ln for ln in out.splitlines() if ln.startswith("RESULT ")]
        assert line, out[-3000:]
        r = json.loads(line[-1][len("RESULT "):])
        results[r["pid"]] = r
    r0, r1 = results[0], results[1]
    # Both processes solved the coordinator's snapshot (process 1 passed
    # garbage placement content) and agree bit-for-bit on the outcome.
    assert r0["violated_after"] == [] and r1["violated_after"] == []
    assert r0["n_proposals"] == r1["n_proposals"] > 0
    assert r0["digest_hash"] == r1["digest_hash"]
    assert r0["digest"] == r1["digest"]


_CHILD_WHATIF = textwrap.dedent("""
    import hashlib, json, sys
    import numpy as np
    sys.path.insert(0, __REPO__)
    from cruise_control_tpu.utils.hermetic import force_cpu
    force_cpu(n_devices=4)
    import jax
    pid = int(sys.argv[1])
    from cruise_control_tpu.parallel import multihost
    multihost.initialize(__ADDR__, num_processes=2, process_id=pid)
    assert len(jax.devices()) == 8

    from cruise_control_tpu.testing import random_cluster as rc
    props = rc.ClusterProperties(num_brokers=8, num_racks=4, num_topics=10,
                                 num_replicas=192, mean_cpu=0.01,
                                 mean_disk=60.0, mean_nw_in=60.0,
                                 mean_nw_out=60.0, seed=11)
    state, placement, meta = rc.generate(props, pad_replicas_to=256)
    if pid == 1:
        import jax.numpy as jnp
        placement = placement.replace(
            broker=jnp.zeros_like(placement.broker))   # garbage content
    res = multihost.batch_remove_scenarios_multihost(
        state, placement, meta, [[0], [1], [2], [3]],
        goal_names=["RackAwareGoal", "ReplicaCapacityGoal"],
        scenario_parallelism=2, num_candidates=64)
    payload = {
        "pid": pid,
        "violated": np.asarray(res.violated_after).tolist(),
        "stranded": int(np.asarray(res.stranded_after).sum()),
        "placements_hash": hashlib.sha256(
            np.asarray(res.final_placements.broker).tobytes()).hexdigest(),
    }
    print("RESULT " + json.dumps(payload), flush=True)
""")


def test_two_process_scenario_mesh_what_ifs(tmp_path):
    """The DP x MP analog across REAL processes: the remove-broker what-if
    batch shards its scenario axis over two coordinated processes (replica
    axis within), and both return bit-identical lane results."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    addr = f"127.0.0.1:{port}"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = tmp_path / "child_whatif.py"
    script.write_text(_CHILD_WHATIF.replace("__REPO__", repr(repo))
                      .replace("__ADDR__", repr(addr)))
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    procs = [subprocess.Popen([sys.executable, str(script), str(pid)],
                              stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True, env=env)
             for pid in (0, 1)]
    outs = [p.communicate(timeout=840)[0] for p in procs]
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out[-3000:]
    results = {}
    for out in outs:
        line = [ln for ln in out.splitlines() if ln.startswith("RESULT ")]
        assert line, out[-3000:]
        r = json.loads(line[-1][len("RESULT "):])
        results[r["pid"]] = r
    r0, r1 = results[0], results[1]
    assert r0["stranded"] == r1["stranded"] == 0
    assert r0["violated"] == r1["violated"]
    assert all(v == 0 for lane in r0["violated"] for v in lane)
    assert r0["placements_hash"] == r1["placements_hash"]


_CHILD_DEATH = textwrap.dedent("""
    import os, sys, time
    sys.path.insert(0, __REPO__)
    from cruise_control_tpu.utils.hermetic import force_cpu
    force_cpu(n_devices=4)
    import jax
    pid = int(sys.argv[1])
    from cruise_control_tpu.parallel import multihost
    # Tight heartbeat so failure detection is test-sized (production keeps
    # the default; the knob is the point).
    multihost.initialize(__ADDR__, num_processes=2, process_id=pid,
                         heartbeat_timeout_s=10)
    print(f"pid{pid} up", flush=True)
    if pid == 1:
        os._exit(17)          # die abruptly before the collective
    import jax.numpy as jnp
    # The survivor enters the broadcast that now can never complete.
    out = multihost.broadcast_from_coordinator(jnp.arange(8.0))
    print("pid0 unexpectedly completed", flush=True)
""")


def test_worker_death_terminates_survivor_crisply(tmp_path):
    """A peer killed mid-solve must NOT leave the survivor hanging in the
    orphaned collective: the coordination service's heartbeat timeout
    (multihost.initialize(heartbeat_timeout_s=...)) terminates it with an
    'unhealthy tasks' diagnosis — the SPMD analog of the reference's ZK
    session-loss handling (BrokerFailureDetector.java:64-92)."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    addr = f"127.0.0.1:{port}"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = tmp_path / "child_death.py"
    script.write_text(_CHILD_DEATH.replace("__REPO__", repr(repo))
                      .replace("__ADDR__", repr(addr)))
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    procs = [subprocess.Popen([sys.executable, str(script), str(pid)],
                              stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True, env=env)
             for pid in (0, 1)]
    out1, _ = procs[1].communicate(timeout=300)
    assert procs[1].returncode == 17          # the scripted abrupt death
    # Survivor must exit (non-zero) well before the test timeout, with the
    # coordination service's diagnosis on its stderr — not hang.
    out0, _ = procs[0].communicate(timeout=240)
    assert procs[0].returncode != 0, out0[-2000:]
    assert "pid0 unexpectedly completed" not in out0
    assert ("unhealthy" in out0 or "heartbeat" in out0
            or "distributed service detected fatal errors" in out0), \
        out0[-3000:]
