"""Multi-host solver execution (parallel/multihost.py).

Spawns two REAL coordinated processes (jax.distributed over the gRPC
coordinator — the DCN control channel) each with 4 virtual CPU devices,
forming one 8-device global mesh, and runs a full sharded proposal
generation on it.  This is the same mechanism a multi-host TPU deployment
uses; only the transport under the collectives differs (Gloo here,
ICI/DCN there).  SURVEY §5 distributed-backend requirement.
"""

import hashlib
import json
import os
import socket
import subprocess
import sys
import textwrap

import pytest

_CHILD = textwrap.dedent("""
    import hashlib, json, sys
    sys.path.insert(0, __REPO__)
    from cruise_control_tpu.utils.hermetic import force_cpu
    force_cpu(n_devices=4)
    import jax
    pid = int(sys.argv[1])
    from cruise_control_tpu.parallel import multihost
    multihost.initialize(__ADDR__, num_processes=2, process_id=pid)
    multihost.initialize(__ADDR__, num_processes=2, process_id=pid)  # no-op repeat
    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 8, len(jax.devices())

    from cruise_control_tpu.testing import random_cluster as rc
    props = rc.ClusterProperties(num_brokers=8, num_racks=4, num_topics=10,
                                 num_replicas=192, mean_cpu=0.01,
                                 mean_disk=60.0, mean_nw_in=60.0,
                                 mean_nw_out=60.0, seed=11)
    # Both processes build the same-shaped snapshot (same seed here; a
    # worker could equally pass zeros — process 0's content is broadcast).
    state, placement, meta = rc.generate(props, pad_replicas_to=256)
    if pid == 1:
        import jax.numpy as jnp
        placement = placement.replace(
            broker=jnp.zeros_like(placement.broker))   # garbage content
    result = multihost.propose_multihost(
        state, placement, meta,
        goal_names=["RackAwareGoal", "ReplicaCapacityGoal",
                    "ReplicaDistributionGoal"])
    digest = sorted((str(p.topic_partition),
                     tuple(r.broker_id for r in p.new_replicas))
                    for p in result.proposals)
    print("RESULT " + json.dumps({
        "pid": pid,
        "violated_after": result.violated_goals_after,
        "n_proposals": len(result.proposals),
        "digest_hash": hashlib.sha256(
            json.dumps(digest).encode()).hexdigest(),
        "digest": digest[:5],
    }), flush=True)
""")


def test_two_process_global_mesh_propose(tmp_path):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    addr = f"127.0.0.1:{port}"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = tmp_path / "child.py"
    script.write_text(_CHILD.replace("__REPO__", repr(repo))
                      .replace("__ADDR__", repr(addr)))
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    procs = [subprocess.Popen([sys.executable, str(script), str(pid)],
                              stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True, env=env)
             for pid in (0, 1)]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=840)
        outs.append(out)
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out[-3000:]
    results = {}
    for out in outs:
        line = [ln for ln in out.splitlines() if ln.startswith("RESULT ")]
        assert line, out[-3000:]
        r = json.loads(line[-1][len("RESULT "):])
        results[r["pid"]] = r
    r0, r1 = results[0], results[1]
    # Both processes solved the coordinator's snapshot (process 1 passed
    # garbage placement content) and agree bit-for-bit on the outcome.
    assert r0["violated_after"] == [] and r1["violated_after"] == []
    assert r0["n_proposals"] == r1["n_proposals"] > 0
    assert r0["digest_hash"] == r1["digest_hash"]
    assert r0["digest"] == r1["digest"]
