"""Service-layer tests: config system, REST endpoints over a live server,
async user tasks, two-step verification (models
KafkaCruiseControlServletEndpointTest / UserTaskManagerTest)."""

import json
import time
import urllib.error
import urllib.parse
import urllib.request

import pytest

from cruise_control_tpu.common.exceptions import ConfigError
from cruise_control_tpu.config.config_def import ConfigDef, ConfigType, load_properties
from cruise_control_tpu.config.cruise_control_config import CruiseControlConfig
from cruise_control_tpu.servlet.server import USER_TASK_HEADER, CruiseControlApp
from cruise_control_tpu.servlet.user_tasks import TaskState, UserTaskManager
from tests.test_facade import build_stack


# ------------------------------------------------------------------- config


def test_config_defaults_and_coercion():
    cfg = CruiseControlConfig({"cpu.capacity.threshold": "0.9",
                               "self.healing.enabled": "true",
                               "max.replicas.per.broker": "5000"})
    assert cfg["cpu.capacity.threshold"] == 0.9
    assert cfg["self.healing.enabled"] is True
    assert cfg["max.replicas.per.broker"] == 5000
    assert cfg.goal_names()[0] == "RackAwareGoal"


def test_config_accepts_java_class_names():
    cfg = CruiseControlConfig({
        "default.goals": "com.linkedin.kafka.cruisecontrol.analyzer.goals."
                         "RackAwareGoal,com.linkedin.kafka.cruisecontrol."
                         "analyzer.goals.ReplicaCapacityGoal"})
    assert cfg.goal_names() == ["RackAwareGoal", "ReplicaCapacityGoal"]


def test_config_validates():
    with pytest.raises(ConfigError):
        CruiseControlConfig({"cpu.capacity.threshold": "1.5"})
    with pytest.raises(ConfigError):
        CruiseControlConfig({"default.goals": "NoSuchGoal"})


def test_config_properties_file(tmp_path):
    p = tmp_path / "cc.properties"
    p.write_text("# comment\nwebserver.http.port=7777\n"
                 "disk.balance.threshold=1.3\n")
    cfg = CruiseControlConfig.from_properties_file(str(p))
    assert cfg["webserver.http.port"] == 7777
    assert abs(cfg.balancing_constraint().balance_threshold[3] - 1.3) < 1e-6


def test_reference_properties_file_parses():
    """The reference's shipped cruisecontrol.properties must parse."""
    props = load_properties("/root/reference/config/cruisecontrol.properties")
    cfg = CruiseControlConfig(props)
    assert cfg.goal_names("hard.goals") == [
        "RackAwareGoal", "ReplicaCapacityGoal", "DiskCapacityGoal",
        "NetworkInboundCapacityGoal", "NetworkOutboundCapacityGoal",
        "CpuCapacityGoal"]


# --------------------------------------------------------------- user tasks


def test_user_task_manager_dedup_and_retention():
    utm = UserTaskManager(num_threads=2, completed_retention_ms=1e9)
    t1 = utm.submit("rebalance", "dryrun=true", lambda p: 42)
    t1.future.result()
    same = utm.get_or_create(t1.task_id, "rebalance", "dryrun=true", lambda p: 43)
    assert same is t1
    assert same.future.result() == 42
    assert t1.state is TaskState.COMPLETED


def test_user_task_error_state():
    utm = UserTaskManager(num_threads=1)

    def boom(progress):
        raise ValueError("nope")

    t = utm.submit("rebalance", "", boom)
    with pytest.raises(ValueError):
        t.future.result()
    assert t.state is TaskState.COMPLETED_WITH_ERROR


# ------------------------------------------------------------------- server


@pytest.fixture(scope="module")
def app():
    cc, backend, cluster = build_stack(num_brokers=4, partitions=8)
    application = CruiseControlApp(cc, port=0)
    application.start()
    yield application
    application.stop()


def _get(app, endpoint, **params):
    qs = urllib.parse.urlencode(params)
    url = f"http://127.0.0.1:{app.port}/kafkacruisecontrol/{endpoint}"
    if qs:
        url += f"?{qs}"
    with urllib.request.urlopen(url) as r:
        return r.status, json.loads(r.read().decode()), dict(r.headers)


def _post(app, endpoint, headers=None, **params):
    qs = urllib.parse.urlencode(params)
    url = f"http://127.0.0.1:{app.port}/kafkacruisecontrol/{endpoint}"
    if qs:
        url += f"?{qs}"
    req = urllib.request.Request(url, method="POST")
    for k, v in (headers or {}).items():
        req.add_header(k, v)
    try:
        with urllib.request.urlopen(req) as r:
            return r.status, json.loads(r.read().decode()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode()), dict(e.headers)


def test_state_endpoint(app):
    status, body, _ = _get(app, "state")
    assert status == 200
    assert body["MonitorState"]["numValidWindows"] == 5
    assert body["ExecutorState"]["state"] == "NO_TASK_IN_PROGRESS"


def test_load_and_partition_load(app):
    status, body, _ = _get(app, "load")
    assert status == 200 and body["numBrokers"] == 4
    status, body, _ = _get(app, "partition_load", entries=3)
    assert status == 200 and len(body["records"]) == 3


def test_kafka_cluster_state(app):
    status, body, _ = _get(app, "kafka_cluster_state")
    assert status == 200
    assert body["KafkaBrokerState"]["Summary"]["brokers"] == 4


def test_rebalance_dryrun_roundtrip(app):
    status, body, headers = _post(app, "rebalance", dryrun="true",
                                  goals="ReplicaDistributionGoal")
    assert status in (200, 202)
    task_id = headers.get(USER_TASK_HEADER)
    assert task_id
    deadline = time.time() + 30
    while status == 202 and time.time() < deadline:
        time.sleep(0.1)
        status, body, headers = _post(app, "rebalance",
                                      headers={USER_TASK_HEADER: task_id},
                                      dryrun="true",
                                      goals="ReplicaDistributionGoal")
    assert status == 200
    assert body["dryrun"] is True and body["executed"] is False
    # The task shows up in user_tasks.
    _, tasks, _ = _get(app, "user_tasks")
    assert any(t["UserTaskId"] == task_id for t in tasks["userTasks"])


def test_unknown_endpoint_404(app):
    status, body, _ = _get(app, "state")
    assert status == 200
    code, body, _ = _post(app, "nonsense")
    assert code == 404


def test_missing_brokerid_400(app):
    code, body, _ = _post(app, "remove_broker", dryrun="true")
    assert code == 400


def test_admin_self_healing_toggle(app):
    code, body, _ = _post(app, "admin", enable_self_healing_for="broker_failure")
    assert code == 200
    assert body["selfHealingEnabledBefore"]["BROKER_FAILURE"] in (True, False)
    _post(app, "admin", disable_self_healing_for="broker_failure")


def test_pause_resume_sampling(app):
    code, body, _ = _post(app, "pause_sampling", reason="test")
    assert code == 200
    code, body, _ = _post(app, "resume_sampling", reason="test")
    assert code == 200


def test_two_step_verification_flow():
    cc, backend, cluster = build_stack(num_brokers=4, partitions=8)
    app2 = CruiseControlApp(cc, port=0, two_step_verification=True)
    app2.start()
    try:
        code, body, _ = _post(app2, "rebalance", dryrun="true")
        assert code == 202 and "reviewResult" in body
        review_id = body["reviewResult"]["Id"]
        code, board, _ = _get(app2, "review_board")
        assert any(r["Id"] == review_id for r in board["RequestInfo"])
        code, body, _ = _post(app2, "review", approve=str(review_id))
        assert code == 200
        code, body, headers = _post(app2, "rebalance", dryrun="true",
                                    review_id=str(review_id),
                                    goals="ReplicaDistributionGoal")
        assert code in (200, 202)
    finally:
        app2.stop()


def test_cli_parameter_validation():
    """CCParameter semantics: malformed values are rejected client-side
    (argparse usage error), valid ones normalized."""
    import pytest
    from cruise_control_tpu.client.cccli import build_parser

    parser = build_parser()
    ns = parser.parse_args(["rebalance", "--dryrun", "YES",
                            "--destination_broker_ids", "1, 2,3"])
    assert ns.dryrun == "true"
    assert ns.destination_broker_ids == "1,2,3"
    for bad in (["rebalance", "--dryrun", "maybe"],
                ["partition_load", "--entries", "-3"],
                ["remove_broker", "--brokerid", "1,x"],
                ["admin", "--enable_self_healing_for", "bogus"]):
        with pytest.raises(SystemExit):
            parser.parse_args(bad)


@pytest.mark.skipif(__import__("shutil").which("openssl") is None,
                    reason="openssl CLI not available")
def test_ssl_listener(tmp_path):
    """TLS listener (KafkaCruiseControlApp.java:100-120 SSL connector): a
    https request against a self-signed cert succeeds; plain http does not."""
    import ssl
    import subprocess

    cert = tmp_path / "cert.pem"
    key = tmp_path / "key.pem"
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", str(key), "-out", str(cert), "-days", "1",
         "-subj", "/CN=localhost"],
        check=True, capture_output=True)

    cc, backend, cluster = build_stack()
    app = CruiseControlApp(cc, port=0, ssl_certfile=str(cert),
                           ssl_keyfile=str(key))
    app.start()
    try:
        ctx = ssl.create_default_context(cafile=str(cert))
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_REQUIRED
        url = f"https://127.0.0.1:{app.port}/kafkacruisecontrol/state"
        body = json.load(urllib.request.urlopen(url, context=ctx, timeout=10))
        assert "MonitorState" in body
        with pytest.raises(Exception):
            urllib.request.urlopen(
                f"http://127.0.0.1:{app.port}/kafkacruisecontrol/state",
                timeout=5)
    finally:
        app.stop()


def test_rebalance_disk_uses_intra_broker_goals(app):
    """rebalance_disk=true swaps in the intra-broker goal list (reference
    RebalanceParameters) and rejects mixing with kafka_assigner."""
    status, body, headers = _post(app, "rebalance", dryrun="true",
                                  rebalance_disk="true")
    task_id = headers.get(USER_TASK_HEADER)
    deadline = time.time() + 60
    while status == 202 and time.time() < deadline:
        time.sleep(0.1)
        status, body, headers = _post(
            app, "rebalance", headers={USER_TASK_HEADER: task_id},
            dryrun="true", rebalance_disk="true")
    assert status == 200
    goals_run = [g["goal"] for g in body["result"]["goals"]]
    assert goals_run == ["IntraBrokerDiskCapacityGoal",
                         "IntraBrokerDiskUsageDistributionGoal"]
    status, body, _ = _post(app, "rebalance", dryrun="true",
                            rebalance_disk="true", kafka_assigner="true")
    assert status == 400


def test_static_ui_serving(tmp_path):
    """webserver.ui.diskpath serving (KafkaCruiseControlApp.setupWebUi /
    Jetty DefaultServlet): index.html at the prefix root, content-type by
    extension, API prefix untouched, and no path escape from the UI dir."""
    ui = tmp_path / "dist"
    ui.mkdir()
    (ui / "index.html").write_text("<html>cc-ui</html>")
    (ui / "app.js").write_text("console.log(1)")
    secret = tmp_path / "secret.txt"
    secret.write_text("keep out")

    cc, backend, cluster = build_stack(num_brokers=4, partitions=8)
    app = CruiseControlApp(cc, port=0, ui_diskpath=str(ui))
    app.start()
    try:
        base = f"http://127.0.0.1:{app.port}"
        with urllib.request.urlopen(f"{base}/") as r:
            assert r.status == 200
            assert b"cc-ui" in r.read()
            assert "text/html" in r.headers["Content-Type"]
        with urllib.request.urlopen(f"{base}/app.js") as r:
            assert "javascript" in r.headers["Content-Type"]
        # API prefix still wins over the frontend.
        with urllib.request.urlopen(
                f"{base}/kafkacruisecontrol/state") as r:
            assert r.status == 200
        for bad in ("/../secret.txt", "/%2e%2e/secret.txt", "/missing.html",
                    "/%00", "/a%00b.html"):
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(base + bad)
            assert ei.value.code == 404, bad
    finally:
        app.stop()


def test_static_ui_requires_auth_when_security_enabled(tmp_path):
    """With a security provider configured, frontend assets are covered by
    the same authentication as the API (the reference secures the whole
    Jetty context the DefaultServlet is mounted in)."""
    import base64

    from cruise_control_tpu.servlet.security import BasicSecurityProvider, Role

    ui = tmp_path / "dist"
    ui.mkdir()
    (ui / "index.html").write_text("<html>cc-ui</html>")
    cc, backend, cluster = build_stack(num_brokers=4, partitions=8)
    sec = BasicSecurityProvider(users={"bob": ("pw", Role.VIEWER)})
    app = CruiseControlApp(cc, port=0, ui_diskpath=str(ui), security=sec)
    app.start()
    try:
        base = f"http://127.0.0.1:{app.port}"
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{base}/")
        assert ei.value.code == 401
        req = urllib.request.Request(f"{base}/", headers={
            "Authorization": "Basic "
            + base64.b64encode(b"bob:pw").decode()})
        with urllib.request.urlopen(req) as r:
            assert r.status == 200 and b"cc-ui" in r.read()
    finally:
        app.stop()


def test_static_ui_custom_urlprefix(tmp_path):
    """Non-default webserver.ui.urlprefix: the bare prefix and files under
    it serve; near-miss paths and the root 404."""
    ui = tmp_path / "dist"
    ui.mkdir()
    (ui / "index.html").write_text("<html>cc-ui</html>")
    (ui / "app.js").write_text("console.log(1)")
    cc, backend, cluster = build_stack(num_brokers=4, partitions=8)
    app = CruiseControlApp(cc, port=0, ui_diskpath=str(ui),
                           ui_urlprefix="/ui/*")
    app.start()
    try:
        base = f"http://127.0.0.1:{app.port}"
        with urllib.request.urlopen(f"{base}/ui") as r:
            assert b"cc-ui" in r.read()
        with urllib.request.urlopen(f"{base}/ui/app.js") as r:
            assert b"console" in r.read()
        for bad in ("/", "/uix", "/uifoo/app.js"):
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(base + bad)
            assert ei.value.code == 404, bad
    finally:
        app.stop()


def test_static_ui_carries_spnego_mutual_auth_token(tmp_path):
    """A mutual-auth GSS reply token must ride UI asset responses exactly as
    it rides API responses (RFC 4559 §4.2)."""
    import base64

    from cruise_control_tpu.servlet.security import (
        Role,
        SpnegoSecurityProvider,
    )

    ui = tmp_path / "dist"
    ui.mkdir()
    (ui / "index.html").write_text("<html>cc-ui</html>")

    def validator(token: bytes):
        assert token == b"ticket"
        return "carol@EXAMPLE.COM", b"server-reply"

    sec = SpnegoSecurityProvider(validator,
                                 roles_by_user={"carol": Role.ADMIN})
    cc, backend, cluster = build_stack(num_brokers=4, partitions=8)
    app = CruiseControlApp(cc, port=0, ui_diskpath=str(ui), security=sec)
    app.start()
    try:
        base = f"http://127.0.0.1:{app.port}"
        tok = base64.b64encode(b"ticket").decode()
        req = urllib.request.Request(
            f"{base}/", headers={"Authorization": "Negotiate " + tok})
        with urllib.request.urlopen(req) as r:
            assert b"cc-ui" in r.read()
            reply = r.headers["WWW-Authenticate"]
        assert reply == "Negotiate " + base64.b64encode(b"server-reply").decode()
    finally:
        app.stop()


def test_custom_api_urlprefix():
    """webserver.api.urlprefix relocates the REST mount point."""
    cc, backend, cluster = build_stack(num_brokers=4, partitions=8)
    app = CruiseControlApp(cc, port=0, api_urlprefix="/cc/*")
    app.start()
    try:
        base = f"http://127.0.0.1:{app.port}"
        with urllib.request.urlopen(f"{base}/cc/state") as r:
            assert r.status == 200
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{base}/kafkacruisecontrol/state")
        assert ei.value.code == 404
    finally:
        app.stop()


def test_plugin_class_overrides_via_config():
    """Explicit *.class keys reflectively override the mode-derived
    defaults (AbstractConfig.getConfiguredInstance semantics)."""
    from cruise_control_tpu.config.cruise_control_config import CruiseControlConfig
    from cruise_control_tpu.main import build_app
    from cruise_control_tpu.monitor.sample_store import NoopSampleStore
    from cruise_control_tpu.monitor.sampler import SyntheticWorkloadSampler

    cfg = CruiseControlConfig({
        "metric.sampler.class":
            "cruise_control_tpu.monitor.sampler.SyntheticWorkloadSampler",
        "sample.store.class":
            "cruise_control_tpu.monitor.sample_store.NoopSampleStore",
        "anomaly.notifier.class":
            "cruise_control_tpu.detector.notifier.SelfHealingNotifier",
        "min.valid.partition.ratio": 0.25,
    })
    app = build_app(cfg, port=0)
    try:
        runner = app.cc.task_runner
        assert isinstance(runner.sampler, SyntheticWorkloadSampler)
        assert isinstance(runner.sample_store, NoopSampleStore)
        assert app.cc.default_completeness is not None
        assert (app.cc.default_completeness
                .min_monitored_partitions_percentage == 0.25)
    finally:
        app.user_tasks.shutdown()


def test_get_configured_instance_config_passing():
    """Plugin config contract: a declared ``config`` param or a Kafka-style
    ``**configs`` catch-all receives the config; bare classes don't."""
    from cruise_control_tpu.config.config_def import get_configured_instance

    class Declared:
        def __init__(self, config=None):
            self.config = config

    class CatchAll:
        def __init__(self, **configs):
            self.config = configs.get("config")

    class Bare:
        pass

    reg = {"Declared": Declared, "CatchAll": CatchAll, "Bare": Bare}
    cfg = {"k": "v"}
    assert get_configured_instance("Declared", reg, config=cfg).config is cfg
    assert get_configured_instance("CatchAll", reg, config=cfg).config is cfg
    assert get_configured_instance("Bare", reg, config=cfg) is not None


# ----------------------------------------------------------------- OpenAPI


def test_openapi_artifact_current_and_complete():
    """docs/openapi.yaml is generated (scripts/gen_openapi.py) and must match
    the live endpoint tables — the reference ships src/yaml/endpoints/* and
    ResponseTest validates against it; here drift fails the build."""
    import os

    from cruise_control_tpu.servlet.openapi import API_PREFIX, build_spec, render_yaml
    from cruise_control_tpu.servlet.server import GET_ENDPOINTS, POST_ENDPOINTS

    spec = build_spec()
    for endpoint in GET_ENDPOINTS | POST_ENDPOINTS:
        path = f"{API_PREFIX}/{endpoint}"
        assert path in spec["paths"], f"endpoint {endpoint} missing from spec"
        method = "get" if endpoint in GET_ENDPOINTS else "post"
        op = spec["paths"][path][method]
        ref = op["responses"]["200"]["content"]["application/json"]["schema"]
        cname = ref["$ref"].rsplit("/", 1)[-1]
        assert cname in spec["components"]["schemas"]

    artifact = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "docs", "openapi.yaml")
    with open(artifact) as f:
        committed = f.read()
    assert committed == render_yaml(), \
        "docs/openapi.yaml is stale — run scripts/gen_openapi.py"

    # The committed YAML parses and round-trips to the same document.
    import yaml
    assert yaml.safe_load(committed) == spec


def test_ack_endpoints_match_schemas(app):
    """Response-schema checks for the small endpoints the heavier tests
    don't cover (the solving endpoints are validated where they already run:
    rebalance in test_security_schemas, review flow above)."""
    from cruise_control_tpu.servlet.schemas import ENDPOINT_SCHEMAS, validate

    status, body, _ = _get(app, "bootstrap", start="0", end="1")
    assert status == 200
    validate(body, ENDPOINT_SCHEMAS["bootstrap"])

    status, body, _ = _get(app, "train", start="0", end="1e15")
    assert status == 200
    validate(body, ENDPOINT_SCHEMAS["train"])

    status, body, _ = _get(app, "metrics", json="true")
    assert status == 200
    validate(body, ENDPOINT_SCHEMAS["metrics"])

    status, body, _ = _post(app, "pause_sampling", reason="schema-check")
    assert status == 200
    validate(body, ENDPOINT_SCHEMAS["pause_sampling"])
    status, body, _ = _post(app, "resume_sampling", reason="schema-check")
    assert status == 200
    validate(body, ENDPOINT_SCHEMAS["resume_sampling"])

    status, body, _ = _post(app, "stop_proposal_execution")
    assert status == 200
    validate(body, ENDPOINT_SCHEMAS["stop_proposal_execution"])

    status, body, _ = _post(app, "admin",
                            enable_self_healing_for="broker_failure")
    assert status == 200
    validate(body, ENDPOINT_SCHEMAS["admin"])


def test_solving_endpoints_match_operation_schema(app):
    """Every async solving endpoint's completed body is a valid
    OptimizationResult (the shared response schema in docs/openapi.yaml).
    Runs AFTER the rebalance roundtrip in this module, so the goal-stack
    compiles are already cached — each call here is a warm solve."""
    from cruise_control_tpu.servlet.schemas import ENDPOINT_SCHEMAS, validate

    def poll_done(endpoint, **params):
        deadline = time.time() + 150
        task_id = None
        while time.time() < deadline:
            headers = {USER_TASK_HEADER: task_id} if task_id else {}
            status, body, hdrs = _post(app, endpoint, headers=headers, **params)
            task_id = hdrs.get(USER_TASK_HEADER, task_id)
            if status == 200 and "progress" not in body:
                return body
            time.sleep(0.3)
        raise AssertionError(f"{endpoint} never completed")

    for endpoint, params in (
        ("remove_broker", {"brokerid": "3", "dryrun": "true"}),
        ("add_broker", {"brokerid": "3", "dryrun": "true"}),
        ("fix_offline_replicas", {"dryrun": "true"}),
        ("demote_broker", {"brokerid": "1", "dryrun": "true"}),
        ("topic_configuration", {"topic": ".*", "replication_factor": "2",
                                 "dryrun": "true"}),
    ):
        body = poll_done(endpoint, **params)
        validate(body, ENDPOINT_SCHEMAS[endpoint])
