"""Execution observatory tests (docs/EXECUTION.md).

Covers the off-path guarantee (solver jit-cache keys bitwise identical with
the observatory on or off), the provenance stamping + explain rendering, the
flight recorder's EWMA/ETA/inflight bookkeeping, and the joined
provenance-with-live-progress view during a storm-runner execution.
"""

import time

import pytest

from cruise_control_tpu.executor.tasks import (
    ExecutionTask,
    ExecutionTaskState,
    TaskType,
)
from cruise_control_tpu.obsvc.execution import (
    PATHS,
    ExecutionFlightRecorder,
    execution,
    path_histogram,
)
from tests.test_executor import proposal


# ------------------------------------------------------- off-path guarantee


def test_observatory_off_path_cache_keys_bitwise_identical():
    """Acceptance: the observatory is host-side numpy over materialized
    snapshots — flipping it on compiles NOTHING new and perturbs NO existing
    jit-cache key.  (Contrast PR-9's round recorder, which adds separate
    keyed executables; this one must add none at all.)"""
    from cruise_control_tpu.analyzer import GoalOptimizer
    from cruise_control_tpu.analyzer import solver as solver_mod
    from cruise_control_tpu.testing import deterministic as det

    rec = execution()
    prev = rec.enabled
    state, placement, meta = det.unbalanced2().freeze(pad_replicas_to=64,
                                                      pad_brokers_to=8)
    opt = GoalOptimizer(goal_names=["ReplicaDistributionGoal"],
                        solver=solver_mod.GoalSolver())
    solve_keys = lambda: {k for k in opt.solver._round_cache
                          if isinstance(k, tuple) and k and k[0] == "solve"}
    try:
        rec.configure(enabled=False)
        res_off = opt.optimizations(state, placement, meta)
        off_keys = solve_keys()
        assert off_keys
        assert all(p.provenance is None for p in res_off.proposals)

        rec.configure(enabled=True)
        res_on = opt.optimizations(state, placement, meta)
    finally:
        rec.configure(enabled=prev)
        rec.reset()
    assert solve_keys() == off_keys         # bitwise identical, zero new keys
    # Same moves either way; the on-path run stamps lineage onto each.
    assert ({p.topic_partition for p in res_on.proposals}
            == {p.topic_partition for p in res_off.proposals})
    assert res_on.proposals
    for p in res_on.proposals:
        assert p.provenance is not None
        assert p.provenance["path"] in PATHS
        assert p.provenance["goal"] == "ReplicaDistributionGoal"
    # ?explain=true rendering: provenance + histogram only when asked.
    plain = res_on.to_dict()
    assert "proposals" not in plain and "provenancePaths" not in plain
    explained = res_on.to_dict(explain=True)
    hist = explained["provenancePaths"]
    assert sum(hist.values()) == len(res_on.proposals)
    assert all(e["provenance"]["path"] in PATHS
               for e in explained["proposals"])


# --------------------------------------------------- flight recorder units


def _task(i, old, new):
    return ExecutionTask(proposal("T", i, old, new),
                         TaskType.INTER_BROKER_REPLICA_ACTION)


def test_recorder_ewma_and_eta():
    rec = ExecutionFlightRecorder(alpha=0.5)
    tasks = [_task(i, [0, 1], [2, 1]) for i in range(4)]
    rec.begin_batch(tasks, principal="admin", request_id="req-1")
    assert rec.seconds_per_move() == 0.0    # no completions yet

    def complete(task, at_ms):
        rec.on_transition(task, ExecutionTaskState.IN_PROGRESS, at_ms)
        task.transition(ExecutionTaskState.IN_PROGRESS, at_ms)
        rec.on_transition(task, ExecutionTaskState.COMPLETED, at_ms)
        task.transition(ExecutionTaskState.COMPLETED, at_ms)

    complete(tasks[0], 1000.0)
    assert rec.seconds_per_move() == 0.0    # one completion: no dt yet
    complete(tasks[1], 2000.0)              # dt=1.0s seeds the EWMA
    assert rec.seconds_per_move() == pytest.approx(1.0)
    complete(tasks[2], 2500.0)              # dt=0.5: 0.5*0.5 + 0.5*1.0
    assert rec.seconds_per_move() == pytest.approx(0.75)
    assert rec.moves_per_second() == pytest.approx(1 / 0.75)
    assert rec.eta_seconds() == pytest.approx(1 * 0.75)   # 1 move left
    prog = rec.progress()
    assert prog["active"] and prog["throughput"]["completed"] == 3
    assert prog["throughput"]["etaSeconds"] == pytest.approx(0.75, abs=0.01)
    assert prog["batch"]["principal"] == "admin"
    assert prog["batch"]["requestId"] == "req-1"

    summary = rec.end_batch(completed=3, dead=0, aborted=1, moved_mb=1.5)
    assert summary["completed"] == 3 and summary["aborted"] == 1
    assert summary["pathHistogram"] == {"unknown": 4}   # nothing stamped
    # Idle again: every throughput read returns 0 (SLO never burns idle).
    assert rec.seconds_per_move() == 0.0
    assert rec.eta_seconds() == 0.0
    assert rec.inflight_moves() == 0
    assert rec.drain() == [summary]
    assert rec.drain() == []                # drained once
    assert rec.state_summary()["lastBatch"] == summary


def test_recorder_inflight_per_broker():
    rec = ExecutionFlightRecorder()
    t1, t2 = _task(0, [0, 1], [2, 1]), _task(1, [0, 3], [3, 0])
    rec.begin_batch([t1, t2])
    rec.on_transition(t1, ExecutionTaskState.IN_PROGRESS, 0.0)
    t1.transition(ExecutionTaskState.IN_PROGRESS, 0.0)
    rec.on_transition(t2, ExecutionTaskState.IN_PROGRESS, 0.0)
    t2.transition(ExecutionTaskState.IN_PROGRESS, 0.0)
    assert rec.inflight_moves() == 2
    # t1 involves brokers {0,1,2}, t2 {0,3}: broker 0 counts both.
    assert rec.progress()["inflightPerBroker"] == {
        "0": 2, "1": 1, "2": 1, "3": 1}
    rec.on_transition(t1, ExecutionTaskState.COMPLETED, 1.0)
    t1.transition(ExecutionTaskState.COMPLETED, 1.0)
    assert rec.progress()["inflightPerBroker"] == {"0": 1, "3": 1}
    rec.reset()


def test_recorder_tuner_events_and_disabled_noop():
    from cruise_control_tpu.common.metrics import registry
    rec = ExecutionFlightRecorder()
    rec.begin_batch([_task(0, [0, 1], [2, 1])])
    base = registry().counter("Executor.tuner-decreases").count
    rec.record_tuner("decrease", "task-dead", cap=2)
    rec.record_tuner("increase", "batch-drained", cap=3)
    assert registry().counter("Executor.tuner-decreases").count == base + 1
    prog = rec.progress()
    assert prog["batch"]["tunerDecreases"] == 1
    assert prog["batch"]["tunerIncreases"] == 1
    assert [e["signal"] for e in prog["tunerEvents"]] == [
        "task-dead", "batch-drained"]
    assert prog["tunerEvents"][0]["cap"] == 2
    rec.reset()

    off = ExecutionFlightRecorder(enabled=False)
    off.begin_batch([_task(0, [0, 1], [2, 1])])
    off.on_transition(_task(1, [0, 1], [2, 1]),
                      ExecutionTaskState.IN_PROGRESS, 0.0)
    assert off.end_batch(1, 0, 0, 0.0) is None
    assert off.progress() == {"enabled": False, "active": False,
                              "tunerEvents": [], "recentBatches": []}


def test_path_histogram_counts_unknown():
    p1 = proposal("T", 0, [0, 1], [2, 1])
    p2 = proposal("T", 1, [0, 1], [3, 1])
    object.__setattr__(p2, "provenance", {"path": "relax", "goal": "G"})
    assert path_histogram([p1, p2]) == {"unknown": 1, "relax": 1}


# ------------------------------------- joined view during a storm execution


def test_execution_progress_joined_during_storm_execution():
    """Acceptance: GET /execution_progress returns joined provenance + live
    progress + ETA while a storm-runner execution is in flight."""
    from cruise_control_tpu.fuzzsvc.scenario import generate_scenario
    from cruise_control_tpu.fuzzsvc.storm import _wait_idle, build_storm_stack

    rec = execution()
    prev = rec.enabled
    rec.configure(enabled=True)
    rec.reset()
    sc = generate_scenario(3146, kind="exp_skew")
    # Slow each task down (25 backend polls) and pin per-broker concurrency
    # to 1 so the batch drains in many small waves — the poll loop below is
    # guaranteed mid-flight snapshots.
    stack = build_storm_stack(sc, num_brokers=6, partitions=16, rf=2,
                              polls_to_finish=25)
    stack.cc.executor.adjuster.current = 1
    stack.cc.executor.adjuster.max_concurrency = 1
    stack.cc.executor.config.concurrent_leader_movements = 1
    try:
        res = stack.cc.rebalance(dryrun=False)
        assert res.executed
        live, with_eta = None, None
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            prog = rec.progress()
            if prog["active"]:
                live = prog
                tp = prog["throughput"]
                if tp["etaSeconds"] is not None and tp["remaining"] > 0:
                    with_eta = prog
                    break
            elif live is not None:
                break                       # batch ended after we saw it live
            time.sleep(0.001)
        assert live is not None, "never observed the batch in flight"
        assert live["batch"]["total"] == len(live["tasks"])
        hist = live["batch"]["pathHistogram"]
        assert sum(hist.values()) == live["batch"]["total"]
        for t in live["tasks"]:
            assert t["provenance"] is not None          # joined lineage
            assert t["provenance"]["path"] in PATHS
            assert t["state"] in ("pending", "in_progress", "completed",
                                  "aborting", "aborted", "dead")
        if with_eta is not None:            # ≥2 completions observed live
            tp = with_eta["throughput"]
            assert tp["secondsPerMove"] > 0
            assert tp["etaSeconds"] == pytest.approx(
                tp["remaining"] * tp["secondsPerMove"], rel=0.01)
        assert _wait_idle(stack.cc, timeout_s=60.0)
        batches = rec.drain()
        assert batches, "no batch summary recorded"
        last = batches[-1]
        assert last["moves"] == live["batch"]["total"]
        assert last["completed"] + last["dead"] + last["aborted"] > 0
        assert sum(last["pathHistogram"].values()) == last["moves"]
        # The servlet view is this same payload.
        assert rec.state_summary()["lastBatch"]["executionId"] \
            == live["batch"]["executionId"]
    finally:
        stack.cc.anomaly_detector.shutdown()
        rec.configure(enabled=prev)
        rec.reset()
