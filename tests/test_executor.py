"""Executor tests.

Models the reference's ``ExecutionTaskPlannerTest`` / ``ExecutionTaskManagerTest``
and the embedded-broker ``ExecutorTest`` — the FakeClusterBackend +
FakeMetadataBackend pair replaces the embedded ZK/brokers.
"""

import numpy as np
import pytest

from cruise_control_tpu.common.actions import (
    ExecutionProposal,
    ReplicaPlacementInfo,
    TopicPartition,
)
from cruise_control_tpu.common.exceptions import OngoingExecutionError
from cruise_control_tpu.executor.backend import FakeClusterBackend
from cruise_control_tpu.executor.executor import Executor, ExecutorConfig, ExecutorState
from cruise_control_tpu.executor.planner import ExecutionTaskPlanner
from cruise_control_tpu.executor.strategies import (
    PostponeUrpReplicaMovementStrategy,
    PrioritizeLargeReplicaMovementStrategy,
    PrioritizeSmallReplicaMovementStrategy,
)
from cruise_control_tpu.executor.tasks import (
    ExecutionTask,
    ExecutionTaskState,
    TaskType,
)
from cruise_control_tpu.monitor.metadata import (
    BrokerInfo,
    FakeMetadataBackend,
    PartitionInfo,
)


def proposal(topic, part, old, new, size=100.0):
    return ExecutionProposal(
        topic_partition=TopicPartition(topic, part),
        partition_size=size,
        old_leader=ReplicaPlacementInfo(old[0]),
        old_replicas=tuple(ReplicaPlacementInfo(b) for b in old),
        new_replicas=tuple(ReplicaPlacementInfo(b) for b in new),
    )


def _metadata(num_brokers=4):
    brokers = [BrokerInfo(i, rack=str(i % 2), host=f"h{i}") for i in range(num_brokers)]
    parts = [PartitionInfo("T", p, leader=p % num_brokers,
                           replicas=(p % num_brokers, (p + 1) % num_brokers))
             for p in range(8)]
    return FakeMetadataBackend(brokers, parts)


def test_planner_task_types():
    planner = ExecutionTaskPlanner()
    tasks = planner.add_proposals([
        proposal("T", 0, [0, 1], [2, 1]),       # replica move
        proposal("T", 1, [0, 1], [1, 0]),       # pure leadership
    ])
    types = sorted((t.task_type for t in tasks), key=lambda t: t.value)
    assert types == [TaskType.INTER_BROKER_REPLICA_ACTION, TaskType.LEADER_ACTION]


def test_planner_respects_per_broker_caps():
    planner = ExecutionTaskPlanner()
    planner.add_proposals([
        proposal("T", 0, [0, 1], [2, 1]),
        proposal("T", 1, [0, 1], [3, 1]),
        proposal("T", 2, [0, 1], [2, 1]),
    ])
    ready = {b: 1 for b in range(4)}
    batch = planner.inter_broker_tasks(ready, {})
    # Every proposal involves brokers 0 and 1 — cap 1 allows only one task.
    assert len(batch) == 1
    assert len(planner.remaining_inter_broker_tasks) == 2


def test_strategies_order():
    small = proposal("T", 0, [0], [1], size=10)
    large = proposal("T", 1, [0], [1], size=1000)
    t_small = ExecutionTask(small, TaskType.INTER_BROKER_REPLICA_ACTION)
    t_large = ExecutionTask(large, TaskType.INTER_BROKER_REPLICA_ACTION)
    assert PrioritizeLargeReplicaMovementStrategy().order(
        [t_small, t_large])[0] is t_large
    assert PrioritizeSmallReplicaMovementStrategy().order(
        [t_large, t_small])[0] is t_small
    urp = PostponeUrpReplicaMovementStrategy({("T", 1)})
    assert urp.order([t_large, t_small])[0] is t_small


def test_task_state_machine():
    t = ExecutionTask(proposal("T", 0, [0], [1]),
                      TaskType.INTER_BROKER_REPLICA_ACTION)
    t.transition(ExecutionTaskState.IN_PROGRESS, 1.0)
    with pytest.raises(ValueError):
        t.transition(ExecutionTaskState.PENDING)
    t.transition(ExecutionTaskState.COMPLETED, 2.0)
    assert t.done


def test_executor_end_to_end():
    md = _metadata()
    backend = FakeClusterBackend(md, polls_to_finish=2)
    ex = Executor(backend, ExecutorConfig(progress_check_interval_s=0.001))
    props = [
        proposal("T", 0, [0, 1], [2, 1]),
        proposal("T", 1, [1, 2], [3, 2]),
        proposal("T", 2, [2, 3], [3, 2]),       # leadership only
    ]
    ex.execute_proposals(props, wait=True)
    assert ex.state is ExecutorState.NO_TASK_IN_PROGRESS
    # Metadata reflects the new assignments.
    cluster = md.fetch()
    by_tp = {(p.topic, p.partition): p for p in cluster.partitions}
    assert by_tp[("T", 0)].replicas == (2, 1)
    assert by_tp[("T", 1)].replicas == (3, 2)
    assert by_tp[("T", 2)].leader == 3
    summary = ex.tracker.summary()
    assert summary["inter_broker_replica"]["completed"] == 2
    assert summary["leadership"]["completed"] == 1
    assert ex.tracker.finished_data_movement_mb > 0


def test_executor_rejects_concurrent_execution():
    md = _metadata()
    backend = FakeClusterBackend(md, polls_to_finish=50)
    ex = Executor(backend, ExecutorConfig(progress_check_interval_s=0.01))
    ex.execute_proposals([proposal("T", 0, [0, 1], [2, 1])], wait=False)
    with pytest.raises(OngoingExecutionError):
        ex.execute_proposals([proposal("T", 1, [1, 2], [3, 2])])
    ex.user_triggered_stop_execution()
    ex._thread.join(timeout=5)
    assert ex.state is ExecutorState.NO_TASK_IN_PROGRESS


def test_executor_refuses_external_reassignment():
    md = _metadata()
    backend = FakeClusterBackend(md, polls_to_finish=10)
    # Simulate an externally-started reassignment.
    ext = ExecutionTask(proposal("T", 7, [0], [1]),
                        TaskType.INTER_BROKER_REPLICA_ACTION)
    backend.execute_replica_reassignments([ext])
    ex = Executor(backend)
    with pytest.raises(OngoingExecutionError):
        ex.execute_proposals([proposal("T", 0, [0, 1], [2, 1])])


def test_executor_stop_marks_pending_dead():
    md = _metadata()
    backend = FakeClusterBackend(md, polls_to_finish=1000)
    cfg = ExecutorConfig(progress_check_interval_s=0.001,
                         concurrent_partition_movements_per_broker=1)
    ex = Executor(backend, cfg)
    props = [proposal("T", i, [0, 1], [2 + (i % 2), 1]) for i in range(4)]
    ex.execute_proposals(props, wait=False)
    import time
    time.sleep(0.05)
    ex.user_triggered_stop_execution()
    ex._thread.join(timeout=5)
    s = ex.tracker.summary()["inter_broker_replica"]
    assert s.get("aborted", 0) + s.get("dead", 0) >= 1


def test_generating_proposals_guard():
    md = _metadata()
    ex = Executor(FakeClusterBackend(md))
    ex.set_generating_proposals_for_execution(True)
    with pytest.raises(OngoingExecutionError):
        ex.set_generating_proposals_for_execution(True)
    ex.set_generating_proposals_for_execution(False)


def test_throttles_set_and_cleared():
    md = _metadata()
    backend = FakeClusterBackend(md, polls_to_finish=1)
    cfg = ExecutorConfig(progress_check_interval_s=0.001,
                         replication_throttle_bytes_per_s=1_000_000)
    ex = Executor(backend, cfg)
    seen = {}
    orig = backend.set_throttles

    def spy(rate, partitions, brokers=(), proposals=()):
        seen["rate"] = rate
        seen["partitions"] = list(partitions)
        seen["brokers"] = list(brokers)
        orig(rate, partitions, brokers, proposals)

    backend.set_throttles = spy
    ex.execute_proposals([proposal("T", 0, [0, 1], [2, 1])], wait=True)
    assert seen["rate"] == 1_000_000
    assert seen["brokers"] == [0, 1, 2]       # old ∪ new replicas
    assert backend.throttle_rate is None      # cleared after execution


def _action_gauge_values():
    from cruise_control_tpu.common.metrics import registry
    snap = registry().snapshot()
    return {name: rec.get("value") for name, rec in snap.items()
            if name.startswith(("Executor.replica-action-",
                                "Executor.leadership-action-"))}


def test_action_gauges_zero_after_completed_execution():
    """Stale-gauge guard: the per-state action gauges report the live batch
    only, so a finished execution leaves every one of them at zero."""
    md = _metadata()
    backend = FakeClusterBackend(md, polls_to_finish=2)
    ex = Executor(backend, ExecutorConfig(progress_check_interval_s=0.001))
    ex.execute_proposals([
        proposal("T", 0, [0, 1], [2, 1]),
        proposal("T", 2, [2, 3], [3, 2]),       # leadership only
    ], wait=True)
    assert ex.state is ExecutorState.NO_TASK_IN_PROGRESS
    vals = _action_gauge_values()
    assert len(vals) == 10                      # 2 kinds x 5 states
    assert all(v == 0 for v in vals.values()), vals


def test_action_gauges_zero_after_aborted_execution():
    """Aborted/dead tasks stay in the lifetime-cumulative tracker; the
    gauges must not keep exporting them after the batch ends."""
    md = _metadata()
    backend = FakeClusterBackend(md, polls_to_finish=1000)
    cfg = ExecutorConfig(progress_check_interval_s=0.001,
                         concurrent_partition_movements_per_broker=1)
    ex = Executor(backend, cfg)
    props = [proposal("T", i, [0, 1], [2 + (i % 2), 1]) for i in range(4)]
    ex.execute_proposals(props, wait=False)
    import time
    time.sleep(0.05)
    ex.user_triggered_stop_execution()
    ex._thread.join(timeout=5)
    s = ex.tracker.summary()["inter_broker_replica"]
    assert s.get("aborted", 0) + s.get("dead", 0) >= 1   # tracker keeps them
    vals = _action_gauge_values()
    assert all(v == 0 for v in vals.values()), vals       # gauges don't
