"""Resilience layer tests: retry budgets, circuit breaker, reconnecting
admin backend, crash-safe execution journal + startup reconciliation,
backend-down executor pause, solver device-failover, and /health
degraded-mode serving.

Everything here is transport/state-machine level — no solves, no XLA — so
the whole module rides the tier-1 budget.  The storm-with-fault-injection
soak lives at the bottom behind ``@pytest.mark.slow``.
"""

import json
import os
import signal
import threading
import time

import pytest

from cruise_control_tpu import resilience
from cruise_control_tpu.common.metrics import registry
from cruise_control_tpu.executor.backend import FakeClusterBackend
from cruise_control_tpu.executor.broker_simulator import BrokerSimulator
from cruise_control_tpu.executor.executor import (
    Executor,
    ExecutorConfig,
    ExecutorState,
)
from cruise_control_tpu.executor.journal import ExecutionJournal
from cruise_control_tpu.executor.planner import ExecutionTaskPlanner
from cruise_control_tpu.executor.subprocess_backend import (
    BackendCircuitOpenError,
    BackendTransportError,
    SocketClusterBackend,
)
from cruise_control_tpu.executor.tasks import ExecutionTaskState
from cruise_control_tpu.resilience.circuit import CircuitBreaker, CircuitState
from cruise_control_tpu.resilience.failover import is_device_failure
from cruise_control_tpu.resilience.reconnect import ReconnectingBackend
from cruise_control_tpu.resilience.retry import (
    RetryBudgetExhausted,
    RetryPolicy,
    call_with_retry,
)
from tests.test_executor import _metadata, proposal


class _FixedRng:
    def random(self):
        return 0.5  # jitter factor exactly 1.0


# ------------------------------------------------------------------ retry


def test_retry_backoff_sequence_and_success():
    sleeps = []
    clock = [0.0]

    def sleep(s):
        sleeps.append(s)
        clock[0] += s

    calls = [0]

    def fn():
        calls[0] += 1
        if calls[0] < 4:
            raise BackendTransportError("flap")
        return "ok"

    policy = RetryPolicy(max_attempts=4, base_delay_s=0.1, max_delay_s=5.0,
                         multiplier=2.0, jitter=0.5, deadline_s=30.0)
    out = call_with_retry(fn, policy, retry_on=(BackendTransportError,),
                          name="t", rng=_FixedRng(),
                          clock=lambda: clock[0], sleep=sleep)
    assert out == "ok" and calls[0] == 4
    assert sleeps == pytest.approx([0.1, 0.2, 0.4])


def test_retry_budget_exhausted_carries_cause():
    def fn():
        raise BackendTransportError("always")

    policy = RetryPolicy(max_attempts=2, base_delay_s=0.0, deadline_s=30.0)
    with pytest.raises(RetryBudgetExhausted) as ei:
        call_with_retry(fn, policy, retry_on=(BackendTransportError,),
                        name="t", rng=_FixedRng(),
                        clock=lambda: 0.0, sleep=lambda s: None)
    assert isinstance(ei.value.__cause__, BackendTransportError)


def test_retry_deadline_cuts_attempts_short():
    clock = [0.0]
    calls = [0]

    def fn():
        calls[0] += 1
        clock[0] += 20.0  # each attempt burns most of the deadline
        raise BackendTransportError("slow flap")

    policy = RetryPolicy(max_attempts=10, base_delay_s=1.0, deadline_s=30.0)
    with pytest.raises(RetryBudgetExhausted):
        call_with_retry(fn, policy, retry_on=(BackendTransportError,),
                        name="t", rng=_FixedRng(),
                        clock=lambda: clock[0], sleep=lambda s: None)
    assert calls[0] < 10


def test_retry_non_retryable_propagates_immediately():
    calls = [0]

    def fn():
        calls[0] += 1
        raise ValueError("not a transport problem")

    with pytest.raises(ValueError):
        call_with_retry(fn, RetryPolicy(max_attempts=5, base_delay_s=0.0),
                        retry_on=(BackendTransportError,), name="t",
                        rng=_FixedRng(), sleep=lambda s: None)
    assert calls[0] == 1


# ---------------------------------------------------------------- circuit


def test_circuit_closed_open_half_open_reclose():
    clock = [0.0]
    cb = CircuitBreaker("t", failure_threshold=2, reset_timeout_s=10.0,
                        clock=lambda: clock[0])
    assert cb.state is CircuitState.CLOSED and cb.allow()
    cb.record_failure()
    assert cb.state is CircuitState.CLOSED
    cb.record_failure()
    assert cb.state is CircuitState.OPEN and cb.state_value() == 2
    assert not cb.allow()
    clock[0] = 10.0
    assert cb.allow()                      # half-open probe granted
    assert cb.state is CircuitState.HALF_OPEN
    assert not cb.allow()                  # probe budget is 1
    cb.record_success()
    assert cb.state is CircuitState.CLOSED and cb.reclose_count == 1
    assert cb.allow()


def test_circuit_half_open_failure_reopens():
    clock = [0.0]
    cb = CircuitBreaker("t", failure_threshold=1, reset_timeout_s=5.0,
                        clock=lambda: clock[0])
    cb.record_failure()
    assert cb.state is CircuitState.OPEN
    clock[0] = 5.0
    assert cb.allow()
    cb.record_failure()                    # the probe itself failed
    assert cb.state is CircuitState.OPEN and cb.open_count == 2
    clock[0] = 6.0
    assert not cb.allow()                  # timeout restarted


def test_circuit_success_resets_failure_streak():
    cb = CircuitBreaker("t", failure_threshold=3)
    cb.record_failure()
    cb.record_failure()
    cb.record_success()
    cb.record_failure()
    cb.record_failure()
    assert cb.state is CircuitState.CLOSED


# ----------------------------------------------------- reconnecting backend


class _FakeInner:
    """Minimal transport double with the poison/in-progress surface."""

    def __init__(self, fail_times=0):
        self.fail_times = fail_times
        self.poisoned = None
        self.calls = 0

    def in_progress_reassignments(self):
        return {("T", 1)}

    def describe_topics(self):
        self.calls += 1
        if self.fail_times > 0:
            self.fail_times -= 1
            raise BackendTransportError("mid-call death")
        return [{"topic": "T"}]

    def _poison(self, why):
        self.poisoned = why


def test_reconnecting_backend_rebuilds_and_repolls():
    inners = []

    def factory():
        inners.append(_FakeInner(fail_times=1 if not inners else 0))
        return inners[-1]

    rb = ReconnectingBackend(
        factory, policy=RetryPolicy(max_attempts=3, base_delay_s=0.0),
        name="t")
    assert rb.inner_backend() is None      # lazy: no connect at construction
    assert rb.describe_topics() == [{"topic": "T"}]
    # First inner died mid-call, was poisoned+discarded, second succeeded.
    assert len(inners) == 2
    assert inners[0].poisoned is not None
    assert rb.inner_backend() is inners[1]
    # Every (re)connect re-anchors on the cluster's in-flight work.
    assert rb.last_repoll == {("T", 1)}


def test_reconnecting_backend_circuit_opens_and_probe_recovers():
    clock = [0.0]
    down = [True]

    def factory():
        if down[0]:
            raise ConnectionError("peer down")
        return _FakeInner()

    cb = CircuitBreaker("t", failure_threshold=2, reset_timeout_s=5.0,
                        clock=lambda: clock[0])
    rb = ReconnectingBackend(
        factory, policy=RetryPolicy(max_attempts=2, base_delay_s=0.0),
        circuit=cb, name="t")
    with pytest.raises(BackendTransportError):
        rb.describe_topics()
    assert cb.state is CircuitState.OPEN
    # Fast-fail while open: the typed error lets the executor pause.
    with pytest.raises(BackendCircuitOpenError):
        rb.describe_topics()
    assert not rb.probe()                  # circuit still holding the door
    clock[0] = 5.0
    down[0] = False
    assert rb.probe()                      # half-open probe succeeds
    assert cb.state is CircuitState.CLOSED
    assert rb.describe_topics() == [{"topic": "T"}]


# ---------------------------------------------------------------- journal


def _tasks(n=3):
    planner = ExecutionTaskPlanner()
    return list(planner.add_proposals(
        [proposal("T", p, [0, 1], [2, 1]) for p in range(n)]))


def test_journal_crash_replay_and_torn_line(tmp_path):
    path = str(tmp_path / "journal.jsonl")
    j = ExecutionJournal(path)
    t0, t1, t2 = _tasks(3)
    j.begin_batch([t0, t1, t2])
    j.record_transition(t0, ExecutionTaskState.IN_PROGRESS)
    j.record_transition(t0, ExecutionTaskState.COMPLETED)
    j.record_transition(t1, ExecutionTaskState.IN_PROGRESS)
    # Simulated kill -9: no end_batch, and a torn half-record at the tail.
    j.close()
    with open(path, "a", encoding="utf-8") as f:
        f.write('{"event": "transi')
    replay = ExecutionJournal(path).replay()
    assert replay is not None and not replay.complete
    assert len(replay.tasks) == 3
    states = {t.execution_id: t.last_state for t in replay.tasks.values()}
    assert states[t0.execution_id] == "completed"
    assert states[t1.execution_id] == "in_progress"
    assert states[t2.execution_id] == "pending"
    orphan_ids = {t.execution_id for t in replay.orphans()}
    assert orphan_ids == {t1.execution_id, t2.execution_id}
    assert ExecutionJournal(path).lag() == 2


def test_journal_clean_batch_has_no_lag(tmp_path):
    path = str(tmp_path / "journal.jsonl")
    j = ExecutionJournal(path)
    (t0,) = _tasks(1)
    j.begin_batch([t0])
    j.record_transition(t0, ExecutionTaskState.IN_PROGRESS)
    j.record_transition(t0, ExecutionTaskState.COMPLETED)
    j.end_batch({"completed": 1, "dead": 0, "aborted": 0})
    replay = ExecutionJournal(path).replay()
    assert replay.complete and replay.outcome == {"completed": 1, "dead": 0,
                                                  "aborted": 0}
    assert ExecutionJournal(path).lag() == 0


def test_journal_written_during_normal_execution(tmp_path):
    md = _metadata()
    cluster = FakeClusterBackend(md, polls_to_finish=1)
    ex = Executor(cluster, ExecutorConfig(progress_check_interval_s=0.001))
    path = str(tmp_path / "journal.jsonl")
    ex.set_journal(ExecutionJournal(path))
    ex.execute_proposals([proposal("T", 0, [0, 1], [2, 1])], wait=True)
    replay = ExecutionJournal(path).replay()
    assert replay.complete
    assert all(t.terminal for t in replay.tasks.values())
    assert ExecutionJournal(path).lag() == 0


def test_executor_recover_from_journal_reconciles(tmp_path):
    """Crash round-trip: journal written by a 'previous life', reconciled
    against the live backend — re-adopt / complete / roll back — then the
    journal is retired and /state surfaces the summary."""
    md = _metadata()
    cluster = FakeClusterBackend(md, polls_to_finish=500)
    path = str(tmp_path / "journal.jsonl")

    # Previous life: accepted 3 tasks, submitted 2, crashed.
    t0, t1, t2 = _tasks(3)
    j = ExecutionJournal(path)
    j.begin_batch([t0, t1, t2])
    j.record_transition(t0, ExecutionTaskState.IN_PROGRESS)
    j.record_transition(t1, ExecutionTaskState.IN_PROGRESS)
    j.close()                              # kill -9 (no end_batch)
    # t0 is still genuinely moving on the cluster; t1's movement finished
    # while we were down; t2 never went out.
    cluster.execute_replica_reassignments([t0])

    ex = Executor(cluster, ExecutorConfig(progress_check_interval_s=0.001))
    ex.set_journal(ExecutionJournal(path))
    summary = ex.recover_from_journal(adoption_timeout_s=0.05)
    assert summary["status"] == "reconciled"
    assert summary["journaledTasks"] == 3
    assert summary["rolledBack"] == 1      # t2: accepted, never submitted
    assert summary["completed"] == 1       # t1: gone from the cluster
    # t0 is adopted and actively polled, but at 500 polls-to-finish it
    # cannot drain inside the short adoption window.
    assert summary["stillInFlight"] == 1
    assert ex.state_summary()["journalRecovery"]["status"] == "reconciled"
    assert not os.path.exists(path)        # journal retired after reconcile


def test_executor_recovery_keeps_journal_when_backend_down(tmp_path):
    class _DeadBackend:
        def in_progress_reassignments(self):
            raise BackendTransportError("peer down")

    path = str(tmp_path / "journal.jsonl")
    (t0,) = _tasks(1)
    j = ExecutionJournal(path)
    j.begin_batch([t0])
    j.record_transition(t0, ExecutionTaskState.IN_PROGRESS)
    j.close()
    ex = Executor(_DeadBackend(), ExecutorConfig())
    ex.set_journal(ExecutionJournal(path))
    summary = ex.recover_from_journal(adoption_timeout_s=0.05)
    assert summary["status"] == "backend-unavailable"
    assert os.path.exists(path)            # kept for the next restart


# ------------------------------------------------- executor pause / resume


class _CircuitFlakyBackend(FakeClusterBackend):
    """Raises the circuit-open error on every call while ``down`` is set;
    the probe hook reports recovery once it clears."""

    def __init__(self, metadata):
        super().__init__(metadata, polls_to_finish=1)
        self.down = threading.Event()
        self.probes = 0

    def _gate(self):
        if self.down.is_set():
            raise BackendCircuitOpenError("circuit open")

    def execute_replica_reassignments(self, tasks):
        self._gate()
        super().execute_replica_reassignments(tasks)

    def finished(self, task):
        self._gate()
        return super().finished(task)

    def probe(self):
        self.probes += 1
        return not self.down.is_set()


def _wait_for(cond, timeout=5.0):
    deadline = time.monotonic() + timeout
    while not cond():
        if time.monotonic() > deadline:
            return False
        time.sleep(0.002)
    return True

def _state_count(tracker, state):
    return sum(by_state.get(state.value, 0)
               for by_state in tracker.summary().values())


def test_executor_pauses_on_open_circuit_and_resumes():
    md = _metadata()
    backend = _CircuitFlakyBackend(md)
    ex = Executor(backend, ExecutorConfig(progress_check_interval_s=0.001,
                                          task_execution_alert_timeout_s=0.2))
    backend.down.set()
    ex.execute_proposals([proposal("T", 0, [0, 1], [2, 1])], wait=False)
    assert _wait_for(lambda: ex.state is ExecutorState.PAUSED_BACKEND_DOWN), \
        f"never paused (state={ex.state})"
    # Outage far longer than the alert timeout: the pause must protect the
    # batch from rotting to DEAD.
    time.sleep(0.3)
    backend.down.clear()
    assert _wait_for(lambda: not ex.has_ongoing_execution)
    assert backend.probes > 0
    assert _state_count(ex.tracker, ExecutionTaskState.COMPLETED) == 1
    assert _state_count(ex.tracker, ExecutionTaskState.DEAD) == 0


def test_executor_stop_while_paused_marks_batch_dead():
    md = _metadata()
    backend = _CircuitFlakyBackend(md)
    ex = Executor(backend, ExecutorConfig(progress_check_interval_s=0.001))
    backend.down.set()
    ex.execute_proposals([proposal("T", 0, [0, 1], [2, 1])], wait=False)
    assert _wait_for(lambda: ex.state is ExecutorState.PAUSED_BACKEND_DOWN)
    ex.user_triggered_stop_execution()
    assert _wait_for(lambda: not ex.has_ongoing_execution)
    # The popped-but-unsubmitted batch must not leak as forever-PENDING.
    assert _state_count(ex.tracker, ExecutionTaskState.PENDING) == 0


def test_backend_errors_sensor_counts_absorbed_failures():
    md = _metadata()

    class _FlakyPoll(FakeClusterBackend):
        def finished(self, task):
            if not hasattr(self, "_flapped"):
                self._flapped = True
                raise BackendTransportError("one-off flap")
            return super().finished(task)

    backend = _FlakyPoll(md, polls_to_finish=1)
    ex = Executor(backend, ExecutorConfig(progress_check_interval_s=0.001))
    before = registry().counter("Executor.backend-errors").count
    ex.execute_proposals([proposal("T", 0, [0, 1], [2, 1])], wait=True)
    assert registry().counter("Executor.backend-errors").count == before + 1
    assert _state_count(ex.tracker, ExecutionTaskState.COMPLETED) == 1


# ------------------------------------------------------- solver failover


def test_is_device_failure_classification():
    class XlaRuntimeError(Exception):
        pass

    assert is_device_failure(XlaRuntimeError("anything"))
    assert is_device_failure(RuntimeError("DEVICE_LOST: tpu gone"))
    assert is_device_failure(OSError("Socket closed"))
    chained = ValueError("wrapper")
    chained.__cause__ = XlaRuntimeError("inner")
    assert is_device_failure(chained)
    assert not is_device_failure(ValueError("plain bad input"))
    assert not is_device_failure(RuntimeError("ordinary failure"))


def test_solver_cpu_failover_tags_degraded():
    from tests.test_facade import build_stack

    cc, _, _ = build_stack()

    class XlaRuntimeError(Exception):
        pass

    class _FlakyOptimizer:
        def __init__(self):
            self.calls = []

        def optimizations(self, state, placement, meta, options=None,
                          model_generation=None, budget=None):
            self.calls.append(model_generation)
            if len(self.calls) == 1:
                raise XlaRuntimeError("DEVICE_LOST: core dumped")
            return "solved"

    opt = _FlakyOptimizer()
    before = registry().counter(
        "Resilience.solver-cpu-failovers").count
    result, degraded = cc._solve_with_failover(opt, None, None, None, None,
                                               generation=(1, 1))
    assert result == "solved" and degraded
    # The CPU retry must not trust the (possibly poisoned) cache entry.
    assert opt.calls == [(1, 1), None]
    assert cc._solver_degraded_at is not None
    assert registry().counter(
        "Resilience.solver-cpu-failovers").count == before + 1
    assert cc.health()["probes"]["device"]["status"] == "degraded"
    # A clean solve clears the degraded flag.
    result, degraded = cc._solve_with_failover(opt, None, None, None, None,
                                               generation=None)
    assert not degraded and cc._solver_degraded_at is None
    assert cc.health()["probes"]["device"]["status"] == "ready"

    def boom(*a, **k):
        raise ValueError("not device-shaped")

    opt.optimizations = boom
    with pytest.raises(ValueError):
        cc._solve_with_failover(opt, None, None, None, None, None)


def test_solver_failover_invalidates_resident_model():
    """A device failure mid-solve must drop the resident device buffers:
    they live on (or were produced by) the failed backend, so the CPU retry
    rebuilds fresh tensors and later requests full-freeze instead of
    scatter-applying into poisoned memory."""
    from tests.test_facade import build_stack

    class XlaRuntimeError(Exception):
        pass

    cc, _, _ = build_stack()
    cc.proposals()
    s0 = cc.resident.stats()
    assert s0["resident"] and s0["fullFreezes"] == 1

    real = cc.optimizer.optimizations
    calls = {"n": 0}

    def flaky(state, placement, meta, options=None, model_generation=None,
              budget=None):
        calls["n"] += 1
        if calls["n"] == 1:
            raise XlaRuntimeError("DEVICE_LOST: core dumped")
        return real(state, placement, meta, options=options,
                    model_generation=model_generation, budget=budget)

    cc.optimizer.optimizations = flaky
    r = cc.rebalance(dryrun=True)
    assert r.degraded and r.optimizer_result is not None
    s1 = cc.resident.stats()
    assert s1["invalidationReasons"].get("device-failover") == 1
    # The retry's refreeze bypasses the resident cache entirely — no entry
    # survives the failover, and no delta was applied into dead buffers.
    assert not s1["resident"]
    assert s1["deltaApplies"] == s0["deltaApplies"]

    # The next clean request re-establishes residency via a full freeze.
    cc.optimizer.optimizations = real
    cc.proposals()
    s2 = cc.resident.stats()
    assert s2["resident"] and s2["fullFreezes"] == s0["fullFreezes"] + 1


# ----------------------------------------------------------------- health


def test_health_rollup_and_endpoint():
    from cruise_control_tpu.servlet.schemas import HEALTH_SCHEMA, validate
    from cruise_control_tpu.servlet.server import CruiseControlApp
    from tests.test_facade import build_stack

    cc, _, _ = build_stack()
    body = cc.health()
    validate(body, HEALTH_SCHEMA)
    assert body["status"] == "ready"
    assert set(body["probes"]) == {"model", "backend", "device", "journal"}

    app = CruiseControlApp(cc, port=0)
    try:
        status, payload, headers = app.handle("GET", "health", {}, None)
        assert status == 200 and payload["status"] == "ready"

        # Trip the published backend breaker: rollup goes unhealthy, the
        # endpoint 503s with Retry-After, and propose traffic is shed while
        # reads and the stop control still serve.
        cb = CircuitBreaker("backend", failure_threshold=1)
        cb.record_failure()
        resilience.set_backend_circuit(cb)
        try:
            assert cc.health()["status"] == "unhealthy"
            status, payload, headers = app.handle("GET", "health", {}, None)
            assert status == 503 and "Retry-After" in headers
            before = registry().counter(
                "Resilience.admission-rejections").count
            status, payload, headers = app.handle("POST", "rebalance", {},
                                                  None)
            assert status == 503 and "Retry-After" in headers
            assert payload["error"] == "ServiceUnhealthy"
            assert registry().counter(
                "Resilience.admission-rejections").count == before + 1
            status, _, _ = app.handle("GET", "state", {}, None)
            assert status == 200
            status, _, _ = app.handle("POST", "stop_proposal_execution", {},
                                      None)
            assert status == 200
        finally:
            resilience.set_backend_circuit(None)
        status, payload, _ = app.handle("GET", "health", {}, None)
        assert status == 200
    finally:
        app.server.server_close()
        app.user_tasks.shutdown()


def test_health_journal_probe_degraded(tmp_path):
    from tests.test_facade import build_stack

    cc, _, _ = build_stack()
    path = str(tmp_path / "journal.jsonl")
    (t0,) = _tasks(1)
    j = ExecutionJournal(path)
    j.begin_batch([t0])
    j.record_transition(t0, ExecutionTaskState.IN_PROGRESS)
    j.close()                              # crash: orphan left on disk
    cc.executor.set_journal(ExecutionJournal(path))
    health = cc.health()
    assert health["status"] == "degraded"
    assert health["probes"]["journal"]["status"] == "degraded"
    assert health["probes"]["journal"]["lag"] == 1
    cc.executor.recover_from_journal(adoption_timeout_s=0.05)
    assert cc.health()["probes"]["journal"]["status"] == "ready"


def test_health_viewer_role_and_openapi_row():
    from cruise_control_tpu.servlet.openapi import build_spec
    from cruise_control_tpu.servlet.security import Role, required_role

    assert required_role("GET", "health") is Role.VIEWER
    spec = build_spec()
    assert "/kafkacruisecontrol/health" in spec["paths"]
    assert "503" in spec["paths"]["/kafkacruisecontrol/health"]["get"][
        "responses"]


# -------------------------------------------------------- simulator chaos


def test_simulator_chaos_knobs():
    sim = BrokerSimulator()
    assert sim.handle({"op": "chaos", "drop_p": 1.0})["chaos"]["drop_p"] == 1.0
    assert sim.chaos_action("is_done") == "drop"
    sim.handle({"op": "chaos", "drop_p": 0.0, "reset_p": 1.0})
    assert sim.chaos_action("is_done") == "reset"
    # Control-plane ops are immune so chaos stays steerable.
    for op in ("chaos", "auth", "shutdown", "bootstrap"):
        assert sim.chaos_action(op) is None
    sim.handle({"op": "chaos", "reset_p": 0.0})
    assert sim.chaos_action("is_done") is None
    # Seeded: the same seed yields the same decision stream.
    sim.handle({"op": "chaos", "drop_p": 0.5, "seed": 7})
    first = [sim.chaos_action("is_done") for _ in range(16)]
    sim.handle({"op": "chaos", "seed": 7})
    assert [sim.chaos_action("is_done") for _ in range(16)] == first


# -------------------------------------------------- socket e2e reconnect


def test_socket_reconnect_after_simulator_kill():
    """Kill -9 the admin peer mid-session: the reconnecting wrapper rebuilds
    the transport against the respawned peer and the session keeps going."""
    from cruise_control_tpu.fuzzsvc.storm import spawn_simulator

    proc, port = spawn_simulator()
    box = {"port": port}

    def factory():
        return SocketClusterBackend("127.0.0.1", box["port"],
                                    request_timeout_s=2.0)

    rb = ReconnectingBackend(
        factory, policy=RetryPolicy(max_attempts=4, base_delay_s=0.01,
                                    max_delay_s=0.05, deadline_s=10.0),
        circuit=CircuitBreaker("e2e", failure_threshold=50,
                               reset_timeout_s=0.05),
        name="e2e")
    try:
        rb.request("bootstrap", partitions=[
            {"topic": "T", "partition": 0, "replicas": [0, 1], "leader": 0}])
        assert [p["topic"] for p in rb.describe_topics()] == ["T"]
        reconnects = registry().counter(
            "Resilience.backend.reconnects").count

        os.kill(proc.pid, signal.SIGKILL)
        proc.wait(timeout=5)
        proc, box["port"] = spawn_simulator()

        # The first call after the kill rides the retry policy through the
        # dead socket onto the fresh peer (empty state — it's a new sim).
        assert rb.describe_topics() == []
        assert registry().counter(
            "Resilience.backend.reconnects").count > reconnects
        assert rb.last_repoll == set()
    finally:
        rb.close()
        proc.kill()
        proc.wait(timeout=5)


# ----------------------------------------------------------- chaos storm


@pytest.mark.slow
def test_storm_socket_transport_with_fault_injection():
    """The acceptance soak: a storm over the REAL socket transport with
    chaos (latency + drops + resets) armed must converge with a coherent
    audit ring and no lost tasks, and the circuit must be observed opening
    and re-closing."""
    from cruise_control_tpu.fuzzsvc.scenario import generate_scenario
    from cruise_control_tpu.fuzzsvc.storm import build_storm_stack, run_storm

    sc = generate_scenario(205, kind="dead_disks")
    stack = build_storm_stack(
        sc, transport="socket",
        chaos={"delay_p": 0.2, "delay_ms": 5, "drop_p": 0.03,
               "reset_p": 0.03, "seed": 7})
    try:
        report = run_storm(sc, cycles=3, stack=stack)
        assert report.ok, report.problems
        assert report.cycles_run == 3
        # No lost tasks: every journal... every tracked task reached a
        # terminal state (the tracker would otherwise still hold it).
        tracker = stack.cc.executor.tracker
        assert _state_count(tracker, ExecutionTaskState.PENDING) == 0
        assert _state_count(tracker, ExecutionTaskState.IN_PROGRESS) == 0

        # Deterministic circuit exercise: full reset storm → open; disarm →
        # probe until it re-closes.
        stack.backend.request("chaos", reset_p=1.0, drop_p=0.0,
                              delay_p=0.0)
        cb = stack.backend.circuit
        opened = False
        for _ in range(20):
            try:
                stack.backend.describe_topics()
            except BackendCircuitOpenError:
                opened = True
                break
            except BackendTransportError:
                continue  # budget exhausted before the breaker tripped
        assert opened and cb.open_count > 0
        # Disarm chaos over a raw side-channel: while reset_p=1.0 the
        # wrapper's reconnect re-poll gets reset too, so it can never
        # re-establish on its own — exactly the outage the circuit models.
        raw = SocketClusterBackend("127.0.0.1", stack.port,
                                   request_timeout_s=2.0)
        raw.request("chaos", reset_p=0.0)
        raw._poison("side-channel done")   # close() would shut the sim down
        deadline = time.monotonic() + 10.0
        while cb.state is not CircuitState.CLOSED:
            assert time.monotonic() < deadline, "circuit never re-closed"
            stack.backend.probe()
            time.sleep(0.05)
        assert cb.reclose_count > 0
    finally:
        stack.cc.executor.user_triggered_stop_execution(user=False)
        try:
            stack.backend.close()
        finally:
            if stack.proc is not None:
                stack.proc.kill()
                stack.proc.wait(timeout=5)
