"""Model-fidelity observatory tests (docs/MONITORING.md).

Covers the off-path guarantee (solver jit-cache keys bitwise identical with
the recorder on or off), the fingerprint stamping + explain rendering, the
staleness verdict strings and their disabled-by-default thresholds, the
self-healing staleness gate (IGNORED `stale_model` audit entry, fix never
starts, propose traffic serves with an advisory `modelStale` tag), and
`GET /model_quality` serving over HTTP during a storm-runner execution.
"""

import json
import time
import urllib.error
import urllib.request
from types import SimpleNamespace

import pytest

from cruise_control_tpu.obsvc.fidelity import (
    EXTRAPOLATION_KINDS,
    ModelFidelityRecorder,
    fidelity,
)


def _completeness(generation=7, valid_windows=(0, 1, 2, 3, 4),
                  num_entities=10, avg_available=3, avg_adjacent=1,
                  forecast=1, valid_ratio=0.5):
    """A synthetic MetricSampleCompleteness: record_fingerprint reads it
    through getattr, so a namespace with the right fields is enough."""
    return SimpleNamespace(
        generation=generation,
        valid_windows=list(valid_windows),
        num_entity_windows=num_entities * len(valid_windows),
        num_windows_avg_available=avg_available,
        num_windows_avg_adjacent=avg_adjacent,
        num_windows_forecast=forecast,
        valid_entity_ratio=valid_ratio,
    )


# ------------------------------------------------------- off-path guarantee


def test_fidelity_off_path_cache_keys_bitwise_identical():
    """Acceptance: the recorder is host-side bookkeeping over materialized
    completeness output — flipping it on compiles NOTHING new and perturbs
    NO existing jit-cache key; it only stamps host dicts onto results."""
    from cruise_control_tpu.analyzer import GoalOptimizer
    from cruise_control_tpu.analyzer import solver as solver_mod
    from cruise_control_tpu.testing import deterministic as det

    rec = fidelity()
    prev = (rec.enabled, rec.min_valid_partition_ratio, rec.max_age_ms)
    state, placement, meta = det.unbalanced2().freeze(pad_replicas_to=64,
                                                      pad_brokers_to=8)
    opt = GoalOptimizer(goal_names=["ReplicaDistributionGoal"],
                        solver=solver_mod.GoalSolver())
    solve_keys = lambda: {k for k in opt.solver._round_cache
                          if isinstance(k, tuple) and k and k[0] == "solve"}
    try:
        rec.configure(enabled=False)
        res_off = opt.optimizations(state, placement, meta)
        off_keys = solve_keys()
        assert off_keys
        assert res_off.fingerprint is None
        assert all(p.fingerprint is None for p in res_off.proposals)

        rec.configure(enabled=True)
        fp = rec.record_fingerprint(_completeness(generation=42),
                                    window_ms=1000)
        assert fp is not None
        res_on = opt.optimizations(state, placement, meta)
    finally:
        rec.configure(enabled=prev[0], min_valid_partition_ratio=prev[1],
                      max_age_ms=prev[2])
        rec.reset()
    assert solve_keys() == off_keys         # bitwise identical, zero new keys
    # Same moves either way; the on-path run stamps data-quality lineage.
    assert ({p.topic_partition for p in res_on.proposals}
            == {p.topic_partition for p in res_off.proposals})
    assert res_on.proposals
    assert res_on.fingerprint is not None
    assert res_on.fingerprint["generation"] == 42
    for p in res_on.proposals:
        assert p.fingerprint is not None
        assert p.fingerprint["generation"] == 42
    # ?explain=true rendering: fingerprint on the result and each proposal,
    # absent from the plain render.
    plain = res_on.to_dict()
    assert "modelFingerprint" not in plain and "proposals" not in plain
    explained = res_on.to_dict(explain=True)
    assert explained["modelFingerprint"]["generation"] == 42
    assert all(e["modelFingerprint"]["generation"] == 42
               for e in explained["proposals"])


# ------------------------------------------------- fingerprint + verdict units


def test_fingerprint_fields_and_age_recompute():
    now = [1_000_000.0]                     # seconds
    rec = ModelFidelityRecorder(enabled=True, clock=lambda: now[0])
    comp = _completeness(generation=3, valid_windows=(2, 3, 4),
                         num_entities=4, avg_available=2, avg_adjacent=1,
                         forecast=0, valid_ratio=0.75)
    fp = rec.record_fingerprint(comp, window_ms=1000, dead_brokers=[5, 1],
                                capacity_source="StaticCapacityResolver",
                                kind="delta")
    assert fp["generation"] == 3
    assert fp["windowEndMs"] == 5 * 1000    # (max valid window + 1) * window
    assert fp["validWindows"] == 3
    assert fp["validPartitionRatio"] == 0.75
    assert fp["deadBrokers"] == [1, 5]
    assert fp["capacitySource"] == "StaticCapacityResolver"
    assert fp["kind"] == "delta"
    denom = 4 * 3
    assert fp["extrapolatedFraction"] == {
        "AVG_AVAILABLE": round(2 / denom, 6),
        "AVG_ADJACENT": round(1 / denom, 6),
        "FORECAST": 0.0,
    }
    assert set(fp["extrapolatedFraction"]) == set(EXTRAPOLATION_KINDS)
    # ageMs is recomputed at every read against the moving clock.
    age0 = rec.current_fingerprint()["ageMs"]
    now[0] += 7.5
    assert rec.current_fingerprint()["ageMs"] == pytest.approx(
        age0 + 7500.0, abs=1.0)
    assert rec.fingerprint_age_ms() == pytest.approx(age0 + 7500.0, abs=1.0)
    assert rec.state_summary()["modelDeltaApplies"] == 1


def test_staleness_reason_strings_and_disabled_defaults():
    now = [2_000.0]
    rec = ModelFidelityRecorder(enabled=True, clock=lambda: now[0])
    # No fingerprint yet: never stale, even with thresholds set.
    rec.configure(enabled=True, min_valid_partition_ratio=0.9, max_age_ms=1)
    assert rec.staleness_reason() is None
    assert rec.fingerprint_age_ms() == 0.0          # cold boot never burns
    assert rec.invalid_partition_ratio() == 0.0

    rec.record_fingerprint(_completeness(valid_ratio=0.5), window_ms=1000)
    reason = rec.staleness_reason()
    assert reason == "valid-partition-ratio 0.500 < 0.9"
    # Ratio passes -> age threshold takes over (windows ended at 5s, now 2000s).
    rec.configure(enabled=True, min_valid_partition_ratio=0.4,
                  max_age_ms=60_000)
    reason = rec.staleness_reason()
    assert reason.startswith("fingerprint-age ")
    assert reason.endswith("ms > 60000ms")
    # Default thresholds (0.0 / 0) mean the gate is off: same fingerprint,
    # no verdict, and the inverted-validity gauge still reads honestly.
    rec.configure(enabled=True, min_valid_partition_ratio=0.0, max_age_ms=0)
    assert rec.staleness_reason() is None
    assert rec.invalid_partition_ratio() == pytest.approx(0.5)


# ------------------------------------------------------- ingest-side units


def test_on_fetch_counter_and_last_fetch():
    from cruise_control_tpu.common.metrics import registry
    rec = ModelFidelityRecorder(enabled=True, clock=lambda: 12.0)
    base = registry().counter("Monitor.fetched-samples").count
    rec.on_fetch(7, 3)
    assert registry().counter("Monitor.fetched-samples").count == base + 10
    assert rec.quality()["lastFetch"] == {
        "partitionSamples": 7, "brokerSamples": 3, "atMs": 12000.0}


def test_on_fetch_disabled_counts_but_keeps_no_state():
    from cruise_control_tpu.common.metrics import registry
    rec = ModelFidelityRecorder(enabled=False)
    base = registry().counter("Monitor.fetched-samples").count
    rec.on_fetch(4, 1)
    # The fetch HAPPENED — pipeline sensors count regardless; only the
    # recorder's own state stays untouched.
    assert registry().counter("Monitor.fetched-samples").count == base + 5
    assert rec._last_fetch["atMs"] is None


def test_on_dropped_causes_and_unknown_cause_raises():
    from cruise_control_tpu.common.metrics import registry
    rec = ModelFidelityRecorder(enabled=True)
    sensors = {"undecodable": "Monitor.dropped-samples-undecodable",
               "inconsistent": "Monitor.dropped-samples-inconsistent",
               "out_of_order": "Monitor.out-of-order-samples"}
    for cause, sensor in sensors.items():
        base = registry().counter(sensor).count
        rec.on_dropped(cause, count=3)
        assert registry().counter(sensor).count == base + 3
    with pytest.raises(ValueError):
        rec.on_dropped("cosmic_rays")


def test_on_window_close_ring_latency_and_history_event():
    from cruise_control_tpu.common.metrics import registry
    from cruise_control_tpu.obsvc.history import history
    rec = ModelFidelityRecorder(enabled=True)
    base = registry().counter("Monitor.window-closes").count
    rec.on_window_close(4, 1000, now_ms=5250.0)      # window [4000,5000)
    assert registry().counter("Monitor.window-closes").count == base + 1
    ring = rec.quality()["windowQuality"]
    assert ring[-1] == {"window": 4, "windowEndMs": 5000,
                        "closedAtMs": 5250.0, "ingestCommitMs": 250.0}
    # The event-driven history sample landed at the close timestamp.
    pts = history().series("Monitor.window-closes")
    assert [5250.0, float(base + 1)] in pts
    # A close stamped before its own window end clamps latency at zero.
    rec.on_window_close(5, 1000, now_ms=5500.0)
    assert rec.quality()["windowQuality"][-1]["ingestCommitMs"] == 0.0


def test_on_window_close_disabled_still_counts_no_ring():
    from cruise_control_tpu.common.metrics import registry
    rec = ModelFidelityRecorder(enabled=False)
    base = registry().counter("Monitor.window-closes").count
    rec.on_window_close(1, 1000, now_ms=2100.0)
    assert registry().counter("Monitor.window-closes").count == base + 1
    assert rec._windows.maxlen and len(rec._windows) == 0


def test_record_liveness_flap_detection():
    from cruise_control_tpu.common.metrics import registry
    rec = ModelFidelityRecorder(enabled=True)
    counter = registry().counter("Monitor.broker-liveness-flaps")
    base = counter.count
    rec.record_liveness({0: True, 1: True}, now_ms=1.0)
    assert counter.count == base            # first observation: no flap
    rec.record_liveness({0: True, 1: False}, now_ms=2.0)
    assert counter.count == base + 1        # broker 1 flipped
    rec.record_liveness({0: True, 1: False}, now_ms=3.0)
    assert counter.count == base + 1        # steady state: no flap
    rec.record_liveness({0: False, 1: True}, now_ms=4.0)
    assert counter.count == base + 3        # both flipped
    flaps = rec.quality()["livenessFlaps"]
    assert flaps[0] == {"broker": 1, "alive": False, "atMs": 2.0}
    assert {(f["broker"], f["alive"]) for f in flaps[-2:]} == {
        (0, False), (1, True)}


def test_ring_bounds_and_resize_preserves_entries():
    rec = ModelFidelityRecorder(enabled=True, ring_size=4)
    for g in range(6):
        rec.record_fingerprint(_completeness(generation=g), window_ms=1000)
    fps = rec.quality()["recentFingerprints"]
    assert [f["generation"] for f in fps] == [2, 3, 4, 5]   # oldest evicted
    rec.configure(enabled=True, ring_size=8)
    fps = rec.quality()["recentFingerprints"]
    assert [f["generation"] for f in fps] == [2, 3, 4, 5]   # survived resize
    rec.record_fingerprint(_completeness(generation=6), window_ms=1000)
    assert len(rec.quality()["recentFingerprints"]) == 5


def test_record_fingerprint_disabled_returns_none():
    rec = ModelFidelityRecorder(enabled=False)
    assert rec.record_fingerprint(_completeness(), window_ms=1000) is None
    assert rec.current_fingerprint() is None
    assert rec.quality()["fingerprint"] is None


def test_fingerprint_with_no_valid_windows():
    rec = ModelFidelityRecorder(enabled=True, clock=lambda: 100.0)
    fp = rec.record_fingerprint(
        _completeness(valid_windows=(), num_entities=0, avg_available=0,
                      avg_adjacent=0, forecast=0, valid_ratio=0.0),
        window_ms=1000)
    assert fp["windowEndMs"] is None and fp["ageMs"] is None
    assert fp["validWindows"] == 0
    assert rec.fingerprint_age_ms() == 0.0      # ageless, not infinitely old
    # Age threshold cannot fire without a window end; ratio still can.
    rec.configure(enabled=True, max_age_ms=1)
    assert rec.staleness_reason() is None
    rec.configure(enabled=True, min_valid_partition_ratio=0.5)
    assert "valid-partition-ratio" in rec.staleness_reason()


def test_gauge_reads_from_current_fingerprint():
    rec = ModelFidelityRecorder(enabled=True, clock=lambda: 100.0)
    assert rec.valid_partition_ratio() == 0.0
    assert rec.extrapolated_fraction() == 0.0
    rec.record_fingerprint(
        _completeness(num_entities=10, valid_windows=(0, 1), avg_available=4,
                      avg_adjacent=2, forecast=2, valid_ratio=0.8),
        window_ms=1000)
    assert rec.valid_partition_ratio() == pytest.approx(0.8)
    assert rec.invalid_partition_ratio() == pytest.approx(0.2)
    assert rec.extrapolated_fraction() == pytest.approx(8 / 20)


def test_quality_and_state_summary_shapes():
    rec = ModelFidelityRecorder(enabled=True, clock=lambda: 50.0)
    rec.record_fingerprint(_completeness(), window_ms=1000)
    rec.record_fingerprint(_completeness(), window_ms=1000, kind="delta")
    q = rec.quality()
    assert set(q) == {"enabled", "fingerprint", "stale", "thresholds",
                      "windowQuality", "recentFingerprints", "livenessFlaps",
                      "lastFetch"}
    assert q["thresholds"] == {"minValidPartitionRatio": 0.0, "maxAgeMs": 0}
    s = rec.state_summary()
    assert s["modelFreezes"] == 1 and s["modelDeltaApplies"] == 1
    assert s["ringSize"] == 64 and s["fingerprint"]["kind"] == "delta"


def test_reset_clears_all_state():
    rec = ModelFidelityRecorder(enabled=True, clock=lambda: 50.0)
    rec.on_fetch(1, 1)
    rec.on_window_close(0, 1000, now_ms=1100.0)
    rec.record_liveness({0: True}, now_ms=1.0)
    rec.record_liveness({0: False}, now_ms=2.0)
    rec.record_fingerprint(_completeness(), window_ms=1000)
    rec.reset()
    assert rec.current_fingerprint() is None
    q = rec.quality()
    assert q["windowQuality"] == [] and q["livenessFlaps"] == []
    assert q["recentFingerprints"] == [] and q["lastFetch"]["atMs"] is None
    assert rec.state_summary()["modelFreezes"] == 0


def test_sensors_registered_eagerly():
    """The drift guard requires every documented sensor to exist before any
    traffic: register_sensors() ran at import time."""
    from cruise_control_tpu.common.metrics import registry
    snap = registry().snapshot()
    for name in ("Monitor.fingerprint-age-ms", "Monitor.valid-partition-ratio",
                 "Monitor.invalid-partition-ratio",
                 "Monitor.extrapolated-fraction", "Monitor.fetched-samples",
                 "Monitor.stored-samples", "Monitor.out-of-order-samples",
                 "Monitor.dropped-samples-undecodable",
                 "Monitor.dropped-samples-inconsistent",
                 "Monitor.window-closes", "Monitor.broker-liveness-flaps",
                 "Monitor.model-freezes", "Monitor.model-delta-applies",
                 "Monitor.stale-model-gates",
                 "Monitor.ingest-commit-latency-ms"):
        assert name in snap, f"{name} not registered at import"


# --------------------------------------------------------- staleness gate


def test_stale_gate_vetoes_self_healing_but_not_propose_traffic():
    """Acceptance: with a forced-stale model, an anomaly fix dispatch lands
    an IGNORED `stale_model` audit entry (fingerprint attached) and never
    starts, while user propose traffic still serves — tagged modelStale."""
    from cruise_control_tpu.common.metrics import registry
    from cruise_control_tpu.detector.anomalies import (
        GoalViolations,
        SloViolationAnomaly,
    )
    from cruise_control_tpu.obsvc.audit import audit_log
    from tests.test_facade import build_stack

    cc, backend, cluster = build_stack()
    rec = fidelity()
    prev = (rec.enabled, rec.min_valid_partition_ratio, rec.max_age_ms)
    audit_log().clear()
    gate_counter = registry().counter("Monitor.stale-model-gates")
    base_gates = gate_counter.count
    try:
        # Any fingerprint from the synthetic stack is ancient by wall clock
        # (sample windows start at epoch 0), so max_age_ms=1 forces STALE.
        rec.configure(enabled=True, min_valid_partition_ratio=0.0,
                      max_age_ms=1)
        rec.record_fingerprint(_completeness(generation=11), window_ms=1000)
        assert rec.staleness_reason() is not None

        fixed = cc._fix_anomaly(
            GoalViolations(fixable=["ReplicaDistributionGoal"]))
        assert fixed is False                       # the fix never starts
        assert not cc.executor.has_ongoing_execution
        assert gate_counter.count == base_gates + 1
        entries = [e for e in audit_log().entries()
                   if e["anomalyType"] == "GOAL_VIOLATION"]
        assert entries, audit_log().entries()
        entry = entries[-1]
        assert entry["decision"] == "IGNORED"
        assert entry["description"]["reason"] == "stale_model"
        assert "fingerprint-age" in entry["description"]["detail"]
        assert entry["description"]["fingerprint"]["generation"] == 11
        assert entry["outcome"] is None             # no FIX ever recorded

        # SloViolationAnomaly is exempt: no model data behind its fix.  The
        # gate must not veto it (it fails later for unrelated reasons or
        # dispatches normally — here we only assert no stale_model entry).
        cc._fix_anomaly(SloViolationAnomaly(
            objective="solve-time", sensor="GoalOptimizer.x",
            threshold=100.0, worst_value=250.0,
            burn_rate_short=3.0, burn_rate_long=2.0))
        assert gate_counter.count == base_gates + 1     # still just one
        slo_stale = [e for e in audit_log().entries()
                     if e["anomalyType"] == "SLO_VIOLATION"
                     and isinstance(e["description"], dict)
                     and e["description"].get("reason") == "stale_model"]
        assert slo_stale == []

        # Propose traffic is advisory-only: it serves, tagged modelStale.
        r = cc.rebalance(goals=["ReplicaDistributionGoal"], dryrun=True)
        assert r.dryrun and not r.executed
        assert r.model_stale is True
        assert r.to_dict()["modelStale"] is True
        # The solve froze a fresh model, so the result carries its own
        # (still wall-clock-stale) fingerprint.
        assert r.optimizer_result.fingerprint is not None
        state = cc.state()
        mq = state["MonitorState"]["modelQualityState"]
        assert mq["enabled"] and mq["stale"] is not None
        assert state["MonitorState"]["numValidWindows"] == 5
    finally:
        rec.configure(enabled=prev[0], min_valid_partition_ratio=prev[1],
                      max_age_ms=prev[2])
        rec.reset()
        audit_log().clear()
        cc.anomaly_detector.shutdown()


# ------------------------------------- /model_quality during a storm cycle


def _http_get(port, endpoint):
    url = f"http://127.0.0.1:{port}/kafkacruisecontrol/{endpoint}"
    try:
        with urllib.request.urlopen(url) as r:
            return r.status, json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode())


def test_model_quality_served_during_storm_cycle():
    """Acceptance: GET /model_quality answers 404 while disabled and serves
    the fingerprint + window-quality payload over HTTP while a storm-runner
    execution is in flight."""
    from cruise_control_tpu.fuzzsvc.scenario import generate_scenario
    from cruise_control_tpu.fuzzsvc.storm import _wait_idle, build_storm_stack
    from cruise_control_tpu.servlet.server import CruiseControlApp

    rec = fidelity()
    prev = (rec.enabled, rec.min_valid_partition_ratio, rec.max_age_ms)
    sc = generate_scenario(4146, kind="exp_skew")
    stack = build_storm_stack(sc, num_brokers=6, partitions=16, rf=2,
                              polls_to_finish=10)
    stack.cc.executor.adjuster.current = 1
    stack.cc.executor.adjuster.max_concurrency = 1
    stack.cc.executor.config.concurrent_leader_movements = 1
    app = CruiseControlApp(stack.cc, port=0)
    app.start()
    try:
        rec.configure(enabled=False)
        status, body = _http_get(app.port, "model_quality")
        assert status == 404 and "disabled" in body["error"]

        rec.configure(enabled=True, min_valid_partition_ratio=0.0,
                      max_age_ms=0)
        res = stack.cc.rebalance(dryrun=False)
        assert res.executed
        solved_fp = res.optimizer_result.fingerprint
        assert solved_fp is not None                # freeze stamped the solve

        live = None
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            if not stack.cc.executor.has_ongoing_execution:
                break
            status, body = _http_get(app.port, "model_quality")
            assert status == 200
            live = body
            time.sleep(0.001)
        assert live is not None, "never polled mid-execution"
        assert live["enabled"] is True
        assert live["stale"] is None                # thresholds at defaults
        fp = live["fingerprint"]
        assert fp is not None
        assert fp["generation"] == solved_fp["generation"]
        assert fp["validWindows"] > 0
        assert set(fp["extrapolatedFraction"]) == set(EXTRAPOLATION_KINDS)
        assert live["recentFingerprints"], "freeze not in the ring"
        assert live["thresholds"] == {"minValidPartitionRatio": 0.0,
                                      "maxAgeMs": 0}

        assert _wait_idle(stack.cc, timeout_s=60.0)
        # The executor journaled the generation it acted on (joined lineage).
        status, body = _http_get(app.port, "state")
        assert status == 200
        mq = body["MonitorState"]["modelQualityState"]
        assert mq["enabled"] and mq["modelFreezes"] >= 1
        assert mq["fingerprint"]["generation"] == solved_fp["generation"]
    finally:
        app.stop()
        stack.cc.anomaly_detector.shutdown()
        rec.configure(enabled=prev[0], min_valid_partition_ratio=prev[1],
                      max_age_ms=prev[2])
        rec.reset()
