"""Out-of-process executor driver tests.

The integration analog of the reference's embedded-broker ``ExecutorTest``
(``CCKafkaIntegrationTestHarness`` + real AdminClient): a full rebalance runs
executor → SubprocessClusterBackend → broker_simulator PROCESS, verifying
movement application, batching caps, throttle set/clear
(ReplicationThrottleHelper.java:29-321 key names), and dead-task handling
when a broker never completes its movement.
"""

import json
import subprocess
import sys

import pytest

from cruise_control_tpu.common.actions import (
    ExecutionProposal,
    ReplicaPlacementInfo,
    TopicPartition,
)
from cruise_control_tpu.executor.broker_simulator import (
    BrokerSimulator,
    FOLLOWER_THROTTLED_RATE,
    FOLLOWER_THROTTLED_REPLICAS,
    LEADER_THROTTLED_RATE,
    LEADER_THROTTLED_REPLICAS,
)
from cruise_control_tpu.executor.executor import Executor, ExecutorConfig
from cruise_control_tpu.executor.subprocess_backend import (
    BackendTransportError,
    SubprocessClusterBackend,
)
from cruise_control_tpu.executor.tasks import ExecutionTaskState, TaskType


def proposal(topic, part, old, new, size=100.0, old_dirs=None, new_dirs=None):
    old_dirs = old_dirs or [None] * len(old)
    new_dirs = new_dirs or [None] * len(new)
    return ExecutionProposal(
        topic_partition=TopicPartition(topic, part),
        partition_size=size,
        old_leader=ReplicaPlacementInfo(old[0], old_dirs[0]),
        old_replicas=tuple(ReplicaPlacementInfo(b, d)
                           for b, d in zip(old, old_dirs)),
        new_replicas=tuple(ReplicaPlacementInfo(b, d)
                           for b, d in zip(new, new_dirs)),
    )


def bootstrap_partitions():
    """4 brokers; T-0..T-3 on (p%4, (p+1)%4)."""
    return [{"topic": "T", "partition": p,
             "replicas": [p % 4, (p + 1) % 4], "leader": p % 4}
            for p in range(4)]


@pytest.fixture
def backend():
    b = SubprocessClusterBackend.spawn(bootstrap_partitions(),
                                       polls_to_finish=2)
    yield b
    b.close()


def test_simulator_unit_roundtrip():
    """The simulator itself, in-process: movement lifecycle + config ops."""
    sim = BrokerSimulator(polls_to_finish=2)
    sim.handle({"op": "bootstrap", "partitions": bootstrap_partitions()})
    sim.handle({"op": "alter_partition_reassignments",
                "reassignments": [{"topic": "T", "partition": 0,
                                   "replicas": [2, 1]}]})
    assert sim.handle({"op": "list_partition_reassignments"})[
        "reassignments"] == [{"topic": "T", "partition": 0}]
    assert sim.handle({"op": "is_done", "topic": "T", "partition": 0})["done"] is False
    assert sim.handle({"op": "is_done", "topic": "T", "partition": 0})["done"] is True
    state = sim.partitions[("T", 0)]
    assert state["replicas"] == [2, 1]
    # Old leader (0) was removed → first new replica leads.
    assert state["leader"] == 2
    # Unknown partition errors instead of inventing state.
    resp = sim.handle({"op": "alter_partition_reassignments",
                       "reassignments": [{"topic": "X", "partition": 9,
                                          "replicas": [0]}]})
    assert not resp["ok"] and "unknown partition" in resp["error"]


def test_full_rebalance_through_subprocess(backend):
    """Executor drives replica moves + leadership through the child process;
    final assignments in the CHILD match the proposals."""
    proposals = [
        proposal("T", 0, [0, 1], [2, 1]),        # replica move 0 -> 2
        proposal("T", 1, [1, 2], [3, 2]),        # replica move 1 -> 3
        proposal("T", 2, [2, 3], [3, 2]),        # pure leadership 2 -> 3
    ]
    ex = Executor(backend, ExecutorConfig(progress_check_interval_s=0.01))
    ex.execute_proposals(proposals, wait=True)

    final = {(d["topic"], d["partition"]): d for d in backend.describe_topics()}
    assert final[("T", 0)]["replicas"] == [2, 1]
    assert final[("T", 1)]["replicas"] == [3, 2]
    assert final[("T", 2)]["leader"] == 3
    done = ex.tracker.count(TaskType.INTER_BROKER_REPLICA_ACTION,
                            ExecutionTaskState.COMPLETED)
    assert done >= 2


def test_throttles_set_and_cleared_through_subprocess(backend):
    """Rate configs appear on involved brokers and replica lists on involved
    topics during execution, with the reference's exact key names, and are
    removed afterwards — while operator-set values on INVOLVED entities are
    preserved (rates not overwritten, replica lists merged then restored),
    per ReplicationThrottleHelper's merge/restore semantics."""
    backend.request("incremental_alter_configs", entity_type="broker",
                    entity=3, ops=[{"name": LEADER_THROTTLED_RATE,
                                    "value": "12345"}])
    # Operator throttles on INVOLVED entities: broker 0's leader rate and an
    # operator entry in topic T's leader replica list.
    backend.request("incremental_alter_configs", entity_type="broker",
                    entity=0, ops=[{"name": LEADER_THROTTLED_RATE,
                                    "value": "777"}])
    backend.request("incremental_alter_configs", entity_type="topic",
                    entity="T", ops=[{"name": LEADER_THROTTLED_REPLICAS,
                                      "value": "0:9"}])
    ex = Executor(backend, ExecutorConfig(progress_check_interval_s=0.01,
                                          replication_throttle_bytes_per_s=1000))
    ex.execute_proposals([proposal("T", 0, [0, 1], [2, 1])], wait=True)

    log = backend.stats()["config_log"]
    # All values ever SET per key (cleanup restores are set ops too, so the
    # merged execution-time value is asserted via membership, not last-wins).
    set_values = {}
    for e in log:
        if e.get("op", "set") != "delete":
            set_values.setdefault(
                (e["entity_type"], str(e["entity"]), e["name"]),
                []).append(e.get("value"))
    set_entries = set_values
    # Brokers 1,2 get both rates; broker 0's leader rate was operator-set so
    # only its follower rate is added.
    for b in ("1", "2"):
        assert ("broker", b, LEADER_THROTTLED_RATE) in set_entries
        assert ("broker", b, FOLLOWER_THROTTLED_RATE) in set_entries
    assert ("broker", "0", FOLLOWER_THROTTLED_RATE) in set_entries
    # Leader list = operator entry + OLD replicas (serve catch-up reads);
    # follower list = the ADDING replica (issues the catch-up fetch).
    assert "0:9,0:0,0:1" in \
        set_entries[("topic", "T", LEADER_THROTTLED_REPLICAS)]
    assert "0:2" in set_entries[("topic", "T", FOLLOWER_THROTTLED_REPLICAS)]

    # Cleanup: our configs gone, operator values restored exactly.
    for b in (1, 2):
        cfg = backend.request("describe_configs", entity_type="broker",
                              entity=b)["configs"]
        assert LEADER_THROTTLED_RATE not in cfg, cfg
    cfg0 = backend.request("describe_configs", entity_type="broker",
                           entity=0)["configs"]
    assert cfg0[LEADER_THROTTLED_RATE] == "777"
    assert FOLLOWER_THROTTLED_RATE not in cfg0
    cfg3 = backend.request("describe_configs", entity_type="broker",
                           entity=3)["configs"]
    assert cfg3[LEADER_THROTTLED_RATE] == "12345"
    cfg_t = backend.request("describe_configs", entity_type="topic",
                            entity="T")["configs"]
    assert cfg_t.get(LEADER_THROTTLED_REPLICAS) == "0:9"
    assert FOLLOWER_THROTTLED_REPLICAS not in cfg_t


def test_batching_respects_movement_cap(backend):
    """Per-broker concurrency 1: the child must never see more than one
    in-flight movement per broker."""
    proposals = [proposal("T", p, [p % 4, (p + 1) % 4],
                          [(p + 2) % 4, (p + 1) % 4]) for p in range(4)]
    ex = Executor(backend, ExecutorConfig(
        progress_check_interval_s=0.01,
        concurrent_partition_movements_per_broker=1))
    ex.execute_proposals(proposals, wait=True)
    per_broker = backend.stats()["max_inflight_per_broker"]
    assert per_broker and all(n <= 1 for n in per_broker.values()), per_broker


def test_logdir_moves_through_subprocess():
    parts = [{"topic": "T", "partition": 0, "replicas": [0, 1], "leader": 0,
              "logdirs": {"0": 0, "1": 0}}]
    backend = SubprocessClusterBackend.spawn(parts, polls_to_finish=2)
    try:
        p = proposal("T", 0, [0, 1], [0, 1], old_dirs=[0, 0], new_dirs=[1, 0])
        ex = Executor(backend, ExecutorConfig(progress_check_interval_s=0.01))
        ex.execute_proposals([p], wait=True)
        final = backend.describe_topics()[0]
        assert final["logdirs"]["0"] == 1
        assert ex.tracker.count(TaskType.INTRA_BROKER_REPLICA_ACTION,
                                ExecutionTaskState.COMPLETED) == 1
    finally:
        backend.close()


def test_dead_task_on_failed_broker(backend):
    """A movement onto a failed broker never completes; the executor's
    alert timeout marks it DEAD and the rest of the batch still lands."""
    backend.request("fail_broker", broker=3)
    proposals = [
        proposal("T", 0, [0, 1], [2, 1]),        # healthy
        proposal("T", 1, [1, 2], [3, 2]),        # 3 is down -> stuck
    ]
    ex = Executor(backend, ExecutorConfig(progress_check_interval_s=0.01,
                                          task_execution_alert_timeout_s=0.3))
    ex.execute_proposals(proposals, wait=True)
    assert ex.tracker.count(TaskType.INTER_BROKER_REPLICA_ACTION,
                            ExecutionTaskState.COMPLETED) == 1
    assert ex.tracker.count(TaskType.INTER_BROKER_REPLICA_ACTION,
                            ExecutionTaskState.DEAD) == 1
    final = {(d["topic"], d["partition"]): d for d in backend.describe_topics()}
    assert final[("T", 0)]["replicas"] == [2, 1]
    assert final[("T", 1)]["replicas"] == [1, 2]   # unchanged


def test_dead_peer_surfaces_as_timeout_then_dead_tasks():
    """Killing the child mid-execution: submissions raise, progress polls
    report unfinished, and the executor converges with DEAD tasks instead of
    hanging."""
    backend = SubprocessClusterBackend.spawn(bootstrap_partitions(),
                                             polls_to_finish=50)
    ex = Executor(backend, ExecutorConfig(progress_check_interval_s=0.01,
                                          task_execution_alert_timeout_s=0.3))
    ex.execute_proposals([proposal("T", 0, [0, 1], [2, 1])], wait=False)
    backend.proc.kill()
    ex._thread.join(timeout=10)
    assert not ex._thread.is_alive()
    assert ex.tracker.count(TaskType.INTER_BROKER_REPLICA_ACTION,
                            ExecutionTaskState.DEAD) == 1
    with pytest.raises(BackendTransportError):
        backend.request("ping")


def test_throttle_setup_failure_aborts_with_dead_tasks(backend):
    """A peer failure at throttle-setup time must abort the execution with
    the planned tasks marked DEAD — not kill the thread with tasks stuck
    PENDING.  (A peer dead BEFORE start is caller-visible instead: the
    pre-start external-reassignment check raises, see below.)"""
    def broken(rate, partitions, brokers=(), proposals=()):
        raise BackendTransportError("peer write failed mid-setup")

    backend.set_throttles = broken
    ex = Executor(backend, ExecutorConfig(progress_check_interval_s=0.01,
                                          replication_throttle_bytes_per_s=1000))
    ex.execute_proposals([proposal("T", 0, [0, 1], [2, 1])], wait=True)
    assert ex.tracker.count(TaskType.INTER_BROKER_REPLICA_ACTION,
                            ExecutionTaskState.DEAD) == 1
    assert ex.tracker.count(TaskType.INTER_BROKER_REPLICA_ACTION,
                            ExecutionTaskState.PENDING) == 0
    # Nothing moved in the child.
    final = {(d["topic"], d["partition"]): d for d in backend.describe_topics()}
    assert final[("T", 0)]["replicas"] == [0, 1]


def test_dead_peer_before_start_is_caller_visible():
    """execute_proposals' pre-start in-flight check runs on the CALLER
    thread; a peer that is already gone surfaces there as an exception, with
    no tasks enqueued (Executor.java caller-facing sanity failures)."""
    backend = SubprocessClusterBackend.spawn(bootstrap_partitions())
    backend.proc.kill()
    backend.proc.wait(timeout=5)
    ex = Executor(backend, ExecutorConfig(progress_check_interval_s=0.01))
    with pytest.raises(BackendTransportError):
        ex.execute_proposals([proposal("T", 0, [0, 1], [2, 1])], wait=True)
    for state in ExecutionTaskState:
        assert ex.tracker.count(TaskType.INTER_BROKER_REPLICA_ACTION,
                                state) == 0


def test_dead_peer_during_leadership_marks_dead():
    """A peer that dies around a leadership election must not hang the
    executor in LEADER_MOVEMENT forever: either the submit fails (dead-batch
    path) or the progress polls never finish (alert-timeout path) — both
    must converge to a DEAD task and a finished thread."""
    backend = SubprocessClusterBackend.spawn(bootstrap_partitions(),
                                             polls_to_finish=50)
    ex = Executor(backend, ExecutorConfig(progress_check_interval_s=0.01,
                                          task_execution_alert_timeout_s=0.3))
    ex.execute_proposals([proposal("T", 2, [2, 3], [3, 2])], wait=False)
    backend.proc.kill()
    ex._thread.join(timeout=15)
    assert not ex._thread.is_alive()
    assert ex.tracker.count(TaskType.LEADER_ACTION,
                            ExecutionTaskState.DEAD) == 1


def test_full_rebalance_over_tcp_socket():
    """The network-facing driver: the same rebalance rides a real TCP
    socket to a listener peer (broker_simulator --listen)."""
    from cruise_control_tpu.executor.subprocess_backend import (
        SocketClusterBackend,
    )
    backend = SocketClusterBackend.spawn_networked(bootstrap_partitions(),
                                                   polls_to_finish=2)
    try:
        ex = Executor(backend, ExecutorConfig(progress_check_interval_s=0.01))
        ex.execute_proposals([proposal("T", 0, [0, 1], [2, 1]),
                              proposal("T", 2, [2, 3], [3, 2])], wait=True)
        final = {(d["topic"], d["partition"]): d
                 for d in backend.describe_topics()}
        assert final[("T", 0)]["replicas"] == [2, 1]
        assert final[("T", 2)]["leader"] == 3
    finally:
        backend.close()


def test_simulator_main_stdio_roundtrip():
    """The __main__ stdio framing itself (bad json, shutdown rc=0)."""
    proc = subprocess.Popen(
        [sys.executable, "-m", "cruise_control_tpu.executor.broker_simulator"],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True)
    try:
        proc.stdin.write("this is not json\n")
        proc.stdin.write(json.dumps({"id": 1, "op": "ping"}) + "\n")
        proc.stdin.write(json.dumps({"id": 2, "op": "shutdown"}) + "\n")
        proc.stdin.flush()
        lines = [json.loads(proc.stdout.readline()) for _ in range(3)]
        assert lines[0]["ok"] is False
        assert lines[1] == {"id": 1, "ok": True}
        assert lines[2] == {"id": 2, "ok": True}
        assert proc.wait(timeout=5) == 0
    finally:
        if proc.poll() is None:
            proc.kill()


def test_offline_logdir_detection_through_subprocess(backend):
    """describeLogDirs parity: a logdir failed in the broker-simulator
    process surfaces through the backend query and fires DiskFailures in
    the detector (DiskFailureDetector.java:1-118)."""
    from cruise_control_tpu.detector.detectors import DiskFailureDetector

    assert backend.offline_logdirs() == {}
    det = DiskFailureDetector(backend.offline_logdirs)
    assert det.detect() == []
    backend.request("fail_logdir", broker=2, logdir=1)
    backend.request("fail_logdir", broker=2, logdir=0)
    assert backend.offline_logdirs() == {2: [0, 1]}
    anomalies = det.detect()
    assert len(anomalies) == 1
    assert anomalies[0].failed_disks == {2: [0, 1]}
    backend.request("restore_logdir", broker=2, logdir=0)
    backend.request("restore_logdir", broker=2, logdir=1)
    assert det.detect() == []


def test_facade_disk_failure_detector_reads_executor_backend():
    """The assembled service's disk-failure detector polls the executor's
    cluster backend, not a stub."""
    from tests.test_facade import build_stack

    cc, backend, cluster = build_stack(num_brokers=4, partitions=8)
    cc.executor.backend.offline_disks = {1: [0]}
    from cruise_control_tpu.detector.anomalies import AnomalyType
    det = cc.anomaly_detector.detectors[AnomalyType.DISK_FAILURE]
    anomalies = det.detect()
    assert len(anomalies) == 1 and anomalies[0].failed_disks == {1: [0]}


def test_service_assembly_connects_socket_admin_backend():
    """executor.admin.backend.address through build_app: the assembled
    service's executor drives a NETWORK admin peer (broker_simulator
    --listen), not the in-process fake."""
    import subprocess as sp
    import sys

    from cruise_control_tpu.config.cruise_control_config import (
        CruiseControlConfig,
    )
    from cruise_control_tpu.executor.subprocess_backend import (
        SocketClusterBackend,
    )
    from cruise_control_tpu.main import build_app
    from cruise_control_tpu.resilience import ReconnectingBackend

    proc = sp.Popen(
        [sys.executable, "-m",
         "cruise_control_tpu.executor.broker_simulator", "--listen", "0"],
        stdout=sp.PIPE, stderr=sp.DEVNULL, text=True)
    try:
        import json as _json
        import select as _select
        ready, _, _ = _select.select([proc.stdout], [], [], 20.0)
        assert ready, "broker_simulator printed no listen banner in 20s"
        port = int(_json.loads(proc.stdout.readline())["listening"])
        cfg = CruiseControlConfig(
            {"executor.admin.backend.address": f"127.0.0.1:{port}"})
        app = build_app(cfg, port=0)
        try:
            admin = app.cc.executor.backend
            # build_app wraps the socket transport in the reconnecting/
            # circuit-breaking layer by default.
            assert isinstance(admin, ReconnectingBackend)
            # The executor's queries cross the real socket.
            assert admin.in_progress_reassignments() == set()
            assert isinstance(admin.inner_backend(), SocketClusterBackend)
            assert admin.offline_logdirs() == {}
            admin.request("fail_logdir", broker=1, logdir=0)
            assert admin.offline_logdirs() == {1: [0]}
            admin.close()
        finally:
            app.user_tasks.shutdown()
    finally:
        proc.kill()
        proc.wait()


def test_socket_backend_shared_secret_auth(tmp_path):
    """Authenticated admin listener (the role Kafka SASL plays for the
    reference's AdminClient edge): the right token works, a missing or
    wrong token is rejected before any admin op executes."""
    import socket

    from cruise_control_tpu.executor.subprocess_backend import (
        SocketClusterBackend,
    )

    token_file = tmp_path / "admin.secret"
    token_file.write_text("s3cret-token\n")
    backend = SocketClusterBackend.spawn_networked(
        bootstrap_partitions(), polls_to_finish=1,
        auth_token_file=str(token_file), auth_secret="s3cret-token")
    port = backend._sock.getpeername()[1]
    try:
        assert len(backend.describe_topics()) == 4   # authed stream works
        # Release the (serial) listener without shutting the simulator down
        # (the makefile streams hold io-refs: the fd only really closes — and
        # the server only sees EOF — once they are closed too).
        backend._rstream.close()
        backend._wstream.close()
        backend._sock.close()

        def raw_exchange(payload: bytes) -> dict:
            with socket.create_connection(("127.0.0.1", port),
                                          timeout=10) as s:
                s.sendall(payload)
                return json.loads(s.makefile("r").readline())

        # Wrong token: one error frame, disconnected.
        resp = raw_exchange(b'{"id": 1, "op": "auth", "token": "nope"}\n')
        assert resp["ok"] is False and "auth" in resp["error"]
        # No auth at all: the first admin op is rejected, not executed.
        resp = raw_exchange(b'{"id": 1, "op": "describe_topics"}\n')
        assert resp["ok"] is False and "auth" in resp["error"]

        # Rejections cost nothing: a correctly-authed reconnect still sees
        # the bootstrapped cluster state.
        again = SocketClusterBackend("127.0.0.1", port,
                                     auth_secret="s3cret-token")
        assert len(again.describe_topics()) == 4
        again.proc = backend.proc        # let close() reap the child
        backend.proc = None
        again.close()
    finally:
        backend.close()


@pytest.mark.skipif(__import__("shutil").which("openssl") is None,
                    reason="openssl CLI not available")
def test_socket_backend_tls(tmp_path):
    """TLS admin listener: a CA-pinned client completes admin ops; a
    plaintext client cannot speak to it (and does not crash the listener)."""
    from cruise_control_tpu.executor.subprocess_backend import (
        BackendTransportError,
        SocketClusterBackend,
    )

    cert, key = tmp_path / "cert.pem", tmp_path / "key.pem"
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", str(key), "-out", str(cert), "-days", "1",
         "-subj", "/CN=localhost"],
        check=True, capture_output=True)
    backend = SocketClusterBackend.spawn_networked(
        bootstrap_partitions(), polls_to_finish=1,
        ssl_cert=str(cert), ssl_key=str(key), ssl_cafile=str(cert))
    port = backend._sock.getpeername()[1]
    try:
        assert len(backend.describe_topics()) == 4   # TLS stream works
        backend._rstream.close()                     # release the listener
        backend._wstream.close()
        backend._sock.close()

        with pytest.raises(BackendTransportError):
            plain = SocketClusterBackend("127.0.0.1", port,
                                         request_timeout_s=5.0)
            plain.describe_topics()

        # The failed handshake did not kill the listener.
        again = SocketClusterBackend("127.0.0.1", port,
                                     ssl_cafile=str(cert))
        assert len(again.describe_topics()) == 4
        again.proc = backend.proc
        backend.proc = None
        again.close()
    finally:
        backend.close()
