"""Multi-device sharding tests (8 virtual CPU devices via conftest).

SURVEY §5: the production solver must run sharded over the replica axis of a
``jax.sharding.Mesh`` with XLA-inserted collectives, and scenario batches over
a scenario axis — these tests assert PARITY between the sharded and
single-device solves on the same snapshot.
"""

import jax
import numpy as np

from cruise_control_tpu.analyzer import GoalOptimizer
from cruise_control_tpu.parallel import make_solver_mesh
from cruise_control_tpu.testing import random_cluster as rc

GOALS = ["RackAwareGoal", "ReplicaCapacityGoal", "DiskCapacityGoal",
         "NetworkInboundUsageDistributionGoal", "ReplicaDistributionGoal"]


def _cluster():
    props = rc.ClusterProperties(num_brokers=16, num_racks=4, num_topics=24,
                                 num_replicas=2048, seed=5)
    # The replica axis must divide the mesh's replica dimension (production
    # freeze() pads to power-of-two size classes; mirror that here).
    return rc.generate(props, pad_replicas_to=2048)


def test_mesh_shapes():
    mesh = make_solver_mesh(8)
    assert mesh.shape == {"scenario": 1, "replica": 8}
    mesh = make_solver_mesh(8, scenario_parallelism=4)
    assert mesh.shape == {"scenario": 4, "replica": 2}


def test_sharded_solver_parity():
    """Replica-sharded production solve == single-device solve."""
    state, placement, meta = _cluster()
    base = GoalOptimizer(goal_names=GOALS).optimizations(state, placement, meta)

    mesh = make_solver_mesh(8)
    sharded = GoalOptimizer(goal_names=GOALS, mesh=mesh).optimizations(
        state, placement, meta)

    for b, s in zip(base.goal_infos, sharded.goal_infos):
        assert b.goal_name == s.goal_name
        assert s.violated_brokers_after == b.violated_brokers_after, b.goal_name
    # Equivalent solution QUALITY (sharded reduction order shifts argmin
    # tie-breaks, so individual placements may differ): per-resource CV of
    # the final distribution must match closely.
    cv_base = np.asarray(base.stats_after.cv())
    cv_shard = np.asarray(sharded.stats_after.cv())
    np.testing.assert_allclose(cv_shard, cv_base, rtol=0.05, atol=5e-3)
    # The sharded run really placed arrays on all 8 devices.
    assert len(sharded.final_placement.broker.sharding.device_set) == 8


def test_sharded_batch_scenarios_parity():
    """Scenario-axis-sharded what-if batch == single-device batch."""
    state, placement, meta = _cluster()
    sets = [[0], [1], [2], [3]]
    base = GoalOptimizer(goal_names=GOALS).batch_remove_scenarios(
        state, placement, meta, sets, num_candidates=64)

    mesh = make_solver_mesh(8, scenario_parallelism=4)
    opt = GoalOptimizer(goal_names=GOALS, mesh=mesh)
    res = opt.batch_remove_scenarios(state, placement, meta, sets,
                                     num_candidates=64)
    np.testing.assert_array_equal(res.stranded_after, base.stranded_after)
    np.testing.assert_array_equal(res.violated_after, base.violated_after)
    for s, ids in enumerate(sets):
        pl = res.placement_for(s)
        brokers = np.asarray(pl.broker)[np.asarray(state.valid)]
        for bid in ids:
            assert (brokers != bid).all()
