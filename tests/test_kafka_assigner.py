"""kafka-assigner mode tests.

Mirrors the reference's ``KafkaAssignerEvenRackAwareGoalTest`` /
``KafkaAssignerDiskUsageDistributionGoalTest`` behavior contracts:
position-even counts + per-partition rack distinctness for the even goal,
count-preserving swap-only disk balance for the disk goal, and the
``kafka_assigner=true`` request-path switch (RunnableUtils.java).
"""

import numpy as np

from cruise_control_tpu.analyzer import GoalOptimizer
from cruise_control_tpu.common.resources import Resource
from cruise_control_tpu.model import ops
from cruise_control_tpu.model.builder import ClusterModel
from cruise_control_tpu.testing import deterministic as det


def _clumped_cluster():
    """4 brokers / 2 racks; 8 RF=2 partitions all packed onto rack 0."""
    cm = det.homogeneous_cluster({0: 0, 1: 0, 2: 1, 3: 1})
    for p in range(8):
        cm.create_replica("T1", p, broker_id=0, index=0, is_leader=True)
        cm.create_replica("T1", p, broker_id=1, index=1, is_leader=False)
        cm.set_replica_load("T1", p, 0, det.load(1.0, 5.0, 3.0, 10.0))
        cm.set_replica_load("T1", p, 1, det.load(0.2, 5.0, 0.0, 10.0))
    return cm.freeze(pad_replicas_to=64, pad_brokers_to=8)


def test_even_rack_aware_goal():
    state, placement, meta = _clumped_cluster()
    opt = GoalOptimizer(goal_names=["KafkaAssignerEvenRackAwareGoal"])
    res = opt.optimizations(state, placement, meta)
    final = res.final_placement
    valid = np.asarray(state.valid)
    brokers = np.asarray(final.broker)[valid]
    leaders = np.asarray(final.is_leader)[valid]
    parts = np.asarray(state.partition)[valid]
    racks = np.asarray(state.rack)

    # Per-partition rack distinctness (RF=2 over 2 racks).
    for p in np.unique(parts):
        rows = parts == p
        assert len(set(racks[brokers[rows]].tolist())) == 2, p

    # Position-even: 8 leaders over 4 brokers -> 2 each; same for followers.
    lead_counts = np.bincount(brokers[leaders], minlength=4)[:4]
    foll_counts = np.bincount(brokers[~leaders], minlength=4)[:4]
    assert lead_counts.max() - lead_counts.min() <= 1, lead_counts
    assert foll_counts.max() - foll_counts.min() <= 1, foll_counts


def test_even_rack_aware_evacuates_dead_broker():
    cm = det.homogeneous_cluster({0: 0, 1: 0, 2: 1, 3: 1})
    for p in range(6):
        cm.create_replica("T1", p, broker_id=p % 4, index=0, is_leader=True)
        cm.set_replica_load("T1", p, p % 4, det.load(1.0, 5.0, 3.0, 10.0))
    cm.set_broker_state(3, alive=False)
    state, placement, meta = cm.freeze(pad_replicas_to=64, pad_brokers_to=8)
    opt = GoalOptimizer(goal_names=["KafkaAssignerEvenRackAwareGoal"])
    res = opt.optimizations(state, placement, meta)
    brokers = np.asarray(res.final_placement.broker)[np.asarray(state.valid)]
    assert (brokers != 3).all()


def _uneven_disk_cluster():
    """Two brokers, equal counts, unequal disk: only swaps can balance."""
    capacity = {Resource.CPU: det.TYPICAL_CPU_CAPACITY, Resource.NW_IN: 1000.0,
                Resource.NW_OUT: det.MEDIUM_BROKER_CAPACITY, Resource.DISK: 20.0}
    cm = det.homogeneous_cluster({0: 0, 1: 1}, capacity=capacity)
    disk = {("T1", 0): (0, 10.0), ("T1", 1): (0, 8.0),
            ("T2", 0): (1, 4.0), ("T2", 1): (1, 2.0)}
    for (topic, part), (broker, value) in disk.items():
        cm.create_replica(topic, part, broker_id=broker, index=0, is_leader=True)
        cm.set_replica_load(topic, part, broker, det.load(1.0, 1.0, 0.0, value))
    return cm.freeze(pad_replicas_to=64, pad_brokers_to=8)


def test_kafka_assigner_disk_goal_swaps_only():
    state, placement, meta = _uneven_disk_cluster()
    opt = GoalOptimizer(goal_names=["KafkaAssignerDiskUsageDistributionGoal"])
    res = opt.optimizations(state, placement, meta)
    final = res.final_placement
    bl = np.asarray(ops.broker_load(state, final))[:2, Resource.DISK]
    cap = np.asarray(state.capacity)[:2, Resource.DISK]
    avg = bl.sum() / cap.sum()
    assert (bl <= avg * 1.1 * cap + 1e-4).all(), bl
    counts = np.bincount(np.asarray(final.broker)[np.asarray(state.valid)],
                         minlength=2)[:2]
    assert counts.tolist() == [2, 2]


def test_kafka_assigner_request_param():
    from cruise_control_tpu.analyzer.goals.registry import KAFKA_ASSIGNER_GOALS
    from cruise_control_tpu.servlet.server import _goals
    assert _goals({"kafka_assigner": "true"}) == KAFKA_ASSIGNER_GOALS
    assert _goals({"goals": "RackAwareGoal"}) == ["RackAwareGoal"]
    assert _goals({}) is None
