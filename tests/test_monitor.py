"""Monitor-layer tests.

Models the reference's core aggregator tests (``RawMetricValuesTest``,
``MetricSampleAggregatorTest`` with fake entities) and the mocked
``LoadMonitorTest`` — no external cluster, a FakeMetadataBackend plays the
embedded-broker role.
"""

import numpy as np
import pytest

from cruise_control_tpu.common.exceptions import NotEnoughValidWindowsError
from cruise_control_tpu.monitor import metric_def as md
from cruise_control_tpu.monitor.aggregator import (
    AggregationOptions,
    Extrapolation,
    MetricSampleAggregator,
)
from cruise_control_tpu.monitor.load_monitor import (
    LoadMonitor,
    ModelCompletenessRequirements,
)
from cruise_control_tpu.monitor.metadata import (
    BrokerInfo,
    FakeMetadataBackend,
    MetadataClient,
    PartitionInfo,
)
from cruise_control_tpu.monitor.sample_store import FileSampleStore
from cruise_control_tpu.monitor.sampler import SyntheticWorkloadSampler
from cruise_control_tpu.monitor.samples import (
    BrokerMetricSample,
    PartitionMetricSample,
)
from cruise_control_tpu.monitor.task_runner import LoadMonitorTaskRunner, RunnerState

W = 1000  # small window for tests


def _agg(**kw):
    defaults = dict(num_windows=5, window_ms=W, min_samples_per_window=2)
    defaults.update(kw)
    return MetricSampleAggregator(md.COMMON_METRIC_DEF, **defaults)


def _metrics(cpu=1.0, nw_in=10.0, nw_out=5.0, disk=100.0):
    m = np.zeros(md.COMMON_METRIC_DEF.size)
    m[md.CPU_USAGE] = cpu
    m[md.LEADER_BYTES_IN] = nw_in
    m[md.LEADER_BYTES_OUT] = nw_out
    m[md.DISK_USAGE] = disk
    return m


def fill(agg, entity, windows, per_window=2, cpu=1.0, disk=100.0):
    for w in windows:
        for i in range(per_window):
            agg.add_sample(entity, w * W + 10 * (i + 1), _metrics(cpu=cpu, disk=disk))


def test_avg_and_latest_strategies():
    agg = _agg()
    e = ("t", 0)
    agg.add_sample(e, 100, _metrics(cpu=1.0, disk=50.0))
    agg.add_sample(e, 200, _metrics(cpu=3.0, disk=70.0))
    fill(agg, e, [1, 2, 3, 4, 5])  # later windows so window 0 completes
    res = agg.aggregate(0, 6 * W)
    vae = res.values_and_extrapolations[e]
    w0 = vae.windows.index(0)
    # CPU is AVG: (1+3)/2; DISK is LATEST: the t=200 sample wins.
    assert vae.values[md.CPU_USAGE, w0] == pytest.approx(2.0)
    assert vae.values[md.DISK_USAGE, w0] == pytest.approx(70.0)


def test_avg_available_extrapolation():
    agg = _agg()
    e = ("t", 0)
    fill(agg, e, [0, 1, 2, 3], per_window=2)
    agg.add_sample(e, 4 * W + 10, _metrics())      # 1 < min_samples: AVG_AVAILABLE
    fill(agg, e, [5], per_window=1)                # active window (excluded)
    res = agg.aggregate(0, 6 * W)
    vae = res.values_and_extrapolations[e]
    w4 = vae.windows.index(4)
    assert vae.extrapolations[w4] is Extrapolation.AVG_AVAILABLE


def test_avg_adjacent_extrapolation():
    agg = _agg()
    e = ("t", 0)
    fill(agg, e, [0, 1, 3, 4])                     # window 2 empty
    fill(agg, e, [5], per_window=1)                # active
    res = agg.aggregate(0, 6 * W)
    vae = res.values_and_extrapolations[e]
    w2 = vae.windows.index(2)
    assert vae.extrapolations[w2] is Extrapolation.AVG_ADJACENT
    assert vae.values[md.CPU_USAGE, w2] == pytest.approx(1.0)


def test_forecast_extrapolation_trailing_gap():
    agg = _agg()
    e = ("t", 0)
    fill(agg, e, [0, 1, 2], cpu=2.0)
    # Windows 3,4 empty; 5 active.
    fill(agg, ("other", 1), [5], per_window=1)
    res = agg.aggregate(0, 6 * W)
    vae = res.values_and_extrapolations[e]
    w4 = vae.windows.index(4)
    assert vae.extrapolations[w4] in (Extrapolation.FORECAST,
                                      Extrapolation.AVG_ADJACENT)
    assert vae.values[md.CPU_USAGE, w4] == pytest.approx(2.0)


def test_entity_invalid_when_leading_windows_empty():
    agg = _agg()
    good, bad = ("t", 0), ("t", 1)
    fill(agg, good, [0, 1, 2, 3, 4])
    fill(agg, bad, [3, 4])                         # windows 0-2 have no history
    fill(agg, good, [5], per_window=1)             # active
    res = agg.aggregate(0, 6 * W)
    assert good in res.values_and_extrapolations
    assert bad not in res.values_and_extrapolations
    assert res.completeness.valid_entity_ratio == pytest.approx(0.5)


def test_completeness_gate_raises():
    agg = _agg()
    fill(agg, ("t", 0), [3, 4])
    fill(agg, ("t", 1), [0, 1, 2, 3, 4])
    fill(agg, ("t", 1), [5], per_window=1)
    with pytest.raises(NotEnoughValidWindowsError):
        agg.aggregate(0, 6 * W, AggregationOptions(min_valid_entity_ratio=0.9))


def test_window_rollout_drops_old_samples():
    agg = _agg()
    e = ("t", 0)
    fill(agg, e, [0])
    fill(agg, e, [10])                             # jump rolls the ring
    assert agg.add_sample(e, 50, _metrics()) is False  # window 0 long gone
    assert agg.num_available_windows() == 5


def test_retain_entities():
    agg = _agg()
    fill(agg, ("t", 0), [0, 1])
    fill(agg, ("t", 1), [0, 1])
    agg.retain_entities({("t", 0)})
    assert agg.all_entities() == [("t", 0)]


def test_out_of_order_samples_dropped_with_counter():
    from cruise_control_tpu.common.metrics import registry
    ctr = registry().counter("Monitor.out-of-order-samples")
    agg = _agg()
    e = ("t", 0)
    fill(agg, e, [0, 1, 2, 3])
    before = ctr.count
    # Window 1 closed when window 3 became active: the late sample must be
    # dropped (it would scatter into a committed buffer), counted once.
    assert agg.add_sample(e, 1 * W + 500, _metrics()) is False
    assert ctr.count == before + 1
    # The still-active window is NOT out of order.
    assert agg.add_sample(e, 3 * W + 500, _metrics()) is True
    assert ctr.count == before + 1
    # A batch that spans the window it advances past keeps its in-ring part.
    n = agg.add_samples([e, e], np.array([3 * W + 600.0, 4 * W + 10.0]),
                        np.stack([_metrics(), _metrics()]))
    assert n == 2 and ctr.count == before + 1


def test_first_batch_ingest_exempt_from_out_of_order_drop():
    from cruise_control_tpu.common.metrics import registry
    ctr = registry().counter("Monitor.out-of-order-samples")
    before = ctr.count
    agg = _agg()
    e = ("t", 0)
    # A batched bootstrap replay arrives oldest-first in ONE call: the roll
    # to the newest window must not retro-drop the older windows' samples.
    n = agg.add_samples([e] * 3,
                        np.array([10.0, W + 10.0, 2 * W + 10.0]),
                        np.stack([_metrics()] * 3))
    assert n == 3
    assert ctr.count == before


def test_no_valid_extrapolation_invalidates_entity():
    # Leading empty window with no prior history and an empty right
    # neighbor: no extrapolation kind applies (NO_VALID_EXTRAPOLATION), so
    # the entity drops out of the aggregation entirely.
    agg = _agg()
    good, bad = ("t", 0), ("t", 1)
    fill(agg, good, [0, 1, 2, 3, 4])
    fill(agg, bad, [2, 3, 4])                      # windows 0,1 unfillable
    fill(agg, good, [5], per_window=1)             # active
    res = agg.aggregate(0, 6 * W)
    assert bad not in res.values_and_extrapolations
    comp = res.completeness
    assert comp.num_valid_entities == 1
    # By-kind counts cover VALID entities only — the invalid one must not
    # leak its (nonexistent) fills into the fingerprint accounting.
    assert (comp.num_windows_avg_available + comp.num_windows_avg_adjacent
            + comp.num_windows_forecast) == 0
    assert comp.num_entity_windows == len(comp.valid_windows)


def test_max_extrapolations_overflow_flips_entity_invalid():
    # Two AVG_AVAILABLE windows: under a cap of 1 the entity overflows its
    # extrapolation budget and flips invalid; a cap of 2 keeps it valid.
    def build(cap):
        agg = _agg(max_allowed_extrapolations_per_entity=cap)
        e = ("t", 0)
        fill(agg, e, [0, 1, 2])
        agg.add_sample(e, 3 * W + 10, _metrics())  # 1 < min_samples
        agg.add_sample(e, 4 * W + 10, _metrics())  # 1 < min_samples
        fill(agg, e, [5], per_window=1)            # active
        return e, agg.aggregate(0, 6 * W)

    e, res = build(cap=2)
    assert e in res.values_and_extrapolations
    assert res.completeness.num_windows_avg_available == 2
    e, res = build(cap=1)
    assert e not in res.values_and_extrapolations
    assert res.completeness.num_valid_entities == 0
    assert res.completeness.num_windows_avg_available == 0


def test_completeness_by_kind_counts_match_recount():
    # Mixed gap pattern across two entities; the completeness by-kind
    # tallies must equal an independent recount of the per-entity
    # extrapolation maps (the fingerprint_coherent fuzz invariant's check,
    # pinned here as a unit test).
    agg = _agg(max_allowed_extrapolations_per_entity=4)
    a, b = ("t", 0), ("t", 1)
    fill(agg, a, [0, 1, 3, 4])
    agg.add_sample(a, 2 * W + 10, _metrics())      # 1 < min: AVG_AVAILABLE
    fill(agg, b, [0, 1, 2])                        # w3, w4 empty: FORECAST
    fill(agg, a, [5], per_window=1)                # active
    res = agg.aggregate(0, 6 * W)
    recount = {Extrapolation.AVG_AVAILABLE: 0, Extrapolation.AVG_ADJACENT: 0,
               Extrapolation.FORECAST: 0}
    for vae in res.values_and_extrapolations.values():
        for kind in vae.extrapolations.values():
            recount[kind] += 1
    comp = res.completeness
    assert comp.num_windows_avg_available == recount[Extrapolation.AVG_AVAILABLE]
    assert comp.num_windows_avg_adjacent == recount[Extrapolation.AVG_ADJACENT]
    assert comp.num_windows_forecast == recount[Extrapolation.FORECAST]
    assert comp.num_entity_windows == (comp.num_valid_entities
                                       * len(comp.valid_windows))


# ------------------------------------------------------------- load monitor


def _fake_cluster(num_brokers=3, partitions_per_topic=4, rf=2):
    brokers = [BrokerInfo(i, rack=str(i % 2), host=f"h{i}") for i in range(num_brokers)]
    parts = []
    for t in ("A", "B"):
        for p in range(partitions_per_topic):
            reps = tuple((p + i) % num_brokers for i in range(rf))
            parts.append(PartitionInfo(topic=t, partition=p, leader=reps[0],
                                       replicas=reps, in_sync=reps))
    return FakeMetadataBackend(brokers, parts)


def _monitored(backend, windows=5):
    client = MetadataClient(backend, ttl_ms=0)
    lm = LoadMonitor(client, num_windows=windows, window_ms=W,
                     min_samples_per_window=1)
    sampler = SyntheticWorkloadSampler()
    runner = LoadMonitorTaskRunner(lm, sampler, sampling_interval_ms=W)
    return lm, runner


def test_load_monitor_end_to_end():
    backend = _fake_cluster()
    lm, runner = _monitored(backend)
    # Feed 6 windows of synthetic samples directly (bootstrap path).
    runner.bootstrap(0, 6 * W)
    assert lm.meet_completeness_requirements(
        ModelCompletenessRequirements(min_required_num_windows=3,
                                      min_monitored_partitions_percentage=0.9))
    state, placement, meta = lm.cluster_model(0, 6 * W)
    assert meta.num_brokers == 3
    assert meta.num_replicas == 16           # 8 partitions × rf 2
    # Leader loads populated: cluster-wide CPU > 0.
    from cruise_control_tpu.model import ops
    bl = np.asarray(ops.broker_load(state, placement))
    assert bl[:, 0].sum() > 0
    assert bl[:, 3].sum() > 0


def test_load_monitor_feeds_optimizer():
    backend = _fake_cluster()
    lm, runner = _monitored(backend)
    runner.bootstrap(0, 6 * W)
    backend.kill_broker(2)
    state, placement, meta = lm.cluster_model(0, 6 * W, pad_replicas_to=64,
                                              pad_brokers_to=8)
    from cruise_control_tpu.analyzer import GoalOptimizer
    res = GoalOptimizer(goal_names=["ReplicaCapacityGoal"]).optimizations(
        state, placement, meta)
    # All replicas of the dead broker get relocation proposals.
    assert len(res.proposals) > 0
    alive = np.asarray(state.alive)
    final = np.asarray(res.final_placement.broker)[:meta.num_replicas]
    assert alive[final].all()


def test_sample_store_roundtrip(tmp_path):
    store = FileSampleStore(str(tmp_path))
    s = PartitionMetricSample(broker_id=1, topic="t", partition=0, time_ms=123.0)
    s.record(md.CPU_USAGE, 0.5)
    store.store_samples([s], [])
    got = []
    store.load_samples(lambda x: got.append(x), lambda x: None)
    assert len(got) == 1
    assert got[0].topic == "t"
    assert got[0].metrics[md.CPU_USAGE] == pytest.approx(0.5)


def test_log_sample_store_restart_resume(tmp_path):
    """KafkaSampleStore semantics over the transport SPI: samples stored by
    one process generation are replayed by the next (fresh store over the
    same logs), with the multi-consumer reload pool."""
    from cruise_control_tpu.monitor.sample_store import LogSampleStore
    from cruise_control_tpu.reporter import FileTransport

    def make_store():
        return LogSampleStore(
            FileTransport(str(tmp_path / "p"), num_partitions=4),
            FileTransport(str(tmp_path / "b"), num_partitions=4),
            num_loaders=3)

    store = make_store()
    psamples = []
    for i in range(10):
        s = PartitionMetricSample(broker_id=i % 3, topic=f"t{i % 4}",
                                  partition=i, time_ms=100.0 + i)
        s.record(md.CPU_USAGE, 0.1 * i)
        psamples.append(s)
    b = BrokerMetricSample(broker_id=2, time_ms=50.0)
    b.record(md.CPU_USAGE, 0.7)
    store.store_samples(psamples, [b])

    # "Restart": a brand-new store instance over the same log directories.
    got_p, got_b = [], []
    n = make_store().load_samples(got_p.append, got_b.append)
    assert n == 11
    assert {(s.topic, s.partition) for s in got_p} == \
        {(s.topic, s.partition) for s in psamples}
    assert len(got_b) == 1 and got_b[0].broker_id == 2
    assert got_b[0].metrics[md.CPU_USAGE] == pytest.approx(0.7)

    # Appends after the reload land on the next reload (log positions are
    # per-reload, not global — the reference reloads from offset 0 too).
    store2 = make_store()
    extra = PartitionMetricSample(broker_id=0, topic="late", partition=99,
                                  time_ms=500.0)
    extra.record(md.CPU_USAGE, 1.0)
    store2.store_samples([extra], [])
    got2 = []
    assert make_store().load_samples(got2.append, lambda x: None) == 12


def test_log_sample_store_bounded_retention(tmp_path):
    """Partitions are trimmed to half the cap once they exceed it, so the
    store (and every restart's replay) stays bounded."""
    from cruise_control_tpu.monitor.sample_store import LogSampleStore
    from cruise_control_tpu.reporter import FileTransport

    store = LogSampleStore(
        FileTransport(str(tmp_path / "p"), num_partitions=1),
        FileTransport(str(tmp_path / "b"), num_partitions=1),
        max_records_per_partition=10)
    for i in range(25):
        s = PartitionMetricSample(broker_id=0, topic="t", partition=0,
                                  time_ms=float(i))
        s.record(md.CPU_USAGE, float(i))
        store.store_samples([s], [])
    got = []
    store.load_samples(got.append, lambda x: None)
    assert len(got) <= 10
    # The NEWEST samples survive the trim.
    assert max(s.time_ms for s in got) == 24.0


def test_task_runner_states_and_pause():
    backend = _fake_cluster()
    lm, runner = _monitored(backend)
    assert runner.state is RunnerState.NOT_STARTED
    runner.start()
    assert runner.state is RunnerState.RUNNING
    runner.pause_sampling("test")
    assert runner.state is RunnerState.PAUSED
    assert runner.run_sampling_once() == 0       # paused: no ingest
    runner.resume_sampling()
    assert runner.run_sampling_once() > 0
    runner.shutdown()


def test_metadata_generation_tracks_changes():
    backend = _fake_cluster()
    client = MetadataClient(backend, ttl_ms=0)
    g0 = client.refresh_metadata().generation
    client.refresh_metadata()
    assert client.generation == g0               # unchanged topology
    backend.kill_broker(1)
    client.refresh_metadata()
    assert client.generation == g0 + 1


def test_follower_replicas_have_load():
    """ADVICE r1: _populate must set load on every replica, so a follower-only
    broker shows non-zero utilization (MonitorUtils.populatePartitionLoad)."""
    backend = _fake_cluster()
    lm, runner = _monitored(backend)
    runner.bootstrap(0, 6 * W)
    state, placement, meta = lm.cluster_model(0, 6 * W)
    from cruise_control_tpu.model import ops
    bl = np.asarray(ops.broker_load(state, placement))[:meta.num_brokers]
    # Every broker hosts at least one replica in _fake_cluster; all must show
    # non-zero disk (col 3) load — follower-role load derives from leader load.
    assert (bl[:, 3] > 0).all()
    fol = np.asarray(state.follower_load)[:meta.num_replicas]
    assert fol[:, 3].sum() > 0


def test_num_available_windows_epoch_timestamps():
    """ADVICE r1: with absolute epoch-ms first samples, available windows must
    count from the first-observed window, not from window index 0."""
    agg = _agg()
    e = ("t", 0)
    base = 1_700_000  # epoch-like: window index base/W >> num_windows
    fill(agg, e, [base // W])
    assert agg.num_available_windows() == 0      # only the active window so far
    fill(agg, e, [base // W + 1])
    assert agg.num_available_windows() == 1


def test_first_batch_ingest_counts_all_windows():
    """A batched first ingest spanning several windows must count its oldest
    accepted window as first-observed, and completeness must not report
    windows that predate the first sample."""
    agg = _agg()
    e = ("t", 0)
    base = 1_700_000 // W
    fill(agg, e, [base + i for i in range(5)])    # one batched bootstrap
    fill(agg, e, [base + 5], per_window=1)        # active window
    assert agg.num_available_windows() == 5
    res = agg.aggregate(-np.inf, np.inf)
    assert res.completeness.valid_windows == [base + i for i in range(5)]


def test_completeness_empty_before_first_completed_window():
    agg = _agg()
    e = ("t", 0)
    fill(agg, e, [1_700_000 // W], per_window=1)  # single active window only
    comp = agg.completeness(-np.inf, np.inf)
    assert comp.valid_windows == []


def test_forecast_is_linear_fit():
    """FORECAST must extrapolate the trend (reference RawMetricValues does a
    linear fit over recent windows), not carry the last value forward."""
    agg = _agg()
    e = ("t", 0)
    for w, cpu in zip([0, 1, 2], [1.0, 2.0, 3.0]):   # slope +1/window
        fill(agg, e, [w], cpu=cpu)
    fill(agg, ("other", 1), [3, 4], per_window=2)    # windows 3,4 empty for e
    fill(agg, ("other", 1), [5], per_window=1)       # active window
    res = agg.aggregate(0, 6 * W)
    vae = res.values_and_extrapolations[e]
    w3 = vae.windows.index(3)
    w4 = vae.windows.index(4)
    assert vae.extrapolations[w3] is Extrapolation.FORECAST
    assert vae.values[md.CPU_USAGE, w3] == pytest.approx(4.0, abs=1e-3)
    assert vae.values[md.CPU_USAGE, w4] == pytest.approx(5.0, abs=1e-3)


def test_forecast_far_gap_carries_forward():
    """When the nearest non-empty window is >5 back, the linear fit has no
    points in its lookback — the fill must carry the last value, not emit 0."""
    agg = _agg(num_windows=12, max_allowed_extrapolations_per_entity=11)
    e = ("t", 0)
    fill(agg, e, [0], cpu=5.0)
    fill(agg, ("other", 1), list(range(1, 12)))      # keep windows completing
    fill(agg, ("other", 1), [12], per_window=1)      # active
    res = agg.aggregate(0, 13 * W)
    vae = res.values_and_extrapolations[e]
    for w in (7, 9, 10):
        wi = vae.windows.index(w)
        assert vae.values[md.CPU_USAGE, wi] == pytest.approx(5.0, abs=1e-3), w


def test_env_and_topic_config_capacity_resolvers():
    from cruise_control_tpu.monitor.capacity import (
        BrokerEnvCapacityResolver,
        FixedBrokerCapacityResolver,
        TopicConfigDiskCapacityResolver,
    )
    from cruise_control_tpu.common.resources import Resource

    env = {"BROKER_CPU_CAPACITY": "64", "BROKER_NW_IN_CAPACITY": "1e5",
           "BROKER_NW_OUT_CAPACITY": "1e5", "BROKER_DISK_CAPACITY": "5e5",
           "BROKER_NUM_CORES": "8"}
    r = BrokerEnvCapacityResolver(env=env)
    info = r.capacity_for_broker("r", "h", 3)
    assert info.capacity[Resource.CPU] == 64.0
    assert info.num_cores == 8
    with pytest.raises(ValueError):
        BrokerEnvCapacityResolver(env={})

    base = FixedBrokerCapacityResolver({Resource.CPU: 100.0,
                                        Resource.NW_IN: 1e5,
                                        Resource.NW_OUT: 1e5,
                                        Resource.DISK: 1e5})
    t = TopicConfigDiskCapacityResolver(base, {0: 2e5}, headroom_factor=1.5)
    assert t.capacity_for_broker("r", "h", 0).capacity[Resource.DISK] == 3e5
    assert t.capacity_for_broker("r", "h", 1).capacity[Resource.DISK] == 1e5
