"""Benchmark: full multi-goal proposal generation wall-clock.

BASELINE.md config #3: RandomCluster 200 brokers / 50K replicas, full
hard-goal stack + ResourceDistribution soft goals.  The north-star budget
(BASELINE.json) is a <10 s full proposal at 2.6K brokers / 1M replicas on one
v5e chip; this bench reports the 200-broker config so every round has a
comparable number, with ``vs_baseline`` = north-star-budget / measured (>1 ⇒
inside budget).  Wall-clock excludes one warmup solve (jit compile is cached
across snapshots of the same size class in production).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import time

NORTH_STAR_BUDGET_S = 10.0


def select_backend() -> str:
    """Pick the JAX backend BEFORE the first in-process jax import.

    The axon TPU backend rides a tunnel that can be down or version-skewed,
    and its init can hang or raise — either would turn the whole bench into
    rc≠0.  Probe it in a throwaway subprocess with a timeout; on any failure
    force the CPU platform (and deregister the axon PJRT factory) so the
    bench always produces a number, annotated with the backend it ran on.
    """
    from cruise_control_tpu.utils.hermetic import force_cpu, probe_tpu
    if probe_tpu():
        return "tpu"
    force_cpu()
    return "cpu"

GOALS = [
    "RackAwareGoal",
    "ReplicaCapacityGoal",
    "DiskCapacityGoal",
    "NetworkInboundCapacityGoal",
    "NetworkOutboundCapacityGoal",
    "CpuCapacityGoal",
    "ReplicaDistributionGoal",
    "NetworkInboundUsageDistributionGoal",
    "NetworkOutboundUsageDistributionGoal",
    "CpuUsageDistributionGoal",
    "DiskUsageDistributionGoal",
    "LeaderReplicaDistributionGoal",
]


def main() -> None:
    backend = select_backend()

    from cruise_control_tpu.analyzer import BalancingConstraint, GoalOptimizer
    from cruise_control_tpu.testing import random_cluster as rc

    props = rc.ClusterProperties(
        num_brokers=200, num_racks=10, num_topics=1000, num_replicas=50_000,
        mean_cpu=0.006, mean_disk=90.0, mean_nw_in=90.0, mean_nw_out=90.0,
        seed=3140)
    state, placement, meta = rc.generate(props)

    constraint = BalancingConstraint()
    optimizer = GoalOptimizer(constraint=constraint, goal_names=GOALS)

    # Warmup: populates the per-goal jit caches (one compile per goal class).
    optimizer.optimizations(state, placement, meta)

    t0 = time.monotonic()
    result = optimizer.optimizations(state, placement, meta)
    elapsed = time.monotonic() - t0

    print(json.dumps({
        "metric": "proposal_generation_wall_clock_200brokers_50k_replicas_full_goals",
        "value": round(elapsed, 4),
        "unit": "seconds",
        "vs_baseline": round(NORTH_STAR_BUDGET_S / max(elapsed, 1e-9), 3),
        "backend": backend,
    }))


if __name__ == "__main__":
    main()
