"""Benchmark: full multi-goal proposal generation wall-clock.

All five BASELINE.md configs, one JSON line each (headline LAST):

- config #1: DeterministicCluster harness — 6 brokers / 3 racks / ~200
  replicas, default goals (the direct comparator for a Java-side
  ``DeterministicClusterTest``-style measurement).
- config #2: RandomCluster 200 brokers / 50K replicas, a single
  ResourceDistributionGoal (``RandomCluster.java:55-121`` driven as in
  ``RandomClusterTest``).
- config #3 (headline): RandomCluster 200 brokers / 50K replicas, full
  hard-goal stack + distribution soft goals — comparable across rounds.
- config #4: 2.6K brokers / 1M replicas, full default goal stack — the
  north-star scale (<10 s budget on one v5e chip).
- config #5: remove-broker what-ifs at 2.6K brokers / 1M replicas as a
  vmapped scenario batch through the production
  ``GoalOptimizer.batch_remove_scenarios`` (hard-goal stack), in FIVE rows:
  the round-comparable lane batch (cold + warm), ONE scenario decommissioning
  64 brokers at once (the reference's RemoveBrokersRunnable removes a *set*
  in one operation — BASELINE's literal shape; cold + warm), and the full
  64-lane batch even on the CPU fallback.

``vs_baseline`` = north-star-budget / measured (>1 ⇒ inside budget).
``vs_java`` is absent from every line: this image carries NO JVM (see
BASELINE.md "Java baseline status"), so the Java GoalOptimizer has never
been timed here — configs #1/#2 exist so the ratio can be computed the day
a JVM is available, not to fake one now.
Wall-clock excludes one warmup solve (jit compile is cached across snapshots
of the same size class in production).
"""

from __future__ import annotations

import json
import os
import time

NORTH_STAR_BUDGET_S = 10.0
CAPTURE_FILE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "tpu_attempts", "captured.jsonl")


def select_backend() -> str:
    """Pick the JAX backend BEFORE the first in-process jax import.

    The axon TPU backend rides a tunnel that can be down or version-skewed,
    and its init can hang or raise — either would turn the whole bench into
    rc≠0.  Probe it in a throwaway subprocess with a timeout; on any failure
    force the CPU platform (and deregister the axon PJRT factory) so the
    bench always produces a number, annotated with the backend it ran on.
    """
    from cruise_control_tpu.utils.hermetic import force_cpu, probe_tpu
    if probe_tpu():
        return "tpu"
    force_cpu()
    return "cpu"

GOALS = [
    "RackAwareGoal",
    "ReplicaCapacityGoal",
    "DiskCapacityGoal",
    "NetworkInboundCapacityGoal",
    "NetworkOutboundCapacityGoal",
    "CpuCapacityGoal",
    "ReplicaDistributionGoal",
    "NetworkInboundUsageDistributionGoal",
    "NetworkOutboundUsageDistributionGoal",
    "CpuUsageDistributionGoal",
    "DiskUsageDistributionGoal",
    "LeaderReplicaDistributionGoal",
]


TPU_CHILD_TIMEOUT_S = 1800.0


def main() -> None:
    import os
    import subprocess
    import sys

    only = None
    if "--only" in sys.argv:
        # Run a subset of configs (e.g. ``--only 3`` for the smallest
        # full-stack compile).  Used by scripts/tpu_capture.py to grab the
        # cheapest TPU datapoint first while the flaky tunnel is alive.
        only = {int(c) for c in
                sys.argv[sys.argv.index("--only") + 1].split(",")}

    if "--tpu-child" in sys.argv:
        # Parent already probed the backend; just run.  Application errors
        # exit 3 (the parent fails loud instead of masking them with a CPU
        # rerun); backend/runtime deaths exit 4 (CPU fallback).
        if os.environ.get("CC_TPU_PERSIST_CACHE"):
            # TPU executables are compiled server-side for the TPU — the
            # XLA:CPU "different machine features across processes" SIGILL
            # (tests/conftest.py) does not apply, and a persisted cache lets
            # a second tunnel-alive window skip straight to the bigger
            # configs.  Opt-in so the driver's own run stays hermetic.
            from cruise_control_tpu.utils.hermetic import (
                enable_persistent_compilation_cache)
            enable_persistent_compilation_cache()
        try:
            run("tpu", only=only)
        except Exception as e:
            import traceback
            traceback.print_exc()
            from jax.errors import JaxRuntimeError
            sys.exit(4 if isinstance(e, (JaxRuntimeError, OSError)) else 3)
        return

    only_args = (["--only", sys.argv[sys.argv.index("--only") + 1]]
                 if only is not None else [])
    backend = select_backend()
    if backend == "tpu":
        # The tunneled TPU backend can hang MID-RUN (not just at init) — a
        # half-dead tunnel passes the probe and then stalls a dispatch
        # forever.  Run the TPU attempt in a watchdogged subprocess; on any
        # failure or timeout, fall back to CPU so the bench always emits
        # its JSON lines.
        try:
            # stdout is INHERITED so the child's JSON lines stream out as
            # they are produced — a harness kill mid-run still leaves every
            # already-emitted line on stdout (the headline goes first).
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--tpu-child",
                 *only_args],
                timeout=TPU_CHILD_TIMEOUT_S)
            if proc.returncode == 0:
                return
            if proc.returncode == 3:
                sys.exit(3)     # application bug on the TPU path: fail loud
            sys.stderr.write(f"\ntpu child rc={proc.returncode}; "
                             "falling back to cpu\n")
        except subprocess.TimeoutExpired:
            sys.stderr.write("\ntpu child timed out; falling back to cpu\n")
    from cruise_control_tpu.utils.hermetic import force_cpu
    force_cpu()
    run("cpu", only=only)


HARD_GOALS = GOALS[:6]


def _emit(metric: str, seconds: float, backend: str, **extra) -> None:
    """One JSON line; ``vs_baseline`` is ALWAYS budget/value (whole
    measurement) so the field stays comparable across metrics and rounds."""
    print(json.dumps({
        "metric": metric,
        "value": round(seconds, 4),
        "unit": "seconds",
        "vs_baseline": round(NORTH_STAR_BUDGET_S / max(seconds, 1e-9), 3),
        "backend": backend,
        **extra,
    }), flush=True)


def _timed(fn) -> float:
    fn()                      # warmup: populate per-goal jit caches
    t0 = time.monotonic()
    fn()
    return time.monotonic() - t0


def run(backend: str, only=None) -> None:
    from cruise_control_tpu.analyzer import GoalOptimizer
    from cruise_control_tpu.testing import random_cluster as rc
    # NOTE: the persistent compilation cache is deliberately NOT enabled on
    # the CPU path: on this VM, XLA:CPU detects different machine features
    # across processes and warns that loading mismatched AOT results "could
    # lead to execution errors such as SIGILL" — the benchmark artifact must
    # never die to a stale cache entry.  (scripts/profile_solve.py opts in;
    # the TPU child opts in via CC_TPU_PERSIST_CACHE, where executables are
    # TPU-targeted and the CPU feature skew is irrelevant.)
    # "warm" below therefore always means the IN-PROCESS jit cache.
    want = lambda c: only is None or c in only

    # ---- config #3 (headline) first, so a number exists even if the harness
    # cuts the run short; re-emitted last for tail parsers.
    headline = None
    state = placement = meta = None
    if want(3) or want(2):
        props = rc.ClusterProperties(
            num_brokers=200, num_racks=10, num_topics=1000,
            num_replicas=50_000, mean_cpu=0.006, mean_disk=90.0,
            mean_nw_in=90.0, mean_nw_out=90.0, seed=3140)
        state, placement, meta = rc.generate(props)
    if want(3):
        optimizer = GoalOptimizer(goal_names=GOALS)
        headline = _timed(
            lambda: optimizer.optimizations(state, placement, meta))
        _emit("proposal_generation_wall_clock_200brokers_50k_replicas_"
              "full_goals", headline, backend)
        del optimizer

    # ---- config #1: DeterministicCluster harness (6 brokers / 3 racks /
    # ~200 replicas, default goals — BASELINE.md config #1).
    if want(1):
        from cruise_control_tpu.testing import deterministic as det
        cm = det.homogeneous_cluster({0: 0, 1: 0, 2: 1, 3: 1, 4: 2, 5: 2})
        for p in range(100):
            lead, foll = p % 6, (p + 1 + p % 3) % 6
            cm.create_replica("T1", p, broker_id=lead, index=0, is_leader=True)
            cm.create_replica("T1", p, broker_id=foll, index=1,
                              is_leader=False)
            cm.set_replica_load("T1", p, lead,
                                det.load(0.5, 120.0, 180.0, 220.0))
            cm.set_replica_load("T1", p, foll,
                                det.load(0.1, 120.0, 0.0, 220.0))
        d_state, d_placement, d_meta = cm.freeze(pad_replicas_to=256,
                                                 pad_brokers_to=8)
        opt_det = GoalOptimizer(goal_names=GOALS)
        det_s = _timed(
            lambda: opt_det.optimizations(d_state, d_placement, d_meta))
        _emit("proposal_generation_wall_clock_deterministic_6brokers_"
              "200replicas", det_s, backend)
        del d_state, d_placement, opt_det

    # ---- config #2: 200 brokers / 50K replicas, ONE ResourceDistributionGoal
    # (reuses config #3's still-live snapshot and solver caches).
    if want(2):
        opt_single = GoalOptimizer(
            goal_names=["NetworkInboundUsageDistributionGoal"])
        single_s = _timed(
            lambda: opt_single.optimizations(state, placement, meta))
        _emit("proposal_generation_wall_clock_200brokers_50k_replicas_single_"
              "resource_distribution_goal", single_s, backend)
        del opt_single
    del state, placement

    # ---- config #4 fixture: north-star scale (2.6K brokers / 1M replicas)
    if want(4):
        big = rc.ClusterProperties(
            num_brokers=2600, num_racks=40, num_topics=2000,
            num_replicas=1_000_000, mean_cpu=0.0035, mean_disk=90.0,
            mean_nw_in=90.0, mean_nw_out=90.0, seed=3141)
        b_state, b_placement, b_meta = rc.generate(big)

        # config #4: full default stack at north-star scale.
        opt_big = GoalOptimizer(goal_names=GOALS)
        elapsed = _timed(
            lambda: opt_big.optimizations(b_state, b_placement, b_meta))
        _emit("proposal_generation_wall_clock_2600brokers_1m_replicas_"
              "full_goals", elapsed, backend)
        del opt_big, b_state, b_placement

    # config #5: decommission what-ifs over a HEALTHY cluster (the realistic
    # remove_broker setting — lanes pay for evacuation, not a full repair),
    # one vmapped program per goal.  One timed call (compile included — the
    # lane batch IS the amortization); the CPU fallback runs fewer lanes in
    # the round-comparable rows, then the full spec shapes follow.
    if want(5):
        healthy = rc.ClusterProperties(
            num_brokers=2600, num_racks=40, num_topics=2000,
            num_replicas=1_000_000, mean_cpu=0.002, mean_disk=60.0,
            mean_nw_in=60.0, mean_nw_out=60.0, seed=3142)
        h_state, h_placement, h_meta = rc.generate(healthy)
        lanes = 64 if backend == "tpu" else 16
        sets = [[b] for b in range(lanes)]
        opt_hard = GoalOptimizer(goal_names=HARD_GOALS)
        t0 = time.monotonic()
        opt_hard.batch_remove_scenarios(h_state, h_placement, h_meta, sets,
                                        num_candidates=512)
        batch_s = time.monotonic() - t0
        # vs_baseline stays budget/whole-batch (comparable across rounds);
        # per_lane_vs_budget is the honest per-study comparison — the
        # reference runs each decommission what-if as a separate request.
        _emit("remove_broker_what_ifs_2600brokers_1m_replicas_hard_goals",
              batch_s, backend, value_per_lane=round(batch_s / lanes, 4),
              per_lane_vs_budget=round(
                  NORTH_STAR_BUDGET_S / max(batch_s / lanes, 1e-9), 3),
              lanes=lanes, includes_compile=True,
              compile_cache="cold")
        # Warm repeat: the in-process jit cache now holds every lane program —
        # this is what the precompute daemon's steady state (and any repeat
        # what-if at the same size class) pays.
        sets_w = [[lanes + b] for b in range(lanes)]
        t0 = time.monotonic()
        opt_hard.batch_remove_scenarios(h_state, h_placement, h_meta, sets_w,
                                        num_candidates=512)
        warm_s = time.monotonic() - t0
        _emit("remove_broker_what_ifs_2600brokers_1m_replicas_hard_goals_warm",
              warm_s, backend, value_per_lane=round(warm_s / lanes, 4),
              per_lane_vs_budget=round(
                  NORTH_STAR_BUDGET_S / max(warm_s / lanes, 1e-9), 3),
              lanes=lanes, includes_compile=False,
              compile_cache="warm")

        # BASELINE config #5 AT SPEC — "decommission 64 at once" is the
        # reference's RemoveBrokersRunnable semantics: ONE operation removes
        # a *set* of brokers, all 64 brokers' replicas evacuating in the same
        # solve (a different, harder problem than 64 single-broker what-ifs).
        t0 = time.monotonic()
        opt_hard.batch_remove_scenarios(
            h_state, h_placement, h_meta, [list(range(64))],
            num_candidates=512)
        one_s = time.monotonic() - t0
        _emit("remove_64_brokers_single_scenario_2600brokers_1m_replicas_"
              "hard_goals", one_s, backend, brokers_removed=64, scenarios=1,
              includes_compile=True, compile_cache="cold")
        # Warm repeat on a different 64-broker set: what a second
        # decommission request at this size class pays.
        t0 = time.monotonic()
        opt_hard.batch_remove_scenarios(
            h_state, h_placement, h_meta, [list(range(64, 128))],
            num_candidates=512)
        one_w = time.monotonic() - t0
        _emit("remove_64_brokers_single_scenario_2600brokers_1m_replicas_"
              "hard_goals_warm", one_w, backend, brokers_removed=64,
              scenarios=1, includes_compile=False, compile_cache="warm")

        # The full 64-lane what-if batch, run even on CPU (once, slow is
        # fine) so a number at BASELINE's exact lane count exists.  Guarded:
        # a batch-64 1M-replica program may exceed host RAM on the CPU
        # fallback — skip honestly rather than die and lose prior lines.
        if lanes != 64:
            try:
                sets64 = [[b] for b in range(64)]
                t0 = time.monotonic()
                opt_hard.batch_remove_scenarios(
                    h_state, h_placement, h_meta, sets64, num_candidates=512)
                b64_s = time.monotonic() - t0
                _emit("remove_broker_what_ifs_64lanes_2600brokers_1m_replicas"
                      "_hard_goals", b64_s, backend,
                      value_per_lane=round(b64_s / 64, 4),
                      per_lane_vs_budget=round(
                          NORTH_STAR_BUDGET_S / max(b64_s / 64, 1e-9), 3),
                      lanes=64, includes_compile=True, compile_cache="cold")
            except MemoryError:
                import sys
                sys.stderr.write("64-lane batch exceeded host RAM on the CPU "
                                 "fallback; row skipped\n")
        del h_state, h_placement, opt_hard

    if backend == "cpu":
        _replay_captured_tpu_rows()

    # Headline repeated LAST: the driver's artifact parser takes the tail line.
    if headline is not None:
        _emit("proposal_generation_wall_clock_200brokers_50k_replicas_"
              "full_goals", headline, backend)


def _replay_captured_tpu_rows() -> None:
    """Re-emit TPU rows captured by ``scripts/tpu_capture.py`` earlier in the
    round.  The tunneled TPU dies unpredictably (BASELINE.md round-4 status),
    so live windows are harvested whenever they occur; a row measured then is
    real data the round-end CPU-fallback run must not drop.  Replayed rows
    keep their measured values and carry ``"replayed": true`` plus the
    capture timestamp — they are NOT measurements of this process."""
    rows = []
    try:
        with open(CAPTURE_FILE) as f:
            for line in f:
                try:
                    rows.append(json.loads(line))
                except ValueError:
                    pass   # torn tail write from a killed capture daemon
    except OSError:
        return
    best = {}
    for row in rows:
        if row.get("backend") == "tpu" and "metric" in row:
            best[row["metric"]] = row          # latest capture wins
    for row in best.values():
        row["replayed"] = True
        print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
