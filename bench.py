"""Benchmark: full multi-goal proposal generation wall-clock.

All five BASELINE.md configs plus the resident-model steady-state config,
one JSON line each (headline LAST):

- config #1: DeterministicCluster harness — 6 brokers / 3 racks / ~200
  replicas, default goals (the direct comparator for a Java-side
  ``DeterministicClusterTest``-style measurement).
- config #2: RandomCluster 200 brokers / 50K replicas, a single
  ResourceDistributionGoal (``RandomCluster.java:55-121`` driven as in
  ``RandomClusterTest``).
- config #3 (headline): RandomCluster 200 brokers / 50K replicas, full
  hard-goal stack + distribution soft goals — comparable across rounds.
- config #4: 2.6K brokers / 1M replicas, the FULL default goal stack (all
  15 registry goals) — the north-star scale (<10 s budget on one v5e chip).
- config #5: remove-broker what-ifs at 2.6K brokers / 1M replicas as a
  vmapped scenario batch through the production
  ``GoalOptimizer.batch_remove_scenarios`` (hard-goal stack): the
  round-comparable lane batch (cold + warm), ONE scenario decommissioning
  64 brokers at once (the reference's RemoveBrokersRunnable removes a *set*
  in one operation — BASELINE's literal shape; cold + warm), and the full
  64-lane batch (cold + warm) even on the CPU fallback — the compilesvc
  lane-chunking planner routes 64 lanes through already-compiled widths,
  so the first 64-lane call should pay (close to) zero fresh compiles.
- config #6: the resident-model steady state at 2.6K brokers / 1M
  replicas.  One full freeze seeds the ``ResidentModelService``; each
  steady round mutates ~64 partitions' loads on the SAME builder and
  re-proposes through the delta-scatter path (the production facade flow
  after one LoadMonitor window).  The row carries the full-freeze cost,
  the mean delta-apply cost, their ratio (``freeze_transfer_reduction``),
  and the sensor-verified count of full freezes paid during the steady
  rounds (must be 0).  Two lane rows follow on the SAME resident tensors:
  the 16-lane decommission batch seeded from the raw snapshot and the
  identical batch ``warm_start``-ed from the already-solved base
  placement — the executable is shared (the seed placement is a traced
  input), so the pair isolates what per-lane early exit buys.
- config #7: the anytime tradeoff — config #3's snapshot re-solved under
  ``SolveBudget`` deadlines at 25/50/100% of the calibrated steady-state
  (warm, unbudgeted) solve time, segmented executables pre-compiled off
  the clock.  Each row carries ``partial`` / ``preempted_goals`` next to
  the usual quality fields: what balancedness a fraction of the latency
  buys, and what the segment-boundary overhead costs at 100%.
- config #8: the convex-relaxation ladder at the healthy north-star shape
  (2.6K brokers / 1M replicas) — cold lanes, warm lanes, and the full
  15-goal sequential propose, each solved with ``solver.relaxation``
  OFF (the greedy baseline) then ON.  Each rung's row carries
  ``greedy_s`` / ``speedup`` next to the relax-side value, plus the fast
  path's own attribution: ``relax_ms`` (the fenced ``solve.relax`` span
  wall), ``repair_rounds`` (the greedy rounds left AFTER rounding — the
  repair contract's cost), and ``quality_delta`` (relax balancedness
  minus greedy balancedness; ≥ 0 means the fractional solve lost
  nothing).  The warm-lane rung is ISSUE 15's acceptance comparison
  against the r05 4.73 s/lane warm what-if row.
- config #9: storm-backed execution throughput — solve then EXECUTE against
  the storm runner's in-process broker simulator (production backend wire
  shapes, ``polls_to_finish=2``), reporting the execution flight recorder's
  batch summary: ``execute_ms`` / ``moves_per_s`` plus the provenance path
  histogram (relax/rounding/repair/greedy) the executed moves carried.
  Measures the executor's submit/poll machinery, not broker I/O.

``vs_baseline`` = north-star-budget / measured (>1 ⇒ inside budget).
``vs_java`` is absent from every line: this image carries NO JVM (see
BASELINE.md "Java baseline status"), so the Java GoalOptimizer has never
been timed here — configs #1/#2 exist so the ratio can be computed the day
a JVM is available, not to fake one now.

Every row carries ``violated_after`` (violated-broker count summed over
goals after optimization) and ``balancedness`` (hard=3.0/soft=1.0 weighted
satisfied-goal score, [0,100]), plus ``fresh_compiles`` /
``includes_compile`` / ``compile_cache`` derived from the compilesvc
telemetry's compile counter around the timed region — the labels are
measured, not asserted.

The obsvc span tracer is ON for every bench run (since r06): each row
carries ``split_ms`` — the freeze / transfer / delta-apply / solve
millisecond split from the tracer rollup, drained per row — so the round
artifact proves where the milliseconds went, not just the total.  Every
row pays the same per-goal block_until_ready fence, so the series stays
internally comparable (r05-and-earlier rows were unfenced).  ``--trace``
additionally attaches the FULL per-phase rollup (``{phase: {count,
total_ms, mean_ms}}``) as a ``trace`` field — per-goal wall plus the
solver's fenced ``device_ms`` attribution.

``--convergence`` turns on the solver's round recorder
(``trace.solver.rounds``) for the run and attaches a ``convergence`` field
to every row: per-goal round-curve summaries (rounds_to_90pct,
acceptance_rate, stall_rounds, moves_total) for each sequential solve the
row paid for, and per-lane early-exit-round histograms for each what-if
batch — drained per row like ``split_ms``, warmup solves included.  Note
the recorder changes the solver's jit-cache key, so ``--convergence``
wall-clocks are not comparable to default rows.
"""

from __future__ import annotations

import json
import os
import sys
import time

NORTH_STAR_BUDGET_S = 10.0
CAPTURE_FILE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "tpu_attempts", "captured.jsonl")


def select_backend() -> str:
    """Pick the JAX backend BEFORE the first in-process jax import.

    The axon TPU backend rides a tunnel that can be down or version-skewed,
    and its init can hang or raise — either would turn the whole bench into
    rc≠0.  Probe it in a throwaway subprocess with a timeout; on any failure
    force the CPU platform (and deregister the axon PJRT factory) so the
    bench always produces a number, annotated with the backend it ran on.
    """
    from cruise_control_tpu.utils.hermetic import force_cpu, probe_tpu
    if probe_tpu():
        return "tpu"
    force_cpu()
    return "cpu"

# The FULL default stack, byte-for-byte ``goals.registry.DEFAULT_GOALS``
# (tests/test_bench_goals.py asserts they cannot drift apart).  The first
# six are the hard capacity/rack goals — HARD_GOALS below relies on that
# registry ordering.
GOALS = [
    "RackAwareGoal",
    "ReplicaCapacityGoal",
    "DiskCapacityGoal",
    "NetworkInboundCapacityGoal",
    "NetworkOutboundCapacityGoal",
    "CpuCapacityGoal",
    "ReplicaDistributionGoal",
    "PotentialNwOutGoal",
    "DiskUsageDistributionGoal",
    "NetworkInboundUsageDistributionGoal",
    "NetworkOutboundUsageDistributionGoal",
    "CpuUsageDistributionGoal",
    "TopicReplicaDistributionGoal",
    "LeaderReplicaDistributionGoal",
    "LeaderBytesInDistributionGoal",
]

HARD_GOALS = GOALS[:6]

TPU_CHILD_TIMEOUT_S = 1800.0


def _parse_only(argv):
    """``--only 3`` / ``--only 1,5`` → {3} / {1, 5}.  A missing or
    non-numeric argument is a usage error, not a traceback."""
    if "--only" not in argv:
        return None
    try:
        raw = argv[argv.index("--only") + 1]
        return {int(c) for c in raw.split(",")}
    except (IndexError, ValueError):
        sys.stderr.write("usage: bench.py [--only N[,N...]] [--trace] "
                         "[--convergence]  (config numbers 1-9, e.g. "
                         "--only 3 or --only 1,5)\n")
        raise SystemExit(2)


def _enable_trace() -> None:
    """Switch the obsvc tracer on for this process so every emitted row
    carries its ``split_ms`` phase attribution (and, under ``--trace``, the
    full rollup).  Enabled per PROCESS (the TPU child re-enables for
    itself) right before ``run``."""
    from cruise_control_tpu.obsvc.tracer import tracer
    tracer().configure(enabled=True, ring_size=64)
    # Memory observatory in FULL analysis mode: every fresh compile stashes
    # its Lowered, and _emit's finalize_full() AOT-recompiles once per
    # executable family OUTSIDE the timed regions, so each row's
    # peak_bytes / temp_bytes come from XLA's own buffer assignment without
    # inflating cold-compile measurements.
    from cruise_control_tpu.obsvc.memory import memory_ledger
    memory_ledger().configure(enabled=True, analysis_mode="full")
    if "--convergence" in sys.argv:
        from cruise_control_tpu.analyzer.solver import set_round_recording
        from cruise_control_tpu.obsvc.convergence import convergence
        set_round_recording(True)
        convergence().configure(enabled=True, ring_size=256)


def main() -> None:
    import subprocess

    # Run a subset of configs (e.g. ``--only 3`` for the smallest
    # full-stack compile).  Used by scripts/tpu_capture.py to grab the
    # cheapest TPU datapoint first while the flaky tunnel is alive.
    only = _parse_only(sys.argv)

    if "--tpu-child" in sys.argv:
        # Parent already probed the backend; just run.  Application errors
        # exit 3 (the parent fails loud instead of masking them with a CPU
        # rerun); backend/runtime deaths exit 4 (CPU fallback).
        persist = os.environ.get("CC_TPU_PERSIST_CACHE")
        if persist:
            # TPU executables are compiled server-side for the TPU — the
            # XLA:CPU "different machine features across processes" SIGILL
            # (tests/conftest.py) does not apply, and a persisted cache lets
            # a second tunnel-alive window skip straight to the bigger
            # configs.  Opt-in so the driver's own run stays hermetic.
            # Routed through the compilesvc manager: versioned key dirs,
            # quarantine-on-corruption, eviction (a value other than a bare
            # "1"/"true" flag names the cache root).
            from cruise_control_tpu.compilesvc import compile_service
            from cruise_control_tpu.compilesvc.service import goal_stack_hash
            svc = compile_service()
            svc.cache.enabled = True
            if persist.lower() not in ("1", "true", "yes"):
                svc.cache.root = persist
            svc.cache.activate(platform_name="tpu",
                               goal_stack_hash=goal_stack_hash(GOALS))
        try:
            _enable_trace()
            run("tpu", only=only)
        except Exception as e:
            import traceback
            traceback.print_exc()
            from jax.errors import JaxRuntimeError
            sys.exit(4 if isinstance(e, (JaxRuntimeError, OSError)) else 3)
        return

    only_args = (["--only", sys.argv[sys.argv.index("--only") + 1]]
                 if only is not None else [])
    for flag in ("--trace", "--convergence"):
        if flag in sys.argv:
            only_args.append(flag)      # child re-reads its own argv
    backend = select_backend()
    if backend == "tpu":
        # The tunneled TPU backend can hang MID-RUN (not just at init) — a
        # half-dead tunnel passes the probe and then stalls a dispatch
        # forever.  Run the TPU attempt in a watchdogged subprocess; on any
        # failure or timeout, fall back to CPU so the bench always emits
        # its JSON lines.
        try:
            # stdout is INHERITED so the child's JSON lines stream out as
            # they are produced — a harness kill mid-run still leaves every
            # already-emitted line on stdout (the headline goes first).
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--tpu-child",
                 *only_args],
                timeout=TPU_CHILD_TIMEOUT_S)
            if proc.returncode == 0:
                return
            if proc.returncode == 3:
                sys.exit(3)     # application bug on the TPU path: fail loud
            sys.stderr.write(f"\ntpu child rc={proc.returncode}; "
                             "falling back to cpu\n")
        except subprocess.TimeoutExpired:
            sys.stderr.write("\ntpu child timed out; falling back to cpu\n")
    from cruise_control_tpu.utils.hermetic import force_cpu
    force_cpu()
    _enable_trace()
    run("cpu", only=only)


def _emit(metric: str, seconds: float, backend: str, **extra) -> dict:
    """One JSON line; ``vs_baseline`` is ALWAYS budget/value (whole
    measurement) so the field stays comparable across metrics and rounds.
    Returns the emitted row (config #6 reads its own ``split_ms`` back)."""
    row = {
        "metric": metric,
        "value": round(seconds, 4),
        "unit": "seconds",
        "vs_baseline": round(NORTH_STAR_BUDGET_S / max(seconds, 1e-9), 3),
        "backend": backend,
        **extra,
    }
    from cruise_control_tpu.obsvc.tracer import tracer
    tr = tracer()
    if tr.enabled:
        # Drained per row: each row's rollup covers only the phases since
        # the previous row (warmup calls included — honest attribution).
        roll = tr.rollup(reset=True)
        row["split_ms"] = _split_ms(roll)
        if "--trace" in sys.argv:
            row["trace"] = roll
    # Worst-case executable memory across every cost-ledger row so far —
    # cumulative, not drained: a row's bytes answer "what must fit in HBM
    # to run everything up to and including this config".
    from cruise_control_tpu.obsvc.memory import cost_ledger
    cost_ledger().finalize_full()
    mem = cost_ledger().maxima()
    row["peak_bytes"] = mem["peak_bytes"]
    row["temp_bytes"] = mem["temp_bytes"]
    if "--convergence" in sys.argv:
        from cruise_control_tpu.obsvc.convergence import convergence
        recs = convergence().drain()
        if recs:
            row["convergence"] = _convergence_rows(recs)
    print(json.dumps(row), flush=True)
    return row


def _convergence_rows(recs: list) -> list:
    """Per-row convergence attribution (``--convergence``): drained per row
    like ``split_ms``, so each entry covers only the solves since the
    previous row.  Sequential solves carry per-goal curve summaries; what-if
    batches carry per-lane early-exit-round histograms ({rounds: lanes} per
    goal — a warm-started batch should shift mass toward fewer rounds)."""
    out = []
    for rec in recs:
        if rec["kind"] == "what_if":
            hist = {}
            for goal, lane_rounds in rec["laneRounds"].items():
                counts: dict = {}
                for r in lane_rounds:
                    counts[r] = counts.get(r, 0) + 1
                hist[goal] = {str(k): v for k, v in sorted(counts.items())}
            out.append({"kind": "what_if", "lanes": rec["lanes"],
                        "warm_start": rec["warmStart"],
                        "early_exit_rounds": hist})
        else:
            out.append({"kind": rec["kind"],
                        "goals": {g["goal"]:
                                  g.get("stats", {"rounds_total": g["rounds"]})
                                  for g in rec["goals"]}})
    return out


def _split_ms(roll: dict) -> dict:
    """The freeze / transfer / delta-apply / solve millisecond split for one
    row, from the drained tracer rollup.  ``solve`` is the sequential
    ``optimize`` span plus the batched ``batch_optimize`` span; rows frozen
    outside the resident service (rc.generate fixtures) honestly report 0
    for the model phases."""
    g = lambda k: roll.get(k, {}).get("total_ms", 0.0)
    return {
        "freeze": g("model.freeze"),
        "transfer": g("model.transfer"),
        "delta_apply": g("model.delta_apply"),
        "solve": round(g("optimize") + g("batch_optimize"), 3),
    }


def _compile_fields(fresh: int) -> dict:
    """Row annotations derived from the measured compile-counter delta —
    "cold"/"warm" reports what the timed region actually paid, so a first
    call that rode the lane-chunk planner onto already-compiled widths is
    honestly warm."""
    return {"fresh_compiles": fresh, "includes_compile": fresh > 0,
            "compile_cache": "cold" if fresh > 0 else "warm"}


def _fingerprint_fields() -> dict:
    """Model-fidelity columns for rows whose model is fed by a monitored
    ingest: the current fingerprint's valid-partition ratio and total
    extrapolated fraction, plus the mean ingest→window-commit latency.
    Rows solved from bare fixture snapshots (nothing feeding the fidelity
    recorder) honestly report None/0."""
    from cruise_control_tpu.common.metrics import registry
    from cruise_control_tpu.obsvc.fidelity import fidelity
    fp = fidelity().current_fingerprint()
    stats = registry().timer("Monitor.ingest-commit-latency-ms").stats()
    return {
        "valid_ratio": (round(fp["validPartitionRatio"], 4)
                        if fp is not None else None),
        "extrapolated_fraction": (
            round(sum(fp["extrapolatedFraction"].values()), 4)
            if fp is not None else None),
        "ingest_ms": round(stats["mean_ms"], 3),
    }


def _timed_once(fn):
    """Time ONE call (compile included when it happens).  Returns
    ``(seconds, result, fresh_compiles)`` — the compile count is the
    compilesvc telemetry delta across the call."""
    from cruise_control_tpu.compilesvc import telemetry
    tel = telemetry()
    before = tel.compile_count()
    t0 = time.monotonic()
    out = fn()
    return time.monotonic() - t0, out, tel.compile_count() - before


def _timed(fn):
    """Warmup once (populate per-goal jit caches), then time the second
    call; same ``(seconds, result, fresh_compiles)`` shape as
    ``_timed_once``."""
    fn()
    return _timed_once(fn)


def _quality(result) -> dict:
    """violated_after/balancedness for a sequential ``OptimizerResult``:
    violated-broker count summed over goals, and the optimizer's own
    hard=3.0/soft=1.0 weighted score."""
    return {
        "violated_after": sum(int(g.violated_brokers_after)
                              for g in result.goal_infos),
        "balancedness": round(result.balancedness_score, 3),
    }


def _batch_quality(res) -> dict:
    """violated_after/balancedness for a ``BatchScenarioResult`` row: the
    batch total of violated brokers and the WORST lane's balancedness (one
    bad lane must not hide behind a mean)."""
    worst = min(res.balancedness(s) for s in range(res.num_scenarios))
    return {"violated_after": int(res.violated_after.sum()),
            "balancedness": round(worst, 3)}


def run(backend: str, only=None) -> None:
    from cruise_control_tpu.analyzer import GoalOptimizer
    from cruise_control_tpu.testing import random_cluster as rc
    # NOTE: the persistent compilation cache is deliberately NOT enabled on
    # the CPU path: on this VM, XLA:CPU detects different machine features
    # across processes and warns that loading mismatched AOT results "could
    # lead to execution errors such as SIGILL" — the benchmark artifact must
    # never die to a stale cache entry.  (scripts/profile_solve.py opts in;
    # the TPU child opts in via CC_TPU_PERSIST_CACHE, now routed through
    # compilesvc.PersistentCompileCache, where executables are TPU-targeted
    # and the CPU feature skew is irrelevant.)
    # "warm" below therefore always means the IN-PROCESS jit cache.
    want = lambda c: only is None or c in only

    # ---- config #3 (headline) first, so a number exists even if the harness
    # cuts the run short; re-emitted last for tail parsers.
    headline = None
    state = placement = meta = None
    if want(3) or want(2):
        props = rc.ClusterProperties(
            num_brokers=200, num_racks=10, num_topics=1000,
            num_replicas=50_000, mean_cpu=0.006, mean_disk=90.0,
            mean_nw_in=90.0, mean_nw_out=90.0, seed=3140)
        state, placement, meta = rc.generate(props)
    if want(3):
        optimizer = GoalOptimizer(goal_names=GOALS)
        h_s, h_res, h_fresh = _timed(
            lambda: optimizer.optimizations(state, placement, meta))
        headline = (h_s, {**_quality(h_res), **_compile_fields(h_fresh)})
        _emit("proposal_generation_wall_clock_200brokers_50k_replicas_"
              "full_goals", h_s, backend, **headline[1])
        del optimizer, h_res

    # ---- config #1: DeterministicCluster harness (6 brokers / 3 racks /
    # ~200 replicas, default goals — BASELINE.md config #1).
    if want(1):
        from cruise_control_tpu.testing import deterministic as det
        cm = det.homogeneous_cluster({0: 0, 1: 0, 2: 1, 3: 1, 4: 2, 5: 2})
        for p in range(100):
            lead, foll = p % 6, (p + 1 + p % 3) % 6
            cm.create_replica("T1", p, broker_id=lead, index=0, is_leader=True)
            cm.create_replica("T1", p, broker_id=foll, index=1,
                              is_leader=False)
            cm.set_replica_load("T1", p, lead,
                                det.load(0.5, 120.0, 180.0, 220.0))
            cm.set_replica_load("T1", p, foll,
                                det.load(0.1, 120.0, 0.0, 220.0))
        d_state, d_placement, d_meta = cm.freeze(pad_replicas_to=256,
                                                 pad_brokers_to=8)
        opt_det = GoalOptimizer(goal_names=GOALS)
        det_s, det_res, det_fresh = _timed(
            lambda: opt_det.optimizations(d_state, d_placement, d_meta))
        _emit("proposal_generation_wall_clock_deterministic_6brokers_"
              "200replicas", det_s, backend, **_quality(det_res),
              **_compile_fields(det_fresh))
        del d_state, d_placement, opt_det, det_res

    # ---- config #2: 200 brokers / 50K replicas, ONE ResourceDistributionGoal
    # (reuses config #3's still-live snapshot and solver caches).
    if want(2):
        opt_single = GoalOptimizer(
            goal_names=["NetworkInboundUsageDistributionGoal"])
        single_s, single_res, single_fresh = _timed(
            lambda: opt_single.optimizations(state, placement, meta))
        _emit("proposal_generation_wall_clock_200brokers_50k_replicas_single_"
              "resource_distribution_goal", single_s, backend,
              **_quality(single_res), **_compile_fields(single_fresh))
        del opt_single, single_res
    del state, placement

    # ---- config #4 fixture: north-star scale (2.6K brokers / 1M replicas)
    if want(4):
        big = rc.ClusterProperties(
            num_brokers=2600, num_racks=40, num_topics=2000,
            num_replicas=1_000_000, mean_cpu=0.0035, mean_disk=90.0,
            mean_nw_in=90.0, mean_nw_out=90.0, seed=3141)
        b_state, b_placement, b_meta = rc.generate(big)

        # config #4: full default stack (all 15 goals) at north-star scale.
        opt_big = GoalOptimizer(goal_names=GOALS)
        elapsed, big_res, big_fresh = _timed(
            lambda: opt_big.optimizations(b_state, b_placement, b_meta))
        _emit("proposal_generation_wall_clock_2600brokers_1m_replicas_"
              "full_goals", elapsed, backend, goals=len(GOALS),
              **_quality(big_res), **_compile_fields(big_fresh))
        del opt_big, b_state, b_placement, big_res

    # config #5: decommission what-ifs over a HEALTHY cluster (the realistic
    # remove_broker setting — lanes pay for evacuation, not a full repair),
    # one vmapped program per goal.  One timed call (compile included — the
    # lane batch IS the amortization); the CPU fallback runs fewer lanes in
    # the round-comparable rows, then the full spec shapes follow.
    if want(5):
        healthy = rc.ClusterProperties(
            num_brokers=2600, num_racks=40, num_topics=2000,
            num_replicas=1_000_000, mean_cpu=0.002, mean_disk=60.0,
            mean_nw_in=60.0, mean_nw_out=60.0, seed=3142)
        h_state, h_placement, h_meta = rc.generate(healthy)
        lanes = 64 if backend == "tpu" else 16
        sets = [[b] for b in range(lanes)]
        opt_hard = GoalOptimizer(goal_names=HARD_GOALS)
        batch_s, batch_res, batch_fresh = _timed_once(
            lambda: opt_hard.batch_remove_scenarios(
                h_state, h_placement, h_meta, sets, num_candidates=512))
        # vs_baseline stays budget/whole-batch (comparable across rounds);
        # per_lane_vs_budget is the honest per-study comparison — the
        # reference runs each decommission what-if as a separate request.
        _emit("remove_broker_what_ifs_2600brokers_1m_replicas_hard_goals",
              batch_s, backend, value_per_lane=round(batch_s / lanes, 4),
              per_lane_vs_budget=round(
                  NORTH_STAR_BUDGET_S / max(batch_s / lanes, 1e-9), 3),
              lanes=lanes, **_batch_quality(batch_res),
              **_compile_fields(batch_fresh))
        # Warm repeat: the in-process jit cache now holds every lane program —
        # this is what the warmup daemon's steady state (and any repeat
        # what-if at the same size class) pays.
        sets_w = [[lanes + b] for b in range(lanes)]
        warm_s, warm_res, warm_fresh = _timed_once(
            lambda: opt_hard.batch_remove_scenarios(
                h_state, h_placement, h_meta, sets_w, num_candidates=512))
        _emit("remove_broker_what_ifs_2600brokers_1m_replicas_hard_goals_warm",
              warm_s, backend, value_per_lane=round(warm_s / lanes, 4),
              per_lane_vs_budget=round(
                  NORTH_STAR_BUDGET_S / max(warm_s / lanes, 1e-9), 3),
              lanes=lanes, **_batch_quality(warm_res),
              **_compile_fields(warm_fresh))
        del batch_res, warm_res

        # BASELINE config #5 AT SPEC — "decommission 64 at once" is the
        # reference's RemoveBrokersRunnable semantics: ONE operation removes
        # a *set* of brokers, all 64 brokers' replicas evacuating in the same
        # solve (a different, harder problem than 64 single-broker what-ifs).
        one_s, one_res, one_fresh = _timed_once(
            lambda: opt_hard.batch_remove_scenarios(
                h_state, h_placement, h_meta, [list(range(64))],
                num_candidates=512))
        _emit("remove_64_brokers_single_scenario_2600brokers_1m_replicas_"
              "hard_goals", one_s, backend, brokers_removed=64, scenarios=1,
              **_batch_quality(one_res), **_compile_fields(one_fresh))
        # Warm repeat on a different 64-broker set: what a second
        # decommission request at this size class pays.
        one_w, one_w_res, one_w_fresh = _timed_once(
            lambda: opt_hard.batch_remove_scenarios(
                h_state, h_placement, h_meta, [list(range(64, 128))],
                num_candidates=512))
        _emit("remove_64_brokers_single_scenario_2600brokers_1m_replicas_"
              "hard_goals_warm", one_w, backend, brokers_removed=64,
              scenarios=1, **_batch_quality(one_w_res),
              **_compile_fields(one_w_fresh))
        del one_res, one_w_res

        # The full 64-lane what-if batch, run even on CPU (once cold, once
        # warm; slow is fine) so numbers at BASELINE's exact lane count
        # exist.  The lane-chunk planner should route 64 lanes through the
        # 16-wide executables the round-comparable rows already compiled —
        # fresh_compiles says whether it did.  Guarded: a 1M-replica batch
        # may exceed host RAM on the CPU fallback — skip honestly rather
        # than die and lose prior lines.
        if lanes != 64:
            try:
                sets64 = [[b] for b in range(64)]
                b64_s, b64_res, b64_fresh = _timed_once(
                    lambda: opt_hard.batch_remove_scenarios(
                        h_state, h_placement, h_meta, sets64,
                        num_candidates=512))
                _emit("remove_broker_what_ifs_64lanes_2600brokers_1m_replicas"
                      "_hard_goals", b64_s, backend,
                      value_per_lane=round(b64_s / 64, 4),
                      per_lane_vs_budget=round(
                          NORTH_STAR_BUDGET_S / max(b64_s / 64, 1e-9), 3),
                      lanes=64, **_batch_quality(b64_res),
                      **_compile_fields(b64_fresh))
                del b64_res
                sets64_w = [[64 + b] for b in range(64)]
                w64_s, w64_res, w64_fresh = _timed_once(
                    lambda: opt_hard.batch_remove_scenarios(
                        h_state, h_placement, h_meta, sets64_w,
                        num_candidates=512))
                _emit("remove_broker_what_ifs_64lanes_2600brokers_1m_replicas"
                      "_hard_goals_warm", w64_s, backend,
                      value_per_lane=round(w64_s / 64, 4),
                      per_lane_vs_budget=round(
                          NORTH_STAR_BUDGET_S / max(w64_s / 64, 1e-9), 3),
                      lanes=64, **_batch_quality(w64_res),
                      **_compile_fields(w64_fresh))
                del w64_res
            except MemoryError:
                sys.stderr.write("64-lane batch exceeded host RAM on the CPU "
                                 "fallback; row skipped\n")
        del h_state, h_placement, opt_hard

    # ---- config #6: resident-model steady state (delta propose) plus the
    # raw-seed vs warm-started lane pair, at the north-star shape.
    if want(6):
        _delta_propose_rows(backend, lanes=64 if backend == "tpu" else 16)

    # ---- config #7: the anytime quality/latency tradeoff under deadlines.
    if want(7):
        _deadline_rows(backend)

    # ---- config #8: the convex-relaxation fast path vs pure greedy.
    if want(8):
        _relax_rows(backend)

    # ---- config #9: storm-backed execution throughput via the execution
    # flight recorder.
    if want(9):
        _execution_rows(backend)

    if backend == "cpu":
        _replay_captured_tpu_rows()

    # Headline repeated LAST: the driver's artifact parser takes the tail line.
    if headline is not None:
        _emit("proposal_generation_wall_clock_200brokers_50k_replicas_"
              "full_goals", headline[0], backend, **headline[1])


def _deadline_rows(backend: str) -> None:
    """Config #7 (module docstring): the anytime solve under a wall-clock
    budget.  Calibrate the steady-state (warm, unbudgeted) solve time on
    the headline 200-broker/50K-replica snapshot, pre-compile the
    segmented executables off the clock, then re-solve with deadlines at
    25/50/100% of steady state — each row carries violated_after /
    balancedness plus how many goals the budget preempted, so the artifact
    shows what quality a fraction of the latency buys."""
    from cruise_control_tpu.analyzer import GoalOptimizer
    from cruise_control_tpu.analyzer.budget import SolveBudget
    from cruise_control_tpu.testing import random_cluster as rc

    props = rc.ClusterProperties(
        num_brokers=200, num_racks=10, num_topics=1000,
        num_replicas=50_000, mean_cpu=0.006, mean_disk=90.0,
        mean_nw_in=90.0, mean_nw_out=90.0, seed=3140)
    state, placement, meta = rc.generate(props)
    opt = GoalOptimizer(goal_names=GOALS)
    # Cold fused pass pays the compile; the warm repeat IS the steady state.
    _timed(lambda: opt.optimizations(state, placement, meta))
    steady_s, _, _ = _timed(
        lambda: opt.optimizations(state, placement, meta))
    # Budgeted solves dispatch the segmented executables — a parallel jit
    # family.  Compile it off the clock with an unreachable deadline so the
    # timed rows measure the anytime tradeoff, not XLA.
    opt.optimizations(state, placement, meta,
                      budget=SolveBudget(deadline_ms=1e12))
    for frac in (0.25, 0.5, 1.0):
        deadline_ms = steady_s * 1000.0 * frac
        # One timed call with a FRESH budget (the clock starts at
        # construction); everything is warm, so the wall is pure solve.
        s, res, fresh = _timed_once(
            lambda: opt.optimizations(
                state, placement, meta,
                budget=SolveBudget(deadline_ms=deadline_ms)))
        _emit(f"anytime_deadline_{int(frac * 100)}pct_steady_state_"
              "200brokers_50k_replicas_full_goals", s, backend,
              deadline_ms=round(deadline_ms, 1),
              steady_state_s=round(steady_s, 4),
              partial=bool(res.partial),
              preempted_goals=sum(1 for g in res.goal_infos if g.preempted),
              **_quality(res), **_compile_fields(fresh))
        del res
    del state, placement, opt


def _relax_rows(backend: str, props=None, lanes=None,
                num_candidates: int = 512,
                tag: str = "2600brokers_1m_replicas") -> None:
    """Config #8 (module docstring): the convex-relaxation fast path vs the
    pure greedy solver, rung by rung on the healthy north-star snapshot.

    Each rung solves the SAME problem twice — relaxation off (the greedy
    baseline) then on — and emits ONE row whose ``value`` is the relax-side
    wall, with ``greedy_s`` / ``speedup`` / ``relax_ms`` /
    ``repair_rounds`` / ``quality_delta`` alongside.  The lane rungs run
    the hard stack plus EVERY relax-eligible distribution goal (the family
    the fast path targets) so both sides optimize an identical stack; each
    solve gets a fresh broker window so nothing is a literal re-solve.
    The warm-lane rung is ISSUE 15's acceptance comparison against the r05
    4.73 s/lane warm row."""
    from cruise_control_tpu.analyzer import GoalOptimizer
    from cruise_control_tpu.analyzer import relax as relax_mod
    from cruise_control_tpu.analyzer.goals.registry import is_relax_eligible
    from cruise_control_tpu.obsvc.tracer import tracer
    from cruise_control_tpu.testing import random_cluster as rc

    if props is None:
        props = rc.ClusterProperties(
            num_brokers=2600, num_racks=40, num_topics=2000,
            num_replicas=1_000_000, mean_cpu=0.002, mean_disk=60.0,
            mean_nw_in=60.0, mean_nw_out=60.0, seed=3142)
    state, placement, meta = rc.generate(props)
    if lanes is None:
        lanes = 64 if backend == "tpu" else 16
    lane_goals = HARD_GOALS + [g for g in GOALS if is_relax_eligible(g)]
    opt = GoalOptimizer(goal_names=lane_goals)

    def lane_batch(first: int):
        ss = [[first + b] for b in range(lanes)]
        return opt.batch_remove_scenarios(state, placement, meta, ss,
                                          num_candidates=num_candidates)

    def relax_wall_ms() -> float:
        # Peek without reset — _emit's own drain closes out the row, so the
        # row's split_ms still covers the relax-side solve it reports.
        return round(tracer().rollup().get("solve.relax", {})
                     .get("total_ms", 0.0), 3)

    prev_on = relax_mod.relaxation_enabled()
    prev = relax_mod.relaxation_params()
    try:
        # ---- rung 1: COLD lanes.  Greedy pays its lane compiles first;
        # the relax side then pays only its own -X-bucket compile (the
        # greedy repair executables are shared) — fresh_compiles says what
        # the timed region actually paid.
        relax_mod.set_relaxation(False)
        g_cold_s, g_cold_res, _ = _timed_once(lambda: lane_batch(0))
        g_cold_q = _batch_quality(g_cold_res)
        del g_cold_res
        tracer().rollup(reset=True)     # the row attributes only the relax side
        relax_mod.set_relaxation(True)
        r_cold_s, r_cold_res, r_cold_fresh = _timed_once(
            lambda: lane_batch(lanes))
        q = _batch_quality(r_cold_res)
        _emit(f"relax_ladder_cold_lanes_{tag}", r_cold_s, backend,
              value_per_lane=round(r_cold_s / lanes, 4), lanes=lanes,
              greedy_s=round(g_cold_s, 4),
              speedup=round(g_cold_s / max(r_cold_s, 1e-9), 3),
              relax_ms=relax_wall_ms(),
              repair_rounds=int(r_cold_res.rounds.sum()),
              quality_delta=round(
                  q["balancedness"] - g_cold_q["balancedness"], 3),
              **q, **_compile_fields(r_cold_fresh))
        del r_cold_res

        # ---- rung 2: WARM lanes — the acceptance rung.  Every executable
        # is in-cache on BOTH sides; each side still solves a fresh broker
        # window, so the pair isolates solve wall, not cache luck.
        relax_mod.set_relaxation(False)
        g_warm_s, g_warm_res, _ = _timed_once(lambda: lane_batch(2 * lanes))
        g_warm_q = _batch_quality(g_warm_res)
        del g_warm_res
        tracer().rollup(reset=True)
        relax_mod.set_relaxation(True)
        r_warm_s, r_warm_res, r_warm_fresh = _timed_once(
            lambda: lane_batch(3 * lanes))
        q = _batch_quality(r_warm_res)
        _emit(f"relax_ladder_warm_lanes_{tag}", r_warm_s, backend,
              value_per_lane=round(r_warm_s / lanes, 4),
              per_lane_vs_budget=round(
                  NORTH_STAR_BUDGET_S / max(r_warm_s / lanes, 1e-9), 3),
              lanes=lanes, greedy_s=round(g_warm_s, 4),
              greedy_s_per_lane=round(g_warm_s / lanes, 4),
              speedup=round(g_warm_s / max(r_warm_s, 1e-9), 3),
              relax_ms=relax_wall_ms(),
              repair_rounds=int(r_warm_res.rounds.sum()),
              quality_delta=round(
                  q["balancedness"] - g_warm_q["balancedness"], 3),
              **q, **_compile_fields(r_warm_fresh))
        del r_warm_res

        # ---- rung 3: the FULL 15-goal sequential propose on the same
        # snapshot.  Relax engages on the eligible goals only; the other
        # goals run today's greedy path, and the repair telemetry comes
        # straight from the per-goal infos.
        opt_full = GoalOptimizer(goal_names=GOALS)
        relax_mod.set_relaxation(False)
        g_full_s, g_full_res, _ = _timed(
            lambda: opt_full.optimizations(state, placement, meta))
        g_full_q = _quality(g_full_res)
        del g_full_res
        relax_mod.set_relaxation(True)
        r_full_s, r_full_res, r_full_fresh = _timed(
            lambda: opt_full.optimizations(state, placement, meta))
        q = _quality(r_full_res)
        infos = [i for i in r_full_res.goal_infos if i.relaxed]
        _emit(f"relax_ladder_full_goals_{tag}", r_full_s, backend,
              goals=len(GOALS), relaxed_goals=len(infos),
              greedy_s=round(g_full_s, 4),
              speedup=round(g_full_s / max(r_full_s, 1e-9), 3),
              relax_ms=round(sum(i.relax_ms for i in infos), 3),
              repair_rounds=sum(i.repair_rounds for i in infos),
              relax_fallbacks=sum(1 for i in infos if i.relax_fallback),
              quality_delta=round(
                  q["balancedness"] - g_full_q["balancedness"], 3),
              **q, **_compile_fields(r_full_fresh))
        del r_full_res, opt_full
    finally:
        relax_mod.set_relaxation(prev_on, iterations=prev[0],
                                 candidates=prev[1], waves=prev[2],
                                 tolerance=prev[3])
    del state, placement, opt


def _execution_rows(backend: str, partitions: int = 48,
                    polls_to_finish: int = 2) -> None:
    """Config #9 (module docstring): end-to-end execution throughput on the
    storm runner's in-process simulator stack — solve, then EXECUTE the
    proposals against the production SubprocessClusterBackend wire shapes,
    and report the execution flight recorder's drained batch summary:
    ``execute_ms`` (batch wall from first submission to drain) and
    ``moves_per_s`` (terminal moves over that wall), plus the provenance
    path histogram the moves carried.  The row measures the executor's
    poll/submit machinery, not broker I/O — the simulator completes a
    movement after ``polls_to_finish`` polls."""
    from cruise_control_tpu.fuzzsvc.scenario import generate_scenario
    from cruise_control_tpu.fuzzsvc.storm import _wait_idle, build_storm_stack
    from cruise_control_tpu.obsvc.execution import execution

    rec = execution()
    prev = rec.enabled
    rec.configure(enabled=True)
    rec.drain()                       # this row owns the next batch summary
    sc = generate_scenario(3146, kind="exp_skew")
    stack = build_storm_stack(sc, num_brokers=6, partitions=partitions,
                              rf=2, polls_to_finish=polls_to_finish)
    try:
        t0 = time.monotonic()
        res = stack.cc.rebalance(dryrun=False)
        if not _wait_idle(stack.cc, timeout_s=120.0):
            sys.stderr.write("config #9: executor never went idle; "
                             "row skipped\n")
            return
        wall_s = time.monotonic() - t0
        batches = rec.drain()
        if not batches:
            sys.stderr.write("config #9: no execution batch recorded; "
                             "row skipped\n")
            return
        b = batches[-1]
        _emit("storm_execution_throughput_6brokers_"
              f"{partitions}partitions", wall_s, backend,
              execute_ms=b["durationMs"],
              moves_per_s=b["movesPerSecond"],
              moves=b["moves"], completed=b["completed"],
              dead=b["dead"], aborted=b["aborted"],
              provenance_paths=b["pathHistogram"],
              executed=bool(res.executed))
    finally:
        stack.cc.anomaly_detector.shutdown()
        rec.configure(enabled=prev)
        rec.reset()


def _delta_propose_rows(backend: str, props=None, lanes: int = 16,
                        tag: str = "2600brokers_1m_replicas",
                        mutations: int = 64, rounds: int = 3) -> None:
    """Config #6 (module docstring): the resident-model steady state.

    One full freeze seeds a ``ResidentModelService`` from a live builder;
    each steady round mutates ``mutations`` random partitions' loads on
    that SAME builder and re-proposes through the delta-scatter path — the
    production facade flow once the LoadMonitor has published a window.
    The steady row's ``freeze_transfer_reduction`` divides the seed row's
    measured freeze+transfer milliseconds by the mean delta-apply cost;
    ``full_freezes_steady_state`` is the sensor-verified count of full
    freezes paid during the steady rounds (0 ⇔ the delta contract held).
    """
    import numpy as np
    from cruise_control_tpu.analyzer import GoalOptimizer
    from cruise_control_tpu.common.metrics import registry
    from cruise_control_tpu.compilesvc import compile_service
    from cruise_control_tpu.model.builder import builder_from_snapshot
    from cruise_control_tpu.model.resident import (
        DELTA_APPLY_SENSOR,
        FULL_FREEZE_SENSOR,
        ResidentModelService,
    )
    from cruise_control_tpu.obsvc.tracer import tracer
    from cruise_control_tpu.testing import random_cluster as rc

    if props is None:
        props = rc.ClusterProperties(
            num_brokers=2600, num_racks=40, num_topics=2000,
            num_replicas=1_000_000, mean_cpu=0.002, mean_disk=60.0,
            mean_nw_in=60.0, mean_nw_out=60.0, seed=3143)
    state, placement, meta = rc.generate(props)
    builder = builder_from_snapshot(state, placement, meta)
    del state, placement, meta

    svc = ResidentModelService()
    pad_fn = compile_service().pad_targets
    reg = registry()
    full_ctr = reg.counter(FULL_FREEZE_SENSOR)
    delta_ctr = reg.counter(DELTA_APPLY_SENSOR)

    tracer().rollup(reset=True)   # this config's rows attribute only itself
    freeze_s, (r_state, r_placement, r_meta), _ = _timed_once(
        lambda: svc.snapshot(builder, pad_fn))
    freeze_row = _emit(f"resident_full_freeze_{tag}", freeze_s, backend,
                       replicas=props.num_replicas,
                       brokers=props.num_brokers)
    split = freeze_row.get("split_ms", {})
    freeze_transfer_ms = round(split.get("freeze", 0.0)
                               + split.get("transfer", 0.0), 3)

    # Base solve: warms the sequential executables AND produces the solved
    # base placement the warm-started lanes seed from.  Its (cold) compile
    # rides the steady row's split under "solve" — honest attribution, same
    # as every other config's warmup.
    opt = GoalOptimizer(goal_names=HARD_GOALS)
    base_res = opt.optimizations(r_state, r_placement, r_meta)

    rng = np.random.default_rng(314159)
    pairs = list(builder.partitions().keys())

    # Fidelity sidecar (untimed): a small aggregator fed once per steady
    # round — the production cadence of monitor samples arriving between
    # delta proposes — so the steady row's fingerprint columns
    # (valid_ratio / extrapolated_fraction / ingest_ms) are measurements
    # of a live ingest→fingerprint pipeline, not hardcoded constants.
    from cruise_control_tpu.monitor.aggregator import MetricSampleAggregator
    from cruise_control_tpu.monitor.metric_def import COMMON_METRIC_DEF
    from cruise_control_tpu.obsvc.fidelity import fidelity
    fid = fidelity()
    fid_window_ms = 500
    fid_agg = MetricSampleAggregator(
        COMMON_METRIC_DEF, num_windows=8, window_ms=fid_window_ms,
        min_samples_per_window=1,
        max_allowed_extrapolations_per_entity=64)
    fid_pairs = pairs[:64]
    fid_vals = np.ones(COMMON_METRIC_DEF.size)

    def ingest_fidelity() -> None:
        now_ms = time.time() * 1000.0
        before_w = fid_agg.current_window
        for fp_pair in fid_pairs:
            fid_agg.add_sample(fp_pair, now_ms, fid_vals)
        after_w = fid_agg.current_window
        if before_w >= 0:
            for w in range(max(before_w, after_w - 9), after_w):
                fid.on_window_close(w, fid_window_ms, now_ms=now_ms)
        comp = fid_agg.completeness(0, now_ms)
        if comp.valid_windows:
            fid.record_fingerprint(comp, window_ms=fid_window_ms,
                                   kind="delta", now_ms=now_ms)

    def mutate() -> None:
        # Small multiplicative load drift on whole partitions: the shape of
        # a real inter-window change, and it keeps hard goals satisfiable.
        for _ in range(mutations):
            t, p = pairs[int(rng.integers(len(pairs)))]
            for r in builder.partition(t, p):
                builder.set_replica_load(
                    t, p, r.broker_id,
                    r.leader_load * float(rng.uniform(0.85, 1.2)))

    def propose():
        s, p, m = svc.snapshot(builder, pad_fn)
        return opt.optimizations(s, p, m)

    # One untimed warmup round (the _timed convention): it pays the scatter
    # executable's compile at the steady slot bucket, exactly what the boot
    # warmup daemon pays in production.
    mutate()
    propose()

    full0, delta0 = full_ctr.count, delta_ctr.count
    da0 = tracer().rollup().get("model.delta_apply",
                                {"count": 0, "total_ms": 0.0})
    steady, fresh_total, res = [], 0, base_res
    for _ in range(rounds):
        mutate()
        ingest_fidelity()
        dt, res, fresh = _timed_once(propose)
        steady.append(dt)
        fresh_total += fresh
    steady_s = sum(steady) / len(steady)
    full_steady = int(full_ctr.count - full0)
    if full_steady:
        sys.stderr.write(f"steady state paid {full_steady} full re-freezes "
                         "— the delta path did not hold\n")
    da1 = tracer().rollup().get("model.delta_apply",
                                {"count": 0, "total_ms": 0.0})
    da_count = da1["count"] - da0["count"]
    da_mean = (da1["total_ms"] - da0["total_ms"]) / max(da_count, 1)
    _emit(f"steady_state_delta_propose_{tag}_hard_goals", steady_s, backend,
          rounds=rounds, mutations_per_round=mutations,
          full_freeze_s=round(freeze_s, 4),
          freeze_transfer_ms=freeze_transfer_ms,
          delta_apply_ms_mean=round(da_mean, 3),
          freeze_transfer_reduction=round(
              freeze_transfer_ms / max(da_mean, 1e-6), 1),
          full_freezes_steady_state=full_steady,
          delta_applies=int(delta_ctr.count - delta0),
          **_fingerprint_fields(),
          **_quality(res), **_compile_fields(fresh_total))

    # Lane pair on the SAME resident tensors: raw-snapshot seed first, then
    # the identical batch warm-started from the solved base placement.  The
    # seed placement is a traced input, so the second batch reuses the
    # first's executables — the pair isolates per-lane early exit.
    c_state, c_placement, c_meta = svc.snapshot(builder, pad_fn)
    base = (res.final_placement if res.final_placement is not None
            else base_res.final_placement)
    sets = [[b] for b in range(lanes)]
    cold_s, cold_res, cold_fresh = _timed_once(
        lambda: opt.batch_remove_scenarios(
            c_state, c_placement, c_meta, sets, num_candidates=512))
    _emit(f"remove_broker_what_ifs_{tag}_hard_goals_resident_base", cold_s,
          backend, value_per_lane=round(cold_s / lanes, 4), lanes=lanes,
          warm_start=False, **_batch_quality(cold_res),
          **_compile_fields(cold_fresh))
    warm_s, warm_res, warm_fresh = _timed_once(
        lambda: opt.batch_remove_scenarios(
            c_state, c_placement, c_meta, sets, num_candidates=512,
            warm_start=base))
    _emit(f"remove_broker_what_ifs_{tag}_hard_goals_warm_started", warm_s,
          backend, value_per_lane=round(warm_s / lanes, 4), lanes=lanes,
          warm_start=True, **_batch_quality(warm_res),
          **_compile_fields(warm_fresh))


def _replay_captured_tpu_rows() -> None:
    """Re-emit TPU rows captured by ``scripts/tpu_capture.py`` earlier in the
    round.  The tunneled TPU dies unpredictably (BASELINE.md round-4 status),
    so live windows are harvested whenever they occur; a row measured then is
    real data the round-end CPU-fallback run must not drop.  Replayed rows
    keep their measured values and carry ``"replayed": true`` plus the
    capture timestamp — they are NOT measurements of this process."""
    rows = []
    try:
        with open(CAPTURE_FILE) as f:
            for line in f:
                try:
                    rows.append(json.loads(line))
                except ValueError:
                    pass   # torn tail write from a killed capture daemon
    except OSError:
        return
    best = {}
    for row in rows:
        if row.get("backend") == "tpu" and "metric" in row:
            best[row["metric"]] = row          # latest capture wins
    for row in best.values():
        row["replayed"] = True
        print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
